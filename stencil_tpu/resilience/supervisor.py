"""Checkpoint/resume run supervisor: the rung BELOW the in-process ladder.

The resilience story so far is in-process: VMEM_OOM / COMPILE_REJECT walk
the degradation ladder, TRANSIENT retries with backoff, DIVERGENCE
propagates.  What none of that survives is the process dying — a
preemption notice, a SIGKILL, a FATAL dispatch error, a wedged device.
``RunSupervisor`` closes that gap around any step loop:

* **Cadence checkpoints** — every N steps and/or every T wall-clock
  seconds, an atomic checkpoint lands in the retention ring
  (``io/checkpoint.save_to_ring``), carrying the step counter and the
  caller's resumable run state (tuned decisions in effect, kernel axes).
* **Preemption handling** — a SIGTERM (the cloud preemption notice) or
  ``KeyboardInterrupt`` is classified PREEMPTED, takes one final
  checkpoint (donation-guarded: a mid-dispatch kill whose buffers are
  already consumed skips the save — the last ring entry stands), and
  returns a resumable outcome (``EXIT_RESUMABLE``, the sysexits
  EX_TEMPFAIL convention schedulers re-queue on).
* **Resume** — ``resume()`` restores the newest VALID ring checkpoint
  (corrupt entries fall back to older ones) and returns the step to
  continue from; the saved ``run_state`` is exposed for the caller to
  re-apply its decisions.
* **Restart budget** — a FATAL or STALL classification mid-run restores
  the last valid checkpoint IN-PROCESS and re-runs, up to
  ``max_restarts`` times (``supervisor.restart`` event + counter per
  restart).  The ladder keeps handling VMEM_OOM/COMPILE_REJECT and retry
  keeps handling TRANSIENT before anything reaches here; DIVERGENCE is
  never restarted (the same numerics diverge again).
* **Flight recorder** — a rank-0 ``status.json`` heartbeat in the
  checkpoint dir per chunk (step, steady-state rate, checkpoint age,
  watchdog state, restart count, last classified error) and a
  ``crash_report.json`` (classified cause + the last-N telemetry events
  from the in-memory ring) on any propagating FATAL/STALL/PREEMPTED
  exit; ``python -m stencil_tpu.status <dir>`` renders both
  (telemetry/flight.py, docs/observability.md "Flight recorder").

Knobs (validated reads — utils/config.py): ``STENCIL_CHECKPOINT_DIR``,
``STENCIL_CHECKPOINT_EVERY`` (steps), ``STENCIL_CHECKPOINT_EVERY_S``
(wall-clock), ``STENCIL_CHECKPOINT_KEEP`` (ring size),
``STENCIL_CHECKPOINT_BACKEND`` (auto|npz|orbax),
``STENCIL_CHECKPOINT_VERIFY`` (digest checks on restore),
``STENCIL_SUPERVISOR_RESTARTS`` (restart budget).
"""

from __future__ import annotations

import dataclasses
import signal
import threading
import time
from typing import Callable, Optional

from stencil_tpu import telemetry
from stencil_tpu.io.checkpoint import restore_latest, save_to_ring
from stencil_tpu.resilience.retry import buffers_live
from stencil_tpu.resilience.taxonomy import FailureClass, classify
from stencil_tpu.telemetry import names as tm
from stencil_tpu.telemetry.flight import FlightRecorder
from stencil_tpu.utils.logging import log_info, log_warn

#: sysexits EX_TEMPFAIL — "try again later"; schedulers re-queue this code
EXIT_RESUMABLE = 75

#: sentinel for "no SIGTERM handler was installed" (distinct from a
#: previous handler that reads back as None — installed at the C level)
_NOT_INSTALLED = object()


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    """Where and how often to checkpoint, and how hard to fight for the run."""

    dir: str
    every_steps: int = 0  # 0 = no step cadence
    every_seconds: float = 0.0  # 0 = no wall-clock cadence
    keep: int = 3
    max_restarts: int = 2
    backend: Optional[str] = None  # None = orbax when installed, else npz
    verify: bool = True

    @classmethod
    def from_env(cls, dir: Optional[str] = None, **overrides) -> Optional["SupervisorConfig"]:
        """Environment-driven config; returns None when no directory is set
        anywhere (supervision is strictly opt-in)."""
        from stencil_tpu.utils.config import (
            env_bool,
            env_choice,
            env_float,
            env_int,
            env_str,
        )

        dir = dir or env_str("STENCIL_CHECKPOINT_DIR", None)
        if dir is None:
            return None
        backend = env_choice(
            "STENCIL_CHECKPOINT_BACKEND", "auto", ("auto", "npz", "orbax")
        )
        fields = dict(
            dir=dir,
            every_steps=env_int("STENCIL_CHECKPOINT_EVERY", 0, minimum=0),
            every_seconds=env_float("STENCIL_CHECKPOINT_EVERY_S", 0.0, minimum=0.0),
            keep=env_int("STENCIL_CHECKPOINT_KEEP", 3, minimum=1),
            max_restarts=env_int("STENCIL_SUPERVISOR_RESTARTS", 2, minimum=0),
            backend=None if backend == "auto" else backend,
            verify=env_bool("STENCIL_CHECKPOINT_VERIFY", True),
        )
        fields.update(overrides)
        return cls(**fields)


@dataclasses.dataclass
class RunOutcome:
    """What ``run`` achieved: ``completed`` runs reached ``total_steps``;
    preempted runs stopped early with a final checkpoint and the resumable
    exit code."""

    completed: bool
    step: int
    restarts: int
    preempted: bool = False
    exit_code: int = 0


class RunSupervisor:
    """Wraps a step loop with checkpoint/resume/restart (module docstring).

    ``run_state`` is a zero-arg callable returning the JSON-safe decision
    record to persist with every checkpoint (tuned picks, kernel axes);
    after ``resume()`` the restored record is available as
    ``last_run_state`` for the caller to re-apply.
    """

    def __init__(
        self,
        dd,
        config: SupervisorConfig,
        label: str = "run",
        run_state: Optional[Callable[[], dict]] = None,
        flight: Optional[FlightRecorder] = None,
    ):
        self.dd = dd
        self.config = config
        self.label = label
        self._run_state = run_state
        self.last_run_state: dict = {}
        #: the ring path the last resume() restored from (None = cold start)
        self.resumed_path: Optional[str] = None
        #: the flight recorder: per-chunk heartbeat ``status.json`` +
        #: ``crash_report.json`` on any propagating exit, both in the
        #: checkpoint dir — ``python -m stencil_tpu.status <dir>`` renders
        #: them (docs/observability.md "Flight recorder")
        self.flight = flight if flight is not None else FlightRecorder(
            config.dir, label=label
        )
        self._last_error: Optional[str] = None
        self._preempted = False
        self._preempt_why = ""

    # --- resume ---------------------------------------------------------------

    def resume(self) -> int:
        """Restore the newest ring checkpoint that restores CLEANLY into
        the domain; returns the step to continue from (0 on a cold start —
        distinguish via ``resumed_path``).  Entries that fail structurally
        OR at restore-time digest verification are skipped (counted +
        event-logged by ``restore_latest``), each hashed exactly once."""
        self.resumed_path = None
        found = restore_latest(self.dd, self.config.dir, verify=self.config.verify)
        if found is None:
            log_info(f"{self.label}: no checkpoint under {self.config.dir}; cold start")
            return 0
        path, manifest, step = found
        self.last_run_state = manifest.get("run_state") or {}
        self.resumed_path = path
        return step

    # --- checkpointing --------------------------------------------------------

    def checkpoint(self, step: int, reason: str = "cadence") -> str:
        return save_to_ring(
            self.dd,
            self.config.dir,
            step,
            keep=self.config.keep,
            backend=self.config.backend,
            run_state=self._run_state() if self._run_state is not None else None,
            reason=reason,
        )

    def _final_checkpoint(self, step: int, reason: str) -> None:
        """Best-effort final save: skipped (with the last ring entry left
        standing) when the interrupted dispatch already consumed its donated
        buffers — reading them back would be a use-after-free."""
        if not buffers_live(self.dd._curr):
            log_warn(
                f"{self.label}: skipping final checkpoint at step {step} — a "
                "donated buffer was already consumed mid-dispatch; the last "
                "ring checkpoint stands"
            )
            return
        try:
            self.checkpoint(step, reason=reason)
        except Exception as e:  # the exit path must stay resumable
            log_warn(f"{self.label}: final checkpoint failed ({e}); the last ring checkpoint stands")

    # --- flight recorder ------------------------------------------------------

    def _watchdog_state(self) -> str:
        wd = getattr(self.dd, "_get_watchdog", lambda: None)()
        if wd is None:
            return "off"
        return (
            f"armed({wd.deadline_s:g}s{', abort' if wd.abort else ''})"
        )

    def _heartbeat(
        self, step: int, total_steps: int, restarts: int, last_ck: float,
        phase: str = "running",
    ) -> None:
        """One status.json rewrite: progress, rate, checkpoint age,
        watchdog arming, restart count, last classified error, and the
        caller's run_state (which carries the decisions in effect —
        ladder rung / kernel axes when the model exposes them)."""
        self.flight.heartbeat(
            step,
            total_steps,
            phase=phase,
            checkpoint_age_s=round(time.monotonic() - last_ck, 3),
            restarts=restarts,
            watchdog=self._watchdog_state(),
            last_error=self._last_error,
            run_state=self._run_state() if self._run_state is not None else None,
        )

    # --- preemption -----------------------------------------------------------

    def _install_sigterm(self):
        """SIGTERM -> preemption flag, checked between chunks.  Only the
        main thread may install handlers; elsewhere (a driver already under
        its own supervisor thread) SIGTERM keeps its default meaning.
        Returns ``_NOT_INSTALLED`` when nothing was installed — distinct
        from a previous handler of ``None`` (set at the C level), which
        must still be restored (as SIG_DFL) on exit."""
        if threading.current_thread() is not threading.main_thread():
            return _NOT_INSTALLED

        def handler(signum, frame):
            self._preempted = True
            self._preempt_why = "SIGTERM"
            log_warn(
                f"{self.label}: SIGTERM — will checkpoint and exit resumable "
                "at the next step boundary"
            )

        try:
            return signal.signal(signal.SIGTERM, handler)
        except (ValueError, OSError):  # non-main interpreter contexts
            return _NOT_INSTALLED

    # --- the supervised loop --------------------------------------------------

    def run(
        self,
        total_steps: int,
        advance: Callable[[int], None],
        start_step: Optional[int] = None,
        chunk: Optional[int] = None,
        on_chunk: Optional[Callable[[int, int], None]] = None,
    ) -> RunOutcome:
        """Drive ``advance(n)`` from ``start_step`` (default: ``resume()``)
        to ``total_steps`` under the full survival contract.  ``chunk``
        bounds the steps per ``advance`` call (default: the step cadence, or
        the whole remainder); ``on_chunk(done_step, n)`` runs after each
        successful chunk (drivers hang their timing/paraview hooks here)."""
        cfg = self.config
        step = self.resume() if start_step is None else int(start_step)
        if chunk is None:
            if cfg.every_steps:
                chunk = cfg.every_steps
            elif cfg.every_seconds:
                # wall-clock-only cadence: the timer is only consulted
                # BETWEEN chunks, so one whole-remainder chunk would never
                # checkpoint mid-run — step singly instead
                chunk = 1
            else:
                chunk = max(total_steps - step, 1)
        chunk = max(int(chunk), 1)
        restarts = 0
        self._preempted = False
        prev_handler = self._install_sigterm()
        last_ck = time.monotonic()
        from stencil_tpu.io.checkpoint import ring_entries

        if not ring_entries(cfg.dir):
            # anchor the ring: a FATAL/STALL before the first cadence
            # checkpoint must still have a rung to restart from (a cheap
            # listdir — the resume() above already paid the validation
            # pass when entries existed)
            self.checkpoint(step, reason="initial")
        # first heartbeat before any chunk: a kill during the very first
        # dispatch must still leave a readable status.json
        self._heartbeat(step, total_steps, restarts, last_ck)
        try:
            while step < total_steps:
                n = min(chunk, total_steps - step)
                if cfg.every_steps:
                    # land chunks ON cadence boundaries so resumed runs
                    # re-walk identical dispatch partitions
                    to_boundary = cfg.every_steps - (step % cfg.every_steps)
                    n = min(n, to_boundary)
                mid_chunk = False
                try:
                    advance(n)
                except (Exception, KeyboardInterrupt) as e:
                    cls = classify(e)
                    self._last_error = f"{cls.value}: {str(e)[:300]}"
                    if cls is FailureClass.PREEMPTED:
                        # the chunk died partway: the domain is an UNKNOWN
                        # number of iterations past `step`, so no final
                        # checkpoint may be labeled with it — the last ring
                        # entry stands and resume re-runs from there
                        # (deterministic, so still bitwise)
                        self._preempted = True
                        mid_chunk = True
                        self._preempt_why = self._preempt_why or type(e).__name__
                    elif (
                        cls in (FailureClass.FATAL, FailureClass.STALL)
                        and restarts < cfg.max_restarts
                    ):
                        restored = self.resume()
                        if self.resumed_path is None:
                            # nothing valid to restart from — the exit is
                            # final, so dump the post-mortem first
                            self.flight.crash_report(cls.value, error=str(e))
                            raise
                        restarts += 1
                        telemetry.inc(tm.SUPERVISOR_RESTARTS)
                        telemetry.emit_event(
                            tm.EVENT_SUPERVISOR_RESTART,
                            label=self.label,
                            step=step,
                            restart=restarts,
                            budget=cfg.max_restarts,
                            failure_class=cls.value,
                            error=str(e)[:300],
                        )
                        log_warn(
                            f"{self.label}: {cls.value} at step ~{step} "
                            f"({e}); restarting from the last checkpoint "
                            f"({restarts}/{cfg.max_restarts})"
                        )
                        step = restored
                        last_ck = time.monotonic()
                        self._heartbeat(step, total_steps, restarts, last_ck)
                        continue
                    else:
                        # out of budget, no checkpoint to restart from, or a
                        # class the in-process machinery owns — propagate,
                        # leaving the crash report as the post-mortem
                        self.flight.crash_report(cls.value, error=str(e))
                        raise
                else:
                    step += n
                    if on_chunk is not None:
                        on_chunk(step, n)
                    self._heartbeat(step, total_steps, restarts, last_ck)
                if self._preempted:
                    if mid_chunk:
                        log_warn(
                            f"{self.label}: preemption interrupted a chunk "
                            f"mid-flight; skipping the final checkpoint (step "
                            "label would be stale) — the last ring entry stands"
                        )
                    else:
                        self._final_checkpoint(step, reason="preempt")
                    log_warn(
                        f"{self.label}: preempted ({self._preempt_why}) at "
                        f"step {step}; exiting resumable (code {EXIT_RESUMABLE})"
                    )
                    self._heartbeat(
                        step, total_steps, restarts, last_ck, phase="preempted"
                    )
                    self.flight.crash_report(
                        "preempted",
                        error=self._preempt_why,
                        mid_chunk=mid_chunk,
                        resumable_step=step,
                    )
                    return RunOutcome(
                        completed=False,
                        step=step,
                        restarts=restarts,
                        preempted=True,
                        exit_code=EXIT_RESUMABLE,
                    )
                now = time.monotonic()
                hit_steps = cfg.every_steps and step % cfg.every_steps == 0
                hit_wall = cfg.every_seconds and now - last_ck >= cfg.every_seconds
                if step < total_steps and (hit_steps or hit_wall):
                    self.checkpoint(step, reason="cadence")
                    last_ck = now
        finally:
            if prev_handler is not _NOT_INSTALLED:
                # a C-level previous handler reads back as None — restore
                # the default disposition rather than leaving OUR handler
                # swallowing SIGTERMs after run() returned
                signal.signal(
                    signal.SIGTERM,
                    prev_handler if prev_handler is not None else signal.SIG_DFL,
                )
        # completion checkpoint: the artifact soak/chaos harnesses compare
        # (manifest digests make that a metadata read), and the natural
        # resume-past-the-end no-op marker
        self.checkpoint(step, reason="final")
        self._heartbeat(
            step, total_steps, restarts, time.monotonic(), phase="completed"
        )
        return RunOutcome(completed=True, step=step, restarts=restarts)
