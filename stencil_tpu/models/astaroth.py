"""Astaroth MHD proxy: radius-3, sin-wave field, 6-neighbor averaging.

Parity target: reference bin/astaroth_sim.cu — a proxy for the Astaroth
magnetohydrodynamics code used to study compute/communication overlap:

* radius 3 in all 26 directions (astaroth_sim.cu:184)
* init: ``sin(2*pi/period * (x + y + z))`` over the interior
  (astaroth_sim.cu:15-61; period = 10 by default there)
* stencil: mean of the 6 face neighbors at distance 1 via ``Accessor``
  (astaroth_sim.cu:65-83) — the radius-3 halo is exchanged even though the
  proxy kernel reads only distance 1, exactly like the reference (it models
  Astaroth's real communication volume with a cheap kernel)
* loop: interior launch / exchange / exterior launches, 5 fixed iterations
  (astaroth_sim.cu:223-274)

The reference keeps 3 more quantities commented out (astaroth_sim.cu:193-196);
``num_quantities`` makes that scaling axis explicit here (the real Astaroth
exchanges 8 fields).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from stencil_tpu.core.radius import Radius
from stencil_tpu.domain import DistributedDomain
from stencil_tpu.utils.config import MethodFlags, PlacementStrategy


class AstarothSim:
    def __init__(
        self,
        x: int,
        y: int,
        z: int,
        num_quantities: int = 1,
        period: float = 10.0,
        overlap: bool = True,
        strategy: PlacementStrategy = PlacementStrategy.NodeAware,
        devices=None,
        dtype=jnp.float32,
        kernel_impl: str = "jnp",  # "jnp" | "pallas" (plane streaming)
        interpret: bool = False,
        schedule: str = "per-step",  # "per-step" (reference parity: exchange
        # every iteration, modeling Astaroth's comm volume) | "wavefront"
        # (opt-in: the radius-3 shell already feeds 3 levels of the
        # distance-1 stencil, so exchange every m <= 3 steps and run an
        # m-level wavefront kernel — same field values up to last-ulp
        # fusion effects, ~1/m the traffic)
    ):
        self.dd = DistributedDomain(x, y, z)
        self.dd.set_radius(Radius.constant(3))  # astaroth_sim.cu:184
        self.dd.set_placement(strategy)
        if devices is not None:
            self.dd.set_devices(devices)
        self.period = period
        self.handles = [
            self.dd.add_data(f"d{i}", dtype=dtype) for i in range(num_quantities)
        ]
        self.overlap = overlap
        self.kernel_impl = kernel_impl
        self.interpret = interpret
        if schedule not in ("per-step", "wavefront"):
            raise ValueError(f"unknown schedule {schedule!r}")
        self.schedule = schedule
        self._step = None
        self._marks_shell_stale = False
        self._wavefront_m = 0

    def realize(self) -> None:
        self.dd.realize()
        w = 2 * math.pi / self.period
        for h in self.handles:
            self.dd.init_by_coords(h, lambda x, y, z: jnp.sin(w * (x + y + z)))
        if self.kernel_impl == "pallas":
            if self.dd.halo_multiplier() != 1:
                raise ValueError("pallas path requires halo multiplier 1")
            if not self.overlap:
                raise ValueError(
                    "overlap=False has no meaning for the fused pallas step; "
                    "use kernel_impl='jnp' for overlap comparisons"
                )
            if self.schedule == "wavefront":
                self._step = self._make_wavefront_step()
            else:
                self._step = self._make_pallas_step()
        else:
            if self.schedule == "wavefront":
                raise ValueError("schedule='wavefront' requires kernel_impl='pallas'")
            self._step = self.dd.make_step(self._kernel, overlap=self.overlap)

    def _wrap_step_fn(self, per_shard):
        """Shared jit/shard_map wrapper for the pallas step makers:
        ``per_shard(steps, *blocks) -> blocks`` over P('x','y','z') shards.
        check_vma off: pallas_call outputs carry no vma annotation."""
        from functools import partial

        import jax
        from jax.sharding import PartitionSpec as P

        from stencil_tpu.parallel.mesh import MESH_AXES

        dd = self.dd
        names = [h.name for h in self.handles]
        spec = P(*MESH_AXES)

        @partial(jax.jit, static_argnums=1, donate_argnums=0)
        def step(curr, steps: int = 1):
            fn = jax.shard_map(
                partial(per_shard, steps),
                mesh=dd.mesh,
                in_specs=tuple(spec for _ in names),
                out_specs=tuple(spec for _ in names),
                check_vma=False,
            )
            outs = fn(*[curr[k] for k in names])
            return dict(zip(names, outs))

        return step

    def _make_pallas_step(self):
        """Plane-streaming mean-of-6 kernel (ops/plane_stencil) fused with the
        exchange — one HBM read + one write per plane per iteration."""
        from jax import lax

        from stencil_tpu.ops.exchange import halo_exchange_multi
        from stencil_tpu.ops.plane_stencil import mean6_plane_step
        from stencil_tpu.parallel.mesh import MESH_AXES

        dd = self.dd
        shell = dd._shell_radius
        lo, hi = shell.lo(), shell.hi()
        mesh_shape = tuple(dd.mesh.shape[a] for a in MESH_AXES)
        valid_last = dd._valid_last
        interpret = self.interpret

        def per_shard(steps, *blocks):
            def body(_, bs):
                # joint exchange: ≤6 permutes for any field count
                bs = halo_exchange_multi(bs, shell, mesh_shape, valid_last=valid_last)
                return tuple(
                    mean6_plane_step(b, lo, hi, interpret=interpret) for b in bs
                )

            return lax.fori_loop(0, steps, body, tuple(blocks))

        return self._wrap_step_fn(per_shard)

    def _make_wavefront_step(self):
        """Opt-in temporal schedule: one radius-3 shell exchange feeds an
        m-level mean6 wavefront (m <= 3, VMEM-fitted) — the per-step
        schedule's field values up to last-ulp fusion effects, at ~1/m the
        exchange traffic and HBM passes.  Requires even (unpadded) sizes (the wavefront kernel has no
        padded-axis form)."""
        from jax import lax

        from stencil_tpu.ops.exchange import halo_exchange_multi
        from stencil_tpu.ops.jacobi_pallas import wavefront_vmem_fits
        from stencil_tpu.ops.plane_stencil import mean6_shell_wavefront_step
        from stencil_tpu.parallel.mesh import MESH_AXES

        dd = self.dd
        if any(v is not None for v in dd._valid_last):
            raise ValueError("schedule='wavefront' requires even (unpadded) sizes")
        shell = dd._shell_radius
        s_w = shell.lo().x  # uniform radius 3
        raw = dd.local_spec().raw_size()
        itemsize = self.handles[0].dtype.itemsize
        m = 1
        for cand in range(2, s_w + 1):
            if wavefront_vmem_fits(cand, raw.y, raw.z, itemsize, d2_itemsize=0):
                m = cand
        self._wavefront_m = m
        mesh_shape = tuple(dd.mesh.shape[a] for a in MESH_AXES)
        valid_last = dd._valid_last
        interpret = self.interpret
        self._marks_shell_stale = True

        def per_shard(steps, *blocks):
            def macro(depth, bs):
                bs = halo_exchange_multi(bs, shell, mesh_shape, valid_last=valid_last)
                return tuple(
                    mean6_shell_wavefront_step(b, depth, s_w, interpret=interpret)
                    for b in bs
                )

            macros, rem = divmod(steps, m)
            bs = lax.fori_loop(0, macros, lambda _, b: macro(m, b), tuple(blocks))
            if rem:
                bs = macro(rem, bs)
            return bs

        return self._wrap_step_fn(per_shard)

    def _kernel(self, views, info):
        out = {}
        for h in self.handles:
            src = views[h.name]
            out[h.name] = (
                src.sh(-1, 0, 0)
                + src.sh(0, -1, 0)
                + src.sh(0, 0, -1)
                + src.sh(1, 0, 0)
                + src.sh(0, 1, 0)
                + src.sh(0, 0, 1)
            ) / 6.0
        return out

    def step(self, steps: int = 1) -> None:
        self.dd.run_step(self._step, steps)
        if self._marks_shell_stale:
            self.dd.mark_shell_stale()

    def field(self, i: int = 0) -> np.ndarray:
        return self.dd.quantity_to_host(self.handles[i])

    def block_until_ready(self) -> None:
        self.dd.block_until_ready()
