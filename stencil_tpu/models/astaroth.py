"""Astaroth MHD proxy: radius-3, sin-wave field, 6-neighbor averaging.

Parity target: reference bin/astaroth_sim.cu — a proxy for the Astaroth
magnetohydrodynamics code used to study compute/communication overlap:

* radius 3 in all 26 directions (astaroth_sim.cu:184)
* init: ``sin(2*pi/period * (x + y + z))`` over the interior
  (astaroth_sim.cu:15-61; period = 10 by default there)
* stencil: mean of the 6 face neighbors at distance 1 via ``Accessor``
  (astaroth_sim.cu:65-83) — the radius-3 halo is exchanged even though the
  proxy kernel reads only distance 1, exactly like the reference (it models
  Astaroth's real communication volume with a cheap kernel)
* loop: interior launch / exchange / exterior launches, 5 fixed iterations
  (astaroth_sim.cu:223-274)

The reference keeps 3 more quantities commented out (astaroth_sim.cu:193-196);
``num_quantities`` makes that scaling axis explicit here (the real Astaroth
exchanges 8 fields).

The pallas path runs ``_kernel`` VERBATIM under the plane-streaming engine
(``ops/stream.py``): the default ``schedule="auto"`` upgrades to the m-level
temporal wavefront — m <= 3 x the halo multiplier, since the radius-3 shell
feeds 3 levels of the distance-1 stencil per multiplier step (a
``set_halo_multiplier(2)`` run wavefronts 6 levels per exchange) — whenever
shards are even, ~2.6x faster at 512^3 than the per-step schedule; on one
device it upgrades further to the exchange-free wrap route.  ``--schedule
per-step`` restores exact exchange-cadence parity with the reference (one
exchange per iteration, modeling Astaroth's real communication volume).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from stencil_tpu.core.radius import Radius
from stencil_tpu.domain import DistributedDomain
from stencil_tpu.utils.config import MethodFlags, PlacementStrategy


class AstarothSim:
    def __init__(
        self,
        x: int,
        y: int,
        z: int,
        num_quantities: int = 1,
        period: float = 10.0,
        overlap: bool = True,
        strategy: PlacementStrategy = PlacementStrategy.NodeAware,
        devices=None,
        dtype=jnp.float32,
        kernel_impl: str = "jnp",  # "jnp" | "pallas" (plane streaming)
        interpret: bool = False,
        schedule: str = "auto",  # "auto" (DEFAULT: the radius-3 shell
        # already feeds 3 levels of the distance-1 stencil, so exchange
        # every m steps (m <= 3 x the halo multiplier) and run an m-level
        # wavefront kernel — same field values up to last-ulp fusion
        # effects, ~1/m the traffic; a single device upgrades to the
        # exchange-free wrap route) | "wavefront" (forced: raises when not
        # viable) | "per-step" (reference parity escape hatch: exchange
        # every iteration, modeling Astaroth's real communication volume —
        # astaroth_sim.cu:223-274)
        check_divergence_every: int = 0,  # divergence sentinel cadence
        # (resilience/sentinel.py); 0 = off
        stream_overlap: str = "auto",  # pallas engine only: the stream
        # engine's split-step overlap schedule (ops/stream.py
        # STREAM_OVERLAP; "auto" = env > tuned > static off)
        stream_halo: str = "auto",  # pallas engine only: the stream
        # engine's halo consumption mode (ops/stream.py STREAM_HALO;
        # "fused" lands the packed yzpack_* messages directly in the
        # pass's VMEM planes; "auto" = env > tuned > static array)
        exchange_route: str = None,  # pin the halo exchange's y/z-sweep
        # route (ops/exchange.py EXCHANGE_ROUTES; None/"auto" = env >
        # tuned > static direct)
        compute_unit: str = "auto",  # pallas engine only: the level
        # kernels' execution unit ("vpu" | "mxu" | "mxu_band" | "auto" =
        # env > tuned > static vpu).  The mxu units run ``_kernel_mxu`` —
        # the same mean-of-6 written through the views' banded-contraction
        # seam (PlaneView.plane_nbr_sum; ≤1 ulp/level vs vpu; mxu_band =
        # the blocked band form)
        mxu_input: str = "auto",  # pallas engine only: MXU contraction
        # operand precision ("f32" | "bf16" | "auto" = env > tuned >
        # static f32); inert under vpu
        storage_dtype: str = None,  # field buffers' storage axis ("native"
        # | "bf16" | None/"auto" = env > tuned > static native): bf16
        # stores f32 fields at 2 B/cell end-to-end while the stream kernels
        # accumulate at f32; the XLA engine degrades to native
    ):
        self.dd = DistributedDomain(x, y, z)
        self.dd.set_radius(Radius.constant(3))  # astaroth_sim.cu:184
        self.dd.set_placement(strategy)
        if devices is not None:
            self.dd.set_devices(devices)
        self.period = period
        self.handles = [
            self.dd.add_data(f"d{i}", dtype=dtype) for i in range(num_quantities)
        ]
        self.overlap = overlap
        self.kernel_impl = kernel_impl
        self.interpret = interpret
        if schedule not in ("auto", "per-step", "wavefront"):
            raise ValueError(f"unknown schedule {schedule!r}")
        self.schedule = schedule
        self.stream_overlap = stream_overlap
        self.stream_halo = stream_halo
        if exchange_route not in (None, "auto"):
            self.dd.set_exchange_route(exchange_route)
        self.compute_unit = compute_unit
        self.mxu_input = mxu_input
        self.storage_dtype_request = storage_dtype
        self._storage_dtype = "native"
        if check_divergence_every:
            self.dd.set_divergence_check(check_divergence_every)
        self._step = None

    def realize(self) -> None:
        # storage dtype resolves BEFORE allocation (explicit >
        # STENCIL_STORAGE_DTYPE > tuned "stream" config > static native);
        # only the pallas (stream) engine has f32-accumulate kernels
        from stencil_tpu.ops.jacobi_pallas import resolve_storage_dtype

        tuned = None
        if self.storage_dtype_request in (None, "auto") and self.kernel_impl == "pallas":
            from stencil_tpu import tune

            cfg = tune.best_config(self.dd.tune_key("stream"))
            tuned = (cfg or {}).get("storage_dtype")
        sd, _src = resolve_storage_dtype(
            self.storage_dtype_request,
            tuned,
            [h.dtype for h in self.handles],
            where="astaroth",
            engine_ok=self.kernel_impl == "pallas",
            engine_why="the XLA slice engine has no f32-accumulate kernels",
        )
        self._storage_dtype = sd
        if sd != "native":
            self.dd.set_storage(sd)
        self.dd.realize()
        w = 2 * math.pi / self.period
        for h in self.handles:
            self.dd.init_by_coords(h, lambda x, y, z: jnp.sin(w * (x + y + z)))
        # shipped numerics guardband (docs/observability.md "Numerics
        # observatory"): the mean-of-6 update is non-expansive, so every
        # quantity's magnitude stays under its unit-amplitude sin init —
        # a growing absmax means the numerics drifted.  Envelope at 1.5x
        # the amplitude: far above any rounding, far below a real blow-up.
        from stencil_tpu.telemetry.numerics import magnitude_envelope

        self.dd.numerics().register_guardband(
            magnitude_envelope(1.5, quantities=tuple(h.name for h in self.handles))
        )
        if self.dd.halo_multiplier() != 1 and self.schedule == "per-step":
            # on EITHER kernel_impl a multiplier means fewer, wider
            # exchanges — the opposite of the cadence 'per-step' promises
            raise ValueError(
                "schedule='per-step' (exchange-cadence parity) "
                "contradicts a halo multiplier; use schedule='auto'"
            )
        if self.kernel_impl == "pallas":
            # the plane-streaming ENGINE (ops/stream.py) runs the model's own
            # _kernel verbatim: per-step exchange = plane route, wavefront
            # schedule = the engine's m-level temporal route (m <= 3 x the
            # halo multiplier — the radius-3 shell feeds 3 levels of the
            # distance-1 stencil per multiplier step); step() counts RAW
            # iterations on every engine (see AstarothSim.step)
            if not self.overlap:
                raise ValueError(
                    "overlap=False has no meaning for the fused pallas step; "
                    "use kernel_impl='jnp' for overlap comparisons"
                )
        elif self.schedule == "wavefront":
            raise ValueError("schedule='wavefront' requires kernel_impl='pallas'")
        self._step = self._build_step()

    def _build_step(self):
        """The ONE step-construction site, shared by ``realize()`` and
        ``rebuild_after_reshard`` — every knob threaded into ``make_step``
        lives here exactly once, so a post-reshard rebuild can never
        silently drop an axis the first build carried."""
        if self.kernel_impl == "pallas":
            path = {"auto": "auto", "wavefront": "wavefront", "per-step": "plane"}[
                self.schedule
            ]
            return self.dd.make_step(
                self._kernel,
                engine="stream",
                x_radius=1,
                stream_path=path,
                # _kernel updates each field from itself only, so many-field
                # runs may stream per-field at full wavefront depth
                separable=True,
                interpret=self.interpret,
                stream_overlap=self.stream_overlap,
                stream_halo=self.stream_halo,
                compute_unit=self.compute_unit,
                mxu_input=self.mxu_input,
                # the declared axis-separable contraction form — what lets
                # compute_unit=mxu engage on this kernel
                mxu_kernel=self._kernel_mxu,
            )
        return self.dd.make_step(self._kernel, overlap=self.overlap)

    def rebuild_after_reshard(self) -> None:
        """Rebuild the step for the domain's CURRENT mesh — the
        supervisor's ``on_mesh_change`` hook (the Jacobi3D twin): a
        reshard or cross-mesh restore leaves ``self.dd`` on the new
        geometry, and the built step closes over the old one."""
        self._step = self._build_step()

    @property
    def _wavefront_m(self) -> int:
        """CURRENT wavefront depth (0 = per-step) — read from the live
        stream plan, which the engine's runtime VMEM fallback may have
        stepped down after realize()."""
        plan = getattr(self._step, "_stream_plan", None)
        if plan is not None and plan["route"] == "wavefront":
            return plan["m"]
        return 0

    def _kernel(self, views, info):
        # iterate the views HANDED IN (not self.handles): each field updates
        # from itself only, so the kernel is correct on any subset — the
        # separability the stream engine exploits for per-field passes
        out = {}
        for name, src in views.items():
            out[name] = (
                src.sh(-1, 0, 0)
                + src.sh(0, -1, 0)
                + src.sh(0, 0, -1)
                + src.sh(1, 0, 0)
                + src.sh(0, 1, 0)
                + src.sh(0, 0, 1)
            ) / 6.0
        return out

    def _kernel_mxu(self, views, info):
        # the SAME mean-of-6 with its four in-plane taps written through the
        # banded-contraction seam (PlaneView.plane_nbr_sum) — on the MXU
        # when the engine hands the views band matrices, and ≤1 ulp/level
        # from `_kernel` either way (the in-plane pair sums regroup); the
        # x taps stay plane reads.  The vpu `_kernel` above is untouched,
        # so the default path stays bitwise-identical to pre-axis builds.
        out = {}
        for name, src in views.items():
            out[name] = (
                src.sh(-1, 0, 0) + src.sh(1, 0, 0) + src.plane_nbr_sum()
            ) / 6.0
        return out

    def step(self, steps: int = 1) -> None:
        """Advance ``steps`` RAW iterations — uniform across engines (the
        stream engine counts raw iterations natively; the XLA route under a
        halo multiplier is built in macro steps, so ``steps`` must divide
        into whole macros there)."""
        mult = self.dd.halo_multiplier()
        if self.kernel_impl == "jnp" and mult > 1:
            if steps % mult:
                raise ValueError(
                    f"steps={steps} must be a multiple of the halo "
                    f"multiplier {mult} on the jnp engine (macro steps)"
                )
            steps //= mult
        # label routes dispatch-phase fault injection / retry logs to THIS
        # model (the stream engine's own ladder hooks stay labeled stream:*)
        self.dd.run_step(self._step, steps, label="astaroth")

    def field(self, i: int = 0) -> np.ndarray:
        return self.dd.quantity_to_host(self.handles[i])

    def block_until_ready(self) -> None:
        self.dd.block_until_ready()
