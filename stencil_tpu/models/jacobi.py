"""7-point Jacobi heat stencil with hot/cold sphere forcing.

Parity target: reference bin/jacobi3d.cu — the flagship app.  Semantics
replicated exactly:

* single float quantity, radius-1 faces-only stencil (jacobi3d.cu:205-214,227)
* init: whole domain at (HOT+COLD)/2 (jacobi3d.cu:15-29)
* forcing (jacobi3d.cu:40-66): a hot sphere (radius = X/10) centered at
  (X/3, Y/2, Z/2) is clamped to HOT each step; a cold sphere at (2X/3, Y/2,
  Z/2) clamped to COLD; elsewhere next = mean of the 6 face neighbors.
  ``dist`` is the reference's float-sqrt truncated to integer
  (jacobi3d.cu:31-33).
* iteration: overlapped interior/exchange/exterior pipeline or single
  whole-region kernel under --no-overlap (jacobi3d.cu:265-337).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from stencil_tpu.utils.compat import shard_map

from stencil_tpu.core.dim3 import Dim3
from stencil_tpu.core.radius import Radius
from stencil_tpu.domain import DistributedDomain
from stencil_tpu.utils.config import MethodFlags, PlacementStrategy

COLD_TEMP = 0.0
HOT_TEMP = 1.0


class Jacobi3D:
    def __init__(
        self,
        x: int,
        y: int,
        z: int,
        overlap: bool = True,
        strategy: PlacementStrategy = PlacementStrategy.NodeAware,
        methods: MethodFlags = MethodFlags.All,
        devices=None,
        dtype=jnp.float32,
        kernel_impl: str = "jnp",  # "jnp" (XLA slices) | "pallas" (plane streaming)
        interpret: bool = False,  # pallas interpreter mode (CPU testing)
        temporal_k="auto",  # wrap-path temporal blocking depth (int | "auto")
        pallas_path: str = "auto",  # "auto"|"wrap"|"slab"|"shell"|"wavefront"
        check_divergence_every: int = 0,  # divergence sentinel cadence
        # (resilience/sentinel.py); 0 = off
        wavefront_alias: bool = None,  # input_output_aliases on the wavefront
        # kernels: None = env (STENCIL_WAVEFRONT_ALIAS) > tuned config >
        # un-aliased static default; the autotuner's candidate builds set it
        # explicitly
        z_ring: bool = None,  # z-RING vs padded layout preference: None =
        # env (STENCIL_Z_RING) > tuned config > ring default; structural
        # gates (lane alignment, slab mode) still apply either way
        compute_unit: str = None,  # level kernels' execution unit ("vpu" |
        # "mxu" | "mxu_band" | None/"auto"): mxu contracts the in-plane
        # taps against banded coefficient matrices on the matrix unit (≤1
        # ulp/level vs vpu); mxu_band runs the blocked (2r+1)-band form of
        # the same contraction (ulp-pinned vs dense, ~n/(2r+1)× fewer
        # FLOPs).  None/"auto" = STENCIL_COMPUTE_UNIT > tuned config >
        # static vpu; structural guards (non-f32 compute, routes with no
        # contraction kernel, untilable plane geometry for the band form)
        # degrade with a warning
        mxu_input: str = None,  # MXU contraction operand precision ("f32"
        # | "bf16" | None/"auto" = STENCIL_MXU_INPUT > tuned config >
        # static f32): bf16 narrows the operands (~2× MXU ratio) under the
        # unchanged f32-accumulate contract — analytic bound
        # tests/ulp.mxu_bf16_input_atol; inert under vpu
        storage_dtype: str = None,  # field buffers' storage axis ("native"
        # | "bf16" | None/"auto"): bf16 stores f32 fields at 2 B/cell
        # end-to-end (HBM, VMEM pipeline, exchange messages) while the
        # kernels accumulate at f32 and downcast once per pass.  None/
        # "auto" = STENCIL_STORAGE_DTYPE > tuned config > static native;
        # non-f32 fields and the XLA engine degrade to native with a warning
    ):
        self.dd = DistributedDomain(x, y, z)
        # radius 1 on faces only (jacobi3d.cu:205-214)
        radius = Radius.constant(0)
        radius.set_face(1)
        self.dd.set_radius(radius)
        self.dd.set_methods(methods)
        self.dd.set_placement(strategy)
        if devices is not None:
            self.dd.set_devices(devices)
        self.h = self.dd.add_data("temp", dtype=dtype)
        self.overlap = overlap
        self.kernel_impl = kernel_impl
        self.interpret = interpret
        self.temporal_k = temporal_k
        if pallas_path not in ("auto", "wrap", "slab", "shell", "wavefront"):
            raise ValueError(f"unknown pallas_path {pallas_path!r}")
        self.pallas_path_request = pallas_path
        self.wavefront_alias_request = wavefront_alias
        self.z_ring_request = z_ring
        self.compute_unit_request = compute_unit
        self.mxu_input_request = mxu_input
        self.storage_dtype_request = storage_dtype
        # resolved axes (realize() / the step builders fill these in)
        self._compute_unit = "vpu"
        self._mxu_input = "f32"
        self._storage_dtype = "native"
        self._mxu_flops_iter = 0  # analytic MXU FLOPs per raw iteration
        if check_divergence_every:
            self.dd.set_divergence_check(check_divergence_every)
        # tuned config applied by _plan_wavefront (auto mode only)
        self._tuned_wavefront = None
        self._step = None
        self._ladder = None  # degradation ladder, built at realize()
        # fast paths (wrap/slab kernels) advance interiors only; the carried
        # shell goes stale and raw readback must re-exchange (mark_shell_stale)
        self._marks_shell_stale = False
        # which pallas route realize() picked:
        # "wrap" | "wavefront" | "slab" | "shell"
        self._pallas_path = None

    def realize(self) -> None:
        self._wavefront_m = 0
        # storage dtype resolves FIRST: it shapes the allocation and the
        # VMEM-model itemsizes every later plan (wavefront fits, temporal-k)
        # consults
        self._resolve_storage()
        if self.kernel_impl == "pallas" and self.pallas_path_request in ("auto", "wavefront"):
            # must be decided BEFORE dd.realize(): the wavefront path rides
            # the halo-multiplier machinery (m-wide shells, exchange every m
            # steps), which shapes the allocation
            if self.pallas_path_request == "wavefront":
                self._wavefront_m = self._plan_wavefront()  # raises if not viable
            elif self.dd.halo_multiplier() == 1 and self._planned_devices() > 1:
                try:
                    m = self._plan_wavefront()
                except ValueError:
                    m = 0  # uneven sizes etc. — slab/shell routes handle it
                # depth 1 buys nothing over the slab route; require real blocking
                self._wavefront_m = m if m >= 2 else 0
            if self._wavefront_m:
                self.dd.set_halo_multiplier(self._wavefront_m)
        self.dd.realize()
        # set compute region to (HOT+COLD)/2 (jacobi3d.cu:15-29, 253-263)
        mid = (HOT_TEMP + COLD_TEMP) / 2
        self.dd.init_by_coords(self.h, lambda x, y, z: jnp.full((), mid) + 0 * (x + y + z))
        # shipped numerics guardband (docs/observability.md "Numerics
        # observatory"): jacobi's clamped mean-of-6 update obeys the
        # diffusion max principle — the field can never leave [COLD, HOT];
        # a cell outside the band is numerical drift long before anything
        # overflows to inf.  Registration is idempotent (keyed by label);
        # it fires only on the numerics cadence, so an unsnapshotted run
        # pays nothing.
        from stencil_tpu.telemetry.numerics import max_principle

        # band widened by 1e-5 of the span: the f32-accumulated mean can
        # legitimately overshoot the exact bound by a few ulps (six adds at
        # magnitude ~6 before the divide) — the guardband hunts drift, not
        # last-ulp rounding
        pad = 1e-5 * (HOT_TEMP - COLD_TEMP)
        self.dd.numerics().register_guardband(
            max_principle(
                COLD_TEMP - pad, HOT_TEMP + pad, quantities=(self.h.name,)
            )
        )
        if self.kernel_impl == "pallas":
            if self._wavefront_m:
                self._step = self._make_wavefront_step()
            else:
                # the plane-streaming kernel hard-codes a 1-cell shell ring
                if self.dd.halo_multiplier() != 1:
                    raise ValueError(
                        "kernel_impl='pallas' requires halo multiplier 1 "
                        "(the plane kernel assumes a radius-1 shell); use "
                        "kernel_impl='jnp' with set_halo_multiplier, or "
                        "pallas_path='wavefront' which sets its own"
                    )
                self._step = self._make_pallas_step()
        else:
            self._step = self.dd.make_step(self._kernel, overlap=self.overlap)
        self._ladder = self._make_ladder()

    def _planned_devices(self) -> int:
        import jax

        devs = self.dd._devices
        return len(devs) if devs is not None else len(jax.devices())

    def _prospective_tune_route(self):
        """The workload-key route the build WILL consult (pre-realize
        mirror of the route choice) — where the tuned compute-unit/
        storage-dtype fields live; None when no tunable pallas route can be
        reached (jnp engine, forced slab/shell)."""
        if self.kernel_impl != "pallas":
            return None
        req = self.pallas_path_request
        single = self._planned_devices() == 1
        if req == "wrap" or (req == "auto" and single):
            return "jacobi-wrap"
        if req in ("auto", "wavefront") and not single:
            return "jacobi-wavefront"
        return None

    def _resolve_storage(self) -> None:
        """Resolve the storage-dtype axis (explicit ctor knob >
        ``STENCIL_STORAGE_DTYPE`` > tuned config > static ``native`` —
        ops/jacobi_pallas.resolve_storage_dtype) and pin the result on the
        domain BEFORE allocation.  The XLA engine has no f32-accumulate
        kernels, so it structurally degrades bf16 to native."""
        from stencil_tpu.ops.jacobi_pallas import resolve_storage_dtype

        route = self._prospective_tune_route()
        tuned = None
        if self.storage_dtype_request in (None, "auto") and route is not None:
            from stencil_tpu import tune

            cfg = tune.best_config(self.dd.tune_key(route))
            tuned = (cfg or {}).get("storage_dtype")
        sd, _src = resolve_storage_dtype(
            self.storage_dtype_request,
            tuned,
            [self.h.dtype],
            where=f"jacobi:{route or self.kernel_impl}",
            engine_ok=self.kernel_impl == "pallas",
            engine_why="the XLA slice engine has no f32-accumulate kernels",
        )
        self._storage_dtype = sd
        if sd != "native":
            self.dd.set_storage(sd)

    def _plan_wavefront(self) -> int:
        """Choose the wavefront depth m (>= 1) before ``dd.realize()``: mirror
        the domain's deterministic mesh/shard computation and fit
        ``temporal_k`` ("auto") within the shard extents and the modeled VMEM
        limit.  Prefers the z-slab kernel variant (z halos never touch the
        tiled array) and records the choice in ``self._wavefront_z_planned``.

        PADDED (uneven) shards are supported on the PLAIN kernel variant:
        the valid-width exchange places each halo contiguously after the
        valid cells, so the wavefront's shrinking-validity and
        wrapped-coordinate arguments hold unchanged at the dynamic positions
        (see ``ops/stream.plan_stream``); the z-slab form's static interior
        emit slices keep it even-shard-only, and the depth is capped by the
        smallest VALID extent (partition.hpp:83-114 parity: remainders run
        at full speed)."""
        import jax

        from stencil_tpu.ops.jacobi_pallas import (
            _WRAP_MAX_K,
            warn_if_over_vmem_budget,
            wavefront_vmem_fits,
        )
        from stencil_tpu.parallel.mesh import make_mesh

        dd = self.dd
        if dd.halo_multiplier() != 1:
            raise ValueError("pallas_path='wavefront' manages the halo multiplier itself")
        devices = list(dd._devices) if dd._devices is not None else jax.devices()
        _, placement = make_mesh(
            dd._size, dd._radius, devices, dd._strategy, force_dim=dd._force_dim
        )
        dim = placement.dim()
        n = [-(-dd._size[ax] // dim[ax]) for ax in range(3)]
        padded = any(dd._size[ax] != n[ax] * dim[ax] for ax in range(3))
        # last-shard valid extents; min caps the depth (a shard must fill an
        # m-wide halo for its neighbor from valid cells)
        v = [dd._size[ax] - n[ax] * (dim[ax] - 1) for ax in range(3)]
        if min(v) < 1:
            raise ValueError(
                f"pallas_path='wavefront': empty last shard for {tuple(dd._size)} "
                f"over mesh {tuple(dim)}"
            )
        n_min = min(min(n), min(v))
        # pipeline planes stream at the STORAGE itemsize; the level ring
        # carries the f32_accumulate working precision (native itemsize)
        itemsize = self.dd.field_dtype(self.h).itemsize
        ring_itemsize = self.h.dtype.itemsize
        # PROSPECTIVE compute unit (emit=False — the authoritative
        # resolution with its telemetry event happens at build time in
        # _make_wavefront_step): folds the contraction form's resident
        # band-matrix constants into the depth gate below
        from stencil_tpu import tune
        from stencil_tpu.ops.jacobi_pallas import (
            mxu_supported,
            resolve_compute_unit,
        )

        p_mxu = False  # False or the prospective unit string — the VMEM
        # model prices the resolved variant (dense constants vs band tiles)
        if mxu_supported([self.h.dtype]):  # else build-time warns once
            cfg0 = tune.best_config(dd.tune_key("jacobi-wavefront")) or {}
            p_unit, _ = resolve_compute_unit(
                self.compute_unit_request, cfg0.get("compute_unit"),
                [self.h.dtype], where="jacobi-wavefront", emit=False,
            )
            from stencil_tpu.ops.jacobi_pallas import unit_uses_mxu

            p_mxu = p_unit if unit_uses_mxu(p_unit) else False
        # planning diagnostics for the autotuner's candidate-space builder
        # (tune/runners.autotune_jacobi_wavefront)
        self._wavefront_plan_info = {
            "n": tuple(n), "valid": tuple(v), "padded": padded, "n_min": n_min,
        }

        def fits(m, z):
            return wavefront_vmem_fits(
                m, n[1] + 2 * m, n[2] + 2 * m, itemsize, z_slabs=z,
                ring_itemsize=ring_itemsize, mxu=p_mxu,
            )

        if self.temporal_k != "auto":
            m = int(self.temporal_k)
            if not 1 <= m <= n_min:
                raise ValueError(
                    f"wavefront temporal_k={m} needs 1 <= m <= min(shard/valid)={n_min}"
                )
            warn_if_over_vmem_budget(m, n[1] + 2 * m, n[2] + 2 * m, itemsize,
                                     ring_itemsize, mxu=p_mxu)
            self._wavefront_z_planned = fits(m, True) and not padded
            return m
        # the autotuner's persisted on-device measurement beats the static
        # model below (docs/tuning.md); only structural bounds are
        # re-checked — a tuned m may exceed the shell-traffic heuristic cap,
        # that is the point of measuring
        from stencil_tpu import tune

        cfg = tune.best_config(dd.tune_key("jacobi-wavefront"))
        if cfg is not None:
            m = cfg.get("m")
            if isinstance(m, int) and 1 <= m <= n_min:
                self._tuned_wavefront = cfg
                self._wavefront_z_planned = fits(m, True) and not padded
                return m
            from stencil_tpu.utils.logging import log_warn

            log_warn(
                f"tuned config {cfg} for jacobi-wavefront is structurally "
                f"invalid here (need 1 <= m <= {n_min}); using the static plan"
            )
        # n_min//4 caps the redundant shell traffic: a depth-m macro step
        # exchanges ~6*m*n^2 extra cells against m*n^3 of compute, so keep
        # the shell a small fraction of the shard
        depth_cap = min(_WRAP_MAX_K, max(1, n_min // 4), n_min)
        for z_mode in ((True, False) if not padded else (False,)):
            m = 1 if not z_mode else 0
            for cand in range(2, depth_cap + 1):
                if fits(cand, z_mode):
                    m = cand
            if m >= 2 or not z_mode:
                self._wavefront_z_planned = z_mode and m >= 2
                return max(m, 1)
        raise AssertionError("unreachable: z_mode=False always returns")

    def _make_wavefront_step(self):
        """Temporally-blocked multi-device step: one m-wide shell exchange
        feeds an m-level wavefront kernel (``jacobi_shell_wavefront_step``) —
        ~8/m HBM bytes per cell per iteration, the multi-device counterpart
        of the wrap path's temporal blocking.  A steps%m remainder runs one
        shallower wavefront over the same shell.

        The z halos never touch the big array (``STENCIL_Z_SLABS=0``
        disables): a z-halo read or write on the tiled layout rewrites whole
        (8,128)-tile columns (~a full-domain pass per exchange, probe12d),
        so the z-shell lives in a separate z-major (Xr, 2m, Yr) packed slab
        array (rows [0,m) = low halo, [m,2m) = high) that the kernel
        consumes (VMEM column patching via one small per-plane transpose)
        and emits (next macro's outgoing slabs).  Corner data propagates on the slabs themselves:
        after the z ppermute, each slab is extended with rows from the y
        neighbors and then planes from the x neighbors (two hops carry the
        xyz-corner cells from the diagonal blocks), mirroring the sweep
        order of the in-array exchange."""
        from functools import partial

        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from stencil_tpu.ops.exchange import halo_exchange_shard
        from stencil_tpu.ops.jacobi_pallas import (
            _ZRING_OFF,
            jacobi_shell_wavefront_step,
            jacobi_zring_wavefront_step,
            pack_d2,
            yz_dist2_plane,
            zring_dist2_plane,
        )
        from stencil_tpu.ops.stream import (
            lane_pad_width,
            make_slab_extenders,
            permute_and_extend_z_slabs,
            prime_z_slabs,
        )
        from stencil_tpu.parallel.mesh import MESH_AXES

        dd = self.dd
        m = self._wavefront_m
        # effective depth <= the allocated shell width m: the VMEM-OOM
        # fallback steps it down WITHOUT reallocating (the kernel supports
        # depth < shell via interior_offset; the exchange keeps the full
        # m-wide shell, just refreshed every `depth` steps)
        depth_run = getattr(self, "_wavefront_depth", m)
        assert 1 <= depth_run <= m, (depth_run, m)
        n = dd.local_spec().sz
        shell = dd._shell_radius
        mesh_shape = tuple(dd.mesh.shape[a] for a in MESH_AXES)
        gsize = tuple(dd.size())
        raw = dd.local_spec().raw_size()
        interpret = self.interpret
        name = self.h.name
        from stencil_tpu.utils.config import env_bool

        tuned = self._tuned_wavefront or {}
        # compute-unit axis: explicit ctor knob > STENCIL_COMPUTE_UNIT >
        # tuned config > static vpu; non-f32 compute dtypes degrade
        from stencil_tpu.ops.jacobi_pallas import (
            mxu_flops_per_plane,
            resolve_compute_unit,
            resolve_mxu_input,
            unit_uses_mxu,
        )

        unit, _unit_src = resolve_compute_unit(
            self.compute_unit_request,
            tuned.get("compute_unit"),
            [self.h.dtype],
            where="jacobi-wavefront",
        )
        self._compute_unit = unit
        mi, _mi_src = resolve_mxu_input(
            self.mxu_input_request, tuned.get("mxu_input"), unit,
            where="jacobi-wavefront",
        )
        self._mxu_input = mi
        f32_acc = dd.field_dtype(self.h) != self.h.dtype
        kern_kw = {
            "compute_unit": unit, "f32_accumulate": f32_acc, "mxu_input": mi,
        }
        z_slab_mode = env_bool("STENCIL_Z_SLABS", True) and getattr(
            self, "_wavefront_z_planned", False
        )
        # In-place aliasing serializes the deep-m pipeline (probe21b, 512^3:
        # m=16 aliased 84k vs un-aliased 102k Mcells/s) — default to a fresh
        # output buffer and trade one raw-sized HBM allocation for ~20%.
        # The un-aliased kernel leaves high-x shell planes UNINITIALIZED;
        # every consumer (next macro's exchange, stale-shell readback)
        # rewrites the shell before reading it, so no garbage escapes.
        # Precedence: constructor request (autotuner candidate builds) >
        # STENCIL_WAVEFRONT_ALIAS (validated read) > the tuned config for
        # this workload > the un-aliased static default above.
        if self.wavefront_alias_request is not None:
            alias = bool(self.wavefront_alias_request)
        else:
            env_alias = env_bool("STENCIL_WAVEFRONT_ALIAS", None)
            if env_alias is not None:
                alias = env_alias
            elif tuned.get("alias") is not None:
                alias = bool(tuned["alias"])
            else:
                alias = False
        self._marks_shell_stale = True
        self._pallas_path = "wavefront"
        self._wavefront_z_slabs = z_slab_mode
        Xr, Yr, Zr = raw.x, raw.y, raw.z
        # z-RING layout: when the shard's z interior is lane-aligned, drop
        # the z-shell columns from HBM entirely — the kernel stages each
        # plane into a ring-layout working plane whose lane wrap is
        # periodic-consistent (jacobi_zring_wavefront_step) — cutting the
        # streamed bytes by the whole z pad share (~20% at 512^3 m=16,
        # probe24/25).  STENCIL_Z_RING=0 restores the padded layout.
        # ring preference: constructor request > STENCIL_Z_RING (validated
        # read) > the tuned config's measured layout pick > ring by default
        # (probe25d: neutral wall-clock on v5e, smaller footprint)
        if self.z_ring_request is not None:
            ring_pref = bool(self.z_ring_request)
        else:
            env_ring = env_bool("STENCIL_Z_RING", None)
            if env_ring is not None:
                ring_pref = env_ring
            elif tuned.get("z_ring") is not None:
                ring_pref = bool(tuned["z_ring"])
            else:
                ring_pref = True
        z_ring_mode = (
            z_slab_mode
            and n.z % 128 == 0
            and 2 * m <= _ZRING_OFF
            and ring_pref
        )
        self._wavefront_z_ring = z_ring_mode
        # Ragged lane extents cripple the plane DMA (probe22: 512^2x516
        # streams 30% slower than 512^3; 512^2x640 runs at full per-byte
        # rate), so the z-slab route rounds the plane width up to a 128
        # multiple with dead columns the kernel treats as outside the domain
        # (z_valid).  Padding/unpadding happens once per step() dispatch,
        # amortized over the device-side macro loop.
        Zp = lane_pad_width(Zr) if z_slab_mode else Zr
        # analytic MXU FLOPs per raw iteration (all shards): one band
        # contraction pair per streamed plane per level, counted for the
        # RESOLVED variant (the dense model over-reports a band-tiled run
        # by ~n/(2r+1)) on the plane geometry the kernel actually
        # CONTRACTS — the z-ring kernel works over the (Yr, OFF + Zi)
        # ring plane, the padded-shell kernel over (Yr, Zp); the variant
        # a geometry admits (band_tile_plan) differs with the width, so
        # pricing the wrong plane could count the wrong variant — the
        # kernel.mxu.flops per-step increment (step())
        _flops_pz = (_ZRING_OFF + n.z) if z_ring_mode else Zp
        self._mxu_flops_iter = (
            mxu_flops_per_plane(Yr, _flops_pz, unit) * Xr * dd.num_subdomains()
            if unit_uses_mxu(unit)
            else 0
        )

        def per_shard(steps, raw_block):
            # origin (and everything derived from it, like the d2 planes)
            # must be computed INSIDE each loop body: axis_index lowers to
            # partition-id, which XLA's SPMD partitioner rejects as a
            # while-loop operand on some toolchains (see ops/stream.py
            # origin_of; LICM re-hoists it after partitioning)
            def origin_of():
                return jnp.stack(
                    [lax.axis_index(MESH_AXES[ax]) * n[ax] for ax in range(3)]
                )

            def d2_of(origin):
                return pack_d2(
                    yz_dist2_plane(
                        origin[1] - m, origin[2] - m, (raw.y, Zp), gsize
                    ),
                    gsize,
                )

            if not z_slab_mode:
                def macro_plain(depth, b):
                    origin = origin_of()
                    yz_d2 = d2_of(origin)
                    b = halo_exchange_shard(
                        b, shell, mesh_shape, valid_last=dd._valid_last
                    )
                    return jacobi_shell_wavefront_step(
                        b, depth, origin, yz_d2, gsize, interior_offset=m,
                        alias=alias, interpret=interpret, **kern_kw,
                    )

                macros, rem = divmod(steps, depth_run)
                b = lax.fori_loop(
                    0, macros, lambda _, b: macro_plain(depth_run, b), raw_block
                )
                if rem:
                    b = macro_plain(rem, b)
                return b

            # slab y/x extension (corner propagation) + z permute + priming
            # are shared with the generic engine (ops/stream.py helpers)
            yext, xext = make_slab_extenders(Xr, Yr, m, mesh_shape)

            if z_ring_mode:
                # z-interior-only HBM layout + ring-layout working planes
                Zi = n.z

                def macro_ring(depth, carry):
                    origin = origin_of()
                    ring_d2 = pack_d2(
                        zring_dist2_plane(
                            origin[1] - m, origin[2], m, Yr, Zi, gsize
                        ),
                        gsize,
                    )
                    b, zout = carry
                    b = halo_exchange_shard(b, shell, mesh_shape, axes=(0, 1))
                    zs = permute_and_extend_z_slabs(zout, m, mesh_shape, yext, xext)
                    return jacobi_zring_wavefront_step(
                        b, depth, origin, ring_d2, gsize, z_slabs=zs,
                        interior_offset=m, alias=alias, interpret=interpret,
                        **kern_kw,
                    )

                b0 = lax.slice(
                    raw_block, (0, 0, m), (Xr, Yr, m + Zi)
                )  # drop the z-shell columns from the streamed array
                carry = (b0, prime_z_slabs(raw_block, Zr, m))
                macros, rem = divmod(steps, depth_run)
                carry = lax.fori_loop(
                    0, macros, lambda _, c: macro_ring(depth_run, c), carry
                )
                if rem:
                    carry = macro_ring(rem, carry)
                # re-inflate with zero z-shell columns instead of writing
                # back into raw_block: equivalent (the shell is stale either
                # way) and lets raw_block's buffer die at the b0 slice
                # instead of living across the whole macro loop
                return jnp.pad(carry[0], ((0, 0), (0, 0), (m, m)))

            def macro(depth, carry):
                origin = origin_of()
                yz_d2 = d2_of(origin)
                b, zout = carry
                # x/y shells in the array (cheap: planes / sublane rows)
                b = halo_exchange_shard(b, shell, mesh_shape, axes=(0, 1))
                # zout is z-major (Xr, 2m, Yr): [(-z)-bound | (+z)-bound]
                zs = permute_and_extend_z_slabs(zout, m, mesh_shape, yext, xext)
                return jacobi_shell_wavefront_step(
                    b, depth, origin, yz_d2, gsize, interior_offset=m,
                    z_slabs=zs, z_valid=Zr, alias=alias, interpret=interpret,
                    **kern_kw,
                )

            # prime the slab carry from the block's interior z boundaries
            # (z-major), then lane-pad the block
            carry = (
                jnp.pad(raw_block, ((0, 0), (0, 0), (0, Zp - Zr))),
                prime_z_slabs(raw_block, Zr, m),
            )
            macros, rem = divmod(steps, depth_run)
            carry = lax.fori_loop(0, macros, lambda _, c: macro(depth_run, c), carry)
            if rem:
                carry = macro(rem, carry)
            return carry[0][:, :, :Zr]

        spec = P(*MESH_AXES)

        @partial(jax.jit, static_argnums=1, donate_argnums=0)
        def step(curr, steps: int = 1):
            # check_vma off: pallas_call outputs carry no vma annotation
            fn = shard_map(
                partial(per_shard, steps),
                mesh=dd.mesh,
                in_specs=(spec,),
                out_specs=spec,
                check_vma=False,
            )
            return {name: fn(curr[name])}

        return step

    def _make_pallas_step(self):
        """Fused exchange + plane-streaming pallas kernel (ops/jacobi_pallas):
        one HBM read + one write per plane per iteration, vs ~6 reads for the
        XLA slice formulation.

        Three routes, fastest applicable wins (``self._pallas_path`` records
        the choice):

        * ``wrap``  — 1 subdomain: periodic wrap folds into the kernel, no
          exchange at all.
        * ``slab``  — multi-device, even sizes: 6 bare face-slab ppermutes
          consumed DIRECTLY by the kernel (``jacobi_slab_step``) — no shell
          writes, no halo re-read; the traffic of the wrap kernel plus the 6
          messages.  The TPU expression of the reference's production
          overlapped multi-GPU pipeline (jacobi3d.cu:265-337).
          SUPERSEDED as a default by the temporally-blocked ``wavefront``
          (m levels per exchange vs this route's 1); kept for explicit
          request and as the m=1 structural baseline.  Its Mosaic
          z-column-rotate constraint (128-aligned shard x-extent) makes it
          unreachable for most real mesh shapes — by design we did not lift
          it, since the wavefront route both outperforms it and has no such
          constraint.
        * ``shell`` — fallback (uneven/padded sizes, or shards with < 2
          x-planes): the general shell-carrying exchange + plane kernel.
        """
        from functools import partial

        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from stencil_tpu.ops.exchange import halo_exchange_shard
        from stencil_tpu.ops.jacobi_pallas import (
            choose_temporal_k,
            jacobi_plane_step,
            jacobi_wrap_step,
            yz_dist2_plane,
        )
        from stencil_tpu.parallel.mesh import MESH_AXES

        dd = self.dd
        want = self.pallas_path_request
        if want == "wrap" and dd.num_subdomains() != 1:
            raise ValueError("pallas_path='wrap' requires a single subdomain")
        # the slab kernel's z-column dynamic rotate (pltpu.roll on a (Y, X)
        # slab) compiles only when the lane extent X is 128-aligned (Mosaic
        # "unsupported unaligned shape" otherwise — scripts/probe11b at 64^3)
        slab_aligned = self.interpret or dd.local_spec().sz.x % 128 == 0
        if want == "slab" and (
            any(v is not None for v in dd._valid_last)
            or dd.local_spec().sz.x < 2
            or not slab_aligned
        ):
            raise ValueError(
                "pallas_path='slab' requires even (unpadded) sizes, >= 2 "
                "x-planes per shard, and a 128-aligned x-extent per shard "
                "when compiled for TPU"
            )
        if want == "wrap" or (want == "auto" and dd.num_subdomains() == 1):
            # single-device fast path: the periodic wrap folds into the
            # kernel's index maps/rotates — no shell reads, no exchange (the
            # reference's same-GPU translate kernels disappear too).  The
            # shell-carrying HBM layout is kept; interior is sliced out once
            # per dispatch and written back (amortized over `steps`).
            spec_ = dd.local_spec()
            n = spec_.sz
            lo = dd._shell_radius.lo()
            name = self.h.name
            interpret = self.interpret
            self._marks_shell_stale = True
            self._pallas_path = "wrap"
            # pipeline planes stream at the STORAGE itemsize; the level
            # compute-unit axis: explicit ctor knob > STENCIL_COMPUTE_UNIT >
            # tuned config > static vpu — resolved BEFORE the depth choice
            # so the VMEM model can fold in the contraction form's resident
            # band matrices (choose_temporal_k's mxu= term)
            from stencil_tpu import tune
            from stencil_tpu.ops.jacobi_pallas import (
                mxu_flops_per_plane,
                resolve_compute_unit,
                resolve_mxu_input,
                unit_uses_mxu,
            )

            cfg = tune.best_config(dd.tune_key("jacobi-wrap")) or {}
            unit, _unit_src = resolve_compute_unit(
                self.compute_unit_request,
                cfg.get("compute_unit"),
                [self.h.dtype],
                where="jacobi-wrap",
            )
            self._compute_unit = unit
            mi, _mi_src = resolve_mxu_input(
                self.mxu_input_request, cfg.get("mxu_input"), unit,
                where="jacobi-wrap",
            )
            self._mxu_input = mi
            # ring carries the f32_accumulate working precision, so the
            # VMEM model takes both (a storage-only model under bf16 would
            # admit depths whose f32 ring blows the budget); the mxu term
            # prices the RESOLVED variant (dense constants vs band tiles)
            k = choose_temporal_k(
                (n.x, n.y, n.z), dd.field_dtype(self.h).itemsize,
                self.temporal_k,
                tune_key=dd.tune_key("jacobi-wrap"),
                ring_itemsize=self.h.dtype.itemsize,
                mxu=unit if unit_uses_mxu(unit) else False,
            )
            self._wrap_k = k
            f32_acc = dd.field_dtype(self.h) != self.h.dtype
            kern_kw = {
                "compute_unit": unit, "f32_accumulate": f32_acc,
                "mxu_input": mi,
            }
            self._mxu_flops_iter = (
                mxu_flops_per_plane(n.y, n.z, unit) * n.x
                if unit_uses_mxu(unit)
                else 0
            )

            @partial(jax.jit, static_argnums=1, donate_argnums=0)
            def step(curr, steps: int = 1):
                arr = curr[name]
                block = lax.slice(
                    arr, (lo.x, lo.y, lo.z), (lo.x + n.x, lo.y + n.y, lo.z + n.z)
                )
                # temporal blocking: steps//k wavefront dispatches touch HBM
                # once per k iterations; the remainder runs unblocked.  Each
                # level's arithmetic is identical to a k=1 pass, so any
                # (blocked, remainder) split is bit-exact vs k=1.
                blocked, rem = divmod(steps, k)
                if blocked:
                    block = lax.fori_loop(
                        0,
                        blocked,
                        lambda _, b: jacobi_wrap_step(
                            b, interpret=interpret, k=k, **kern_kw
                        ),
                        block,
                    )
                if rem:
                    # one k=rem wavefront (rem < k <= X//2 so always valid);
                    # bit-exact and one HBM pass instead of rem
                    block = jacobi_wrap_step(
                        block, interpret=interpret, k=rem, **kern_kw
                    )
                # stencil-lint: disable=sliver-dus whole-interior write-back into the shell-carrying array after the k-loop — block spans the full interior, not a y/z sliver
                return {name: lax.dynamic_update_slice(arr, block, (lo.x, lo.y, lo.z))}

            return step
        if want in ("auto", "slab") and (
            all(v is None for v in dd._valid_last)
            and dd.local_spec().sz.x >= 2
            and slab_aligned
        ):
            return self._make_slab_step()
        self._pallas_path = "shell"
        self._resolve_unit_no_contraction("jacobi-shell")
        n = dd.local_spec().sz
        shell = dd._shell_radius
        mesh_shape = tuple(dd.mesh.shape[a] for a in MESH_AXES)
        gsize = tuple(dd.size())
        valid_last = dd._valid_last
        interpret = self.interpret
        name = self.h.name
        f32_acc = dd.field_dtype(self.h) != self.h.dtype

        def per_shard(steps, block):
            shape_yz = (block.shape[1] - 2, block.shape[2] - 2)

            def body(_, b):
                # inside the loop body: axis_index as a while operand trips
                # the SPMD partitioner on some toolchains (see ops/stream.py)
                origin = jnp.stack(
                    [lax.axis_index(MESH_AXES[ax]) * n[ax] for ax in range(3)]
                )
                yz_d2 = yz_dist2_plane(origin[1], origin[2], shape_yz, gsize)
                b = halo_exchange_shard(b, shell, mesh_shape, valid_last=valid_last)
                return jacobi_plane_step(
                    b, origin, yz_d2, gsize, interpret=interpret,
                    f32_accumulate=f32_acc,
                )

            return lax.fori_loop(0, steps, body, block)

        spec = P(*MESH_AXES)

        @partial(jax.jit, static_argnums=1, donate_argnums=0)
        def step(curr, steps: int = 1):
            # check_vma off: pallas_call out_shape carries no vma annotation
            fn = shard_map(
                partial(per_shard, steps),
                mesh=dd.mesh,
                in_specs=(spec,),
                out_specs=spec,
                check_vma=False,
            )
            return {name: fn(curr[name])}

        return step

    def _make_slab_step(self):
        """Multi-device fast path: ppermute six BARE face slabs and hand them
        to ``jacobi_slab_step``, which patches the boundary rows/columns while
        streaming planes — no shell blend writes, no halo re-read (the double
        traffic of the shell route).  The interior is sliced out of the
        shell-carrying storage once per dispatch and written back once, both
        amortized over the device-side step loop.  Matches the reference's
        production overlapped pipeline (jacobi3d.cu:265-337); exactly 6
        collective-permutes per iteration, the same count test_hlo pins for
        the general exchange."""
        from functools import partial

        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from stencil_tpu.ops.exchange import _shift_from_high, _shift_from_low
        from stencil_tpu.ops.jacobi_pallas import jacobi_slab_step, yz_dist2_plane
        from stencil_tpu.parallel.mesh import MESH_AXES

        dd = self.dd
        n = dd.local_spec().sz
        lo = dd._shell_radius.lo()
        mesh_shape = tuple(dd.mesh.shape[a] for a in MESH_AXES)
        gsize = tuple(dd.size())
        interpret = self.interpret
        name = self.h.name
        self._marks_shell_stale = True
        self._pallas_path = "slab"
        self._resolve_unit_no_contraction("jacobi-slab")
        f32_acc = dd.field_dtype(self.h) != self.h.dtype

        def per_shard(steps, raw_block):
            block = lax.slice(
                raw_block, (lo.x, lo.y, lo.z), (lo.x + n.x, lo.y + n.y, lo.z + n.z)
            )

            def body(_, b):
                # inside the loop body: axis_index as a while operand trips
                # the SPMD partitioner on some toolchains (see ops/stream.py)
                origin = jnp.stack(
                    [lax.axis_index(MESH_AXES[ax]) * n[ax] for ax in range(3)]
                )
                yz_d2 = yz_dist2_plane(origin[1], origin[2], (n.y, n.z), gsize)
                # each slab is the sender's outermost interior plane — the
                # -dir convention at radius 1 (packer.cuh:91-93); z-slabs
                # travel transposed so lanes ride the x axis (see
                # jacobi_slab_step's layout note)
                xlo = _shift_from_low(b[n.x - 1], MESH_AXES[0], mesh_shape[0])
                xhi = _shift_from_high(b[0], MESH_AXES[0], mesh_shape[0])
                ylo = _shift_from_low(b[:, n.y - 1, :], MESH_AXES[1], mesh_shape[1])
                yhi = _shift_from_high(b[:, 0, :], MESH_AXES[1], mesh_shape[1])
                zlo = _shift_from_low(b[:, :, n.z - 1].T, MESH_AXES[2], mesh_shape[2])
                zhi = _shift_from_high(b[:, :, 0].T, MESH_AXES[2], mesh_shape[2])
                return jacobi_slab_step(
                    b, xlo, xhi, ylo, yhi, zlo, zhi, origin, yz_d2, gsize,
                    interpret=interpret, f32_accumulate=f32_acc,
                )

            block = lax.fori_loop(0, steps, body, block)
            # stencil-lint: disable=sliver-dus whole-interior write-back after the step loop — block spans the full interior, not a y/z sliver
            return lax.dynamic_update_slice(raw_block, block, (lo.x, lo.y, lo.z))

        spec = P(*MESH_AXES)

        @partial(jax.jit, static_argnums=1, donate_argnums=0)
        def step(curr, steps: int = 1):
            # check_vma off: pallas_call outputs carry no vma annotation
            fn = shard_map(
                partial(per_shard, steps),
                mesh=dd.mesh,
                in_specs=(spec,),
                out_specs=spec,
                check_vma=False,
            )
            return {name: fn(curr[name])}

        return step

    def _resolve_unit_no_contraction(self, where: str) -> None:
        """Compute-unit resolution for routes WITHOUT a contraction kernel
        (slab/shell): any mxu request — explicit, env, or tuned — degrades
        to vpu with a warning instead of crashing or silently engaging."""
        from stencil_tpu.ops.jacobi_pallas import resolve_compute_unit

        unit, _src = resolve_compute_unit(
            self.compute_unit_request,
            None,
            [self.h.dtype],
            where=where,
            engine_ok=False,
            engine_why="the slab/shell routes have no contraction kernels",
        )
        self._compute_unit = unit
        self._mxu_flops_iter = 0

    def _kernel(self, views, info):
        size = info.global_size
        hot_c = Dim3(size.x // 3, size.y // 2, size.z // 2)
        cold_c = Dim3(size.x * 2 // 3, size.y // 2, size.z // 2)
        sphere_r = size.x // 10

        src = views["temp"]
        val = (
            src.sh(1, 0, 0)
            + src.sh(-1, 0, 0)
            + src.sh(0, 1, 0)
            + src.sh(0, -1, 0)
            + src.sh(0, 0, 1)
            + src.sh(0, 0, -1)
        ) / 6.0

        cx, cy, cz = info.coords()

        def dist2(c: Dim3):
            return (cx - c.x) ** 2 + (cy - c.y) ** 2 + (cz - c.z) ** 2

        # the reference's truncated-float-sqrt membership (jacobi3d.cu:31-33):
        # floor(sqrtf(d2)) <= r  is exactly  d2 < (r+1)^2  while
        # (r+1)*ulp(r+1) < 1, i.e. r+1 < ~2896 (gx up to ~29,000 at
        # r = gx/10) — beyond that correctly-rounded sqrtf((r+1)^2 - 1)
        # rounds up to exactly r+1 and the predicates diverge.  Amply
        # satisfied at realistic sizes, so skip the sqrt entirely.
        in_r2 = (sphere_r + 1) ** 2
        val = jnp.where(dist2(hot_c) < in_r2, HOT_TEMP, val)
        val = jnp.where(dist2(cold_c) < in_r2, COLD_TEMP, val)
        return {"temp": val.astype(src.center().dtype)}

    def rebuild_after_reshard(self) -> None:
        """Rebuild the step function + ladder for the domain's CURRENT
        mesh — the supervisor's ``on_mesh_change`` hook: a reshard (or a
        restore onto a different mesh) leaves ``self.dd`` on the new
        geometry, but the built steps close over the old one.  Device
        state is untouched; this only re-traces the step builders."""
        if self.kernel_impl == "pallas":
            if self._wavefront_m:
                self._step = self._make_wavefront_step()
            else:
                self._step = self._make_pallas_step()
        else:
            self._step = self.dd.make_step(self._kernel, overlap=self.overlap)
        self._ladder = self._make_ladder()

    def step(self, steps: int = 1) -> None:
        """Advance ``steps`` RAW iterations — uniform across engines.  The
        XLA route under a halo multiplier is built in macro steps
        (make_step: one exchange per ``mult`` iterations), so ``steps`` must
        divide into whole macros there; the pallas routes count raw
        iterations natively (their wavefront manages its own multiplier)."""
        mult = self.dd.halo_multiplier()
        if self.kernel_impl == "jnp" and mult > 1:
            if steps % mult:
                raise ValueError(
                    f"steps={steps} must be a multiple of the halo "
                    f"multiplier {mult} on the jnp engine (macro steps)"
                )
            steps //= mult
        # analytic, from the plan the run STARTS on (a mid-run ladder
        # step-down keeps the pre-degrade count for this call)
        mxu_flops = steps * self._mxu_flops_iter
        self._ladder.step(steps)
        if mxu_flops:
            from stencil_tpu import telemetry
            from stencil_tpu.telemetry import names as tm

            telemetry.inc(tm.KERNEL_MXU_FLOPS, mxu_flops)
        if self._marks_shell_stale:
            self.dd.mark_shell_stale()

    def _rung_name(self) -> str:
        if self.kernel_impl != "pallas":
            return "xla"
        suffix = (
            f",{self._compute_unit}" if self._compute_unit != "vpu" else ""
        )
        if self.dd.storage_dtype() == "bf16":
            suffix += ",bf16"
        if self._pallas_path == "wrap":
            return f"wrap[k={self._wrap_k}{suffix}]"
        if self._pallas_path == "wavefront":
            depth = getattr(self, "_wavefront_depth", self._wavefront_m)
            return f"wavefront[depth={depth}{suffix}]"
        return (self._pallas_path or "pallas") + suffix

    def _run_current(self, steps: int = 1) -> None:
        # resolves self._step at CALL time: the degradation ladder swaps the
        # built step underneath when a rung steps down
        self.dd.run_step(self._step, steps, label="jacobi")

    def _make_ladder(self):
        """The model's degradation ladder (resilience/ladder.py): wrap
        re-plans at k-1 per descent, the wavefront keeps its allocated
        m-wide shell and advances fewer levels per pass — the same implicit
        order the old hand-rolled try/except walked, now with classified
        failures, donation-guarded re-invocation, and fault-injection hooks
        labeled ``jacobi:<rung>``."""
        from stencil_tpu.resilience.ladder import DegradationLadder, Rung

        def rung():
            return Rung(name=self._rung_name(), build=lambda: self._run_current)

        def lower(rung_, cls, exc):
            return rung() if self._step_down(cls) else None

        return DegradationLadder(
            rung(), lower=lower, label="jacobi", buffers=lambda: self.dd._curr
        )

    def _step_down(self, cls) -> bool:
        """Runtime fallback for the bespoke pallas paths: when Mosaic
        rejects the planned temporal depth (scoped-VMEM OOM or another
        classified compile reject — the calibrated model under-estimated on
        this toolchain), rebuild one level shallower instead of crashing.
        The wavefront keeps its allocated m-wide shell and just advances
        fewer levels per pass (``_wavefront_depth``); the wrap path re-plans
        with ``temporal_k-1``.  Returns True when a shallower rebuild was
        installed."""
        from stencil_tpu.utils.logging import log_warn

        if self.kernel_impl != "pallas":
            return False
        # the new-axis rungs come BEFORE any depth descent: an mxu or bf16
        # build carries its own extra compiler surface (band matmuls /
        # mixed-dtype pipelines), so the failure may be the axis's fault,
        # not the depth's — step the axis down at the SAME depth first.
        # The contraction walks band → dense → vpu: the blocked form's
        # reshape/batched-dot lowering may be what the compiler rejected
        # while the dense contraction still serves the matrix unit.
        if self._compute_unit == "mxu_band":
            log_warn(
                f"compute_unit=mxu_band on the {self._pallas_path} route "
                f"exceeded the compiler's capability ({cls.value}); stepping "
                "down to the dense mxu form at the same depth"
            )
            self.compute_unit_request = "mxu"  # forced for the rebuild
            self._rebuild_current_route()
            return True
        if self._compute_unit == "mxu":
            log_warn(
                f"compute_unit=mxu on the {self._pallas_path} route exceeded "
                f"the compiler's capability ({cls.value}); stepping down to "
                "vpu at the same depth"
            )
            self.compute_unit_request = "vpu"  # forced for the rebuild
            self._rebuild_current_route()
            return True
        if self.dd.storage_dtype() == "bf16":
            log_warn(
                f"storage_dtype=bf16 on the {self._pallas_path} route "
                f"exceeded the compiler's capability ({cls.value}); stepping "
                "down to native storage at the same depth (exact: every "
                "bfloat16 value upcasts losslessly)"
            )
            self._convert_storage_to_native()
            self._rebuild_current_route()
            return True
        if self._pallas_path == "wrap" and self._wrap_k > 1:
            self.temporal_k = self._wrap_k - 1
            log_warn(
                f"wrap temporal depth k={self._wrap_k} exceeded the compiler's "
                f"capability ({cls.value}); retrying k={self.temporal_k} "
                "(for vmem_oom: recalibrate the VMEM model / "
                "STENCIL_VMEM_LIMIT_BYTES for this toolchain)"
            )
            self._step = self._make_pallas_step()
            return True
        if self._pallas_path == "wavefront":
            depth = getattr(self, "_wavefront_depth", self._wavefront_m)
            if depth <= 1:
                return False
            self._wavefront_depth = depth - 1
            log_warn(
                f"wavefront depth {depth} exceeded the compiler's capability "
                f"({cls.value}); retrying depth {depth - 1} over the same "
                f"{self._wavefront_m}-wide shell (for vmem_oom: recalibrate "
                "the VMEM model for this toolchain)"
            )
            self._step = self._make_wavefront_step()
            return True
        return False

    def _rebuild_current_route(self) -> None:
        """Rebuild the installed step for the CURRENT route after an axis
        step-down (mxu->vpu / bf16->native) — same depth, same allocation.
        The wrap rebuild re-runs ``choose_temporal_k`` (whose auto/tuned
        resolution could shift under the changed storage itemsize), so pin
        the depth explicitly: the axis steps down FIRST, depth only through
        its own later ladder rungs."""
        if self._pallas_path == "wrap":
            self.temporal_k = self._wrap_k
        if self._pallas_path == "wavefront":
            self._step = self._make_wavefront_step()
        else:
            self._step = self._make_pallas_step()

    def _convert_storage_to_native(self) -> None:
        """Runtime bf16->native step-down: upcast the live field buffers
        (exact — every bfloat16 is an f32) and re-mark the domain native so
        rebuilt kernels, the exchange, and the byte accounting all follow.
        Post-realize by necessity (this is a ladder rung, the allocation
        already exists), hence the direct ``_storage`` write rather than
        ``set_storage``'s pre-realize setter."""
        dd = self.dd
        dd._storage = "native"
        self._storage_dtype = "native"
        for h in dd._handles:
            for slot in (dd._curr, dd._next):
                if h.name in slot:
                    slot[h.name] = slot[h.name].astype(h.dtype)
        # the analytic exchange-bytes cache and the compiled exchange were
        # built over the narrow buffers; drop both so they re-derive
        dd._exchange_nbytes = None
        dd._packed_nbytes = dd._packed_nkernels = 0
        dd._exchange_many_fn = None

    def temperature(self) -> np.ndarray:
        return self.dd.quantity_to_host(self.h)

    def block_until_ready(self) -> None:
        self.dd.block_until_ready()


def weak_scaled_size(base: int, num_subdomains: int) -> int:
    """jacobi3d.cu:167-169: scale each axis by numSubdoms^(1/3), rounded."""
    return int(float(base) * float(num_subdomains) ** 0.33333 + 0.5)
