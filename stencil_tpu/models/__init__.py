"""Model families: the reference's driver applications as reusable models.

* ``jacobi`` — 7-point Jacobi heat stencil with hot/cold sphere forcing
  (reference bin/jacobi3d.cu), the flagship app.
* ``astaroth`` — radius-3 multi-quantity MHD proxy (reference
  bin/astaroth_sim.cu).
"""

from stencil_tpu.models.jacobi import Jacobi3D
from stencil_tpu.models.astaroth import AstarothSim

__all__ = ["Jacobi3D", "AstarothSim"]
