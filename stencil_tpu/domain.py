"""``DistributedDomain`` — the public orchestrator.

Parity target: reference ``DistributedDomain`` (include/stencil/stencil.hpp:61
+ src/stencil.cu).  Same lifecycle: construct with a global size, configure
(``set_radius`` / ``add_data`` / ``set_methods`` / ``set_placement``), then
``realize()`` and iterate ``exchange()`` / compute / ``swap()``.

TPU design (not a translation):

* A quantity is ONE global ``jax.Array`` sharded ``P('x','y','z')`` over the
  3D device mesh.  Each shard is the reference's ``LocalDomain`` allocation —
  interior plus halo shell (``raw_size``) — so the global array has shape
  ``dim * raw_size`` and the *logical* user domain is the union of shard
  interiors.  Double buffering is two array slots whose references swap
  (reference src/local_domain.cu:41-54); buffer donation makes the step
  in-place in HBM.
* ``exchange()`` is a jitted 3-axis-sweep ppermute (ops/exchange.py) — the
  whole transport layer of the reference.
* ``make_step`` builds the fused exchange+compute step with
  interior/exterior overlap (reference src/stencil.cu:567-666 +
  jacobi3d.cu:265-337): interior compute carries no data dependency on the
  ppermutes, so XLA overlaps communication with compute — the job of the
  reference's entire sender/recver state-machine zoo.

Uneven global sizes (the reference's ±1-cell remainders, partition.hpp:83-114)
are handled by pad-and-mask: every shard is padded to ``ceil(size/dim)`` (XLA
shards must be equal), the LAST shard on a padded axis owns the remainder, the
exchange uses per-shard dynamic slab offsets so halos carry VALID cells across
the periodic wrap, and host gather/scatter masks the padding (SURVEY.md §7
"Hard parts").
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import time
from functools import partial
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from stencil_tpu.core.dim3 import Dim3, Rect3
from stencil_tpu.utils.compat import shard_map
from stencil_tpu.core.geometry import LocalSpec
from stencil_tpu.core.radius import Radius
from stencil_tpu.ops.exchange import (
    halo_exchange_multi,
    halo_exchange_shard,
    make_exchange_fn,
)
from stencil_tpu.parallel.mesh import MESH_AXES, make_mesh
from stencil_tpu.parallel.placement import Placement
from stencil_tpu import telemetry
from stencil_tpu.telemetry import names as tm
from stencil_tpu.utils.config import MethodFlags, PlacementStrategy
from stencil_tpu.utils.logging import log_debug, log_info, log_warn


@dataclasses.dataclass(frozen=True)
class DataHandle:
    """Typed handle to a named quantity (reference local_domain.cuh:17-25).

    ``components`` are leading per-cell dims (N-D data — the reference's
    future-work item, README.md:157-176): a (3,)-component quantity stores a
    vector per cell as a (3, X, Y, Z) array, unsharded on the component dim.
    """

    name: str
    dtype: object
    components: tuple = ()

    def cell_count(self) -> int:
        n = 1
        for c in self.components:
            n *= c
        return n


@dataclasses.dataclass
class DomainStats:
    """Setup/exchange wall-time accounting (reference STENCIL_SETUP_STATS /
    STENCIL_EXCHANGE_STATS, stencil.hpp:106-131).  Setup phases map:
    mpi_topo -> process/device discovery, placement -> partition+QAP solve,
    realize -> array allocation, plan -> exchange-fn construction,
    create -> jit trace+compile of the exchange (the analog of sender/recver
    creation + CUDA-Graph capture, src/stencil.cu:385-529)."""

    time_topo: float = 0.0
    time_placement: float = 0.0
    time_realize: float = 0.0
    time_plan: float = 0.0
    time_create: float = 0.0
    time_exchange: float = 0.0
    time_swap: float = 0.0


class ShardView:
    """Per-shard stencil-term access used inside step kernels.

    ``sh(dx,dy,dz)`` returns the region's cells shifted by the offset —
    the reference's ``src[o + Dim3(dx,dy,dz)]`` Accessor pattern
    (accessor.hpp:27-40) as a fused slice.
    """

    def __init__(self, block: jax.Array, r_lo: Dim3, region: Tuple[slice, slice, slice]):
        self._block = block
        self._lo = r_lo
        self._region = region

    def sh(self, dx: int = 0, dy: int = 0, dz: int = 0) -> jax.Array:
        idx = []
        for ax, d in zip(range(3), (dx, dy, dz)):
            s = self._region[ax]
            idx.append(slice(self._lo[ax] + s.start + d, self._lo[ax] + s.stop + d))
        # leading component dims (N-D data) ride unsliced
        return self._block[(Ellipsis,) + tuple(idx)]

    def center(self) -> jax.Array:
        return self.sh(0, 0, 0)


@dataclasses.dataclass
class BlockInfo:
    """Traced per-shard context handed to step kernels."""

    origin: Tuple[jax.Array, jax.Array, jax.Array]  # global coords of interior start
    interior: Dim3  # interior size per shard
    global_size: Dim3
    radius: Radius
    region: Tuple[slice, slice, slice]  # interior-local region being computed

    def coords(self):
        """Global (x, y, z) coordinate arrays for the region, broadcastable
        to the region's shape.  Wrapped periodically: regions extended into
        the halo shell (halo-multiplier sub-steps) see the coordinates of the
        cells the shell mirrors."""
        s = self.region
        g = self.global_size
        cx = (self.origin[0] + jnp.arange(s[0].start, s[0].stop)) % g.x
        cy = (self.origin[1] + jnp.arange(s[1].start, s[1].stop)) % g.y
        cz = (self.origin[2] + jnp.arange(s[2].start, s[2].stop)) % g.z
        return cx[:, None, None], cy[None, :, None], cz[None, None, :]


#: a step kernel: (views, info) -> {name: new values for info.region}
StepKernel = Callable[[Dict[str, ShardView], BlockInfo], Dict[str, jax.Array]]


def _qspec(h: DataHandle) -> P:
    """PartitionSpec for a quantity: spatial dims sharded over the mesh,
    leading component dims (N-D data) unsharded."""
    return P(*([None] * len(h.components)), *MESH_AXES)


class DistributedDomain:
    def __init__(self, x: int, y: int, z: int):
        self._size = Dim3(x, y, z)
        self._radius = Radius.constant(0)
        self._handles: List[DataHandle] = []
        self._methods = MethodFlags.All
        self._strategy = PlacementStrategy.NodeAware
        self._devices: Optional[Sequence] = None
        self._realized = False
        # post-realize state
        self.mesh: Optional[Mesh] = None
        self.placement: Optional[Placement] = None
        self._spec: Optional[LocalSpec] = None
        self._valid_last: Tuple[Optional[int], Optional[int], Optional[int]] = (None, None, None)
        self._curr: Dict[str, jax.Array] = {}
        self._next: Dict[str, jax.Array] = {}
        self._exchange_fn = None
        self._exchange_many_fn = None
        self._exchange_count = 0
        # z-sweep exchange route (ops/exchange.py EXCHANGE_ROUTES): resolved
        # at realize() — explicit request > STENCIL_EXCHANGE_ROUTE > tuned
        # config > static "direct"; packed-route analytic accounting rides it
        self._exchange_route_req: Optional[str] = None
        self._exchange_route = "direct"
        # storage-dtype axis (ops/jacobi_pallas STORAGE_DTYPES): models
        # resolve the axis (explicit > STENCIL_STORAGE_DTYPE > tuned >
        # static native) and pin the RESOLVED value here before realize();
        # field allocation, exchange byte accounting, and the packed z-shell
        # messages all follow ``field_dtype``
        self._storage = "native"
        self._packed_nbytes = 0
        self._packed_nkernels = 0
        self._halo_mult = 1
        self._shell_stale = False
        self._shell_radius: Optional[Radius] = None
        self._force_dim: Optional[Dim3] = None
        self.stats = DomainStats()
        # blocking per-exchange timing costs a device sync per call, exactly
        # like the reference's barrier-per-call EXCHANGE_STATS (default OFF,
        # CMakeLists.txt:20); opt in via env or enable_exchange_stats().
        from stencil_tpu.utils.config import env_bool, env_int

        self._exchange_stats = env_bool("STENCIL_EXCHANGE_STATS", False)
        # resilience: divergence sentinel (off unless STENCIL_DIVERGENCE_EVERY
        # or set_divergence_check sets a cadence) + dispatch retry policy,
        # both lazily built on first run_step

        self._divergence_every = env_int("STENCIL_DIVERGENCE_EVERY", 0, minimum=0)
        self._sentinel = None
        # numerics observatory (telemetry/numerics.py): the fused on-device
        # field-health engine, built lazily on first use; the observe
        # cadence (snapshots + guardbands per STENCIL_NUMERICS_EVERY /
        # --numerics-every) is independent of the sentinel's
        self._numerics_every = env_int("STENCIL_NUMERICS_EVERY", 0, minimum=0)
        self._numerics = None
        self._retry_policy = None
        # dispatch watchdog (resilience/watchdog.py): resolved lazily from
        # STENCIL_WATCHDOG_S at first dispatch, or installed programmatically
        self._watchdog = None
        self._watchdog_resolved = False
        # analytic bytes per exchange (exchange_bytes_total), computed once
        # per realize() for the telemetry counters; the per-hop decomposition
        # (exchange_hop_bytes) is cached beside it as (counter, bytes) pairs
        self._exchange_nbytes: Optional[int] = None
        self._hop_nbytes: Optional[List[Tuple[str, int]]] = None

    def set_watchdog(self, wd) -> None:
        """Install (or clear, with ``None``) a dispatch watchdog
        (``resilience/watchdog.DispatchWatchdog``): every ``run_step`` and
        ``exchange`` dispatch is then armed with its deadline — a dispatch
        that wedges past it emits a ``watchdog.stall`` event naming the
        phase, and in abort mode is interrupted and re-raised as a
        classified ``StallError`` for the supervisor to restart on.
        Without this call, ``STENCIL_WATCHDOG_S`` configures one from the
        environment at first dispatch."""
        self._watchdog = wd
        self._watchdog_resolved = True

    def _get_watchdog(self):
        if not self._watchdog_resolved:
            from stencil_tpu.resilience.watchdog import DispatchWatchdog

            self._watchdog = DispatchWatchdog.from_env()
            self._watchdog_resolved = True
        return self._watchdog

    def _watched_call(self, phase: str, fn):
        """Run one dispatch under the watchdog (when configured).

        The jitted call returns at ENQUEUE on asynchronous backends — a
        wedged collective surfaces at the sync — so the watched region
        includes a ``block_until_ready`` on the dispatch's own outputs:
        the deadline covers the execution, not just the enqueue.  (The
        sync is watchdog-mode only; unwatched dispatches keep jax's async
        pipelining.)  An abort-mode interrupt is converted into the
        classified ``StallError``; in observe-only mode a KeyboardInterrupt
        stays a KeyboardInterrupt — a user Ctrl-C must never be re-labeled
        by a stale, uninterrupting deadline trip."""
        wd = self._get_watchdog()
        if wd is None:
            return fn()
        try:
            with wd.watch(phase):
                out = fn()
                jax.block_until_ready(out)
                return out
        except KeyboardInterrupt:
            if wd.abort:
                stall = wd.take_stall()
                if stall is not None:
                    raise stall from None
            raise

    def set_divergence_check(self, every: int) -> None:
        """Enable the divergence sentinel (resilience/sentinel.py): every
        ``every`` raw steps run through ``run_step``, each floating quantity
        is checked for NaN/Inf on-device (ONE fused numerics dispatch —
        telemetry/numerics.py) and a classified ``DIVERGENCE`` error names
        the quantity, the global first-non-finite coordinate, and the
        bracketing step window.  0 disables (the default).  A mid-run
        cadence change preserves the sentinel's accumulated step count, so
        reported divergence steps stay correct."""
        self._divergence_every = int(every)
        if self._sentinel is not None:
            self._sentinel.set_every(self._divergence_every)

    def set_numerics_every(self, every: int) -> None:
        """Enable the numerics observatory's snapshot cadence
        (telemetry/numerics.py): every ``every`` raw steps through
        ``run_step``, one fused on-device health snapshot (per-quantity
        min/max/absmax/mean/L2/non-finite stats) lands in the engine's
        ring and runs the registered guardbands.  0 disables (the
        default; ``STENCIL_NUMERICS_EVERY`` / ``--numerics-every`` set it
        from the run surface).  Like ``set_divergence_check``, a mid-run
        change preserves the accumulated step count."""
        self._numerics_every = int(every)
        if self._numerics is not None:
            self._numerics.set_every(self._numerics_every)

    def numerics(self):
        """This domain's :class:`~stencil_tpu.telemetry.numerics.
        NumericsEngine` — the fused on-device field-statistics program
        (built lazily, memoized per geometry signature, auto-rebuilt after
        a mesh transition).  The divergence sentinel, the observe cadence,
        and direct callers (tests, guardband registration) all share this
        one engine, so they share one compiled program and one snapshot
        ring."""
        if self._numerics is None:
            from stencil_tpu.telemetry.numerics import NumericsEngine

            self._numerics = NumericsEngine(self, every=self._numerics_every)
        return self._numerics

    # --- configuration (stencil.hpp:276-306) ---------------------------------
    def set_radius(self, radius) -> None:
        self._radius = Radius.constant(radius) if isinstance(radius, int) else radius

    def radius(self) -> Radius:
        return self._radius

    def add_data(self, name: str, dtype=jnp.float32, components=()) -> DataHandle:
        h = DataHandle(name, jnp.dtype(dtype), tuple(components))
        self._handles.append(h)
        return h

    def set_methods(self, methods: MethodFlags) -> None:
        self._methods = methods

    def set_placement(self, strategy: PlacementStrategy) -> None:
        self._strategy = strategy

    def set_devices(self, devices: Sequence) -> None:
        """Analog of set_gpus (stencil.hpp:306): restrict/order the devices."""
        self._devices = devices

    def set_partition(self, px: int, py: int, pz: int) -> None:
        """Force the process grid instead of deriving it (manual partition,
        the reference's future-work item, README.md:157-176).  The product
        must equal the device count at realize()."""
        assert not self._realized
        self._force_dim = Dim3(px, py, pz)

    def set_halo_multiplier(self, k: int) -> None:
        """Allocate ``k * radius``-wide shells and run ``k`` compute sub-steps
        per exchange — fewer, larger messages (the reference's future-work
        item, README.md:157-176; BASELINE.md config #5).  A step built by
        ``make_step`` then advances ``k`` iterations per call."""
        assert k >= 1
        assert not self._realized, "set_halo_multiplier must precede realize()"
        self._halo_mult = int(k)

    def halo_multiplier(self) -> int:
        return self._halo_mult

    def set_exchange_route(self, route: Optional[str]) -> None:
        """Pin the y/z-sweep exchange route (ops/exchange.py
        ``EXCHANGE_ROUTES``: ``direct`` | ``zpack_xla`` | ``zpack_pallas``
        | ``yzpack_xla`` | ``yzpack_pallas``).
        ``None``/"auto" restores planner resolution: ``STENCIL_EXCHANGE_ROUTE``,
        then the tuned config (``tune.best_config`` on this domain's
        "exchange" workload key), then the static ``direct`` fallback.  An
        explicit pin — like every explicit request — never consults the
        tuner; it still steps down to ``direct`` if the packed kernels are
        rejected at compile (the resilience ladder) or NO packed sweep can
        structurally engage (uneven packed axes, unsupported dtype) — a
        partially engageable route runs its eligible sweeps packed and the
        rest direct."""
        from stencil_tpu.ops.exchange import EXCHANGE_ROUTES

        if route in (None, "auto"):
            self._exchange_route_req = None
            return
        if route not in EXCHANGE_ROUTES:
            raise ValueError(
                f"unknown exchange route {route!r} (one of {EXCHANGE_ROUTES})"
            )
        assert not self._realized, "set_exchange_route must precede realize()"
        self._exchange_route_req = route

    def exchange_route(self) -> str:
        """The resolved y/z-sweep route (meaningful after ``realize()``)."""
        return self._exchange_route

    def set_storage(self, storage: str) -> None:
        """Pin the field buffers' STORAGE dtype axis (``"native"`` |
        ``"bf16"`` — ops/jacobi_pallas ``STORAGE_DTYPES``).  Callers (the
        models' ctor knobs) resolve the axis through
        ``resolve_storage_dtype`` — precedence explicit >
        ``STENCIL_STORAGE_DTYPE`` > tuned config > static ``native``, with
        the structural f32-only / f32-accumulate-engine gates — and hand
        the RESOLVED value here before ``realize()``.  Under ``bf16`` every
        f32 field allocates as bfloat16 (HBM planes, the VMEM pipeline
        blocks streamed from them, and the fused exchange messages all
        narrow to 2 B/cell); the kernels accumulate at f32 and downcast
        once per pass (the ``f32_accumulate`` contract), and host readback
        (``quantity_to_host`` etc.) upcasts back to the native dtype."""
        from stencil_tpu.ops.jacobi_pallas import STORAGE_DTYPES

        if storage not in STORAGE_DTYPES:
            raise ValueError(
                f"unknown storage dtype {storage!r} (one of {STORAGE_DTYPES})"
            )
        assert not self._realized, "set_storage must precede realize()"
        self._storage = storage

    def storage_dtype(self) -> str:
        """The resolved storage axis: ``"native"`` or ``"bf16"``."""
        return self._storage

    def field_dtype(self, h: DataHandle):
        """The dtype ``h``'s buffers actually store: bfloat16 under the
        bf16 storage axis for f32 fields (the only narrowing the analytic
        error contract covers — see ``bf16_supported``), the native dtype
        otherwise."""
        if self._storage == "bf16" and jnp.dtype(h.dtype) == jnp.float32:
            return jnp.dtype(jnp.bfloat16)
        return h.dtype

    def tune_key(self, route: str):
        """The autotuner ``WorkloadKey`` for this domain under ``route`` —
        THE one place the (chip kind, domain shape, dtype, n_fields, mesh
        shape, radius, engine route) tuple is assembled, so every planner
        consults the same cache entry.  Works pre-realize too: the mesh dim
        is mirrored from the deterministic ``make_mesh`` computation (the
        same mirror ``Jacobi3D._plan_wavefront`` relies on)."""
        from stencil_tpu.tune.key import WorkloadKey, chip_kind

        if self.placement is not None:
            dim = self.placement.dim()
        else:
            devices = (
                list(self._devices) if self._devices is not None else jax.devices()
            )
            _, placement = make_mesh(
                self._size, self._radius, devices, self._strategy,
                force_dim=self._force_dim,
            )
            dim = placement.dim()
        r = self._radius
        rmax = max(
            r.lo().x, r.lo().y, r.lo().z, r.hi().x, r.hi().y, r.hi().z
        )
        if route == "exchange":
            # the exchange operates on the SHELL (user radius × halo
            # multiplier): its z message depth is what a route winner was
            # measured at, so the multiplier must re-key the workload.  The
            # temporally-blocked routes key by the user radius instead —
            # there the multiplier IS the tuned axis, not a key axis.
            rmax *= max(self._halo_mult, 1)
        dtypes = ",".join(sorted({h.dtype.name for h in self._handles}))
        return WorkloadKey(
            chip=chip_kind(),
            domain=(self._size.x, self._size.y, self._size.z),
            dtype=dtypes or "float32",
            n_fields=max(len(self._handles), 1),
            mesh=(dim.x, dim.y, dim.z),
            radius=rmax,
            route=route,
        )

    def size(self) -> Dim3:
        return self._size

    # --- realize (src/stencil.cu:27-539) -------------------------------------
    def enable_exchange_stats(self, on: bool = True) -> None:
        self._exchange_stats = on

    def _derive_geometry(self, devices):
        """Mesh/placement/spec for THIS domain over ``devices`` — the one
        place the padded-equal-split geometry (and its admissibility
        checks) is computed, shared by ``realize()`` and the reshard
        target planning so the two can never drift."""
        mesh, placement = make_mesh(
            self._size, self._radius, devices, self._strategy,
            force_dim=self._force_dim,
        )
        dim = placement.dim()
        # uneven sizes: pad each axis's shard to ceil(size/dim) and mask (the
        # reference's +-1-cell remainders, partition.hpp:83-114; XLA shards
        # must be equal).  The LAST shard on a padded axis owns
        # ``size - (dim-1)*n_pad`` valid cells.
        n = Dim3(*(-(-self._size[ax] // dim[ax]) for ax in range(3)))
        vlast = []
        for ax in range(3):
            v = self._size[ax] - (dim[ax] - 1) * n[ax]
            vlast.append(None if v == n[ax] else v)
        # the SHELL radius is the user radius times the halo multiplier: the
        # allocation, the exchange, and the bytes model all use it; compute
        # sub-steps shrink by the user radius
        r = self._radius.scaled(self._halo_mult)
        max_r = max(r.lo().x, r.lo().y, r.lo().z, r.hi().x, r.hi().y, r.hi().z)
        min_valid = min(v if v is not None else n[ax] for ax, v in enumerate(vlast))
        if min_valid <= 0:
            # pad-and-mask confines the remainder to ONE trailing shard; a
            # split where (dim-1)*ceil(size/dim) >= size (e.g. 10 cells over
            # 8 shards) leaves the last shard empty.  The reference spreads
            # +-1-cell remainders across shards instead (partition.hpp:83-114)
            # — that scheme has no equal-shard analog, so reject explicitly.
            raise ValueError(
                f"axis remainder does not fit in one trailing shard: size "
                f"{self._size} over mesh {dim} gives last-shard valid cells "
                f"{vlast}; choose a mesh dim with (dim-1)*ceil(size/dim) < size"
            )
        if min(n.x, n.y, n.z) < max_r or min_valid < max_r:
            raise ValueError(
                f"subdomain {n} (last-shard valid {vlast}) smaller than radius shell"
            )
        # all shards share one spec (padded equal split); per-shard origin varies
        spec = LocalSpec.make(n, Dim3(0, 0, 0), r)
        return mesh, placement, spec, tuple(vlast), r

    def realize(self, allocate: bool = True) -> None:
        """``allocate=False`` sets up mesh/placement/geometry WITHOUT creating
        arrays or compiling the exchange — for AOT work over device-less
        topologies (``jax.experimental.topologies``), where ``make_step`` can
        then be lowered/compiled against abstract sharded shapes (used by the
        overlap-schedule proof, tests/test_overlap_schedule.py)."""
        self._radius.validate()
        if self._storage == "bf16":
            # the structural gate the model resolvers apply, repeated here
            # for direct set_storage() callers: the f32-accumulate stream
            # passes upcast EVERY quantity uniformly, so a mixed domain with
            # non-f32 fields (f64 would silently lose 29 mantissa bits, int
            # fields have no f32 round trip contract) must degrade the whole
            # axis — only all-f32 domains narrow (``bf16_supported``)
            from stencil_tpu.ops.jacobi_pallas import bf16_supported

            if not bf16_supported([h.dtype for h in self._handles]):
                log_warn(
                    "storage bf16 cannot engage: fields are "
                    f"{[jnp.dtype(h.dtype).name for h in self._handles]}, "
                    "not all f32; degrading to native storage"
                )
                self._storage = "native"
        t0 = time.perf_counter()
        devices = list(self._devices) if self._devices is not None else jax.devices()
        self.stats.time_topo = time.perf_counter() - t0
        t0 = time.perf_counter()
        (
            self.mesh,
            self.placement,
            self._spec,
            self._valid_last,
            self._shell_radius,
        ) = self._derive_geometry(devices)
        self.stats.time_placement = time.perf_counter() - t0
        dim = self.placement.dim()
        raw = self._spec.raw_size()
        sharding = NamedSharding(self.mesh, P(*MESH_AXES))
        gshape = (dim.x * raw.x, dim.y * raw.y, dim.z * raw.z)
        if not allocate:
            self._realized = True
            log_info(f"realized (abstract) {self._size} over mesh {dim} (raw shard {raw})")
            return
        t0 = time.perf_counter()
        for h in self._handles:
            hsharding = NamedSharding(self.mesh, _qspec(h))
            fdt = self.field_dtype(h)
            self._curr[h.name] = jnp.zeros(h.components + gshape, dtype=fdt, device=hsharding)
            self._next[h.name] = jnp.zeros(h.components + gshape, dtype=fdt, device=hsharding)
        self.stats.time_realize = time.perf_counter() - t0
        t0 = time.perf_counter()
        if self._methods in (MethodFlags.AllGather, MethodFlags.RollCompare):
            # debug methods: two independent oracles for the ppermute path
            # (stencil.hpp:29-41 method selection); even (unpadded) sizes only
            from stencil_tpu.ops.exchange import (
                make_exchange_fn_allgather,
                make_exchange_fn_rollcompare,
            )

            if any(v is not None for v in self._valid_last):
                raise ValueError("debug exchange methods require even sizes")
            if any(h.components for h in self._handles):
                raise ValueError(
                    "debug exchange methods support scalar quantities only"
                )
            maker = (
                make_exchange_fn_allgather
                if self._methods == MethodFlags.AllGather
                else make_exchange_fn_rollcompare
            )
            self._exchange_fn = maker(self.mesh, self._shell_radius, self._spec, dim)
            self._exchange_route = "direct"  # the debug oracles have no z route
            self.stats.time_plan = time.perf_counter() - t0
            # eager trace+compile of the exchange — the analog of the
            # reference's sender/recver creation + CUDA-Graph capture
            # (src/stencil.cu:385-529); later exchange() calls hit the
            # executable cache.
            if self._handles:
                t0 = time.perf_counter()
                self._exchange_fn.lower(self._curr).compile()
                self._record_exchange_compile(t0, "realize")
        else:
            self._exchange_route = self._resolve_exchange_route()
            self.stats.time_plan = time.perf_counter() - t0
            # build + eager-compile through the route ladder: a packed route
            # the compiler rejects (VMEM_OOM / COMPILE_REJECT) steps down to
            # `direct`; the compile itself rides the transient-retry policy
            # (remote-compile tunnel drops — the BENCH_r05 class — retry
            # instead of killing realize)
            t0 = time.perf_counter()
            self._exchange_fn = self._build_exchange_with_ladder()
            if self._handles:
                self._record_exchange_compile(t0, f"realize:{self._exchange_route}")
        self._realized = True
        log_info(f"realized {self._size} over mesh {dim} (raw shard {raw})")

    def _record_exchange_compile(self, t0: float, label: str) -> None:
        self.stats.time_create = time.perf_counter() - t0
        telemetry.observe(tm.COMPILE_SECONDS, self.stats.time_create)
        telemetry.emit_event(
            tm.EVENT_COMPILE,
            phase="exchange",
            label=label,
            seconds=round(self.stats.time_create, 6),
        )

    def mesh_dim(self) -> Tuple[int, int, int]:
        """The current mesh extent as a plain tuple (heartbeat/telemetry)."""
        d = self.placement.dim()
        return (d.x, d.y, d.z)

    # --- elastic capacity ------------------------------------------------------

    def reshard(self, devices=None, force_dim=None, source: str = "request") -> dict:
        """Live mesh transition: move the realized interior state onto a
        new device mesh IN MEMORY — the on-device generalization of
        checkpoint-elastic-restore (docs/resilience.md "Elastic capacity").

        The interiors travel as a schedule of portable collectives
        (``parallel/redistribute.py``, per arxiv 2112.01075) with peak
        per-chip memory bounded by a constant number of shard-sized staging
        buffers — never a full gather — at the STORED dtype, so the result
        is bitwise-identical to a checkpoint-elastic-restore round trip.
        Afterward the domain is fully re-realized for the new geometry:
        fresh exchange plan/executable (route re-resolved — the tuner is
        consulted under the new mesh's workload key), zeroed ``next`` slot,
        zeroed shells (exactly ``set_quantity``'s scatter), reset analytic
        counters.  Steps built by ``make_step`` close over the OLD mesh and
        must be rebuilt by the caller (the supervisor's ``on_mesh_change``
        hook does this for supervised runs).

        Raises :class:`~stencil_tpu.parallel.redistribute.ReshardImpossibleError`
        when redistribution is structurally impossible (no admissible
        partition on the target devices, source buffers already consumed) —
        the supervisor answers that with the checkpoint-elastic-restore
        fallback.  Returns a stats dict (seconds/bytes/from_mesh/to_mesh).
        """
        from stencil_tpu.parallel.redistribute import (
            ReshardImpossibleError,
            SideGeometry,
            plan_redistribution,
            redistribute_array,
        )
        from stencil_tpu.resilience.retry import buffers_live

        assert self._realized, "reshard() needs a realized domain"
        t0 = time.perf_counter()
        if self._methods in (MethodFlags.AllGather, MethodFlags.RollCompare):
            raise ReshardImpossibleError(
                "debug exchange methods do not support live resharding"
            )
        if self._handles and not self._curr:
            raise ReshardImpossibleError(
                "domain was realized without allocation — nothing to move"
            )
        if self._handles and not buffers_live(self._curr):
            raise ReshardImpossibleError(
                "a donated source buffer was already consumed mid-dispatch; "
                "redistribution has nothing to read — fall back to "
                "checkpoint-elastic-restore"
            )
        devices = list(devices) if devices is not None else jax.devices()
        # the new force_dim is pinned only while deriving the TARGET
        # geometry, then restored until the install point below: a failure
        # anywhere before installation (inadmissible partition, an error
        # mid-collective) must leave the domain — including a
        # set_partition pin — exactly as it was
        old_force = self._force_dim
        new_force = Dim3.of(force_dim) if force_dim is not None else None
        self._force_dim = new_force
        try:
            try:
                mesh, placement, spec, vlast, shell = self._derive_geometry(devices)
            except ValueError as e:
                raise ReshardImpossibleError(
                    f"no admissible partition on the target devices: {e}"
                ) from e
        finally:
            self._force_dim = old_force
        src_geom = SideGeometry.of_domain(self)
        raw = spec.raw_size()
        lo = shell.lo()
        dim = placement.dim()
        dst_geom = SideGeometry(
            dim=(dim.x, dim.y, dim.z),
            n=tuple(spec.sz),
            raw=(raw.x, raw.y, raw.z),
            lo=(lo.x, lo.y, lo.z),
            valid_last=vlast,
            devices=tuple(mesh.devices.flat),
        )
        plan = plan_redistribution(tuple(self._size), src_geom, dst_geom)
        new_curr: Dict[str, jax.Array] = {}
        nbytes = 0
        # one traced+compiled schedule per DISTINCT (components, dtype)
        # signature — fused multi-quantity domains share it (a fresh
        # build_redistribute_fn per quantity would re-trace identical
        # programs: jit caches by function identity)
        from stencil_tpu.parallel.redistribute import build_redistribute_fn

        fn_cache: Dict[tuple, object] = {}
        for h in self._handles:
            fdt = self.field_dtype(h)
            sig = (tuple(h.components), jnp.dtype(fdt).name)
            if sig not in fn_cache:
                fn_cache[sig] = build_redistribute_fn(
                    plan, tuple(h.components), fdt
                )[0]
            new_curr[h.name] = redistribute_array(
                plan, self._curr[h.name], h.components, fdt, mesh, _qspec(h),
                fn=fn_cache[sig],
            )
            nbytes += (
                int(np.prod(tuple(self._size)))
                * h.cell_count()
                * jnp.dtype(fdt).itemsize
            )
        from_mesh = self.mesh_dim()
        # install the new geometry + redistributed buffers; fresh zero
        # ``next`` slot, exactly like realize()
        self._devices = devices
        self._force_dim = new_force
        self.mesh, self.placement = mesh, placement
        self._spec, self._valid_last, self._shell_radius = spec, vlast, shell
        self._curr = new_curr
        gshape = (dim.x * raw.x, dim.y * raw.y, dim.z * raw.z)
        self._next = {}
        for h in self._handles:
            hsharding = NamedSharding(self.mesh, _qspec(h))
            self._next[h.name] = jnp.zeros(
                h.components + gshape, dtype=self.field_dtype(h), device=hsharding
            )
        # re-realize the exchange plan/executable for the new geometry:
        # the route re-resolves (explicit pin > env > tuned — the tuner is
        # re-keyed automatically, tune_key reads the new placement) and the
        # analytic byte models recompute lazily
        self._exchange_many_fn = None
        self._exchange_nbytes = None
        self._hop_nbytes = None
        self._packed_nbytes = self._packed_nkernels = 0
        self._shell_stale = False
        if self._numerics is not None:
            # the stats program closes over the OLD mesh/spec; the engine's
            # signature check would also catch this lazily, but a mesh
            # transition is the one known invalidation point — be explicit
            self._numerics.on_mesh_change()
        t1 = time.perf_counter()
        self._exchange_route = self._resolve_exchange_route()
        self._exchange_fn = self._build_exchange_with_ladder()
        if self._handles:
            self._record_exchange_compile(t1, f"reshard:{self._exchange_route}")
        dt = time.perf_counter() - t0
        telemetry.inc(tm.RESHARDS)
        telemetry.inc(tm.RESHARD_BYTES, nbytes)
        telemetry.observe(tm.RESHARD_SECONDS, dt)
        telemetry.emit_event(
            tm.EVENT_RESHARD,
            from_mesh=list(from_mesh),
            to_mesh=list(self.mesh_dim()),
            seconds=round(dt, 6),
            bytes=nbytes,
            quantities=len(self._handles),
            source=source,
        )
        log_info(
            f"resharded {self._size} from mesh {from_mesh} to "
            f"{self.mesh_dim()} in {dt:.3f}s ({nbytes} B moved in-memory)"
        )
        return {
            "seconds": dt,
            "bytes": nbytes,
            "from_mesh": list(from_mesh),
            "to_mesh": list(self.mesh_dim()),
        }

    def re_realize(self, devices=None, force_dim=None) -> None:
        """Fresh realize onto a new device set, DISCARDING the in-memory
        state (fields re-zero, like a first realize): the first half of
        the checkpoint-elastic-restore fallback — when ``reshard()`` is
        structurally impossible, the supervisor re-realizes here and
        restores the last ring checkpoint onto the new mesh."""
        assert self._realized, "re_realize() follows a realized domain"
        self._devices = list(devices) if devices is not None else None
        self._force_dim = Dim3.of(force_dim) if force_dim is not None else None
        self._curr = {}
        self._next = {}
        self._exchange_fn = None
        self._exchange_many_fn = None
        self._exchange_nbytes = None
        self._hop_nbytes = None
        self._packed_nbytes = self._packed_nkernels = 0
        self._shell_stale = False
        if self._numerics is not None:
            self._numerics.on_mesh_change()
        self._realized = False
        self.realize()

    def _resolve_exchange_route(self) -> str:
        """Resolve the z-sweep exchange route for this realize.  Precedence
        (mirrors the stream-alias rule): explicit ``set_exchange_route`` >
        ``STENCIL_EXCHANGE_ROUTE`` (validated read) > the tuned config
        (``tune.best_config`` on the "exchange" workload key) > the static
        ``direct`` fallback (ROADMAP: calibration constants are fallbacks).
        A route the pack pipeline structurally cannot serve (uneven z split,
        unsupported dtype) degrades to ``direct`` with a warning — a stale
        or wrong persisted config must never crash a run the fallback could
        have served.  Every resolution is an ``exchange.route`` telemetry
        decision event."""
        from stencil_tpu.ops.exchange import EXCHANGE_ROUTES, route_supported
        from stencil_tpu.utils.config import env_choice

        route: Optional[str] = None
        source = "static"
        if self._exchange_route_req is not None:
            route, source = self._exchange_route_req, "explicit"
        else:
            env = env_choice(
                "STENCIL_EXCHANGE_ROUTE", "auto", ("auto",) + EXCHANGE_ROUTES
            )
            if env != "auto":
                route, source = env, "env"
        if route is None:
            from stencil_tpu import tune

            cfg = tune.best_config(self.tune_key("exchange"))
            tuned = (cfg or {}).get("exchange_route")
            if tuned is not None:
                if tuned in EXCHANGE_ROUTES:
                    route, source = str(tuned), "tuned"
                else:
                    log_warn(
                        f"tuned exchange_route {tuned!r} is not one of "
                        f"{EXCHANGE_ROUTES}; using the static 'direct' fallback"
                    )
        if route is None:
            route = "direct"
        # degrade only when NO packed sweep of the route can engage (each
        # sweep degrades independently inside the exchange — a yzpack route
        # over an uneven y still packs its z sweep, and vice versa)
        if not route_supported(
            route,
            [self.field_dtype(h) for h in self._handles],
            self._valid_last,
        ):
            log_warn(
                f"exchange route {route!r} ({source}) cannot engage here "
                "(uneven packed axes or unsupported dtype); degrading to "
                "'direct'"
            )
            route, source = "direct", source + "/degraded"
        telemetry.emit_event(tm.EVENT_EXCHANGE_ROUTE, route=route, source=source)
        return route

    def make_exchange_route_fn(
        self,
        route: str,
        donate: bool = True,
        axes: Tuple[int, ...] = (0, 1, 2),
    ):
        """One jitted exchange over this domain's quantities for ``route``,
        eagerly compiled (compile rides the transient-retry policy, so
        remote-compile tunnel drops retry instead of dying).  The production
        path uses it at realize; the autotuner's route trials and
        bench-exchange's A/B build non-donating (``donate=False``) variants
        so measuring never consumes the live buffers."""
        from stencil_tpu.resilience import inject
        from stencil_tpu.resilience.retry import execute_with_retry

        fn = make_exchange_fn(
            self.mesh,
            self._shell_radius,
            valid_last=self._valid_last,
            route=route,
            axes=axes,
            donate=donate,
        )
        if self._handles:
            label = f"compile:exchange:{route}"

            def compile_unit():
                # the fault hook sits INSIDE the retried unit (the run_step
                # dispatch() pattern) so injected tunnel drops exercise the
                # same retry path the real remote-compile failures take
                inject.maybe_fail("compile", label)
                return fn.lower(self._curr).compile()

            execute_with_retry(compile_unit, label=label)
        return fn

    def _build_exchange_with_ladder(self):
        """Build (and compile) the production exchange for the resolved
        route.  Packed routes ride a two-rung degradation ladder: a VMEM_OOM
        or COMPILE_REJECT building the packed exchange descends to
        ``direct`` (counted + event-logged by the ladder) instead of failing
        realize."""
        route = self._exchange_route
        if route == "direct":
            return self.make_exchange_route_fn("direct")
        from stencil_tpu.resilience.ladder import DegradationLadder, Rung

        def rung_for(rt: str) -> Rung:
            return Rung(rt, build=lambda rt=rt: self.make_exchange_route_fn(rt))

        def lower(rung, cls, exc):
            return rung_for("direct") if rung.name != "direct" else None

        ladder = DegradationLadder(rung_for(route), lower, label="exchange")
        fn = ladder.built()
        if ladder.rung.name != route:
            self._exchange_route = ladder.rung.name
            telemetry.emit_event(
                tm.EVENT_EXCHANGE_ROUTE, route=ladder.rung.name, source="ladder"
            )
        return fn

    def abstract_arrays(self) -> Dict[str, jax.ShapeDtypeStruct]:
        """Sharded ShapeDtypeStructs matching the quantity arrays — lowering
        inputs for AOT compilation (pairs with ``realize(allocate=False)``)."""
        dim = self.placement.dim()
        raw = self._spec.raw_size()
        gshape = (dim.x * raw.x, dim.y * raw.y, dim.z * raw.z)
        return {
            h.name: jax.ShapeDtypeStruct(
                h.components + gshape,
                self.field_dtype(h),
                sharding=NamedSharding(self.mesh, _qspec(h)),
            )
            for h in self._handles
        }

    # --- geometry accessors ---------------------------------------------------
    def local_spec(self) -> LocalSpec:
        return self._spec

    def subdomain_size(self) -> Dim3:
        return self._spec.sz

    def get_interior(self) -> Rect3:
        """Interior region in interior-local coords (src/stencil.cu:567-610)."""
        return self._spec.interior()

    def get_exterior(self) -> List[Rect3]:
        return self._spec.exterior()

    def num_subdomains(self) -> int:
        return self.placement.dim().flatten()

    def shard_valid(self, idx) -> Dim3:
        """Valid (unpadded) interior extent of the shard at mesh index ``idx``
        (last shard on a padded axis owns the remainder)."""
        idx = Dim3.of(idx)
        dim = self.placement.dim()
        n = self._spec.sz
        return Dim3(
            *(
                (self._valid_last[ax] if (idx[ax] == dim[ax] - 1 and self._valid_last[ax] is not None) else n[ax])
                for ax in range(3)
            )
        )

    # --- data movement --------------------------------------------------------
    def _to_raw_global(self, interior: np.ndarray, dtype) -> np.ndarray:
        """Scatter a (*components, X,Y,Z) user-domain array into the
        shell-carrying global layout (host-side; used for init and small
        domains).  Leading component dims pass through."""
        dim = self.placement.dim()
        n = self._spec.sz
        raw = self._spec.raw_size()
        lo = self._shell_radius.lo()
        comps = interior.shape[:-3]
        out = np.zeros(comps + (dim.x * raw.x, dim.y * raw.y, dim.z * raw.z), dtype=dtype)
        for ix in range(dim.x):
            for iy in range(dim.y):
                for iz in range(dim.z):
                    v = self.shard_valid((ix, iy, iz))
                    src = interior[
                        ...,
                        ix * n.x : ix * n.x + v.x,
                        iy * n.y : iy * n.y + v.y,
                        iz * n.z : iz * n.z + v.z,
                    ]
                    out[
                        ...,
                        ix * raw.x + lo.x : ix * raw.x + lo.x + v.x,
                        iy * raw.y + lo.y : iy * raw.y + lo.y + v.y,
                        iz * raw.z + lo.z : iz * raw.z + lo.z + v.z,
                    ] = src
        return out

    def _from_raw_global(self, raw_arr: np.ndarray) -> np.ndarray:
        dim = self.placement.dim()
        n = self._spec.sz
        raw = self._spec.raw_size()
        lo = self._shell_radius.lo()
        comps = raw_arr.shape[:-3]
        out = np.zeros(comps + (self._size.x, self._size.y, self._size.z), dtype=raw_arr.dtype)
        for ix in range(dim.x):
            for iy in range(dim.y):
                for iz in range(dim.z):
                    v = self.shard_valid((ix, iy, iz))
                    out[
                        ...,
                        ix * n.x : ix * n.x + v.x,
                        iy * n.y : iy * n.y + v.y,
                        iz * n.z : iz * n.z + v.z,
                    ] = raw_arr[
                        ...,
                        ix * raw.x + lo.x : ix * raw.x + lo.x + v.x,
                        iy * raw.y + lo.y : iy * raw.y + lo.y + v.y,
                        iz * raw.z + lo.z : iz * raw.z + lo.z + v.z,
                    ]
        return out

    def set_quantity(self, h: DataHandle, interior: np.ndarray, slot: str = "curr") -> None:
        """Load a full (*components, X,Y,Z) user-domain array into a
        quantity's interior."""
        want = h.components + tuple(self._size)
        assert interior.shape == want, (interior.shape, want)
        raw = self._to_raw_global(np.asarray(interior), self.field_dtype(h))
        sharding = NamedSharding(self.mesh, _qspec(h))
        arr = jax.device_put(jnp.asarray(raw), sharding)
        (self._curr if slot == "curr" else self._next)[h.name] = arr

    def quantity_to_host(self, h: DataHandle, slot: str = "curr") -> np.ndarray:
        """Gather a quantity's interior to a (X,Y,Z) host array (analog of
        reference quantity_to_host, local_domain.cuh:329-346)."""
        arr = (self._curr if slot == "curr" else self._next)[h.name]
        # bf16-storage buffers upcast back to the native dtype at readback
        # (exact: every bfloat16 is an f32)
        return self._from_raw_global(np.asarray(jax.device_get(arr))).astype(
            h.dtype, copy=False
        )

    def region_to_host(self, h: DataHandle, region: Rect3, slot: str = "curr") -> np.ndarray:
        """Arbitrary-region readback in USER-domain (global) coordinates —
        the reference's ``LocalDomain::region_to_host``
        (src/local_domain.cu:97, local_domain.cuh:329-346) lifted to the
        distributed domain.  Gathers only the shards the region touches."""
        assert self._realized
        r = Rect3(Dim3.of(region.lo), Dim3.of(region.hi))
        assert r.lo.all_gt(-1) and (self._size - r.hi).all_gt(-1), (r, self._size)
        dim = self.placement.dim()
        n = self._spec.sz
        raw = self._spec.raw_size()
        lo = self._shell_radius.lo()
        arr = (self._curr if slot == "curr" else self._next)[h.name]
        ext = r.extent()
        out = np.zeros(h.components + (ext.x, ext.y, ext.z), dtype=h.dtype)
        shard_lo = Dim3(*(r.lo[a] // n[a] for a in range(3)))
        shard_hi = Dim3(*((r.hi[a] - 1) // n[a] if r.hi[a] > r.lo[a] else shard_lo[a] for a in range(3)))
        for ix in range(shard_lo.x, min(shard_hi.x, dim.x - 1) + 1):
            for iy in range(shard_lo.y, min(shard_hi.y, dim.y - 1) + 1):
                for iz in range(shard_lo.z, min(shard_hi.z, dim.z - 1) + 1):
                    idx = Dim3(ix, iy, iz)
                    v = self.shard_valid(idx)
                    # overlap of the request with this shard's valid interior
                    olo = Dim3(*(max(r.lo[a], idx[a] * n[a]) for a in range(3)))
                    ohi = Dim3(*(min(r.hi[a], idx[a] * n[a] + v[a]) for a in range(3)))
                    if not (ohi - olo).all_gt(0):
                        continue
                    block = arr[
                        ...,
                        ix * raw.x + lo.x + olo.x - ix * n.x : ix * raw.x + lo.x + ohi.x - ix * n.x,
                        iy * raw.y + lo.y + olo.y - iy * n.y : iy * raw.y + lo.y + ohi.y - iy * n.y,
                        iz * raw.z + lo.z + olo.z - iz * n.z : iz * raw.z + lo.z + ohi.z - iz * n.z,
                    ]
                    out[
                        ...,
                        olo.x - r.lo.x : ohi.x - r.lo.x,
                        olo.y - r.lo.y : ohi.y - r.lo.y,
                        olo.z - r.lo.z : ohi.z - r.lo.z,
                    ] = np.asarray(jax.device_get(block)).astype(
                        h.dtype, copy=False
                    )
        return out

    def interior_to_host(self, h: DataHandle, slot: str = "curr") -> np.ndarray:
        """Whole-interior readback (reference ``interior_to_host``,
        local_domain.cuh:329-346) — alias of ``quantity_to_host``."""
        return self.quantity_to_host(h, slot)

    def mark_shell_stale(self) -> None:
        """Fast-path steps that skip the shell entirely (the single-device
        wrap kernel; any path exchanging bare slabs) leave the carried shell
        holding whatever the last real exchange wrote — arbitrarily old.
        Models using such paths mark the shell stale so raw readback
        re-exchanges first (``quantity_to_host`` reads interiors only and
        never needs this)."""
        self._shell_stale = True

    def raw_to_host(self, h: DataHandle, slot: str = "curr") -> np.ndarray:
        """The raw shell-carrying global array (halos visible) for tests.

        Halos reflect the most recent exchange — for the standard step paths
        that is the exchange at the top of the last iteration (pre-compute
        neighbor values, exactly the reference's shell contents between
        exchanges).  A shell marked stale (``mark_shell_stale``) is first
        refreshed with one production exchange so it is at least that fresh."""
        if self._shell_stale and slot == "curr":
            self._curr = self._exchange_fn(self._curr)
            self._shell_stale = False
        arr = (self._curr if slot == "curr" else self._next)[h.name]
        return np.asarray(jax.device_get(arr)).astype(h.dtype, copy=False)

    def init_by_coords(self, h: DataHandle, fn, include_halo: bool = False) -> None:
        """Device-side init: ``fn(cx, cy, cz)`` maps broadcastable global
        coordinate arrays to values.  Fills the interior (and optionally the
        shell, for analytic whole-domain fields)."""
        n = self._spec.sz
        raw = self._spec.raw_size()
        lo = self._shell_radius.lo()
        mesh_shape = tuple(self.mesh.shape[a] for a in MESH_AXES)

        comps = h.components

        def per_shard(block):
            ox = lax.axis_index(MESH_AXES[0]) * n.x
            oy = lax.axis_index(MESH_AXES[1]) * n.y
            oz = lax.axis_index(MESH_AXES[2]) * n.z
            if include_halo:
                cx = ox - lo.x + jnp.arange(raw.x)
                cy = oy - lo.y + jnp.arange(raw.y)
                cz = oz - lo.z + jnp.arange(raw.z)
                vals = fn(cx[:, None, None], cy[None, :, None], cz[None, None, :])
                return jnp.broadcast_to(vals, comps + tuple(raw)).astype(block.dtype)
            cx = ox + jnp.arange(n.x)
            cy = oy + jnp.arange(n.y)
            cz = oz + jnp.arange(n.z)
            vals = fn(cx[:, None, None], cy[None, :, None], cz[None, None, :])
            vals = jnp.broadcast_to(vals, comps + tuple(n)).astype(block.dtype)
            return block.at[
                ..., lo.x : lo.x + n.x, lo.y : lo.y + n.y, lo.z : lo.z + n.z
            ].set(vals)

        spec = _qspec(h)
        out = jax.jit(
            shard_map(per_shard, mesh=self.mesh, in_specs=(spec,), out_specs=spec)
        )(self._curr[h.name])
        self._curr[h.name] = out

    # --- the hot path ---------------------------------------------------------
    @contextlib.contextmanager
    def _phase_timer(self, attr: str, histogram: str, span_name: str = None,
                     sync: bool = False):
        """THE timing path for the per-call hot-loop phases: one
        ``perf_counter`` pair feeds both the reference-parity ``DomainStats``
        accumulator (``attr``) and the telemetry histogram/span.  Active when
        exchange-stats (the reference's blocking per-call opt-in,
        stencil.hpp:106-131) or telemetry is enabled; otherwise it yields
        immediately — zero per-step formatting work.  ``sync=True`` adds the
        honest device sync timing requires (see ``block_until_ready``)."""
        if not (self._exchange_stats or telemetry.enabled()):
            yield
            return
        t0 = time.perf_counter()
        yield
        if sync:
            self.block_until_ready()
        dt = time.perf_counter() - t0
        setattr(self.stats, attr, getattr(self.stats, attr) + dt)
        telemetry.observe(histogram, dt)
        if span_name is not None:
            telemetry.record_span(span_name, t0, dt)

    def _account_exchanges(self, n: int) -> None:
        """Counter bookkeeping for ``n`` (possibly fused) halo exchanges:
        analytic bytes via ``exchange_bytes_total`` (src/stencil.cu:6-25),
        computed once and cached — counters are always live, so this must
        stay a dict hit + two int adds on the hot path."""
        if self._exchange_nbytes is None:
            self._exchange_nbytes = (
                self.exchange_bytes_total() if self._handles else 0
            )
            telemetry.set_gauge(
                tm.EXCHANGE_BYTES_PER_EXCHANGE, self._exchange_nbytes
            )
            # per-hop decomposition for the comms roofline: modeled once,
            # then the hot path is one inc per TRAFFICKED hop (size-1 mesh
            # axes are dropped here — their counters stay seeded at 0)
            self._hop_nbytes = [
                (tm.EXCHANGE_HOP_BYTES[(axis, side)], nb)
                for (axis, side), nb in sorted(
                    self.exchange_hop_bytes().items()
                )
                if nb
            ] if self._handles else []
            if self._handles and self._exchange_route != "direct":
                # analytic packed-route traffic (like the bytes model above:
                # modeled once, an int multiply on the hot path).  Each
                # sweep counts only when it can actually engage — a yzpack
                # route over an uneven z still packs (and counts) its y
                # sweep, and vice versa.
                from stencil_tpu.ops.exchange import (
                    Y_PACK_ROUTES,
                    ypack_message_stats,
                    ypack_supported,
                    zpack_message_stats,
                    zpack_supported,
                )

                raw = self._spec.raw_size()
                shell = self._shell_radius
                itemsizes = [
                    self.field_dtype(h).itemsize
                    for h in self._handles
                    for _ in range(h.cell_count())
                ]
                dtypes = [self.field_dtype(h) for h in self._handles]
                nbytes = kernels = 0
                if zpack_supported(dtypes, self._valid_last):
                    nb, nk = zpack_message_stats(
                        (raw.x, raw.y, raw.z),
                        shell.axis(2, -1),
                        shell.axis(2, +1),
                        itemsizes,
                    )
                    nbytes += nb
                    kernels += nk
                if self._exchange_route in Y_PACK_ROUTES and ypack_supported(
                    dtypes, self._valid_last
                ):
                    nb, nk = ypack_message_stats(
                        (raw.x, raw.y, raw.z),
                        shell.axis(1, -1),
                        shell.axis(1, +1),
                        itemsizes,
                    )
                    nbytes += nb
                    kernels += nk
                self._packed_nbytes = nbytes * self.num_subdomains()
                self._packed_nkernels = kernels * self.num_subdomains()
        telemetry.inc(tm.EXCHANGE_COUNT, n)
        telemetry.inc(tm.EXCHANGE_BYTES, n * self._exchange_nbytes)
        for counter, nb in self._hop_nbytes:
            telemetry.inc(counter, n * nb)
        if self._packed_nkernels:
            telemetry.inc(tm.EXCHANGE_PACKED_BYTES, n * self._packed_nbytes)
            telemetry.inc(tm.EXCHANGE_PACKED_KERNELS, n * self._packed_nkernels)

    def exchange(self) -> None:
        """Fill every quantity's halo shell (src/stencil.cu:670-864)."""
        assert self._realized
        with self._phase_timer(
            "time_exchange", tm.EXCHANGE_SECONDS, tm.SPAN_EXCHANGE, sync=True
        ):
            self._curr = self._watched_call(
                "exchange", lambda: self._exchange_fn(self._curr)
            )
            self._shell_stale = False
        self._exchange_count += 1
        self._account_exchanges(1)

    def exchange_many(self, steps: int) -> None:
        """Run ``steps`` exchanges in ONE device dispatch (``lax.fori_loop``
        over the exchange).  Timing helper for tunneled dev backends where a
        per-call honest sync costs a host round trip (~100 ms) that would
        swamp the exchange itself; exchanging is idempotent on a filled
        domain, so looping it measures steady-state exchange cost."""
        assert self._realized
        if self._exchange_many_fn is None:
            inner = self._exchange_fn

            @partial(jax.jit, static_argnums=1, donate_argnums=0)
            def many(arrays, s):
                return lax.fori_loop(0, s, lambda _, a: inner(a), arrays)

            self._exchange_many_fn = many
        self._curr = self._exchange_many_fn(self._curr, steps)
        self._shell_stale = False
        self._exchange_count += steps
        self._account_exchanges(steps)

    def swap(self) -> None:
        """Swap curr/next slots (src/stencil.cu:541-561)."""
        with self._phase_timer("time_swap", tm.SWAP_SECONDS):
            self._curr, self._next = self._next, self._curr

    def block_until_ready(self) -> None:
        """Wait for all in-flight device work on the current buffers.

        On standard backends (tpu/gpu/cpu) ``jax.Array.block_until_ready``
        is sufficient and nothing else runs — timings stay clean.  Tunneled
        dev backends (e.g. ``axon``) report readiness before execution
        finishes; there a 1-element readback of an *addressable* shard forces
        true completion (per-process addressable, so multi-host safe)."""
        for a in self._curr.values():
            a.block_until_ready()
        # jax.default_backend() reports "tpu" THROUGH the axon tunnel too, so
        # detect the tunnel by the platform REQUEST instead (measured: after
        # an exchange, block_until_ready returns in 55 us where the true
        # device time is ~3 ms — readiness is reported before execution ends).
        # The config knob wins over the env var (a conftest/sitecustomize may
        # re-pin one but not the other — tests/conftest.py sets both).
        platforms = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
        if "axon" not in platforms:
            return
        for a in self._curr.values():
            shard = a.addressable_shards[0].data
            jax.device_get(shard[(slice(0, 1),) * shard.ndim])

    def get_curr(self, h: DataHandle) -> jax.Array:
        return self._curr[h.name]

    def get_next(self, h: DataHandle) -> jax.Array:
        return self._next[h.name]

    def exchange_bytes_total(self) -> int:
        """Analytic bytes-per-exchange across all subdomains
        (src/stencil.cu:6-25 exchange_bytes_for_method analog)."""
        from stencil_tpu.core.geometry import exchange_bytes

        per_dom = exchange_bytes(
            self._spec,
            [
                self.field_dtype(h).itemsize * h.cell_count()
                for h in self._handles
            ],
        )
        return per_dom * self.num_subdomains()

    def exchange_hop_bytes(self) -> Dict[Tuple[str, str], int]:
        """Analytic bytes-per-exchange over each mesh hop, keyed
        ``(mesh axis name, side)`` with side in ``low``/``high`` — the
        per-direction decomposition of the sweep traffic
        (core/geometry.py ``sweep_hop_bytes``) summed across subdomains.
        Hops on mesh axes of size 1 report 0: their ppermute self-wraps
        (the periodic boundary inside one chip), so no fabric traffic.
        Feeds the ``exchange.hop.*.bytes`` counters and the per-hop table
        in the weak-scaling artifacts (docs/observability.md "Fabric
        observatory")."""
        from stencil_tpu.core.geometry import sweep_hop_bytes

        per_dom = sweep_hop_bytes(
            self._spec,
            [
                self.field_dtype(h).itemsize * h.cell_count()
                for h in self._handles
            ],
        )
        n_sub = self.num_subdomains()
        shape = dict(self.mesh.shape) if self.mesh is not None else {}
        return {
            (MESH_AXES[axis], side): (
                nb * n_sub if shape.get(MESH_AXES[axis], 1) > 1 else 0
            )
            for (axis, side), nb in per_dom.items()
        }

    def write_plan(self, prefix: str = "plan", link_model=None) -> str:
        """Dump the communication plan — the analog of the reference's
        per-rank ``plan_<rank>.txt`` (src/stencil.cu:259-353): the placement
        report plus one line per direction with the message extent and bytes
        (all riding the collective exchange), then the projected ICI/DCN
        exchange cost (``parallel/cost.py`` — measured defaults, or a
        ``LinkModel`` built from this framework's pingpong/bench-alltoallv
        output).  Returns the path written."""
        from stencil_tpu.core.direction_map import DIRECTIONS_26
        from stencil_tpu.core.geometry import exchange_bytes
        from stencil_tpu.parallel.cost import (
            LinkModel,
            axis_edge_kinds,
            format_cost_report,
            projected_exchange_cost,
        )

        lines = [self.placement.report(), "", "# messages (method=ppermute for all)"]
        spec = self._spec
        itemsizes = [
            self.field_dtype(h).itemsize * h.cell_count()
            for h in self._handles
        ]
        for d in DIRECTIONS_26:
            if spec.radius.dir(-d) == 0:
                continue
            ext = spec.halo_extent(-d)
            nbytes = sum(spec.halo_bytes(-d, s) for s in itemsizes)
            lines.append(f"dir={d} extent={ext} bytes={nbytes} method=ppermute")
        total = exchange_bytes(spec, itemsizes)
        lines.append(f"# total bytes per exchange per subdomain: {total}")
        link = link_model or LinkModel()
        rows, total_ms = projected_exchange_cost(
            spec, itemsizes, axis_edge_kinds(self.mesh), link
        )
        lines += format_cost_report(rows, total_ms, link, self._halo_mult)
        path = f"{prefix}_{jax.process_index()}.txt"
        from stencil_tpu.utils.artifact import atomic_write_text

        atomic_write_text(path, "\n".join(lines) + "\n")
        return path

    def exchange_bytes_for_method(self, method: MethodFlags) -> int:
        """Per-method byte counter (src/stencil.cu:6-25).  On TPU every
        transport is the collective path, so all bytes are attributed to
        ``Ppermute`` (= reference All) and the debug methods report 0."""
        if method & MethodFlags.Ppermute:
            return self.exchange_bytes_total()
        return 0

    # --- fused step builder ---------------------------------------------------
    def make_step(
        self,
        kernel: StepKernel,
        overlap: bool = True,
        donate: bool = True,
        engine: str = "xla",
        x_radius: int = None,
        stream_path: str = "auto",  # stream engine route:
        # auto|wrap|plane|wavefront (auto: wrap on one device, wavefront
        # when a shell >= 2 allows temporal blocking, plane otherwise)
        separable: bool = False,  # stream engine: kernel is correct on view
        # subsets (each field reads only itself) -> per-field passes may
        # replace the joint pass when many fields blow the VMEM model
        stream_depth: int = None,  # stream engine: cap the temporal depth
        # (auto maximizes it — the right call for bandwidth-bound kernels,
        # wrong for compute-heavy ones, whose VPU work scales with depth)
        stream_overlap: str = "auto",  # stream engine: split-step overlap
        # schedule (ops/stream.py STREAM_OVERLAP): "split" dispatches the
        # interior pass with no data dependency on the shell ppermutes and
        # recomputes the boundary bands from fresh halos afterward —
        # bitwise-identical to "off"; "auto" resolves env > tuned > off
        stream_halo: str = "auto",  # stream engine: halo consumption mode
        # (ops/stream.py STREAM_HALO): "fused" lands the packed yzpack_*
        # exchange messages directly in the pass's level-0 VMEM planes (no
        # big-array halo write at all) — bitwise-identical to "array";
        # "auto" resolves env > tuned > array (docs/tuning.md "Fused halo
        # consumption")
        compute_unit: str = "auto",  # stream engine: the level kernels'
        # execution unit (ops/jacobi_pallas COMPUTE_UNITS): "mxu" routes
        # the separable in-plane taps through banded contractions on the
        # matrix unit — needs `mxu_kernel`; "mxu_band" runs the blocked
        # (2r+1)-band form of the same contraction; "auto" resolves env >
        # tuned > the static vpu (docs/tuning.md "Compute unit and
        # storage dtype")
        mxu_input: str = "auto",  # stream engine: MXU contraction operand
        # precision (ops/jacobi_pallas MXU_INPUTS): "bf16" narrows the
        # operands under the unchanged f32-accumulate contract; "auto"
        # resolves env > tuned > the static f32; inert under vpu
        mxu_kernel=None,  # stream engine: the kernel's DECLARED
        # axis-separable contraction form, written against
        # PlaneView.plane_nbr_sum (≤1 ulp/level vs `kernel`); None =
        # no mxu form, compute_unit=mxu structurally degrades to vpu
        interpret: bool = False,  # stream engine only: pallas interpret mode
    ):
        """Build ``step(curr) -> next`` fusing exchange + compute.

        With a halo multiplier ``k`` (``set_halo_multiplier``) each built step
        is a MACRO step: one exchange of ``k*r``-wide shells followed by ``k``
        compute sub-steps over shrinking valid regions — ``step(curr, s)``
        advances ``s*k`` iterations with ``s`` exchanges.

        ``overlap=True`` splits interior/exterior (reference overlap pipeline,
        jacobi3d.cu:265-337): the interior update reads no halo cells and so
        carries no dependency on the ppermutes — XLA schedules them
        concurrently.  ``overlap=False`` computes the whole region after the
        exchange (jacobi3d.cu:312-329 --no-overlap).

        ``engine`` selects the compute lowering for the SAME kernel callable:

        * ``"xla"`` — shifted-slice formulation (this method's body).  Fully
          general (padded shards, N-D data, any shifts) but each shifted
          operand re-reads the block from HBM (~6 reads/cell for a 7-point
          stencil).
        * ``"stream"`` — the plane-streaming engine (``ops/stream.py``):
          x-planes ride a VMEM ring so each HBM plane is read once per pass;
          a uniform shell >= 2 upgrades to the temporal wavefront (m levels
          per pass, padded shards included on the plain variant) and a
          single device to the exchange-free wrap route.  Requires
          elementwise kernels with all shifts within ``x_radius`` (default:
          the max user radius) and no N-D component data.  This is how USER
          stencils reach the flagship paths' speed — the reference's
          user-kernel model (accessor.hpp:13-40) where the cache hierarchy
          is an explicit plane ring.  The ``overlap`` flag is the XLA
          engine's; the stream engine's split-step schedule is selected by
          ``stream_overlap`` instead ("off" | "split" | "auto" — a tuner
          axis, see docs/tuning.md "Stream overlap"); ``stream_depth`` caps
          the temporal depth for compute-heavy kernels.
        """
        assert self._realized
        if engine == "stream":
            from stencil_tpu.ops.stream import make_stream_step

            if x_radius is None:
                x_radius = max(
                    max(self._radius.lo()[ax], self._radius.hi()[ax])
                    for ax in range(3)
                )
            return make_stream_step(
                self, kernel, x_radius=x_radius, path=stream_path,
                separable=separable, interpret=interpret, donate=donate,
                max_depth=stream_depth, overlap=stream_overlap,
                halo=stream_halo, compute_unit=compute_unit,
                mxu_input=mxu_input,
                mxu_kernel=mxu_kernel,
            )
        if engine != "xla":
            raise ValueError(f"unknown engine {engine!r}")
        if compute_unit not in (None, "auto"):
            # the XLA slice engine has no pallas level kernels — resolve
            # through the shared chain so an explicit mxu request degrades
            # with the standard warning + kernel.compute_unit event instead
            # of being silently dropped (env/tuned stay un-consulted here:
            # there is no unit to switch)
            from stencil_tpu.ops.jacobi_pallas import resolve_compute_unit

            resolve_compute_unit(
                compute_unit, None, [h.dtype for h in self._handles],
                where="xla", engine_ok=False,
                engine_why="the XLA slice engine has no pallas level kernels",
            )
        from stencil_tpu.core.geometry import exterior_of, shrink_by_radius

        n = self._spec.sz
        r_user = self._radius
        shell = self._shell_radius
        mult = self._halo_mult
        lo = shell.lo()  # allocation offset of the interior
        mesh_shape = tuple(self.mesh.shape[a] for a in MESH_AXES)
        names = [h.name for h in self._handles]

        # pre-exchange interior: cells whose USER-radius stencil support lies
        # entirely inside the valid interior
        interior_rect = shrink_by_radius(self._spec.compute_region(), r_user)
        # padded axes: the last shard's valid cells end before n_pad, so the
        # overlap-safe interior (computable before the exchange) must also
        # stop short of the earliest possible halo: shrink the high side by
        # the padding width.  Non-last shards lose some overlap (their cells
        # there become exterior, computed after the exchange) — correct for
        # every shard, conservative for most.
        pad_shrink = [
            (n[ax] - self._valid_last[ax]) if self._valid_last[ax] is not None else 0
            for ax in range(3)
        ]
        if any(pad_shrink):
            hi = Dim3(
                *(
                    max(interior_rect.hi[ax] - pad_shrink[ax], interior_rect.lo[ax])
                    for ax in range(3)
                )
            )
            interior_rect = Rect3(interior_rect.lo, hi)

        # halo-multiplier sub-step regions (interior-local coords): the region
        # valid after the exchange is the full shell; each sub-step shrinks it
        # by the user radius, landing exactly on the interior after ``mult``
        # sub-steps.  mult == 1 -> a single region == the compute region.
        shell_rect = Rect3(Dim3(0, 0, 0) - shell.lo(), n + shell.hi())
        sub_regions: List[Rect3] = []
        cur_rect = shell_rect
        for _ in range(mult):
            cur_rect = shrink_by_radius(cur_rect, r_user)
            sub_regions.append(cur_rect)

        def rect_to_slices(rect: Rect3):
            return tuple(slice(rect.lo[ax], rect.hi[ax]) for ax in range(3))

        def region_update(blocks, region, origin):
            views = {k: ShardView(b, lo, region) for k, b in blocks.items()}
            info = BlockInfo(origin, n, self._size, r_user, region)
            return kernel(views, info)

        def write_region(new_block, region, vals):
            idx = tuple(
                slice(lo[ax] + region[ax].start, lo[ax] + region[ax].stop) for ax in range(3)
            )
            # leading component dims (N-D data) ride unsliced
            # stencil-lint: disable=halo-set-in-loop interior compute-region write on the generic correctness-first path, not a halo sliver; the measured fast paths go through ops/stream.py's aliased kernels
            return new_block.at[(Ellipsis,) + idx].set(vals)

        def one_step(blocks):
            """One macro step: exchange + ``mult`` compute sub-steps."""
            origin = tuple(
                lax.axis_index(MESH_AXES[ax]) * n[ax] for ax in range(3)
            )
            if overlap:
                # interior: no shell reads -> no ppermute dependency; XLA
                # schedules it concurrently with the collective
                with jax.named_scope(tm.SPAN_OVERLAP_INTERIOR):
                    int_region = rect_to_slices(interior_rect)
                    int_vals = region_update(blocks, int_region, origin)
            # joint multi-quantity exchange: all fields fuse into one message
            # per direction (reference packer.cuh:52-69), ≤6 permutes total;
            # the z sweep runs the realize-resolved route, so fused steps
            # escape the 64×-amplified thin-z path exactly like exchange()
            exch = dict(
                zip(
                    names,
                    halo_exchange_multi(
                        [blocks[k] for k in names],
                        shell,
                        mesh_shape,
                        valid_last=self._valid_last,
                        route=self._exchange_route,
                    ),
                )
            )
            cur = exch
            for j, rect in enumerate(sub_regions):
                region = rect_to_slices(rect)
                new_blocks = dict(cur)
                if j == 0 and overlap:
                    for k in names:
                        if k in int_vals:
                            new_blocks[k] = write_region(new_blocks[k], int_region, int_vals[k])
                    # exterior slabs (incl. shell extensions) read fresh halos
                    for ext_rect in exterior_of(rect, interior_rect):
                        ext_region = rect_to_slices(ext_rect)
                        vals = region_update(cur, ext_region, origin)
                        for k in names:
                            if k in vals:
                                new_blocks[k] = write_region(new_blocks[k], ext_region, vals[k])
                else:
                    vals = region_update(cur, region, origin)
                    for k in names:
                        if k in vals:
                            new_blocks[k] = write_region(new_blocks[k], region, vals[k])
                cur = new_blocks
            return cur

        def per_shard(steps, *blocks_tuple):
            blocks = dict(zip(names, blocks_tuple))
            # device-side iteration: many steps per dispatch.  The fused,
            # replayed step graph is the TPU analog of the reference's
            # CUDA-Graph pack replay (packer.cuh:168-187) — and in-loop
            # dynamic-update-slices stay in place in HBM.
            blocks = lax.fori_loop(0, steps, lambda _, b: one_step(b), blocks)
            return tuple(blocks[k] for k in names)

        specs = tuple(_qspec(h) for h in self._handles)
        donate_kw = {"donate_argnums": 0} if donate else {}
        # vma validation stays on whenever neither the exchange's blend
        # kernels nor the packed pallas route can engage — user kernels get
        # full varying-manual-axes checking on the plain-DUS path
        from stencil_tpu.ops.exchange import route_vma_check

        check_vma = route_vma_check(
            [self.field_dtype(h) for h in self._handles],
            self._valid_last,
            max((len(h.components) for h in self._handles), default=0),
            self._exchange_route,
        )

        @partial(jax.jit, static_argnums=1, **donate_kw)
        def step(curr: Dict[str, jax.Array], steps: int = 1) -> Dict[str, jax.Array]:
            fn = shard_map(
                partial(per_shard, steps),
                mesh=self.mesh,
                in_specs=specs,
                out_specs=specs,
                check_vma=check_vma,
            )
            outs = fn(*[curr[k] for k in names])
            return dict(zip(names, outs))

        # under a halo multiplier each built step is a MACRO step advancing
        # `mult` raw iterations — consumers that count raw steps (the
        # divergence sentinel) read this factor off the step
        step._raw_steps_per_call = mult
        return step

    def run_step(self, step_fn, steps: int = 1, label: str = None) -> None:
        """Apply a built step to curr and make its output the new curr.

        The built step already fuses the buffer rotation: with donation the
        old curr's HBM is reused for the output (the functional analog of the
        reference's pointer swap, src/local_domain.cu:41-54), so the old
        arrays must not be retained — the ``next`` slot is left untouched.

        ``steps > 1`` runs that many iterations in ONE device dispatch
        (``lax.fori_loop`` inside the shard_map) — essential on TPU, where
        per-dispatch overhead would otherwise dominate small steps.

        This is the resilience layer's DISPATCH boundary (one entry for
        every engine — xla, stream, and the bespoke pallas paths):

        * classified ``TRANSIENT_RUNTIME`` failures (the remote-compile
          tunnel class) retry with exponential backoff — guarded by a
          donated-buffer liveness check, so a failure that surfaced AFTER
          donation propagates instead of re-reading freed memory;
        * the ``STENCIL_FAULT_PLAN`` hook fires here with phase
          ``dispatch`` and this call's ``label`` (models pass their name);
        * the dispatch watchdog (``STENCIL_WATCHDOG_S`` /
          ``set_watchdog``) is armed around the dispatch: a wedge past the
          deadline emits a ``watchdog.stall`` event, and in abort mode
          surfaces as a classified ``StallError`` for supervisor recovery;
        * the divergence sentinel (``set_divergence_check``) runs on its
          cadence after a successful dispatch.

        This is also the TELEMETRY boundary: the dispatch counters
        (``domain.step.*``) and analytic exchange bytes are always counted;
        with telemetry enabled the dispatch is additionally honest-synced and
        its wall time recorded as a span plus a per-raw-iteration histogram
        sample (``domain.step.seconds``) — enabling telemetry therefore adds
        one device sync per dispatch, exactly like exchange-stats.
        """
        from stencil_tpu.resilience import inject
        from stencil_tpu.resilience.retry import RetryPolicy, execute_with_retry
        from stencil_tpu.resilience.sentinel import DivergenceSentinel

        if label is None:
            label = getattr(step_fn, "_resilience_label", "step")
        if self._retry_policy is None:
            self._retry_policy = RetryPolicy.from_env()

        def dispatch():
            inject.maybe_fail("dispatch", label)
            return self._watched_call(
                f"dispatch:{label}", lambda: step_fn(self._curr, steps)
            )

        raw = steps * getattr(step_fn, "_raw_steps_per_call", 1)
        timed = telemetry.enabled()
        t0 = time.perf_counter() if timed else 0.0
        self._curr = execute_with_retry(
            dispatch,
            label=f"dispatch:{label}",
            policy=self._retry_policy,
            buffers=lambda: self._curr,
        )
        if timed:
            self.block_until_ready()
            dt = time.perf_counter() - t0
            telemetry.record_span(tm.SPAN_STEP, t0, dt, label=label, steps=raw)
            telemetry.observe(tm.STEP_SECONDS, dt / max(raw, 1))
        telemetry.inc(tm.STEP_DISPATCHES)
        telemetry.inc(tm.STEP_ITERATIONS, raw)
        # analytic exchange traffic of the fused step: one exchange per macro
        # (= raw iterations / halo multiplier) at exchange_bytes_total bytes —
        # the modeled bytes, not a measured count (exchange-free single-device
        # routes are still attributed their modeled halo traffic)
        self._account_exchanges(max(raw // max(self._halo_mult, 1), 1))
        # streaming-engine steps advance interiors only; the carried shell
        # goes stale and raw readback must re-exchange first
        if getattr(step_fn, "_marks_shell_stale", False):
            self.mark_shell_stale()
        if self._sentinel is None:
            self._sentinel = DivergenceSentinel(self._divergence_every)
        elif self._sentinel.every != self._divergence_every:
            # cadence changed mid-run (set_divergence_check on a domain
            # whose sentinel predates the setter): update in place — a
            # rebuild would silently reset steps_done and mislabel every
            # later divergence step
            self._sentinel.set_every(self._divergence_every)
        # sentinel cadence and the reported step index are in RAW iterations:
        # a macro step (halo multiplier on the xla engine) advances `mult`
        # raw iterations per dispatch-step, which the built step declares
        self._sentinel.after_steps(self, raw)
        # the numerics observatory's independent observe cadence (snapshots
        # + guardbands — telemetry/numerics.py).  ALWAYS accounted, even
        # with the cadence off: the engine's step counter must agree with
        # the sentinel's when the observatory is enabled mid-run (a
        # counter that starts at the enable point would mislabel every
        # snapshot and defeat the shared-dispatch dedupe), and off-cadence
        # accounting is two int ops on a jax-free object
        self.numerics().after_steps(raw)
