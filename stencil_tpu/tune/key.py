"""Workload keys: what a tuned config is FOR.

A config measured on one workload must never be applied to another: the
k-plateau, alias crossover, and layout picks all shift with the chip
generation, domain shape, dtype, field count, mesh, radius, and engine
route (PERF_NOTES.md "re-qualify when the toolchain or chip generation
changes").  ``WorkloadKey`` pins all seven axes; the jax/jaxlib toolchain
version is checked separately by the cache layer (``cache.py``), so a
toolchain upgrade invalidates every persisted config at load time without
changing the key (and hence the cache filename) itself.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Tuple


def chip_kind() -> str:
    """The device kind tuned configs are keyed by — ``device_kind`` when a
    backend is up-able (e.g. "TPU v5e", "cpu"), else the platform name.
    Only called from tuning/plan paths that already initialized jax."""
    import jax

    try:
        return str(jax.devices()[0].device_kind)
    except Exception:  # noqa: BLE001 — device-less topologies, odd backends
        return str(jax.default_backend())


@dataclasses.dataclass(frozen=True)
class WorkloadKey:
    """One tunable workload: (chip kind, global domain shape, dtype,
    n_fields, mesh shape, radius, engine route)."""

    chip: str
    domain: Tuple[int, int, int]
    dtype: str
    n_fields: int
    mesh: Tuple[int, int, int]
    radius: int
    route: str  # "jacobi-wrap" | "jacobi-wavefront" | "stream" | "exchange"
    # | ... — "exchange" keys the halo-exchange route search, whose persisted
    # config carries the ``exchange_route`` field (tune/space.py
    # ``exchange_space``; consulted by DistributedDomain.realize)

    def to_dict(self) -> dict:
        return {
            "chip": self.chip,
            "domain": list(self.domain),
            "dtype": self.dtype,
            "n_fields": self.n_fields,
            "mesh": list(self.mesh),
            "radius": self.radius,
            "route": self.route,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "WorkloadKey":
        return cls(
            chip=str(d["chip"]),
            domain=tuple(int(v) for v in d["domain"]),
            dtype=str(d["dtype"]),
            n_fields=int(d["n_fields"]),
            mesh=tuple(int(v) for v in d["mesh"]),
            radius=int(d["radius"]),
            route=str(d["route"]),
        )

    def digest(self) -> str:
        """Stable content hash — the cache filename stem."""
        canon = json.dumps(self.to_dict(), sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(canon.encode()).hexdigest()[:16]

    def label(self) -> str:
        """Human/log/fault-plan label, e.g.
        ``jacobi-wrap:512x512x512:float32x1:mesh1x1x1``."""
        return (
            f"{self.route}:{'x'.join(map(str, self.domain))}:"
            f"{self.dtype}x{self.n_fields}:mesh{'x'.join(map(str, self.mesh))}"
        )
