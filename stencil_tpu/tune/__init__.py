"""Measurement-driven autotuner with a persistent config cache.

The fast paths were calibrated on ONE v5e and frozen in code (``_WRAP_MAX_K
= 16``, the VMEM-model depth picks, alias/z-ring defaults, route selection).
PERF_NOTES.md documents the k-plateau spanning ~12-24 under heavy contention
noise and says to re-qualify the constants per toolchain/chip generation —
this package is the way to do that:

* ``best_config(key)`` — THE consult entry point.  Every fast-path planner
  (``choose_temporal_k``, ``plan_stream``, ``Jacobi3D._plan_wavefront``)
  asks it for the workload's persisted config and falls back to the static
  calibrated pick on a miss.  Zero trials, zero jax work: a cache hit is a
  file read (memoized per process).
* ``ensure(key, candidates, build_run, ...)`` — consult-or-search: on a
  cache miss, run the burst-aware trial protocol (``trial.py``) over the
  candidate space (``space.py``) and persist the winner, so the SECOND run
  does zero trials.
* ``runners`` — concrete searches for the shipped workloads
  (``autotune_jacobi_wrap``, ``autotune_jacobi_wavefront``,
  ``autotune_stream``), invoked by ``bench.py`` and the ``--tune`` driver
  flag.

Knobs (validated reads, ``utils/config.py``):

* ``STENCIL_TUNE=0``        — ignore tuned configs entirely (static picks)
* ``STENCIL_TUNE_CACHE=D``  — cache directory (default
  ``~/.cache/stencil_tpu/tune``); ``--tune-cache`` overrides per run

Every decision is telemetry (``tune.cache.hit/miss``, ``tune.trials``,
``tune.pruned``, ``tune.selected`` counters; ``tune.decision`` /
``tune.trial`` events) — see docs/tuning.md.
"""

from __future__ import annotations

import contextlib
from typing import Callable, List, Optional

from stencil_tpu import telemetry
from stencil_tpu.telemetry import names as tm
from stencil_tpu.tune import cache as _cache
from stencil_tpu.tune.key import WorkloadKey, chip_kind  # noqa: F401 (re-export)
from stencil_tpu.tune.trial import TrialResult, TuneReport, search  # noqa: F401

#: process-local enable override (driver --tune/--no-tune); None = env
_enabled_override: Optional[bool] = None

#: memoized consults: (cache_dir, key.digest()) -> config dict or None
_memo: dict = {}


def enabled() -> bool:
    """Is tuned-config consultation on?  ``STENCIL_TUNE=0`` (or a driver's
    ``--no-tune``) turns every ``best_config`` into a miss-without-counting,
    i.e. the static calibrated picks."""
    if _enabled_override is not None:
        return _enabled_override
    from stencil_tpu.utils.config import env_bool

    return env_bool("STENCIL_TUNE", True)


def set_enabled(value: Optional[bool]) -> None:
    """Process-local override (``--tune``/``--no-tune``); None restores the
    env-driven default."""
    global _enabled_override
    _enabled_override = value


@contextlib.contextmanager
def disabled():
    """Scoped consult-off — the runners use it to compute the STATIC pick
    (the fallback a search must defend) without reading their own cache."""
    prev = _enabled_override
    set_enabled(False)
    try:
        yield
    finally:
        set_enabled(prev)


def set_cache_dir(path: Optional[str]) -> None:
    """Per-run cache-dir override (driver ``--tune-cache``)."""
    _cache.set_dir_override(path)
    _memo.clear()


def overrides():
    """Opaque snapshot of the process-local overrides — drivers save it in
    ``tune_begin`` and hand it back to ``restore`` in ``tune_end`` so
    sequential in-process runs (tests) don't leak ``--no-tune`` state."""
    return (_enabled_override, _cache._dir_override)


def restore(state) -> None:
    set_enabled(state[0])
    set_cache_dir(state[1])


def reset_memo() -> None:
    """Drop the per-process consult memo (tests that rewrite cache files)."""
    _memo.clear()


def best_config(key: WorkloadKey) -> Optional[dict]:
    """The persisted config for ``key``, or None (caller falls back to its
    static pick).  Counts ``tune.cache.hit``/``tune.cache.miss`` per consult;
    disabled tuning returns None without counting (the fallback is a
    decision, not a miss)."""
    if not enabled():
        return None
    memo_key = (_cache.cache_dir(), key.digest())
    if memo_key in _memo:
        cfg = _memo[memo_key]
    else:
        loaded = _cache.load(key)
        cfg = loaded[0] if loaded is not None else None
        _memo[memo_key] = cfg
    if cfg is None:
        telemetry.inc(tm.TUNE_CACHE_MISS)
        return None
    telemetry.inc(tm.TUNE_CACHE_HIT)
    return dict(cfg)


def record_config(key: WorkloadKey, config: dict, meta: Optional[dict] = None) -> str:
    """Persist ``config`` as the tuned pick for ``key`` (and update the
    consult memo so this process sees it immediately)."""
    path = _cache.store(key, config, meta)
    _memo[(_cache.cache_dir(), key.digest())] = dict(config)
    return path


def ensure(
    key: WorkloadKey,
    candidates: List[dict],
    build_run: Callable[[dict], Callable[[int], None]],
    *,
    depth_key: Optional[str] = None,
    static: Optional[dict] = None,
    reps: int = 3,
    rt: Optional[float] = None,
    prefiltered: int = 0,
) -> TuneReport:
    """Consult-or-search: a warm cache returns immediately with zero trials;
    otherwise run the burst-aware search over ``candidates`` and persist the
    winner.  When every candidate is pruned, the report carries ``static``
    (source ``"static"``) — tuning never crashes a run the fallback could
    have served."""
    cached = best_config(key)
    if cached is not None:
        report = TuneReport(key=key, source="cache", config=cached, static_config=static)
        report.cache_path = _cache.path_for(key)
        telemetry.emit_event(
            tm.EVENT_TUNE_DECISION,
            key=key.label(),
            source="cache",
            config=cached,
            trials=0,
            pruned=0,
        )
        return report
    if not enabled():
        return TuneReport(key=key, source="static", config=static, static_config=static)
    report = search(
        key,
        candidates,
        build_run,
        depth_key=depth_key,
        reps=reps,
        rt=rt,
        prefiltered=prefiltered,
    )
    report.static_config = static
    if report.config is not None:
        meta = {
            "trials": report.trials,
            "pruned": report.pruned,
            "results": report.to_json()["results"],
        }
        report.cache_path = record_config(key, report.config, meta)
        telemetry.inc(tm.TUNE_SELECTED)
    else:
        report.source = "static"
        report.config = static
    telemetry.emit_event(
        tm.EVENT_TUNE_DECISION,
        key=key.label(),
        source=report.source,
        config=report.config,
        trials=report.trials,
        pruned=report.pruned,
    )
    return report
