"""Persistent tuned-config cache: one JSON file per workload key.

Layout: ``<cache_dir>/<key.digest()>.json`` with

    {"schema": 1,
     "jax": "<jax.__version__>", "jaxlib": "<jaxlib.__version__>",
     "key": {...WorkloadKey...},
     "config": {...the winning config...},
     "meta": {...trial provenance (steady-state numbers, trial counts)...}}

``load`` returns ``(config, meta)`` only when the schema AND the jax/jaxlib
versions match the running process — a toolchain upgrade silently
invalidates every persisted config (PERF_NOTES.md: "re-qualify them when
the toolchain or chip generation changes"), exactly like a cold cache.  A
corrupt or truncated file is treated as a miss (warn, never crash): the
cache is an accelerator, not a dependency.

The directory comes from ``STENCIL_TUNE_CACHE`` (validated read,
default ``~/.cache/stencil_tpu/tune``); drivers override it per run via
``--tune-cache`` (``tune.set_cache_dir``).
"""

from __future__ import annotations

import json
import os
from typing import Optional, Tuple

from stencil_tpu.tune.key import WorkloadKey
from stencil_tpu.utils.config import env_str

#: bump when the persisted-config vocabulary changes incompatibly; a schema
#: mismatch is a MISS (stale entries re-qualify, never crash).  History:
#: 1 — depth/alias/layout/stream-plan configs (the autotuner PR);
#: 2 — the ``exchange_route`` field (exchange-route PR): entries persisted
#:     before the packed z-shell routes existed must not be consulted as if
#:     they had compared against them.
SCHEMA = 2

_DEFAULT_DIR = os.path.join("~", ".cache", "stencil_tpu", "tune")

#: process-local override (driver --tune-cache); None = use the env/default
_dir_override: Optional[str] = None


def set_dir_override(path: Optional[str]) -> None:
    global _dir_override
    _dir_override = path


def cache_dir() -> str:
    path = _dir_override or env_str("STENCIL_TUNE_CACHE", _DEFAULT_DIR)
    return os.path.abspath(os.path.expanduser(path))


def _toolchain() -> Tuple[str, str]:
    import jax

    try:
        import jaxlib

        jaxlib_v = getattr(jaxlib, "__version__", "")
    except Exception:  # noqa: BLE001 — jaxlib layout varies across builds
        jaxlib_v = ""
    return jax.__version__, jaxlib_v


def path_for(key: WorkloadKey) -> str:
    return os.path.join(cache_dir(), f"{key.digest()}.json")


def load(key: WorkloadKey) -> Optional[Tuple[dict, dict]]:
    """(config, meta) for ``key``, or None on a miss (absent, corrupt, or
    persisted by a different toolchain/schema)."""
    path = path_for(key)
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        from stencil_tpu.utils.logging import log_warn

        log_warn(f"tune cache {path} is unreadable ({e}); treating as a miss")
        return None
    jax_v, jaxlib_v = _toolchain()
    if (
        not isinstance(doc, dict)
        or doc.get("schema") != SCHEMA
        or doc.get("jax") != jax_v
        or doc.get("jaxlib") != jaxlib_v
        or not isinstance(doc.get("config"), dict)
    ):
        from stencil_tpu.utils.logging import log_info

        log_info(
            f"tune cache {path} is stale (schema/toolchain mismatch); "
            "configs must be re-qualified on this toolchain — treating as a miss"
        )
        return None
    return doc["config"], doc.get("meta") or {}


def store(key: WorkloadKey, config: dict, meta: Optional[dict] = None) -> str:
    """Persist the winning config atomically (utils/artifact.py write-rename:
    a crashed run must not leave a truncated file a later run would
    half-parse)."""
    jax_v, jaxlib_v = _toolchain()
    doc = {
        "schema": SCHEMA,
        "jax": jax_v,
        "jaxlib": jaxlib_v,
        "key": key.to_dict(),
        "config": config,
        "meta": meta or {},
    }
    from stencil_tpu.utils.artifact import atomic_write_json

    return atomic_write_json(path_for(key), doc)
