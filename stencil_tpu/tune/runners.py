"""Concrete autotune searches for the shipped workloads.

Each runner builds the workload key, computes the STATIC pick under
``tune.disabled()`` (the fallback a search must beat — never its own cached
result), generates the candidate space, and hands ``tune.ensure`` a
``build_run`` that compiles/executes the candidate on the device under the
burst-aware protocol.  All candidate state (models, buffers) stays alive for
the whole search — the alternating rounds require every candidate resident
in one process (PERF_NOTES "Measurement discipline").
"""

from __future__ import annotations

from functools import partial
from typing import Optional

from stencil_tpu import tune
from stencil_tpu.tune import space
from stencil_tpu.tune.key import WorkloadKey, chip_kind
from stencil_tpu.tune.trial import TuneReport


def _force_done(arr) -> None:
    """Tunnel-honest completion: a 1-element readback of the first
    addressable shard (block_until_ready returns early through axon)."""
    import jax

    shard = arr.addressable_shards[0].data
    jax.device_get(shard[(slice(0, 1),) * shard.ndim])


def autotune_jacobi_wrap(
    x: int,
    y: int,
    z: int,
    dtype=None,
    interpret: bool = False,
    reps: int = 3,
    ks=None,
    rt: Optional[float] = None,
) -> TuneReport:
    """Tune the single-device wrap kernel's temporal depth ``k`` for this
    chip/shape/dtype.  Candidates span the measured plateau grid plus the
    static ``choose_temporal_k`` pick; a Mosaic VMEM_OOM prunes the failing
    depth and everything deeper."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from stencil_tpu.ops.jacobi_pallas import choose_temporal_k, jacobi_wrap_step

    dtype = jnp.dtype(dtype or jnp.float32)
    key = WorkloadKey(
        chip=chip_kind(),
        domain=(x, y, z),
        dtype=dtype.name,
        n_fields=1,
        mesh=(1, 1, 1),
        radius=1,
        route="jacobi-wrap",
    )
    with tune.disabled():
        static_k = choose_temporal_k((x, y, z), dtype.itemsize)
    candidates, prefiltered = space.jacobi_wrap_space(
        (x, y, z), dtype.itemsize, static_k, ks=ks, dtype=dtype
    )
    # trial buffers allocate lazily at the FIRST candidate build needing
    # them (one per storage dtype — the bf16 twin streams narrow planes):
    # a warm-cache call must not touch device memory at all
    state = {}

    def build_run(cand):
        storage = cand.get("storage_dtype", "native")
        unit = cand.get("compute_unit", "vpu")
        bdt = jnp.bfloat16 if storage == "bf16" else dtype
        if storage not in state:
            state[storage] = jnp.full((x, y, z), 0.5, bdt)
        block = state[storage]
        k = cand["k"]
        kern_kw = {
            "compute_unit": unit,
            "f32_accumulate": storage == "bf16",
            "mxu_input": cand.get("mxu_input", "f32"),
        }

        @partial(jax.jit, static_argnums=1)
        def steps(b, n):
            blocked, rem = divmod(n, k)
            if blocked:
                b = lax.fori_loop(
                    0,
                    blocked,
                    lambda _, bb: jacobi_wrap_step(
                        bb, interpret=interpret, k=k, **kern_kw
                    ),
                    b,
                )
            if rem:
                b = jacobi_wrap_step(b, interpret=interpret, k=rem, **kern_kw)
            return b

        def run(n):
            _force_done(steps(block, n))

        return run

    return tune.ensure(
        key,
        candidates,
        build_run,
        depth_key="k",
        static={
            "k": static_k,
            "compute_unit": "vpu",
            "storage_dtype": "native",
        },
        reps=reps,
        rt=rt,
        prefiltered=prefiltered,
    )


def autotune_jacobi_wavefront(
    x: int,
    y: int,
    z: int,
    dtype=None,
    devices=None,
    interpret: bool = False,
    reps: int = 3,
    ms=None,
    rt: Optional[float] = None,
    strategy=None,  # placement strategy — MUST match the model the caller
    # will build (a different strategy can place a different mesh, which
    # re-keys the workload and orphans the search's cache entry)
) -> TuneReport:
    """Tune the multi-device jacobi wavefront: depth ``m`` (== the halo
    multiplier), ``input_output_aliases`` on/off, and the z-ring vs padded
    layout.  Each candidate is a fully realized ``Jacobi3D`` — expensive by
    design (this is the re-qualification pass), cached so it runs once per
    workload/toolchain."""
    import jax
    import jax.numpy as jnp

    from stencil_tpu.models.jacobi import Jacobi3D

    dtype = jnp.dtype(dtype or jnp.float32)

    def make_model(temporal_k="auto", alias=None, z_ring=None,
                   compute_unit=None, storage_dtype=None, mxu_input=None):
        kwargs = {} if strategy is None else {"strategy": strategy}
        return Jacobi3D(
            x,
            y,
            z,
            devices=devices,
            dtype=dtype,
            kernel_impl="pallas",
            pallas_path="wavefront",
            temporal_k=temporal_k,
            interpret=interpret,
            wavefront_alias=alias,
            z_ring=z_ring,
            compute_unit=compute_unit,
            storage_dtype=storage_dtype,
            mxu_input=mxu_input,
            **kwargs,
        )

    probe = make_model()
    key = probe.dd.tune_key("jacobi-wavefront")
    with tune.disabled():
        static_m = probe._plan_wavefront()  # stashes _wavefront_plan_info
    info = probe._wavefront_plan_info
    # z-ring needs z-slab mode plus a lane-aligned shard z interior
    z_ring_eligible = (
        getattr(probe, "_wavefront_z_planned", False)
        and info["n"][2] % 128 == 0
    )
    from stencil_tpu.ops.jacobi_pallas import (
        band_tile_plan,
        bf16_supported,
        mxu_supported,
    )

    # the band variant needs a tilable plane geometry — the geometry the
    # kernel CONTRACTS (lane-padded under the z-slab route), not the bare
    # raw extent: a ragged raw width that pads to a 128 multiple tiles
    # fine, and prefiltering on the raw dims would drop the band twins
    # from exactly the large padded geometries they were built to win on
    from stencil_tpu.ops.stream import lane_pad_width

    n = info["n"]
    _band_pz = n[2] + 2 * static_m
    if getattr(probe, "_wavefront_z_planned", False):
        _band_pz = lane_pad_width(_band_pz)
    candidates, prefiltered = space.jacobi_wavefront_space(
        static_m,
        # structural caps only (a shard must fill an m-wide halo from valid
        # cells, and the kernel's periodic-coordinate rem needs 2m < the
        # global extent) — deeper than the static shell-traffic heuristic
        # is allowed, measuring past it is the point
        depth_cap=min(info["n_min"], (min(x, y, z) - 1) // 2),
        z_ring_eligible=z_ring_eligible,
        static_z_ring=True,
        ms=ms,
        mxu_ok=mxu_supported([dtype]),
        bf16_ok=bf16_supported([dtype]),
        band_ok=band_tile_plan(n[1] + 2 * static_m, _band_pz) is not None,
    )
    models = {}

    def build_run(cand):
        model = make_model(
            temporal_k=cand["m"], alias=cand["alias"], z_ring=cand.get("z_ring"),
            compute_unit=cand.get("compute_unit"),
            storage_dtype=cand.get("storage_dtype"),
            mxu_input=cand.get("mxu_input"),
        )
        model.realize()
        models[space.candidate_label(cand)] = model  # keep resident

        def run(n):
            model.step(n)
            model.block_until_ready()

        return run

    report = tune.ensure(
        key,
        candidates,
        build_run,
        depth_key="m",
        static={
            "m": static_m,
            "halo_multiplier": static_m,
            "alias": False,
            "z_ring": z_ring_eligible,
            "compute_unit": "vpu",
            "storage_dtype": "native",
        },
        reps=reps,
        rt=rt,
        prefiltered=prefiltered,
    )
    models.clear()  # free candidate HBM before the caller builds the real model
    return report


def autotune_exchange(
    dd,
    reps: int = 3,
    rt: Optional[float] = None,
) -> TuneReport:
    """Tune the halo exchange's z-sweep route (direct vs the packed z-shell
    routes — ops/exchange.py ``EXCHANGE_ROUTES``) for a REALIZED domain.
    Each candidate is a non-donating exchange compiled over the domain's
    live buffers, looped device-side (the ``exchange_many`` protocol) and
    measured under the burst-aware alternating rounds; the domain's state is
    never advanced (exchanging is idempotent on a filled domain).  The
    winner feeds the very next ``realize()`` of this workload via the
    persistent cache — ``DistributedDomain._resolve_exchange_route``
    consults it, with ``direct`` as the static cold-cache fallback."""
    import jax
    from functools import partial as _partial

    from jax import lax

    key = dd.tune_key("exchange")
    candidates, prefiltered = space.exchange_space(dd)
    fns = {}  # keep every candidate's executable resident for the rounds

    def build_run(cand):
        route = cand["exchange_route"]
        fn = dd.make_exchange_route_fn(route, donate=False)
        fns[route] = fn

        @_partial(jax.jit, static_argnums=1)
        def many(arrays, s):
            return lax.fori_loop(0, s, lambda _, a: fn(a), arrays)

        def run(n):
            out = many(dd._curr, n)
            _force_done(next(iter(out.values())))

        return run

    report = tune.ensure(
        key,
        candidates,
        build_run,
        depth_key=None,
        static={"exchange_route": "direct"},
        reps=reps,
        rt=rt,
        prefiltered=prefiltered,
    )
    fns.clear()
    return report


def autotune_stream(
    dd,
    kernel,
    x_radius: int = 1,
    separable: bool = False,
    interpret: bool = False,
    reps: int = 3,
    rt: Optional[float] = None,
    mxu_kernel=None,
) -> TuneReport:
    """Tune the generic stream engine's plan (route, depth, alias, overlap,
    fused halo, compute unit) for a REALIZED domain + user kernel.  Trials run
    non-donating steps over the
    domain's live buffers (the domain state is never advanced), so the
    tuned plan feeds the very next ``make_step(engine="stream")`` on the
    same process via the cache.  ``mxu_kernel`` is the kernel's declared
    contraction form — without it the compute-unit A/B is structurally
    prefiltered (an mxu candidate could only degrade to vpu and measure a
    duplicate)."""
    from stencil_tpu.ops.jacobi_pallas import mxu_supported
    from stencil_tpu.ops.stream import _build_stream_step, plan_stream

    key = dd.tune_key("stream")
    with tune.disabled():
        static_plan = plan_stream(dd, x_radius, "auto", separable)
    mxu_ok = mxu_kernel is not None and mxu_supported(
        [h.dtype for h in dd._handles]
    )
    candidates, prefiltered = space.stream_space(
        dd, x_radius, separable, static_plan, mxu_ok=mxu_ok
    )

    def build_run(cand):
        plan = dict(cand)
        plan.pop("halo_multiplier", None)
        if "alias" in plan:
            # candidate builds must be forcible — the alias A/B has to
            # compile two DIFFERENT kernels even under STENCIL_STREAM_ALIAS
            # (the marker stays out of the persisted config: `cand` wins)
            plan["alias_forced"] = True
        if "overlap" in plan:
            # same for the overlap A/B under STENCIL_STREAM_OVERLAP: the
            # off and split candidates must build their own schedules
            plan["overlap_forced"] = True
        if "halo" in plan:
            # and for the fused-halo A/B under STENCIL_STREAM_HALO
            plan["halo_forced"] = True
        if "compute_unit" in plan:
            # and for the compute-unit A/B under STENCIL_COMPUTE_UNIT
            plan["compute_unit_forced"] = True
        step = _build_stream_step(
            dd, kernel, x_radius, plan, interpret, donate=False,
            mxu_kernel=mxu_kernel,
        )

        def run(n):
            out = step(dd._curr, n)
            _force_done(next(iter(out.values())))

        return run

    static = dict(static_plan)
    static.setdefault("halo_multiplier", static.get("m", 1))
    static.setdefault("overlap", "off")
    static.setdefault("halo", "array")
    static.setdefault("compute_unit", "vpu")
    return tune.ensure(
        key,
        candidates,
        build_run,
        depth_key="m",
        static=static,
        reps=reps,
        rt=rt,
        prefiltered=prefiltered,
    )
