"""Burst-aware trial protocol + candidate search.

This is the measurement discipline PERF_NOTES.md mandates, implemented:

* **Alternate candidates within one process.**  The first timed run after an
  idle/compile gap is up to ~35% faster than steady state (probe25d rep0),
  so sequential best-of-N per candidate spuriously favors whichever side ran
  first.  Here every measurement round visits every surviving candidate
  before any candidate is visited again.
* **Discard rep 0.**  Each candidate's first round eats its own post-idle
  burst; it never enters the statistic.
* **Steady-state median.**  Contention noise on shared chips is heavy-tailed
  (the k-plateau measured 142-202 Gcells/s at one config); the median of the
  remaining rounds is the per-candidate figure of merit.

Dispatch sizing rides ``bin/_common.timed_inner_loop`` (device-side
iteration, host-round-trip subtraction, auto-scaled inner count) calibrated
once on the first surviving candidate and reused for all — candidates tune
the SAME workload, so one calibration keeps the rounds comparable.

Failures route through the resilience taxonomy (``resilience/taxonomy.py``):
a ``VMEM_OOM`` prunes the candidate AND its deeper neighbors (a deeper
temporal depth can only need more VMEM), a ``COMPILE_REJECT`` prunes just
the candidate, ``TRANSIENT_RUNTIME`` retries via the PR-1 retry policy, and
``DIVERGENCE``/``FATAL`` propagate.  ``STENCIL_FAULT_PLAN`` hooks fire at
``compile``/``execute`` phases with labels ``tune:<route>:<candidate>`` so
every pruning path is testable on CPU.
"""

from __future__ import annotations

import dataclasses
import statistics
import time
from typing import Callable, List, Optional

from stencil_tpu import telemetry
from stencil_tpu.resilience import inject
from stencil_tpu.resilience.retry import execute_with_retry
from stencil_tpu.resilience.taxonomy import FailureClass, classify
from stencil_tpu.telemetry import names as tm
from stencil_tpu.tune.key import WorkloadKey
from stencil_tpu.tune.space import candidate_label, deeper_neighbors


@dataclasses.dataclass
class TrialResult:
    """One candidate's outcome: a steady-state figure, or why it was pruned."""

    config: dict
    seconds_per_iter: Optional[float] = None  # steady-state median, per RAW iter
    samples: List[float] = dataclasses.field(default_factory=list)
    pruned: bool = False
    failure_class: Optional[str] = None
    error: Optional[str] = None


@dataclasses.dataclass
class TuneReport:
    """What a ``tune.ensure``/search run decided and how it got there."""

    key: WorkloadKey
    source: str  # "cache" | "search" | "static"
    config: Optional[dict]
    trials: int = 0  # candidates actually measured this run (0 on cache hit)
    pruned: int = 0
    results: List[TrialResult] = dataclasses.field(default_factory=list)
    cache_path: Optional[str] = None
    #: the no-tune fallback the search had to defend (bench embeds its
    #: steady-state number next to the winner's)
    static_config: Optional[dict] = None

    @property
    def cache_hit(self) -> bool:
        return self.source == "cache"

    def result_for(self, config: dict) -> Optional[TrialResult]:
        for r in self.results:
            if r.config == config:
                return r
        return None

    def to_json(self) -> dict:
        """JSON-safe summary for BENCH artifacts / --metrics-out files."""
        return {
            "source": self.source,
            "config": self.config,
            "trials": self.trials,
            "pruned": self.pruned,
            "results": [
                {
                    "config": r.config,
                    "seconds_per_iter": r.seconds_per_iter,
                    "pruned": r.pruned,
                    "failure_class": r.failure_class,
                }
                for r in self.results
            ],
        }


def _prune(result: TrialResult, cls: FailureClass, exc: BaseException) -> None:
    result.pruned = True
    result.failure_class = cls.value
    result.error = str(exc)[:300]


def measure_alternating(
    runs: List[Callable[[int], None]],
    inner,
    rt: float,
    reps: int,
    timer: Callable[[], float] = time.perf_counter,
) -> List[List[float]]:
    """``reps`` steady-state per-iteration samples for each run in ``runs``,
    measured under the burst-aware protocol: ``reps + 1`` rounds alternating
    across all runs within this process, the rep-0 (post-idle-burst) round
    discarded.  Every run must already be warmed at its inner count
    (compiles must not land inside the timing).  ``inner`` is one dispatch
    size for all runs, or a per-run list (``bench.py`` sizes its headline
    and exchange-path dispatches differently).  Shared by the autotuner and
    ``bench.py``'s headline-vs-exchange-path comparison."""
    inners = list(inner) if isinstance(inner, (list, tuple)) else [inner] * len(runs)
    assert len(inners) == len(runs), (len(inners), len(runs))
    samples: List[List[float]] = [[] for _ in runs]
    for rep in range(reps + 1):
        for i, run in enumerate(runs):
            t0 = timer()
            run(inners[i])
            dt = timer() - t0 - rt
            if rep > 0:  # rep 0 harvests the post-idle burst — discard
                samples[i].append(dt / inners[i])
    return samples


def search(
    key: WorkloadKey,
    candidates: List[dict],
    build_run: Callable[[dict], Callable[[int], None]],
    *,
    depth_key: Optional[str] = None,
    reps: int = 3,
    inner: int = 4,
    rt: Optional[float] = None,
    prefiltered: int = 0,
    timer: Callable[[], float] = time.perf_counter,
) -> TuneReport:
    """Measure ``candidates`` under the burst-aware protocol and return a
    ``TuneReport`` whose config is the steady-state winner (or None when
    every candidate was pruned).

    ``build_run(candidate)`` returns ``run(n)``: one synchronous dispatch of
    ``n`` RAW iterations (jit-cached per static ``n``) — build/compile
    failures there are classified and prune rather than crash.
    ``depth_key`` names the candidate field whose larger values are "deeper"
    (``k``/``m``): a VMEM_OOM prunes those neighbors untried.
    ``prefiltered`` counts candidates the caller's VMEM model already
    excluded — they join the pruned telemetry so the counter reflects the
    whole space."""
    from stencil_tpu.bin._common import host_round_trip_s, timed_inner_loop

    if reps < 1:
        raise ValueError(f"tune trials need reps >= 1, got {reps}")
    results = [TrialResult(config=dict(c)) for c in candidates]
    by_id = {id(c): r for c, r in zip(candidates, results)}
    label_of = {id(c): candidate_label(c) for c in candidates}

    if prefiltered:
        telemetry.inc(tm.TUNE_PRUNED, prefiltered)

    def prune_with_neighbors(cand, cls, exc):
        r = by_id[id(cand)]
        _prune(r, cls, exc)
        victims = 1
        if cls is FailureClass.VMEM_OOM:
            for nb in deeper_neighbors(cand, candidates, depth_key):
                nr = by_id[id(nb)]
                if not nr.pruned:
                    _prune(nr, cls, exc)
                    victims += 1
        telemetry.inc(tm.TUNE_PRUNED, victims)

    # --- build + warm (compiles happen here, classified and prunable) -------
    runs = {}
    for cand in candidates:
        r = by_id[id(cand)]
        if r.pruned:  # a shallower sibling's VMEM_OOM already took it out
            continue
        lbl = f"tune:{key.route}:{label_of[id(cand)]}"

        def wrap(run, _lbl):
            # every invocation (warm, calibration, re-warm, timed rounds)
            # rides the transient-retry policy with the execute-phase fault
            # hook INSIDE the retried unit (the run_step dispatch() pattern):
            # an injected/real transient is consumed by retries, never
            # crashes the search.  A retried round's sample is inflated by
            # the backoff, which the steady-state MEDIAN absorbs.
            def attempt(n):
                inject.maybe_fail("execute", _lbl)
                return run(n)

            return lambda n: execute_with_retry(attempt, n, label=_lbl)

        try:
            inject.maybe_fail("compile", lbl)
            run = execute_with_retry(build_run, cand, label=lbl)
            wrapped = wrap(run, lbl)
            wrapped(inner)  # warm/compile at inner
        except Exception as e:  # noqa: BLE001 — classified below
            cls = classify(e)
            if cls in (FailureClass.VMEM_OOM, FailureClass.COMPILE_REJECT):
                prune_with_neighbors(cand, cls, e)
                continue
            raise
        runs[id(cand)] = wrapped

    alive = [c for c in candidates if not by_id[id(c)].pruned]
    if alive:
        if rt is None:
            rt = host_round_trip_s()
        # calibrate the dispatch size once, on the first survivor (the
        # candidates share one workload, so one inner count keeps rounds
        # comparable); its samples are discarded — the alternating rounds
        # below are the only ones that count
        _, inner = timed_inner_loop(runs[id(alive[0])], inner, rt, 1)
        for c in alive[1:]:
            runs[id(c)](inner)  # re-warm at the calibrated static count
        rounds = measure_alternating(
            [runs[id(c)] for c in alive], inner, rt, reps, timer=timer
        )
        for c, samples in zip(alive, rounds):
            r = by_id[id(c)]
            r.samples = samples
            r.seconds_per_iter = statistics.median(samples)
            telemetry.inc(tm.TUNE_TRIALS)
            telemetry.emit_event(
                tm.EVENT_TUNE_TRIAL,
                key=key.label(),
                candidate=label_of[id(c)],
                seconds_per_iter=r.seconds_per_iter,
            )
    for r in results:
        if r.pruned:
            telemetry.emit_event(
                tm.EVENT_TUNE_TRIAL,
                key=key.label(),
                candidate=candidate_label(r.config),
                failure_class=r.failure_class,
                error=r.error,
            )

    winner: Optional[TrialResult] = None
    for r in results:
        if r.seconds_per_iter is None:
            continue
        if winner is None or r.seconds_per_iter < winner.seconds_per_iter:
            winner = r
    return TuneReport(
        key=key,
        source="search",
        config=dict(winner.config) if winner else None,
        trials=sum(1 for r in results if r.seconds_per_iter is not None),
        pruned=prefiltered + sum(1 for r in results if r.pruned),
        results=results,
    )
