"""Discrete candidate spaces for the autotuner.

Axes (ISSUE: the constants PERF_NOTES.md says to re-qualify per chip):

* **temporal depth** ``k`` (wrap) / ``m`` (wavefront) — the HBM-traffic
  lever (~8/k B/cell/iter); the static default ``_WRAP_MAX_K = 16`` sits
  mid-plateau on the one v5e the probes ran on.
* **input_output_aliases on/off** — aliasing serializes the deep pipeline
  (probe21b) but halves the working set; the crossover is chip-dependent.
* **z-ring vs padded layout** — measured NEUTRAL on the probe chip (the
  pipeline is VPU-bound there); a faster-VPU generation flips it.
* **stream route** (wrap/plane/wavefront) and grouping — the generic
  engine's plan axes.
* **overlap** (off/split) — the stream engine's split-step schedule
  (ops/stream.py ``STREAM_OVERLAP``): dispatch the interior pass with no
  ppermute dependency and recompute the boundary bands afterward, so the
  collectives hide behind the VPU work at the cost of ~``6·3w``-wide band
  recomputes; ``off`` is the static fallback, and the win flips with the
  exchange/compute cost ratio — measured, not assumed.
* **exchange route** (direct/zpack_xla/zpack_pallas/yzpack_xla/
  yzpack_pallas) — the halo exchange's y/z-sweep implementation: sliced
  thin slivers vs the packed lane-major z-shell message and, on the
  ``yzpack_*`` routes, the packed sublane-major y-shell message too
  (ops/exchange.py EXCHANGE_ROUTES); ``direct`` is the static fallback,
  the packed routes attack the measured amplification of shell-carrying
  halo storage (PERF_NOTES "Thin z-region access" / "Thin y-region
  access").
* **halo consumption** (array/fused) — the stream engine's fused
  unpack→blend mode (ops/stream.py ``STREAM_HALO``): under ``fused`` the
  packed ``yzpack_*`` messages land directly in the pass's level-0 VMEM
  working planes and the big array never sees a halo write; ``array`` is
  the static fallback — the win trades the saved unpack/blend dispatches
  against per-plane patch selects, so it is measured, not assumed.
* **compute unit** (vpu/mxu) — the level kernels' execution unit
  (ops/jacobi_pallas ``COMPUTE_UNITS``): the roll+add chain on the vector
  lanes vs one banded contraction per in-plane axis on the matrix unit —
  the "Break the VPU wall" lever (PERF_NOTES "VPU wall": the k≈12-24
  plateau is roll+add-bound, not DMA).  ``vpu`` is the static fallback;
  mxu candidates are structurally prefiltered to f32-compute plans.
* **storage dtype** (native/bf16) — bf16 field buffers with f32
  accumulation in-kernel, halving bytes/cell on the DMA-bound shallow-k
  paths; ``native`` is the static fallback, bf16 prefiltered to f32
  fields (the only narrowing with an analytic error contract).
* **halo multiplier** — for the temporally-blocked paths the multiplier IS
  the wavefront depth (the m-wide shell is exchanged every m steps), so the
  ``m`` axis covers it; candidate dicts carry ``halo_multiplier == m`` to
  make that explicit in persisted configs.

Every space includes the CURRENT STATIC PICK as a candidate, so the search
winner is never worse than the no-tune fallback under the same protocol.
Candidates the VMEM model already excludes are returned separately
(``prefiltered``) — they count into the ``tune.pruned`` telemetry without
burning a trial.
"""

from __future__ import annotations

from typing import List, Optional, Tuple


def candidate_label(cand: dict) -> str:
    """Stable short label for logs / fault-plan targeting, e.g.
    ``alias=0/k=8``.  ``/``-separated, NOT commas: ``STENCIL_FAULT_PLAN``
    splits its entry list on commas, and these labels must be targetable."""
    parts = []
    for k in sorted(cand):
        v = cand[k]
        if isinstance(v, bool):
            v = int(v)
        parts.append(f"{k}={v}")
    return "/".join(parts)


#: candidate fields DERIVED from the depth (documentation riders in the
#: persisted config, not independent axes) — excluded when comparing
#: candidates for deeper-neighbor pruning, or the mirrored value would make
#: every deeper candidate look like a different config family
_DERIVED_FIELDS = ("halo_multiplier",)


def deeper_neighbors(cand: dict, candidates: List[dict], depth_key: Optional[str]) -> List[dict]:
    """Candidates identical to ``cand`` except for a LARGER ``depth_key``
    value — the ones a VMEM_OOM at ``cand`` proves can't compile either.
    Depth-derived riders (``halo_multiplier == m``) are ignored in the
    comparison."""
    if not depth_key or depth_key not in cand:
        return []

    def base_of(c):
        return {
            k: v
            for k, v in c.items()
            if k != depth_key and k not in _DERIVED_FIELDS
        }

    base = base_of(cand)
    return [
        c
        for c in candidates
        if c is not cand
        and c.get(depth_key) is not None
        and base_of(c) == base
        and c[depth_key] > cand[depth_key]
    ]


#: depth grid spanning the measured plateau and its edges (probe20b/c/d:
#: k=8 128-132, k=12 190, k=16 142-202, k=20-24 ~190, k=32 152 Gcells/s)
_DEPTH_GRID = (4, 8, 12, 16, 20, 24)


def jacobi_wrap_space(
    shape: Tuple[int, int, int],
    itemsize: int,
    static_k: int,
    ks=None,
    dtype=None,
) -> Tuple[List[dict], int]:
    """(candidates, prefiltered_count) over the wrap kernel's temporal depth
    ``k`` plus, at the static depth, the compute-unit and storage-dtype
    A/Bs (one twin each, like the wavefront space's z-ring pair — the axes
    are independent of depth to first order, so one pair per search
    re-qualifies them cheaply).  Structural prefilters: ``mxu`` only for
    f32-compute plans, ``bf16`` only for f32 fields — filtered twins count
    into ``tune.pruned`` without burning a trial.  ``ks`` overrides the
    depth grid (tests / narrow re-qualification); ``dtype`` (default f32)
    drives the axis prefilters."""
    import jax.numpy as jnp

    from stencil_tpu.ops.jacobi_pallas import (
        band_tile_plan,
        bf16_supported,
        mxu_supported,
        wavefront_vmem_fits,
    )

    dtype = jnp.dtype(dtype or jnp.float32)
    X, Y, Z = shape
    grid = sorted({static_k, *(ks if ks is not None else _DEPTH_GRID)})
    grid = [k for k in grid if 1 <= k <= max(1, X // 2)]
    kept, prefiltered = [], 0
    for k in grid:
        # the static pick always runs (it IS the fallback being defended);
        # other depths must pass the VMEM model to be worth a compile
        if k == static_k or wavefront_vmem_fits(k, Y, Z, itemsize):
            kept.append(
                {"k": k, "compute_unit": "vpu", "storage_dtype": "native"}
            )
        else:
            prefiltered += 1
    # the axis A/Bs at the static depth (persisted winners carry the axes
    # explicitly; pre-axis cache entries without the fields stay warm —
    # absent = the static vpu/native/f32, no schema bump).  Unlike the
    # static pick itself the twins are NOT the defended fallback, so they
    # must pass the VMEM model — with the resolved variant's resident
    # contraction constants / bf16's narrow pipeline planes over an f32
    # level ring folded in.
    if mxu_supported([dtype]) and wavefront_vmem_fits(
        static_k, Y, Z, itemsize, mxu=True
    ):
        kept.append(
            {"k": static_k, "compute_unit": "mxu", "storage_dtype": "native"}
        )
    else:
        prefiltered += 1
    # the band-tiled variant twin + its bf16-INPUT leg (the doubled-ratio
    # arm of the "VPU wall" break-even model) — prefiltered when the plane
    # geometry admits no tiling (the kernel would just re-measure dense)
    if (
        mxu_supported([dtype])
        and band_tile_plan(Y, Z) is not None
        and wavefront_vmem_fits(static_k, Y, Z, itemsize, mxu="mxu_band")
    ):
        kept.append(
            {"k": static_k, "compute_unit": "mxu_band",
             "storage_dtype": "native"}
        )
        kept.append(
            {"k": static_k, "compute_unit": "mxu_band",
             "storage_dtype": "native", "mxu_input": "bf16"}
        )
    else:
        prefiltered += 2
    if bf16_supported([dtype]) and wavefront_vmem_fits(
        static_k, Y, Z, jnp.dtype(jnp.bfloat16).itemsize,
        ring_itemsize=itemsize,
    ):
        kept.append(
            {"k": static_k, "compute_unit": "vpu", "storage_dtype": "bf16"}
        )
    else:
        prefiltered += 1
    return kept, prefiltered


def jacobi_wavefront_space(
    static_m: int,
    depth_cap: int,
    z_ring_eligible: bool,
    static_z_ring: bool,
    ms=None,
    mxu_ok: bool = False,
    bf16_ok: bool = False,
    band_ok: bool = False,
) -> Tuple[List[dict], int]:
    """(candidates, prefiltered) over the multi-device wavefront: depth ``m``
    (== the halo multiplier: the m-wide shell is exchanged every m steps),
    alias on/off, and — at the static depth — z-ring vs padded layout plus
    the compute-unit / storage-dtype A/Bs (``mxu_ok`` / ``bf16_ok`` /
    ``band_ok`` are the structural prefilters the caller evaluates: f32
    compute / f32 fields / a band-tilable raw plane geometry).
    ``depth_cap`` is the structural bound (shard/valid extents)."""
    grid = sorted({static_m, *(ms if ms is not None else _DEPTH_GRID)})
    grid = [m for m in grid if 1 <= m <= depth_cap]
    cands: List[dict] = []

    def cand(m, alias, z_ring, unit="vpu", storage="native"):
        return {
            "m": m,
            "halo_multiplier": m,
            "alias": alias,
            "z_ring": z_ring,
            "compute_unit": unit,
            "storage_dtype": storage,
        }

    for m in grid:
        for alias in (False, True):
            cands.append(cand(m, alias, static_z_ring and z_ring_eligible))
    if z_ring_eligible:
        # the layout A/B at the static depth only: probe25d measured it
        # NEUTRAL on v5e, so one pair per search re-qualifies it cheaply
        cands.append(cand(static_m, False, not static_z_ring))
    static_ring = static_z_ring and z_ring_eligible
    prefiltered = 0
    # the new-axis A/Bs at the static depth (one twin each, like z-ring)
    if mxu_ok:
        cands.append(cand(static_m, False, static_ring, unit="mxu"))
    else:
        prefiltered += 1
    # the band-tiled variant twin + its bf16-input leg
    if mxu_ok and band_ok:
        cands.append(cand(static_m, False, static_ring, unit="mxu_band"))
        c = cand(static_m, False, static_ring, unit="mxu_band")
        c["mxu_input"] = "bf16"
        cands.append(c)
    else:
        prefiltered += 2
    if bf16_ok:
        cands.append(cand(static_m, False, static_ring, storage="bf16"))
    else:
        prefiltered += 1
    return cands, prefiltered


def exchange_space(dd) -> Tuple[List[dict], int]:
    """(candidates, prefiltered) over the halo exchange's y/z-sweep route
    (``ops/exchange.py`` EXCHANGE_ROUTES) for a REALIZED domain: ``direct``
    (the static fallback — the thin-z sliver path, ~64×-amplified on the
    (8,128) tiling, PERF_NOTES "Thin z-region access"; the y sliver is
    sublane-amplified ~8/(2r), "Thin y-region access") vs the packed
    z-shell routes (``zpack_xla`` / ``zpack_pallas``: lane-major ``(2m, Y,
    Xpad)`` messages) and the y+z packed routes (``yzpack_xla`` /
    ``yzpack_pallas``: additionally the sublane-major ``(2m, X, Z)`` y
    message).  Candidates that structurally cannot engage are prefiltered —
    they count into ``tune.pruned`` without burning a trial.  A ``zpack_*``
    candidate needs the z sweep; a ``yzpack_*`` candidate needs the Y sweep
    (with y ineligible it would compile and measure a byte-identical
    duplicate of its ``zpack_*`` sibling)."""
    from stencil_tpu.ops.exchange import (
        EXCHANGE_ROUTES,
        Y_PACK_ROUTES,
        ypack_supported,
        zpack_supported,
    )

    cands: List[dict] = [{"exchange_route": "direct"}]
    shell = dd._shell_radius
    dtypes = [dd.field_dtype(h) for h in dd._handles]
    z_ok = (
        shell is not None
        and (shell.axis(2, -1) > 0 or shell.axis(2, +1) > 0)
        and zpack_supported(dtypes, dd._valid_last)
    )
    y_ok = (
        shell is not None
        and (shell.axis(1, -1) > 0 or shell.axis(1, +1) > 0)
        and ypack_supported(dtypes, dd._valid_last)
    )
    prefiltered = 0
    for route in EXCHANGE_ROUTES[1:]:
        if y_ok if route in Y_PACK_ROUTES else z_ok:
            cands.append({"exchange_route": route})
        else:
            prefiltered += 1
    return cands, prefiltered


def stream_space(dd, x_radius: int, separable: bool, static_plan: dict,
                 mxu_ok: bool = False) -> Tuple[List[dict], int]:
    """(candidates, prefiltered) of full stream-engine plans around the
    static pick: the static plan, its shallower depths, the alias flip, the
    plane route as the m=1 structural baseline, the split-step overlap
    A/B (``overlap ∈ {off, split}``, ops/stream.py — the interior pass
    dispatched with no ppermute dependency), and the compute-unit A/B
    (``compute_unit ∈ {vpu, mxu}`` — the banded-contraction form; only when
    ``mxu_ok``: the kernel declares an mxu form AND computes at f32).
    Every candidate is a plan dict ``_build_stream_step`` accepts verbatim
    (+ ``alias``/``overlap``/``compute_unit``).

    Every candidate carries explicit ``overlap``, ``halo``, and
    ``compute_unit`` fields ("off"/"array"/"vpu" unless it IS that axis's
    twin) so persisted winners record the axes — while older entries
    WITHOUT the fields stay consultable (absent = the static
    off/array/vpu, ops/stream.py ``_overlap_request`` /
    ``_halo_request`` / the compute-unit resolver); no cache schema
    bump.  The split twin of a z-slab wavefront re-plans to the plain form
    (``plain_wavefront_plan``): split needs z halos in the big array for
    the exchange it overlaps.  The fused-halo twin (``halo="fused"`` —
    the packed messages land in the pass's level-0 VMEM planes,
    docs/tuning.md "Fused halo consumption") re-plans the same way and is
    structurally prefiltered unless the domain's resolved exchange route
    packs the y shell (``fused_halo_ineligible``)."""
    from stencil_tpu.ops.stream import (
        fused_halo_ineligible,
        plain_wavefront_plan,
        plan_stream,
    )

    cands: List[dict] = []

    def add(plan: dict, alias: Optional[bool], overlap: str = "off",
            unit: str = "vpu", halo: str = "array") -> None:
        c = dict(plan)
        if alias is not None:
            c["alias"] = alias
        c["overlap"] = overlap
        c["halo"] = halo
        c["compute_unit"] = unit
        c.setdefault("halo_multiplier", c.get("m", 1))
        if c not in cands:
            cands.append(c)

    nq = len(dd._handles)
    static_alias = nq >= 4  # the _build_stream_step auto rule
    add(static_plan, static_alias if static_plan["route"] != "wrap" else None)
    if static_plan["route"] in ("wavefront", "wrap"):
        m = static_plan["m"]
        depths = sorted({d for d in (*_DEPTH_GRID, m // 2) if 2 <= d < m})[-2:]
        for d in depths:
            shallower = plan_stream(
                dd, x_radius, static_plan["route"], separable, max_m=d
            )
            add(shallower, static_alias if shallower["route"] != "wrap" else None)
        if static_plan["route"] == "wavefront":
            add(static_plan, not static_alias)  # the alias A/B (probe21b)
    if static_plan["route"] != "plane":
        try:
            add(plan_stream(dd, x_radius, "plane", separable), None)
        except ValueError:
            pass
    # the overlap A/B: a split twin of the static plan (via the plain-form
    # re-plan when the static pick is a z-slab wavefront), plus a split twin
    # of the plane baseline when one made the space — both measured against
    # their off siblings under the same protocol
    split_bases: List[Tuple[dict, Optional[bool]]] = []
    if static_plan["route"] in ("plane", "wavefront"):
        base = static_plan
        if static_plan.get("z_slabs"):
            base = plain_wavefront_plan(dd, static_plan)
        if base is not None:
            split_bases.append((base, static_alias))
    for c in cands:
        if c["route"] == "plane" and c["overlap"] == "off":
            split_bases.append((c, c.get("alias")))
            break
    for base, alias_pick in split_bases:
        b = {k: v for k, v in base.items()
             if k not in ("overlap", "halo", "halo_multiplier")}
        add(b, alias_pick, overlap="split")
    prefiltered = 0
    # the fused-halo A/B: a fused twin of the static plan (plain-form
    # re-plan when the static pick is a z-slab wavefront, like split),
    # measured against its array sibling — prefiltered when the fused mode
    # structurally cannot engage (non-yzpack exchange route, uneven
    # shards, wrap route, unsupported dtype)
    fused_base = None
    if static_plan["route"] in ("plane", "wavefront"):
        fused_base = static_plan
        if static_plan.get("z_slabs"):
            fused_base = plain_wavefront_plan(dd, static_plan)
    if fused_base is not None and fused_halo_ineligible(
        dd,
        dict(fused_base, overlap="off", z_slabs=fused_base.get("z_slabs", False)),
        getattr(dd, "_exchange_route", "direct"),
    ) is None:
        b = {k: v for k, v in fused_base.items()
             if k not in ("overlap", "halo", "halo_multiplier")}
        add(b, static_alias, halo="fused")
    else:
        prefiltered += 1
    # the compute-unit A/B: an mxu twin of the static plan, measured against
    # its vpu sibling under the same protocol (the "Break the VPU wall"
    # lever — the win depends on where the plan sits relative to the
    # roll+add wall, so it is measured, not assumed), plus the band-tiled
    # variant twin when the raw plane geometry tiles (band_tile_plan) —
    # pre-variant cache entries (compute_unit="mxu" winners) stay warm:
    # the value keeps its meaning and absent mxu_input = the static f32
    if mxu_ok:
        from stencil_tpu.ops.jacobi_pallas import band_tile_plan

        b = {
            k: v
            for k, v in static_plan.items()
            if k not in ("overlap", "halo_multiplier", "compute_unit")
        }
        add(b, static_alias if static_plan["route"] != "wrap" else None,
            unit="mxu")
        raw = dd.local_spec().raw_size()
        if band_tile_plan(raw.y, raw.z) is not None:
            add(b, static_alias if static_plan["route"] != "wrap" else None,
                unit="mxu_band")
        else:
            prefiltered += 1
    else:
        prefiltered += 2
    # static verdicts: candidates whose MODELED footprint busts the
    # scoped-VMEM budget (analysis/vmem.py), or whose kernels the Mosaic
    # legality model rejects (analysis/kernels.py — x64 index arithmetic,
    # rotate operand width, sub-granule block windows), are pruned here,
    # before the search pays a compile-and-catch VMEM_OOM/COMPILE_REJECT
    # for them.  plan_stream already depth-gates the vpu plans through the
    # VMEM model, so that leg mostly catches the twins the planner never
    # modeled — the mxu twin's resident band matrices foremost.  The
    # static pick always survives (it IS the no-tune fallback being
    # defended), matching the wrap space's rule.
    from stencil_tpu.analysis import check_kernel_legal, check_vmem

    kept = []
    for c in cands:
        is_static = (
            all(c.get(k) == v for k, v in static_plan.items()
                if k not in ("halo_multiplier", "alias"))
            and c.get("overlap", "off") == "off"
            and c.get("halo", "array") == "array"
            and c.get("compute_unit", "vpu") == "vpu"
        )
        if not is_static and (
            check_vmem(dd, c) is not None
            or check_kernel_legal(dd, c) is not None
        ):
            prefiltered += 1
        else:
            kept.append(c)
    return kept, prefiltered
