"""Static VMEM verdicts — the analytic footprint re-derived where the
compiler would otherwise discover it by failing.

Two entry points over the SAME models the planners use
(``ops/jacobi_pallas.wavefront_vmem_bytes`` / ``ops/stream.stream_vmem_fits``):

* :func:`check_vmem` — pre-build: a stream PLAN against a realized domain.
  ``tune/space.stream_space`` consults it to prefilter candidates before
  paying a compile-and-catch VMEM_OOM (the pruned twin still counts into
  ``tune.pruned``), and the stream ladder prefilters rungs through it on
  real backends (``resilience/ladder.py`` ``prefilter=``).
* :func:`check_traced` — post-trace: the ``vmem-budget`` contract recomputes
  the footprint from the TRACED pallas-call shapes (the planes the program
  actually streams), so a helper that resized buffers behind the planner's
  back still gets caught.

Both return ``None`` for "fits" or a human reason string — never raise on a
fit question (a malformed plan is the caller's bug and does raise).

The mxu accounting is the piece the stream planner historically did NOT
model (its ``stream_vmem_fits`` has no band-matrix term — mxu twins were
compile-and-catch until this module): the DENSE contraction form parks two
f32 band matrices per kernel resident in VMEM (``band_matrix``: (y, y) and
(z, z), tile-padded).  The ``mxu_band`` variant parks only the KB-scale
wide tiles (``band_wide_tile``) — the footprint cut that makes previously
VMEM-pruned mxu candidates admissible — and ``mxu_input="bf16"`` halves
the constants either way (``ops/jacobi_pallas.mxu_vmem_extra_bytes`` is
the shared term).
"""

from __future__ import annotations

from typing import List, Optional, Sequence


def stream_plan_vmem_bytes(
    m: int,
    plane_y: int,
    plane_z: int,
    itemsizes: Sequence[int],
    z_slabs: bool = False,
    ring_itemsizes: Optional[Sequence[int]] = None,
    mxu=False,
    fused: bool = False,
    mxu_input: str = "f32",
) -> int:
    """Modeled VMEM block bytes of a stream plan (stack margin excluded —
    compare against :func:`budget_and_margin`).  The generic-engine model
    (``stream_vmem_fits``'s accounting) plus the MXU constants term for
    the resolved variant (``mxu`` — a bool for the dense form, or the
    compute-unit string) and, under ``halo="fused"``, the double-buffered
    fused-shell side blocks: per field, one (1, y, z) x-slab plane plus
    the (1, 2m, z) y and (1, 2m, y) z message blocks per grid step."""
    from stencil_tpu.ops.jacobi_pallas import (
        _mxu_unit_of,
        _padded_plane_bytes,
        mxu_vmem_extra_bytes,
    )

    ring = list(itemsizes) if ring_itemsizes is None else list(ring_itemsizes)
    est = 0
    for it, rit in zip(itemsizes, ring):
        est += 2 * m * _padded_plane_bytes(plane_y, plane_z, rit)
        est += 4 * _padded_plane_bytes(plane_y, plane_z, it)
        if z_slabs:
            est += 4 * _padded_plane_bytes(2 * m, plane_y, it)
        if fused:
            est += 2 * _padded_plane_bytes(plane_y, plane_z, it)
            est += 2 * _padded_plane_bytes(2 * m, plane_z, it)
            est += 2 * _padded_plane_bytes(2 * m, plane_y, it)
    unit = _mxu_unit_of(mxu)
    if unit:
        est += mxu_vmem_extra_bytes(plane_y, plane_z, unit, mxu_input)
    return est


def budget_and_margin(n_fields: int, budget: Optional[int] = None):
    """(requested scoped-VMEM budget bytes, per-plan stack margin) — the
    calibrated numbers the planners gate on (``STENCIL_VMEM_LIMIT_BYTES``
    validated read unless ``budget`` overrides)."""
    from stencil_tpu.ops.jacobi_pallas import _VMEM_STACK_MARGIN, _vmem_budget

    return (budget if budget is not None else _vmem_budget(),
            _VMEM_STACK_MARGIN * max(1, n_fields))


def check_vmem(dd, plan: dict, budget: Optional[int] = None) -> Optional[str]:
    """Does this stream plan's modeled footprint fit the scoped-VMEM budget
    on this realized domain?  ``None`` = fits; otherwise a reason string
    naming the estimate and the budget.  The per-field itemsizes honor the
    storage axis (bf16 buffers stream 2 B planes but carry f32 level
    rings — the ``f32_accumulate`` contract), and an MXU ``compute_unit``
    folds the resident contraction constants of the resolved variant in
    (dense circulants vs the band variant's small tiles, narrowed under
    ``mxu_input="bf16"``)."""
    from stencil_tpu.ops.jacobi_pallas import unit_uses_mxu

    route = plan.get("route")
    if route not in ("wrap", "wavefront", "plane"):
        raise ValueError(f"not a stream plan: {plan!r}")
    m = int(plan.get("m", 1))
    raw = dd.local_spec().raw_size()
    itemsizes: List[int] = [dd.field_dtype(h).itemsize for h in dd._handles]
    ring_sizes: List[int] = [h.dtype.itemsize for h in dd._handles]
    if plan.get("grouping") == "per-field" and len(itemsizes) > 1:
        itemsizes = [max(itemsizes)]
        ring_sizes = [max(ring_sizes)]
    unit = plan.get("compute_unit", "vpu")
    est = stream_plan_vmem_bytes(
        m,
        raw.y,
        raw.z,
        itemsizes,
        z_slabs=bool(plan.get("z_slabs")),
        ring_itemsizes=ring_sizes,
        mxu=unit if unit_uses_mxu(unit) else False,
        fused=plan.get("halo") == "fused",
        mxu_input=plan.get("mxu_input", "f32"),
    )
    cap, margin = budget_and_margin(len(itemsizes), budget)
    if est + margin > cap:
        tags = "".join(
            t
            for t, on in (
                (f",{unit}", unit_uses_mxu(unit)),
                (",fused", plan.get("halo") == "fused"),
            )
            if on
        )
        return (
            f"plan {plan.get('route')}[m={m}{tags}] models "
            f"{est / 1e6:.1f} MB of VMEM blocks (+{margin / 1e6:.1f} MB "
            f"stack) against the {cap / 1e6:.1f} MB budget"
        )
    return None


def check_traced(art, budget: Optional[int] = None) -> Optional[str]:
    """The ``vmem-budget`` contract's core: re-derive the footprint from the
    TRACED program — depth from the plan, plane dims and itemsizes from the
    3-D operands of the pallas calls actually in the jaxpr — and gate it
    against the budget.  ``None`` when it fits, or when the artifact has no
    stream plan / no pallas calls to model."""
    from stencil_tpu.analysis import jaxpr as jx

    plan = art.plan
    if not plan or plan.get("route") not in ("wrap", "wavefront", "plane"):
        return None
    # one pallas call = one streaming pass over its 3-D block operands (one
    # per field in a joint pass); model the heaviest call in the program
    best: Optional[tuple] = None  # ((y, z), [itemsizes]) with max raw bytes
    for e in jx.iter_eqns(art.closed):
        if e.primitive.name != "pallas_call":
            continue
        blocks = [
            v.aval
            for v in e.invars
            if len(getattr(getattr(v, "aval", None), "shape", ())) == 3
            and min(v.aval.shape) > 1
        ]
        if not blocks:
            continue
        import jax.numpy as jnp

        big = max(blocks, key=lambda a: a.shape[-2] * a.shape[-1])
        sizes = [a.dtype.itemsize for a in blocks]
        # bf16 STORAGE blocks still carry their level ring at the f32
        # accumulator (the f32_accumulate contract) — pricing the ring at
        # the traced 2-byte itemsize is exactly the storage-only model
        # that admitted ring-blown depths before the planners were fixed
        rings = [
            4 if a.dtype == jnp.bfloat16 else a.dtype.itemsize
            for a in blocks
        ]
        weight = sum(
            a.shape[-2] * a.shape[-1] * a.dtype.itemsize for a in blocks
        )
        if best is None or weight > best[0]:
            best = (weight, tuple(big.shape[-2:]), sizes, rings)
    if best is None:
        return None
    from stencil_tpu.ops.jacobi_pallas import unit_uses_mxu

    unit = plan.get("compute_unit", "vpu")
    _, (py, pz), itemsizes, ring_itemsizes = best
    est = stream_plan_vmem_bytes(
        int(plan.get("m", 1)),
        py,
        pz,
        itemsizes,
        z_slabs=bool(plan.get("z_slabs")),
        ring_itemsizes=ring_itemsizes,
        mxu=unit if unit_uses_mxu(unit) else False,
        fused=plan.get("halo") == "fused",
        mxu_input=plan.get("mxu_input", "f32"),
    )
    cap, margin = budget_and_margin(
        len(itemsizes), budget if budget is not None else art.vmem_budget
    )
    if est + margin > cap:
        return (
            f"traced pallas planes ({py}, {pz}) at depth m="
            f"{plan.get('m', 1)} model {est / 1e6:.1f} MB of VMEM blocks "
            f"(+{margin / 1e6:.1f} MB stack) against the {cap / 1e6:.1f} MB "
            "budget"
        )
    return None
