"""The canonical program matrix — the REAL built artifacts the contracts
verify, swept over route × overlap × compute-unit × storage-dtype in
interpret/CPU mode (the tier-1 gate
``tests/test_analysis.py::test_canonical_programs_verify`` and the CLI both
run exactly this list).

Each spec builds a small realized domain on the fake 8-chip mesh (the
conftest trick), builds the step / exchange the spec names, and traces it
to a :class:`~stencil_tpu.analysis.framework.ProgramArtifact`.  Domains are
16³ (or 17³ for the padded/uneven variants — a 17-cell axis over 2 shards
forces the pad-and-mask path and, with it, the PLAIN wavefront form).

Traces are taken under ``STENCIL_HALO_BLEND=1``: the blend kernels are the
TPU-shaped lowering of the y/z halo writes (their absence on CPU would
re-introduce the very sliver writes the ``sliver-dus`` contract hunts),
exactly as the bitwise blend tests force it.

The coverage ledger (``stencil_tpu/analysis/registry.py``) mirrors which
axis values this matrix exercises; ``tests/test_analysis.py::
test_registry_matches_matrix`` pins the two against each other, and the
``contract-coverage`` lint rule fails any ops/ module growing an axis
vocabulary past the ledger.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
from typing import Iterable, List, Optional

from stencil_tpu.analysis.framework import ProgramArtifact, step_artifact, trace_artifact

#: devices the matrix needs (the conftest fake-8-chip fleet)
MATRIX_DEVICES = 8


def mean6_kernel(views, info):
    """The canonical 7-point mean — the same kernel every structural test
    streams (all shifts within radius 1, elementwise, separable)."""
    out = {}
    for name, src in views.items():
        out[name] = (
            src.sh(-1, 0, 0) + src.sh(1, 0, 0)
            + src.sh(0, -1, 0) + src.sh(0, 1, 0)
            + src.sh(0, 0, -1) + src.sh(0, 0, 1)
        ) / 6.0
    return out


def mean6_kernel_mxu(views, info):
    """The declared contraction form: in-plane taps through
    ``PlaneView.plane_nbr_sum`` (the banded-matmul lowering)."""
    out = {}
    for name, src in views.items():
        out[name] = (
            src.sh(-1, 0, 0) + src.sh(1, 0, 0) + src.plane_nbr_sum()
        ) / 6.0
    return out


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One canonical program: what to build and which axes it exercises."""

    label: str
    kind: str = "step"  # "step"|"exchange"|"redistribute"|"numerics"|"serve"
    size: tuple = (16, 16, 16)
    n_devices: int = MATRIX_DEVICES
    halo_mult: int = 1
    n_fields: int = 1
    exchange_route: str = "direct"
    stream_path: str = "auto"
    overlap: str = "off"
    halo: str = "array"
    compute_unit: str = "vpu"
    mxu_input: str = "f32"
    storage_dtype: str = "native"
    reshard_to: tuple = ()  # redistribute only: the target mesh dim
    serve_mode: str = ""  # serve only: "batched" | "subslice" (pack.SERVE_MODES)

    @property
    def axes(self) -> dict:
        return {
            "route": self.stream_path,
            "overlap": self.overlap,
            "halo": self.halo,
            "exchange_route": self.exchange_route,
            "compute_unit": self.compute_unit,
            "mxu_input": self.mxu_input,
            "storage_dtype": self.storage_dtype,
        }


#: the matrix.  Route notes: at halo-mult 2 the auto plan is the z-slab
#: wavefront; a split request re-plans to the PLAIN form, and the padded
#: 17³ variants force the plain form under overlap=off too — so both
#: wavefront forms, the plane baseline, and the single-device wrap route
#: are all traced.  The z-slab entry keeps its per-level slab permutes
#: (exchange-structure pins the generic exchange via the exchange:* entries
#: instead — see that contract's ``applies_to``).
CANONICAL_PROGRAMS: List[ProgramSpec] = [
    ProgramSpec("step:wrap/off", n_devices=1),
    ProgramSpec("step:plane/off/direct", stream_path="plane"),
    # (The former plane/split program was deduped when the mxu_band entry
    # landed: both wavefront/split programs exercise every split-schedule
    # contract clause — interior independence, exterior taint, band-blend
    # sliver hygiene — and the plane route stays covered at overlap=off by
    # two programs; no contract discriminates plane×split from
    # wavefront×split, so the build-time budget goes to the new axis.)
    ProgramSpec(
        "step:plane/off/zpack_pallas",
        stream_path="plane",
        exchange_route="zpack_pallas",
        n_fields=2,
    ),
    ProgramSpec(
        "step:wavefront/off/direct/uneven", size=(17, 17, 17), halo_mult=2
    ),
    ProgramSpec("step:wavefront/off/direct/zslab", halo_mult=2, n_fields=2),
    ProgramSpec("step:wavefront/split/direct", halo_mult=2, overlap="split"),
    ProgramSpec(
        "step:wavefront/split/zpack_xla",
        halo_mult=2,
        overlap="split",
        exchange_route="zpack_xla",
        n_fields=2,
    ),
    ProgramSpec(
        "step:wavefront/split/direct/mxu",
        halo_mult=2,
        overlap="split",
        compute_unit="mxu",
    ),
    # the band-tiled contraction variant with bf16 MXU inputs: one program
    # covers both new axis values (the accum-dtype contract verifies every
    # bf16-operand dot_general still pins the f32 accumulator, and the
    # vmem-budget contract prices the band tiles instead of the dense
    # circulants).  16³ at mult 2 shards to 12-wide raw planes — band
    # granule 3 — so the traced program really runs the blocked form.
    ProgramSpec(
        "step:wavefront/off/direct/mxu_band/bf16in",
        halo_mult=2,
        compute_unit="mxu_band",
        mxu_input="bf16",
    ),
    ProgramSpec(
        "step:wavefront/off/direct/bf16/uneven",
        size=(17, 17, 17),
        halo_mult=2,
        storage_dtype="bf16",
    ),
    ProgramSpec(
        "step:wavefront/off/yzpack_pallas/fused",
        halo_mult=2,
        exchange_route="yzpack_pallas",
        halo="fused",
    ),
    ProgramSpec(
        "step:plane/off/yzpack_xla/fused",
        stream_path="plane",
        exchange_route="yzpack_xla",
        halo="fused",
        n_fields=2,
    ),
    ProgramSpec("exchange:direct", kind="exchange", halo_mult=2, n_fields=2),
    ProgramSpec(
        "exchange:zpack_xla",
        kind="exchange",
        halo_mult=2,
        exchange_route="zpack_xla",
    ),
    ProgramSpec(
        "exchange:zpack_pallas",
        kind="exchange",
        halo_mult=2,
        exchange_route="zpack_pallas",
        n_fields=2,
    ),
    ProgramSpec(
        "exchange:yzpack_xla",
        kind="exchange",
        halo_mult=2,
        exchange_route="yzpack_xla",
        n_fields=2,
    ),
    ProgramSpec(
        "exchange:yzpack_pallas",
        kind="exchange",
        halo_mult=2,
        exchange_route="yzpack_pallas",
    ),
    # the numerics observatory's fused stats program (telemetry/numerics.py)
    # on its hardest geometry: an UNEVEN halo-multiplier multi-quantity
    # domain — pad-and-mask validity masking, mult-2 shell offsets, and two
    # quantities through one dispatch.  The numerics-bounded contract holds
    # the scalar-outputs / no-gather / psum-reduced claims on exactly the
    # program the sentinel and the snapshot cadence dispatch.
    ProgramSpec(
        "numerics:stats/uneven",
        kind="numerics",
        size=(17, 17, 17),
        halo_mult=2,
        n_fields=2,
    ),
    # the elastic-capacity collective (parallel/redistribute.py): a shrink
    # of an UNEVEN halo-multiplier domain from the full 8-chip mesh onto 4
    # chips — the redistribute-bounded contract holds its staging bound
    # and no-gather claim on the really-planned schedule (uneven shards
    # and mult-2 shells give the chunk decomposition its hardest shapes)
    ProgramSpec(
        "redistribute:2x2x2->2x2x1/uneven",
        kind="redistribute",
        size=(17, 17, 17),
        halo_mult=2,
        reshard_to=(2, 2, 1),
    ),
    # the serving layer's packed dispatches (serve/pack.py — one program
    # per SERVE_MODES value, the batch-isolation contract's corpus):
    # "batched" traces the REAL batched callable (make_batched_dispatch
    # over a full-fleet XLA-engine step, leading batch axis 4) and pins
    # that no collective ever communicates over the batch axis and every
    # output keeps its batch dim; "subslice" traces two tenants' steps on
    # DISJOINT 4-chip sub-meshes through one program and pins that no
    # tenant's outputs are reachable from another tenant's inputs and
    # every shard_map stays confined to its tenant's device set.
    ProgramSpec("serve:batched", kind="serve", serve_mode="batched"),
    ProgramSpec(
        "serve:subslice", kind="serve", serve_mode="subslice", n_devices=4
    ),
]


def covered_axis_values() -> dict:
    """{axis tuple name: set of values the matrix exercises} — derived from
    the spec list, compared against the jax-free coverage ledger by
    ``test_registry_matches_matrix``."""
    out = {
        "EXCHANGE_ROUTES": set(),
        "STREAM_OVERLAP": set(),
        "STREAM_HALO": set(),
        "COMPUTE_UNITS": set(),
        "MXU_INPUTS": set(),
        "STORAGE_DTYPES": set(),
    }
    out["SERVE_MODES"] = set()
    for s in CANONICAL_PROGRAMS:
        if s.kind == "serve":
            # a serve program's step axes are incidental (the packers ride
            # whatever steps the tenants built); only its MODE is coverage
            out["SERVE_MODES"].add(s.serve_mode)
            continue
        out["EXCHANGE_ROUTES"].add(s.exchange_route)
        out["STREAM_OVERLAP"].add(s.overlap)
        out["STREAM_HALO"].add(s.halo)
        out["COMPUTE_UNITS"].add(s.compute_unit)
        out["MXU_INPUTS"].add(s.mxu_input)
        out["STORAGE_DTYPES"].add(s.storage_dtype)
    return out


@contextlib.contextmanager
def tpu_shaped_trace():
    """Force the TPU-shaped lowering knobs for a CPU trace: blend kernels
    on (their absence is a CPU-only divergence that would hide/seed sliver
    writes the contracts pin)."""
    # stencil-lint: disable=env-read save/restore WRITES of the knob around a trace, not a config consult — the consuming read stays validated in ops/halo_blend.py
    prev = os.environ.get("STENCIL_HALO_BLEND")
    # stencil-lint: disable=env-read see above: this is the write half of the save/restore
    os.environ["STENCIL_HALO_BLEND"] = "1"
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop("STENCIL_HALO_BLEND", None)
        else:
            # stencil-lint: disable=env-read restore half of the save/restore write
            os.environ["STENCIL_HALO_BLEND"] = prev


def _build_domain(spec: ProgramSpec):
    import jax
    import jax.numpy as jnp

    from stencil_tpu.core.radius import Radius
    from stencil_tpu.domain import DistributedDomain

    devices = jax.devices()
    if len(devices) < spec.n_devices:
        raise RuntimeError(
            f"canonical matrix needs {spec.n_devices} devices, have "
            f"{len(devices)} — run under the fake-8-chip CPU config "
            "(conftest / the analysis CLI set it up)"
        )
    dd = DistributedDomain(*spec.size)
    dd.set_radius(Radius.constant(1))
    dd.set_devices(devices[: spec.n_devices])
    if spec.n_devices > 1:
        dd.set_exchange_route(spec.exchange_route)
    if spec.halo_mult > 1:
        dd.set_halo_multiplier(spec.halo_mult)
    if spec.storage_dtype != "native":
        dd.set_storage(spec.storage_dtype)
    handles = [dd.add_data(f"q{i}") for i in range(spec.n_fields)]
    dd.realize()
    for i, h in enumerate(handles):
        dd.init_by_coords(
            h, lambda x, y, z, i=i: jnp.sin(0.13 * (x + 2 * y + 3 * z) + i)
        )
    return dd


def _redistribute_artifact(spec: ProgramSpec, dd) -> ProgramArtifact:
    """Trace the really-planned redistribution schedule source mesh ->
    ``spec.reshard_to`` (the exact jitted program ``DistributedDomain.
    reshard`` dispatches), with the staging bound in ``meta``."""
    import jax

    from stencil_tpu.core.radius import Radius
    from stencil_tpu.domain import DistributedDomain
    from stencil_tpu.parallel.redistribute import (
        SideGeometry,
        plan_redistribution,
        redistribution_program,
    )

    n_target = 1
    for v in spec.reshard_to:
        n_target *= v
    tgt = DistributedDomain(*spec.size)
    tgt.set_radius(Radius.constant(1))
    tgt.set_devices(jax.devices()[:n_target])
    tgt.set_partition(*spec.reshard_to)
    if spec.halo_mult > 1:
        tgt.set_halo_multiplier(spec.halo_mult)
    tgt.realize(allocate=False)  # geometry only — the plan needs no arrays
    plan = plan_redistribution(
        tuple(spec.size),
        SideGeometry.of_domain(dd),
        SideGeometry.of_domain(tgt),
    )
    fn, example, meta = redistribution_program(plan)
    closed = jax.make_jaxpr(fn)(example)
    return ProgramArtifact(
        label=spec.label,
        kind="redistribute",
        closed=closed,
        n_devices=len(plan.union_devices),
        meta=meta,
    )


def _numerics_artifact(spec: ProgramSpec, dd) -> ProgramArtifact:
    """Trace the fused numerics stats program — exactly the jitted
    callable ``NumericsEngine.snapshot`` dispatches — with the quantity
    count in ``meta`` for the scalar-output bound."""
    import jax

    from stencil_tpu.telemetry.numerics import NumericsEngine

    fn, args, names = NumericsEngine(dd).program()
    closed = jax.make_jaxpr(fn)(*args)
    return ProgramArtifact(
        label=spec.label,
        kind="numerics",
        closed=closed,
        dd=dd,
        n_devices=spec.n_devices,
        meta={"n_quantities": len(names)},
    )


def _serve_artifact(spec: ProgramSpec, dd) -> ProgramArtifact:
    """Trace the serving layer's packed-dispatch programs (serve/pack.py)
    for the batch-isolation contract.

    ``batched`` — the REAL batched callable (``ops/stream.py
    make_batched_dispatch``) over a full-fleet XLA-engine step, batch 4;
    meta carries the batch extent and the mesh axis names so the contract
    can pin "no collective over the batch axis" and "outputs keep the
    batch dim".

    ``subslice`` — two tenants' steps on DISJOINT sub-meshes (devices
    [0:n) and [n:2n)) traced through ONE program ``(cA, cB) -> (outA,
    outB)``; meta carries the per-tenant input/output leaf counts (the
    pytree flatten order: tenant A's fields then tenant B's) and device
    sets so the contract can hold the cross-tenant taint and shard_map
    confinement claims."""
    import jax
    import jax.numpy as jnp

    from stencil_tpu.ops.stream import make_batched_dispatch
    from stencil_tpu.parallel.mesh import MESH_AXES

    if spec.serve_mode == "batched":
        step = dd.make_step(mean6_kernel, donate=False)
        batched = make_batched_dispatch(step, 1, "vmap")
        batch = 4
        stacked = {
            k: jnp.stack([v] * batch) for k, v in dd._curr.items()
        }
        closed = jax.make_jaxpr(batched)(stacked)
        return ProgramArtifact(
            label=spec.label,
            kind="serve",
            closed=closed,
            dd=dd,
            n_devices=spec.n_devices,
            meta={
                "mode": "batched",
                "batch": batch,
                "mesh_axes": tuple(MESH_AXES),
            },
        )
    from stencil_tpu.core.radius import Radius
    from stencil_tpu.domain import DistributedDomain

    devices = jax.devices()
    dd_b = DistributedDomain(*spec.size)
    dd_b.set_radius(Radius.constant(1))
    dd_b.set_devices(devices[spec.n_devices : 2 * spec.n_devices])
    handles = [dd_b.add_data(f"q{i}") for i in range(spec.n_fields)]
    dd_b.realize()
    for i, h in enumerate(handles):
        dd_b.init_by_coords(
            h, lambda x, y, z, i=i: jnp.cos(0.11 * (x + 2 * y + 3 * z) + i)
        )
    step_a = dd.make_step(mean6_kernel, donate=False)
    step_b = dd_b.make_step(mean6_kernel, donate=False)

    def both(c_a, c_b):
        return step_a(c_a, 1), step_b(c_b, 1)

    closed = jax.make_jaxpr(both)(dd._curr, dd_b._curr)
    sets = [
        sorted(d.id for d in dd.mesh.devices.flat),
        sorted(d.id for d in dd_b.mesh.devices.flat),
    ]
    return ProgramArtifact(
        label=spec.label,
        kind="serve",
        closed=closed,
        dd=dd,
        n_devices=2 * spec.n_devices,
        meta={
            "mode": "subslice",
            "input_groups": [len(dd._curr), len(dd_b._curr)],
            "output_groups": [len(dd._curr), len(dd_b._curr)],
            "device_sets": sets,
        },
    )


#: traced canonical programs memoized by label — tracing the 21-program
#: matrix costs ~tens of seconds and every per-contract consumer
#: (tests/test_analysis.py's contract tests, repeated in-process CLI
#: calls, the kernel verifier's report sweep) hits the same specs; an
#: artifact is immutable-in-practice (contracts only read it), so sharing
#: is safe.  ``reset_program_cache`` is the test-isolation hook.
_PROGRAM_MEMO: dict = {}


def reset_program_cache() -> None:
    _PROGRAM_MEMO.clear()


def build_program(spec: ProgramSpec) -> ProgramArtifact:
    """Build and trace one canonical program (interpret/CPU mode), memoized
    by label across contracts and callers (see ``_PROGRAM_MEMO``)."""
    cached = _PROGRAM_MEMO.get(spec.label)
    if cached is not None:
        return cached
    art = _build_program_uncached(spec)
    _PROGRAM_MEMO[spec.label] = art
    return art


def _build_program_uncached(spec: ProgramSpec) -> ProgramArtifact:
    with tpu_shaped_trace():
        dd = _build_domain(spec)
        if spec.kind == "serve":
            return _serve_artifact(spec, dd)
        if spec.kind == "numerics":
            return _numerics_artifact(spec, dd)
        if spec.kind == "redistribute":
            return _redistribute_artifact(spec, dd)
        if spec.kind == "exchange":
            fn = dd.make_exchange_route_fn(spec.exchange_route, donate=False)
            return trace_artifact(
                fn,
                dd._curr,
                label=spec.label,
                kind="exchange",
                axes=spec.axes,
                dd=dd,
                n_devices=spec.n_devices,
            )
        kw = dict(
            engine="stream",
            interpret=True,
            stream_path=spec.stream_path,
            stream_overlap=spec.overlap,
            stream_halo=spec.halo,
            compute_unit=spec.compute_unit,
            mxu_input=spec.mxu_input,
        )
        from stencil_tpu.ops.jacobi_pallas import unit_uses_mxu

        if unit_uses_mxu(spec.compute_unit):
            kw["mxu_kernel"] = mean6_kernel_mxu
        step = dd.make_step(mean6_kernel, **kw)
        return step_artifact(dd, step, label=spec.label, axes=spec.axes)


def build_matrix(
    labels: Optional[Iterable[str]] = None,
) -> List[ProgramArtifact]:
    """Build every canonical program (or the named subset)."""
    wanted = set(labels) if labels is not None else None
    if wanted is not None:
        known = {s.label for s in CANONICAL_PROGRAMS}
        unknown = wanted - known
        if unknown:
            raise ValueError(
                f"unknown program(s) {sorted(unknown)}; known: {sorted(known)}"
            )
    return [
        build_program(s)
        for s in CANONICAL_PROGRAMS
        if wanted is None or s.label in wanted
    ]
