"""Command-line front end: ``python -m stencil_tpu.analysis``.

Exit codes mirror the lint CLI: 0 clean, 1 findings, 2 usage error.

The default run builds and verifies the whole canonical matrix in
interpret/CPU mode — the CLI forces the fake-8-chip host platform BEFORE
jax initializes, so it works on any machine (the conftest trick, owned
here for non-pytest invocations).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional, Sequence


def _force_cpu_mesh() -> None:
    """The canonical matrix runs on the fake 8-chip CPU fleet; set the
    backend knobs before jax initializes (no-op if it already did — then
    the caller is responsible, e.g. pytest's conftest)."""
    if "jax" in sys.modules:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="stencil-analysis",
        description=(
            "Machine-check the traced-program invariants (split-step "
            "overlap independence, fused exchange structure, thin-z "
            "relayout traps, donation soundness, f32 accumulation, VMEM "
            "budgets, span-registry drift) against the canonical built-"
            "program matrix.  See docs/static-analysis.md 'Program "
            "contracts'."
        ),
    )
    p.add_argument(
        "--select",
        metavar="CONTRACT[,CONTRACT...]",
        help="run only these contracts (comma-separated ids)",
    )
    p.add_argument(
        "--program",
        action="append",
        metavar="LABEL",
        help="verify only the named canonical program(s) (repeatable; "
        "see --list-programs)",
    )
    p.add_argument(
        "--fixture",
        metavar="PATH",
        help="verify a fixture module instead of the matrix: a .py file "
        "defining build() -> ProgramArtifact (the contract-fixture corpus "
        "under tests/analysis_fixtures/)",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable output on stdout"
    )
    p.add_argument(
        "--timings",
        action="store_true",
        help="print a per-contract wall-time summary on stderr (always "
        "included in --json as contract_seconds)",
    )
    p.add_argument(
        "--list-contracts",
        action="store_true",
        help="print the contract catalog (id + rationale) and exit",
    )
    p.add_argument(
        "--list-programs",
        action="store_true",
        help="print the canonical program matrix and exit",
    )
    return p


def _load_fixture(path: str):
    import importlib.util

    name = os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(f"_analysis_fixture_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    if not hasattr(mod, "build"):
        raise ValueError(f"{path} defines no build() -> ProgramArtifact")
    return mod.build()


def main(argv: Optional[Sequence[str]] = None) -> int:
    from stencil_tpu.analysis import framework

    args = build_parser().parse_args(argv)
    if args.list_contracts:
        for cls in sorted(framework.all_contracts(), key=lambda c: c.name):
            print(f"{cls.name}: {cls.why}")
        return 0
    select = args.select.split(",") if args.select else None
    if args.list_programs:
        from stencil_tpu.analysis.programs import CANONICAL_PROGRAMS

        for s in CANONICAL_PROGRAMS:
            print(s.label)
        return 0
    try:  # validate ids BEFORE any jax work: unknown --select is usage
        framework._select(select)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    _force_cpu_mesh()
    if args.fixture:
        try:
            artifacts = [_load_fixture(args.fixture)]
        except OSError as e:
            print(
                f"cannot read {e.filename or args.fixture}: {e.strerror}",
                file=sys.stderr,
            )
            return 2
        except ValueError as e:  # no build() in the module
            print(str(e), file=sys.stderr)
            return 2
    else:
        from stencil_tpu.analysis.programs import CANONICAL_PROGRAMS, build_matrix

        if args.program:
            known = {s.label for s in CANONICAL_PROGRAMS}
            unknown = sorted(set(args.program) - known)
            if unknown:
                print(
                    f"unknown program(s) {unknown}; known: {sorted(known)}",
                    file=sys.stderr,
                )
                return 2
        # a failure INSIDE the canonical builds is a real break, not a
        # usage error — let it traceback instead of masking it as exit 2
        artifacts = build_matrix(labels=args.program)
    timings = {}
    findings = framework.check_artifacts(
        artifacts, select=select, timings=timings
    )
    if args.timings:
        framework.render_timings(timings)
    if args.json:
        print(
            framework.render_json(
                findings, programs=len(artifacts), timings=timings
            )
        )
    else:
        framework.render_human(findings)
        if not findings:
            print(
                f"stencil-analysis: {len(artifacts)} program(s) verified "
                "clean",
                file=sys.stderr,
            )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
