"""Canonical-matrix registry — the jax-free ground truth of WHAT the
program-contract verifier sweeps.

``stencil_tpu.analysis`` machine-checks the traced-program invariants
against real built artifacts (docs/static-analysis.md "Program contracts"),
and its value collapses the moment a new route ships outside the sweep: an
exchange route or overlap schedule that no canonical program exercises is
an unverified fast path.  This module records, per tuner axis, which ops/
module DEFINES the axis vocabulary and which values the canonical matrix
(``analysis/programs.py``) covers — and the ``contract-coverage`` lint rule
(``lint/rules/contract_coverage.py``) fails any ops/ module that grows the
vocabulary without growing the matrix.

Kept deliberately jax-free (plain literals, stdlib only): the lint rules
import it at check time, and the linter must run in milliseconds in any
interpreter.  The analysis package itself asserts the literals against the
real matrix (``tests/test_analysis.py::test_registry_matches_matrix``), so
this file cannot drift from the programs it describes.
"""

from __future__ import annotations

#: axis-vocabulary assignments the coverage rule watches: the NAME of the
#: module-level tuple in ops/ -> (defining module, values the canonical
#: matrix covers).  Growing the tuple in ops/ without growing the matching
#: entry here (and a canonical program for the new value) fails lint.
CANONICAL_AXES = {
    "EXCHANGE_ROUTES": {
        "module": "stencil_tpu/ops/exchange.py",
        "covered": (
            "direct",
            "zpack_xla",
            "zpack_pallas",
            "yzpack_xla",
            "yzpack_pallas",
        ),
    },
    "STREAM_OVERLAP": {
        "module": "stencil_tpu/ops/stream.py",
        "covered": ("off", "split"),
    },
    "STREAM_HALO": {
        "module": "stencil_tpu/ops/stream.py",
        "covered": ("array", "fused"),
    },
    "COMPUTE_UNITS": {
        "module": "stencil_tpu/ops/jacobi_pallas.py",
        "covered": ("vpu", "mxu", "mxu_band"),
    },
    "MXU_INPUTS": {
        "module": "stencil_tpu/ops/jacobi_pallas.py",
        "covered": ("f32", "bf16"),
    },
    "STORAGE_DTYPES": {
        "module": "stencil_tpu/ops/jacobi_pallas.py",
        "covered": ("native", "bf16"),
    },
    "SERVE_MODES": {
        "module": "stencil_tpu/serve/pack.py",
        "covered": ("batched", "subslice"),
    },
}
