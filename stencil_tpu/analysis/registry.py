"""Canonical-matrix registry — the jax-free ground truth of WHAT the
program-contract verifier sweeps.

``stencil_tpu.analysis`` machine-checks the traced-program invariants
against real built artifacts (docs/static-analysis.md "Program contracts"),
and its value collapses the moment a new route ships outside the sweep: an
exchange route or overlap schedule that no canonical program exercises is
an unverified fast path.  This module records, per tuner axis, which ops/
module DEFINES the axis vocabulary and which values the canonical matrix
(``analysis/programs.py``) covers — and the ``contract-coverage`` lint rule
(``lint/rules/contract_coverage.py``) fails any ops/ module that grows the
vocabulary without growing the matrix.

Kept deliberately jax-free (plain literals, stdlib only): the lint rules
import it at check time, and the linter must run in milliseconds in any
interpreter.  The analysis package itself asserts the literals against the
real matrix (``tests/test_analysis.py::test_registry_matches_matrix``), so
this file cannot drift from the programs it describes.
"""

from __future__ import annotations

#: axis-vocabulary assignments the coverage rule watches: the NAME of the
#: module-level tuple in ops/ -> (defining module, values the canonical
#: matrix covers).  Growing the tuple in ops/ without growing the matching
#: entry here (and a canonical program for the new value) fails lint.
CANONICAL_AXES = {
    "EXCHANGE_ROUTES": {
        "module": "stencil_tpu/ops/exchange.py",
        "covered": (
            "direct",
            "zpack_xla",
            "zpack_pallas",
            "yzpack_xla",
            "yzpack_pallas",
        ),
    },
    "STREAM_OVERLAP": {
        "module": "stencil_tpu/ops/stream.py",
        "covered": ("off", "split"),
    },
    "STREAM_HALO": {
        "module": "stencil_tpu/ops/stream.py",
        "covered": ("array", "fused"),
    },
    "COMPUTE_UNITS": {
        "module": "stencil_tpu/ops/jacobi_pallas.py",
        "covered": ("vpu", "mxu", "mxu_band"),
    },
    "MXU_INPUTS": {
        "module": "stencil_tpu/ops/jacobi_pallas.py",
        "covered": ("f32", "bf16"),
    },
    "STORAGE_DTYPES": {
        "module": "stencil_tpu/ops/jacobi_pallas.py",
        "covered": ("native", "bf16"),
    },
    "SERVE_MODES": {
        "module": "stencil_tpu/serve/pack.py",
        "covered": ("batched", "subslice"),
    },
}

#: kernel-coverage ledger — the ``contract-coverage`` pattern one level
#: down: every top-level ops/ function that issues a ``pallas_call`` must
#: be named here, per defining module, so the kernel verifier's sweep
#: (``analysis/kernels.py``; contracts ``kernel-race``/``kernel-coverage``/
#: ``tiling-legal``) has a statically-checkable inventory of the pallas
#: box it is expected to open.  The ``kernel-ledger`` lint rule
#: (``lint/rules/kernel_ledger.py``) fails any ops/ module that grows a
#: kernel without growing this ledger; the kernels themselves are reached
#: through the canonical matrix (``analysis/programs.py``) plus the
#: fixture corpus (``tests/analysis_fixtures/``).
PALLAS_KERNELS = {
    "stencil_tpu/ops/halo_blend.py": (
        "blend_slab",
        "blend_slab_dynamic",
    ),
    "stencil_tpu/ops/jacobi_pallas.py": (
        "jacobi_wrap_step",
        "jacobi_shell_wavefront_step",
        "jacobi_zring_wavefront_step",
        "jacobi_slab_step",
        "jacobi_plane_step",
    ),
    "stencil_tpu/ops/pack.py": (
        "pallas_pack_slab",
        "pallas_unpack_slab",
        "pack_zshell_pallas",
        "unpack_zshell_pallas",
        "pack_yshell_pallas",
        "unpack_yshell_pallas",
    ),
    "stencil_tpu/ops/plane_stencil.py": (
        "mean6_shell_wavefront_step",
        "mean6_plane_step",
    ),
    "stencil_tpu/ops/stream.py": (
        "stream_plane_pass",
        "stream_wavefront_pass",
        "stream_wrap_pass",
    ),
}
