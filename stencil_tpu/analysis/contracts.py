"""The program-contract catalog — every traced-program invariant this tree
machine-checks (docs/static-analysis.md "Program contracts").

Each contract generalizes a property previously guarded by a one-off test
walker or a source-level heuristic the tracer can defeat:

* ``overlap-independence`` — the split-step schedule's latency-hiding
  property IS a dataflow property (arxiv 2401.16677 makes the same point:
  overlap is what the compiler's dependency graph permits).  Replaces the
  hand-rolled taint pass ``tests/test_overlap_structural.py`` carried.
* ``exchange-structure``  — the fused ≤6-permute one-message-per-direction
  exchange (packer.cuh:52-69's collapse) must survive every route and any
  quantity count.
* ``sliver-dus``          — the thin-z relayout trap (PERF_NOTES "Thin
  z-region access") checked on the traced program, where the source rule
  (``lint/rules/layout_traps.py``) cannot see through helpers.
* ``fused-halo``          — the fused unpack→blend mode's headline claim
  (``halo="fused"``, ops/stream.py): the big array never sees a halo
  write — no partial-window update on a raw-shaped array, no blend/unpack
  kernel consuming a (big array, thin slab) pair; the shell data flows
  message → VMEM patch → pass output only.
* ``redistribute-bounded`` — the elastic-capacity collective's headline
  claim (``parallel/redistribute.py``, per arxiv 2112.01075): the traced
  redistribution program moves shard-sized staging buffers through
  permutation rounds — every intermediate inside the shard-mapped body
  stays under a constant multiple of the shard size, and no gathering
  collective (all_gather / all_to_all) appears anywhere.  A full-gather
  "redistribution" would pass every numeric test and OOM only at scale.
* ``numerics-bounded``    — the numerics observatory's headline claim
  (``telemetry/numerics.py``): the fused field-stats program reduces
  on-device (psum/pmin/pmax inside the shard_map) and ships
  O(#quantities) scalars — scalar-only outputs under the per-quantity
  budget, no gathering collective anywhere.  A per-quantity host gather
  would pass every numeric test and silently reintroduce the PR-1
  sentinel's device→host cost.
* ``donation-soundness``  — the jaxpr-level twin of the ``donated-reuse``
  lint rule: a donated/aliased buffer must be dead after the call.
* ``accum-dtype``         — every contraction in a kernel jaxpr pins an
  f32+ accumulator (the bf16-storage/f32-accumulate contract).
* ``vmem-budget``         — the analytic footprint recomputed from the
  traced shapes must fit the chip budget (``analysis/vmem.py``; the same
  verdict ``tune/space.py`` and the stream ladder consult statically).
* ``span-registry``       — every dotted named-scope label in the traced
  program is a registered span (``telemetry/names.py ALL_SPANS``): drift
  the source-level ``span-name`` rule cannot see through f-strings or
  indirection falls out of device-time attribution silently.
* ``kernel-race``         — the kernel verifier's deliberate descent
  (``analysis/kernels.py``): no two PARALLEL grid points of any pallas
  call write the same output block unless the writes are provably
  identical; sequential grids keep their last-write-wins replays.
* ``kernel-coverage``     — every output block of every pallas call is
  written by some grid point or carried in via a shape-and-dtype-
  consistent ``input_output_aliases`` entry (the donation-soundness
  analog one level down); boundary shells up to the plan's depth margin
  are the one sanctioned gap.
* ``tiling-legal``        — the Mosaic tiling-legality model over the
  traced kernels: no rotate on unaligned or non-32-bit planes, no
  blocked windows at sub-granule offsets, no int64 index arithmetic —
  the static form of PR 6's COMPILE_REJECT runtime rejections
  (``analysis/kernels.py``; ``check_kernel_legal`` is the same verdict
  pre-build for the tuner and the stream ladder).
"""

from __future__ import annotations

from typing import List

from stencil_tpu.analysis.framework import (
    Contract,
    Finding,
    ProgramArtifact,
    register,
)

#: a z-window update narrower than this is certainly a sliver — halo and
#: band writes are radius-sized (≤ ~6 cells); whole-interior write-backs
#: are hundreds of lanes wide.  Below the f32 sublane extent of the (8,128)
#: tile the DUS is guaranteed partial-tile relayout bait.
SLIVER_Z_LIMIT = 8

#: the fused-exchange bound: ≤ 2 ppermutes per axis sweep, ≤ 6 total,
#: regardless of quantity count (SURVEY.md §7 "26-neighbor exchange")
MAX_PERMUTES = 6


def _exchanging(art: ProgramArtifact) -> bool:
    return art.n_devices > 1


@register
class OverlapIndependence(Contract):
    name = "overlap-independence"
    why = (
        "under overlap=split the step.overlap.interior pallas call must be "
        "transitively ppermute-free (XLA cannot serialize what the dataflow "
        "does not order); under off no pallas call may claim an overlap scope"
    )

    def applies_to(self, art: ProgramArtifact) -> bool:
        return art.kind in ("step", "fn") and "overlap" in art.axes

    def check(self, art: ProgramArtifact) -> List[Finding]:
        from stencil_tpu.analysis import jaxpr as jx
        from stencil_tpu.telemetry import names as tm

        rows = jx.pallas_taint_rows(art.closed)
        out: List[Finding] = []
        split = art.axes.get("overlap") == "split"
        if not split:
            # the off schedule must not masquerade as split: no pallas call
            # inside an overlap scope, and (on the direct exchanging route,
            # where no pre-exchange pack kernels exist) every pass consumes
            # the exchanged blocks — the historic sanity inverse
            for ns, _ in rows:
                if tm.SPAN_OVERLAP_INTERIOR in ns or tm.SPAN_OVERLAP_EXTERIOR in ns:
                    out.append(
                        art.finding(
                            self.name,
                            f"overlap=off program carries a pallas call in an "
                            f"overlap scope: {ns!r}",
                        )
                    )
            if (
                _exchanging(art)
                and art.axes.get("exchange_route", "direct") == "direct"
                and not (art.plan or {}).get("z_slabs")
            ):
                if not rows:
                    out.append(
                        art.finding(
                            self.name,
                            "exchanging off program traced no jaxpr holding "
                            "both ppermutes and pallas calls",
                        )
                    )
                for ns, tainted in rows:
                    if not tainted:
                        out.append(
                            art.finding(
                                self.name,
                                "off-schedule pallas call does NOT consume "
                                f"the exchanged blocks (scope {ns!r}) — the "
                                "taint pass is measuring an artifact",
                            )
                        )
            return out
        if not _exchanging(art):
            return out  # nothing to overlap on one device
        if not rows:
            return [
                art.finding(
                    self.name,
                    "split program traced no jaxpr holding both ppermutes "
                    "and pallas calls — the schedule is not what it claims",
                )
            ]
        clean_interior = [
            ns for ns, t in rows if not t and tm.SPAN_OVERLAP_INTERIOR in ns
        ]
        if not clean_interior:
            out.append(
                art.finding(
                    self.name,
                    "no ppermute-free pallas call inside the "
                    f"{tm.SPAN_OVERLAP_INTERIOR!r} scope: the interior pass "
                    "depends on the exchange it is meant to hide; rows="
                    f"{[(ns, t) for ns, t in rows]}",
                )
            )
        exterior = [(ns, t) for ns, t in rows if tm.SPAN_OVERLAP_EXTERIOR in ns]
        if not exterior:
            out.append(
                art.finding(
                    self.name,
                    f"split program has no {tm.SPAN_OVERLAP_EXTERIOR!r} band "
                    "passes — nothing recomputes the boundary",
                )
            )
        for ns, t in exterior:
            if not t:
                out.append(
                    art.finding(
                        self.name,
                        f"exterior band pass at {ns!r} does not consume the "
                        "exchanged halos — the boundary fix-up reads stale "
                        "data",
                    )
                )
        if art.axes.get("exchange_route", "direct") == "direct":
            # the strong historic pin: with no pre-exchange pack kernels in
            # the program, EVERY pallas call outside the interior scope must
            # consume exchanged data
            for ns, t in rows:
                if not t and tm.SPAN_OVERLAP_INTERIOR not in ns:
                    out.append(
                        art.finding(
                            self.name,
                            f"pallas call outside the interior scope is "
                            f"ppermute-free ({ns!r}) — more of the program "
                            "than the declared interior dodges the exchange",
                        )
                    )
        return out


@register
class ExchangeStructure(Contract):
    name = "exchange-structure"
    why = (
        "every exchange route traces to <=6 ppermutes, one fused message "
        "per direction, independent of the quantity count (the reference's "
        "packed-buffer collapse, packer.cuh:52-69)"
    )

    def applies_to(self, art: ProgramArtifact) -> bool:
        if not _exchanging(art):
            return False
        if art.kind == "exchange":
            return True
        # the z-slab wavefront interleaves per-level slab permutes with the
        # pass BY DESIGN (ROADMAP "finish the packed-exchange story") — its
        # generic-exchange structure is pinned via the exchange artifacts
        return art.kind in ("step", "fn") and not (art.plan or {}).get("z_slabs")

    def check(self, art: ProgramArtifact) -> List[Finding]:
        from collections import Counter

        from stencil_tpu.analysis import jaxpr as jx

        out: List[Finding] = []
        saw_any = False
        for j in jx.walk(getattr(art.closed, "jaxpr", art.closed)):
            pps = [e for e in j.eqns if e.primitive.name == "ppermute"]
            if not pps:
                continue
            saw_any = True
            if len(pps) > MAX_PERMUTES:
                out.append(
                    art.finding(
                        self.name,
                        f"one traced exchange issues {len(pps)} ppermutes "
                        f"(> {MAX_PERMUTES}): the per-direction fusion is "
                        "broken",
                    )
                )
            scopes = Counter(jx.name_stack_str(e) for e in pps)
            for ns, n in scopes.items():
                if n > 1:
                    out.append(
                        art.finding(
                            self.name,
                            f"{n} ppermutes under one direction scope "
                            f"({ns!r}): the per-quantity messages did not "
                            "fuse into one buffer per direction",
                        )
                    )
        if art.kind == "exchange" and not saw_any:
            out.append(
                art.finding(
                    self.name,
                    "exchange program traced no ppermute at all on a "
                    "multi-device mesh",
                )
            )
        return out


@register
class SliverDus(Contract):
    name = "sliver-dus"
    why = (
        "no dynamic-update-slice on a big array with a z-extent below the "
        "(8,128) tile granule — the thin-z relayout trap, checked where the "
        "source rule cannot see through helpers (PERF_NOTES probe6)"
    )

    def applies_to(self, art: ProgramArtifact) -> bool:
        # the redistribution schedule writes staging windows whose extents
        # are whatever the mesh intersection yields — a one-shot capacity
        # transition, not a per-step hot path; its own contract
        # (redistribute-bounded) checks what actually matters there.  The
        # serve programs wrap whatever step each TENANT built (the
        # baseline XLA route included, whose shell scatter this trap is a
        # known property of) — the per-engine step programs already hold
        # this pin on the streamed hot paths, and batch-isolation checks
        # what packing itself must guarantee
        return art.kind not in ("redistribute", "serve")

    def check(self, art: ProgramArtifact) -> List[Finding]:
        from stencil_tpu.analysis import jaxpr as jx

        out: List[Finding] = []
        for e in jx.iter_eqns(art.closed):  # pallas bodies opaque: VMEM-
            # ref updates are tile-local, not big-array relayout bait
            if e.primitive.name == "dynamic_update_slice":
                operand, update = e.invars[0].aval, e.invars[1].aval
            elif e.primitive.name == "scatter":
                # ``.at[static slices].set`` lowers to scatter on some
                # toolchains — same window write, same relayout bait
                operand, update = e.invars[0].aval, e.invars[-1].aval
                if len(update.shape) != len(operand.shape):
                    continue  # gather-style updates, not a window write
            else:
                continue
            if len(operand.shape) < 3:
                continue
            if min(operand.shape[-3:]) < SLIVER_Z_LIMIT:
                # a narrow STAGING buffer (the z-slab route's (x, 2m, y)
                # slab extenders), not the big domain array — those sites
                # carry their own reasoned source-level suppressions
                continue
            oz, uz = operand.shape[-1], update.shape[-1]
            if uz < oz and uz < SLIVER_Z_LIMIT:
                out.append(
                    art.finding(
                        self.name,
                        f"{e.primitive.name} writes a {uz}-deep z window "
                        f"of a {tuple(operand.shape)} array (scope "
                        f"{jx.name_stack_str(e)!r}) — relayout bait on the "
                        "(8,128) tiling; route it through the blend kernels "
                        "(ops/halo_blend.py) or the packed exchange",
                    )
                )
        return out


@register
class FusedHalo(Contract):
    name = "fused-halo"
    why = (
        "under halo=fused the big array must never see a halo write: no "
        "partial-window DUS/scatter on a raw-shaped array and no blend/"
        "unpack kernel pairing a raw-shaped aliased block with a thin slab "
        "— the packed messages land in the pass's VMEM planes only"
    )

    def applies_to(self, art: ProgramArtifact) -> bool:
        return art.kind in ("step", "fn") and art.axes.get("halo") == "fused"

    def check(self, art: ProgramArtifact) -> List[Finding]:
        from stencil_tpu.analysis import jaxpr as jx

        raw = None
        if art.dd is not None:
            r = art.dd.local_spec().raw_size()
            raw = (r.x, r.y, r.z)

        def is_raw(aval) -> bool:
            shape = tuple(getattr(aval, "shape", ()))
            if len(shape) < 3:
                return False
            if raw is not None:
                return shape[-3:] == raw
            return True  # fixtures without a domain: any big 3-D array

        out: List[Finding] = []
        for e in jx.iter_eqns(art.closed):
            if e.primitive.name in ("dynamic_update_slice", "scatter"):
                operand = e.invars[0].aval
                update = (
                    e.invars[1].aval
                    if e.primitive.name == "dynamic_update_slice"
                    else e.invars[-1].aval
                )
                if len(getattr(update, "shape", ())) != len(
                    getattr(operand, "shape", ())
                ):
                    continue  # gather-style scatter, not a window write
                if is_raw(operand) and tuple(update.shape) != tuple(operand.shape):
                    out.append(
                        art.finding(
                            self.name,
                            f"{e.primitive.name} writes a partial window of "
                            f"a raw-shaped {tuple(operand.shape)} array "
                            f"(scope {jx.name_stack_str(e)!r}) — the fused "
                            "program must not write halo data into the big "
                            "array",
                        )
                    )
            elif e.primitive.name == "pallas_call":
                # a blend/unpack kernel: a SMALL call (block + slab [+ a
                # scalar-prefetch operand]) pairing one raw-shaped input
                # with a strictly-smaller 3-D slab.  The fused passes carry
                # the origin ref plus per-quantity raws AND three shell
                # side-buffers, so they never match this signature.
                avals = [getattr(v, "aval", None) for v in e.invars]
                three_d = [
                    a for a in avals if len(getattr(a, "shape", ())) == 3
                ]
                if len(avals) > 3 or not three_d:
                    continue
                raws_in = [a for a in three_d if is_raw(a)]
                slabs = [
                    a
                    for a in three_d
                    for b in raws_in
                    if a is not b
                    and all(x <= y for x, y in zip(a.shape, b.shape))
                    and any(x < y for x, y in zip(a.shape, b.shape))
                ]
                if raws_in and slabs:
                    out.append(
                        art.finding(
                            self.name,
                            "blend/unpack-shaped pallas call (a raw-shaped "
                            "block paired with a thin slab, scope "
                            f"{jx.name_stack_str(e)!r}) — the fused program "
                            "must land shells in the pass's VMEM planes, "
                            "never back in the big array",
                        )
                    )
        return out


#: collectives that materialize gathered state — the exact failure mode
#: the bounded redistribution schedule exists to avoid
_GATHERING_PRIMITIVES = frozenset(
    {"all_gather", "all_gather_invariant", "all_to_all"}
)


def _aval_nbytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for s in shape:
        n *= int(s)
    return n * dtype.itemsize


@register
class RedistributeBounded(Contract):
    name = "redistribute-bounded"
    why = (
        "the traced redistribution program moves bounded staging buffers "
        "through ppermute rounds: every intermediate inside the "
        "shard-mapped body stays under meta['bound_bytes'] (a constant "
        "multiple of the shard size) and no gathering collective appears — "
        "a full-gather reshard passes every numeric test and OOMs at scale "
        "(parallel/redistribute.py, arxiv 2112.01075)"
    )

    def applies_to(self, art: ProgramArtifact) -> bool:
        return art.kind == "redistribute"

    def check(self, art: ProgramArtifact) -> List[Finding]:
        from stencil_tpu.analysis import jaxpr as jx
        from stencil_tpu.parallel.redistribute import STAGING_BOUND_FACTOR

        out: List[Finding] = []
        bound = art.meta.get("bound_bytes")
        if not isinstance(bound, int) or bound <= 0:
            return [
                art.finding(
                    self.name,
                    "redistribute artifact carries no meta['bound_bytes'] — "
                    "the staging bound cannot be verified",
                )
            ]
        for e in jx.iter_eqns(art.closed):
            if e.primitive.name in _GATHERING_PRIMITIVES:
                out.append(
                    art.finding(
                        self.name,
                        f"{e.primitive.name} (scope "
                        f"{jx.name_stack_str(e)!r}) — a gathering collective "
                        "in a redistribution program materializes more than "
                        "the bounded staging schedule allows",
                    )
                )
        bodies = [
            sub
            for e in jx.iter_eqns(art.closed)
            if e.primitive.name == "shard_map"
            for sub in jx.eqn_subjaxprs(e)
        ]
        if not bodies:
            return out + [
                art.finding(
                    self.name,
                    "redistribution program traced no shard_map body — the "
                    "per-chip memory bound has nothing to hold against",
                )
            ]
        saw_permute = False
        for body in bodies:
            for j in jx.walk(body):
                for e in j.eqns:
                    if e.primitive.name == "ppermute":
                        saw_permute = True
                    for v in e.outvars:
                        nb = _aval_nbytes(getattr(v, "aval", None))
                        if nb > bound:
                            out.append(
                                art.finding(
                                    self.name,
                                    f"{e.primitive.name} (scope "
                                    f"{jx.name_stack_str(e)!r}) materializes "
                                    f"a {nb}-byte intermediate inside the "
                                    f"shard-mapped body (> the "
                                    f"{bound}-byte staging bound, "
                                    f"{STAGING_BOUND_FACTOR}x the shard) — "
                                    "the schedule is not memory-bounded",
                                )
                            )
        if art.meta.get("union_ranks", 2) > 1 and not saw_permute:
            out.append(
                art.finding(
                    self.name,
                    "multi-rank redistribution program issues no ppermute — "
                    "nothing actually moves through the collective schedule",
                )
            )
        return out


#: in-program reducing collectives — what the numerics stats program must
#: use instead of gathering (psum spells itself psum2 on current jax)
_REDUCING_PRIMITIVES = frozenset({"psum", "psum2", "pmin", "pmax"})


@register
class NumericsBounded(Contract):
    name = "numerics-bounded"
    why = (
        "the fused numerics stats program reduces on-device and ships "
        "O(#quantities) SCALARS to the host: every traced output is a "
        "0-d scalar, the output count is bounded by the per-quantity "
        "scalar budget, no gathering collective appears anywhere, and a "
        "multi-device program really reduces with psum/pmin/pmax — a "
        "per-quantity host gather would pass every numeric test and "
        "silently reintroduce the PR-1 sentinel's cost "
        "(telemetry/numerics.py, arxiv 2401.16677)"
    )

    def applies_to(self, art: ProgramArtifact) -> bool:
        return art.kind == "numerics"

    def check(self, art: ProgramArtifact) -> List[Finding]:
        from stencil_tpu.analysis import jaxpr as jx
        from stencil_tpu.telemetry.numerics import SCALARS_PER_QUANTITY

        out: List[Finding] = []
        nq = art.meta.get("n_quantities")
        if not isinstance(nq, int) or nq <= 0:
            return [
                art.finding(
                    self.name,
                    "numerics artifact carries no meta['n_quantities'] — "
                    "the scalar-output bound cannot be verified",
                )
            ]
        jaxpr = getattr(art.closed, "jaxpr", art.closed)
        outvars = list(jaxpr.outvars)
        if len(outvars) > SCALARS_PER_QUANTITY * nq:
            out.append(
                art.finding(
                    self.name,
                    f"{len(outvars)} outputs for {nq} quantities (> the "
                    f"{SCALARS_PER_QUANTITY}/quantity scalar budget) — the "
                    "host transfer is no longer O(#quantities)",
                )
            )
        for v in outvars:
            shape = tuple(getattr(getattr(v, "aval", None), "shape", ()))
            if shape != ():
                out.append(
                    art.finding(
                        self.name,
                        f"output with shape {shape} — the numerics program "
                        "must ship scalars, never arrays (a shaped output "
                        "is a gather in disguise)",
                    )
                )
        saw_reduce = False
        for e in jx.iter_eqns(art.closed):
            if e.primitive.name in _GATHERING_PRIMITIVES:
                out.append(
                    art.finding(
                        self.name,
                        f"{e.primitive.name} (scope "
                        f"{jx.name_stack_str(e)!r}) — a gathering "
                        "collective in the stats program materializes "
                        "whole fields; reduce with psum/pmin/pmax instead",
                    )
                )
            if e.primitive.name in _REDUCING_PRIMITIVES:
                saw_reduce = True
        if art.n_devices > 1 and not saw_reduce:
            out.append(
                art.finding(
                    self.name,
                    "multi-device numerics program issues no reducing "
                    "collective (psum/pmin/pmax) — per-shard stats were "
                    "never combined, so the scalars describe one shard, "
                    "not the domain",
                )
            )
        return out


#: named-axis collectives whose axis names the batch-isolation contract
#: inspects — a collective naming the BATCH axis (vmap's axis, not a mesh
#: axis) mixes tenants that share a batched dispatch
_NAMED_COLLECTIVES = frozenset(
    {
        "ppermute",
        "psum",
        "psum2",
        "pmin",
        "pmax",
        "pbroadcast",
        "all_gather",
        "all_gather_invariant",
        "all_to_all",
    }
)


def _collective_axes(eqn) -> list:
    """Every axis a collective eqn communicates over (ppermute spells them
    ``axis_name``, psum and friends ``axes``).  Mesh-axis collectives carry
    the axis NAME (a string); a collective traced through ``vmap`` carries
    the POSITIONAL batch axis as an int — both are returned, because in a
    batched serving program an int axis IS the batch axis."""
    axes = []
    for key in ("axis_name", "axes"):
        val = eqn.params.get(key)
        if val is None:
            continue
        if not isinstance(val, (tuple, list)):
            val = (val,)
        axes.extend(val)
    return axes


@register
class BatchIsolation(Contract):
    name = "batch-isolation"
    why = (
        "a packed serving dispatch must not couple tenants: in a BATCHED "
        "program no collective communicates over the batch axis (only the "
        "mesh axes) and every output keeps its leading batch dim; in a "
        "SUB-SLICE program no tenant's outputs are dataflow-reachable "
        "from another tenant's inputs and every shard_map stays confined "
        "to exactly one tenant's device set; neither form may gather — "
        "cross-tenant coupling would pass every single-tenant test and "
        "corrupt a neighbor only under production packing (serve/pack.py)"
    )

    def applies_to(self, art: ProgramArtifact) -> bool:
        return art.kind == "serve"

    def check(self, art: ProgramArtifact) -> List[Finding]:
        from stencil_tpu.analysis import jaxpr as jx

        out: List[Finding] = []
        mode = art.meta.get("mode")
        if mode not in ("batched", "subslice"):
            return [
                art.finding(
                    self.name,
                    f"serve artifact carries meta['mode']={mode!r} — the "
                    "isolation claims cannot be verified",
                )
            ]
        for e in jx.iter_eqns(art.closed):
            if e.primitive.name in _GATHERING_PRIMITIVES:
                out.append(
                    art.finding(
                        self.name,
                        f"{e.primitive.name} (scope "
                        f"{jx.name_stack_str(e)!r}) — a gathering "
                        "collective in a packed serving program "
                        "materializes state across tenants",
                    )
                )
        if mode == "batched":
            out.extend(self._check_batched(art, jx))
        else:
            out.extend(self._check_subslice(art, jx))
        return out

    def _check_batched(self, art: ProgramArtifact, jx) -> List[Finding]:
        out: List[Finding] = []
        batch = art.meta.get("batch")
        mesh_axes = set(art.meta.get("mesh_axes") or ())
        if not isinstance(batch, int) or batch < 2 or not mesh_axes:
            return [
                art.finding(
                    self.name,
                    "batched artifact needs meta['batch'] >= 2 and "
                    "meta['mesh_axes'] — the batch-axis claims cannot be "
                    "verified",
                )
            ]
        for e in jx.iter_eqns(art.closed):
            if e.primitive.name not in _NAMED_COLLECTIVES:
                continue
            stray = [
                n for n in _collective_axes(e)
                if not (isinstance(n, str) and n in mesh_axes)
            ]
            if stray:
                out.append(
                    art.finding(
                        self.name,
                        f"{e.primitive.name} communicates over non-mesh "
                        f"axis(es) {stray} (scope "
                        f"{jx.name_stack_str(e)!r}) — a collective over "
                        "the batch axis mixes tenants that share one "
                        "batched dispatch",
                    )
                )
        jaxpr = getattr(art.closed, "jaxpr", art.closed)
        for v in jaxpr.outvars:
            shape = tuple(getattr(getattr(v, "aval", None), "shape", ()))
            if not shape or shape[0] != batch:
                out.append(
                    art.finding(
                        self.name,
                        f"output with shape {shape} does not keep the "
                        f"leading batch dim {batch} — per-tenant slices "
                        "cannot be separated back out of the dispatch",
                    )
                )
        return out

    def _check_subslice(self, art: ProgramArtifact, jx) -> List[Finding]:
        out: List[Finding] = []
        in_groups = art.meta.get("input_groups")
        out_groups = art.meta.get("output_groups")
        device_sets = [
            frozenset(s) for s in (art.meta.get("device_sets") or [])
        ]
        jaxpr = getattr(art.closed, "jaxpr", art.closed)
        if (
            not in_groups
            or not out_groups
            or len(device_sets) != len(in_groups)
            or sum(in_groups) != len(jaxpr.invars)
            or sum(out_groups) != len(jaxpr.outvars)
        ):
            return [
                art.finding(
                    self.name,
                    "subslice artifact needs matching meta['input_groups']/"
                    "['output_groups']/['device_sets'] — the per-tenant "
                    "isolation claims cannot be verified",
                )
            ]
        # slice the flat invar/outvar lists back into per-tenant groups
        # (the builder records the pytree flatten order)
        in_of, out_of, i, o = [], [], 0, 0
        for n_in, n_out in zip(in_groups, out_groups):
            in_of.append(list(jaxpr.invars[i : i + n_in]))
            out_of.append(list(jaxpr.outvars[o : o + n_out]))
            i += n_in
            o += n_out
        # per-tenant forward taint at the top level: seed every OTHER
        # tenant's inputs, flow conservatively through the top-level eqns
        # (pjit boundaries — a traced sub-call mixes whatever it consumes),
        # and require this tenant's outputs stay untainted
        for t in range(len(in_groups)):
            tainted = set()
            for s, group in enumerate(in_of):
                if s != t:
                    tainted.update(id(v) for v in group)
            for e in jaxpr.eqns:
                if any(
                    id(v) in tainted
                    for v in e.invars
                    if not isinstance(v, jx.Literal)
                ):
                    tainted.update(id(v) for v in e.outvars)
            dirty = [v for v in out_of[t] if id(v) in tainted]
            if dirty:
                out.append(
                    art.finding(
                        self.name,
                        f"tenant {t}'s output(s) are dataflow-reachable "
                        f"from another tenant's inputs ({len(dirty)} of "
                        f"{len(out_of[t])} outputs tainted) — sub-slice "
                        "execution is not isolated",
                    )
                )
        # every shard_map must stay confined to exactly one tenant's
        # declared device set — an eqn spanning two sets is a collective
        # bridge between "disjoint" sub-slices
        for e in jx.iter_eqns(art.closed):
            if e.primitive.name != "shard_map":
                continue
            mesh = e.params.get("mesh")
            devs = getattr(mesh, "devices", None)
            if devs is None:
                continue
            ids = {int(d.id) for d in devs.flat}
            if not any(ids <= s for s in device_sets):
                out.append(
                    art.finding(
                        self.name,
                        f"shard_map over devices {sorted(ids)} (scope "
                        f"{jx.name_stack_str(e)!r}) is not confined to "
                        "any single tenant's declared device set "
                        f"{[sorted(s) for s in device_sets]} — its "
                        "collectives bridge sub-slices",
                    )
                )
        return out


@register
class DonationSoundness(Contract):
    name = "donation-soundness"
    why = (
        "every donated/aliased input in the traced program is dead after "
        "the consuming call or rebound — the jaxpr-level twin of the "
        "donated-reuse lint rule (SSA + anti-dependency scheduling make "
        "the remaining hazards exact per jaxpr)"
    )

    def check(self, art: ProgramArtifact) -> List[Finding]:
        from stencil_tpu.analysis import jaxpr as jx

        out: List[Finding] = []
        for j in jx.walk(getattr(art.closed, "jaxpr", art.closed)):
            for eqn, other, why in jx.donation_hazards(j):
                where = (
                    "the jaxpr outputs"
                    if other == "outvars"
                    else f"a later {other.primitive.name} eqn"
                )
                out.append(
                    art.finding(
                        self.name,
                        f"{eqn.primitive.name} (scope "
                        f"{jx.name_stack_str(eqn)!r}) vs {where}: {why}",
                    )
                )
        return out


@register
class AccumDtype(Contract):
    name = "accum-dtype"
    why = (
        "every dot_general in a kernel jaxpr carries an f32+ "
        "preferred_element_type — bf16 operands must never accumulate at "
        "bf16 (the f32-accumulate contract, docs/tuning.md)"
    )

    def check(self, art: ProgramArtifact) -> List[Finding]:
        import jax.numpy as jnp

        from stencil_tpu.analysis import jaxpr as jx

        out: List[Finding] = []
        # descend into pallas kernels: the contractions live INSIDE them
        for e in jx.iter_eqns(art.closed, opaque=()):
            if e.primitive.name != "dot_general":
                continue
            pref = e.params.get("preferred_element_type")
            ok = (
                pref is not None
                and jnp.issubdtype(pref, jnp.floating)
                and jnp.dtype(pref).itemsize >= 4
            )
            if not ok:
                out.append(
                    art.finding(
                        self.name,
                        f"dot_general (scope {jx.name_stack_str(e)!r}) "
                        f"carries preferred_element_type={pref!r} — the "
                        "accumulator must be an explicit >=32-bit float",
                    )
                )
        return out


@register
class VmemBudget(Contract):
    name = "vmem-budget"
    why = (
        "the analytic per-kernel VMEM footprint, recomputed from the traced "
        "shapes, fits the chip budget — the static form of the "
        "compile-and-catch VMEM_OOM prune (analysis/vmem.py)"
    )

    def applies_to(self, art: ProgramArtifact) -> bool:
        return art.plan is not None

    def check(self, art: ProgramArtifact) -> List[Finding]:
        from stencil_tpu.analysis import vmem

        reason = vmem.check_traced(art)
        if reason is not None:
            return [art.finding(self.name, reason)]
        return []


@register
class KernelRace(Contract):
    name = "kernel-race"
    why = (
        "no two grid points that differ in a declared-parallel grid dim "
        "may write the same output block of a pallas call unless the "
        "writes are provably identical — parallel dims leave the order "
        "unspecified, so an overlap is a silent value race on chip "
        "(sequential grids keep their deliberate last-write-wins replays; "
        "analysis/kernels.py)"
    )

    def applies_to(self, art: ProgramArtifact) -> bool:
        return art.closed is not None

    def check(self, art: ProgramArtifact) -> List[Finding]:
        from stencil_tpu.analysis import kernels

        return [
            art.finding(self.name, msg) for msg in kernels.check_races(art)
        ]


@register
class KernelCoverage(Contract):
    name = "kernel-coverage"
    why = (
        "every output block of every pallas call is written by some grid "
        "point or carried in via input_output_aliases — whose in/out "
        "shape-and-dtype consistency is checked too (the donation-"
        "soundness analog one level down); an unwritten block past the "
        "plan's shell margin ships uninitialized VMEM to HBM "
        "(analysis/kernels.py)"
    )

    def applies_to(self, art: ProgramArtifact) -> bool:
        return art.closed is not None

    def check(self, art: ProgramArtifact) -> List[Finding]:
        from stencil_tpu.analysis import kernels

        return [
            art.finding(self.name, msg)
            for msg in kernels.check_coverage(art)
        ]


@register
class TilingLegal(Contract):
    name = "tiling-legal"
    why = (
        "every traced pallas kernel survives the Mosaic tiling-legality "
        "model — no rotate on unaligned or non-32-bit planes, no blocked "
        "windows at sub-granule offsets, no int64 index arithmetic: the "
        "static form of the COMPILE_REJECT runtime failures PR 6 ate "
        "(analysis/kernels.py; the tuner and the stream ladder consult "
        "the same verdict pre-build via check_kernel_legal)"
    )

    def applies_to(self, art: ProgramArtifact) -> bool:
        return art.closed is not None

    def check(self, art: ProgramArtifact) -> List[Finding]:
        from stencil_tpu.analysis import kernels

        return [
            art.finding(self.name, msg) for msg in kernels.check_tiling(art)
        ]


@register
class SpanRegistry(Contract):
    name = "span-registry"
    why = (
        "every named-scope label in the traced program is a registered span "
        "(telemetry/names.py ALL_SPANS) — an unregistered scope silently "
        "falls out of device-time attribution.  The exchange sweeps' "
        "per-direction scopes (exchange.<axis>.<side>) are covered too: "
        "the undotted-local-marker escape hatch is gone now that every "
        "in-kernel scope comes from the registry"
    )

    def check(self, art: ProgramArtifact) -> List[Finding]:
        from stencil_tpu.analysis import jaxpr as jx
        from stencil_tpu.telemetry import names as tm

        out: List[Finding] = []
        for label in sorted(jx.scope_labels(art.closed)):
            if label not in tm.ALL_SPANS:
                out.append(
                    art.finding(
                        self.name,
                        f"named scope {label!r} is not a registered span — "
                        "add it to telemetry/names.py ALL_SPANS or rename "
                        "the scope",
                    )
                )
        return out
