"""Kernel-level static verifier — the analyzer's deliberate descent into
the pallas box.

Everything else in this package holds ``pallas_call`` conservatively OPAQUE
(``analysis/jaxpr.py``): for array-dataflow questions (taint, donation,
DUS scanning) a kernel's inner jaxpr describes VMEM-ref mutation and must
not be mistaken for array dataflow.  But the kernels are exactly where the
remaining historically-runtime failure classes live — write races between
grid points, block-map coverage gaps, and Mosaic lowering rejections — and
THOSE are decidable from the pallas call's own metadata, because BlockSpec
index maps are pure functions of the grid indices.  This module evaluates
them concretely over the (bounded) grid and turns three runtime failure
classes into static verdicts:

* **Write races** (:func:`check_races`, contract ``kernel-race``).  TPU
  grids are SEQUENTIAL by default (``dimension_semantics`` "arbitrary"):
  two grid points landing on the same output block is a deliberate
  last-write-wins replay, and every streaming kernel in ops/ relies on it
  (the wrap pass revisits ``(i - k) % X``, the wavefront clamps
  ``max(i - m, 0)``, the plane pass clamps ``clip(i - r, 0, X - 1)``).  A
  race exists only when two grid points that differ in a dim DECLARED
  ``"parallel"`` (compiler_params ``dimension_semantics``) write the same
  output block — then the execution order is unspecified.  Exemption: the
  writes are provably identical (every input footprint coincides for the
  two points and the body never reads ``program_id``), the replicated-
  write idiom.
* **Coverage** (:func:`check_coverage`, contract ``kernel-coverage``).
  Every output block must be written by some grid point, or carried in via
  ``input_output_aliases`` — whose in/out shape-and-dtype consistency is
  checked here too, the ``donation-soundness`` analog one level down.
  Unaliased wavefront outputs deliberately leave an uninitialized trailing
  shell (``max(i - m, 0)`` never reaches the last ``m`` blocks; downstream
  slicing drops them), so boundary-confined gaps up to the artifact's
  shell margin (``plan["m"]``, or ``meta["kernel_shell_margin"]``) are
  tolerated.  A second deliberate-gap idiom: lane-padded message buffers
  (``ops/pack.py lane_pad``) round their minor extent up to 128 and never
  visit the dead pad columns, so a trailing minor-dim run of uncovered
  blocks shorter than one lane tile — on an output whose minor extent is
  a 128-multiple — is tolerated too.  Any other gap fires.
* **Mosaic tiling legality** (:func:`check_tiling`, contract
  ``tiling-legal``; :func:`check_kernel_legal` is the pre-build plan
  surface).  The shape/op legality model for the lowering failures PR 6
  ate at runtime, with the pinned wordings the failure taxonomy classifies
  as COMPILE_REJECT (``resilience/taxonomy.py``):

  - Mosaic's rotate on a plane that is not natively tiled (minor %% 128,
    second-minor %% 8 for the 32-bit tiling) — "unsupported unaligned
    shape".  Static amounts have the two-slices+concatenate fallback
    (``ops/jacobi_pallas._make_roll`` picks it), TRACED amounts have no
    static form; either way a ``roll`` eqn on an unaligned plane cannot
    lower.
  - rotate on non-32-bit data — "rotate with non-32-bit data" (narrow
    floats upcast before the roll; 8-byte and narrow-int dtypes fail).
  - blocked windows at sub-granule offsets — a BlockSpec that blocks the
    second-minor dim with a MULTI-ROW block extent that is not a multiple
    of the (8, 128) f32 / (16, 128) bf16 sublane granule (or the minor
    dim off the 128 lane granule) places windows straddling tile rows at
    offsets Mosaic rejects as "invalid offsets in tiling target".
    Offsets, not extents: a narrow single-block operand (the split
    schedule's ``3w``-wide band sub-blocks) is legal, and so are
    DEGENERATE extent-1 windows — the pack kernels stream one lane
    column / sublane row per grid step (``ops/pack.py``), measured legal
    on v5e (partial-tile transfers cost bandwidth, not legality —
    PERF_NOTES "HBM ragged-edge tax").  Only a grid of multi-row windows
    whose extent is off the granule has no representable tiled layout.
  - int64 grid index arithmetic (``jax_enable_x64``) — Mosaic index
    arithmetic is 32-bit ("failed to legalize").  Config legs are scoped
    to where the config is the KERNEL's fault: the traced contract fires
    on int64 index-map avals only when ambient x64 is OFF (someone forced
    the widening; under global x64 every map is int64 by default and the
    verdict belongs to the plan surface), and the plan surface applies
    its x64 leg only when the process would actually lower via Mosaic
    (:func:`_mosaic_target` — tier-1's CPU/interpret runs deliberately
    enable x64 and must not have their tuner spaces vetoed by it).

The footprint evaluation is bounded: grids with more than
``GRID_EVAL_BOUND`` points (or index maps taking scalar-prefetch operands,
whose block choice is a runtime value) are skipped with a note rather than
evaluated — the canonical kernels' grids are tens of points, and a bound
keeps the contract wall-time flat.  Skipping is conservative-quiet, never
conservative-loud: an unevaluable map yields no verdict, not a finding.

``check_kernel_legal(dd, plan)`` mirrors ``vmem.check_vmem`` exactly: a
stream PLAN against a realized domain, ``None`` = legal, else a reason
string.  ``tune/space.stream_space`` prefilters statically-illegal
candidates with zero compile attempts, and the stream ladder descends
rungs it rejects as recorded COMPILE_REJECT descents without compiling
(``resilience/ladder.py`` tuple-returning ``prefilter=``).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

from stencil_tpu.analysis import jaxpr as jx

#: hard cap on concretely-evaluated grid points per pallas call — canonical
#: streaming grids are O(X + shell) ~ tens of points; past this bound the
#: footprint analysis records a note and abstains (see module docstring)
GRID_EVAL_BOUND = 4096

#: the 32-bit native tile; narrower dtypes double the sublane granule
#: (``ops/jacobi_pallas._padded_plane_bytes`` is the same model)
LANE_GRANULE = 128


def sublane_granule(itemsize: int) -> int:
    """Sublane rows of one native tile: 8 for f32, 16 for bf16, 32 for i8."""
    return max(8, 32 // max(1, int(itemsize)))


@dataclasses.dataclass
class BlockUse:
    """One operand/output BlockMapping, flattened for the shape legs."""

    role: str  # "in" / "out"
    index: int  # operand (or output) position within its role
    block_shape: Tuple[int, ...]
    array_shape: Tuple[int, ...]
    dtype: object
    #: concrete block-index tuples per grid point, in grid iteration order;
    #: None when the map is unevaluable (scalar-prefetch args, grid bound)
    footprint: Optional[List[Tuple[int, ...]]]
    index_map_i64: bool = False

    @property
    def nblocks(self) -> Tuple[int, ...]:
        return tuple(
            -(-a // b) for a, b in zip(self.array_shape, self.block_shape)
        )


@dataclasses.dataclass
class KernelReport:
    """Everything the three contracts need from ONE pallas call."""

    label: str
    grid: Tuple[int, ...]
    parallel_dims: Tuple[int, ...]  # grid dims declared "parallel"
    inputs: List[BlockUse]
    outputs: List[BlockUse]
    #: {output index: aliased operand's BlockUse} per input_output_aliases
    aliases: Dict[int, BlockUse]
    alias_faults: List[str]  # in/out shape-or-dtype mismatches
    scratch: List[Tuple[Tuple[int, ...], object]]  # (shape, dtype)
    #: (plane shape, itemsize, traced amount?) per in-body rotate eqn
    rolls: List[Tuple[Tuple[int, ...], int, bool]]
    reads_program_id: bool
    notes: List[str]


def _dimension_semantics(params: dict) -> Tuple[str, ...]:
    cp = params.get("compiler_params")
    if isinstance(cp, dict):  # {'mosaic': {'dimension_semantics': ...}}
        for sub in cp.values():
            if isinstance(sub, dict) and sub.get("dimension_semantics"):
                return tuple(sub["dimension_semantics"])
        return ()
    ds = getattr(cp, "dimension_semantics", None)
    return tuple(ds) if ds else ()


def _aval_of(var):
    return getattr(var, "aval", None)


def _iter_body_eqns(body):
    stack = [body]
    while stack:
        j = stack.pop()
        for e in j.eqns:
            yield e
            stack.extend(jx.eqn_subjaxprs(e))


def _eval_index_map(bm, points) -> Optional[List[Tuple[int, ...]]]:
    """Concrete per-grid-point block indices, or None when the map takes
    non-grid operands (scalar prefetch — a runtime block choice)."""
    import jax.numpy as jnp
    from jax import core as jax_core

    imj = bm.index_map_jaxpr
    if len(imj.jaxpr.invars) != len(points[0]):
        return None
    # feed grid indices at each invar's own aval dtype (int32 normally,
    # int64 when the program was traced under x64 — tier-1's default)
    dtypes = [getattr(v.aval, "dtype", jnp.int32) for v in imj.jaxpr.invars]
    out: List[Tuple[int, ...]] = []
    for pt in points:
        vals = jax_core.eval_jaxpr(
            imj.jaxpr,
            imj.consts,
            *(jnp.asarray(g, dtype=dt) for g, dt in zip(pt, dtypes)),
        )
        out.append(tuple(int(v) for v in vals))
    return out


def _block_use(role, idx, bm, points, note_sink) -> BlockUse:
    sd = bm.array_shape_dtype
    # block_shape entries are ints or the pallas ``Mapped`` sentinel (the
    # user-facing ``None``: a size-1 dim squeezed out of the kernel ref)
    block = tuple(
        int(b) if isinstance(b, (int,)) or hasattr(b, "__index__") else 1
        for b in bm.block_shape
    )
    footprint = None
    i64 = any(
        str(getattr(a, "dtype", "")) == "int64"
        for a in bm.index_map_jaxpr.out_avals
    )
    if points is not None:
        footprint = _eval_index_map(bm, points)
        if footprint is None:
            note_sink.append(
                f"{role}[{idx}] index map takes runtime operands "
                "(scalar prefetch) — footprint not evaluable"
            )
    return BlockUse(
        role, idx, block, tuple(sd.shape), sd.dtype, footprint, i64
    )


def kernel_reports(closed, grid_bound: int = GRID_EVAL_BOUND) -> List[KernelReport]:
    """One :class:`KernelReport` per pallas call anywhere in ``closed`` —
    the shared front half of all three kernel contracts."""
    cached = _REPORT_CACHE.get(id(closed))
    if cached is not None and cached[0] is closed:
        return cached[1]
    reports: List[KernelReport] = []
    for eqn in jx.iter_eqns(closed):
        if eqn.primitive.name != "pallas_call":
            continue
        params = eqn.params
        gm = params["grid_mapping"]
        grid = tuple(int(g) for g in gm.grid)
        notes: List[str] = []
        npoints = 1
        for g in grid:
            npoints *= g
        points = None
        if npoints <= grid_bound:
            points = list(itertools.product(*(range(g) for g in grid)))
        else:
            notes.append(
                f"grid {grid} exceeds the {grid_bound}-point evaluation "
                "bound — footprints not evaluated"
            )
        nidx = gm.num_index_operands
        bms = list(gm.block_mappings)
        n_in = gm.num_inputs
        inputs = [
            _block_use("in", k, bm, points, notes)
            for k, bm in enumerate(bms[:n_in])
        ]
        outputs = [
            _block_use("out", k, bm, points, notes)
            for k, bm in enumerate(bms[n_in : n_in + gm.num_outputs])
        ]
        aliases: Dict[int, BlockUse] = {}
        alias_faults: List[str] = []
        for pair in params.get("input_output_aliases") or ():
            in_op, out_i = int(pair[0]), int(pair[1])
            k = in_op - nidx  # operand index -> block-mapping index
            if not (0 <= k < len(inputs) and 0 <= out_i < len(outputs)):
                alias_faults.append(
                    f"alias {in_op}->{out_i} names a non-block operand"
                )
                continue
            src, dst = inputs[k], outputs[out_i]
            if src.array_shape != dst.array_shape or str(src.dtype) != str(
                dst.dtype
            ):
                alias_faults.append(
                    f"alias {in_op}->{out_i} carries "
                    f"{src.dtype}{list(src.array_shape)} into "
                    f"{dst.dtype}{list(dst.array_shape)} — aliased buffers "
                    "must agree in shape and dtype"
                )
            aliases[out_i] = src
        body = params["jaxpr"]
        rolls: List[Tuple[Tuple[int, ...], int, bool]] = []
        reads_pid = False
        for e in _iter_body_eqns(body):
            name = e.primitive.name
            if name == "program_id":
                reads_pid = True
            elif name in ("roll", "tpu_roll", "dynamic_rotate"):
                plane = _aval_of(e.invars[0])
                amt = e.invars[1] if len(e.invars) > 1 else None
                traced = amt is not None and not isinstance(amt, jx.Literal)
                rolls.append(
                    (
                        tuple(getattr(plane, "shape", ())),
                        int(getattr(getattr(plane, "dtype", None), "itemsize", 4)),
                        traced,
                    )
                )
        nscratch = gm.num_scratch_operands
        scratch: List[Tuple[Tuple[int, ...], object]] = []
        if nscratch:
            for v in body.invars[-nscratch:]:
                aval = _aval_of(v)
                shape = tuple(getattr(aval, "shape", ()) or ())
                scratch.append((shape, getattr(aval, "dtype", None)))
        nsi = params.get("name_and_src_info")
        label = getattr(nsi, "name", None) or eqn.primitive.name
        reports.append(
            KernelReport(
                label=str(label),
                grid=grid,
                parallel_dims=tuple(
                    d
                    for d, sem in enumerate(_dimension_semantics(params))
                    if sem == "parallel"
                ),
                inputs=inputs,
                outputs=outputs,
                aliases=aliases,
                alias_faults=alias_faults,
                scratch=scratch,
                rolls=rolls,
                reads_program_id=reads_pid,
                notes=notes,
            )
        )
    _REPORT_CACHE[id(closed)] = (closed, reports)
    return reports


#: reports memoized per traced program — the three contracts (and the
#: fixture sweep) hit the same artifact objects back to back; keying on
#: ``id(closed)`` is safe because the entry holds the jaxpr alive
_REPORT_CACHE: Dict[int, Tuple[object, List[KernelReport]]] = {}


def reset_report_cache() -> None:
    _REPORT_CACHE.clear()


# ---------------------------------------------------------------------------
# contract cores
# ---------------------------------------------------------------------------


def check_races(art) -> List[str]:
    """``kernel-race``: no two PARALLEL grid points may write the same
    output block unless the writes are provably identical."""
    out: List[str] = []
    for rep in kernel_reports(art.closed):
        if not rep.parallel_dims:
            continue  # sequential grid: revisits are last-write-wins replay
        for o in rep.outputs:
            if o.footprint is None:
                continue
            by_block: Dict[Tuple[int, ...], List[int]] = {}
            points = list(
                itertools.product(*(range(g) for g in rep.grid))
            )
            for flat, blk in enumerate(o.footprint):
                by_block.setdefault(blk, []).append(flat)
            for blk, flats in by_block.items():
                if len(flats) < 2:
                    continue
                pair = _parallel_differing_pair(
                    [points[f] for f in flats], rep.parallel_dims
                )
                if pair is None:
                    continue
                if _provably_identical(rep, flats):
                    continue
                out.append(
                    f"{rep.label}: parallel grid points {pair[0]} and "
                    f"{pair[1]} both write block {blk} of output "
                    f"{o.index} — execution order is unspecified under "
                    f"dimension_semantics parallel dims {rep.parallel_dims}"
                )
    return out


def _parallel_differing_pair(points, parallel_dims):
    for a, b in itertools.combinations(points, 2):
        if any(a[d] != b[d] for d in parallel_dims):
            return (a, b)
    return None


def _provably_identical(rep: KernelReport, flats: Sequence[int]) -> bool:
    """The replicated-write exemption: identical input footprints at every
    colliding grid point and a body that never reads ``program_id``."""
    if rep.reads_program_id:
        return False
    for i in rep.inputs:
        if i.footprint is None:
            return False
        blocks = {i.footprint[f] for f in flats}
        if len(blocks) > 1:
            return False
    return True


def _shell_margin(art) -> int:
    meta = getattr(art, "meta", None) or {}
    if "kernel_shell_margin" in meta:
        return int(meta["kernel_shell_margin"])
    plan = getattr(art, "plan", None) or {}
    return int(plan.get("m", 0) or 0)


def check_coverage(art) -> List[str]:
    """``kernel-coverage``: every output block written by some grid point,
    or carried in via a shape-and-dtype-consistent alias; deliberate
    boundary shells up to the artifact's margin tolerated."""
    margin = _shell_margin(art)
    out: List[str] = []
    for rep in kernel_reports(art.closed):
        out.extend(f"{rep.label}: {m}" for m in rep.alias_faults)
        for o in rep.outputs:
            if o.index in rep.aliases:
                continue  # carried in: every unwritten block keeps its input
            if o.footprint is None:
                continue
            covered = set(o.footprint)
            nblocks = o.nblocks
            uncovered = [
                b
                for b in itertools.product(*(range(n) for n in nblocks))
                if b not in covered
            ]
            if uncovered:
                uncovered = _drop_lane_pad(uncovered, covered, o)
            bad = [
                u
                for u in uncovered
                if not _boundary_tolerable(u, nblocks, margin)
            ]
            if bad:
                out.append(
                    f"{rep.label}: output {o.index} "
                    f"({o.dtype}{list(o.array_shape)}, blocks {list(nblocks)}) "
                    f"leaves {len(bad)} block(s) unwritten beyond the "
                    f"{margin}-block shell margin (first: {bad[0]}) and is "
                    "not carried in via input_output_aliases"
                )
    return out


def _drop_lane_pad(uncovered, covered, o: BlockUse):
    """The dead lane-padding exemption (module docstring): on an output
    whose minor extent is a 128-multiple (the ``lane_pad`` round-up
    signature), a trailing minor-dim run of uncovered blocks spanning
    fewer than 128 elements is the pad the kernel deliberately never
    visits — drop it from the gap set."""
    d = len(o.array_shape) - 1
    if d < 0 or o.array_shape[d] % LANE_GRANULE != 0:
        return uncovered
    c = max((b[d] for b in covered), default=-1) + 1
    if c >= o.nblocks[d]:
        return uncovered  # minor dim fully reached: no trailing run
    pad_elems = o.array_shape[d] - c * o.block_shape[d]
    if not 0 < pad_elems < LANE_GRANULE:
        return uncovered
    return [u for u in uncovered if u[d] < c]


def _boundary_tolerable(u, nblocks, margin) -> bool:
    if margin <= 0:
        return False
    return any(
        u[d] < margin or u[d] >= n - margin
        for d, n in enumerate(nblocks)
        if n > 1
    )


def _roll_faults(rep: KernelReport) -> List[str]:
    out: List[str] = []
    for shape, itemsize, traced in rep.rolls:
        if itemsize != 4:
            out.append(
                f"{rep.label}: in-kernel rotate on a {itemsize}-byte plane "
                f"{list(shape)} — Mosaic rejects 'rotate with non-32-bit "
                "data' (narrow floats must upcast before the roll; see "
                "ops/jacobi_pallas._make_roll)"
            )
            continue
        minor = shape[-1] if shape else 0
        second = shape[-2] if len(shape) >= 2 else 0
        if minor % LANE_GRANULE != 0 or (len(shape) >= 2 and second % 8 != 0):
            kind = "traced-amount" if traced else "static-amount"
            fix = (
                "no static-slice fallback exists for a traced amount"
                if traced
                else "use the two-slices+concatenate form "
                "(ops/jacobi_pallas._make_roll picks it automatically)"
            )
            out.append(
                f"{rep.label}: {kind} rotate on a non-natively-tiled plane "
                f"{list(shape)} (minor % 128 / second-minor % 8) — Mosaic "
                f"rejects it as 'unsupported unaligned shape'; {fix}"
            )
    return out


def _window_faults(rep: KernelReport) -> List[str]:
    out: List[str] = []
    for use in rep.inputs + rep.outputs:
        shape = use.block_shape
        if len(shape) < 2:
            continue
        nblocks = use.nblocks
        itemsize = int(getattr(use.dtype, "itemsize", 4))
        sub = sublane_granule(itemsize)
        legs = (
            (len(shape) - 1, LANE_GRANULE, "lane"),
            (len(shape) - 2, sub, "sublane"),
        )
        for d, gran, name in legs:
            # extent-1 windows are the degenerate single-row/column
            # stream (the pack idiom), measured legal on v5e; only a
            # grid of MULTI-ROW sub-granule windows straddles tile rows
            if nblocks[d] > 1 and shape[d] > 1 and shape[d] % gran != 0:
                out.append(
                    f"{rep.label}: {use.role}[{use.index}] blocks the "
                    f"{name} dim into {nblocks[d]} windows of extent "
                    f"{shape[d]} — multi-row window offsets fall off the "
                    f"({sub}, {LANE_GRANULE}) {use.dtype} tile grid "
                    "('invalid offsets in tiling target')"
                )
    return out


def _index_faults(rep: KernelReport) -> List[str]:
    import jax

    if jax.config.jax_enable_x64:
        # ambient x64 widens EVERY index map to int64 — that is the trace
        # config's doing, not any one kernel's, and the verdict for it
        # belongs to the plan surface (check_kernel_legal's x64 leg).
        # Firing here would flag the whole canonical matrix under tier-1's
        # deliberate x64 default.  Only an int64 map under 32-bit ambient
        # config is a kernel explicitly forcing the widening.
        return []
    bad = [
        f"{u.role}[{u.index}]"
        for u in rep.inputs + rep.outputs
        if u.index_map_i64
    ]
    if not bad:
        return []
    return [
        f"{rep.label}: index maps for {', '.join(bad)} produce int64 block "
        "offsets under jax_enable_x64 — Mosaic index arithmetic is 32-bit "
        "(the lowering 'failed to legalize' class)"
    ]


def check_tiling(art) -> List[str]:
    """``tiling-legal``: the traced surface of the Mosaic legality model
    (module docstring) over every pallas call in the artifact."""
    out: List[str] = []
    for rep in kernel_reports(art.closed):
        out.extend(_roll_faults(rep))
        out.extend(_window_faults(rep))
        out.extend(_index_faults(rep))
    return out


# ---------------------------------------------------------------------------
# pre-build plan surface (the check_vmem twin)
# ---------------------------------------------------------------------------


def _mosaic_target() -> bool:
    """Would a build issued NOW lower through Mosaic?  The x64 leg is a
    process-config fact and only matters where Mosaic actually runs — on
    the CPU/interpret tiers (which deliberately enable x64) it must not
    veto anything.  Tests monkeypatch this to simulate a TPU process."""
    import jax

    return jax.default_backend() == "tpu"


def check_kernel_legal(dd, plan: dict) -> Optional[str]:
    """Would this stream plan's kernels survive Mosaic lowering on this
    realized domain?  ``None`` = legal; otherwise a reason string naming
    the leg, mirroring :func:`stencil_tpu.analysis.vmem.check_vmem` (a
    malformed plan raises — that is the caller's bug, not a verdict).

    The legs are the plan-derivable slice of the traced model: int64 index
    arithmetic under x64, rotate operand width (the streaming kernels
    rotate every resident plane; narrow floats upcast inside
    ``_make_roll``, 8-byte and narrow integer dtypes cannot), and the
    blocked-window offset granule over the pass's block layout (all three
    stream passes stream single-window ``(1, Y, Z)``-family blocks today,
    so this leg guards future geometries rather than current ones).
    """
    route = plan.get("route")
    if route not in ("wrap", "wavefront", "plane"):
        raise ValueError(f"not a stream plan: {plan!r}")
    import jax

    if _mosaic_target() and jax.config.jax_enable_x64:
        return (
            f"plan {route}[m={plan.get('m', 1)}] would trace its grid and "
            "coordinate index arithmetic at int64 under jax_enable_x64 — "
            "Mosaic index arithmetic is 32-bit (failed to legalize)"
        )
    import jax.numpy as jnp

    for h in dd._handles:
        dt = dd.field_dtype(h)
        if dt.itemsize == 8:
            return (
                f"plan {route}[m={plan.get('m', 1)}] rotates resident "
                f"{dt} planes in-kernel — Mosaic rejects 'rotate with "
                "non-32-bit data' and 8-byte dtypes have no upcast path"
            )
        if dt.itemsize < 4 and not jnp.issubdtype(dt, jnp.floating):
            return (
                f"plan {route}[m={plan.get('m', 1)}] rotates resident "
                f"{dt} planes in-kernel — narrow integer dtypes have no "
                "f32 upcast path ('rotate with non-32-bit data')"
            )
    raw = dd.local_spec().raw_size()
    m = int(plan.get("m", 1))
    # the pass block layouts: (block shape, array shape) per streamed
    # operand family — one x-plane window over the raw block, plus the
    # z-slab message blocks when the plan carries them
    layouts = [((1, raw.y, raw.z), (raw.x, raw.y, raw.z))]
    if plan.get("z_slabs"):
        layouts.append(((1, 2 * m, raw.y), (raw.x, 2 * m, raw.y)))
    for h in dd._handles:
        itemsize = dd.field_dtype(h).itemsize
        sub = sublane_granule(itemsize)
        for block, array in layouts:
            for d, gran, name in (
                (len(block) - 1, LANE_GRANULE, "lane"),
                (len(block) - 2, sub, "sublane"),
            ):
                nb = -(-array[d] // block[d])
                if nb > 1 and block[d] > 1 and block[d] % gran != 0:
                    return (
                        f"plan {route}[m={m}] blocks the {name} dim into "
                        f"{nb} windows of extent {block[d]} — sub-granule "
                        "window offsets ('invalid offsets in tiling "
                        "target')"
                    )
    return None
