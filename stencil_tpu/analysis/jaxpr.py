"""Dataflow analysis over closed jaxprs — the verifier's core machinery.

The repo's hardest invariants live in the TRACED program, not the source:
split-step overlap is a property of the dependency graph the compiler sees
(no dataflow edge from any ppermute into the interior pass), the fused
exchange is a property of the permute count per direction, the thin-z
relayout trap a property of the lowered dynamic-update-slices.  Source
lint (``stencil_tpu/lint``) cannot see through helpers, f-strings, or
tracing — this module walks the jaxpr itself.

Three tools, shared by every contract (``analysis/contracts.py``):

* :func:`walk` / :func:`iter_eqns` — generic descent into the subjaxprs an
  eqn's params carry (pjit, scan, while, cond, shard_map, custom calls),
  with an ``opaque`` set of primitives NOT descended into.  ``pallas_call``
  is opaque by default: a pallas kernel's inner jaxpr describes VMEM-ref
  mutation, not array dataflow, and a contract scanning for e.g. big-array
  dynamic-update-slices must not mistake a tile-local ref update for one.
  The opacity is a TAINT-analysis stance, not ignorance: the kernel
  verifier (``analysis/kernels.py``) descends into pallas bodies
  deliberately, through the call's own metadata (grid, BlockSpec index
  maps, aliases) where the questions ARE kernel-level.
* :func:`taint_rows` — var-level forward taint/reachability inside one
  jaxpr: which eqns transitively consume a source primitive's outputs.
  Opaque eqns (pallas calls, custom calls) are treated CONSERVATIVELY:
  taint flows through them (tainted in => tainted out) and never gets
  lost inside — pinned by ``tests/test_analysis.py``'s opacity fixture.
* :func:`scope_labels` — the named-scope labels (``jax.named_scope`` /
  ``telemetry.annotate``) stamped on eqn source info, the strings XProf
  device-time attribution and the overlap proofs key on.

The ``Literal`` import shim below is THE one home for the jax-0.4.x
core-type move (``jax.extend.core`` vs ``jax.core``); the overlap test's
local copy moved here.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable, Iterator, List, Optional, Set, Tuple

try:  # jax moved core types under jax.extend over the 0.4.x line
    from jax.extend.core import Literal
except ImportError:  # pragma: no cover - older toolchains
    from jax.core import Literal

#: primitives whose inner jaxpr is NOT array dataflow and is never
#: descended into by default — the analyzer treats them as opaque nodes
#: (conservative flow-through).  ``custom_call``-style primitives carry no
#: subjaxpr at all and are opaque by construction.
OPAQUE_PRIMITIVES = frozenset({"pallas_call"})


def subjaxprs(value) -> Iterator:
    """Yield every (raw) Jaxpr found in one eqn-param value — the value may
    be a ClosedJaxpr, a Jaxpr, or a list/tuple of either (``cond`` branches,
    ``custom_jvp`` pairs)."""
    objs = value if isinstance(value, (list, tuple)) else [value]
    for o in objs:
        if hasattr(o, "jaxpr") and hasattr(o, "consts"):  # ClosedJaxpr
            yield o.jaxpr
        elif hasattr(o, "eqns") and hasattr(o, "invars"):  # Jaxpr
            yield o


def eqn_subjaxprs(eqn) -> Iterator:
    """Every subjaxpr carried by one eqn's params."""
    for v in eqn.params.values():
        yield from subjaxprs(v)


def walk(jaxpr, opaque: Iterable[str] = OPAQUE_PRIMITIVES) -> Iterator:
    """Yield ``jaxpr`` and every nested subjaxpr, depth-first, skipping the
    bodies of ``opaque`` primitives.  Pass ``opaque=()`` to descend into
    everything (the accum-dtype contract reads INSIDE pallas kernels)."""
    opaque = frozenset(opaque)
    yield jaxpr
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in opaque:
            continue
        for j in eqn_subjaxprs(eqn):
            yield from walk(j, opaque)


def iter_eqns(closed, opaque: Iterable[str] = OPAQUE_PRIMITIVES) -> Iterator:
    """Every eqn of a ClosedJaxpr (or Jaxpr) across all non-opaque nesting
    levels."""
    root = getattr(closed, "jaxpr", closed)
    for j in walk(root, opaque):
        yield from j.eqns


def primitive_counts(closed, opaque: Iterable[str] = OPAQUE_PRIMITIVES) -> dict:
    """{primitive name: eqn count} over the whole (non-opaque) program."""
    out: dict = {}
    for e in iter_eqns(closed, opaque):
        out[e.primitive.name] = out.get(e.primitive.name, 0) + 1
    return out


def name_stack_str(eqn) -> str:
    """The eqn's named-scope stack as a ``/``-joined string (empty when the
    eqn was traced outside any scope)."""
    return str(eqn.source_info.name_stack)


def scope_labels(closed, opaque: Iterable[str] = ()) -> Set[str]:
    """Every named-scope label appearing on any eqn's source info, split
    out of the ``a/b/c`` stack strings.  Transform frames (``jit(f)``,
    ``vmap(...)``) carry parentheses and are dropped — what remains is the
    labels user code pushed via ``jax.named_scope``/``telemetry.annotate``.
    Descends into opaque bodies by default: a scope entered around a pallas
    call is stamped on the call eqn itself, not its body."""
    out: Set[str] = set()
    root = getattr(closed, "jaxpr", closed)
    for j in walk(root, opaque):
        for e in j.eqns:
            ns = name_stack_str(e)
            if not ns:
                continue
            for part in ns.split("/"):
                if part and "(" not in part and "<" not in part:
                    out.add(part)
    return out


@dataclasses.dataclass(frozen=True)
class TaintRow:
    """One watched eqn inside a tainted-dataflow pass: its primitive name,
    its scope stack, and whether any of its (non-literal) inputs
    transitively depend on a source eqn's outputs."""

    primitive: str
    scopes: str
    tainted: bool
    eqn: object = dataclasses.field(repr=False, compare=False, default=None)


def taint_rows(
    jaxpr,
    source: Callable[[object], bool],
    watch: Callable[[object], bool],
    opaque: Iterable[str] = OPAQUE_PRIMITIVES,
) -> List[TaintRow]:
    """Forward var-level taint inside ONE jaxpr: an eqn for which
    ``source(eqn)`` holds taints its outputs; any eqn consuming a tainted
    var taints its own outputs (conservative flow-through — opaque eqns and
    eqns with subjaxprs included: a source anywhere INSIDE an eqn's nested
    bodies also marks the eqn as a source, so taint cannot be laundered
    through a scan/while/pjit wrapper).  Returns one row per eqn for which
    ``watch(eqn)`` holds, in program order.

    This is the generalized form of the overlap test's hand-rolled walker:
    ``source = ppermute eqns``, ``watch = pallas calls`` reproduces its
    ``(name_stack, tainted)`` rows exactly.
    """
    opaque = frozenset(opaque)
    tainted_vars: Set[int] = set()
    rows: List[TaintRow] = []

    def contains_source(eqn) -> bool:
        if source(eqn):
            return True
        if eqn.primitive.name in opaque:
            return False
        return any(
            source(e2)
            for j in eqn_subjaxprs(eqn)
            for jj in walk(j, opaque)
            for e2 in jj.eqns
        )

    for eqn in jaxpr.eqns:
        invars = [v for v in eqn.invars if not isinstance(v, Literal)]
        src_tainted = any(id(v) in tainted_vars for v in invars)
        if contains_source(eqn) or src_tainted:
            tainted_vars.update(id(v) for v in eqn.outvars)
        if watch(eqn):
            rows.append(
                TaintRow(
                    primitive=eqn.primitive.name,
                    scopes=name_stack_str(eqn),
                    tainted=src_tainted,
                    eqn=eqn,
                )
            )
    return rows


def pallas_taint_rows(closed) -> List[Tuple[str, bool]]:
    """For every jaxpr holding both ppermutes and pallas calls — the loop
    bodies where exchange and passes live — one ``(name_stack, tainted)``
    row per pallas_call, where ``tainted`` means the call's inputs
    transitively depend on some ppermute output.  The overlap-independence
    contract (and the ported ``tests/test_overlap_structural.py``) keys on
    these rows."""
    out: List[Tuple[str, bool]] = []
    root = getattr(closed, "jaxpr", closed)
    for j in walk(root):
        prims = {e.primitive.name for e in j.eqns}
        if "ppermute" not in prims or "pallas_call" not in prims:
            continue
        rows = taint_rows(
            j,
            source=lambda e: e.primitive.name == "ppermute",
            watch=lambda e: e.primitive.name == "pallas_call",
        )
        out.extend((r.scopes, r.tainted) for r in rows)
    return out


def donated_operands(eqn) -> List[Tuple[object, str]]:
    """``(var, kind)`` for the invars this eqn consumes in place: a pjit's
    ``donated_invars`` (kind ``"donated"``) and a pallas call's
    ``input_output_aliases`` (kind ``"aliased"``) — the jaxpr-level twins
    of ``donate_argnums`` and buffer aliasing.  Literals excluded."""
    out: List[Tuple[object, str]] = []
    if eqn.primitive.name == "pjit":
        donated = eqn.params.get("donated_invars") or ()
        for v, d in zip(eqn.invars, donated):
            if d and not isinstance(v, Literal):
                out.append((v, "donated"))
        return out
    aliases = eqn.params.get("input_output_aliases") or ()
    for pair in aliases:
        idx = pair[0] if isinstance(pair, (tuple, list)) else pair
        if isinstance(idx, int) and 0 <= idx < len(eqn.invars):
            v = eqn.invars[idx]
            if not isinstance(v, Literal):
                out.append((v, "aliased"))
    return out


def donation_hazards(jaxpr) -> List[Tuple[object, object, str]]:
    """``(consuming_eqn, other_use, why)`` hazards inside ONE jaxpr.

    SSA + XLA anti-dependency scheduling make a plain later READ of an
    in-place-aliased operand legal (the reader is ordered before the
    write — the split schedule's blend chain relies on exactly this), so
    that is NOT flagged.  What cannot be scheduled away:

    * a pjit-DONATED operand with any later use (or escaping as a jaxpr
      output): the donation silently cannot engage — the plan claims
      in-place, the compiler double-buffers (``other_use`` is the later
      eqn or the string ``"outvars"``);
    * TWO in-place consumers (donating or aliasing) of the same SSA value:
      double writers of one buffer;
    * an ALIASED operand escaping as a jaxpr output: the caller receives
      the pre-write value, so the alias is voided by a copy.
    """
    out: List[Tuple[object, object, str]] = []
    outvar_ids = {id(v) for v in jaxpr.outvars if not isinstance(v, Literal)}
    for i, eqn in enumerate(jaxpr.eqns):
        donated = donated_operands(eqn)
        if not donated:
            continue
        for var, kind in donated:
            for later in jaxpr.eqns[i + 1 :]:
                later_inplace = {
                    id(v) for v, _ in donated_operands(later)
                }
                if id(var) in later_inplace:
                    out.append(
                        (eqn, later, "a second in-place consumer writes the "
                         "same buffer")
                    )
                elif kind == "donated" and any(
                    id(v) == id(var)
                    for v in later.invars
                    if not isinstance(v, Literal)
                ):
                    out.append(
                        (eqn, later, "a donated buffer is read after the "
                         "donating call — the donation cannot engage")
                    )
            if id(var) in outvar_ids:
                why = (
                    "a donated buffer escapes as a jaxpr output"
                    if kind == "donated"
                    else "an aliased operand escapes as a jaxpr output — "
                    "the alias is voided by a copy"
                )
                out.append((eqn, "outvars", why))
    return out


def lowered_text(fn, *args, static_argnums=None, **kwargs) -> str:
    """The lowered StableHLO text of ``fn(*args)`` — the HLO-level probe for
    contracts that need to see past the jaxpr (collective-permute counts
    after SPMD partitioning, fusion shapes).  CPU/interpret-safe: lowering
    stops before backend compilation."""
    import jax

    jit_kw = {}
    if static_argnums is not None:
        jit_kw["static_argnums"] = static_argnums
    return jax.jit(fn, **jit_kw).lower(*args, **kwargs).as_text()
