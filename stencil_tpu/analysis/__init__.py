"""stencil-analysis — the program-contract verifier.

Where ``stencil_tpu.lint`` machine-checks SOURCE invariants over the stdlib
AST, this package machine-checks the TRACED-PROGRAM invariants over closed
jaxprs (and lowered HLO text): var-level taint/reachability, eqn visitors
that descend into pjit/scan/while subjaxprs (pallas calls and custom calls
stay opaque to the TAINT analysis, conservatively — the kernel verifier
``analysis/kernels.py`` descends into pallas bodies deliberately), and a
registry of program contracts checked
against REAL built artifacts — the canonical route × overlap ×
compute-unit × storage-dtype matrix (``analysis/programs.py``).

Entry points:

* ``python -m stencil_tpu.analysis``      — verify the canonical matrix
  (exit 0 clean / 1 findings / 2 usage; ``--select``, ``--json``,
  ``--list-contracts``, ``--program``, ``--fixture`` — mirroring the lint
  CLI).
* :func:`check` / :func:`check_artifacts` — in-process verification, the
  tier-1 gate's path (``tests/test_analysis.py``).
* :func:`check_vmem` — the static VMEM verdict ``tune/space.py`` and the
  stream ladder consult to prune candidates before a compile-and-catch
  VMEM_OOM.
* :func:`check_kernel_legal` — the static Mosaic tiling-legality verdict
  (``analysis/kernels.py``), wired beside ``check_vmem``: the tuner prunes
  statically-illegal candidates with zero compile attempts and the ladder
  records them as COMPILE_REJECT descents without compiling.

This module stays import-light (no jax at import time): the lint rules
read the coverage ledger (``analysis/registry.py``) through it, and
``--list-contracts`` must answer in milliseconds.
"""

from stencil_tpu.analysis.framework import (  # noqa: F401
    Contract,
    Finding,
    ProgramArtifact,
    all_contracts,
    check,
    check_artifacts,
    register,
    step_artifact,
    trace_artifact,
)


def check_vmem(dd, plan, budget=None):
    """Static scoped-VMEM verdict for a stream plan on a realized domain —
    ``None`` fits, else the reason (``analysis/vmem.py``)."""
    from stencil_tpu.analysis import vmem as _vmem

    return _vmem.check_vmem(dd, plan, budget=budget)


def check_kernel_legal(dd, plan):
    """Static Mosaic tiling-legality verdict for a stream plan on a realized
    domain — ``None`` legal, else the reason (``analysis/kernels.py``)."""
    from stencil_tpu.analysis import kernels as _kernels

    return _kernels.check_kernel_legal(dd, plan)
