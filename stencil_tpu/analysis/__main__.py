"""``python -m stencil_tpu.analysis`` — see ``analysis/cli.py``."""

import sys

from stencil_tpu.analysis.cli import main

sys.exit(main())
