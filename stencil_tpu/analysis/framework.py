"""Program-contract framework: artifacts, contracts, registry, engine.

The analog of ``lint/framework.py`` one level down the stack: where a lint
``Rule`` checks SOURCE, a :class:`Contract` checks a TRACED PROGRAM — a
:class:`ProgramArtifact` wrapping the closed jaxpr of a really-built step
(or exchange, or any jitted callable) plus the build-time facts a contract
needs (the stream plan, the domain handle, the axis values the program
claims to exercise).

Contracts are data, like lint rules: id, rationale, an ``applies_to``
predicate over the artifact, a ``check`` returning findings.  The registry
is populated by ``@register`` at ``analysis/contracts.py`` import time; the
CLI (``python -m stencil_tpu.analysis``) and the tier-1 gate
(``tests/test_analysis.py``) both run every registered contract over the
canonical program matrix (``analysis/programs.py``).

Kept import-light: jax is only touched when an artifact is actually traced
(``trace_artifact``), so ``--list-contracts`` and the lint rules' registry
reads stay milliseconds.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class Finding:
    """One contract violation: contract id, program label, message."""

    contract: str
    program: str
    message: str

    def render(self) -> str:
        return f"{self.program}: [{self.contract}] {self.message}"

    def as_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ProgramArtifact:
    """One traced program under verification.

    ``label``  — stable display id (``step:wavefront/split/direct/...``).
    ``kind``   — ``"step"`` (a built stream/domain step), ``"exchange"``
                 (a bare exchange fn), or ``"fn"`` (anything else — the
                 fixture corpus's synthetic programs).
    ``closed`` — the ClosedJaxpr of the program.
    ``axes``   — the axis values this program claims to exercise
                 (``route``/``overlap``/``exchange_route``/``compute_unit``/
                 ``storage_dtype``); contracts scope their pins on these.
    ``plan``   — the stream plan dict (steps only; None otherwise).
    ``dd``     — the realized domain (when available: vmem re-derivation).
    ``n_devices`` — mesh size the program was built for (1 = no exchange).
    ``vmem_budget`` — budget override in bytes for the vmem contract
                 (fixtures pin tiny budgets without touching the env).
    ``meta``   — free-form build facts for kind-specific contracts (the
                 redistribution programs carry their staging bound here).
    """

    label: str
    kind: str
    closed: object
    axes: dict = dataclasses.field(default_factory=dict)
    plan: Optional[dict] = None
    dd: object = None
    n_devices: int = 1
    vmem_budget: Optional[int] = None
    meta: dict = dataclasses.field(default_factory=dict)

    def finding(self, contract: str, message: str) -> Finding:
        return Finding(contract=contract, program=self.label, message=message)


def trace_artifact(
    fn: Callable,
    *args,
    label: str,
    kind: str = "fn",
    static_argnums=None,
    **meta,
) -> ProgramArtifact:
    """Trace ``fn(*args)`` to a closed jaxpr and wrap it as an artifact.
    ``meta`` passes through to the artifact fields (``axes=``, ``plan=``,
    ``dd=``, ``n_devices=``, ``vmem_budget=``)."""
    import jax

    kw = {}
    if static_argnums is not None:
        kw["static_argnums"] = static_argnums
    closed = jax.make_jaxpr(fn, **kw)(*args)
    return ProgramArtifact(label=label, kind=kind, closed=closed, **meta)


def step_artifact(dd, step, label: str, axes: dict,
                  vmem_budget: Optional[int] = None) -> ProgramArtifact:
    """Artifact for a ladder-wrapped domain step (``make_step``'s return):
    traces the CURRENT rung's built impl over the domain's live buffers —
    the same program the dispatcher runs."""
    ladder = getattr(step, "_resilience", None)
    fn = ladder.built() if ladder is not None else step
    plan = getattr(step, "_stream_plan", None)
    art = trace_artifact(
        fn,
        dd._curr,
        1,
        static_argnums=1,
        label=label,
        kind="step",
        axes=dict(axes),
        plan=dict(plan) if plan else None,
        dd=dd,
        n_devices=dd.num_subdomains(),
        vmem_budget=vmem_budget,
    )
    return art


class Contract:
    """Base class: subclass, set ``name``/``why``, implement ``check``.

    ``name`` is the id used in output and ``--select``; ``why`` the
    one-line rationale (``--list-contracts``, the docs catalog).
    ``applies_to(art)`` scopes the contract to the artifacts whose claims
    it can actually pin — the engine only calls ``check`` on those."""

    name: str = ""
    why: str = ""

    def applies_to(self, art: ProgramArtifact) -> bool:
        return True

    def check(self, art: ProgramArtifact) -> List[Finding]:
        raise NotImplementedError


#: the global registry, populated by ``@register`` at
#: ``analysis/contracts.py`` import time
_REGISTRY: List[type] = []


def register(cls: type) -> type:
    assert cls.name, f"{cls.__name__} must set a contract name"
    assert all(cls.name != c.name for c in _REGISTRY), f"duplicate {cls.name}"
    _REGISTRY.append(cls)
    return cls


def all_contracts() -> List[type]:
    """Registered contract classes (importing the contracts module on
    demand, the lint ``all_rules`` pattern)."""
    from stencil_tpu.analysis import contracts as _contracts  # noqa: F401

    return list(_REGISTRY)


def _select(select: Optional[Iterable[str]]) -> List[Contract]:
    classes = all_contracts()
    if select is not None:
        wanted = set(select)
        known = {c.name for c in classes}
        unknown = wanted - known
        if unknown:
            raise ValueError(
                f"unknown contract(s) {sorted(unknown)}; known: {sorted(known)}"
            )
        classes = [c for c in classes if c.name in wanted]
    return [c() for c in classes]


def check(
    artifact: ProgramArtifact,
    contract: Optional[str] = None,
    select: Optional[Iterable[str]] = None,
    timings: Optional[Dict[str, float]] = None,
) -> List[Finding]:
    """Run contracts over ONE artifact.  ``contract=`` selects a single id
    (the ported structural tests' entry point); ``select=`` a list; both
    None runs every registered contract that applies.  ``timings=`` is an
    out-param dict accumulating per-contract wall seconds."""
    if contract is not None:
        select = [contract]
    out: List[Finding] = []
    for c in _select(select):
        if not c.applies_to(artifact):
            continue
        t0 = time.perf_counter()
        out.extend(c.check(artifact))
        if timings is not None:
            timings[c.name] = (
                timings.get(c.name, 0.0) + time.perf_counter() - t0
            )
    return sorted(out, key=lambda f: (f.program, f.contract, f.message))


def check_artifacts(
    artifacts: Sequence[ProgramArtifact],
    select: Optional[Iterable[str]] = None,
    timings: Optional[Dict[str, float]] = None,
) -> List[Finding]:
    """Run contracts over a whole artifact set (the canonical matrix).
    ``timings=`` accumulates wall seconds per contract id across the set —
    the CLI's ``--timings`` summary and ``--json`` ``contract_seconds``."""
    out: List[Finding] = []
    for art in artifacts:
        out.extend(check(art, select=select, timings=timings))
    return out


def applied_contracts(artifacts: Sequence[ProgramArtifact]) -> List[str]:
    """The contract ids whose ``applies_to`` held for at least one of these
    artifacts — what a clean ``check_artifacts`` run actually verified
    (callers recording a 'verified' claim must not list contracts that
    never ran; weak.py's ``--verify`` artifact field)."""
    out = set()
    for c in _select(None):
        if any(c.applies_to(a) for a in artifacts):
            out.add(c.name)
    return sorted(out)


def render_json(
    findings: List[Finding],
    programs: int,
    timings: Optional[Dict[str, float]] = None,
) -> str:
    return json.dumps(
        {
            "findings": [f.as_json() for f in findings],
            "count": len(findings),
            "programs_checked": programs,
            "contracts": sorted(c.name for c in all_contracts()),
            "contract_seconds": {
                k: round(v, 4) for k, v in sorted((timings or {}).items())
            },
        },
        indent=2,
        sort_keys=True,
    )


def render_human(findings: List[Finding], stream=None) -> None:
    import sys

    stream = stream or sys.stderr
    for f in findings:
        print(f.render(), file=stream)
    if findings:
        print(f"{len(findings)} program-contract finding(s)", file=stream)


def render_timings(timings: Dict[str, float], stream=None) -> None:
    """Per-contract wall-time summary, slowest first (``--timings``; the
    one-shot gate surfaces this on failure so a matrix-growth slowdown is
    attributable to a contract, not a mystery)."""
    import sys

    stream = stream or sys.stderr
    for name, secs in sorted(timings.items(), key=lambda kv: -kv[1]):
        print(f"{secs:8.3f}s  {name}", file=stream)
