"""Rank-tagged structured JSONL event sink.

One line per event, appended to ``<dir>/events_<rank>.jsonl``:

    {"ts": <epoch seconds>, "event": "<names.EVENT_*>", "rank": <int>,
     ...event-specific fields...}

The rank tag uses the same fail-closed probe as ``utils/logging._rank``: jax
is consulted ONLY when a backend is verifiably already initialized, so
emitting an event can never trigger a backend bring-up (on a remote-TPU
container that is a tunnel probe that can hang for minutes).  Before
initialization events tag rank 0 — and the whole sink path is resolved
lazily at first emit, after which the rank is stable for the file's
lifetime.

Writes are line-buffered appends; every line is one complete JSON document,
so a crashed run leaves a readable (if truncated) log.  Non-JSON field
values degrade to ``str()`` rather than failing the run.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from stencil_tpu.utils.logging import _rank


class EventSink:
    def __init__(self, out_dir: str):
        self._dir = out_dir
        self._f = None
        self._path: Optional[str] = None

    def path(self) -> str:
        if self._path is None:
            self._path = os.path.join(self._dir, f"events_{_rank()}.jsonl")
        return self._path

    def emit(self, event: str, fields: dict) -> None:
        if self._f is None:
            os.makedirs(self._dir, exist_ok=True)
            self._f = open(self.path(), "a", buffering=1)
        rec = {"ts": time.time(), "event": event, "rank": _rank()}
        rec.update(fields)
        self._f.write(json.dumps(rec, default=str) + "\n")

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
