"""Process-local metrics registry: counters, gauges, histograms.

Histograms are backed by ``utils/statistics.Statistics`` — the reference's
benchmark aggregate (bin/statistics.hpp) — so every timing series reports
the same min/max/avg/stddev/med/**trimean** the reference's CSVs headline,
and a BENCH-JSON telemetry section is directly comparable to the
reference's per-benchmark Statistics rows.

Counters and gauges are plain in-process numbers (one dict lookup + an add
under the GIL); they carry no formatting or I/O, so they stay recorded even
when telemetry output is disabled — a post-hoc ``snapshot()`` after a failed
run still shows how many retries/descents happened.  Snapshot values are
JSON-safe: NaN statistics (empty histogram, single-sample stddev) become
``None``, never the non-strict-JSON ``NaN`` token.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, Optional

from stencil_tpu.utils.statistics import Statistics


def _json_safe(x: float) -> Optional[float]:
    return None if isinstance(x, float) and math.isnan(x) else x


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Last-value-wins numeric gauge."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Value distribution with the reference's Statistics aggregates."""

    __slots__ = ("name", "stats")

    def __init__(self, name: str):
        self.name = name
        self.stats = Statistics()

    def observe(self, v: float) -> None:
        self.stats.insert(v)

    def snapshot(self) -> Dict[str, Optional[float]]:
        s = self.stats
        return {
            "count": s.count(),
            "min": _json_safe(s.min()),
            "max": _json_safe(s.max()),
            "avg": _json_safe(s.avg()),
            "stddev": _json_safe(s.stddev()),
            "med": _json_safe(s.med()),
            "trimean": _json_safe(s.trimean()),
            # the tail view the trimean discards: cross-round diffs of a
            # timing series need p95/p99 to see a regression that only
            # shows up as jitter (p50 rides along as the self-check twin
            # of med)
            "p50": _json_safe(s.quantile(0.50)),
            "p95": _json_safe(s.quantile(0.95)),
            "p99": _json_safe(s.quantile(0.99)),
        }


class MetricsRegistry:
    """Get-or-create registry of the three metric kinds.

    A name owns ONE kind: registering it as a second kind raises (the same
    name reported as both a counter and a histogram would silently fork the
    series across rounds).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def _get(self, table, name: str, factory):
        m = table.get(name)
        if m is not None:
            return m
        with self._lock:
            m = table.get(name)
            if m is None:
                for other in (self._counters, self._gauges, self._histograms):
                    if other is not table and name in other:
                        raise ValueError(
                            f"telemetry name {name!r} already registered as a "
                            "different metric kind"
                        )
                m = table[name] = factory(name)
            return m

    def counter(self, name: str) -> Counter:
        return self._get(self._counters, name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(self._gauges, name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(self._histograms, name, Histogram)

    def snapshot(
        self,
        seed_counters: Iterable[str] = (),
        seed_histograms: Iterable[str] = (),
    ) -> dict:
        """Plain-dict snapshot.  ``seed_counters`` names appear with value 0
        even when never incremented, and ``seed_histograms`` names appear as
        empty distributions (count 0, None stats), so the snapshot schema is
        stable across rounds (a diff shows '0 -> 3 retries', not a key
        appearing — and a cross-round diff of ``fabric.link.gbps`` never
        KeyErrors on a registry that hasn't probed yet)."""
        counters = {name: 0 for name in sorted(seed_counters)}
        counters.update({c.name: c.value for c in self._counters.values()})
        histograms = {
            name: Histogram(name).snapshot() for name in sorted(seed_histograms)
        }
        histograms.update(
            {h.name: h.snapshot() for h in sorted_values(self._histograms)}
        )
        return {
            "counters": dict(sorted(counters.items())),
            "gauges": {g.name: g.value for g in sorted_values(self._gauges)},
            "histograms": dict(sorted(histograms.items())),
        }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


def sorted_values(table: dict):
    return (table[k] for k in sorted(table))
