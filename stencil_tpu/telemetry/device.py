"""Device-time attribution: parse ``jax.profiler`` trace dumps offline and
attribute DEVICE time to the named scopes this tree already emits into HLO
metadata.

The host span tracer (``spans.py``) sees wall-clock only — a dispatch that
returns at enqueue looks free, and the split-step overlap win/loss, the
exchange's real cost, and the MXU contraction's share of a step are only
knowable from the device timeline (T3, arxiv 2401.16677: overlap efficiency
comes from fine-grained attribution of compute vs collectives).  This module
closes that gap without any online dependency on the profiler:

* **Capture** (``ProfileCapture``): wrap dispatches with a cadence-gated
  ``jax.profiler`` trace (``STENCIL_PROFILE_EVERY`` / ``--profile-dir``).
  Degrades gracefully — a backend with no profiler (CPU dryrun containers)
  warns once and runs unprofiled; the capture path never crashes a run.
* **Parse** (``find_trace_files`` / ``load_trace_events``): the profiler
  dumps Chrome trace-event JSON (``*.trace.json[.gz]`` under
  ``plugins/profile/<run>/``); we read it back offline — plain stdlib, no
  jax, no TensorBoard.
* **Attribute** (``attribute_device_time``): sum device-row durations per
  named scope (``step.overlap.interior``/``.exterior`` — names.py, entered
  via ``telemetry.annotate`` — plus the exchange/pack kernel families),
  matching scopes as substrings of the event name and its args (XLA carries
  the ``jax.named_scope`` path in op metadata, so scope names survive into
  the trace rows).
* **Merge** (``merge_device_rows`` / ``merge_into_chrome_trace``): append
  the device rows to the host Chrome trace so Perfetto shows host spans and
  device kernels on ONE timeline.  Device clocks are not host clocks;
  alignment shifts the device rows so the capture window starts at the
  host-trace timestamp that opened it (best-effort, recorded in the row
  args as ``device_ts_us``).

Everything here except ``ProfileCapture.__enter__`` is jax-free (the
``jax-import`` lint rule covers this package): parsing a trace from a dead
run must not need a live backend.
"""

from __future__ import annotations

import gzip
import json
import os
import re
import time
from typing import Dict, Iterable, List, Optional, Tuple

from stencil_tpu.telemetry import names

#: the named-scope/kernel families device time is attributed to.  The two
#: ``step.overlap.*`` entries are the annotate() scopes the split schedule
#: enters (names.py); ``exchange``/``pack`` match the collective and pack
#: kernel families by their stable substrings; ``mxu`` matches the banded
#: contraction's dot/matmul kernels.  Matching is case-insensitive
#: substring over the event name and its args values.
PHASE_PATTERNS: Dict[str, Tuple[str, ...]] = {
    names.SPAN_OVERLAP_INTERIOR: (names.SPAN_OVERLAP_INTERIOR,),
    names.SPAN_OVERLAP_EXTERIOR: (names.SPAN_OVERLAP_EXTERIOR,),
    # device rows match the collective/pack kernel families; the
    # ``domain.*`` entries additionally catch our HOST span names so the
    # host-span fallback (scripts/perf_report.py on a CPU container)
    # attributes the same phases
    "exchange": (
        "halo_ppermute",
        "ppermute",
        "collective-permute",
        "collective_permute",
        "all-to-all",
        names.SPAN_EXCHANGE,
    ),
    "pack": ("zpack", "halo_pack", "shell_pack", "unpack"),
    "mxu": ("band_matrix", "dot_general", "matmul", "convolution"),
    "step": (names.SPAN_STEP,),
}

#: one phase per registered exchange direction scope (``exchange.x.low``
#: ...) — the per-hop VIEW of the exchange family for the comms roofline;
#: the kernel sweeps enter these scopes around every ppermute
#: (ops/exchange.py ``_shift_from_low``/``_shift_from_high``)
EXCHANGE_DIRECTION_PHASES: Dict[str, Tuple[str, ...]] = {
    span: (span,) for span in sorted(names.EXCHANGE_DIRECTION_SPANS.values())
}

#: process-name patterns that mark a trace pid as a DEVICE row source
_DEVICE_PROCESS_RE = re.compile(
    r"/device:|TPU|GPU|XLA|Device|Chip", re.IGNORECASE
)

#: pid offset applied to device processes when merging into the host trace
#: (host spans use pid = rank, a small integer — device rows must not
#: collide)
DEVICE_PID_BASE = 1000

#: the analytic counters a capture snapshots at its window boundaries, so
#: the roofline join divides CAPTURE-WINDOW work by capture-window device
#: time — joining whole-run cumulative counters with one window's device
#: seconds would overstate achieved rates by (total / captured) dispatches
CAPTURE_COUNTERS = (
    names.EXCHANGE_BYTES,
    names.EXCHANGE_PACKED_BYTES,
    names.KERNEL_MXU_FLOPS,
) + tuple(sorted(names.EXCHANGE_HOP_BYTES.values()))


# --- locating and loading trace dumps ----------------------------------------


def find_trace_files(profile_dir: str) -> List[str]:
    """Every ``*.trace.json``/``*.trace.json.gz`` under ``profile_dir``
    (the profiler nests them in ``plugins/profile/<run>/``), newest first
    by mtime — callers usually want the latest capture."""
    out = []
    for dirpath, _dirnames, files in os.walk(profile_dir):
        for f in files:
            if f.endswith((".trace.json", ".trace.json.gz")):
                out.append(os.path.join(dirpath, f))
    return sorted(out, key=lambda p: (os.path.getmtime(p), p), reverse=True)


def load_trace_events(path: str) -> List[dict]:
    """The trace-event list from one dump — accepts both the wrapped
    ``{"traceEvents": [...]}`` object and a bare event array, gzipped or
    plain.  A truncated/corrupt dump (the process died mid-write) returns
    [] rather than raising: post-mortem tooling runs on exactly those."""
    opener = gzip.open if path.endswith(".gz") else open
    try:
        with opener(path, "rt", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return []
    events = doc.get("traceEvents", []) if isinstance(doc, dict) else doc
    return [e for e in events if isinstance(e, dict)]


def device_pids(events: Iterable[dict]) -> Dict[int, str]:
    """pid -> process name for every process whose metadata marks it as a
    device timeline (``process_name`` metadata rows matching
    /device:|TPU|GPU|XLA/)."""
    out: Dict[int, str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pname = str((e.get("args") or {}).get("name", ""))
            if _DEVICE_PROCESS_RE.search(pname):
                out[e.get("pid", 0)] = pname
    return out


def _event_text(e: dict) -> str:
    """The searchable text of one event: its name plus every string arg
    value (XLA puts the named-scope path in op-metadata args like ``name``
    / ``long_name`` / ``tf_op``)."""
    parts = [str(e.get("name", ""))]
    args = e.get("args")
    if isinstance(args, dict):
        parts.extend(str(v) for v in args.values() if isinstance(v, str))
    return " ".join(parts).lower()


# --- attribution -------------------------------------------------------------


def attribute_device_time(
    events: List[dict],
    phases: Optional[Dict[str, Tuple[str, ...]]] = None,
) -> Dict[str, dict]:
    """Sum device-row durations per phase.

    Returns ``{phase: {"device_us": float, "events": int}}`` plus two
    synthetic rows: ``_total`` (all device complete-events) and
    ``_unattributed`` (device time matching no phase).  An event matching
    several phases counts toward each (an interior-scope matmul is both
    ``step.overlap.interior`` and ``mxu`` time), so rows are VIEWS of the
    device timeline, not a partition — only ``_total`` is additive.

    Row selection: when the dump carries process metadata, only events on
    DEVICE processes count — a dump whose processes are all host (the CPU
    backend: ``/host:CPU`` full of Python-frame rows) attributes ZERO
    device time rather than wall-clock garbage (callers then degrade to
    the host-span fallback).  Traces with no process metadata at all (our
    own host Chrome dumps, bare event arrays) count every complete event —
    that IS the host-span fallback's input.
    """
    phases = PHASE_PATTERNS if phases is None else phases
    dev = device_pids(events)
    has_process_meta = any(
        e.get("ph") == "M" and e.get("name") == "process_name" for e in events
    )
    out = {p: {"device_us": 0.0, "events": 0} for p in phases}
    out["_total"] = {"device_us": 0.0, "events": 0}
    out["_unattributed"] = {"device_us": 0.0, "events": 0}
    pats = {p: tuple(s.lower() for s in subs) for p, subs in phases.items()}
    for e in events:
        if e.get("ph") != "X":
            continue
        if has_process_meta and e.get("pid") not in dev:
            continue
        dur = float(e.get("dur", 0.0) or 0.0)
        out["_total"]["device_us"] += dur
        out["_total"]["events"] += 1
        text = _event_text(e)
        hit = False
        for p, subs in pats.items():
            if any(s in text for s in subs):
                out[p]["device_us"] += dur
                out[p]["events"] += 1
                hit = True
        if not hit:
            out["_unattributed"]["device_us"] += dur
            out["_unattributed"]["events"] += 1
    return out


def attribute_exchange_directions(events: List[dict]) -> dict:
    """Collective-permute device time per exchange DIRECTION — the per-hop
    half of the comms roofline join.

    Runs ``attribute_device_time`` with one phase per registered
    ``exchange.<axis>.<side>`` scope plus the whole exchange family, and
    returns::

        {"directions": {span: {"device_us", "events"}},   # all six, zeros kept
         "exchange_device_us": float,   # the exchange-family total
         "attributed_us": float,        # summed direction time
         "coverage": float | None,      # attributed / exchange; None when no
                                        # exchange device time was seen
         "total_device_us": float}

    Direction rows are disjoint views (one scope path per trace row), so
    ``attributed_us`` is additive and ``coverage`` is the honest "how much
    of the exchange landed on a named hop" figure the fixture test pins at
    >=90%.  Host-only dumps inherit ``attribute_device_time``'s zero
    behavior: everything 0, coverage None — never wall-clock garbage."""
    phases = dict(EXCHANGE_DIRECTION_PHASES)
    phases["exchange"] = PHASE_PATTERNS["exchange"]
    att = attribute_device_time(events, phases)
    directions = {span: att[span] for span in EXCHANGE_DIRECTION_PHASES}
    exchange_us = att["exchange"]["device_us"]
    attributed_us = sum(d["device_us"] for d in directions.values())
    return {
        "directions": directions,
        "exchange_device_us": exchange_us,
        "attributed_us": attributed_us,
        "coverage": (attributed_us / exchange_us) if exchange_us > 0 else None,
        "total_device_us": att["_total"]["device_us"],
    }


# --- merging device rows into the host Chrome trace --------------------------


def merge_device_rows(
    host_events: List[dict],
    trace_events: List[dict],
    align_ts_us: Optional[float] = None,
) -> List[dict]:
    """Host Chrome-trace events + the device rows of a profiler dump, on
    one timeline.

    Device rows keep their relative timing but are SHIFTED so the earliest
    device event lands at ``align_ts_us`` (default: the earliest host span
    — device clocks and the host ``perf_counter`` epoch share no zero).
    Each device row records its original timestamp under
    ``args.device_ts_us``; device pids are remapped past
    ``DEVICE_PID_BASE`` and re-announced with ``process_name`` metadata so
    Perfetto labels the rows.

    Idempotent: rows from a PREVIOUS merge (pid >= ``DEVICE_PID_BASE`` —
    host spans use pid = rank, a small integer) are dropped first, so
    re-merging (perf_report --merge after a driver already merged at
    exit) replaces the device rows instead of stacking a second copy."""
    host_events = [
        e for e in host_events if int(e.get("pid", 0) or 0) < DEVICE_PID_BASE
    ]
    dev = device_pids(trace_events)
    if not dev:
        return list(host_events)
    rows = [
        e
        for e in trace_events
        if e.get("ph") == "X" and e.get("pid") in dev
    ]
    if not rows:
        return list(host_events)
    t0_dev = min(float(e.get("ts", 0.0)) for e in rows)
    if align_ts_us is None:
        host_ts = [
            float(e["ts"]) for e in host_events if e.get("ph") == "X"
        ]
        align_ts_us = min(host_ts) if host_ts else 0.0
    shift = align_ts_us - t0_dev
    pid_map = {
        pid: DEVICE_PID_BASE + i for i, pid in enumerate(sorted(dev))
    }
    out = list(host_events)
    for pid, name in sorted(dev.items()):
        out.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid_map[pid],
                "args": {"name": f"device: {name}"},
            }
        )
    for e in rows:
        ts = float(e.get("ts", 0.0))
        args = dict(e.get("args") or {})
        args["device_ts_us"] = ts
        out.append(
            {
                "name": e.get("name", ""),
                "ph": "X",
                "ts": ts + shift,
                "dur": float(e.get("dur", 0.0) or 0.0),
                "pid": pid_map[e["pid"]],
                "tid": e.get("tid", 0),
                "args": args,
            }
        )
    return out


def merge_into_chrome_trace(
    chrome_path: str, profile_dir: str
) -> Optional[dict]:
    """Merge the newest profiler dump under ``profile_dir`` into the host
    Chrome trace at ``chrome_path`` (atomic rewrite) and return the
    attribution table (None when either side is missing/empty) — the
    one-call form drivers use at exit."""
    traces = find_trace_files(profile_dir)
    if not traces or not os.path.exists(chrome_path):
        return None
    trace_events = load_trace_events(traces[0])
    if not trace_events:
        return None
    try:
        with open(chrome_path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    merged = merge_device_rows(doc.get("traceEvents", []), trace_events)
    doc["traceEvents"] = merged
    from stencil_tpu.utils.artifact import atomic_write

    with atomic_write(chrome_path) as f:
        json.dump(doc, f)
    return attribute_device_time(trace_events)


# --- cadence capture ---------------------------------------------------------


class ProfileCapture:
    """Cadence-gated ``jax.profiler`` capture around numbered dispatches.

    ``maybe(i)`` is a context manager: it traces into
    ``<dir>/capture_<i>`` when ``i`` is on the cadence (``every=N`` -> a
    capture at i = 0, N, 2N, ...; ``every=0`` -> exactly one capture, at
    i = 0) and is a no-op otherwise.  Each capture increments
    ``profile.captures`` and emits a ``profile.capture`` event; the
    underlying ``telemetry.trace`` wrapper owns the no-profiler-backend
    degrade (warn once, run unprofiled).
    """

    def __init__(self, dir: str, every: int = 0):
        self.dir = str(dir)
        self.every = max(int(every), 0)
        self.captures = 0
        #: analytic-counter DELTAS over the newest capture's window
        #: (``CAPTURE_COUNTERS``) — the honest numerator for the roofline
        #: join against that capture's device time; None before any capture
        self.last_counter_deltas: Optional[Dict[str, int]] = None

    @classmethod
    def from_env(cls, dir: Optional[str] = None) -> Optional["ProfileCapture"]:
        """``--profile-dir`` flag value (or ``STENCIL_PROFILE_DIR``) +
        ``STENCIL_PROFILE_EVERY`` cadence; None when no dir is configured
        anywhere — profiling is strictly opt-in."""
        from stencil_tpu.utils.config import env_int, env_str

        dir = dir or env_str("STENCIL_PROFILE_DIR", None)
        if not dir:
            return None
        return cls(dir, every=env_int("STENCIL_PROFILE_EVERY", 0, minimum=0))

    def want(self, index: int) -> bool:
        if self.every == 0:
            return index == 0
        return index % self.every == 0

    def capture_dir(self, index: int) -> str:
        return os.path.join(self.dir, f"capture_{index:06d}")

    def maybe(self, index: int):
        if not self.want(index):
            import contextlib

            return contextlib.nullcontext()
        return _OneCapture(self, index)

    # --- offline views over everything this capture object wrote ------------

    def attribution(self) -> Optional[dict]:
        """Attribution over the newest capture (None when nothing was
        dumped — e.g. the backend had no profiler)."""
        traces = find_trace_files(self.dir)
        if not traces:
            return None
        events = load_trace_events(traces[0])
        return attribute_device_time(events) if events else None

    def counters_snapshot(self) -> Optional[dict]:
        """The newest capture's counter DELTAS as a snapshot-shaped dict
        (``{"counters": {...}}``) for ``roofline_report`` — pair it with
        ``attribution()``, which also reads the newest capture."""
        if self.last_counter_deltas is None:
            return None
        return {"counters": dict(self.last_counter_deltas)}


class _OneCapture:
    """One cadence hit: enter the profiler trace, account the capture."""

    def __init__(self, owner: ProfileCapture, index: int):
        self.owner = owner
        self.index = index
        self._t0 = 0.0
        self._ctx = None

    def __enter__(self):
        from stencil_tpu import telemetry
        from stencil_tpu.telemetry.spans import trace

        self._c0 = {
            name: telemetry._cfg().registry.counter(name).value
            for name in CAPTURE_COUNTERS
        }
        self._t0 = time.perf_counter()
        self._ctx = trace(self.owner.capture_dir(self.index))
        self._ctx.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        out = self._ctx.__exit__(exc_type, exc, tb)
        from stencil_tpu import telemetry

        reg = telemetry._cfg().registry
        self.owner.last_counter_deltas = {
            name: reg.counter(name).value - self._c0[name]
            for name in CAPTURE_COUNTERS
        }
        self.owner.captures += 1
        telemetry.inc(names.PROFILE_CAPTURES)
        telemetry.emit_event(
            names.EVENT_PROFILE_CAPTURE,
            dir=self.owner.capture_dir(self.index),
            index=self.index,
            seconds=round(time.perf_counter() - self._t0, 6),
        )
        return out
