"""Perf ledger: an append-only JSONL record of benchmark headline series,
with a trailing-median regression gate.

The BENCH_r01..r05 trajectory is the repo's most important time series, and
until now it lived as loose artifact files a human eyeballs.  The ledger
normalizes every artifact into one-line entries

    {"ts": <epoch s>, "key": "<series key>", "value": <float>,
     "unit": "...", "source": "<artifact basename>", "workload": <tune
     workload label when the artifact carries one>, ...}

keyed by series (the headline metric, the exchange-path and astaroth
companions, each weak-scaling mesh/overlap cell, the fabric observatory's
``fabric:link_gbps`` and per-hop ``exchange_hop:*`` series), deduped on
``(key, source, ts)`` so re-ingesting the same file is idempotent while
regenerated artifacts and fresh live runs grow their series.  Appends go through
append-mode writes — one complete JSON document per line, the same crash
contract as the JSONL event sink (and the reason the ``artifact-write``
rule exempts append streams).

The **regression gate** compares each series' newest value against the
median of its trailing window: a drop past the threshold on a
higher-is-better series flags.  ``scripts/perf_ledger.py`` is the CLI
(ingest / check / show), ``bench.py --ledger`` appends the freshly
measured headline, and the tier-2 check runs the gate over the committed
artifacts.

jax-free: the ledger is bookkeeping over files.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

#: default gate: flag when the newest value drops more than 10% below the
#: trailing median
DEFAULT_THRESHOLD = 0.10
#: trailing entries (before the newest) the median is taken over
DEFAULT_WINDOW = 5


# --- artifact -> entries ------------------------------------------------------


def _bench_doc(doc: dict) -> Optional[dict]:
    """The bench result dict inside an artifact: the raw one-line JSON, the
    judge wrapper's ``parsed`` field, or — when a failed run left
    ``parsed: null`` — the last JSON-looking line of its ``tail``."""
    if not isinstance(doc, dict):
        return None
    if "metric" in doc:
        return doc
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed:
        return parsed
    tail = doc.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    cand = json.loads(line)
                except ValueError:
                    continue
                if isinstance(cand, dict) and "metric" in cand:
                    return cand
    return None


def _entry(ts: float, key: str, value, unit: str, source: str, **extra) -> Optional[dict]:
    if not isinstance(value, (int, float)):
        return None
    e = {"ts": ts, "key": key, "value": float(value), "unit": unit,
         "source": source}
    e.update(extra)
    return e


def entries_from_artifact(path: str) -> List[dict]:
    """Normalize one artifact file (a ``BENCH_*.json`` bench result — raw
    or judge-wrapped — a ``weak_scaling_summary.json`` sweep, or a
    ``bench_exchange`` route-A/B JSON line saved to a file) into ledger
    entries.  Unknown shapes return [] rather than raising: the ingest
    loop runs over globs."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return []
    ts = os.path.getmtime(path)
    source = os.path.basename(path)
    out: List[dict] = []

    bench = _bench_doc(doc)
    if bench is not None:
        # the tune decision is the closest thing a BENCH artifact carries
        # to its workload key — ride it along so ledger diffs can tell a
        # perf change from a config change
        extra = {}
        tune = bench.get("tune") or {}
        if tune.get("config") is not None:
            extra["tune_config"] = tune["config"]
        if tune.get("source"):
            extra["tune_source"] = tune["source"]
        out.append(
            _entry(ts, bench.get("metric", "bench"), bench.get("value"),
                   bench.get("unit", ""), source, **extra)
        )
        for field, unit in (
            ("exchange_path_mcells_per_s_per_chip", "Mcells/s"),
            ("astaroth_8q_mupdates_per_s", "Mupdates/s"),
            ("chip_copy_gbps", "GB/s"),
        ):
            out.append(_entry(ts, f"bench.{field}", bench.get(field), unit, source))
        # the compute-unit A/B legs (bench.py mxu_vs_vpu: vpu / mxu /
        # mxu_band / mxu_band+bf16in) as their own series — higher is
        # better, so the trailing-median gate catches a contraction-leg
        # regression exactly like a headline drop
        mxu_ab = bench.get("mxu_vs_vpu") or {}
        for leg, d in sorted((mxu_ab.get("units") or {}).items()):
            out.append(
                _entry(
                    ts,
                    f"mxu_ab:{leg}:mcells_per_s",
                    (d or {}).get("mcells_per_s"),
                    "Mcells/s",
                    source,
                    k=mxu_ab.get("k"),
                )
            )
        # the numerics observatory's on/off A/B (bench.py
        # numerics_overhead): per-snapshot cost of the fused on-device
        # field-health dispatch — LOWER-is-better (the gate flags a rise),
        # so the "cheap enough to leave on" claim is enforced per round
        num_ab = bench.get("numerics_overhead") or {}
        out.append(
            _entry(
                ts,
                "numerics:overhead",
                num_ab.get("snapshot_ms"),
                "ms",
                source,
                better="lower",
                quantities=num_ab.get("quantities"),
            )
        )
        return [e for e in out if e is not None]

    if isinstance(doc, dict) and doc.get("bench") == "weak_scaling_sweep":
        for m in doc.get("meshes", []):
            mesh = "x".join(str(v) for v in (m.get("mesh") or []))
            for ov, val in (m.get("mcells_per_s_per_chip") or {}).items():
                out.append(
                    _entry(ts, f"weak:{mesh}:{ov}", val, "Mcells/s/chip",
                           source, chips=m.get("chips"))
                )
            # the per-hop attribution table (analytic bytes per mesh hop,
            # bin/weak.py): LOWER-is-better — a rise means the halo traffic
            # over that link GREW (a decomposition/packing regression)
            for hop in m.get("exchange_hops") or []:
                out.append(
                    _entry(
                        ts,
                        f"exchange_hop:{mesh}:{hop.get('axis')}."
                        f"{hop.get('side')}:bytes",
                        hop.get("bytes"),
                        "B",
                        source,
                        better="lower",
                        hop_source=hop.get("source"),
                    )
                )
        return [e for e in out if e is not None]

    if isinstance(doc, dict) and doc.get("bench") == "fabric_probe":
        # the fabric observatory's probed link model (telemetry/fabric.py):
        # per-axis/per-direction median link bandwidth plus the slowest-link
        # headline — higher-is-better, so the gate catches a link (cable,
        # routing, throttle) that got slower between rounds
        from stencil_tpu.telemetry.fabric import link_model

        model = link_model(doc)
        for axis, sides in sorted(model.get("axes", {}).items()):
            for side, s in sorted(sides.items()):
                out.append(
                    _entry(
                        ts, f"fabric:link_gbps:{axis}.{side}", s.get("gbps_med"),
                        "GB/s", source, links=s.get("links"),
                        chip=doc.get("chip"),
                    )
                )
        slow = model.get("slowest") or {}
        out.append(
            _entry(
                ts, "fabric:link_gbps", slow.get("gbps"), "GB/s", source,
                axis=slow.get("axis"), side=slow.get("side"),
                chip=doc.get("chip"),
            )
        )
        return [e for e in out if e is not None]

    if isinstance(doc, dict) and doc.get("bench") == "comms_roofline":
        # perf_report.py --json: measured per-hop exchange rates from the
        # trace join — higher-is-better achieved GB/s per direction, plus
        # the direction-attribution coverage (a drop there means exchange
        # device time stopped landing on registered scopes)
        for span, hop in sorted((doc.get("hops") or {}).items()):
            out.append(
                _entry(
                    ts,
                    f"exchange_hop:{hop.get('axis')}.{hop.get('direction')}:gbps",
                    hop.get("gbps"), "GB/s", source,
                    probed_gbps=hop.get("probed_gbps"),
                    device_ms=hop.get("device_ms"),
                )
            )
        out.append(
            _entry(ts, "exchange_hop:coverage", doc.get("coverage"), "",
                   source, bottleneck_axis=doc.get("bottleneck_axis"))
        )
        return [e for e in out if e is not None]

    if isinstance(doc, dict) and doc.get("bench") == "soak_kill_resume":
        # the chaos soak (scripts/run_soak.py): recovery wall clock and the
        # per-transition in-memory reshard timings — both LOWER-is-better
        # (``better: "lower"``; the gate flags rises, not drops).  Only
        # bitwise-identical soaks land: a failed soak's timings describe a
        # broken run, not a perf point.
        if not doc.get("bitwise_identical"):
            return []
        out.append(
            _entry(
                ts, "soak:recovery_seconds", doc.get("recovery_seconds"),
                "s", source, better="lower", kills=len(doc.get("kills") or []),
            )
        )
        rs = [v for v in doc.get("reshard_seconds") or [] if isinstance(v, (int, float))]
        if rs:
            out.append(
                _entry(
                    ts, "reshard:seconds", _median(rs), "s", source,
                    better="lower", transitions=len(rs),
                )
            )
        return [e for e in out if e is not None]

    if isinstance(doc, dict) and doc.get("bench") == "serve_soak":
        # the serving chaos soak / load-generator artifact (run_soak.py
        # --serve, bin/stencil_serve.py): fleet-wide p99 latency and the
        # shed rate — both LOWER-is-better SLO series.  Only soaks whose
        # isolation verdict held land: a run where a poisoned tenant bled
        # into its neighbors describes a broken server, not an SLO point.
        if not doc.get("isolation_ok", doc.get("bitwise_identical")):
            return []
        out.append(
            _entry(
                ts, "serve:p99_ms", doc.get("p99_ms"), "ms", source,
                better="lower", tenants=len(doc.get("tenants") or []),
            )
        )
        out.append(
            _entry(
                ts, "serve:shed_rate", doc.get("shed_rate"), "", source,
                better="lower", requests=doc.get("requests"),
            )
        )
        # aggregate serving throughput (batched/sub-slice packed dispatch
        # lands here as a rate climb) — HIGHER-is-better, the one serve
        # series where the gate flags drops
        tp = doc.get("throughput") or {}
        out.append(
            _entry(
                ts, "serve:throughput", tp.get("requests_per_s"), "1/s",
                source, mcells_per_s=tp.get("mcells_per_s"),
                batch_max=tp.get("batch_max"), subslice=tp.get("subslice"),
            )
        )
        return [e for e in out if e is not None]

    if isinstance(doc, dict) and doc.get("bench") == "exchange":
        # bench_exchange's route A/B (the packed-route wins): direct's
        # steady-state rate plus every packed route's speedup-vs-direct —
        # all higher-is-better, so the trailing-median gate catches a
        # packed-route regression exactly like a headline drop
        ab = doc.get("route_ab") or {}
        direct = ((ab.get("routes") or {}).get("direct") or {}).get(
            "ms_per_exchange"
        )
        if isinstance(direct, (int, float)) and direct > 0:
            out.append(
                _entry(
                    ts,
                    "exchange_ab:direct:exchanges_per_s",
                    1e3 / direct,
                    "1/s",
                    source,
                    extent=doc.get("extent"),
                    quantities=doc.get("quantities"),
                )
            )
        for route, sp in (ab.get("speedup_vs_direct") or {}).items():
            out.append(
                _entry(ts, f"exchange_ab:{route}:speedup", sp, "x", source)
            )
        return [e for e in out if e is not None]

    return []


def entry_from_bench_result(result: dict, source: str = "bench.py") -> Optional[dict]:
    """A live ``bench.py`` result dict -> its headline ledger entry."""
    import time

    return _entry(
        time.time(), result.get("metric", "bench"), result.get("value"),
        result.get("unit", ""), source,
    )


# --- the ledger file ----------------------------------------------------------


def read_ledger(path: str) -> List[dict]:
    """All entries, file order (= append order).  Truncated trailing lines
    (a crash mid-append) are skipped — every complete line is one document."""
    out = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        return []
    return out


def _dedupe_key(e: dict):
    """Identity of one MEASUREMENT: series + source + timestamp.  ``ts``
    must participate — artifact entries stamp the file mtime and live
    bench entries stamp now, so re-ingesting the SAME file is a no-op
    while a regenerated artifact (new mtime) or a fresh ``bench.py
    --ledger`` run (new clock) grows the series; keying on
    ``(key, source)`` alone would cap every repeat-source series at one
    entry forever."""
    return (e.get("key"), e.get("source"), e.get("ts"))


def append_entries(path: str, entries: List[dict]) -> int:
    """Append ``entries`` not already present (dedupe on
    ``(key, source, ts)`` — re-ingesting the same artifacts is
    idempotent); returns how many landed.  Append-mode by design: the
    ledger is the one artifact whose whole point is never rewriting
    history."""
    entries = [e for e in entries if e is not None]
    have = {_dedupe_key(e) for e in read_ledger(path)}
    fresh = [e for e in entries if _dedupe_key(e) not in have]
    if not fresh:
        return 0
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "a", encoding="utf-8") as f:
        for e in fresh:
            f.write(json.dumps(e, sort_keys=True) + "\n")
    return len(fresh)


# --- the regression gate ------------------------------------------------------


def _median(xs: List[float]) -> float:
    xs = sorted(xs)
    n = len(xs)
    if n % 2:
        return xs[n // 2]
    return (xs[n // 2 - 1] + xs[n // 2]) / 2


def check_regressions(
    entries: List[dict],
    threshold: float = DEFAULT_THRESHOLD,
    window: int = DEFAULT_WINDOW,
) -> Tuple[List[dict], List[dict]]:
    """Gate every series: newest value vs the median of up to ``window``
    trailing entries.  Series are higher-is-better throughputs unless the
    newest entry carries ``better: "lower"`` (the soak's seconds series) —
    there a RISE past the threshold flags instead of a drop.  Returns
    ``(rows, regressions)`` — one row per series with >= 2 entries:

        {"key", "value", "trailing_median", "ratio", "n", "regressed"}

    ``regressed`` is True when ``value < (1 - threshold) * median`` (or
    ``value > (1 + threshold) * median`` for lower-is-better series).
    Single-entry series have no history to regress against and are
    reported with ``trailing_median: None``.
    """
    by_key = {}
    for e in entries:
        if isinstance(e.get("value"), (int, float)) and e.get("key"):
            by_key.setdefault(e["key"], []).append(e)
    rows, regressions = [], []
    for key in sorted(by_key):
        # series order is LEDGER order (= append order): the ledger is
        # append-only, so position is the honest round ordering — file
        # mtimes are scrambled by any fresh checkout, and ``ts`` stays
        # informational only
        series = by_key[key]
        newest = series[-1]
        prior = [e["value"] for e in series[:-1]][-window:]
        row = {
            "key": key,
            "value": newest["value"],
            "unit": newest.get("unit", ""),
            "source": newest.get("source"),
            "trailing_median": _median(prior) if prior else None,
            "n": len(series),
            "ratio": None,
            "regressed": False,
        }
        if prior and row["trailing_median"]:
            row["ratio"] = round(newest["value"] / row["trailing_median"], 4)
            if newest.get("better") == "lower":
                row["regressed"] = newest["value"] > (1.0 + threshold) * row[
                    "trailing_median"
                ]
            else:
                row["regressed"] = newest["value"] < (1.0 - threshold) * row[
                    "trailing_median"
                ]
        rows.append(row)
        if row["regressed"]:
            regressions.append(row)
    return rows, regressions
