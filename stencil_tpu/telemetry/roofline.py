"""Roofline reports: join measured device time per phase with the analytic
counters the tree already records.

The PERF_NOTES break-even models (VPU wall, split-step overlap, zpack) all
end in the same table a human currently assembles by hand: achieved GB/s /
GFLOP/s per phase vs the chip's peak.  This module builds that table from
two inputs this repo already produces —

* a **metrics snapshot** (``telemetry.snapshot()`` /
  ``metrics_<rank>.json``): the analytic counters ``domain.exchange.bytes``,
  ``exchange.packed.bytes``, ``kernel.mxu.flops``;
* a **device-time attribution** (``telemetry/device.py``): measured device
  microseconds per phase from a ``jax.profiler`` capture, or — when no
  profiler backend exists — host span durations as a degraded stand-in
  (tagged ``"source": "host"``; host wall-clock of an async dispatch is an
  upper bound on nothing, so the tag matters).

The portable-stencil framework survey (arxiv 2309.04671) ranks kernels by
achieved-vs-roofline; ``scripts/perf_report.py`` renders this module's
JSON as that table, and ``bench.py`` embeds it when profiling is on.

jax-free: reports are built offline, often from a dead run's artifacts.
"""

from __future__ import annotations

from typing import Dict, Optional

from stencil_tpu.telemetry import names

#: nominal per-chip peaks keyed by ``device_kind`` prefix (the same labels
#: ``tune.key.chip_kind`` persists).  Numbers follow PERF_NOTES ("VPU
#: wall": v5e-class ≈ 197 Tf32-FLOP/s MXU, 819 GB/s HBM); unknown chips
#: (and CPU dryruns) carry None peaks — the report then shows achieved
#: rates with a null roofline fraction instead of inventing a ceiling.
PEAKS: Dict[str, dict] = {
    "TPU v5e": {"hbm_gbps": 819.0, "mxu_gflops_f32": 197_000.0,
                "mxu_gflops_bf16": 394_000.0},
    "TPU v5p": {"hbm_gbps": 2765.0, "mxu_gflops_f32": 229_500.0,
                "mxu_gflops_bf16": 459_000.0},
    "TPU v4": {"hbm_gbps": 1228.0, "mxu_gflops_f32": 137_500.0,
               "mxu_gflops_bf16": 275_000.0},
}

#: phase -> the analytic counter carrying its traffic/work (the join key)
PHASE_BYTES_COUNTERS = {
    "exchange": names.EXCHANGE_BYTES,
    "pack": names.EXCHANGE_PACKED_BYTES,
}
PHASE_FLOPS_COUNTERS = {
    "mxu": names.KERNEL_MXU_FLOPS,
}


def peaks_for(chip: Optional[str],
              measured_hbm_gbps: Optional[float] = None) -> dict:
    """The peak table for ``chip`` (prefix match over ``PEAKS``), with the
    MEASURED copy bandwidth substituted for the nominal HBM number when
    available — a time-shared/throttled chip's honest ceiling is what it
    measured, not the datasheet (the ``chip_copy_gbps`` rule bench.py
    already applies to its headline)."""
    out = {"chip": chip, "hbm_gbps": None, "mxu_gflops_f32": None,
           "mxu_gflops_bf16": None, "hbm_source": None}
    if chip:
        for prefix, vals in PEAKS.items():
            if chip.startswith(prefix):
                out.update(vals)
                out["hbm_source"] = "nominal"
                break
    if measured_hbm_gbps:
        out["hbm_gbps"] = float(measured_hbm_gbps)
        out["hbm_source"] = "measured"
    return out


def _counters(snapshot: Optional[dict]) -> dict:
    return (snapshot or {}).get("counters", {}) or {}


def roofline_report(
    snapshot: Optional[dict],
    attribution: Optional[dict],
    chip: Optional[str] = None,
    measured_hbm_gbps: Optional[float] = None,
    source: str = "device",
    counters_scope: str = "run",
) -> dict:
    """The per-phase roofline join.

    ``attribution`` is ``{phase: {"device_us": ..., "events": ...}}``
    (``telemetry.device.attribute_device_time``; a host-span fallback uses
    the same shape with ``source="host"``).  Phases carrying an analytic
    bytes counter report achieved GB/s and their fraction of the HBM
    roofline; the ``mxu`` phase reports GFLOP/s vs the MXU peak; scope
    phases with no counter (interior/exterior) report time and their share
    of total device time — the overlap-efficiency inputs.

    ``counters_scope`` records what window the counters cover, because the
    join is only honest when numerator and denominator cover the SAME
    window: ``"capture"`` = the counter deltas of the profiled window
    (``ProfileCapture.counters_snapshot`` — what the drivers pass);
    ``"run"`` = whole-run cumulative counters (offline ``perf_report``
    over ``metrics_*.json``), where achieved rates overstate by
    (run work / captured work) unless the run captured its whole measured
    loop.
    """
    counters = _counters(snapshot)
    attribution = attribution or {}
    peaks = peaks_for(chip, measured_hbm_gbps)
    total_us = attribution.get("_total", {}).get("device_us", 0.0)
    phases = {}
    for phase, row in attribution.items():
        if phase.startswith("_"):
            continue
        us = float(row.get("device_us", 0.0))
        s = us / 1e6
        entry = {
            "device_ms": round(us / 1e3, 6),
            "events": int(row.get("events", 0)),
            "share_of_device": round(us / total_us, 4) if total_us else None,
            "bytes": None,
            "gbps": None,
            "flops": None,
            "gflops": None,
            "frac_of_roofline": None,
        }
        bc = PHASE_BYTES_COUNTERS.get(phase)
        if bc is not None:
            b = counters.get(bc)
            if b:
                entry["bytes"] = int(b)
                if s > 0:
                    entry["gbps"] = round(b / s / 1e9, 3)
                    if peaks["hbm_gbps"]:
                        entry["frac_of_roofline"] = round(
                            entry["gbps"] / peaks["hbm_gbps"], 4
                        )
        fc = PHASE_FLOPS_COUNTERS.get(phase)
        if fc is not None:
            fl = counters.get(fc)
            if fl:
                entry["flops"] = int(fl)
                if s > 0:
                    entry["gflops"] = round(fl / s / 1e9, 3)
                    if peaks["mxu_gflops_f32"]:
                        entry["frac_of_roofline"] = round(
                            entry["gflops"] / peaks["mxu_gflops_f32"], 4
                        )
        phases[phase] = entry
    return {
        "source": source,
        "counters_scope": counters_scope,
        "peaks": peaks,
        "total_device_ms": round(total_us / 1e3, 6) if total_us else None,
        "unattributed_device_ms": round(
            attribution.get("_unattributed", {}).get("device_us", 0.0) / 1e3,
            6,
        ),
        "phases": phases,
    }


def capture_report(
    capture,
    chip: Optional[str] = None,
    measured_hbm_gbps: Optional[float] = None,
) -> Optional[dict]:
    """``roofline_report`` for a ``ProfileCapture``'s newest window: the
    dump's attribution joined with the capture-window counter deltas
    (whole-run snapshot fallback, tagged in ``counters_scope``).  Returns
    None when the capture produced no device rows (backend without a
    device profiler) — THE shared finalize for ``bench.py`` (embeds the
    report) and ``bin/_common.profile_finalize`` (writes it)."""
    attribution = capture.attribution()
    if attribution is None or attribution["_total"]["events"] == 0:
        return None
    deltas = capture.counters_snapshot()
    from stencil_tpu import telemetry

    return roofline_report(
        deltas if deltas is not None else telemetry.snapshot(),
        attribution,
        chip=chip,
        measured_hbm_gbps=measured_hbm_gbps,
        counters_scope="capture" if deltas is not None else "run",
    )


def comms_roofline(
    direction_attribution: Optional[dict],
    snapshot: Optional[dict],
    fabric_model: Optional[dict] = None,
) -> Optional[dict]:
    """The communication dimension of the roofline: achieved per-link GB/s
    per mesh axis per direction, vs the PROBED link bandwidth when a fabric
    matrix is joined in.

    Three inputs, all artifacts this repo already produces:

    * ``direction_attribution`` — ``device.attribute_exchange_directions``
      over a profiler trace: collective-permute device time per registered
      ``exchange.<axis>.<side>`` scope, plus the coverage fraction of the
      whole exchange family;
    * ``snapshot`` — the analytic ``exchange.hop.<axis>.<side>.bytes``
      counters (``DistributedDomain`` decomposes ``domain.exchange.bytes``
      per hop);
    * ``fabric_model`` — ``telemetry.fabric.link_model`` output (optional:
      without it, achieved rates report with null probed ceilings).

    The bottleneck is the direction with the most device time — the hop a
    topology/placement change must shrink first.  Returns None when there
    is no attribution at all (no trace).
    """
    if not direction_attribution:
        return None
    counters = _counters(snapshot)
    axes_model = (fabric_model or {}).get("axes", {})
    span_to_hop = {
        span: hop for hop, span in names.EXCHANGE_DIRECTION_SPANS.items()
    }
    hops = {}
    bottleneck = None
    for span, row in (direction_attribution.get("directions") or {}).items():
        axis, side = span_to_hop[span]
        us = float(row.get("device_us", 0.0))
        s = us / 1e6
        b = counters.get(names.EXCHANGE_HOP_BYTES[(axis, side)])
        probed = (axes_model.get(axis, {}).get(side) or {}).get("gbps_med")
        entry = {
            "axis": axis,
            "direction": side,
            "device_ms": round(us / 1e3, 6),
            "events": int(row.get("events", 0)),
            "bytes": int(b) if b else None,
            "gbps": round(b / s / 1e9, 3) if (b and s > 0) else None,
            "probed_gbps": probed,
            "frac_of_link": None,
        }
        if entry["gbps"] is not None and probed:
            entry["frac_of_link"] = round(entry["gbps"] / probed, 4)
        hops[span] = entry
        if us > 0 and (bottleneck is None or us > bottleneck["_us"]):
            bottleneck = {"span": span, "_us": us, **entry}
    if bottleneck is not None:
        bottleneck.pop("_us")
    return {
        "coverage": direction_attribution.get("coverage"),
        "exchange_device_ms": round(
            float(direction_attribution.get("exchange_device_us") or 0.0) / 1e3, 6
        ),
        "attributed_ms": round(
            float(direction_attribution.get("attributed_us") or 0.0) / 1e3, 6
        ),
        "hops": hops,
        "bottleneck": bottleneck,
        "bottleneck_axis": bottleneck["axis"] if bottleneck else None,
        "fabric": "probed" if fabric_model else None,
    }


def render_markdown(report: dict) -> str:
    """The report as the PERF_NOTES-style markdown table."""
    peaks = report.get("peaks", {})
    lines = [
        "# Per-phase roofline",
        "",
        f"- chip: `{peaks.get('chip')}`  "
        f"(HBM peak {peaks.get('hbm_gbps')} GB/s "
        f"[{peaks.get('hbm_source') or 'unknown'}], "
        f"MXU f32 peak {peaks.get('mxu_gflops_f32')} GFLOP/s)",
        f"- timing source: **{report.get('source')}** "
        + ("(device truth)" if report.get("source") == "device"
           else "(host spans — async dispatch upper bound only)"),
        f"- counters scope: **{report.get('counters_scope')}** "
        + ("(capture-window deltas — rates are honest)"
           if report.get("counters_scope") == "capture"
           else "(whole-run cumulative — rates overstate unless the "
           "capture covered the whole measured loop)"),
        f"- total device time: {report.get('total_device_ms')} ms "
        f"(unattributed {report.get('unattributed_device_ms')} ms)",
        "",
        "| phase | device ms | events | share | GB/s | GFLOP/s | % of roofline |",
        "|---|---|---|---|---|---|---|",
    ]
    for phase in sorted(report.get("phases", {})):
        e = report["phases"][phase]
        frac = e.get("frac_of_roofline")
        lines.append(
            f"| `{phase}` | {e['device_ms']} | {e['events']} | "
            f"{e.get('share_of_device')} | {e.get('gbps') or ''} | "
            f"{e.get('gflops') or ''} | "
            f"{f'{100 * frac:.1f}%' if frac is not None else ''} |"
        )
    lines.append("")
    comms = report.get("comms")
    if comms:
        cov = comms.get("coverage")
        lines += [
            "## Comms roofline (per mesh hop)",
            "",
            f"- exchange device time: {comms.get('exchange_device_ms')} ms, "
            f"direction coverage "
            + (f"{100 * cov:.1f}%" if cov is not None else "n/a")
            + (
                ""
                if comms.get("fabric")
                else " (no fabric probe joined — probed ceilings null; run "
                "`python -m stencil_tpu.fabric`)"
            ),
            "",
            "| hop | device ms | events | bytes | GB/s | probed GB/s | % of link |",
            "|---|---|---|---|---|---|---|",
        ]
        for span in sorted(comms.get("hops", {})):
            e = comms["hops"][span]
            frac = e.get("frac_of_link")
            lines.append(
                f"| `{span}` | {e['device_ms']} | {e['events']} | "
                f"{e.get('bytes') or ''} | {e.get('gbps') or ''} | "
                f"{e.get('probed_gbps') or ''} | "
                f"{f'{100 * frac:.1f}%' if frac is not None else ''} |"
            )
        bn = comms.get("bottleneck")
        if bn:
            lines += [
                "",
                f"**Bottleneck: mesh axis `{bn['axis']}`** "
                f"(`{bn.get('span')}`, {bn['device_ms']} ms of exchange "
                "device time — the hop a topology/placement change must "
                "shrink first).",
            ]
        lines.append("")
    return "\n".join(lines)
