"""Canonical telemetry names — THE single registry of every metric, span,
and event name this tree may emit.

Every call into the telemetry facade (``telemetry.inc`` / ``observe`` /
``set_gauge`` / ``emit_event`` / ``span`` / ``record_span``) must name its
series through a constant defined here; the ``telemetry-name`` rule of
``stencil_tpu.lint`` (wired as a tier-1 test) rejects free-string names at
call sites.  One
module of constants keeps the cross-round BENCH diffs stable: a renamed or
typo'd series fails the lint instead of silently forking the time series.

Naming scheme: ``<subsystem>.<noun>[.<unit>]``, lowercase, dots between
levels, underscores inside a level.  Histograms of seconds end in
``.seconds``; byte counters end in ``.bytes``.
"""

from __future__ import annotations

# --- counters (monotonic; always recorded, snapshot seeds all of these) -----

#: halo exchanges accounted (direct ``exchange()``/``exchange_many()`` calls
#: plus one per fused-step exchange inside ``run_step`` dispatches)
EXCHANGE_COUNT = "domain.exchange.count"
#: analytic bytes moved by those exchanges (``exchange_bytes_total`` per
#: exchange — the reference's exchange_bytes_for_method accounting)
EXCHANGE_BYTES = "domain.exchange.bytes"
#: ``run_step`` dispatches (device-side loops of many raw iterations)
STEP_DISPATCHES = "domain.step.dispatches"
#: raw stencil iterations advanced through ``run_step``
STEP_ITERATIONS = "domain.step.iterations"
#: transient-failure retry attempts (resilience/retry.py)
RETRY_ATTEMPTS = "resilience.retry.attempts"
#: retries abandoned after exhausting the policy budget
RETRY_EXHAUSTED = "resilience.retry.exhausted"
#: retries refused by the donated-buffer liveness guard
RETRY_REFUSED = "resilience.retry.refused"
#: degradation-ladder descents (resilience/ladder.py)
LADDER_DESCENTS = "resilience.ladder.descents"
#: faults raised by the STENCIL_FAULT_PLAN hook (resilience/inject.py)
FAULTS_INJECTED = "resilience.faults.injected"
#: divergence-sentinel NaN/Inf detections (resilience/sentinel.py)
SENTINEL_TRIPS = "resilience.sentinel.trips"
#: tuned-config cache consultations that found a persisted config (tune/)
TUNE_CACHE_HIT = "tune.cache.hit"
#: consultations that found nothing (cold cache, stale version, corrupt file)
TUNE_CACHE_MISS = "tune.cache.miss"
#: candidate configs actually measured by the autotuner's trial protocol
TUNE_TRIALS = "tune.trials"
#: candidates pruned without a steady-state measurement (VMEM model
#: pre-filter, or an on-device VMEM_OOM/COMPILE_REJECT pruning the candidate
#: and its deeper neighbors)
TUNE_PRUNED = "tune.pruned"
#: winning configs selected (and persisted) by a completed search
TUNE_SELECTED = "tune.selected"
#: analytic bytes moved through packed z-shell message buffers (the
#: ``zpack_*`` exchange routes; 0 under ``direct`` — ops/exchange.py
#: ``zpack_message_stats``)
EXCHANGE_PACKED_BYTES = "exchange.packed.bytes"
#: analytic pack+unpack kernel launches of those packed exchanges
EXCHANGE_PACKED_KERNELS = "exchange.packed.kernels"
#: analytic boundary-band cells RECOMPUTED by the split-step exterior passes
#: (``overlap=split`` on the stream engine, ops/stream.py): the redundant
#: surface work the overlapped schedule pays to free the interior pass from
#: any ppermute dependency; 0 under ``overlap=off``
STEP_OVERLAP_EXTERIOR_CELLS = "step.overlap.exterior_cells"
#: analytic MXU FLOPs issued by the banded-contraction level kernels
#: (``compute_unit=mxu|mxu_band`` — ops/jacobi_pallas.py
#: ``mxu_flops_per_plane``): FLOPs per level per plane for the RESOLVED
#: variant (dense circulant vs blocked band tiles — the dense model
#: over-reports a band-tiled run by ~n/(2r+1)), modeled once per build
#: like the exchange bytes; 0 under ``compute_unit=vpu``
KERNEL_MXU_FLOPS = "kernel.mxu.flops"
#: checkpoints committed (atomic rename completed — io/checkpoint.py)
CHECKPOINT_SAVES = "checkpoint.saves"
#: bytes of quantity data written by those checkpoints (interior cells at
#: the NATIVE dtype — the portable representation the digests cover)
CHECKPOINT_SAVE_BYTES = "checkpoint.save.bytes"
#: successful checkpoint restores (elastic cross-mesh restores included)
CHECKPOINT_RESTORES = "checkpoint.restores"
#: checkpoints REJECTED by validation (missing/partial manifest, digest
#: mismatch) — each one the retention-ring fallback skipped past
CHECKPOINT_INVALID = "checkpoint.invalid"
#: supervisor restarts from the last valid checkpoint after a FATAL/STALL
#: dispatch classification (resilience/supervisor.py restart budget)
SUPERVISOR_RESTARTS = "supervisor.restarts"
#: watchdog deadline trips (resilience/watchdog.py): dispatches that ran
#: past STENCIL_WATCHDOG_S without completing
WATCHDOG_STALLS = "watchdog.stalls"
#: device-profile captures taken by the cadence profiler
#: (telemetry/device.py ``ProfileCapture`` — STENCIL_PROFILE_EVERY /
#: ``--profile-dir``); 0 when profiling is off or the backend has no
#: profiler (the capture degrades to a warn, never a crash)
PROFILE_CAPTURES = "profile.captures"
#: completed in-memory mesh transitions (``DistributedDomain.reshard`` —
#: parallel/redistribute.py): live grow/shrink moves that never touched
#: disk; the checkpoint-elastic-restore fallback counts separately below
RESHARDS = "reshard.count"
#: analytic bytes of interior state moved by those resharding collectives
#: (whole valid interior at the stored dtype, every quantity)
RESHARD_BYTES = "reshard.bytes"
#: capacity changes that could NOT reshard in memory and fell back to
#: checkpoint-elastic-restore (devices gone, no admissible partition,
#: consumed buffers) — each one also charges the supervisor restart budget
RESHARD_FALLBACKS = "reshard.fallbacks"
#: fused on-device field-health snapshots taken (telemetry/numerics.py
#: ``NumericsEngine.snapshot`` — one sharded dispatch, O(#quantities)
#: scalars to the host; the cadence paths STENCIL_NUMERICS_EVERY and the
#: rewired divergence sentinel both count here)
NUMERICS_SNAPSHOTS = "numerics.snapshots"
#: guardband violations observed over those snapshots (the invariant
#: drifted but stayed finite — observe-only unless STENCIL_NUMERICS_ABORT
#: escalates).  Doubles as the event name: one constant, one series.
NUMERICS_DRIFT = "numerics.drift"
#: serving-layer requests ADMITTED past admission control (serve/server.py:
#: VMEM verdict ok, executable warm or compiled under budget, queue slot)
SERVE_ADMITTED = "serve.admitted"
#: requests REFUSED at admission (static VMEM verdict, cold compile over
#: budget, queue full, tenant quarantined/evicted)
SERVE_REJECTED = "serve.rejected"
#: queued requests SHED under load (past-deadline first, then lowest
#: priority to make room for a higher-priority arrival)
SERVE_SHED = "serve.shed"
#: tenants evicted/quarantined by the per-tenant fault envelope (their
#: DIVERGENCE — a poisoned request — must not touch other tenants)
SERVE_EVICTED = "serve.evicted"
#: requests served to completion
SERVE_COMPLETED = "serve.completed"
#: ``StencilServer.drain`` runs that hit the ``max_cycles`` bound with
#: work still queued (no-silent-caps: the truncation also logs the bound
#: and the remaining depth)
SERVE_DRAIN_TRUNCATED = "serve.drain.truncated"
#: packed dispatches (batched group or sub-slice placement) that fell
#: back to serial re-execution after a classified failure
SERVE_BATCH_FALLBACKS = "serve.batch.fallbacks"
#: successful BATCHED dispatches (always-live engagement evidence: the
#: soak's packed legs assert > 0 — histograms only record with telemetry
#: enabled, and digest equality alone cannot prove batching ran)
SERVE_BATCH_DISPATCHES = "serve.batch.dispatches"
#: successful sub-slice packed cycles (same role for the bin-packer)
SERVE_SUBSLICE_DISPATCHES = "serve.subslice.dispatches"
#: analytic bytes moved per exchange over ONE mesh hop — one counter per
#: (axis, direction) so the comms roofline can price each link of the
#: realized mesh (the per-direction decomposition of ``domain.exchange.bytes``
#: — ``DistributedDomain.exchange_hop_bytes``); 0 on axes the mesh does not
#: split
EXCHANGE_HOP_X_LOW_BYTES = "exchange.hop.x.low.bytes"
EXCHANGE_HOP_X_HIGH_BYTES = "exchange.hop.x.high.bytes"
EXCHANGE_HOP_Y_LOW_BYTES = "exchange.hop.y.low.bytes"
EXCHANGE_HOP_Y_HIGH_BYTES = "exchange.hop.y.high.bytes"
EXCHANGE_HOP_Z_LOW_BYTES = "exchange.hop.z.low.bytes"
EXCHANGE_HOP_Z_HIGH_BYTES = "exchange.hop.z.high.bytes"
#: point-to-point fabric-probe transfers actually measured on device
#: (telemetry/fabric.py — 0 when the probe answered from its warm cache)
FABRIC_PROBE_RUNS = "fabric.probe.runs"
#: fabric-probe cache consultations that found a persisted link matrix
FABRIC_CACHE_HIT = "fabric.cache.hit"
#: consultations that found nothing (cold cache, stale schema/toolchain,
#: corrupt artifact) — mirrors the tune-cache miss semantics
FABRIC_CACHE_MISS = "fabric.cache.miss"

#: the per-hop byte counter for one (mesh axis, direction) — direction names
#: follow the receive side: ``low`` receives from the -1 neighbor
EXCHANGE_HOP_BYTES = {
    ("x", "low"): EXCHANGE_HOP_X_LOW_BYTES,
    ("x", "high"): EXCHANGE_HOP_X_HIGH_BYTES,
    ("y", "low"): EXCHANGE_HOP_Y_LOW_BYTES,
    ("y", "high"): EXCHANGE_HOP_Y_HIGH_BYTES,
    ("z", "low"): EXCHANGE_HOP_Z_LOW_BYTES,
    ("z", "high"): EXCHANGE_HOP_Z_HIGH_BYTES,
}

ALL_COUNTERS = frozenset({
    EXCHANGE_COUNT,
    EXCHANGE_BYTES,
    EXCHANGE_PACKED_BYTES,
    EXCHANGE_PACKED_KERNELS,
    STEP_DISPATCHES,
    STEP_ITERATIONS,
    RETRY_ATTEMPTS,
    RETRY_EXHAUSTED,
    RETRY_REFUSED,
    LADDER_DESCENTS,
    FAULTS_INJECTED,
    SENTINEL_TRIPS,
    TUNE_CACHE_HIT,
    TUNE_CACHE_MISS,
    TUNE_TRIALS,
    TUNE_PRUNED,
    TUNE_SELECTED,
    STEP_OVERLAP_EXTERIOR_CELLS,
    KERNEL_MXU_FLOPS,
    CHECKPOINT_SAVES,
    CHECKPOINT_SAVE_BYTES,
    CHECKPOINT_RESTORES,
    CHECKPOINT_INVALID,
    SUPERVISOR_RESTARTS,
    WATCHDOG_STALLS,
    PROFILE_CAPTURES,
    RESHARDS,
    RESHARD_BYTES,
    RESHARD_FALLBACKS,
    NUMERICS_SNAPSHOTS,
    NUMERICS_DRIFT,
    SERVE_ADMITTED,
    SERVE_REJECTED,
    SERVE_SHED,
    SERVE_EVICTED,
    SERVE_COMPLETED,
    SERVE_DRAIN_TRUNCATED,
    SERVE_BATCH_FALLBACKS,
    SERVE_BATCH_DISPATCHES,
    SERVE_SUBSLICE_DISPATCHES,
    EXCHANGE_HOP_X_LOW_BYTES,
    EXCHANGE_HOP_X_HIGH_BYTES,
    EXCHANGE_HOP_Y_LOW_BYTES,
    EXCHANGE_HOP_Y_HIGH_BYTES,
    EXCHANGE_HOP_Z_LOW_BYTES,
    EXCHANGE_HOP_Z_HIGH_BYTES,
    FABRIC_PROBE_RUNS,
    FABRIC_CACHE_HIT,
    FABRIC_CACHE_MISS,
})

# --- gauges (last-value) -----------------------------------------------------

#: analytic bytes per single exchange across all subdomains
EXCHANGE_BYTES_PER_EXCHANGE = "domain.exchange.bytes_per_exchange"
#: checkpoints currently RETAINED in the ring after pruning (last value of
#: ``keep``-bounded ring size — io/checkpoint.py ``save_to_ring``)
CHECKPOINT_RETAINED = "checkpoint.retained"

#: serving request-queue depth after each admission/dispatch (the signal
#: the elasticity policy watches)
SERVE_QUEUE_DEPTH = "serve.queue.depth"
#: tenants currently in the "active" state (admitted, not quarantined)
SERVE_TENANTS_ACTIVE = "serve.tenants.active"
#: fraction of the fleet's devices busy in the most recent dispatch
#: (1.0 = a full-fleet or batched dispatch; a sub-slice pack sums its
#: disjoint slices — the throughput scheduler's utilization signal)
SERVE_OCCUPANCY = "serve.occupancy"

ALL_GAUGES = frozenset({
    EXCHANGE_BYTES_PER_EXCHANGE,
    CHECKPOINT_RETAINED,
    SERVE_QUEUE_DEPTH,
    SERVE_TENANTS_ACTIVE,
    SERVE_OCCUPANCY,
})

# --- histograms (Statistics-backed: min/max/avg/stddev/med/trimean) ----------

#: wall seconds per RAW iteration through ``run_step`` (dispatch time / raw
#: steps, honest-synced)
STEP_SECONDS = "domain.step.seconds"
#: wall seconds per direct ``exchange()`` call (honest-synced)
EXCHANGE_SECONDS = "domain.exchange.seconds"
#: wall seconds per ``swap()`` call
SWAP_SECONDS = "domain.swap.seconds"
#: exchange trace+compile seconds at ``realize()`` (the CUDA-Graph-capture
#: analog, DomainStats.time_create)
COMPILE_SECONDS = "domain.compile.seconds"
#: degradation-ladder rung build (trace/compile) seconds
LADDER_BUILD_SECONDS = "resilience.ladder.build_seconds"
#: wall seconds per checkpoint commit (gather + write + fsync + rename)
CHECKPOINT_SAVE_SECONDS = "checkpoint.save.seconds"
#: wall seconds per checkpoint restore (load + verify + re-scatter)
CHECKPOINT_RESTORE_SECONDS = "checkpoint.restore.seconds"
#: wall seconds per in-memory mesh transition (plan + collective schedule
#: + exchange re-realize + tuner re-key — ``DistributedDomain.reshard``)
RESHARD_SECONDS = "reshard.seconds"
#: wall seconds per fused numerics snapshot (dispatch + the scalar
#: readback — the "cheap enough to leave on" figure bench.py's
#: numerics_overhead A/B regression-gates)
NUMERICS_SNAPSHOT_SECONDS = "numerics.snapshot.seconds"
#: end-to-end wall seconds per served request (enqueue -> response; the
#: fleet-wide series — per-tenant p50/p95/p99 live in each tenant's own
#: Statistics and surface through the heartbeat tenant table)
SERVE_LATENCY_SECONDS = "serve.latency.seconds"
#: wall seconds per AOT executable compile at admission (serve/aot.py —
#: the cost the admission budget bounds)
SERVE_COMPILE_SECONDS = "serve.compile.seconds"
#: requests carried per BATCHED dispatch (serve/pack.py — geometry-matched
#: groups stacked along a leading batch axis into one dispatch)
SERVE_BATCH_SIZE = "serve.batch.size"
#: tenants packed per sub-slice dispatch cycle (disjoint sub-meshes of
#: the fleet executing concurrently)
SERVE_SUBSLICE_COUNT = "serve.subslice.count"
#: measured point-to-point link bandwidth over the realized mesh, GB/s per
#: probed neighbor edge (telemetry/fabric.py — the NVML-distance-matrix
#: analog feeding the comms roofline)
FABRIC_LINK_GBPS = "fabric.link.gbps"
#: wall seconds per fabric-probe sweep (warm-up + all measured rounds)
FABRIC_PROBE_SECONDS = "fabric.probe.seconds"

ALL_HISTOGRAMS = frozenset({
    STEP_SECONDS,
    EXCHANGE_SECONDS,
    SWAP_SECONDS,
    COMPILE_SECONDS,
    LADDER_BUILD_SECONDS,
    CHECKPOINT_SAVE_SECONDS,
    CHECKPOINT_RESTORE_SECONDS,
    RESHARD_SECONDS,
    NUMERICS_SNAPSHOT_SECONDS,
    SERVE_LATENCY_SECONDS,
    SERVE_COMPILE_SECONDS,
    SERVE_BATCH_SIZE,
    SERVE_SUBSLICE_COUNT,
    FABRIC_LINK_GBPS,
    FABRIC_PROBE_SECONDS,
})

# --- spans (Chrome-trace timeline entries) -----------------------------------

SPAN_STEP = "domain.step"
SPAN_EXCHANGE = "domain.exchange"
SPAN_SWAP = "domain.swap"
#: the split-step schedule's two halves (ops/stream.py overlap=split).  These
#: are DEVICE-timeline spans: the split macro enters them as
#: ``telemetry.annotate`` named scopes, so they label the interior stream
#: pass / exterior band passes in compiled HLO metadata and XProf profiles —
#: the tier-1/tier-2 overlap proofs key on the interior scope name.
SPAN_OVERLAP_INTERIOR = "step.overlap.interior"
SPAN_OVERLAP_EXTERIOR = "step.overlap.exterior"
#: the redistribution collective schedule (parallel/redistribute.py): a
#: named scope entered around the per-round slice/permute/blend body, so
#: device-time attribution can price a live mesh transition
SPAN_RESHARD = "reshard.collective"
#: the halo-exchange ppermutes, one DEVICE-timeline scope per (mesh axis,
#: receive direction) — ops/exchange.py enters these around every
#: ``lax.ppermute`` so profiler traces attribute collective-permute device
#: time per link (``exchange.z.low`` receives the -1 z-neighbor's shell)
SPAN_EXCHANGE_X_LOW = "exchange.x.low"
SPAN_EXCHANGE_X_HIGH = "exchange.x.high"
SPAN_EXCHANGE_Y_LOW = "exchange.y.low"
SPAN_EXCHANGE_Y_HIGH = "exchange.y.high"
SPAN_EXCHANGE_Z_LOW = "exchange.z.low"
SPAN_EXCHANGE_Z_HIGH = "exchange.z.high"

#: the direction span for one (mesh axis, receive side)
EXCHANGE_DIRECTION_SPANS = {
    ("x", "low"): SPAN_EXCHANGE_X_LOW,
    ("x", "high"): SPAN_EXCHANGE_X_HIGH,
    ("y", "low"): SPAN_EXCHANGE_Y_LOW,
    ("y", "high"): SPAN_EXCHANGE_Y_HIGH,
    ("z", "low"): SPAN_EXCHANGE_Z_LOW,
    ("z", "high"): SPAN_EXCHANGE_Z_HIGH,
}


def exchange_direction_span(axis: str, side: str) -> str:
    """The registered span name for one exchange hop (axis in x/y/z, side in
    low/high).  In-kernel scopes must come through here (or the constants
    above) so the span registry stays the single name authority."""
    try:
        return EXCHANGE_DIRECTION_SPANS[(axis, side)]
    except KeyError:
        raise ValueError(f"no exchange direction span for {axis!r}/{side!r}") from None


ALL_SPANS = frozenset({
    SPAN_STEP,
    SPAN_EXCHANGE,
    SPAN_SWAP,
    SPAN_OVERLAP_INTERIOR,
    SPAN_OVERLAP_EXTERIOR,
    SPAN_RESHARD,
    SPAN_EXCHANGE_X_LOW,
    SPAN_EXCHANGE_X_HIGH,
    SPAN_EXCHANGE_Y_LOW,
    SPAN_EXCHANGE_Y_HIGH,
    SPAN_EXCHANGE_Z_LOW,
    SPAN_EXCHANGE_Z_HIGH,
})

# --- structured events (JSONL sink) ------------------------------------------

#: a compile happened (fields: phase, label, seconds)
EVENT_COMPILE = "domain.compile"
#: a transient failure is being retried (fields: label, attempt,
#: max_retries, delay_s, error)
EVENT_RETRY = "resilience.retry"
#: the retry budget ran out (fields: label, max_retries, error)
EVENT_RETRY_EXHAUSTED = "resilience.retry_exhausted"
#: a retry was refused by the donated-buffer guard (fields: label, error)
EVENT_RETRY_REFUSED = "resilience.retry_refused"
#: a ladder descent (fields: label, from_rung, to_rung, failure_class)
EVENT_DESCENT = "resilience.descent"
#: a STENCIL_FAULT_PLAN fault fired (fields: phase, label, failure_class)
EVENT_FAULT = "resilience.fault_injected"
#: the divergence sentinel tripped (fields: quantity, step, window =
#: [last clean check, detection step], coord = global first-non-finite
#: cell or null — telemetry/numerics.py feeds all three on-device)
EVENT_DIVERGENCE = "resilience.divergence"
#: a tuning decision (fields: key, source=cache|search|static, config,
#: trials, pruned)
EVENT_TUNE_DECISION = "tune.decision"
#: one autotuner trial finished (fields: key, candidate, seconds_per_iter —
#: or failure_class/error when the candidate was pruned)
EVENT_TUNE_TRIAL = "tune.trial"
#: the exchange planner resolved its z-sweep route (fields: route,
#: source=explicit|env|tuned|static|ladder — or "<orig>/degraded" when a
#: packed pick structurally could not engage)
EVENT_EXCHANGE_ROUTE = "exchange.route"
#: a stream-engine step build resolved its overlap schedule (fields:
#: overlap=off|split, source=explicit|env|tuned|static|ladder or
#: "<orig>/degraded" on a structural step-down, route, m)
EVENT_STEP_OVERLAP = "step.overlap"
#: a stream-engine step build resolved its halo consumption mode (fields:
#: halo=array|fused, source=explicit|env|tuned|static|ladder or
#: "<orig>/degraded" on a structural step-down, route, m, exchange_route)
EVENT_STEP_HALO = "step.halo"
#: a kernel build resolved its compute-unit axis (fields:
#: unit=vpu|mxu|mxu_band, source=explicit|env|tuned|static|ladder or
#: "<orig>/degraded" when a structural guard stepped an mxu request down,
#: where)
EVENT_KERNEL_COMPUTE_UNIT = "kernel.compute_unit"
#: a kernel build resolved its MXU input-precision axis (fields:
#: input=f32|bf16, source — same vocabulary as kernel.compute_unit plus
#: "<orig>/degraded" when the resolved unit has no contraction to feed,
#: unit, where)
EVENT_KERNEL_MXU_INPUT = "kernel.mxu_input"
#: a model build resolved its storage-dtype axis (fields:
#: storage=native|bf16, source — same vocabulary as kernel.compute_unit,
#: where)
EVENT_KERNEL_STORAGE_DTYPE = "kernel.storage_dtype"
#: a checkpoint committed (fields: path, step, backend, bytes, seconds,
#: reason=cadence|final|preempt)
EVENT_CHECKPOINT_SAVE = "checkpoint.save"
#: a checkpoint restored (fields: path, step, backend, elastic, seconds)
EVENT_CHECKPOINT_RESTORE = "checkpoint.restore"
#: a checkpoint failed validation and the ring fell back past it (fields:
#: path, why)
EVENT_CHECKPOINT_FALLBACK = "checkpoint.fallback"
#: the supervisor restarted from the last valid checkpoint (fields: label,
#: step, restart, budget, failure_class, error)
EVENT_SUPERVISOR_RESTART = "supervisor.restart"
#: the watchdog saw a dispatch exceed its deadline (fields: phase,
#: deadline_s, abort)
EVENT_WATCHDOG_STALL = "watchdog.stall"
#: a cadence device-profile capture finished (fields: dir, index,
#: seconds — telemetry/device.py)
EVENT_PROFILE_CAPTURE = "profile.capture"
#: an in-memory mesh transition completed (fields: from_mesh, to_mesh,
#: seconds, bytes, quantities, source=request|capacity_loss|operator)
EVENT_RESHARD = "reshard.transition"
#: a capacity change fell back to checkpoint-elastic-restore (fields:
#: from_mesh, to_mesh, why, step) — charged against the restart budget
EVENT_RESHARD_FALLBACK = "reshard.fallback"
#: sustained healthy progress restored one restart credit (fields: label,
#: step, window, credits_used — STENCIL_RESTART_WINDOW)
EVENT_SUPERVISOR_REPLENISH = "supervisor.replenish"
#: an admission decision (fields: tenant, admitted, why, queue_depth,
#: compile_s when a cold key compiled at admission)
EVENT_SERVE_ADMISSION = "serve.admission"
#: queued load was shed (fields: tenant, why=deadline|priority|injected,
#: queue_depth, waited_s)
EVENT_SERVE_SHED = "serve.load_shed"
#: the per-tenant envelope quarantined/evicted a tenant (fields: tenant,
#: failure_class, why)
EVENT_SERVE_EVICTION = "serve.eviction"
#: the load policy asked for capacity (fields: kind=grow|shrink,
#: queue_depth, source)
EVENT_SERVE_ELASTICITY = "serve.elasticity"
#: a fabric-probe sweep resolved its link matrix (fields: source=cache|probe,
#: topology, chip, edges, seconds, slowest_gbps — telemetry/fabric.py)
EVENT_FABRIC_PROBE = "fabric.probe"

ALL_EVENTS = frozenset({
    EVENT_COMPILE,
    EVENT_RETRY,
    EVENT_RETRY_EXHAUSTED,
    EVENT_RETRY_REFUSED,
    EVENT_DESCENT,
    EVENT_FAULT,
    EVENT_DIVERGENCE,
    EVENT_TUNE_DECISION,
    EVENT_TUNE_TRIAL,
    EVENT_EXCHANGE_ROUTE,
    EVENT_STEP_OVERLAP,
    EVENT_STEP_HALO,
    EVENT_KERNEL_COMPUTE_UNIT,
    EVENT_KERNEL_MXU_INPUT,
    EVENT_KERNEL_STORAGE_DTYPE,
    EVENT_CHECKPOINT_SAVE,
    EVENT_CHECKPOINT_RESTORE,
    EVENT_CHECKPOINT_FALLBACK,
    EVENT_SUPERVISOR_RESTART,
    EVENT_WATCHDOG_STALL,
    EVENT_PROFILE_CAPTURE,
    EVENT_RESHARD,
    EVENT_RESHARD_FALLBACK,
    EVENT_SUPERVISOR_REPLENISH,
    EVENT_SERVE_ADMISSION,
    EVENT_SERVE_SHED,
    EVENT_SERVE_EVICTION,
    EVENT_SERVE_ELASTICITY,
    EVENT_FABRIC_PROBE,
    NUMERICS_DRIFT,
})

#: every registered name, any kind — what the lint checks literals against
ALL_NAMES = ALL_COUNTERS | ALL_GAUGES | ALL_HISTOGRAMS | ALL_SPANS | ALL_EVENTS
