"""On-device numerics observatory: fused field-health statistics.

The PR-1 divergence sentinel answered "did a field go NaN?" by gathering
every quantity to the host per check — a full device→host interior copy per
quantity, and an answer that names only a quantity and a cadence step.  T3
(PAPERS.md, arxiv 2401.16677) sets the production bar instead: numerical
health as always-on, fine-grained telemetry whose overhead is low enough to
leave enabled.  This module is that layer:

* :class:`NumericsEngine` builds ONE fused, jitted, sharded program per
  realized domain that computes, per floating quantity, interior-only
  min / max / absmax / mean / L2 (accumulated at >= f32, the PR-7
  f32-accumulate contract) / non-finite count **and the global 3D
  coordinate of the first non-finite cell** — all reduced across the mesh
  with ``psum``/``pmin``/``pmax`` INSIDE the shard_map, so the host
  transfer is O(#quantities) scalars.  Never a gather: the
  ``numerics-bounded`` program contract (``analysis/contracts.py``)
  machine-checks that claim on the canonical matrix.
* The program is memoized per geometry signature (mesh, spec, per-quantity
  ``(components, dtype)`` — the same signature discipline as
  ``DistributedDomain.reshard``'s redistribute-fn cache) and rebuilt
  automatically after a mesh transition (``on_mesh_change``).
* Snapshots land in a bounded in-memory ring (crash reports embed it) and
  run the registered **guardbands** — per-quantity invariants over the
  stats (shipped examples: the jacobi max-principle bound, the astaroth
  magnitude envelope).  Violations emit ``numerics.drift`` events + the
  counter; observe-only by default, ``STENCIL_NUMERICS_ABORT=1`` escalates
  to a classified ``DIVERGENCE``.

Knobs (validated reads): ``STENCIL_NUMERICS_EVERY`` (snapshot cadence in
raw steps through ``run_step``; 0 = off; ``--numerics-every`` on the model
drivers), ``STENCIL_NUMERICS_ABORT`` (guardband escalation).  The
divergence sentinel (``resilience/sentinel.py``) rides the same engine on
its own cadence — a ``DIVERGENCE`` failure now names the quantity, the
global first-non-finite coordinate, and the bracketing step window.

jax-free at import, like the whole telemetry package (the ``jax-import``
lint rule): jax is touched only when a program is actually built.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

#: snapshots retained in the in-memory ring (crash reports embed the tail;
#: a ring, not a log — long runs must stay O(1) in memory)
RING_SIZE = 16

#: scalar outputs the stats program emits per floating quantity (min, max,
#: absmax, sum, sumsq, finite count, non-finite count, first-bad key) —
#: the numerics-bounded contract bounds the traced program's output count
#: by this
SCALARS_PER_QUANTITY = 8


def _finite_or_none(v) -> Optional[float]:
    """JSON-safe float: non-finite (empty-field inf sentinels, NaN means
    from zero finite cells) becomes None rather than poisoning a document."""
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


@dataclasses.dataclass(frozen=True)
class FieldStats:
    """One quantity's interior-only health at a snapshot.  Moment stats
    (``min``/``max``/``absmax``/``mean``/``l2``) are over FINITE interior
    cells (None when none are finite); the non-finite story is carried
    separately by ``nonfinite`` and ``first_nonfinite`` (the global 3D
    coordinate of the first non-finite cell in row-major order, or None)."""

    name: str
    dtype: str
    min: Optional[float]
    max: Optional[float]
    absmax: Optional[float]
    mean: Optional[float]
    l2: Optional[float]
    finite: int
    nonfinite: int
    first_nonfinite: Optional[Tuple[int, int, int]]

    def as_json(self) -> dict:
        d = dataclasses.asdict(self)
        if self.first_nonfinite is not None:
            d["first_nonfinite"] = list(self.first_nonfinite)
        return d


@dataclasses.dataclass(frozen=True)
class NumericsSnapshot:
    """One fused-dispatch health snapshot of every floating quantity."""

    step: Optional[int]
    window: Optional[Tuple[int, int]]
    ts: float
    seconds: float
    stats: Tuple[FieldStats, ...]

    def stat(self, name: str) -> Optional[FieldStats]:
        for s in self.stats:
            if s.name == name:
                return s
        return None

    def as_json(self) -> dict:
        return {
            "step": self.step,
            "window": list(self.window) if self.window is not None else None,
            "ts": self.ts,
            "seconds": round(self.seconds, 6),
            "quantities": {s.name: s.as_json() for s in self.stats},
        }


@dataclasses.dataclass(frozen=True)
class Guardband:
    """A registered invariant over one snapshot's per-quantity stats.

    ``check(stats)`` returns a violation message (the drift event's
    ``why``) or None; ``quantities`` scopes it (None = every floating
    quantity).  Guardbands see FieldStats, never arrays — they run on the
    O(#quantities) host scalars, so a registered band costs nothing on
    device."""

    label: str
    check: Callable[[FieldStats], Optional[str]]
    quantities: Optional[Tuple[str, ...]] = None

    def applies_to(self, name: str) -> bool:
        return self.quantities is None or name in self.quantities


def max_principle(lo: float, hi: float, quantities: Optional[Sequence[str]] = None) -> Guardband:
    """The diffusion max principle: a pure-averaging update (jacobi's
    mean-of-6 with clamped forcing) can never leave the initial value
    band — a cell outside ``[lo, hi]`` is numerical drift, long before
    anything overflows to inf."""

    def check(st: FieldStats) -> Optional[str]:
        if st.min is not None and st.min < lo:
            return f"min {st.min:g} below the max-principle bound {lo:g}"
        if st.max is not None and st.max > hi:
            return f"max {st.max:g} above the max-principle bound {hi:g}"
        return None

    return Guardband(
        label=f"max-principle[{lo:g},{hi:g}]",
        check=check,
        quantities=tuple(quantities) if quantities is not None else None,
    )


def magnitude_envelope(limit: float, quantities: Optional[Sequence[str]] = None) -> Guardband:
    """A per-quantity magnitude envelope: |field| must stay under
    ``limit`` (the astaroth proxy's averaging update is non-expansive on
    its unit-amplitude sin init, so a growing absmax means the numerics
    drifted)."""

    def check(st: FieldStats) -> Optional[str]:
        if st.absmax is not None and st.absmax > limit:
            return f"absmax {st.absmax:g} outside the magnitude envelope {limit:g}"
        return None

    return Guardband(
        label=f"magnitude-envelope[{limit:g}]",
        check=check,
        quantities=tuple(quantities) if quantities is not None else None,
    )


def _is_floating(dtype) -> bool:
    import numpy as np

    return np.issubdtype(np.dtype(dtype), np.inexact)


class NumericsEngine:
    """Per-domain on-device field-statistics engine (module docstring).

    Bound to a realized :class:`~stencil_tpu.domain.DistributedDomain`;
    hand one out via ``dd.numerics()``.  The fused stats program is built
    lazily on first snapshot and memoized on the domain's geometry
    signature, so a reshard/re-realize transparently rebuilds it (the
    supervisor's ``on_mesh_change`` hook also invalidates eagerly)."""

    def __init__(self, dd, every: int = 0):
        if every < 0:
            raise ValueError(f"numerics cadence must be >= 0, got {every}")
        self.dd = dd
        self.every = int(every)
        self.steps_done = 0
        self.ring = collections.deque(maxlen=RING_SIZE)
        self._guardbands: List[Guardband] = []
        self._fn = None
        self._names: List[str] = []
        self._sig = None

    # --- cadence --------------------------------------------------------------

    def set_every(self, every: int) -> None:
        """Change the snapshot cadence WITHOUT resetting the accumulated
        step count (the same mid-run contract as the sentinel's
        ``set_every``)."""
        if every < 0:
            raise ValueError(f"numerics cadence must be >= 0, got {every}")
        self.every = int(every)

    def after_steps(self, steps: int) -> Optional[NumericsSnapshot]:
        """Account ``steps`` raw iterations just run; snapshot on cadence
        crossings.  With ``every == 0`` this is pure bookkeeping."""
        before = self.steps_done
        self.steps_done += steps
        if not self.every:
            return None
        if before // self.every == self.steps_done // self.every:
            return None
        last = self.last
        if last is not None and last.step == self.steps_done:
            # the sentinel (or a direct caller) already snapshotted this
            # exact step through the same engine — one dispatch serves both
            return last
        return self.snapshot(step=self.steps_done, window=(before, self.steps_done))

    @property
    def last(self) -> Optional[NumericsSnapshot]:
        return self.ring[-1] if self.ring else None

    def last_as_json(self) -> Optional[dict]:
        last = self.last
        return last.as_json() if last is not None else None

    def ring_as_json(self) -> List[dict]:
        return [s.as_json() for s in self.ring]

    # --- guardbands -----------------------------------------------------------

    def register_guardband(self, band: Guardband) -> None:
        """Register (or replace, by label — model rebuilds re-register
        idempotently) one invariant guardband."""
        self._guardbands = [g for g in self._guardbands if g.label != band.label]
        self._guardbands.append(band)

    def guardbands(self) -> Tuple[Guardband, ...]:
        return tuple(self._guardbands)

    def _check_guardbands(self, snap: NumericsSnapshot) -> None:
        from stencil_tpu import telemetry
        from stencil_tpu.telemetry import names as tm
        from stencil_tpu.utils.config import env_bool

        abort = env_bool("STENCIL_NUMERICS_ABORT", False)
        for st in snap.stats:
            for band in self._guardbands:
                if not band.applies_to(st.name):
                    continue
                why = band.check(st)
                if why is None:
                    continue
                telemetry.inc(tm.NUMERICS_DRIFT)
                telemetry.emit_event(
                    tm.NUMERICS_DRIFT,
                    quantity=st.name,
                    guardband=band.label,
                    why=why,
                    step=snap.step,
                    window=list(snap.window) if snap.window else None,
                    abort=abort,
                )
                if abort:
                    from stencil_tpu.resilience.taxonomy import DivergenceError

                    raise DivergenceError(
                        quantity=st.name,
                        step=snap.step,
                        window=snap.window,
                        why=f"guardband {band.label}: {why} "
                        "(STENCIL_NUMERICS_ABORT=1)",
                    )

    # --- the fused stats program ----------------------------------------------

    def _signature(self):
        """Geometry + quantity signature the memoized program is keyed on
        — anything that changes the traced program's shapes, sharding, or
        masking.  A reshard changes the mesh/spec/devices; add_data is
        pre-realize only."""
        dd = self.dd
        dim = dd.placement.dim()
        n = dd._spec.sz
        lo = dd._shell_radius.lo()
        return (
            (dim.x, dim.y, dim.z),
            (n.x, n.y, n.z),
            (lo.x, lo.y, lo.z),
            tuple(dd._valid_last),
            tuple(d.id for d in dd.mesh.devices.flat),
            tuple(
                (h.name, tuple(h.components), str(dd.field_dtype(h)))
                for h in dd._handles
            ),
        )

    def on_mesh_change(self) -> None:
        """Invalidate the memoized program (the supervisor's reshard hook;
        the signature check would also catch it lazily)."""
        self._fn = None
        self._sig = None

    def program(self):
        """``(fn, example_args, names)`` — the fused jitted stats program
        over the floating quantities (in ``names`` order), its example
        inputs (the live buffers), and the quantity names.  Exposed so the
        ``numerics-bounded`` contract can trace exactly the program
        ``snapshot`` dispatches."""
        assert self.dd._realized, "numerics needs a realized domain"
        sig = self._signature()
        if self._fn is None or self._sig != sig:
            self._fn, self._names = self._build()
            self._sig = sig
        args = tuple(self.dd._curr[k] for k in self._names)
        return self._fn, args, list(self._names)

    def _build(self):
        """Build the fused sharded stats program for the CURRENT geometry.

        One shard_map over every floating quantity: per shard the interior
        block is masked to its VALID cells (uneven pad-and-mask shards
        contribute only real cells), moment stats accumulate at >= f32
        (bf16/f32 upcast to f32, f64 stays f64 — the PR-7 contract), and
        everything reduces across the mesh in-program (psum/pmin/pmax), so
        each output is one replicated scalar.  The first-non-finite cell
        reduces as a global row-major linear index (pmin of per-shard
        winners; shard-local row-major order IS global row-major order
        within a shard, so the local argmax of the bad-mask is the shard's
        globally-first bad cell).
        """
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax

        from stencil_tpu.domain import _qspec
        from stencil_tpu.parallel.mesh import MESH_AXES
        from stencil_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        dd = self.dd
        handles = [h for h in dd._handles if _is_floating(h.dtype)]
        names = [h.name for h in handles]
        if not handles:
            return (lambda *args: ()), names
        dim = dd.placement.dim()
        n = dd._spec.sz
        lo = dd._shell_radius.lo()
        size = dd._size
        valid_last = dd._valid_last
        # the global row-major linear index must be exact: int32 covers
        # ~1290^3 cells; larger domains need the x64 mode this container's
        # tests run under (jnp would silently truncate an int64 request)
        total_cells = size.x * size.y * size.z
        if jax.config.jax_enable_x64:
            idx_dtype = jnp.int64
        else:
            idx_dtype = jnp.int32
            if total_cells >= np.iinfo(np.int32).max:
                from stencil_tpu.utils.logging import log_warn

                log_warn(
                    "numerics: first-non-finite index needs int64 for "
                    f"{total_cells} cells but jax x64 is disabled; the "
                    "reported coordinate may wrap on this domain"
                )
        sentinel = int(np.iinfo(np.dtype(idx_dtype)).max)

        def acc_dtype(h):
            # >= f32 accumulation: f64 fields keep f64, everything else
            # (f32 storage, bf16 storage) accumulates at f32
            return jnp.float64 if jnp.dtype(h.dtype) == jnp.float64 else jnp.float32

        def axis_valid(ax, idx):
            v = valid_last[ax]
            if v is None:
                return n[ax]
            return jnp.where(idx == dim[ax] - 1, v, n[ax])

        def per_shard(*blocks):
            idxs = [lax.axis_index(MESH_AXES[ax]) for ax in range(3)]
            # per-axis validity masks (pad-and-mask: the last shard on a
            # padded axis owns fewer valid cells)
            masks = [
                jnp.arange(n[ax]) < axis_valid(ax, idxs[ax]) for ax in range(3)
            ]
            mask3 = (
                masks[0][:, None, None]
                & masks[1][None, :, None]
                & masks[2][None, None, :]
            )
            outs = []
            for h, block in zip(handles, blocks):
                acc = acc_dtype(h)
                interior = block[
                    ...,
                    lo.x : lo.x + n.x,
                    lo.y : lo.y + n.y,
                    lo.z : lo.z + n.z,
                ].astype(acc)
                mask = jnp.broadcast_to(mask3, interior.shape)
                finite = jnp.isfinite(interior) & mask
                inf = jnp.asarray(jnp.inf, acc)
                mn = lax.pmin(
                    jnp.min(jnp.where(finite, interior, inf)), MESH_AXES
                )
                mx = lax.pmax(
                    jnp.max(jnp.where(finite, interior, -inf)), MESH_AXES
                )
                am = lax.pmax(
                    jnp.max(jnp.where(finite, jnp.abs(interior), 0.0)),
                    MESH_AXES,
                )
                zero = jnp.asarray(0.0, acc)
                s = lax.psum(
                    jnp.sum(jnp.where(finite, interior, zero)), MESH_AXES
                )
                s2 = lax.psum(
                    jnp.sum(jnp.where(finite, interior * interior, zero)),
                    MESH_AXES,
                )
                nf = lax.psum(
                    jnp.sum(finite.astype(idx_dtype)), MESH_AXES
                )
                bad = mask & ~jnp.isfinite(interior)
                nbad = lax.psum(jnp.sum(bad.astype(idx_dtype)), MESH_AXES)
                # first bad cell: collapse component dims, then the local
                # row-major argmax (first True) is this shard's globally
                # first bad cell — encode as a global linear index, pmin
                bad_cell = bad
                while bad_cell.ndim > 3:
                    bad_cell = jnp.any(bad_cell, axis=0)
                flat = bad_cell.reshape(-1)
                local = jnp.argmax(flat).astype(idx_dtype)
                has = jnp.any(flat)
                ly_z = jnp.asarray(n.y * n.z, idx_dtype)
                lz = jnp.asarray(n.z, idx_dtype)
                gx = idxs[0] * n.x + local // ly_z
                gy = idxs[1] * n.y + (local // lz) % n.y
                gz = idxs[2] * n.z + local % n.z
                key = (
                    gx.astype(idx_dtype) * (size.y * size.z)
                    + gy.astype(idx_dtype) * size.z
                    + gz.astype(idx_dtype)
                )
                key = jnp.where(has, key, jnp.asarray(sentinel, idx_dtype))
                key = lax.pmin(key, MESH_AXES)
                outs.extend([mn, mx, am, s, s2, nf, nbad, key])
            return tuple(outs)

        specs = tuple(_qspec(h) for h in handles)
        out_specs = tuple(P() for _ in range(SCALARS_PER_QUANTITY * len(handles)))
        fn = jax.jit(
            shard_map(
                per_shard,
                mesh=dd.mesh,
                in_specs=specs,
                out_specs=out_specs,
            )
        )
        return fn, names

    # --- snapshots ------------------------------------------------------------

    def snapshot(
        self, step: Optional[int] = None, window: Optional[Tuple[int, int]] = None
    ) -> NumericsSnapshot:
        """Take one fused on-device health snapshot: ONE sharded dispatch,
        O(#quantities) scalars to the host, appended to the ring; then the
        registered guardbands run over the host scalars (observe-only by
        default — ``STENCIL_NUMERICS_ABORT=1`` escalates a violation to a
        classified ``DIVERGENCE``)."""
        import numpy as np

        from stencil_tpu import telemetry
        from stencil_tpu.telemetry import names as tm

        t0 = time.perf_counter()
        fn, args, names = self.program()
        raw = [np.asarray(v) for v in fn(*args)]  # the O(#q)-scalar transfer
        dd = self.dd
        size = dd._size
        stats = []
        k = SCALARS_PER_QUANTITY
        handles = {h.name: h for h in dd._handles}
        for i, name in enumerate(names):
            mn, mx, am, s, s2, nf, nbad, key = raw[i * k : (i + 1) * k]
            nf = int(nf)
            nbad = int(nbad)
            key = int(key)
            coord = None
            if nbad and 0 <= key < size.x * size.y * size.z:
                coord = (
                    key // (size.y * size.z),
                    (key // size.z) % size.y,
                    key % size.z,
                )
            mean = float(s) / nf if nf else None
            l2 = math.sqrt(float(s2)) if nf else None
            stats.append(
                FieldStats(
                    name=name,
                    dtype=np.dtype(handles[name].dtype).name,
                    min=_finite_or_none(mn),
                    max=_finite_or_none(mx),
                    absmax=_finite_or_none(am),
                    mean=_finite_or_none(mean),
                    l2=_finite_or_none(l2),
                    finite=nf,
                    nonfinite=nbad,
                    first_nonfinite=coord,
                )
            )
        dt = time.perf_counter() - t0
        snap = NumericsSnapshot(
            step=step, window=window, ts=time.time(), seconds=dt,
            stats=tuple(stats),
        )
        self.ring.append(snap)
        telemetry.inc(tm.NUMERICS_SNAPSHOTS)
        telemetry.observe(tm.NUMERICS_SNAPSHOT_SECONDS, dt)
        self._check_guardbands(snap)
        return snap
