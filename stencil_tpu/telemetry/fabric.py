"""Fabric observatory: measured point-to-point interconnect model.

The TPU analog of the reference's NVML link-distance matrix: instead of
asking the driver how GPUs are wired, we MEASURE every realized neighbor
hop of the mesh with a single-edge ``lax.ppermute`` sweep and persist the
result as a per-link bandwidth matrix.  Consumers:

* ``scripts/perf_report.py`` — joins the probed link model against the
  per-direction exchange attribution into a comms roofline (achieved vs
  probed GB/s per mesh axis per direction, bottleneck named).
* heartbeat / ``python -m stencil_tpu.status`` — the fabric matrix and the
  slowest-link callout render in the live status surface.
* future placement/tuner consumers — ``link_model(mesh)`` exposes the
  per-axis/per-direction aggregate without re-probing.

Probe protocol (the repo's one timing discipline, ``tune/trial.py``):
every unique ordered neighbor pair gets a jitted single-pair ppermute
over a flat ``"d"``-axis mesh; all edges are warmed, then measured under
``measure_alternating`` — ``reps + 1`` alternating rounds with the rep-0
post-idle burst discarded and the host round trip subtracted — and each
edge reports the median sample.  An optional second sweep at a small
payload (``lat_nbytes``) reports per-edge latency.

Persistence mirrors ``tune/cache.py`` exactly: one stamped JSON per
``(topology, chip, payload)`` key under ``STENCIL_FABRIC_CACHE`` (default
``~/.cache/stencil_tpu/fabric``), schema + jax/jaxlib toolchain checked on
load, corrupt/stale files are a MISS (warn/info, never crash), stores go
through the atomic write-rename.  A warm ``ensure(mesh)`` does zero device
work.

jax-free at import time (``jax-import`` lint rule): jax enters only inside
the probe path.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, List, Optional, Tuple

from stencil_tpu.telemetry import names
from stencil_tpu.utils.config import env_str

#: bump when the persisted-link vocabulary changes incompatibly; a schema
#: mismatch is a MISS (stale matrices re-probe, never crash).  History:
#: 1 — per-edge gbps links + NxN matrix (the fabric-observatory PR).
SCHEMA = 1

_DEFAULT_DIR = os.path.join("~", ".cache", "stencil_tpu", "fabric")

#: default probe payload per shard (bytes); large enough that a tunneled
#: host round trip does not dominate, small enough to stay off the HBM
#: high-water mark of a running job
DEFAULT_NBYTES = 8 << 20

#: process-local override (driver --fabric-cache); None = env/default
_dir_override: Optional[str] = None


def set_dir_override(path: Optional[str]) -> None:
    global _dir_override
    _dir_override = path


def cache_dir() -> str:
    path = _dir_override or env_str("STENCIL_FABRIC_CACHE", _DEFAULT_DIR)
    return os.path.abspath(os.path.expanduser(path))


def _toolchain() -> Tuple[str, str]:
    import jax

    try:
        import jaxlib

        jaxlib_v = getattr(jaxlib, "__version__", "")
    except Exception:  # noqa: BLE001 — jaxlib layout varies across builds
        jaxlib_v = ""
    return jax.__version__, jaxlib_v


def probe_key(
    topology: Tuple[int, ...], chip: str, nbytes: int, lat_nbytes: Optional[int]
) -> dict:
    """The identity a persisted matrix is keyed by.  Payload sizes are part
    of the key: bandwidth at 8 MiB and at 4 KiB are different facts."""
    return {
        "topology": list(topology),
        "chip": chip,
        "nbytes": int(nbytes),
        "lat_nbytes": None if lat_nbytes is None else int(lat_nbytes),
    }


def key_digest(key: dict) -> str:
    canon = json.dumps(key, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


def path_for(key: dict) -> str:
    return os.path.join(cache_dir(), f"{key_digest(key)}.json")


def load(key: dict) -> Optional[dict]:
    """The persisted probe doc for ``key``, or None on a miss (absent,
    corrupt, or persisted by a different toolchain/schema)."""
    path = path_for(key)
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        from stencil_tpu.utils.logging import log_warn

        log_warn(f"fabric cache {path} is unreadable ({e}); treating as a miss")
        return None
    jax_v, jaxlib_v = _toolchain()
    if (
        not isinstance(doc, dict)
        or doc.get("schema") != SCHEMA
        or doc.get("jax") != jax_v
        or doc.get("jaxlib") != jaxlib_v
        or not isinstance(doc.get("links"), list)
    ):
        from stencil_tpu.utils.logging import log_info

        log_info(
            f"fabric cache {path} is stale (schema/toolchain mismatch); "
            "link models must be re-probed on this toolchain — treating as a miss"
        )
        return None
    return doc


def store(doc: dict) -> str:
    """Persist a probe doc atomically (utils/artifact.py write-rename: a
    crashed probe must not leave a truncated matrix a later run half-parses)."""
    from stencil_tpu.utils.artifact import atomic_write_json

    key = probe_key(
        tuple(doc["topology"]), doc["chip"], doc["nbytes"], doc.get("lat_nbytes")
    )
    return atomic_write_json(path_for(key), doc)


# --- hop enumeration ----------------------------------------------------------


def neighbor_links(shape: Dict[str, int]) -> List[dict]:
    """Every (mesh axis, side, src, dst) hop of a torus mesh, as FLAT device
    indices (C-order over the mesh grid — the index space the flat ``"d"``
    probe mesh and the persisted matrix share).

    Direction naming matches ``ops/exchange.py``: side ``low`` is the link a
    shard RECEIVES its -1 neighbor's slab on (data moves +, so the ordered
    pair is ``i -> i+1``); side ``high`` receives from the +1 neighbor
    (``i+1 -> i``).  Axes of size 1 contribute nothing (a self-wrap is the
    periodic boundary inside one chip, not fabric traffic).  On size-2 axes
    the low and high hop sets coincide as ordered pairs — ``probe`` dedupes
    the measurements, not the attribution rows.
    """
    axes = list(shape)
    sizes = [shape[a] for a in axes]
    strides = [1] * len(axes)
    for i in range(len(axes) - 2, -1, -1):
        strides[i] = strides[i + 1] * sizes[i + 1]

    def flat(coord) -> int:
        return sum(c * s for c, s in zip(coord, strides))

    def coords():
        out = [()]
        for n in sizes:
            out = [c + (i,) for c in out for i in range(n)]
        return out

    links = []
    for ai, axis in enumerate(axes):
        n = sizes[ai]
        if n < 2:
            continue
        for c in coords():
            up = list(c)
            up[ai] = (c[ai] + 1) % n
            # low: every shard receives from its -1 neighbor -> c sends up
            links.append(
                {"axis": axis, "side": "low", "src": flat(c), "dst": flat(tuple(up))}
            )
            # high: every shard receives from its +1 neighbor -> up sends to c
            links.append(
                {"axis": axis, "side": "high", "src": flat(tuple(up)), "dst": flat(c)}
            )
    return links


# --- the probe ----------------------------------------------------------------


def _edge_run(flat_mesh, n_dev: int, src: int, dst: int, n_elems: int):
    """``run(k)``: k chained synchronous dispatches of a jitted single-pair
    ``ppermute`` src->dst (the point-to-point primitive, one compile per
    static edge — ``bin/_common.make_edge_transfer`` reimplemented here so
    telemetry/ never imports the driver layer)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from stencil_tpu.utils.compat import shard_map

    @jax.jit
    def go(x):
        def f(blk):
            return lax.ppermute(blk, "d", [(src, dst)])

        return shard_map(f, mesh=flat_mesh, in_specs=P("d"), out_specs=P("d"))(x)

    x = jax.device_put(
        jnp.ones((n_elems * n_dev,), jnp.float32), NamedSharding(flat_mesh, P("d"))
    )

    def run(k: int) -> None:
        y = x
        for _ in range(k):
            y = go(y)
        jax.block_until_ready(y)

    return run


def _host_round_trip_s() -> float:
    """One device->host readback latency (subtracted from edge timings —
    ``bench.py``'s discipline for tunneled dev backends)."""
    import jax.numpy as jnp

    x = jnp.zeros((8,))
    float(jnp.sum(x))
    t0 = time.perf_counter()
    for _ in range(5):
        float(jnp.sum(x))
    return (time.perf_counter() - t0) / 5


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _sweep_edges(
    flat_mesh, n_dev: int, edges: List[Tuple[int, int]], nbytes: int,
    reps: int, inner: int, rt: float,
) -> Dict[Tuple[int, int], float]:
    """Median seconds per ``(src, dst)`` edge at ``nbytes`` per shard, under
    the alternating rep-0-drop protocol (``tune/trial.measure_alternating``)."""
    from stencil_tpu.tune.trial import measure_alternating

    n_elems = max(1, nbytes // 4)
    runs = [_edge_run(flat_mesh, n_dev, s, d, n_elems) for s, d in edges]
    for run in runs:  # compile + warm OUTSIDE the timed rounds
        run(1)
    samples = measure_alternating(runs, inner, rt, reps)
    return {
        edge: max(_median(samples[i]), 1e-9) for i, edge in enumerate(edges)
    }


def probe(
    mesh,
    nbytes: int = DEFAULT_NBYTES,
    lat_nbytes: Optional[int] = None,
    reps: int = 3,
    inner: int = 1,
) -> dict:
    """Measure every neighbor hop of ``mesh`` and return the stamped probe
    doc (``bench: fabric_probe``).  Does NOT consult or write the cache —
    ``ensure`` is the load-or-probe entry."""
    import jax
    from jax.sharding import Mesh

    from stencil_tpu import telemetry
    from stencil_tpu.tune.key import chip_kind

    devices = mesh.devices.flatten()
    n_dev = len(devices)
    shape = dict(mesh.shape)
    topology = tuple(shape[a] for a in mesh.axis_names)
    links = neighbor_links(shape)
    edges = sorted({(l["src"], l["dst"]) for l in links})

    t_start = time.perf_counter()
    flat_mesh = Mesh(devices, ("d",))
    bw = lat = {}
    if edges:
        rt = _host_round_trip_s()
        bw = _sweep_edges(flat_mesh, n_dev, edges, nbytes, reps, inner, rt)
        if lat_nbytes is not None:
            lat = _sweep_edges(flat_mesh, n_dev, edges, lat_nbytes, reps, inner, rt)
        telemetry.inc(names.FABRIC_PROBE_RUNS, len(edges))
    seconds = time.perf_counter() - t_start

    matrix = [[0.0] * n_dev for _ in range(n_dev)]
    out_links = []
    for l in links:
        sec = bw[(l["src"], l["dst"])]
        gbps = nbytes / sec / 1e9
        entry = dict(l, gbps=round(gbps, 3))
        if lat:
            entry["latency_us"] = round(lat[(l["src"], l["dst"])] * 1e6, 3)
        out_links.append(entry)
        matrix[l["src"]][l["dst"]] = round(gbps, 3)
        telemetry.observe(names.FABRIC_LINK_GBPS, gbps)
    if edges:
        telemetry.observe(names.FABRIC_PROBE_SECONDS, seconds)

    jax_v, jaxlib_v = _toolchain()
    return {
        "schema": SCHEMA,
        "bench": "fabric_probe",
        "jax": jax_v,
        "jaxlib": jaxlib_v,
        "chip": chip_kind(),
        "topology": list(topology),
        "axes": list(mesh.axis_names),
        "n_devices": n_dev,
        "nbytes": int(nbytes),
        "lat_nbytes": None if lat_nbytes is None else int(lat_nbytes),
        "ts": time.time(),
        "protocol": {"reps": reps, "inner": inner, "edges": len(edges)},
        "seconds": round(seconds, 6),
        "links": out_links,
        "matrix": matrix,
    }


def ensure(
    mesh,
    nbytes: int = DEFAULT_NBYTES,
    lat_nbytes: Optional[int] = None,
    reps: int = 3,
    inner: int = 1,
    force: bool = False,
) -> dict:
    """Load-or-probe: the cached matrix for this (topology, chip, payload)
    when the stamp matches — ZERO device work on a warm cache — else one
    probe sweep, persisted for every later run."""
    from stencil_tpu import telemetry
    from stencil_tpu.tune.key import chip_kind

    shape = dict(mesh.shape)
    topology = tuple(shape[a] for a in mesh.axis_names)
    key = probe_key(topology, chip_kind(), nbytes, lat_nbytes)
    doc = None if force else load(key)
    if doc is not None:
        telemetry.inc(names.FABRIC_CACHE_HIT)
        _emit(doc, source="cache")
        return doc
    telemetry.inc(names.FABRIC_CACHE_MISS)
    doc = probe(mesh, nbytes=nbytes, lat_nbytes=lat_nbytes, reps=reps, inner=inner)
    store(doc)
    _emit(doc, source="probe")
    return doc


def _emit(doc: dict, source: str) -> None:
    from stencil_tpu import telemetry

    slowest = link_model(doc).get("slowest") or {}
    telemetry.emit_event(
        names.EVENT_FABRIC_PROBE,
        source=source,
        topology=doc["topology"],
        chip=doc["chip"],
        edges=doc["protocol"]["edges"],
        seconds=doc["seconds"],
        slowest_gbps=slowest.get("gbps"),
    )


# --- derived views ------------------------------------------------------------


def link_model(doc_or_mesh, **ensure_kwargs) -> dict:
    """Per-mesh-axis/per-direction aggregate of a probe doc — the shape
    placement and tuner consumers key on.  Accepts a probe doc, or a Mesh
    (which goes through ``ensure``: a cold cache PROBES).

    Returns ``{"axes": {axis: {side: {"gbps_min", "gbps_med", "links"}}},
    "slowest": {axis, side, gbps, src, dst} | None}``.
    """
    doc = (
        doc_or_mesh
        if isinstance(doc_or_mesh, dict)
        else ensure(doc_or_mesh, **ensure_kwargs)
    )
    axes: Dict[str, dict] = {}
    slowest = None
    for l in doc.get("links", []):
        side = axes.setdefault(l["axis"], {}).setdefault(
            l["side"], {"gbps_min": None, "gbps_med": None, "_gbps": [], "links": 0}
        )
        side["_gbps"].append(l["gbps"])
        side["links"] += 1
        if slowest is None or l["gbps"] < slowest["gbps"]:
            slowest = {k: l[k] for k in ("axis", "side", "gbps", "src", "dst")}
    for per_side in axes.values():
        for side in per_side.values():
            gs = side.pop("_gbps")
            side["gbps_min"] = min(gs)
            side["gbps_med"] = round(_median(gs), 3)
    return {"axes": axes, "slowest": slowest}


def summary(doc: dict) -> dict:
    """Compact JSON-safe fabric state for the heartbeat's ``fabric`` key
    (status.json stays small; the full matrix lives in the artifact)."""
    model = link_model(doc)
    return {
        "topology": doc["topology"],
        "chip": doc["chip"],
        "nbytes": doc["nbytes"],
        "axes": {
            axis: {side: s["gbps_med"] for side, s in per_side.items()}
            for axis, per_side in model["axes"].items()
        },
        "slowest": model["slowest"],
        "matrix": doc["matrix"],
    }
