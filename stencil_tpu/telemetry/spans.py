"""Nestable wall-clock span tracer + Chrome trace-event dump.

Subsumes ``utils/profiling.py``: ``annotate`` (the NVTX-range analog —
``jax.named_scope`` labels the region in compiled HLO and XProf timelines)
and ``trace`` (a ``jax.profiler`` capture) live here now, alongside the
host-side span recorder.

Spans record (name, start, duration, thread, parent, args) tuples that
``chrome_trace_events`` renders as Chrome trace-event JSON — complete
("ph":"X") events with microsecond timestamps — viewable in
``chrome://tracing`` or https://ui.perfetto.dev.  Timestamps are
``time.perf_counter`` offsets from the recorder's epoch: monotonic and
mutually consistent, which is all the trace viewers need.

jax is touched ONLY if it is already imported (``sys.modules`` probe, the
same fail-closed rule as ``logging._rank``): recording a span must never
pull in — let alone initialize — a jax backend.
"""

from __future__ import annotations

import contextlib
import sys
import threading
import time
from typing import List, Optional


def annotate(name: str):
    """Label a region in traces and HLO (the NVTX range analog)."""
    import jax

    return jax.named_scope(name)


@contextlib.contextmanager
def trace(log_dir: Optional[str]):
    """Capture a ``jax.profiler`` trace into ``log_dir`` (no-op when None).
    View with TensorBoard's profile plugin / xprof."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


def _maybe_named_scope(name: str):
    """``jax.named_scope`` when jax is ALREADY imported, else a null context
    — a span must never import jax on behalf of the caller."""
    jax = sys.modules.get("jax")
    if jax is None:
        return contextlib.nullcontext()
    return jax.named_scope(name)


class SpanRecorder:
    """Thread-safe recorder of completed spans with a per-thread name stack
    (so a span knows its parent at record time)."""

    def __init__(self):
        self.epoch = time.perf_counter()
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._tls = threading.local()

    # --- the per-thread nesting stack ----------------------------------------
    def _stack(self) -> list:
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = self._tls.stack = []
        return s

    def current(self) -> Optional[str]:
        s = self._stack()
        return s[-1] if s else None

    def push(self, name: str) -> None:
        self._stack().append(name)

    def pop(self) -> None:
        s = self._stack()
        if s:
            s.pop()

    # --- recording ------------------------------------------------------------
    def record(self, name: str, t0: float, dur: float, parent=None, **args) -> None:
        """Record a completed span.  ``t0`` is a ``time.perf_counter`` value;
        ``dur`` is seconds."""
        if parent is None:
            parent = self.current()
        ev = {
            "name": name,
            "ts": (t0 - self.epoch) * 1e6,  # µs, trace-event convention
            "dur": dur * 1e6,
            "tid": threading.get_ident() & 0xFFFF,
            "args": dict(args, parent=parent) if parent else dict(args),
        }
        with self._lock:
            self._events.append(ev)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def chrome_trace_events(self, pid: int = 0) -> List[dict]:
        """The recorded spans as Chrome trace-event dicts (complete events)."""
        return [
            {
                "name": e["name"],
                "ph": "X",
                "ts": e["ts"],
                "dur": e["dur"],
                "pid": pid,
                "tid": e["tid"],
                "args": e["args"],
            }
            for e in self.events()
        ]
