"""Nestable wall-clock span tracer + Chrome trace-event dump.

Subsumes ``utils/profiling.py``: ``annotate`` (the NVTX-range analog —
``jax.named_scope`` labels the region in compiled HLO and XProf timelines)
and ``trace`` (a ``jax.profiler`` capture) live here now, alongside the
host-side span recorder.

Spans record (name, start, duration, thread, parent, args) tuples that
``chrome_trace_events`` renders as Chrome trace-event JSON — complete
("ph":"X") events with microsecond timestamps — viewable in
``chrome://tracing`` or https://ui.perfetto.dev.  Timestamps are
``time.perf_counter`` offsets from the recorder's epoch: monotonic and
mutually consistent, which is all the trace viewers need.

jax is touched ONLY if it is already imported (``sys.modules`` probe, the
same fail-closed rule as ``logging._rank``): recording a span must never
pull in — let alone initialize — a jax backend.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
from typing import List, Optional

#: warn once per process when the profiler backend is absent — a CPU dryrun
#: container must run a profiled command line unchanged, just without traces
_trace_unavailable_warned = False


def annotate(name: str):
    """Label a region in traces and HLO (the NVTX range analog)."""
    import jax

    return jax.named_scope(name)


@contextlib.contextmanager
def trace(log_dir: Optional[str]):
    """Capture a ``jax.profiler`` trace into ``log_dir`` (no-op when None).
    View with TensorBoard's profile plugin / xprof.

    Degrades gracefully: the directory is created up front (a capture that
    dies mid-run must still leave the dir its tooling expects), and a
    backend with no profiler support (CPU dryrun containers, tunneled dev
    backends) WARNS once and runs the body unprofiled — a profiling knob
    must never crash the run it was meant to observe."""
    if not log_dir:
        yield
        return
    os.makedirs(log_dir, exist_ok=True)
    import jax

    global _trace_unavailable_warned
    ctx = None
    try:
        ctx = jax.profiler.trace(log_dir)
        ctx.__enter__()
    except Exception as e:  # noqa: BLE001 — degrade, never crash the run
        ctx = None
        if not _trace_unavailable_warned:
            _trace_unavailable_warned = True
            from stencil_tpu.utils.logging import log_warn

            log_warn(
                f"jax.profiler unavailable on this backend ({e!r}); "
                f"running unprofiled — {log_dir} will hold no trace"
            )
    try:
        yield
    finally:
        if ctx is not None:
            try:
                ctx.__exit__(None, None, None)
            except Exception as e:  # noqa: BLE001 — a failed trace FINALIZE
                # (profiler died mid-capture) must not eat the run's result
                from stencil_tpu.utils.logging import log_warn

                log_warn(f"jax.profiler trace finalize failed: {e!r}")


def _maybe_named_scope(name: str):
    """``jax.named_scope`` when jax is ALREADY imported, else a null context
    — a span must never import jax on behalf of the caller."""
    jax = sys.modules.get("jax")
    if jax is None:
        return contextlib.nullcontext()
    return jax.named_scope(name)


class SpanRecorder:
    """Thread-safe recorder of completed spans with a per-thread name stack
    (so a span knows its parent at record time)."""

    def __init__(self):
        self.epoch = time.perf_counter()
        self._lock = threading.Lock()
        self._events: List[dict] = []
        #: (ts_us, series, value) counter samples — rendered as Chrome
        #: counter-track ("ph":"C") events so Perfetto shows cumulative
        #: exchange bytes / MXU flops as a throughput track under the spans
        self._counter_samples: List[tuple] = []
        self._counter_last: dict = {}
        self._tls = threading.local()

    # --- the per-thread nesting stack ----------------------------------------
    def _stack(self) -> list:
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = self._tls.stack = []
        return s

    def current(self) -> Optional[str]:
        s = self._stack()
        return s[-1] if s else None

    def push(self, name: str) -> None:
        self._stack().append(name)

    def pop(self) -> None:
        s = self._stack()
        if s:
            s.pop()

    # --- recording ------------------------------------------------------------
    def record(self, name: str, t0: float, dur: float, parent=None, **args) -> None:
        """Record a completed span.  ``t0`` is a ``time.perf_counter`` value;
        ``dur`` is seconds."""
        if parent is None:
            parent = self.current()
        ev = {
            "name": name,
            "ts": (t0 - self.epoch) * 1e6,  # µs, trace-event convention
            "dur": dur * 1e6,
            "tid": threading.get_ident() & 0xFFFF,
            "args": dict(args, parent=parent) if parent else dict(args),
        }
        with self._lock:
            self._events.append(ev)

    def sample_counter(self, name: str, value: float, t: float = None) -> None:
        """Record one counter-track sample at ``t`` (a ``perf_counter``
        value; now when None).  Consecutive identical values are dropped —
        a flat counter contributes one point, not one per span."""
        if t is None:
            t = time.perf_counter()
        ts = (t - self.epoch) * 1e6
        with self._lock:
            if self._counter_last.get(name) == value:
                return
            self._counter_last[name] = value
            self._counter_samples.append((ts, name, value))

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def counter_samples(self) -> List[tuple]:
        with self._lock:
            return list(self._counter_samples)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._counter_samples.clear()
            self._counter_last.clear()

    def chrome_trace_events(self, pid: int = 0) -> List[dict]:
        """The recorded spans as Chrome trace-event dicts (complete events),
        followed by the counter-track samples ("ph":"C" — Perfetto renders
        each series as a value track alongside the spans)."""
        out = [
            {
                "name": e["name"],
                "ph": "X",
                "ts": e["ts"],
                "dur": e["dur"],
                "pid": pid,
                "tid": e["tid"],
                "args": e["args"],
            }
            for e in self.events()
        ]
        out.extend(
            {
                "name": name,
                "ph": "C",
                "ts": ts,
                "pid": pid,
                "args": {"value": value},
            }
            for ts, name, value in self.counter_samples()
        )
        return out
