"""Run flight recorder: a rank-0 heartbeat status file plus a crash report
built from the bounded in-memory event ring.

A supervised long run (resilience/supervisor.py) is opaque from outside the
process: the checkpoint ring says where it COULD resume, not whether it is
alive, how fast it is going, or what it last complained about.  The flight
recorder closes that gap with two artifacts in the run directory, both
written through ``utils/artifact`` (atomic; a kill mid-write leaves the
previous readable state):

* ``status.json`` — the heartbeat, rewritten per chunk: step/total, the
  steady-state rate over a sliding window, checkpoint age, watchdog state,
  ladder rung, restart count, last classified error, and a ``phase``
  (``running`` / ``completed`` / ``preempted`` / a failure class).  A
  reader that finds a stale ``ts`` knows the process died without a word —
  that silence is itself the signal.
* ``crash_report.json`` — dumped on any FATAL/STALL/PREEMPTED (or
  otherwise propagating) exit: the classified cause, the error text, the
  final status, and the last-N telemetry events from the always-live ring
  (``telemetry.recent_events`` — captured even when no JSONL sink was
  configured, exactly like the counters).

``python -m stencil_tpu.status <dir>`` renders either, live or
post-mortem.  Only rank 0 writes (every rank sees the same supervisor
state); jax-free, like everything in this package — the crash path runs
while jax may be mid-failure.
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import Optional

from stencil_tpu.utils.logging import _rank, log_warn


def _write_json(path: str, doc: dict) -> str:
    """Atomic JSON write with ``default=str``: ring events and caller
    ``state`` may hold non-JSON values (numpy scalars, paths) — the same
    tolerance the JSONL sink applies — and these writes run on exit paths
    where a serialization error would mask the real failure."""
    from stencil_tpu.utils.artifact import atomic_write

    with atomic_write(path) as f:
        json.dump(doc, f, indent=2, sort_keys=True, default=str)
        f.write("\n")
    return path

STATUS_FILE = "status.json"
CRASH_FILE = "crash_report.json"

#: heartbeats kept for the sliding steady-state rate window
_RATE_WINDOW = 32

#: events included in a crash report (the ring retains more; a report
#: wants the readable tail, not the whole flight)
CRASH_EVENT_TAIL = 64


class FlightRecorder:
    """Heartbeat + crash-report writer for one supervised run."""

    def __init__(self, dir: str, label: str = "run"):
        self.dir = str(dir)
        self.label = label
        self._window = collections.deque(maxlen=_RATE_WINDOW)
        self._last_status: dict = {}
        #: sticky extras merged under EVERY heartbeat (per-beat ``state``
        #: wins on key collisions) — run-scoped facts a caller establishes
        #: once, like the probed fabric link model (``status.py`` renders
        #: a ``fabric`` key as the matrix + slowest-link callout)
        self.state: dict = {}

    @property
    def status_path(self) -> str:
        return os.path.join(self.dir, STATUS_FILE)

    @property
    def crash_path(self) -> str:
        return os.path.join(self.dir, CRASH_FILE)

    def _rate(self, step: int) -> Optional[float]:
        """Steady-state steps/s over the heartbeat window (None until two
        beats have landed).  A step that moved BACKWARD (the supervisor
        restored an earlier checkpoint) resets the window — pre-restart
        beats would otherwise report None/understated rates for the whole
        post-restart window, exactly when an operator is looking."""
        now = time.monotonic()
        if self._window and step < self._window[-1][1]:
            self._window.clear()
        self._window.append((now, step))
        (t0, s0), (t1, s1) = self._window[0], self._window[-1]
        if t1 <= t0:
            return None
        return (s1 - s0) / (t1 - t0)

    def heartbeat(
        self,
        step: int,
        total_steps: Optional[int] = None,
        phase: str = "running",
        **state,
    ) -> Optional[str]:
        """Atomically rewrite ``status.json`` (rank 0 only; other ranks
        no-op).  ``state`` carries the caller's extras — checkpoint age,
        watchdog state, ladder rung, restarts, last error.  Never raises:
        a full disk must not kill the run it was observing."""
        if _rank() != 0:
            return None
        doc = {
            "ts": time.time(),
            "pid": os.getpid(),
            "label": self.label,
            "phase": phase,
            "step": int(step),
            "total_steps": total_steps,
            "rate_steps_per_s": self._rate(int(step)),
        }
        doc.update(self.state)
        doc.update(state)
        self._last_status = doc
        try:
            return _write_json(self.status_path, doc)
        except Exception as e:  # noqa: BLE001 — the never-raise contract:
            # a heartbeat must not kill the run it observes
            log_warn(f"{self.label}: heartbeat write failed ({e}); continuing")
            return None

    def crash_report(
        self, cause: str, error: Optional[str] = None, **state
    ) -> Optional[str]:
        """Dump ``crash_report.json``: the classified cause, error text,
        final status, metric counters, and the last-N telemetry events
        from the in-memory ring.  Rank 0 only; never raises — this runs on
        exit paths where a second failure would mask the first."""
        if _rank() != 0:
            return None
        from stencil_tpu import telemetry

        doc = {
            "ts": time.time(),
            "pid": os.getpid(),
            "label": self.label,
            "cause": cause,
            "error": (error or "")[:2000] or None,
            "status": dict(self._last_status) or None,
            "counters": telemetry.snapshot().get("counters", {}),
            "events": telemetry.recent_events(CRASH_EVENT_TAIL),
        }
        doc.update(state)
        try:
            return _write_json(self.crash_path, doc)
        except Exception as e:  # noqa: BLE001 — this runs inside exception
            # handlers; a second failure here would MASK the classified one
            log_warn(f"{self.label}: crash report write failed ({e})")
            return None


def read_status(dir: str) -> Optional[dict]:
    """The heartbeat document under ``dir`` (None when absent/corrupt —
    atomic writes make corrupt mean 'never written')."""
    return _read_json(os.path.join(dir, STATUS_FILE))


def read_crash_report(dir: str) -> Optional[dict]:
    return _read_json(os.path.join(dir, CRASH_FILE))


def _read_json(path: str) -> Optional[dict]:
    import json

    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
