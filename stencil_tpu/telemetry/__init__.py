"""Unified telemetry: metrics registry, span tracer, structured event log.

The measurement substrate under every perf claim this repo makes: the
reference instruments every phase with NVTX ranges and reports Statistics
CSVs per benchmark (src/stencil.cu:672-861, bin/statistics.hpp); here the
same visibility is one process-local facade:

* **metrics** (``metrics.py``) — counters / gauges / histograms, histograms
  backed by ``utils/statistics.Statistics`` (trimean and friends for free).
  ``snapshot()`` returns the JSON-safe dict ``bench.py`` embeds in the
  BENCH artifact and every ``bin/`` driver writes via ``--metrics-out``.
* **spans** (``spans.py``) — nestable wall-clock spans dumped as Chrome
  trace-event JSON (``chrome://tracing`` / Perfetto); also home of the
  ``annotate``/``trace`` jax wrappers that used to live in
  ``utils/profiling.py``.
* **events** (``events.py``) — rank-tagged JSONL event log for the signals
  a program must consume (retries, ladder descents, divergence trips).

Knobs (validated reads — ``utils/config.py`` pattern):

* ``STENCIL_TELEMETRY=1|0``     — master switch (default: on iff a dir is set)
* ``STENCIL_TELEMETRY_DIR=D``   — output dir for events + traces; implies on
* ``STENCIL_TELEMETRY_EVENTS``  — JSONL sink on/off (default: on iff dir set)

Design rules (enforced here, asserted by tests):

* **zero-cost when disabled** — ``span()`` yields immediately, ``observe``/
  ``emit_event`` return after one attribute check, no formatting happens.
  Counters/gauges stay live always (an int add; a post-mortem ``snapshot()``
  after a failed run still counts its retries).
* **never initialize a jax backend** — rank tags use the fail-closed
  ``logging._rank`` probe; spans enter ``jax.named_scope`` only when jax is
  already imported.
* **no free-string names** — call sites name series through
  ``telemetry.names`` constants; the ``telemetry-name`` rule of
  ``stencil_tpu.lint`` enforces it (and ``jax-import`` enforces the
  backend-free contract above).
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import time
from typing import List, Optional

from stencil_tpu.telemetry import names  # noqa: F401  (re-export)
from stencil_tpu.telemetry.events import EventSink
from stencil_tpu.telemetry.metrics import MetricsRegistry
from stencil_tpu.telemetry.spans import (  # noqa: F401  (annotate/trace re-export)
    SpanRecorder,
    _maybe_named_scope,
    annotate,
    trace,
)
from stencil_tpu.utils.logging import _rank


#: events kept in the in-memory flight ring (the crash-report tail)
RING_SIZE = 256

#: counters sampled onto Chrome counter tracks at every span record — the
#: cumulative series whose slope IS the throughput Perfetto shows next to
#: the spans (exchange/packed bytes, MXU flops)
_TRACK_COUNTERS = (
    names.EXCHANGE_BYTES,
    names.EXCHANGE_PACKED_BYTES,
    names.KERNEL_MXU_FLOPS,
)


class _Telemetry:
    """Process-local singleton state (module functions below delegate)."""

    def __init__(self):
        self.registry = MetricsRegistry()
        self.spans = SpanRecorder()
        self.sink: Optional[EventSink] = None
        self.enabled = False
        self.out_dir: Optional[str] = None
        self._configured = False
        #: bounded flight ring of the last events — ALWAYS live (one deque
        #: append; the caller already built the fields dict), because the
        #: runs whose last events matter most are the ones that die with
        #: telemetry off.  Dumped by the flight recorder's crash report.
        self.ring = collections.deque(maxlen=RING_SIZE)

    def configure_from_env(self) -> None:
        from stencil_tpu.utils.config import env_bool, env_str

        out_dir = env_str("STENCIL_TELEMETRY_DIR", None)
        enabled = env_bool("STENCIL_TELEMETRY", out_dir is not None)
        events = env_bool("STENCIL_TELEMETRY_EVENTS", out_dir is not None)
        if events and out_dir is None and "STENCIL_TELEMETRY_EVENTS" in os.environ:
            # an explicit EVENTS=1 with nowhere to write is a config error
            # even when the master switch is off — the user asked for a JSONL
            # log they would silently never get
            raise ValueError(
                "STENCIL_TELEMETRY_EVENTS=1 needs STENCIL_TELEMETRY_DIR to "
                "point at a writable directory (events are a JSONL file; "
                "set the dir or unset STENCIL_TELEMETRY_EVENTS)"
            )
        self.enabled = enabled
        self.out_dir = out_dir
        self.sink = EventSink(out_dir) if (enabled and events and out_dir) else None
        self._configured = True


_t = _Telemetry()


def _cfg() -> _Telemetry:
    if not _t._configured:
        _t.configure_from_env()
    return _t


# --- lifecycle ---------------------------------------------------------------


def enabled() -> bool:
    return _cfg().enabled


def enable(dir: Optional[str] = None, events: Optional[bool] = None) -> None:
    """Programmatic enable (tests, driver ``--metrics-out``).  ``dir`` adds
    the JSONL event sink and gives Chrome-trace dumps a default home;
    without it, spans/histograms record in memory only."""
    t = _t
    t._configured = True
    t.enabled = True
    if dir is not None:
        t.out_dir = str(dir)
        os.makedirs(t.out_dir, exist_ok=True)
    if events is None:
        events = t.out_dir is not None
    if events and t.out_dir is None:
        raise ValueError("telemetry events need a directory (enable(dir=...))")
    if t.sink is not None:
        t.sink.close()
    t.sink = EventSink(t.out_dir) if events else None


def disable() -> None:
    t = _t
    t._configured = True
    t.enabled = False
    if t.sink is not None:
        t.sink.close()
        t.sink = None
    t.out_dir = None


def reset() -> None:
    """Clear all recorded metrics, spans, and the event ring (counters
    restart at 0)."""
    t = _cfg()
    t.registry.reset()
    t.spans.clear()
    t.ring.clear()


# --- metrics -----------------------------------------------------------------


def inc(name: str, value: int = 1) -> None:
    """Increment a counter.  Always live (a dict hit + int add)."""
    _cfg().registry.counter(name).inc(value)


def set_gauge(name: str, value: float) -> None:
    _cfg().registry.gauge(name).set(value)


def observe(name: str, value: float) -> None:
    """Record one histogram sample — only while telemetry is enabled, so a
    disabled hot loop never touches the Statistics list."""
    t = _cfg()
    if t.enabled:
        t.registry.histogram(name).observe(value)


def snapshot() -> dict:
    """JSON-safe dict of all metrics.  Every canonical counter name appears
    (0 when untouched) and every canonical histogram appears (empty
    distribution when never observed) so snapshots diff cleanly across
    rounds."""
    return _cfg().registry.snapshot(
        seed_counters=names.ALL_COUNTERS,
        seed_histograms=names.ALL_HISTOGRAMS,
    )


# --- spans -------------------------------------------------------------------


@contextlib.contextmanager
def span(name: str, histogram: Optional[str] = None, **args):
    """Nestable wall-clock span.  When disabled: an immediate yield, nothing
    recorded.  When enabled: records a Chrome-trace event (nested under the
    enclosing span), optionally observes the duration into ``histogram``,
    and labels the region in HLO/XProf if jax is already up."""
    t = _cfg()
    if not t.enabled:
        yield
        return
    parent = t.spans.current()
    t.spans.push(name)
    t0 = time.perf_counter()
    try:
        with _maybe_named_scope(name):
            yield
    finally:
        dur = time.perf_counter() - t0
        t.spans.pop()
        t.spans.record(name, t0, dur, parent=parent, **args)
        _sample_track_counters(t, t0 + dur)
        if histogram is not None:
            t.registry.histogram(histogram).observe(dur)


def record_span(
    name: str, t0: float, dur: float, histogram: Optional[str] = None, **args
) -> None:
    """Post-hoc span record for call sites that already timed themselves
    (``t0`` from ``time.perf_counter``, ``dur`` seconds).  No-op disabled."""
    t = _cfg()
    if not t.enabled:
        return
    t.spans.record(name, t0, dur, **args)
    _sample_track_counters(t, t0 + dur)
    if histogram is not None:
        t.registry.histogram(histogram).observe(dur)


def _sample_track_counters(t: _Telemetry, at: float) -> None:
    """Sample the cumulative track counters onto the Chrome counter tracks
    at span-record time (``at`` is a ``perf_counter`` value).  Three dict
    hits per recorded span; identical consecutive values are dropped by the
    recorder, so quiet series cost one event total."""
    for name in _TRACK_COUNTERS:
        t.spans.sample_counter(name, t.registry.counter(name).value, at)


def dump_chrome_trace(path: Optional[str] = None) -> Optional[str]:
    """Write the recorded spans as Chrome trace-event JSON; returns the path
    (None when there is nothing to write or nowhere to put it).  Open in
    chrome://tracing or https://ui.perfetto.dev."""
    t = _cfg()
    events = t.spans.chrome_trace_events(pid=_rank())
    if not events:
        return None
    if path is None:
        if t.out_dir is None:
            return None
        path = os.path.join(t.out_dir, f"trace_{_rank()}.json")
    from stencil_tpu.utils.artifact import atomic_write

    with atomic_write(path) as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return path


# --- events ------------------------------------------------------------------


def emit_event(name: str, **fields) -> None:
    """Append one structured JSONL event.  The JSONL sink runs only while
    enabled AND a sink directory is configured — guarded before any
    formatting happens.  The in-memory flight ring records ALWAYS (one
    deque append of the dict the caller already built): like the counters,
    the last events before a crash must survive telemetry being off —
    the flight recorder dumps them as the crash report
    (docs/observability.md "Flight recorder")."""
    t = _cfg()
    t.ring.append({"ts": time.time(), "event": name, **fields})
    if t.enabled and t.sink is not None:
        t.sink.emit(name, fields)


def recent_events(n: Optional[int] = None) -> List[dict]:
    """The last ``n`` (default: all retained) events from the bounded
    in-memory flight ring, oldest first — the post-mortem tail a crash
    report captures even when no JSONL sink was configured."""
    ring = _cfg().ring
    out = list(ring)
    if n is not None:
        out = out[-n:]
    return out


def event_log_path() -> Optional[str]:
    t = _cfg()
    return t.sink.path() if t.sink is not None else None


def dump_metrics(path: Optional[str] = None) -> Optional[str]:
    """Write the metrics snapshot as JSON; returns the path (None when
    nowhere to put it).  Default home: ``metrics_<rank>.json`` next to the
    trace/events, which makes a telemetry dir self-contained for
    ``scripts/perf_report.py`` (the roofline join needs the analytic
    counters AND the trace from the same run)."""
    t = _cfg()
    if path is None:
        if t.out_dir is None:
            return None
        path = os.path.join(t.out_dir, f"metrics_{_rank()}.json")
    from stencil_tpu.utils.artifact import atomic_write_json

    return atomic_write_json(path, snapshot())


def write_artifacts() -> dict:
    """Flush end-of-run artifacts (the Chrome trace and metrics snapshot;
    events stream live).  Returns ``{"trace": ..., "events": ...,
    "metrics": ...}`` (path or None each)."""
    return {
        "trace": dump_chrome_trace(),
        "events": event_log_path(),
        "metrics": dump_metrics(),
    }
