"""Paraview CSV point dumps.

Parity target: ``DistributedDomain::write_paraview`` (reference
src/stencil.cu:866-939): one ``<prefix>_<id>.txt`` per subdomain with header
``Z,Y,X,<q0>,<q1>...`` and one row per interior point, z-major, coordinates in
global space, ``%f``-formatted values, NaNs optionally zeroed.
"""

from __future__ import annotations

import numpy as np

from stencil_tpu.core.dim3 import Dim3


def write_paraview(dd, prefix: str, zero_nans: bool = True) -> None:
    """One file per subdomain, matching the reference's id and row layout."""
    dim = dd.placement.dim()
    n = dd.local_spec().sz
    names = [h.name or f"data{i}" for i, h in enumerate(dd._handles)]
    fields = {h.name: dd.quantity_to_host(h) for h in dd._handles}

    for i in range(dim.flatten()):
        idx = dd.placement.partition.idx(i)
        origin = Dim3(idx.x * n.x, idx.y * n.y, idx.z * n.z)
        path = f"{prefix}_{i}.txt"
        with open(path, "w") as f:
            f.write("Z,Y,X" + "".join(f",{c}" for c in names) + "\n")
            for lz in range(n.z):
                for ly in range(n.y):
                    for lx in range(n.x):
                        pos = origin + Dim3(lx, ly, lz)
                        row = f"{pos.z},{pos.y},{pos.x}"
                        for h in dd._handles:
                            val = float(fields[h.name][pos.x, pos.y, pos.z])
                            if zero_nans and np.isnan(val):
                                val = 0.0
                            row += f",{val:f}"
                        f.write(row + "\n")
