"""Paraview CSV point dumps.

Parity target: ``DistributedDomain::write_paraview`` (reference
src/stencil.cu:866-939): one ``<prefix>_<id>.txt`` per subdomain with header
``Z,Y,X,<q0>,<q1>...`` and one row per interior point, z-major, coordinates in
global space, ``%f``-formatted values, NaNs optionally zeroed.
"""

from __future__ import annotations

import numpy as np

from stencil_tpu.core.dim3 import Dim3
from stencil_tpu.utils.artifact import atomic_write


def write_paraview(dd, prefix: str, zero_nans: bool = True) -> None:
    """One file per subdomain, matching the reference's id and row layout."""
    dim = dd.placement.dim()
    n = dd.local_spec().sz
    # N-D quantities dump one column per component: "v" (3,) -> v_0,v_1,v_2
    names = []
    for i, h in enumerate(dd._handles):
        base = h.name or f"data{i}"
        if h.components:
            names += [
                base + "_" + "_".join(map(str, c)) for c in np.ndindex(*h.components)
            ]
        else:
            names.append(base)
    fields = {h.name: dd.quantity_to_host(h) for h in dd._handles}

    for i in range(dim.flatten()):
        idx = dd.placement.partition.idx(i)
        origin = Dim3(idx.x * n.x, idx.y * n.y, idx.z * n.z)
        # uneven (padded) meshes: the trailing shard on a padded axis owns
        # fewer VALID cells than the padded shard size n — dump only those
        # (the reference's subdomains are exactly-sized, src/stencil.cu:884)
        v = dd.shard_valid(idx)
        path = f"{prefix}_{i}.txt"
        # z-major row order, built vectorized (a Python per-cell loop is
        # unusable at the drivers' default 512^3)
        zz, yy, xx = np.meshgrid(
            np.arange(origin.z, origin.z + v.z),
            np.arange(origin.y, origin.y + v.y),
            np.arange(origin.x, origin.x + v.x),
            indexing="ij",
        )
        cols = [zz.ravel(), yy.ravel(), xx.ravel()]
        for h in dd._handles:
            field = fields[h.name]
            comps = list(np.ndindex(*h.components)) if h.components else [()]
            for c in comps:
                block = field[c][
                    origin.x : origin.x + v.x,
                    origin.y : origin.y + v.y,
                    origin.z : origin.z + v.z,
                ]
                vals = np.transpose(block, (2, 1, 0)).ravel().astype(np.float64)
                if zero_nans:
                    # zero NaN only; keep +-inf verbatim (divergence visible)
                    vals = np.nan_to_num(vals, nan=0.0, posinf=np.inf, neginf=-np.inf)
                cols.append(vals)
        table = np.column_stack(cols)
        header = "Z,Y,X" + "".join(f",{c}" for c in names)
        fmt = ["%d", "%d", "%d"] + ["%f"] * len(names)
        # atomic per-file: a dump interrupted by preemption must not leave a
        # truncated CSV next to complete ones (the artifact-write contract —
        # np.savetxt's own open() would)
        with atomic_write(path) as f:
            np.savetxt(f, table, fmt=fmt, delimiter=",", header=header, comments="")
