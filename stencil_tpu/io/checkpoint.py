"""Checkpoint / resume of a DistributedDomain — the long-run survival layer.

The reference has NO restore path (SURVEY.md §5: paraview dumps only); this
module is the deliberate improvement called out there, hardened for
preemption-tolerant long runs (docs/resilience.md "Long-run operation"):

* **Atomic commit** — every checkpoint is staged into a temp directory next
  to its destination (state first, fsync'd; the versioned ``MANIFEST.json``
  last) and renamed into place in one step.  A kill at ANY byte leaves
  either the previous checkpoint or no checkpoint at that path — never a
  half-written directory a later resume would half-parse.  The manifest is
  the commit marker: a directory without one is, by construction, an
  interrupted save.
* **Versioned manifest with per-array digests** — ``MANIFEST.json`` carries
  a schema number, the domain geometry at save time, the full run state
  (step counter, ``storage_dtype``/``compute_unit`` axes, tuned decisions in
  effect — whatever the caller passes), and one sha256 per quantity over the
  PORTABLE interior representation (interior cells at the native dtype —
  bf16-stored fields upcast exactly per the PR-7 f32-accumulate contract).
  Restores verify the digests on the LOADED data before installing it;
  a mismatch raises a classified :class:`CheckpointCorruptError`.
* **Retention ring** — ``save_to_ring`` keeps the last N checkpoints under
  step-numbered directories (``ckpt-000000000042``); ``latest_valid`` walks
  the ring newest→oldest, skipping (and counting) corrupt or partial
  entries, so one bad checkpoint falls back to the previous good one
  instead of killing the resume.
* **Elastic restore** — a checkpoint taken on mesh A restores onto mesh B.
  The ``npz`` backend is portable by construction (interiors re-scatter
  through ``set_quantity``); the ``orbax`` backend detects a topology or
  storage-axis change and re-routes through a host round trip using the
  geometry recorded in the manifest, instead of its historical
  same-topology requirement ("Memory-efficient array redistribution",
  PAPERS.md arxiv 2112.01075, is the on-device generalization of this
  re-scatter).

Backends:

* ``orbax`` (default when installed) — saves the sharded raw arrays (halo
  shells included) directly from device memory; the production path on
  pods.  Same-topology restores stay sharded end-to-end.
* ``npz`` — gathers interiors to host and saves a portable npz; restores
  onto any device count.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
import zipfile
from typing import List, Optional, Tuple

import numpy as np

from stencil_tpu import telemetry
from stencil_tpu.resilience.taxonomy import CheckpointCorruptError
from stencil_tpu.telemetry import names as tm
from stencil_tpu.utils.artifact import atomic_write, atomic_write_json, fsync_dir
from stencil_tpu.utils.logging import log_info, log_warn

#: the commit marker and single source of checkpoint metadata
MANIFEST = "MANIFEST.json"

#: bump when the manifest vocabulary changes incompatibly; a mismatch is a
#: classified corruption (the ring falls back), never a half-parse.
#: History: 1 — atomic manifest+digests+run_state (the long-run PR; the
#: pre-ring ``meta.json`` format is rejected with a pointed error).
SCHEMA = 1

#: retention-ring entry prefix: ``ckpt-<step:012d>``
RING_PREFIX = "ckpt-"


def _orbax_available() -> bool:
    try:
        import orbax.checkpoint  # noqa: F401

        return True
    except ImportError:
        return False


def _digest(arr: np.ndarray) -> str:
    return "sha256:" + hashlib.sha256(
        np.ascontiguousarray(arr).tobytes()
    ).hexdigest()


def _commit_dir(stage: str, path: str) -> None:
    """Atomically make ``stage`` the content of ``path``.  An existing
    checkpoint at ``path`` is moved aside first and removed only after the
    new one is in place, so a crash at any point leaves one of the two
    intact."""
    path = os.path.abspath(path)
    parent = os.path.dirname(path) or "."
    old = None
    if os.path.lexists(path):
        old = f"{path}.old.{os.getpid()}"
        if os.path.lexists(old):
            shutil.rmtree(old, ignore_errors=True)
        os.rename(path, old)
    try:
        os.rename(stage, path)
    except BaseException:
        if old is not None and not os.path.lexists(path):
            os.rename(old, path)
        raise
    fsync_dir(parent)
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)


def save_checkpoint(
    dd,
    path: str,
    step: int = 0,
    backend: Optional[str] = None,
    run_state: Optional[dict] = None,
    reason: str = "explicit",
    digests: Optional[bool] = None,
) -> str:
    """Write all quantities + geometry + run state atomically; returns the
    backend used.  ``run_state`` is the caller's resumable decision record
    (tuned picks, model knobs) — merged over the domain-derived axes this
    function records on its own (``storage_dtype``, ``halo_multiplier``,
    ``exchange_route``).

    ``digests`` controls the per-quantity sha256 over the portable interior
    representation.  The npz backend always has the interiors on host
    anyway, so it always digests; on the orbax backend the gather exists
    ONLY for the digests, so pod-scale cadences can trade verification for
    the sharded direct-from-device save with ``digests=False`` /
    ``STENCIL_CHECKPOINT_DIGESTS=0`` (manifest records ``null`` digests;
    restores then skip byte verification for this checkpoint).

    Multi-host runs (``jax.process_count() > 1``) require the orbax
    backend and save COORDINATED: every process calls into orbax on the
    one shared destination, digests are forced off (the gather would span
    non-addressable shards), and process 0 alone writes the manifest —
    removed first, re-written after orbax completes, so it stays the
    commit marker.  Elastic (cross-mesh) restore is single-controller
    only; multi-host restores require the same topology."""
    import jax

    t0 = time.perf_counter()
    backend = backend or ("orbax" if _orbax_available() else "npz")
    multiprocess = jax.process_count() > 1
    if multiprocess and backend != "orbax":
        raise ValueError(
            "multi-process checkpointing requires the orbax backend: the "
            "npz path gathers whole arrays to host, which spans "
            "non-addressable devices on a multi-host run"
        )
    if digests is None:
        if backend == "npz":
            digests = True
        else:
            from stencil_tpu.utils.config import env_bool

            digests = env_bool("STENCIL_CHECKPOINT_DIGESTS", True)
    if multiprocess and digests and backend == "orbax":
        log_warn(
            "checkpoint digests disabled: the digest gather would span "
            "non-addressable shards on a multi-process run"
        )
        digests = False
    path = os.path.abspath(path)
    parent = os.path.dirname(path) or "."
    os.makedirs(parent, exist_ok=True)
    # portable interiors (native dtype — bf16 storage upcasts exactly):
    # the representation the digests cover for BOTH backends, so a save
    # on one backend/axis config is comparable to any other.  Gathered
    # only when something needs it (the npz payload, or digests).
    interiors = (
        {h.name: dd.quantity_to_host(h) for h in dd._handles}
        if (backend == "npz" or digests)
        else None
    )
    nbytes = (
        int(sum(a.nbytes for a in interiors.values()))
        if interiors is not None
        else int(
            sum(
                int(np.prod(dd.size())) * h.cell_count() * np.dtype(h.dtype).itemsize
                for h in dd._handles
            )
        )
    )
    dim = dd.placement.dim()
    raw = dd.local_spec().raw_size()
    lo = dd._shell_radius.lo()
    # caller record first, domain-derived axes LAST: restore routing
    # (the orbax same-topology storage gate) reads these, so a caller
    # key can never shadow what the domain actually is
    state = dict(run_state or {})
    state.update(
        storage_dtype=dd.storage_dtype(),
        halo_multiplier=dd.halo_multiplier(),
        exchange_route=dd.exchange_route(),
    )
    meta = {
        "schema": SCHEMA,
        "size": list(dd.size()),
        "step": int(step),
        "backend": backend,
        "created": time.time(),
        "quantities": [
            {
                "name": h.name,
                "dtype": str(np.dtype(h.dtype)),
                "components": list(h.components),
                "digest": _digest(interiors[h.name]) if digests else None,
            }
            for h in dd._handles
        ],
        "geometry": {
            "mesh": [dim.x, dim.y, dim.z],
            "raw": [raw.x, raw.y, raw.z],
            "shell_lo": [lo.x, lo.y, lo.z],
            "valid_last": list(dd._valid_last),
        },
        "run_state": state,
    }
    if backend == "orbax" and multiprocess:
        # COORDINATED multi-host save: every process must call orbax on the
        # ONE shared destination (orbax owns the cross-process commit
        # protocol); per-process staging would defeat the coordination and
        # race the final rename.  The manifest stays the commit marker:
        # process 0 removes any previous one first — the entry reads
        # invalid (ring falls back) while being rewritten — and writes the
        # new one only after orbax reports completion.
        import orbax.checkpoint as ocp

        os.makedirs(path, exist_ok=True)
        if jax.process_index() == 0:
            try:
                os.unlink(os.path.join(path, MANIFEST))
            except OSError:
                pass
        ckptr = ocp.StandardCheckpointer()
        arrays = {h.name: dd.get_curr(h) for h in dd._handles}
        ckptr.save(os.path.join(path, "state.orbax"), arrays, force=True)
        ckptr.wait_until_finished()
        ckptr.close()
        if jax.process_index() != 0:
            return backend  # one manifest writer, one telemetry record
        atomic_write_json(os.path.join(path, MANIFEST), meta)
        fsync_dir(path)
    else:
        stage = f"{path}.tmp.{os.getpid()}"
        if os.path.lexists(stage):
            shutil.rmtree(stage)
        os.makedirs(stage)
        try:
            if backend == "orbax":
                import orbax.checkpoint as ocp

                ckptr = ocp.StandardCheckpointer()
                arrays = {h.name: dd.get_curr(h) for h in dd._handles}
                ckptr.save(os.path.join(stage, "state.orbax"), arrays, force=True)
                ckptr.wait_until_finished()
                ckptr.close()
            else:
                with atomic_write(os.path.join(stage, "state.npz"), "wb") as f:
                    np.savez(f, **interiors)
            # manifest LAST: it is the commit marker within the stage — a
            # stage (or a legacy non-atomic dir) without one is an
            # interrupted save
            atomic_write_json(os.path.join(stage, MANIFEST), meta)
            fsync_dir(stage)
            _commit_dir(stage, path)
        except BaseException:
            shutil.rmtree(stage, ignore_errors=True)
            raise
    dt = time.perf_counter() - t0
    telemetry.inc(tm.CHECKPOINT_SAVES)
    telemetry.inc(tm.CHECKPOINT_SAVE_BYTES, nbytes)
    telemetry.observe(tm.CHECKPOINT_SAVE_SECONDS, dt)
    telemetry.emit_event(
        tm.EVENT_CHECKPOINT_SAVE,
        path=path,
        step=int(step),
        backend=backend,
        bytes=nbytes,
        seconds=round(dt, 6),
        reason=reason,
    )
    log_info(f"checkpoint step {step} -> {path} ({backend}, {nbytes} B, {dt:.3f}s)")
    return backend


def load_manifest(path: str) -> dict:
    """The checkpoint's manifest, or a classified error explaining exactly
    why the directory is not usable (the satellite fix: a missing/partial
    manifest must reject with a clear message, not a stack trace
    mid-restore)."""
    mpath = os.path.join(path, MANIFEST)
    if not os.path.isdir(path):
        raise CheckpointCorruptError(path, "no such directory")
    if not os.path.exists(mpath):
        legacy = os.path.join(path, "meta.json")
        why = (
            "pre-atomic 'meta.json' checkpoint format (schema predates the "
            "manifest; re-save with this version)"
            if os.path.exists(legacy)
            else f"missing {MANIFEST} — not a checkpoint, or an interrupted "
            "save that never committed"
        )
        raise CheckpointCorruptError(path, why)
    try:
        with open(mpath) as f:
            meta = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CheckpointCorruptError(path, f"unreadable manifest: {e}") from None
    if not isinstance(meta, dict) or meta.get("schema") != SCHEMA:
        raise CheckpointCorruptError(
            path,
            f"manifest schema {meta.get('schema') if isinstance(meta, dict) else '?'} "
            f"!= {SCHEMA} (saved by an incompatible version)",
        )
    for key in ("size", "step", "backend", "quantities"):
        if key not in meta:
            raise CheckpointCorruptError(path, f"manifest is missing {key!r}")
    return meta


def validate_checkpoint(path: str, verify_digests: bool = True) -> dict:
    """Full standalone validation: manifest well-formed, state present, and
    (npz) every quantity present with a matching content digest.  Returns
    the manifest; raises :class:`CheckpointCorruptError` otherwise.  The
    orbax state is validated structurally here (its array bytes are verified
    against the digests during restore, where they are gathered anyway)."""
    meta = load_manifest(path)
    if meta["backend"] == "orbax":
        if not os.path.isdir(os.path.join(path, "state.orbax")):
            raise CheckpointCorruptError(path, "missing state.orbax directory")
        return meta
    spath = os.path.join(path, "state.npz")
    if not os.path.exists(spath):
        raise CheckpointCorruptError(path, "missing state.npz")
    try:
        with np.load(spath) as data:
            for q in meta["quantities"]:
                if q["name"] not in data.files:
                    raise CheckpointCorruptError(
                        path, f"state.npz is missing quantity {q['name']!r}"
                    )
                if verify_digests and q.get("digest"):
                    got = _digest(data[q["name"]])
                    if got != q["digest"]:
                        raise CheckpointCorruptError(
                            path,
                            f"digest mismatch for {q['name']!r}: manifest "
                            f"{q['digest']} != data {got}",
                        )
    except (OSError, ValueError, zipfile.BadZipFile) as e:
        raise CheckpointCorruptError(path, f"unreadable state.npz: {e}") from None
    return meta


def _check_compat(dd, meta: dict, path: str) -> None:
    if meta["size"] != list(dd.size()):
        raise ValueError(
            f"checkpoint size {meta['size']} != domain {list(dd.size())}"
        )
    by_name = {h.name: h for h in dd._handles}
    saved = {q["name"] for q in meta["quantities"]}
    if saved != set(by_name):
        raise ValueError(
            f"checkpoint quantities {sorted(saved)} != domain "
            f"{sorted(by_name)} ({path})"
        )
    for q in meta["quantities"]:
        h = by_name[q["name"]]
        if q["dtype"] != str(np.dtype(h.dtype)) or tuple(q.get("components", ())) != tuple(
            h.components
        ):
            raise ValueError(
                f"quantity {q['name']!r}: checkpoint dtype/components "
                f"({q['dtype']}, {q.get('components')}) != domain "
                f"({np.dtype(h.dtype)}, {list(h.components)})"
            )


def _interiors_from_raw_global(raw_arr: np.ndarray, geom: dict, size) -> np.ndarray:
    """Extract the valid interiors from a SAVE-TIME raw global array using
    the geometry recorded in the manifest — the standalone (cross-mesh)
    twin of ``DistributedDomain._from_raw_global``, keyed off the saving
    domain's mesh rather than the restoring one's."""
    dim = geom["mesh"]
    raw = geom["raw"]
    lo = geom["shell_lo"]
    valid_last = geom.get("valid_last", [None, None, None])
    # per-axis shard interior is the padded equal split, ceil(size/dim) —
    # the same rule realize() used on the saving mesh
    n = [-(-size[a] // dim[a]) for a in range(3)]
    comps = raw_arr.shape[:-3]
    out = np.zeros(comps + tuple(size), dtype=raw_arr.dtype)
    for ix in range(dim[0]):
        for iy in range(dim[1]):
            for iz in range(dim[2]):
                idx = (ix, iy, iz)
                v = [
                    valid_last[a]
                    if (idx[a] == dim[a] - 1 and valid_last[a] is not None)
                    else n[a]
                    for a in range(3)
                ]
                out[
                    ...,
                    ix * n[0] : ix * n[0] + v[0],
                    iy * n[1] : iy * n[1] + v[1],
                    iz * n[2] : iz * n[2] + v[2],
                ] = raw_arr[
                    ...,
                    ix * raw[0] + lo[0] : ix * raw[0] + lo[0] + v[0],
                    iy * raw[1] + lo[1] : iy * raw[1] + lo[1] + v[1],
                    iz * raw[2] + lo[2] : iz * raw[2] + lo[2] + v[2],
                ]
    return out


def restore_checkpoint(dd, path: str, verify: bool = True) -> int:
    """Load quantities into a realized domain; returns the saved step.

    Digest verification (``verify=True``) happens on the LOADED portable
    interiors BEFORE they are installed — so a corrupt file is rejected
    with a classified :class:`CheckpointCorruptError` while the domain
    still holds its previous state, and a restore onto a storage axis that
    legitimately rounds (native→bf16) is still verified against what was
    actually on disk."""
    t0 = time.perf_counter()
    path = os.path.abspath(path)
    meta = load_manifest(path)
    _check_compat(dd, meta, path)
    by_name = {h.name: h for h in dd._handles}
    geom = meta.get("geometry") or {}
    dim = dd.placement.dim()
    elastic = list(geom.get("mesh", [])) != [dim.x, dim.y, dim.z]
    if meta["backend"] == "orbax":
        import orbax.checkpoint as ocp

        state_path = os.path.join(path, "state.orbax")
        if not os.path.isdir(state_path):
            raise CheckpointCorruptError(path, "missing state.orbax directory")
        same_raw_shape = not elastic and all(
            tuple(h.components)
            + tuple(g * r for g, r in zip([dim.x, dim.y, dim.z], geom.get("raw", [])))
            == dd.get_curr(h).shape
            for h in dd._handles
        )
        storage_match = (meta.get("run_state") or {}).get(
            "storage_dtype", "native"
        ) == dd.storage_dtype()
        ckptr = ocp.StandardCheckpointer()
        try:
            if same_raw_shape and storage_match:
                # same topology AND same storage axis: sharded end-to-end
                target = {h.name: dd.get_curr(h) for h in dd._handles}
                restored = ckptr.restore(state_path, target)
                if verify:
                    installed = dict(dd._curr)
                    dd._curr.update(
                        {q["name"]: restored[q["name"]] for q in meta["quantities"]}
                    )
                    try:
                        for q in meta["quantities"]:
                            if not q.get("digest"):
                                continue  # saved with digests off
                            got = _digest(dd.quantity_to_host(by_name[q["name"]]))
                            if got != q["digest"]:
                                raise CheckpointCorruptError(
                                    path,
                                    f"digest mismatch for {q['name']!r}: "
                                    f"manifest {q['digest']} != restored {got}",
                                )
                    except CheckpointCorruptError:
                        dd._curr = installed  # keep the pre-restore state
                        raise
                else:
                    for q in meta["quantities"]:
                        dd._curr[q["name"]] = restored[q["name"]]
            else:
                # ELASTIC (mesh B != mesh A, or the storage axis changed):
                # restore to host numpy, cut the interiors out of the saved
                # raw layout via the manifest geometry, re-scatter
                restored = ckptr.restore(state_path)
                # verify everything BEFORE installing anything (the npz
                # path's two-phase contract)
                interiors = {}
                for q in meta["quantities"]:
                    h = by_name[q["name"]]
                    interior = _interiors_from_raw_global(
                        np.asarray(restored[q["name"]]), geom, meta["size"]
                    ).astype(h.dtype)
                    if verify and q.get("digest"):
                        got = _digest(interior)
                        if got != q["digest"]:
                            raise CheckpointCorruptError(
                                path,
                                f"digest mismatch for {q['name']!r}: manifest "
                                f"{q['digest']} != data {got}",
                            )
                    interiors[q["name"]] = interior
                for q in meta["quantities"]:
                    dd.set_quantity(by_name[q["name"]], interiors[q["name"]])
        finally:
            ckptr.close()
    else:
        spath = os.path.join(path, "state.npz")
        if not os.path.exists(spath):
            raise CheckpointCorruptError(path, "missing state.npz")
        try:
            data = np.load(spath)
        except (OSError, ValueError) as e:
            raise CheckpointCorruptError(path, f"unreadable state.npz: {e}") from None
        with data:
            # two phases — load+verify EVERYTHING, then install: a digest
            # mismatch on the last quantity must leave the domain fully on
            # its previous state, never half-restored
            loaded = {}
            for q in meta["quantities"]:
                if q["name"] not in data.files:
                    raise CheckpointCorruptError(
                        path, f"state.npz is missing quantity {q['name']!r}"
                    )
                arr = data[q["name"]]
                if verify and q.get("digest"):
                    got = _digest(arr)
                    if got != q["digest"]:
                        raise CheckpointCorruptError(
                            path,
                            f"digest mismatch for {q['name']!r}: manifest "
                            f"{q['digest']} != data {got}",
                        )
                loaded[q["name"]] = arr
        for q in meta["quantities"]:
            h = by_name[q["name"]]
            dd.set_quantity(h, loaded[q["name"]].astype(h.dtype))
    dt = time.perf_counter() - t0
    telemetry.inc(tm.CHECKPOINT_RESTORES)
    telemetry.observe(tm.CHECKPOINT_RESTORE_SECONDS, dt)
    telemetry.emit_event(
        tm.EVENT_CHECKPOINT_RESTORE,
        path=path,
        step=int(meta["step"]),
        backend=meta["backend"],
        elastic=elastic,
        seconds=round(dt, 6),
    )
    log_info(
        f"restored step {meta['step']} from {path} "
        f"({meta['backend']}{', elastic' if elastic else ''}, {dt:.3f}s)"
    )
    return int(meta["step"])


# --- retention ring -----------------------------------------------------------


def ring_entries(root: str) -> List[Tuple[int, str]]:
    """(step, path) for every ring entry under ``root``, oldest first.
    Stage/aside directories from interrupted saves are ignored (and never
    counted against the ring)."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        if not name.startswith(RING_PREFIX) or name.endswith(".tmp") or ".tmp." in name or ".old." in name:
            continue
        try:
            step = int(name[len(RING_PREFIX):])
        except ValueError:
            continue
        out.append((step, os.path.join(root, name)))
    return sorted(out)


def ring_path(root: str, step: int) -> str:
    return os.path.join(root, f"{RING_PREFIX}{step:012d}")


def save_to_ring(
    dd,
    root: str,
    step: int,
    keep: int = 3,
    backend: Optional[str] = None,
    run_state: Optional[dict] = None,
    reason: str = "cadence",
) -> str:
    """Atomic checkpoint into the retention ring at ``root`` and prune to
    the newest ``keep`` entries; returns the committed path."""
    path = ring_path(root, step)
    save_checkpoint(dd, path, step=step, backend=backend, run_state=run_state, reason=reason)
    entries = ring_entries(root)
    for _, old in entries[: max(len(entries) - max(keep, 1), 0)]:
        shutil.rmtree(old, ignore_errors=True)
    # sweep stage/aside survivors of KILLED saves: same-pid cleanup cannot
    # run after a SIGKILL, and the ring has one writer at a time, so any
    # `.tmp.`/`.old.` ring-prefixed dir here is garbage the size of a full
    # checkpoint
    for name in os.listdir(root):
        if name.startswith(RING_PREFIX) and (".tmp." in name or ".old." in name):
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)
    telemetry.set_gauge(tm.CHECKPOINT_RETAINED, min(len(entries), max(keep, 1)))
    return path


def restore_latest(dd, root: str, verify: bool = True) -> Optional[Tuple[str, dict, int]]:
    """Restore the newest ring checkpoint that RESTORES CLEANLY, falling
    back past entries that fail at any stage — structural validation or
    restore-time digest verification (the orbax backends verify bytes only
    at restore, so a standalone ``latest_valid`` pass cannot catch their
    bit rot).  Digest hashing happens exactly once per attempted entry.
    Returns ``(path, manifest, step)``, or None when nothing restores;
    compatibility errors (size/quantity mismatch — a config error, not
    corruption) propagate immediately."""
    for _, path in reversed(ring_entries(root)):
        try:
            meta = load_manifest(path)
            step = restore_checkpoint(dd, path, verify=verify)
            return path, meta, step
        except CheckpointCorruptError as e:
            telemetry.inc(tm.CHECKPOINT_INVALID)
            telemetry.emit_event(tm.EVENT_CHECKPOINT_FALLBACK, path=path, why=e.why)
            log_warn(
                f"checkpoint {path} failed restore ({e.why}); falling back "
                "to the previous ring entry"
            )
    return None


def latest_valid(root: str, verify_digests: bool = True) -> Optional[Tuple[str, dict]]:
    """The newest VALID ring checkpoint as ``(path, manifest)``, or None.
    Corrupt/partial entries are skipped with a warning, a
    ``checkpoint.invalid`` count, and a ``checkpoint.fallback`` event —
    the corruption-detection rung of the resilience story: one bad
    checkpoint costs one cadence of progress, not the run."""
    for step, path in reversed(ring_entries(root)):
        try:
            return path, validate_checkpoint(path, verify_digests=verify_digests)
        except CheckpointCorruptError as e:
            telemetry.inc(tm.CHECKPOINT_INVALID)
            telemetry.emit_event(tm.EVENT_CHECKPOINT_FALLBACK, path=path, why=e.why)
            log_warn(
                f"checkpoint {path} failed validation ({e.why}); falling "
                "back to the previous ring entry"
            )
    return None
