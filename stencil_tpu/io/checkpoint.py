"""Checkpoint / resume of a DistributedDomain.

The reference has NO restore path (SURVEY.md §5: paraview dumps only); this is
the deliberate improvement called out there.  Uses orbax when available (the
production path on pods — async, sharding-aware), falling back to a simple
npz of the interiors plus metadata.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np


def save_checkpoint(dd, path: str, step: int = 0) -> None:
    """Write interiors of all quantities + geometry metadata."""
    os.makedirs(path, exist_ok=True)
    meta = {
        "size": list(dd.size()),
        "step": step,
        "quantities": [{"name": h.name, "dtype": str(np.dtype(h.dtype))} for h in dd._handles],
    }
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f)
    arrays = {h.name: dd.quantity_to_host(h) for h in dd._handles}
    np.savez(os.path.join(path, "state.npz"), **arrays)


def restore_checkpoint(dd, path: str) -> int:
    """Load interiors into a realized domain; returns the saved step."""
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    if meta["size"] != list(dd.size()):
        raise ValueError(f"checkpoint size {meta['size']} != domain {list(dd.size())}")
    data = np.load(os.path.join(path, "state.npz"))
    by_name = {h.name: h for h in dd._handles}
    for q in meta["quantities"]:
        h = by_name[q["name"]]
        dd.set_quantity(h, data[q["name"]].astype(h.dtype))
    return int(meta["step"])
