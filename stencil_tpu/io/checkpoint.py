"""Checkpoint / resume of a DistributedDomain.

The reference has NO restore path (SURVEY.md §5: paraview dumps only); this is
the deliberate improvement called out there.  Two backends:

* ``orbax`` (default when installed) — saves the sharded raw arrays
  (halo shells included) directly from device memory, sharding-aware; the
  production path on pods.  Restore requires the same mesh topology.
* ``npz`` — gathers interiors to host and saves a portable npz; restores onto
  any device count (the interiors are re-scattered through ``set_quantity``).
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np


def _orbax_available() -> bool:
    try:
        import orbax.checkpoint  # noqa: F401

        return True
    except ImportError:
        return False


def save_checkpoint(dd, path: str, step: int = 0, backend: Optional[str] = None) -> str:
    """Write all quantities + geometry metadata; returns the backend used."""
    backend = backend or ("orbax" if _orbax_available() else "npz")
    os.makedirs(path, exist_ok=True)
    meta = {
        "size": list(dd.size()),
        "step": step,
        "backend": backend,
        "quantities": [{"name": h.name, "dtype": str(np.dtype(h.dtype))} for h in dd._handles],
    }
    if backend == "orbax":
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        state = {h.name: dd.get_curr(h) for h in dd._handles}
        ckptr.save(os.path.abspath(os.path.join(path, "state.orbax")), state, force=True)
        ckptr.wait_until_finished()
        ckptr.close()
    else:
        arrays = {h.name: dd.quantity_to_host(h) for h in dd._handles}
        np.savez(os.path.join(path, "state.npz"), **arrays)
    # meta.json last: a failed/interrupted state save must not clobber the
    # metadata of a previously good checkpoint at this path
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f)
    return backend


def restore_checkpoint(dd, path: str) -> int:
    """Load quantities into a realized domain; returns the saved step."""
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    if meta["size"] != list(dd.size()):
        raise ValueError(f"checkpoint size {meta['size']} != domain {list(dd.size())}")
    by_name = {h.name: h for h in dd._handles}
    if meta.get("backend") == "orbax":
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        # restore with the live (sharded) arrays as the structure/sharding
        # template — requires the same mesh topology as the save
        target = {h.name: dd.get_curr(h) for h in dd._handles}
        restored = ckptr.restore(os.path.abspath(os.path.join(path, "state.orbax")), target)
        ckptr.close()
        for q in meta["quantities"]:
            dd._curr[q["name"]] = restored[q["name"]]
    else:
        data = np.load(os.path.join(path, "state.npz"))
        for q in meta["quantities"]:
            h = by_name[q["name"]]
            dd.set_quantity(h, data[q["name"]].astype(h.dtype))
    return int(meta["step"])
