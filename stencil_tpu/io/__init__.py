"""Persistence: paraview point dumps (reference parity) and checkpoint/resume
(a deliberate improvement over the reference, which has none — SURVEY.md §5;
hardened for preemption-tolerant long runs: atomic commit, digest-verified
manifests, retention ring, elastic cross-mesh restore — docs/resilience.md
"Long-run operation")."""

from stencil_tpu.io.checkpoint import (
    latest_valid,
    load_manifest,
    restore_checkpoint,
    save_checkpoint,
    save_to_ring,
    validate_checkpoint,
)
from stencil_tpu.io.paraview import write_paraview

__all__ = [
    "write_paraview",
    "save_checkpoint",
    "restore_checkpoint",
    "save_to_ring",
    "latest_valid",
    "load_manifest",
    "validate_checkpoint",
]
