"""Persistence: paraview point dumps (reference parity) and checkpoint/resume
(a deliberate improvement over the reference, which has none — SURVEY.md §5)."""

from stencil_tpu.io.paraview import write_paraview
from stencil_tpu.io.checkpoint import save_checkpoint, restore_checkpoint

__all__ = ["write_paraview", "save_checkpoint", "restore_checkpoint"]
