"""stencil_tpu — a TPU-native 3D stencil halo-exchange framework.

Built from scratch in JAX/XLA/Pallas with the capabilities of the reference
MPI+CUDA library (``/root/reference``, mengshanfeng/stencil-2).  The reference's
five hand-rolled transports collapse into ``lax.ppermute`` collectives over a
3D device mesh; its CUDA pack/unpack kernels become Pallas kernels; its
double-buffered device allocations become donated, shell-carrying sharded
``jax.Array`` s.

Public API (mirrors reference ``include/stencil/stencil.hpp``):

    from stencil_tpu import DistributedDomain, Radius, Dim3, MethodFlags
"""

from stencil_tpu.core.dim3 import Dim3, Rect3
from stencil_tpu.core.direction_map import DirectionMap, DIRECTIONS_26
from stencil_tpu.core.radius import Radius
from stencil_tpu.core.geometry import LocalSpec
from stencil_tpu.utils.config import (
    MethodFlags,
    PlacementStrategy,
    apply_compile_cache,
)

# Persistent XLA compilation cache (STENCIL_COMPILE_CACHE_DIR): applied at
# package import so it lands before the first backend compile whichever
# entry point the process came through (models, drivers, bench.py).
apply_compile_cache()

__version__ = "0.1.0"

__all__ = [
    "Dim3",
    "Rect3",
    "DirectionMap",
    "DIRECTIONS_26",
    "Radius",
    "LocalSpec",
    "MethodFlags",
    "PlacementStrategy",
    "DistributedDomain",
    "save_checkpoint",
    "restore_checkpoint",
    "write_paraview",
]

_LAZY = {
    # these pull in jax; keep the geometry core importable without it
    "DistributedDomain": ("stencil_tpu.domain", "DistributedDomain"),
    "save_checkpoint": ("stencil_tpu.io.checkpoint", "save_checkpoint"),
    "restore_checkpoint": ("stencil_tpu.io.checkpoint", "restore_checkpoint"),
    "write_paraview": ("stencil_tpu.io.paraview", "write_paraview"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod, attr = _LAZY[name]
        return getattr(importlib.import_module(mod), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
