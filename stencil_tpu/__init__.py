"""stencil_tpu — a TPU-native 3D stencil halo-exchange framework.

Built from scratch in JAX/XLA/Pallas with the capabilities of the reference
MPI+CUDA library (``/root/reference``, mengshanfeng/stencil-2).  The reference's
five hand-rolled transports collapse into ``lax.ppermute`` collectives over a
3D device mesh; its CUDA pack/unpack kernels become Pallas kernels; its
double-buffered device allocations become donated, shell-carrying sharded
``jax.Array`` s.

Public API (mirrors reference ``include/stencil/stencil.hpp``):

    from stencil_tpu import DistributedDomain, Radius, Dim3, MethodFlags
"""

from stencil_tpu.core.dim3 import Dim3, Rect3
from stencil_tpu.core.direction_map import DirectionMap, DIRECTIONS_26
from stencil_tpu.core.radius import Radius
from stencil_tpu.core.geometry import LocalSpec
from stencil_tpu.utils.config import MethodFlags

__version__ = "0.1.0"

__all__ = [
    "Dim3",
    "Rect3",
    "DirectionMap",
    "DIRECTIONS_26",
    "Radius",
    "LocalSpec",
    "MethodFlags",
    "DistributedDomain",
]


def __getattr__(name):
    # DistributedDomain pulls in jax; keep the geometry core importable without it.
    if name == "DistributedDomain":
        from stencil_tpu.domain import DistributedDomain

        return DistributedDomain
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
