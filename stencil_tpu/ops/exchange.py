"""The halo exchange — the reference's entire transport layer as collectives.

Replaces the five transports + poll loop (reference tx_cuda.cuh:39-974,
src/stencil.cu:670-864) with ``lax.ppermute`` inside ``shard_map`` over the 3D
device mesh.  ICI plays NVLink/IPC; DCN plays inter-node MPI; XLA's async
collective scheduling replaces the hand-rolled state machines (SURVEY.md §2.2
"TPU mapping").

Design: each shard is a *shell-carrying* block — interior of size ``n`` plus
``radius`` face-widths of halo on each side, exactly the reference's
``LocalDomain`` allocation (local_domain.cuh:309-313 ``raw_size``).  The
exchange runs **three axis sweeps** (x, then y, then z).  Each sweep sends
slabs spanning the *full* extent of the other axes — including their already-
filled halos — so edge and corner data propagate without dedicated diagonal
messages: 26 neighbor messages collapse into <=6 ppermutes (SURVEY.md §7
"26-neighbor exchange").

The ``-dir`` extent convention holds by construction: the slab sent in
direction ``+a`` has width ``radius(-a)`` (the receiver's ``-a`` halo width),
and the slab sent in ``-a`` has width ``radius(+a)`` (packer.cuh:91-93).

A mesh axis of size 1 still ppermutes to itself — that self-wrap implements
periodic boundaries within one shard, the collapse of the reference's
same-GPU ``PeerAccessSender`` kernels (tx_cuda.cuh:39-104).

The y and z sweeps have selectable ROUTES (``EXCHANGE_ROUTES``, a tuner
axis — docs/tuning.md "Exchange routes"): ``direct`` sends the thin sliver
slabs as sliced (the historical path; the z sliver is ~64×-amplified on
the (8,128) tiling — PERF_NOTES "Thin z-region access" — and the y sliver
~8/(2r)-amplified on the sublane granule — "Thin y-region access"), the
``zpack_*`` routes send the z shell lane-major through the pack pipeline
(``_zpack_sweep`` / ops/pack.py), and the ``yzpack_*`` routes additionally
send the y shell sublane-major (``_ypack_sweep``) — the reference packer's
move (packer.cuh:71-366): reshape the message, not the domain.  All routes
produce bitwise-identical halos.

``fused_shell_exchange`` is the exchange's FUSED-CONSUMER form (the
packed-exchange story's second half): instead of unpacking received
messages back into the big arrays, it returns the received per-axis shell
buffers themselves — sweep-ordered corner patching happens on the small
buffers — so a consumer (the stream engine's ``halo="fused"`` mode,
ops/stream.py) can land them directly in its VMEM working planes and the
big array never sees a halo write at all.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from stencil_tpu.core.dim3 import Dim3
from stencil_tpu.utils.compat import shard_map
from stencil_tpu.core.radius import Radius
from stencil_tpu.parallel.mesh import MESH_AXES
from stencil_tpu.telemetry import names as tm

#: exchange implementations for the y/z axis sweeps — a first-class tuner
#: axis (tune/space.py ``exchange_space``; docs/tuning.md "Exchange
#: routes"):
#:
#: * ``direct``       — send the (X, Y, r) z-sliver and (X, r, Z) y-sliver
#:   slabs as sliced (the historical path; the static no-tune fallback).
#:   On the (8,128)-tiled layout the z sliver is ~64×-amplified (PERF_NOTES
#:   "Thin z-region access"): a radius-2 z exchange costs ~one full-domain
#:   copy at 512³.  The y sliver is sublane-amplified ~8/(2r) (PERF_NOTES
#:   "Thin y-region access") — cheaper, but still the only unfused leg.
#: * ``zpack_xla``    — reshape the message, not the domain: the z shell
#:   travels lane-major as ``(2m, Y, Xpad)`` (ops/pack.py ``pack_zshell_*``)
#:   with XLA fusing the slice+transpose into the permute operand.
#: * ``zpack_pallas`` — same buffer, but packed/unpacked by the tile-local
#:   pallas pipeline (whole x-planes HBM->VMEM, the thin cut in VMEM) so the
#:   big array is never read or written through a thin-z window at all.
#: * ``yzpack_xla``   — ``zpack_xla`` plus the y twin: the y shell travels
#:   sublane-major as ``(2m, X, Z)`` (ops/pack.py ``pack_yshell_*``), so
#:   BOTH thin sweeps ride packed messages and only whole x-plane slabs
#:   remain direct.
#: * ``yzpack_pallas`` — both packed sweeps through the tile-local pallas
#:   pipelines: the big array is never read or written through a thin-y OR
#:   thin-z window.
EXCHANGE_ROUTES = (
    "direct", "zpack_xla", "zpack_pallas", "yzpack_xla", "yzpack_pallas"
)

#: routes whose z sweep rides the packed z-shell pipeline
Z_PACK_ROUTES = ("zpack_xla", "zpack_pallas", "yzpack_xla", "yzpack_pallas")
#: routes whose y sweep rides the packed y-shell pipeline
Y_PACK_ROUTES = ("yzpack_xla", "yzpack_pallas")


def zpack_supported(dtypes, valid_last=None) -> bool:
    """Can the packed z sweep engage for this configuration?  Requires an
    evenly divided z axis (the pack kernels cut the shell at static offsets;
    a padded z falls back to ``direct`` for that sweep) and dtypes whose
    (8,128) tile geometry the kernels know (``halo_blend.supports``)."""
    from stencil_tpu.ops import halo_blend

    if valid_last is not None and valid_last[2] is not None:
        return False
    return all(halo_blend.supports(dt) for dt in dtypes)


def ypack_supported(dtypes, valid_last=None) -> bool:
    """Can the packed y sweep engage?  The y twin of ``zpack_supported``:
    an evenly divided y axis (static row offsets) and known tile
    geometry."""
    from stencil_tpu.ops import halo_blend

    if valid_last is not None and valid_last[1] is not None:
        return False
    return all(halo_blend.supports(dt) for dt in dtypes)


def route_supported(route: str, dtypes, valid_last=None) -> bool:
    """Can ``route`` engage for ANY of its packed sweeps here?  ``direct``
    always; ``zpack_*`` need the z sweep; ``yzpack_*`` engage if EITHER
    packed sweep can (each sweep degrades independently inside the
    exchange, so a partially engageable route is still a different — and
    correct — program from ``direct``)."""
    if route == "direct":
        return True
    z_ok = zpack_supported(dtypes, valid_last)
    if route in Y_PACK_ROUTES:
        return z_ok or ypack_supported(dtypes, valid_last)
    return z_ok


def route_vma_check(dtypes, valid_last, ndim_extra: int, route: str) -> bool:
    """``check_vma`` for a shard_map wrapping the exchange, route-aware: the
    packed pallas routes' outputs carry no vma annotation (exactly like the
    blend kernels), so validation must stay off whenever one can engage."""
    from stencil_tpu.ops import halo_blend

    if route.endswith("pallas") and (
        zpack_supported(dtypes, valid_last)
        or (route in Y_PACK_ROUTES and ypack_supported(dtypes, valid_last))
    ):
        return False
    return halo_blend.vma_check(dtypes, valid_last, ndim_extra)


def zpack_message_stats(raw_spatial, r_lo: int, r_hi: int, itemsizes) -> Tuple[int, int]:
    """Analytic (bytes, kernels) per shard per exchange through a packed z
    sweep: one ``(depth, Y, Xpad)`` buffer per 3D quantity slice per
    direction, one pack + one unpack kernel each (the ``exchange.packed.*``
    telemetry counters — modeled, like ``exchange_bytes_total``)."""
    from stencil_tpu.ops.pack import lane_pad

    X, Y, _ = raw_spatial
    nbytes = 0
    kernels = 0
    for depth in (r_lo, r_hi):
        if depth == 0:
            continue
        for isz in itemsizes:
            nbytes += depth * Y * lane_pad(X) * isz
            kernels += 2  # pack + unpack
    return nbytes, kernels


def ypack_message_stats(raw_spatial, r_lo: int, r_hi: int, itemsizes) -> Tuple[int, int]:
    """The y twin of ``zpack_message_stats``: one sublane-major
    ``(depth, X, Z)`` buffer per quantity slice per direction (no explicit
    pad — Z stays the lane dim), one pack + one unpack kernel each."""
    X, _, Z = raw_spatial
    nbytes = 0
    kernels = 0
    for depth in (r_lo, r_hi):
        if depth == 0:
            continue
        for isz in itemsizes:
            nbytes += depth * X * Z * isz
            kernels += 2  # pack + unpack
    return nbytes, kernels


def _shift_from_low(x, axis_name: str, n: int):
    """Each shard receives the value held by its -1 neighbor (data moves +)."""
    # NVTX analog: a REGISTERED per-direction scope (names.ALL_SPANS), so
    # profiler traces attribute this ppermute's device time to its mesh hop
    with jax.named_scope(tm.exchange_direction_span(axis_name, "low")):
        return lax.ppermute(x, axis_name, [(k, (k + 1) % n) for k in range(n)])


def _shift_from_high(x, axis_name: str, n: int):
    """Each shard receives the value held by its +1 neighbor (data moves -)."""
    with jax.named_scope(tm.exchange_direction_span(axis_name, "high")):
        return lax.ppermute(x, axis_name, [(k, (k - 1) % n) for k in range(n)])


def _fused_shift(slabs: List[jax.Array], shift_fn, name: str, n_dev: int) -> List[jax.Array]:
    """ppermute several quantities' slabs as ONE fused message.

    The reference packs all quantities of one neighbor into a single aligned
    buffer so message count is independent of field count (packer.cuh:52-69,
    146-160).  Here: same-dtype slabs stack along a flattened leading axis
    (one collective-permute carries the stack); mixed dtypes additionally
    fuse byte-wise via ``bitcast_convert_type`` — one buffer per direction,
    exactly the reference's byte-packed layout.  Returns received slabs in
    the original order/shapes.
    """
    if len(slabs) == 1:
        return [shift_fn(slabs[0], name, n_dev)]
    # flatten leading (quantity/batch) dims so same-dtype slabs concatenate
    flat = [s.reshape((-1,) + s.shape[-3:]) for s in slabs]
    groups: Dict[object, List[int]] = {}
    for i, s in enumerate(flat):
        groups.setdefault(s.dtype, []).append(i)
    bufs = [
        (dt, idxs, jnp.concatenate([flat[i] for i in idxs], axis=0))
        for dt, idxs in groups.items()
    ]
    if len(bufs) == 1:
        dt, idxs, buf = bufs[0]
        bufs = [(dt, idxs, shift_fn(buf, name, n_dev))]
    else:
        # mixed dtypes: one byte buffer per direction (packer.cuh:52-69)
        def to_bytes(v):
            if v.dtype == jnp.bool_:
                return v.reshape(-1).astype(jnp.uint8)  # lossless 0/1
            if v.dtype.itemsize > 1:
                return lax.bitcast_convert_type(v.reshape(-1), jnp.uint8).reshape(-1)
            return lax.bitcast_convert_type(v.reshape(-1), jnp.uint8)

        def from_bytes(p, dt):
            if dt == jnp.bool_:
                return p.astype(jnp.bool_)
            if jnp.dtype(dt).itemsize > 1:
                return lax.bitcast_convert_type(
                    p.reshape(-1, jnp.dtype(dt).itemsize), dt
                )
            return lax.bitcast_convert_type(p, dt)

        fused = jnp.concatenate([to_bytes(buf) for _, _, buf in bufs])
        recv_bytes = shift_fn(fused, name, n_dev)
        recv_parts, off = [], 0
        for dt, _, buf in bufs:
            nbytes = buf.size * buf.dtype.itemsize
            p = recv_bytes[off : off + nbytes]
            off += nbytes
            recv_parts.append(from_bytes(p, dt).reshape(buf.shape))
        bufs = [(dt, idxs, rp) for (dt, idxs, _), rp in zip(bufs, recv_parts)]
    out: List[Optional[jax.Array]] = [None] * len(slabs)
    for _, idxs, rbuf in bufs:
        off = 0
        for i in idxs:
            k = flat[i].shape[0]
            out[i] = rbuf[off : off + k].reshape(slabs[i].shape)
            off += k
    return out  # type: ignore[return-value]


def _zpack_sweep(
    blocks: List[jax.Array],
    r_lo: int,
    r_hi: int,
    n_pad: int,
    name: str,
    n_dev: int,
    route: str,
) -> List[jax.Array]:
    """One z-axis sweep through the packed pipeline (the tentpole of the
    exchange-route PR): extract every quantity's 2m-deep shell into
    lane-major ``(2m, Y, Xpad)`` buffers (``ops/pack.py``), ppermute the
    buffers as ONE fused message per direction (the ≤6-permute structure is
    preserved — this replaces the direct sweep's permutes one-for-one), and
    blend them back through aliased tile-local kernels.  On the
    ``zpack_pallas`` route the big array is only ever touched as whole
    x-planes — the ~64×-amplified thin-z access and the ``sliver-dus``
    relayout trap are impossible by construction (PERF_NOTES "Thin z-region
    access").  ``zpack_xla`` sends the same buffer but lets XLA fuse the
    packing; the received shell re-materializes as a thin slab only outside
    the big array, then lands via the blend kernels.

    Leading component/batch dims are flattened into per-slice 3D packs;
    all slices of all quantities still fuse into one message per direction.
    """
    from stencil_tpu.ops import halo_blend
    from stencil_tpu.ops.pack import (
        pack_zshell_pallas,
        pack_zshell_xla,
        unpack_zshell_pallas,
        zshell_to_slab,
    )

    interp = halo_blend.interpret_mode()
    pallas = route.endswith("pallas")
    # each 3D slice of each quantity packs its own buffer; the per-direction
    # message stays ONE fused ppermute regardless (packer.cuh:52-69)
    flat = [b.reshape((-1,) + b.shape[-3:]) for b in blocks]

    def pack_all(z0: int, depth: int) -> List[jax.Array]:
        return [
            pack_zshell_pallas(f[j], z0, depth, interpret=interp)
            if pallas
            else pack_zshell_xla(f[j], z0, depth)
            for f in flat
            for j in range(f.shape[0])
        ]

    lo_bufs = hi_bufs = None
    if r_lo > 0:
        # my low halo [z=0, r_lo) <- -z neighbor's top interior slab
        lo_bufs = _fused_shift(pack_all(n_pad, r_lo), _shift_from_low, name, n_dev)
    if r_hi > 0:
        hi_bufs = _fused_shift(pack_all(r_lo, r_hi), _shift_from_high, name, n_dev)
    blend = halo_blend.enabled()
    out_blocks: List[jax.Array] = []
    idx = 0  # slice cursor — pack_all emits both directions in this order
    for b, f in zip(blocks, flat):
        outs = []
        for j in range(f.shape[0]):
            s = f[j]
            x = s.shape[0]
            if lo_bufs is not None:
                if pallas:
                    s = unpack_zshell_pallas(s, lo_bufs[idx], 0, r_lo, interpret=interp)
                elif blend:
                    s = halo_blend.blend_slab(
                        s, zshell_to_slab(lo_bufs[idx], x), 2, 0, interpret=interp
                    )
                else:
                    s = s.at[:, :, 0:r_lo].set(zshell_to_slab(lo_bufs[idx], x))
            if hi_bufs is not None:
                z0 = r_lo + n_pad
                if pallas:
                    s = unpack_zshell_pallas(s, hi_bufs[idx], z0, r_hi, interpret=interp)
                elif blend:
                    s = halo_blend.blend_slab(
                        s, zshell_to_slab(hi_bufs[idx], x), 2, z0, interpret=interp
                    )
                else:
                    s = s.at[:, :, z0 : z0 + r_hi].set(zshell_to_slab(hi_bufs[idx], x))
            outs.append(s)
            idx += 1
        out = outs[0] if len(outs) == 1 else jnp.concatenate([o[None] for o in outs])
        out_blocks.append(out.reshape(b.shape))
    return out_blocks


def _ypack_sweep(
    blocks: List[jax.Array],
    r_lo: int,
    r_hi: int,
    n_pad: int,
    name: str,
    n_dev: int,
    route: str,
) -> List[jax.Array]:
    """One y-axis sweep through the packed pipeline — the sublane twin of
    ``_zpack_sweep`` (this PR's tentpole): every quantity's 2m-deep y shell
    is extracted into sublane-major ``(2m, X, Z)`` buffers (``ops/pack.py``
    ``pack_yshell_*``), ppermuted as ONE fused message per direction, and
    blended back tile-locally.  On the ``yzpack_pallas`` route the big
    array is only ever touched as whole x-planes — the ~8/(2r) sublane
    amplification of thin y windows (PERF_NOTES "Thin y-region access")
    never hits the big array.  ``yzpack_xla`` sends the same buffer but
    lets XLA fuse the packing; the received shell re-materializes as a thin
    slab only outside the big array, then lands via the blend kernels.

    Leading component/batch dims are flattened into per-slice 3D packs;
    all slices of all quantities still fuse into one message per direction.
    """
    from stencil_tpu.ops import halo_blend
    from stencil_tpu.ops.pack import (
        pack_yshell_pallas,
        pack_yshell_xla,
        unpack_yshell_pallas,
        yshell_to_slab,
    )

    interp = halo_blend.interpret_mode()
    pallas = route.endswith("pallas")
    flat = [b.reshape((-1,) + b.shape[-3:]) for b in blocks]

    def pack_all(y0: int, depth: int) -> List[jax.Array]:
        return [
            pack_yshell_pallas(f[j], y0, depth, interpret=interp)
            if pallas
            else pack_yshell_xla(f[j], y0, depth)
            for f in flat
            for j in range(f.shape[0])
        ]

    lo_bufs = hi_bufs = None
    if r_lo > 0:
        # my low halo [y=0, r_lo) <- -y neighbor's top interior rows
        lo_bufs = _fused_shift(pack_all(n_pad, r_lo), _shift_from_low, name, n_dev)
    if r_hi > 0:
        hi_bufs = _fused_shift(pack_all(r_lo, r_hi), _shift_from_high, name, n_dev)
    blend = halo_blend.enabled()
    out_blocks: List[jax.Array] = []
    idx = 0  # slice cursor — pack_all emits both directions in this order
    for b, f in zip(blocks, flat):
        outs = []
        for j in range(f.shape[0]):
            s = f[j]
            if lo_bufs is not None:
                if pallas:
                    s = unpack_yshell_pallas(s, lo_bufs[idx], 0, r_lo, interpret=interp)
                elif blend:
                    s = halo_blend.blend_slab(
                        s, yshell_to_slab(lo_bufs[idx]), 1, 0, interpret=interp
                    )
                else:
                    s = s.at[:, 0:r_lo, :].set(yshell_to_slab(lo_bufs[idx]))
            if hi_bufs is not None:
                y0 = r_lo + n_pad
                if pallas:
                    s = unpack_yshell_pallas(s, hi_bufs[idx], y0, r_hi, interpret=interp)
                elif blend:
                    s = halo_blend.blend_slab(
                        s, yshell_to_slab(hi_bufs[idx]), 1, y0, interpret=interp
                    )
                else:
                    s = s.at[:, y0 : y0 + r_hi, :].set(yshell_to_slab(hi_bufs[idx]))
            outs.append(s)
            idx += 1
        out = outs[0] if len(outs) == 1 else jnp.concatenate([o[None] for o in outs])
        out_blocks.append(out.reshape(b.shape))
    return out_blocks


def halo_exchange_multi(
    blocks: Sequence[jax.Array],
    radius: Radius,
    mesh_shape: Tuple[int, int, int],
    axis_names: Sequence[str] = MESH_AXES,
    valid_last: Optional[Tuple[Optional[int], Optional[int], Optional[int]]] = None,
    axes: Tuple[int, ...] = (0, 1, 2),
    route: str = "direct",
) -> List[jax.Array]:
    """Fill the halo shells of several shell-carrying shards JOINTLY —
    ≤ 2 ppermutes per axis sweep (≤ 6 total) no matter how many quantities,
    the reference's fused multi-quantity buffers (packer.cuh:52-69).  Must run
    inside ``shard_map`` over a mesh with ``axis_names``.

    Each block's spatial extent is its LAST three dims (leading batch/
    quantity dims ride along inside the fused message); every block must
    share the same spatial shape ``interior + r_lo + r_hi`` per axis, with
    the interior at ``[r_lo, r_lo + n)``.

    ``valid_last`` supports uneven global sizes via pad-and-mask (the
    reference's +-1-cell remainders, partition.hpp:83-114): entry ``a`` is the
    number of VALID interior cells in the LAST shard of axis ``a`` (``None``
    = axis divides evenly).  On a padded axis every shard sends the top slab
    of its own valid cells and writes the received +axis halo right after its
    valid cells — slab positions become per-shard ``lax.dynamic_slice``
    offsets derived from ``axis_index``; the collective itself is unchanged.

    ``route`` picks the y/z-sweep implementations (``EXCHANGE_ROUTES``):
    ``direct`` is today's sliced-slab path; the ``zpack_*`` routes send the
    z shell through the lane-major pack pipeline (``_zpack_sweep``), the
    ``yzpack_*`` routes additionally send the y shell through the
    sublane-major pipeline (``_ypack_sweep``) — bitwise-identical halos,
    differently shaped messages.  A packed sweep that cannot engage
    (uneven axis, unsupported dtype) silently runs ``direct``, so a pinned
    route is always correct.
    """
    if route not in EXCHANGE_ROUTES:
        raise ValueError(f"unknown exchange route {route!r} (one of {EXCHANGE_ROUTES})")
    blocks = list(blocks)
    if not blocks:
        return blocks
    spatial = blocks[0].shape[-3:]
    if not all(b.shape[-3:] == spatial for b in blocks):
        raise ValueError(
            "all quantities must share one spatial (last-3-dims) shape; got "
            f"{[b.shape for b in blocks]}"
        )
    for axis in axes:
        r_lo = radius.axis(axis, -1)  # my low-side halo width
        r_hi = radius.axis(axis, +1)  # my high-side halo width
        if r_lo == 0 and r_hi == 0:
            continue
        name = axis_names[axis]
        n_dev = mesh_shape[axis]
        size = spatial[axis]  # raw extent on this axis
        n_pad = size - r_lo - r_hi  # per-shard (padded) interior width
        v_last = valid_last[axis] if valid_last is not None else None
        uneven = v_last is not None and v_last != n_pad

        # a packed route engages per SWEEP: the y sweep packs on the
        # yzpack_* routes, the z sweep on every packed route; a sweep that
        # structurally cannot engage (uneven axis, unsupported dtype)
        # silently runs direct, so a pinned route is always correct
        if route in Y_PACK_ROUTES and axis == 1 and not uneven:
            from stencil_tpu.ops import halo_blend

            if all(halo_blend.supports(b.dtype) for b in blocks):
                blocks = _ypack_sweep(blocks, r_lo, r_hi, n_pad, name, n_dev, route)
                continue
        if route != "direct" and axis == 2 and not uneven:
            from stencil_tpu.ops import halo_blend

            if all(halo_blend.supports(b.dtype) for b in blocks):
                blocks = _zpack_sweep(blocks, r_lo, r_hi, n_pad, name, n_dev, route)
                continue

        def axslice(b, lo, hi):
            idx = [slice(None)] * b.ndim
            idx[b.ndim - 3 + axis] = slice(lo, hi)
            return tuple(idx)

        def dyn_starts(b, start):
            s = [jnp.int32(0)] * b.ndim
            s[b.ndim - 3 + axis] = start
            return tuple(s)

        def slab_sizes(b, w):
            s = list(b.shape)
            s[b.ndim - 3 + axis] = w
            return tuple(s)

        if uneven:
            idx = lax.axis_index(name)
            n_valid = jnp.where(idx == n_dev - 1, v_last, n_pad).astype(jnp.int32)

        def through_permute(slabs, shift_fn):
            if axis != 0:
                return _fused_shift(slabs, shift_fn, name, n_dev)
            # axis-0 slabs (r, Y, Z) travel as (1, r*Y, Z): the slice is
            # contiguous, and the 2D-spatial buffer keeps XLA's layout
            # assignment from giving the permute operand a transposed layout
            # whose feeder is a full-domain relayout copy (seen as a ~3 ms
            # {2,1,0}->{2,0,1} copy per macro step in the wavefront loop)
            shapes = [s.shape for s in slabs]
            flat = [
                s.reshape(s.shape[:-3] + (1, s.shape[-3] * s.shape[-2], s.shape[-1]))
                for s in slabs
            ]
            out = _fused_shift(flat, shift_fn, name, n_dev)
            return [o.reshape(sh) for o, sh in zip(out, shapes)]

        lo_recv = hi_recv = None
        if r_lo > 0:
            # my low halo [0, r_lo) <- -axis neighbor's top slab of VALID
            # interior, width r_lo (message traveling +axis has extent
            # radius(-axis)).  Uneven: top r_lo rows of my valid interior,
            # [n_valid, n_valid + r_lo) in allocation coords.
            slabs = [
                lax.dynamic_slice(b, dyn_starts(b, n_valid), slab_sizes(b, r_lo))
                if uneven
                else b[axslice(b, n_pad, r_lo + n_pad)]
                for b in blocks
            ]
            lo_recv = through_permute(slabs, _shift_from_low)
        if r_hi > 0:
            # my high halo <- +axis neighbor's interior bottom slab, width
            # r_hi, written right after MY valid cells
            slabs = [b[axslice(b, r_lo, r_lo + r_hi)] for b in blocks]
            hi_recv = through_permute(slabs, _shift_from_high)
        # y/z halo writes go through tile-local pallas blend kernels where
        # possible: plain DUS slivers on those axes bait XLA's layout
        # assignment into transposing the whole array (two full-domain
        # relayout copies per exchange — see ops/halo_blend.py).
        from stencil_tpu.ops import halo_blend

        blend = halo_blend.enabled() and all(
            b.ndim == 3 and halo_blend.supports(b.dtype) for b in blocks
        )
        interp = halo_blend.interpret_mode()
        for j, b in enumerate(blocks):
            if lo_recv is not None:
                # the low halo sits at 0 even on padded axes, so the static
                # kernel serves both cases
                if blend:
                    b = halo_blend.blend_slab(b, lo_recv[j], axis, 0, interpret=interp)
                else:
                    b = b.at[axslice(b, 0, r_lo)].set(lo_recv[j])
            if hi_recv is not None:
                if uneven and blend and axis != 0:
                    b = halo_blend.blend_slab_dynamic(
                        b, hi_recv[j], axis, r_lo + n_valid, interpret=interp
                    )
                elif uneven:
                    # stencil-lint: disable=sliver-dus axis-0 traced offset: an x-plane DUS is contiguous in the (8,128) tiling, no relayout bait
                    b = lax.dynamic_update_slice(
                        b, hi_recv[j], dyn_starts(b, r_lo + n_valid)
                    )
                elif blend:
                    b = halo_blend.blend_slab(
                        b, hi_recv[j], axis, r_lo + n_pad, interpret=interp
                    )
                else:
                    b = b.at[axslice(b, r_lo + n_pad, size)].set(hi_recv[j])
            blocks[j] = b
    return blocks


def halo_exchange_shard(
    block: jax.Array,
    radius: Radius,
    mesh_shape: Tuple[int, int, int],
    axis_names: Sequence[str] = MESH_AXES,
    valid_last: Optional[Tuple[Optional[int], Optional[int], Optional[int]]] = None,
    axes: Tuple[int, ...] = (0, 1, 2),
    route: str = "direct",
) -> jax.Array:
    """Single-quantity convenience wrapper over ``halo_exchange_multi``."""
    return halo_exchange_multi(
        [block], radius, mesh_shape, axis_names, valid_last, axes=axes, route=route
    )[0]


def fused_shell_exchange(
    blocks: Sequence[jax.Array],
    radius: Radius,
    mesh_shape: Tuple[int, int, int],
    axis_names: Sequence[str] = MESH_AXES,
    route: str = "yzpack_xla",
) -> Tuple[List[jax.Array], List[jax.Array], List[jax.Array]]:
    """The exchange WITHOUT the unpack: run the three fused-message sweeps
    and return the received shell buffers instead of writing them into the
    big arrays — the producer half of the stream engine's fused
    unpack→blend mode (``halo="fused"``, ops/stream.py), where the buffers
    land directly in the level-0 VMEM working planes and the big array
    never sees a halo-region write at all (the generalization of the
    z-slab wavefront's bespoke zero-big-array-halo scheme to EVERY axis of
    the generic routes).

    Per quantity (3D scalar blocks, even shards, all shell widths > 0 —
    the stream engine's structural gate), returns:

    * ``xbufs`` — ``(lo_x + hi_x, Y, Z)``: the whole-plane x slabs,
      ``[low-halo planes | high-halo planes]``;
    * ``ybufs`` — ``(X, lo_y + hi_y, Z)``: the packed y shell
      (``pack_yshell_*`` wire format, transposed to the pass's sublane
      orientation);
    * ``zbufs`` — ``(X, lo_z + hi_z, Y)``: the packed z shell (``pack_
      zshell_*`` wire format, transposed to the z-slab pass orientation,
      dead lane-pad columns dropped).

    Correctness mirrors the in-array 3-sweep order EXACTLY, with the
    corner propagation happening on the small buffers instead of through
    big-array halo writes: the y messages' x-shell planes are overwritten
    from the freshly received x slabs before the y permute (the in-array y
    sweep spans x halos the x sweep just filled), and the z messages' x
    columns and y rows are overwritten from the received x slabs and
    (already-patched) y buffers before the z permute.  Every returned
    buffer cell therefore equals the corresponding post-exchange big-array
    cell bitwise — the consumer's VMEM patch (x-replace, then y rows, then
    z columns) replays the sweep order, so fused and unfused programs
    compute identical level-0 planes.

    Structure: one ``_fused_shift`` per direction — the same ≤6-permute,
    one-message-per-direction shape (and the same ``exchange.<axis>.<side>``
    scopes) the ``exchange-structure`` contract pins on every route.
    """
    from stencil_tpu.ops.pack import (
        pack_yshell_pallas,
        pack_yshell_xla,
        pack_zshell_pallas,
        pack_zshell_xla,
    )
    from stencil_tpu.ops import halo_blend

    if route not in Y_PACK_ROUTES:
        raise ValueError(
            f"fused_shell_exchange needs a y+z packed route ({Y_PACK_ROUTES}); "
            f"got {route!r}"
        )
    blocks = list(blocks)
    interp = halo_blend.interpret_mode()
    pallas = route.endswith("pallas")
    X, Y, Z = blocks[0].shape
    lo = [radius.axis(a, -1) for a in range(3)]
    hi = [radius.axis(a, +1) for a in range(3)]
    n = [blocks[0].shape[a] - lo[a] - hi[a] for a in range(3)]
    assert all(b.ndim == 3 and b.shape == (X, Y, Z) for b in blocks)
    assert all(lo[a] > 0 and hi[a] > 0 for a in range(3)), (lo, hi)

    # --- x sweep: whole-plane slabs (the exchange's 2D-spatial layout pin) --
    def permute_x(slabs, shift_fn):
        shapes = [s.shape for s in slabs]
        flat = [s.reshape((1, s.shape[0] * s.shape[1], s.shape[2])) for s in slabs]
        out = _fused_shift(flat, shift_fn, axis_names[0], mesh_shape[0])
        return [o.reshape(sh) for o, sh in zip(out, shapes)]

    xlo = permute_x([b[n[0] : n[0] + lo[0]] for b in blocks], _shift_from_low)
    xhi = permute_x([b[lo[0] : lo[0] + hi[0]] for b in blocks], _shift_from_high)

    # --- y sweep: packed (2m, X, Z) buffers, x-corner-patched pre-permute ---
    def pack_y(y0, depth):
        bufs = [
            pack_yshell_pallas(b, y0, depth, interpret=interp)
            if pallas
            else pack_yshell_xla(b, y0, depth)
            for b in blocks
        ]
        # the in-array y sweep spans x halos the x sweep just filled; here
        # the block's x-shell planes are stale, so the message's x planes
        # are overwritten from the received x slabs (small-buffer writes —
        # the big array is untouched)
        out = []
        for q, buf in enumerate(bufs):
            buf = buf.at[:, 0 : lo[0], :].set(
                jnp.transpose(xlo[q][:, y0 : y0 + depth, :], (1, 0, 2))
            )
            buf = buf.at[:, X - hi[0] : X, :].set(
                jnp.transpose(xhi[q][:, y0 : y0 + depth, :], (1, 0, 2))
            )
            out.append(buf)
        return out

    ylo = _fused_shift(pack_y(n[1], lo[1]), _shift_from_low, axis_names[1], mesh_shape[1])
    yhi = _fused_shift(pack_y(lo[1], hi[1]), _shift_from_high, axis_names[1], mesh_shape[1])

    # --- z sweep: packed (2m, Y, Xpad) buffers, x+y-corner-patched ----------
    def pack_z(z0, depth):
        bufs = [
            pack_zshell_pallas(b, z0, depth, interpret=interp)
            if pallas
            else pack_zshell_xla(b, z0, depth)
            for b in blocks
        ]
        out = []
        for q, buf in enumerate(bufs):
            # x-shell lane columns from the received x slabs...
            buf = buf.at[:, :, 0 : lo[0]].set(
                jnp.transpose(xlo[q][:, :, z0 : z0 + depth], (2, 1, 0))
            )
            buf = buf.at[:, :, X - hi[0] : X].set(
                jnp.transpose(xhi[q][:, :, z0 : z0 + depth], (2, 1, 0))
            )
            # ...then y-shell sublane rows from the received (already
            # x-patched) y buffers — the in-array sweep order x→y→z, so the
            # x∩y∩z corners carry the two-hop diagonal content.  Pad
            # columns past X stay dead (the consumer never reads them).
            buf = buf.at[:, 0 : lo[1], 0:X].set(
                jnp.transpose(ylo[q][:, :, z0 : z0 + depth], (2, 0, 1))
            )
            buf = buf.at[:, Y - hi[1] : Y, 0:X].set(
                jnp.transpose(yhi[q][:, :, z0 : z0 + depth], (2, 0, 1))
            )
            out.append(buf)
        return out

    zlo = _fused_shift(pack_z(n[2], lo[2]), _shift_from_low, axis_names[2], mesh_shape[2])
    zhi = _fused_shift(pack_z(lo[2], hi[2]), _shift_from_high, axis_names[2], mesh_shape[2])

    xbufs = [jnp.concatenate([xlo[q], xhi[q]], axis=0) for q in range(len(blocks))]
    ybufs = [
        jnp.transpose(jnp.concatenate([ylo[q], yhi[q]], axis=0), (1, 0, 2))
        for q in range(len(blocks))
    ]
    zbufs = [
        jnp.transpose(jnp.concatenate([zlo[q], zhi[q]], axis=0), (2, 0, 1))[:X]
        for q in range(len(blocks))
    ]
    return xbufs, ybufs, zbufs


def make_exchange_fn_allgather(mesh: Mesh, radius: Radius, spec, dim):
    """Debug exchange: reconstruct every shard's raw block (interior + filled
    shell) as wrapped windows of the LOGICAL global field, letting XLA insert
    whatever collectives the resharding needs (effectively all-gathers).
    Obviously slow — exists to validate the ppermute path, the role the
    reference's ``MethodFlags`` method selection plays for benchmarking
    alternatives (stencil.hpp:29-41; SURVEY.md §7 "MethodFlags").  Even
    (unpadded) sizes only.
    """
    raw = spec.raw_size()
    n = spec.sz
    lo = radius.lo()
    sharding = NamedSharding(mesh, P(*MESH_AXES))

    def axis_indices(ax: int):
        size = dim[ax] * n[ax]  # logical extent
        parts = [
            (i * n[ax] - lo[ax] + jnp.arange(raw[ax])) % size for i in range(dim[ax])
        ]
        return jnp.concatenate(parts)

    idx = [axis_indices(ax) for ax in range(3)]

    @jax.jit
    def exchange(arrays):
        def one(arr):
            # extract the logical field from the shell-carrying layout
            g = arr.reshape(dim[0], raw[0], dim[1], raw[1], dim[2], raw[2])
            g = g[:, lo[0] : lo[0] + n[0], :, lo[1] : lo[1] + n[1], :, lo[2] : lo[2] + n[2]]
            logical = g.reshape(dim[0] * n[0], dim[1] * n[1], dim[2] * n[2])
            # every raw cell is a wrapped-window read of the logical field
            out = logical[idx[0]][:, idx[1]][:, :, idx[2]]
            return jax.lax.with_sharding_constraint(out, sharding)

        return jax.tree.map(one, arrays)

    return exchange


def make_exchange_fn_rollcompare(mesh: Mesh, radius: Radius, spec, dim):
    """Oracle exchange: wrap-pad the LOGICAL field (``jnp.pad(mode='wrap')``,
    the jnp.roll formulation) and rebuild every shard's raw block by static
    slicing — a formulation structurally independent of both the ppermute
    sweeps and the AllGather window-gather, completing the ``MethodFlags``
    debug set (utils/config.py RollCompare).  Even (unpadded) sizes only."""
    raw = spec.raw_size()
    n = spec.sz
    lo = radius.lo()
    hi = radius.hi()
    sharding = NamedSharding(mesh, P(*MESH_AXES))

    @jax.jit
    def exchange(arrays):
        def one(arr):
            g = arr.reshape(dim[0], raw[0], dim[1], raw[1], dim[2], raw[2])
            g = g[:, lo[0] : lo[0] + n[0], :, lo[1] : lo[1] + n[1], :, lo[2] : lo[2] + n[2]]
            logical = g.reshape(dim[0] * n[0], dim[1] * n[1], dim[2] * n[2])
            padded = jnp.pad(
                logical,
                ((lo[0], hi[0]), (lo[1], hi[1]), (lo[2], hi[2])),
                mode="wrap",
            )
            rows = []
            for ix in range(dim[0]):
                planes = []
                for iy in range(dim[1]):
                    cols = [
                        padded[
                            ix * n[0] : ix * n[0] + raw[0],
                            iy * n[1] : iy * n[1] + raw[1],
                            iz * n[2] : iz * n[2] + raw[2],
                        ]
                        for iz in range(dim[2])
                    ]
                    planes.append(jnp.concatenate(cols, axis=2))
                rows.append(jnp.concatenate(planes, axis=1))
            out = jnp.concatenate(rows, axis=0)
            return jax.lax.with_sharding_constraint(out, sharding)

        return jax.tree.map(one, arrays)

    return exchange


def make_exchange_fn(
    mesh: Mesh,
    radius: Radius,
    ndim_extra: int = 0,
    valid_last: Optional[Tuple[Optional[int], Optional[int], Optional[int]]] = None,
    route: str = "direct",
    axes: Tuple[int, ...] = (0, 1, 2),
    donate: bool = True,
):
    """Build a jitted exchange over a pytree of shell-carrying global arrays.

    Returns ``exchange(arrays) -> arrays`` where each array is sharded
    ``P('x','y','z')`` on its last three dims; leading component/batch dims
    (N-D data, per leaf — ``leaf.ndim - 3``; ``ndim_extra`` sets a floor for
    validation bookkeeping) are unsharded and ride inside the fused
    per-direction messages.  Donates its input (``donate=False`` for
    measurement harnesses that must not consume the domain's live buffers —
    the autotuner's route trials, bench-exchange's A/B): the halo write is
    in-place in HBM, like the reference filling halos inside the existing
    allocation.  ``valid_last`` — see ``halo_exchange_shard``; ``route`` —
    see ``EXCHANGE_ROUTES``; ``axes`` restricts the sweeps (bench-exchange's
    per-axis breakdown).
    """
    if route not in EXCHANGE_ROUTES:
        raise ValueError(f"unknown exchange route {route!r} (one of {EXCHANGE_ROUTES})")
    mesh_shape = tuple(mesh.shape[a] for a in MESH_AXES)

    def leaf_spec(leaf) -> P:
        assert leaf.ndim >= 3, leaf.shape
        return P(*([None] * (leaf.ndim - 3)), *MESH_AXES)

    donate_kw = {"donate_argnums": 0} if donate else {}

    @partial(jax.jit, **donate_kw)
    def exchange(arrays):
        def per_shard(*blocks):
            # ALL quantities (and any leading batch dims) ride one fused
            # message per direction — ≤6 permutes total (packer.cuh:52-69)
            return tuple(
                halo_exchange_multi(
                    blocks,
                    radius,
                    mesh_shape,
                    valid_last=valid_last,
                    axes=axes,
                    route=route,
                )
            )

        leaves, treedef = jax.tree.flatten(arrays)
        # vma validation stays on whenever neither the blend kernels nor the
        # packed pallas route can engage
        max_extra = max(
            [ndim_extra] + [l.ndim - 3 for l in leaves], default=ndim_extra
        )
        shard_fn = shard_map(
            per_shard,
            mesh=mesh,
            in_specs=tuple(leaf_spec(l) for l in leaves),
            out_specs=tuple(leaf_spec(l) for l in leaves),
            check_vma=route_vma_check(
                [l.dtype for l in leaves], valid_last, max_extra, route
            ),
        )
        return jax.tree.unflatten(treedef, list(shard_fn(*leaves)))

    return exchange
