"""The halo exchange — the reference's entire transport layer as collectives.

Replaces the five transports + poll loop (reference tx_cuda.cuh:39-974,
src/stencil.cu:670-864) with ``lax.ppermute`` inside ``shard_map`` over the 3D
device mesh.  ICI plays NVLink/IPC; DCN plays inter-node MPI; XLA's async
collective scheduling replaces the hand-rolled state machines (SURVEY.md §2.2
"TPU mapping").

Design: each shard is a *shell-carrying* block — interior of size ``n`` plus
``radius`` face-widths of halo on each side, exactly the reference's
``LocalDomain`` allocation (local_domain.cuh:309-313 ``raw_size``).  The
exchange runs **three axis sweeps** (x, then y, then z).  Each sweep sends
slabs spanning the *full* extent of the other axes — including their already-
filled halos — so edge and corner data propagate without dedicated diagonal
messages: 26 neighbor messages collapse into <=6 ppermutes (SURVEY.md §7
"26-neighbor exchange").

The ``-dir`` extent convention holds by construction: the slab sent in
direction ``+a`` has width ``radius(-a)`` (the receiver's ``-a`` halo width),
and the slab sent in ``-a`` has width ``radius(+a)`` (packer.cuh:91-93).

A mesh axis of size 1 still ppermutes to itself — that self-wrap implements
periodic boundaries within one shard, the collapse of the reference's
same-GPU ``PeerAccessSender`` kernels (tx_cuda.cuh:39-104).

The z sweep has selectable ROUTES (``EXCHANGE_ROUTES``, a tuner axis —
docs/tuning.md "Exchange routes"): ``direct`` sends the thin-z sliver slab
as sliced (the historical path, ~64×-amplified on the (8,128) tiling —
PERF_NOTES "Thin z-region access"), the ``zpack_*`` routes send the shell
lane-major through the pack pipeline (``_zpack_sweep`` / ops/pack.py), the
reference packer's move (packer.cuh:71-366): reshape the message, not the
domain.  All routes produce bitwise-identical halos.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from stencil_tpu.core.dim3 import Dim3
from stencil_tpu.utils.compat import shard_map
from stencil_tpu.core.radius import Radius
from stencil_tpu.parallel.mesh import MESH_AXES

#: exchange implementations for the z axis sweep — a first-class tuner axis
#: (tune/space.py ``exchange_space``; docs/tuning.md "Exchange routes"):
#:
#: * ``direct``       — send the (X, Y, r) z-sliver slab as sliced (the
#:   historical path; the static no-tune fallback).  On the (8,128)-tiled
#:   layout that sliver is ~64×-amplified (PERF_NOTES "Thin z-region
#:   access"): a radius-2 z exchange costs ~one full-domain copy at 512³.
#: * ``zpack_xla``    — reshape the message, not the domain: the shell
#:   travels lane-major as ``(2m, Y, Xpad)`` (ops/pack.py ``pack_zshell_*``)
#:   with XLA fusing the slice+transpose into the permute operand.
#: * ``zpack_pallas`` — same buffer, but packed/unpacked by the tile-local
#:   pallas pipeline (whole x-planes HBM->VMEM, the thin cut in VMEM) so the
#:   big array is never read or written through a thin-z window at all.
EXCHANGE_ROUTES = ("direct", "zpack_xla", "zpack_pallas")


def zpack_supported(dtypes, valid_last=None) -> bool:
    """Can the packed z routes engage for this configuration?  Requires an
    evenly divided z axis (the pack kernels cut the shell at static offsets;
    a padded z falls back to ``direct`` for that sweep) and dtypes whose
    (8,128) tile geometry the kernels know (``halo_blend.supports``)."""
    from stencil_tpu.ops import halo_blend

    if valid_last is not None and valid_last[2] is not None:
        return False
    return all(halo_blend.supports(dt) for dt in dtypes)


def route_vma_check(dtypes, valid_last, ndim_extra: int, route: str) -> bool:
    """``check_vma`` for a shard_map wrapping the exchange, route-aware: the
    packed pallas route's outputs carry no vma annotation (exactly like the
    blend kernels), so validation must stay off whenever it can engage."""
    from stencil_tpu.ops import halo_blend

    if route == "zpack_pallas" and zpack_supported(dtypes, valid_last):
        return False
    return halo_blend.vma_check(dtypes, valid_last, ndim_extra)


def zpack_message_stats(raw_spatial, r_lo: int, r_hi: int, itemsizes) -> Tuple[int, int]:
    """Analytic (bytes, kernels) per shard per exchange through a packed z
    route: one ``(depth, Y, Xpad)`` buffer per 3D quantity slice per
    direction, one pack + one unpack kernel each (the ``exchange.packed.*``
    telemetry counters — modeled, like ``exchange_bytes_total``)."""
    from stencil_tpu.ops.pack import lane_pad

    X, Y, _ = raw_spatial
    nbytes = 0
    kernels = 0
    for depth in (r_lo, r_hi):
        if depth == 0:
            continue
        for isz in itemsizes:
            nbytes += depth * Y * lane_pad(X) * isz
            kernels += 2  # pack + unpack
    return nbytes, kernels


def _shift_from_low(x, axis_name: str, n: int):
    """Each shard receives the value held by its -1 neighbor (data moves +)."""
    with jax.named_scope(f"halo_ppermute_{axis_name}_from_low"):  # NVTX analog
        return lax.ppermute(x, axis_name, [(k, (k + 1) % n) for k in range(n)])


def _shift_from_high(x, axis_name: str, n: int):
    """Each shard receives the value held by its +1 neighbor (data moves -)."""
    with jax.named_scope(f"halo_ppermute_{axis_name}_from_high"):
        return lax.ppermute(x, axis_name, [(k, (k - 1) % n) for k in range(n)])


def _fused_shift(slabs: List[jax.Array], shift_fn, name: str, n_dev: int) -> List[jax.Array]:
    """ppermute several quantities' slabs as ONE fused message.

    The reference packs all quantities of one neighbor into a single aligned
    buffer so message count is independent of field count (packer.cuh:52-69,
    146-160).  Here: same-dtype slabs stack along a flattened leading axis
    (one collective-permute carries the stack); mixed dtypes additionally
    fuse byte-wise via ``bitcast_convert_type`` — one buffer per direction,
    exactly the reference's byte-packed layout.  Returns received slabs in
    the original order/shapes.
    """
    if len(slabs) == 1:
        return [shift_fn(slabs[0], name, n_dev)]
    # flatten leading (quantity/batch) dims so same-dtype slabs concatenate
    flat = [s.reshape((-1,) + s.shape[-3:]) for s in slabs]
    groups: Dict[object, List[int]] = {}
    for i, s in enumerate(flat):
        groups.setdefault(s.dtype, []).append(i)
    bufs = [
        (dt, idxs, jnp.concatenate([flat[i] for i in idxs], axis=0))
        for dt, idxs in groups.items()
    ]
    if len(bufs) == 1:
        dt, idxs, buf = bufs[0]
        bufs = [(dt, idxs, shift_fn(buf, name, n_dev))]
    else:
        # mixed dtypes: one byte buffer per direction (packer.cuh:52-69)
        def to_bytes(v):
            if v.dtype == jnp.bool_:
                return v.reshape(-1).astype(jnp.uint8)  # lossless 0/1
            if v.dtype.itemsize > 1:
                return lax.bitcast_convert_type(v.reshape(-1), jnp.uint8).reshape(-1)
            return lax.bitcast_convert_type(v.reshape(-1), jnp.uint8)

        def from_bytes(p, dt):
            if dt == jnp.bool_:
                return p.astype(jnp.bool_)
            if jnp.dtype(dt).itemsize > 1:
                return lax.bitcast_convert_type(
                    p.reshape(-1, jnp.dtype(dt).itemsize), dt
                )
            return lax.bitcast_convert_type(p, dt)

        fused = jnp.concatenate([to_bytes(buf) for _, _, buf in bufs])
        recv_bytes = shift_fn(fused, name, n_dev)
        recv_parts, off = [], 0
        for dt, _, buf in bufs:
            nbytes = buf.size * buf.dtype.itemsize
            p = recv_bytes[off : off + nbytes]
            off += nbytes
            recv_parts.append(from_bytes(p, dt).reshape(buf.shape))
        bufs = [(dt, idxs, rp) for (dt, idxs, _), rp in zip(bufs, recv_parts)]
    out: List[Optional[jax.Array]] = [None] * len(slabs)
    for _, idxs, rbuf in bufs:
        off = 0
        for i in idxs:
            k = flat[i].shape[0]
            out[i] = rbuf[off : off + k].reshape(slabs[i].shape)
            off += k
    return out  # type: ignore[return-value]


def _zpack_sweep(
    blocks: List[jax.Array],
    r_lo: int,
    r_hi: int,
    n_pad: int,
    name: str,
    n_dev: int,
    route: str,
) -> List[jax.Array]:
    """One z-axis sweep through the packed pipeline (the tentpole of the
    exchange-route PR): extract every quantity's 2m-deep shell into
    lane-major ``(2m, Y, Xpad)`` buffers (``ops/pack.py``), ppermute the
    buffers as ONE fused message per direction (the ≤6-permute structure is
    preserved — this replaces the direct sweep's permutes one-for-one), and
    blend them back through aliased tile-local kernels.  On the
    ``zpack_pallas`` route the big array is only ever touched as whole
    x-planes — the ~64×-amplified thin-z access and the ``sliver-dus``
    relayout trap are impossible by construction (PERF_NOTES "Thin z-region
    access").  ``zpack_xla`` sends the same buffer but lets XLA fuse the
    packing; the received shell re-materializes as a thin slab only outside
    the big array, then lands via the blend kernels.

    Leading component/batch dims are flattened into per-slice 3D packs;
    all slices of all quantities still fuse into one message per direction.
    """
    from stencil_tpu.ops import halo_blend
    from stencil_tpu.ops.pack import (
        pack_zshell_pallas,
        pack_zshell_xla,
        unpack_zshell_pallas,
        zshell_to_slab,
    )

    interp = halo_blend.interpret_mode()
    pallas = route == "zpack_pallas"
    # each 3D slice of each quantity packs its own buffer; the per-direction
    # message stays ONE fused ppermute regardless (packer.cuh:52-69)
    flat = [b.reshape((-1,) + b.shape[-3:]) for b in blocks]

    def pack_all(z0: int, depth: int) -> List[jax.Array]:
        return [
            pack_zshell_pallas(f[j], z0, depth, interpret=interp)
            if pallas
            else pack_zshell_xla(f[j], z0, depth)
            for f in flat
            for j in range(f.shape[0])
        ]

    lo_bufs = hi_bufs = None
    if r_lo > 0:
        # my low halo [z=0, r_lo) <- -z neighbor's top interior slab
        lo_bufs = _fused_shift(pack_all(n_pad, r_lo), _shift_from_low, name, n_dev)
    if r_hi > 0:
        hi_bufs = _fused_shift(pack_all(r_lo, r_hi), _shift_from_high, name, n_dev)
    blend = halo_blend.enabled()
    out_blocks: List[jax.Array] = []
    idx = 0  # slice cursor — pack_all emits both directions in this order
    for b, f in zip(blocks, flat):
        outs = []
        for j in range(f.shape[0]):
            s = f[j]
            x = s.shape[0]
            if lo_bufs is not None:
                if pallas:
                    s = unpack_zshell_pallas(s, lo_bufs[idx], 0, r_lo, interpret=interp)
                elif blend:
                    s = halo_blend.blend_slab(
                        s, zshell_to_slab(lo_bufs[idx], x), 2, 0, interpret=interp
                    )
                else:
                    s = s.at[:, :, 0:r_lo].set(zshell_to_slab(lo_bufs[idx], x))
            if hi_bufs is not None:
                z0 = r_lo + n_pad
                if pallas:
                    s = unpack_zshell_pallas(s, hi_bufs[idx], z0, r_hi, interpret=interp)
                elif blend:
                    s = halo_blend.blend_slab(
                        s, zshell_to_slab(hi_bufs[idx], x), 2, z0, interpret=interp
                    )
                else:
                    s = s.at[:, :, z0 : z0 + r_hi].set(zshell_to_slab(hi_bufs[idx], x))
            outs.append(s)
            idx += 1
        out = outs[0] if len(outs) == 1 else jnp.concatenate([o[None] for o in outs])
        out_blocks.append(out.reshape(b.shape))
    return out_blocks


def halo_exchange_multi(
    blocks: Sequence[jax.Array],
    radius: Radius,
    mesh_shape: Tuple[int, int, int],
    axis_names: Sequence[str] = MESH_AXES,
    valid_last: Optional[Tuple[Optional[int], Optional[int], Optional[int]]] = None,
    axes: Tuple[int, ...] = (0, 1, 2),
    route: str = "direct",
) -> List[jax.Array]:
    """Fill the halo shells of several shell-carrying shards JOINTLY —
    ≤ 2 ppermutes per axis sweep (≤ 6 total) no matter how many quantities,
    the reference's fused multi-quantity buffers (packer.cuh:52-69).  Must run
    inside ``shard_map`` over a mesh with ``axis_names``.

    Each block's spatial extent is its LAST three dims (leading batch/
    quantity dims ride along inside the fused message); every block must
    share the same spatial shape ``interior + r_lo + r_hi`` per axis, with
    the interior at ``[r_lo, r_lo + n)``.

    ``valid_last`` supports uneven global sizes via pad-and-mask (the
    reference's +-1-cell remainders, partition.hpp:83-114): entry ``a`` is the
    number of VALID interior cells in the LAST shard of axis ``a`` (``None``
    = axis divides evenly).  On a padded axis every shard sends the top slab
    of its own valid cells and writes the received +axis halo right after its
    valid cells — slab positions become per-shard ``lax.dynamic_slice``
    offsets derived from ``axis_index``; the collective itself is unchanged.

    ``route`` picks the z-sweep implementation (``EXCHANGE_ROUTES``):
    ``direct`` is today's sliced-slab path; the ``zpack_*`` routes send the
    z shell through the lane-major pack pipeline (``_zpack_sweep``) —
    bitwise-identical halos, a differently shaped message.  A packed route
    that cannot engage (uneven z, unsupported dtype) silently runs that
    sweep ``direct``, so a pinned route is always correct.
    """
    if route not in EXCHANGE_ROUTES:
        raise ValueError(f"unknown exchange route {route!r} (one of {EXCHANGE_ROUTES})")
    blocks = list(blocks)
    if not blocks:
        return blocks
    spatial = blocks[0].shape[-3:]
    if not all(b.shape[-3:] == spatial for b in blocks):
        raise ValueError(
            "all quantities must share one spatial (last-3-dims) shape; got "
            f"{[b.shape for b in blocks]}"
        )
    for axis in axes:
        r_lo = radius.axis(axis, -1)  # my low-side halo width
        r_hi = radius.axis(axis, +1)  # my high-side halo width
        if r_lo == 0 and r_hi == 0:
            continue
        name = axis_names[axis]
        n_dev = mesh_shape[axis]
        size = spatial[axis]  # raw extent on this axis
        n_pad = size - r_lo - r_hi  # per-shard (padded) interior width
        v_last = valid_last[axis] if valid_last is not None else None
        uneven = v_last is not None and v_last != n_pad

        if route != "direct" and axis == 2 and not uneven:
            from stencil_tpu.ops import halo_blend

            if all(halo_blend.supports(b.dtype) for b in blocks):
                blocks = _zpack_sweep(blocks, r_lo, r_hi, n_pad, name, n_dev, route)
                continue

        def axslice(b, lo, hi):
            idx = [slice(None)] * b.ndim
            idx[b.ndim - 3 + axis] = slice(lo, hi)
            return tuple(idx)

        def dyn_starts(b, start):
            s = [jnp.int32(0)] * b.ndim
            s[b.ndim - 3 + axis] = start
            return tuple(s)

        def slab_sizes(b, w):
            s = list(b.shape)
            s[b.ndim - 3 + axis] = w
            return tuple(s)

        if uneven:
            idx = lax.axis_index(name)
            n_valid = jnp.where(idx == n_dev - 1, v_last, n_pad).astype(jnp.int32)

        def through_permute(slabs, shift_fn):
            if axis != 0:
                return _fused_shift(slabs, shift_fn, name, n_dev)
            # axis-0 slabs (r, Y, Z) travel as (1, r*Y, Z): the slice is
            # contiguous, and the 2D-spatial buffer keeps XLA's layout
            # assignment from giving the permute operand a transposed layout
            # whose feeder is a full-domain relayout copy (seen as a ~3 ms
            # {2,1,0}->{2,0,1} copy per macro step in the wavefront loop)
            shapes = [s.shape for s in slabs]
            flat = [
                s.reshape(s.shape[:-3] + (1, s.shape[-3] * s.shape[-2], s.shape[-1]))
                for s in slabs
            ]
            out = _fused_shift(flat, shift_fn, name, n_dev)
            return [o.reshape(sh) for o, sh in zip(out, shapes)]

        lo_recv = hi_recv = None
        if r_lo > 0:
            # my low halo [0, r_lo) <- -axis neighbor's top slab of VALID
            # interior, width r_lo (message traveling +axis has extent
            # radius(-axis)).  Uneven: top r_lo rows of my valid interior,
            # [n_valid, n_valid + r_lo) in allocation coords.
            slabs = [
                lax.dynamic_slice(b, dyn_starts(b, n_valid), slab_sizes(b, r_lo))
                if uneven
                else b[axslice(b, n_pad, r_lo + n_pad)]
                for b in blocks
            ]
            lo_recv = through_permute(slabs, _shift_from_low)
        if r_hi > 0:
            # my high halo <- +axis neighbor's interior bottom slab, width
            # r_hi, written right after MY valid cells
            slabs = [b[axslice(b, r_lo, r_lo + r_hi)] for b in blocks]
            hi_recv = through_permute(slabs, _shift_from_high)
        # y/z halo writes go through tile-local pallas blend kernels where
        # possible: plain DUS slivers on those axes bait XLA's layout
        # assignment into transposing the whole array (two full-domain
        # relayout copies per exchange — see ops/halo_blend.py).
        from stencil_tpu.ops import halo_blend

        blend = halo_blend.enabled() and all(
            b.ndim == 3 and halo_blend.supports(b.dtype) for b in blocks
        )
        interp = halo_blend.interpret_mode()
        for j, b in enumerate(blocks):
            if lo_recv is not None:
                # the low halo sits at 0 even on padded axes, so the static
                # kernel serves both cases
                if blend:
                    b = halo_blend.blend_slab(b, lo_recv[j], axis, 0, interpret=interp)
                else:
                    b = b.at[axslice(b, 0, r_lo)].set(lo_recv[j])
            if hi_recv is not None:
                if uneven and blend and axis != 0:
                    b = halo_blend.blend_slab_dynamic(
                        b, hi_recv[j], axis, r_lo + n_valid, interpret=interp
                    )
                elif uneven:
                    # stencil-lint: disable=sliver-dus axis-0 traced offset: an x-plane DUS is contiguous in the (8,128) tiling, no relayout bait
                    b = lax.dynamic_update_slice(
                        b, hi_recv[j], dyn_starts(b, r_lo + n_valid)
                    )
                elif blend:
                    b = halo_blend.blend_slab(
                        b, hi_recv[j], axis, r_lo + n_pad, interpret=interp
                    )
                else:
                    b = b.at[axslice(b, r_lo + n_pad, size)].set(hi_recv[j])
            blocks[j] = b
    return blocks


def halo_exchange_shard(
    block: jax.Array,
    radius: Radius,
    mesh_shape: Tuple[int, int, int],
    axis_names: Sequence[str] = MESH_AXES,
    valid_last: Optional[Tuple[Optional[int], Optional[int], Optional[int]]] = None,
    axes: Tuple[int, ...] = (0, 1, 2),
    route: str = "direct",
) -> jax.Array:
    """Single-quantity convenience wrapper over ``halo_exchange_multi``."""
    return halo_exchange_multi(
        [block], radius, mesh_shape, axis_names, valid_last, axes=axes, route=route
    )[0]


def make_exchange_fn_allgather(mesh: Mesh, radius: Radius, spec, dim):
    """Debug exchange: reconstruct every shard's raw block (interior + filled
    shell) as wrapped windows of the LOGICAL global field, letting XLA insert
    whatever collectives the resharding needs (effectively all-gathers).
    Obviously slow — exists to validate the ppermute path, the role the
    reference's ``MethodFlags`` method selection plays for benchmarking
    alternatives (stencil.hpp:29-41; SURVEY.md §7 "MethodFlags").  Even
    (unpadded) sizes only.
    """
    raw = spec.raw_size()
    n = spec.sz
    lo = radius.lo()
    sharding = NamedSharding(mesh, P(*MESH_AXES))

    def axis_indices(ax: int):
        size = dim[ax] * n[ax]  # logical extent
        parts = [
            (i * n[ax] - lo[ax] + jnp.arange(raw[ax])) % size for i in range(dim[ax])
        ]
        return jnp.concatenate(parts)

    idx = [axis_indices(ax) for ax in range(3)]

    @jax.jit
    def exchange(arrays):
        def one(arr):
            # extract the logical field from the shell-carrying layout
            g = arr.reshape(dim[0], raw[0], dim[1], raw[1], dim[2], raw[2])
            g = g[:, lo[0] : lo[0] + n[0], :, lo[1] : lo[1] + n[1], :, lo[2] : lo[2] + n[2]]
            logical = g.reshape(dim[0] * n[0], dim[1] * n[1], dim[2] * n[2])
            # every raw cell is a wrapped-window read of the logical field
            out = logical[idx[0]][:, idx[1]][:, :, idx[2]]
            return jax.lax.with_sharding_constraint(out, sharding)

        return jax.tree.map(one, arrays)

    return exchange


def make_exchange_fn_rollcompare(mesh: Mesh, radius: Radius, spec, dim):
    """Oracle exchange: wrap-pad the LOGICAL field (``jnp.pad(mode='wrap')``,
    the jnp.roll formulation) and rebuild every shard's raw block by static
    slicing — a formulation structurally independent of both the ppermute
    sweeps and the AllGather window-gather, completing the ``MethodFlags``
    debug set (utils/config.py RollCompare).  Even (unpadded) sizes only."""
    raw = spec.raw_size()
    n = spec.sz
    lo = radius.lo()
    hi = radius.hi()
    sharding = NamedSharding(mesh, P(*MESH_AXES))

    @jax.jit
    def exchange(arrays):
        def one(arr):
            g = arr.reshape(dim[0], raw[0], dim[1], raw[1], dim[2], raw[2])
            g = g[:, lo[0] : lo[0] + n[0], :, lo[1] : lo[1] + n[1], :, lo[2] : lo[2] + n[2]]
            logical = g.reshape(dim[0] * n[0], dim[1] * n[1], dim[2] * n[2])
            padded = jnp.pad(
                logical,
                ((lo[0], hi[0]), (lo[1], hi[1]), (lo[2], hi[2])),
                mode="wrap",
            )
            rows = []
            for ix in range(dim[0]):
                planes = []
                for iy in range(dim[1]):
                    cols = [
                        padded[
                            ix * n[0] : ix * n[0] + raw[0],
                            iy * n[1] : iy * n[1] + raw[1],
                            iz * n[2] : iz * n[2] + raw[2],
                        ]
                        for iz in range(dim[2])
                    ]
                    planes.append(jnp.concatenate(cols, axis=2))
                rows.append(jnp.concatenate(planes, axis=1))
            out = jnp.concatenate(rows, axis=0)
            return jax.lax.with_sharding_constraint(out, sharding)

        return jax.tree.map(one, arrays)

    return exchange


def make_exchange_fn(
    mesh: Mesh,
    radius: Radius,
    ndim_extra: int = 0,
    valid_last: Optional[Tuple[Optional[int], Optional[int], Optional[int]]] = None,
    route: str = "direct",
    axes: Tuple[int, ...] = (0, 1, 2),
    donate: bool = True,
):
    """Build a jitted exchange over a pytree of shell-carrying global arrays.

    Returns ``exchange(arrays) -> arrays`` where each array is sharded
    ``P('x','y','z')`` on its last three dims; leading component/batch dims
    (N-D data, per leaf — ``leaf.ndim - 3``; ``ndim_extra`` sets a floor for
    validation bookkeeping) are unsharded and ride inside the fused
    per-direction messages.  Donates its input (``donate=False`` for
    measurement harnesses that must not consume the domain's live buffers —
    the autotuner's route trials, bench-exchange's A/B): the halo write is
    in-place in HBM, like the reference filling halos inside the existing
    allocation.  ``valid_last`` — see ``halo_exchange_shard``; ``route`` —
    see ``EXCHANGE_ROUTES``; ``axes`` restricts the sweeps (bench-exchange's
    per-axis breakdown).
    """
    if route not in EXCHANGE_ROUTES:
        raise ValueError(f"unknown exchange route {route!r} (one of {EXCHANGE_ROUTES})")
    mesh_shape = tuple(mesh.shape[a] for a in MESH_AXES)

    def leaf_spec(leaf) -> P:
        assert leaf.ndim >= 3, leaf.shape
        return P(*([None] * (leaf.ndim - 3)), *MESH_AXES)

    donate_kw = {"donate_argnums": 0} if donate else {}

    @partial(jax.jit, **donate_kw)
    def exchange(arrays):
        def per_shard(*blocks):
            # ALL quantities (and any leading batch dims) ride one fused
            # message per direction — ≤6 permutes total (packer.cuh:52-69)
            return tuple(
                halo_exchange_multi(
                    blocks,
                    radius,
                    mesh_shape,
                    valid_last=valid_last,
                    axes=axes,
                    route=route,
                )
            )

        leaves, treedef = jax.tree.flatten(arrays)
        # vma validation stays on whenever neither the blend kernels nor the
        # packed pallas route can engage
        max_extra = max(
            [ndim_extra] + [l.ndim - 3 for l in leaves], default=ndim_extra
        )
        shard_fn = shard_map(
            per_shard,
            mesh=mesh,
            in_specs=tuple(leaf_spec(l) for l in leaves),
            out_specs=tuple(leaf_spec(l) for l in leaves),
            check_vma=route_vma_check(
                [l.dtype for l in leaves], valid_last, max_extra, route
            ),
        )
        return jax.tree.unflatten(treedef, list(shard_fn(*leaves)))

    return exchange
