"""The halo exchange — the reference's entire transport layer as collectives.

Replaces the five transports + poll loop (reference tx_cuda.cuh:39-974,
src/stencil.cu:670-864) with ``lax.ppermute`` inside ``shard_map`` over the 3D
device mesh.  ICI plays NVLink/IPC; DCN plays inter-node MPI; XLA's async
collective scheduling replaces the hand-rolled state machines (SURVEY.md §2.2
"TPU mapping").

Design: each shard is a *shell-carrying* block — interior of size ``n`` plus
``radius`` face-widths of halo on each side, exactly the reference's
``LocalDomain`` allocation (local_domain.cuh:309-313 ``raw_size``).  The
exchange runs **three axis sweeps** (x, then y, then z).  Each sweep sends
slabs spanning the *full* extent of the other axes — including their already-
filled halos — so edge and corner data propagate without dedicated diagonal
messages: 26 neighbor messages collapse into <=6 ppermutes (SURVEY.md §7
"26-neighbor exchange").

The ``-dir`` extent convention holds by construction: the slab sent in
direction ``+a`` has width ``radius(-a)`` (the receiver's ``-a`` halo width),
and the slab sent in ``-a`` has width ``radius(+a)`` (packer.cuh:91-93).

A mesh axis of size 1 still ppermutes to itself — that self-wrap implements
periodic boundaries within one shard, the collapse of the reference's
same-GPU ``PeerAccessSender`` kernels (tx_cuda.cuh:39-104).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from stencil_tpu.core.dim3 import Dim3
from stencil_tpu.core.radius import Radius
from stencil_tpu.parallel.mesh import MESH_AXES


def _shift_from_low(x, axis_name: str, n: int):
    """Each shard receives the value held by its -1 neighbor (data moves +)."""
    with jax.named_scope(f"halo_ppermute_{axis_name}_from_low"):  # NVTX analog
        return lax.ppermute(x, axis_name, [(k, (k + 1) % n) for k in range(n)])


def _shift_from_high(x, axis_name: str, n: int):
    """Each shard receives the value held by its +1 neighbor (data moves -)."""
    with jax.named_scope(f"halo_ppermute_{axis_name}_from_high"):
        return lax.ppermute(x, axis_name, [(k, (k - 1) % n) for k in range(n)])


def halo_exchange_shard(
    block: jax.Array,
    radius: Radius,
    mesh_shape: Tuple[int, int, int],
    axis_names: Sequence[str] = MESH_AXES,
    valid_last: Optional[Tuple[Optional[int], Optional[int], Optional[int]]] = None,
) -> jax.Array:
    """Fill the halo shell of one shell-carrying shard.  Must run inside
    ``shard_map`` over a mesh with ``axis_names``.

    ``block`` has extent ``interior + r_lo + r_hi`` per axis; the interior
    occupies ``[r_lo, r_lo + n)``.

    ``valid_last`` supports uneven global sizes via pad-and-mask (the
    reference's +-1-cell remainders, partition.hpp:83-114): entry ``a`` is the
    number of VALID interior cells in the LAST shard of axis ``a`` (``None``
    = axis divides evenly).  On a padded axis every shard sends the top slab
    of its own valid cells and writes the received +axis halo right after its
    valid cells — slab positions become per-shard ``lax.dynamic_slice``
    offsets derived from ``axis_index``; the collective itself is unchanged.
    """
    for axis in range(3):
        r_lo = radius.axis(axis, -1)  # my low-side halo width
        r_hi = radius.axis(axis, +1)  # my high-side halo width
        if r_lo == 0 and r_hi == 0:
            continue
        name = axis_names[axis]
        n_dev = mesh_shape[axis]
        size = block.shape[axis]  # raw extent on this axis
        n_pad = size - r_lo - r_hi  # per-shard (padded) interior width
        v_last = valid_last[axis] if valid_last is not None else None
        uneven = v_last is not None and v_last != n_pad

        def axslice(lo, hi):
            idx = [slice(None)] * block.ndim
            idx[axis] = slice(lo, hi)
            return tuple(idx)

        def dyn_starts(start):
            s = [jnp.int32(0)] * block.ndim
            s[axis] = start
            return tuple(s)

        def slab_sizes(w):
            s = list(block.shape)
            s[axis] = w
            return tuple(s)

        if uneven:
            idx = lax.axis_index(name)
            n_valid = jnp.where(idx == n_dev - 1, v_last, n_pad).astype(jnp.int32)
        updates = []
        if r_lo > 0:
            # my low halo [0, r_lo) <- -axis neighbor's top slab of VALID
            # interior, width r_lo (message traveling +axis has extent
            # radius(-axis))
            if uneven:
                # top r_lo rows of my valid interior: [n_valid, n_valid+r_lo)
                # in allocation coords (interior starts at r_lo)
                slab = lax.dynamic_slice(block, dyn_starts(n_valid), slab_sizes(r_lo))
            else:
                slab = block[axslice(n_pad, r_lo + n_pad)]
            recv = _shift_from_low(slab, name, n_dev)
            updates.append((axslice(0, r_lo), None, recv))
        if r_hi > 0:
            # my high halo <- +axis neighbor's interior bottom slab, width
            # r_hi, written right after MY valid cells
            slab = block[axslice(r_lo, r_lo + r_hi)]
            recv = _shift_from_high(slab, name, n_dev)
            if uneven:
                updates.append((None, dyn_starts(r_lo + n_valid), recv))
            else:
                updates.append((axslice(r_lo + n_pad, size), None, recv))
        for sl, starts, val in updates:
            if starts is not None:
                block = lax.dynamic_update_slice(block, val, starts)
            else:
                block = block.at[sl].set(val)
    return block


def make_exchange_fn_allgather(mesh: Mesh, radius: Radius, spec, dim):
    """Debug exchange: reconstruct every shard's raw block (interior + filled
    shell) as wrapped windows of the LOGICAL global field, letting XLA insert
    whatever collectives the resharding needs (effectively all-gathers).
    Obviously slow — exists to validate the ppermute path, the role the
    reference's ``MethodFlags`` method selection plays for benchmarking
    alternatives (stencil.hpp:29-41; SURVEY.md §7 "MethodFlags").  Even
    (unpadded) sizes only.
    """
    raw = spec.raw_size()
    n = spec.sz
    lo = radius.lo()
    sharding = NamedSharding(mesh, P(*MESH_AXES))

    def axis_indices(ax: int):
        size = dim[ax] * n[ax]  # logical extent
        parts = [
            (i * n[ax] - lo[ax] + jnp.arange(raw[ax])) % size for i in range(dim[ax])
        ]
        return jnp.concatenate(parts)

    idx = [axis_indices(ax) for ax in range(3)]

    @jax.jit
    def exchange(arrays):
        def one(arr):
            # extract the logical field from the shell-carrying layout
            g = arr.reshape(dim[0], raw[0], dim[1], raw[1], dim[2], raw[2])
            g = g[:, lo[0] : lo[0] + n[0], :, lo[1] : lo[1] + n[1], :, lo[2] : lo[2] + n[2]]
            logical = g.reshape(dim[0] * n[0], dim[1] * n[1], dim[2] * n[2])
            # every raw cell is a wrapped-window read of the logical field
            out = logical[idx[0]][:, idx[1]][:, :, idx[2]]
            return jax.lax.with_sharding_constraint(out, sharding)

        return jax.tree.map(one, arrays)

    return exchange


def make_exchange_fn(
    mesh: Mesh,
    radius: Radius,
    ndim_extra: int = 0,
    valid_last: Optional[Tuple[Optional[int], Optional[int], Optional[int]]] = None,
):
    """Build a jitted exchange over a pytree of shell-carrying global arrays.

    Returns ``exchange(arrays) -> arrays`` where each array is sharded
    ``P('x','y','z')`` on its last three dims (``ndim_extra`` leading batch/
    quantity dims are unsharded).  Donates its input: the halo write is
    in-place in HBM, like the reference filling halos inside the existing
    allocation.  ``valid_last`` — see ``halo_exchange_shard``.
    """
    mesh_shape = tuple(mesh.shape[a] for a in MESH_AXES)
    spec = P(*([None] * ndim_extra), *MESH_AXES)

    @partial(jax.jit, donate_argnums=0)
    def exchange(arrays):
        def per_shard(*blocks):
            out = []
            for b in blocks:
                # leading batch dims ride along: halo axes are the last three
                if ndim_extra:
                    bb = b.reshape((-1,) + b.shape[-3:])
                    bb = jax.vmap(
                        lambda v: halo_exchange_shard(
                            v, radius, mesh_shape, valid_last=valid_last
                        )
                    )(bb)
                    out.append(bb.reshape(b.shape))
                else:
                    out.append(
                        halo_exchange_shard(b, radius, mesh_shape, valid_last=valid_last)
                    )
            return tuple(out)

        leaves, treedef = jax.tree.flatten(arrays)
        shard_fn = jax.shard_map(
            per_shard,
            mesh=mesh,
            in_specs=tuple(spec for _ in leaves),
            out_specs=tuple(spec for _ in leaves),
        )
        return jax.tree.unflatten(treedef, list(shard_fn(*leaves)))

    return exchange
