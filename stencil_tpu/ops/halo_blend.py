"""Tile-local halo writes — pallas blend kernels for the y/z axes.

Writing a thin received halo slab into the carried shell with
``dynamic_update_slice`` looks cheap, but XLA's layout assignment sees the
y-axis update (a 3-cell sublane sliver) and the z-axis update (a 3-cell lane
sliver) and transposes the WHOLE array to a layout that favors one of them,
paying two full-domain relayout copies per exchange: a radius-3 halo fill of
a 518^3 block measured 9.2 ms where the per-axis work is ~0.45 ms
(scripts/probe6.py; the compiled HLO shows ``{2,0,1}`` internal layouts and a
``copy`` back to ``{2,1,0}``).

These kernels make the write tile-local instead: with
``input_output_aliases`` the block is updated in place, the grid visits ONLY
the (8,128) tiles that contain halo cells, and each visited tile is
read-blended-written in VMEM.  Layout stays the default tiled layout on both
sides (pallas pins it), so the exchange's sweeps stay additive.

Reference analog: the unpack kernels (copy.cuh:26-75) — the reference scatters
received bytes into the shell with a grid-stride loop; GPUs have no tiled
layouts so a plain scatter suffices there.  On TPU the scatter must be
expressed per-tile to avoid the relayout trap; this file is that expression.

The x axis never needs this: x-slabs are whole contiguous planes, which DUS
handles at slab cost in the native layout.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp


def enabled() -> bool:
    """Use the blend kernels for y/z halo writes?  Auto: on for TPU only —
    the relayout trap these kernels dodge is a property of TPU tiled layouts,
    and the tile geometry below is TPU's; any other backend (cpu, gpu, dev
    tunnels) takes the plain-DUS path it has actually been validated on.  Env
    override ``STENCIL_HALO_BLEND=0|1`` forces either path (tests force 1
    with interpret mode to pin blend semantics against DUS)."""
    from stencil_tpu.utils.config import env_choice

    env = env_choice("STENCIL_HALO_BLEND", "auto", ("auto", "0", "1"))
    if env == "0":
        return False
    if env == "1":
        return True
    return jax.default_backend() == "tpu"


def interpret_mode() -> bool:
    return jax.default_backend() != "tpu"

#: second-to-minor (sublane) tile extent per itemsize, minor is always 128
_SUBLANE = {8: 4, 4: 8, 2: 16, 1: 32}


def supports(dtype) -> bool:
    """Blend kernels know the tile geometry only for these itemsizes; exotic
    dtypes (e.g. complex128, itemsize 16) fall back to the DUS path."""
    return jnp.dtype(dtype).itemsize in _SUBLANE


def vma_check(dtypes, valid_last=None, ndim_extra: int = 0) -> bool:
    """The ``check_vma`` value for a shard_map wrapping the exchange: vma
    validation stays ON (True) whenever the blend kernels — whose pallas
    outputs carry no vma annotation — cannot engage for this configuration
    (mirrors the blend condition in ``halo_exchange_multi``)."""
    if not enabled() or ndim_extra != 0:
        return True
    if not all(supports(dt) for dt in dtypes):
        return True
    # padded y/z axes blend too (blend_slab_dynamic), so valid_last does not
    # re-enable validation
    del valid_last
    return False


def _sublane(dtype) -> int:
    return _SUBLANE[jnp.dtype(dtype).itemsize]


def blend_slab(
    block: jax.Array,
    slab: jax.Array,
    axis: int,
    pos: int,
    interpret: bool = False,
) -> jax.Array:
    """Return ``block`` with ``slab`` written at offset ``pos`` along ``axis``
    (0 = x / whole planes, 1 = y / sublane, 2 = z / lane), touching only the
    tiles (axis 0: planes) that contain the region.  ``block`` is consumed
    (aliased to the output).

    The axis-0 case exists for composition, not layout: an x-plane DUS is
    already contiguous, but expressing the write as an aliased pallas call
    keeps the whole halo-write chain in-place inside loop bodies, where the
    jnp ``.at[].set`` form made XLA materialize full-domain copy+DUS fusions
    (~1.4 ms each at 516^3 — scripts/probe12)."""
    from jax.experimental import pallas as pl

    assert axis in (0, 1, 2), axis
    X, Y, Z = block.shape
    r = slab.shape[axis]
    if axis == 0:
        # the aliased input stays in ANY memory space: the kernel never reads
        # it, so the planes being overwritten are not fetched into VMEM
        def kernel0(in_ref, slab_ref, out_ref):
            del in_ref
            out_ref[...] = slab_ref[...]

        return pl.pallas_call(
            kernel0,
            grid=(r,),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec((1, Y, Z), lambda g: (g, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, Y, Z), lambda g: (pos + g, 0, 0)),
            out_shape=jax.ShapeDtypeStruct(block.shape, block.dtype),
            input_output_aliases={0: 0},
            interpret=interpret,
        )(block, slab)
    tile = _sublane(block.dtype) if axis == 1 else 128
    t0 = (pos // tile) * tile  # first touched tile start
    nb = (pos + r - 1) // tile - pos // tile + 1  # tiles spanned
    off = pos - t0  # halo offset inside the first touched tile
    bx = min(8, X)
    gx = -(-X // bx)

    def kernel(in_ref, slab_ref, out_ref):
        g = pl.program_id(1)
        out_ref[...] = in_ref[...]
        for gi in range(nb):
            # static slice bounds per visited tile
            lo = max(off - gi * tile, 0)
            hi = min(off + r - gi * tile, tile)
            s_lo = gi * tile - off + lo  # slab cells already written
            if hi <= lo:
                continue

            def write(gi=gi, lo=lo, hi=hi, s_lo=s_lo):
                if axis == 1:
                    out_ref[:, lo:hi, :] = slab_ref[:, s_lo : s_lo + (hi - lo), :]
                else:
                    out_ref[:, :, lo:hi] = slab_ref[:, :, s_lo : s_lo + (hi - lo)]

            if nb == 1:
                write()
            else:
                pl.when(g == gi)(write)

    if axis == 1:
        blk = (bx, tile, Z)
        sblk = (bx, r, Z)
        index = lambda i, g: (i, t0 // tile + g, 0)
        sindex = lambda i, g: (i, 0, 0)
    else:
        blk = (bx, Y, tile)
        sblk = (bx, Y, r)
        index = lambda i, g: (i, 0, t0 // tile + g)
        sindex = lambda i, g: (i, 0, 0)

    return pl.pallas_call(
        kernel,
        grid=(gx, nb),
        in_specs=[
            pl.BlockSpec(blk, index),
            pl.BlockSpec(sblk, sindex),
        ],
        out_specs=pl.BlockSpec(blk, index),
        out_shape=jax.ShapeDtypeStruct(block.shape, block.dtype),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(block, slab)


def blend_slab_dynamic(
    block: jax.Array,
    slab: jax.Array,
    axis: int,
    pos: jax.Array,
    interpret: bool = False,
) -> jax.Array:
    """``blend_slab`` with a TRACED per-shard offset ``pos`` — the padded
    (uneven) axes case, where the +axis halo lands right after the shard's
    own valid cells (``r_lo + n_valid``, differing on the last shard).  The
    offset rides scalar prefetch (``pltpu.PrefetchScalarGridSpec``) so the
    grid's index map picks the touched tiles per shard at run time; inside
    the kernel the slab rows land via iota==row masks (slab widths are a few
    cells, so ``r`` masked selects beat any gather).  Without this, padded
    domains fall back to ``dynamic_update_slice`` slivers — the full-domain
    relayout trap this module exists to dodge (see module docstring).

    The grid visits ``nb`` tiles starting at the one containing ``pos``,
    indexed MODULO ntiles: a width-r region spans at most nb tiles at any
    alignment, and when it spans fewer the surplus visits wrap to distinct
    low tiles where the kernel's row mask matches nothing and the body is an
    identity copy.  (Clamping instead would revisit the last tile, and with
    resident-block semantics the unconditional ``out = in`` copy of the
    revisit would clobber the rows blended by the first visit.)
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    assert axis in (1, 2), axis
    X, Y, Z = block.shape
    r = slab.shape[axis]
    tile = _sublane(block.dtype) if axis == 1 else 128
    ext = (Y, Z)[axis - 1]
    ntiles = -(-ext // tile)
    # worst-case tiles a width-r region can span at any alignment
    nb = min((r - 1) // tile + 2, ntiles)
    bx = min(8, X)
    gx = -(-X // bx)
    pos = jnp.asarray(pos, jnp.int32).reshape((1,))

    def kernel(pos_ref, in_ref, slab_ref, out_ref):
        g = pl.program_id(1)
        p = pos_ref[0]
        t0 = p // tile
        out_ref[...] = in_ref[...]
        # slab row s lands at row p + s - (t0+g)*tile of the UNWRAPPED tile
        # t0+g; out-of-[0,tile) targets (rows owned by other visits, or any
        # row of a wrapped surplus visit) match no iota and write nothing
        base = p - (t0 + g) * tile
        for s in range(r):
            t = base + s
            if axis == 1:
                rows = jax.lax.broadcasted_iota(jnp.int32, (bx, tile, Z), 1)
                sl = slab_ref[:, s, :][:, None, :]
            else:
                rows = jax.lax.broadcasted_iota(jnp.int32, (bx, Y, tile), 2)
                sl = slab_ref[:, :, s][:, :, None]
            out_ref[...] = jnp.where(rows == t, sl, out_ref[...])

    if axis == 1:
        blk = (bx, tile, Z)
        sblk = (bx, r, Z)
    else:
        blk = (bx, Y, tile)
        sblk = (bx, Y, r)

    # index maps take scalar-prefetch refs AFTER the grid indices (the kernel
    # takes them first)
    def index(i, g, pos_ref):
        tidx = jax.lax.rem(
            pos_ref[0] // tile + jnp.asarray(g, jnp.int32), jnp.int32(ntiles)
        )
        return (i, tidx, 0) if axis == 1 else (i, 0, tidx)

    def sindex(i, g, pos_ref):
        return (i, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(gx, nb),
        in_specs=[
            pl.BlockSpec(blk, index),
            pl.BlockSpec(sblk, sindex),
        ],
        out_specs=pl.BlockSpec(blk, index),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(block.shape, block.dtype),
        input_output_aliases={1: 0},
        interpret=interpret,
    )(pos, block, slab)
