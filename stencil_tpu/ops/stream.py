"""Plane-streaming engine for USER step kernels — fast by default.

In the reference, the stencil kernel is USER code: apps write plain CUDA
through ``Accessor`` (accessor.hpp:13-40, jacobi3d.cu:65-108,
astaroth_sim.cu:65-83) and the GPU cache hierarchy gives every such kernel
operand reuse for free.  The TPU analog of that cache reuse is an explicit
VMEM plane ring — which rounds 1-4 hard-coded into the jacobi/astaroth fast
paths.  This module is the generalization: it runs the SAME ``StepKernel``
signature that ``make_step``'s XLA route runs — ``views[name].sh(dx,dy,dz)``
reads plus ``info.coords()`` — but streams x-planes through VMEM so each HBM
plane is read once per pass instead of once per shifted operand (the XLA
slice formulation re-reads the block ~6x, measured 5-7.5 Gcells/s at 512^3
vs ~40+ for the streamed form).

Two routes, chosen by ``make_stream_step``:

* **plane** — one level per pass: exchange the shell, then stream planes
  with a ``2r``-deep ring (``r`` = the kernel's declared x read distance).
  Works for any per-axis shell widths and any ``r >= 1``.
* **wavefront** — ``m`` levels per pass over an ``s``-wide-shell shard
  (``m <= s // r``, ``r == 1`` only): each HBM plane is read and written
  once per ``m`` iterations (~``8/m`` B/cell), the temporal blocking that
  makes the flagship paths beat the bandwidth roofline.  Supports the z-slab
  form (z halos never touch the tiled array — see
  ``jacobi_shell_wavefront_step``'s layout notes) including the lane-padding
  of ragged plane widths, generalized to any field count.

The engine is bit-compatible with the XLA route: both call the user kernel
with the same per-cell arithmetic, so outputs agree exactly (modulo compiler
excess precision, which the interpret-mode tests pin).

**Split-step overlap schedule** (``overlap ∈ {off, split}``, a tuner axis —
docs/tuning.md "Stream overlap"): the exchange-then-compute macro serializes
the packed shell ppermutes against the whole pass.  Under ``split`` the
macro is restructured so XLA's latency-hiding scheduler can fly the
collectives behind the bulk of the VPU work (the reference's L6
interior/exterior orchestration, src/stencil.cu:567-666; T3/arxiv
2401.16677 is the modern treatment):

* the **interior pass** is the unchanged full-block pass run on the
  PRE-exchange blocks — it carries no data dependency on any ppermute, so
  the scheduler issues ``collective-permute-start`` before it and ``-done``
  after it.  Cells within the dependency cone of the (stale) shell compute
  garbage there, by design;
* the **exterior passes** recompute exactly that boundary band — six narrow
  sub-block passes (width ``3w`` rounded up to the axis tile granule,
  ``w = m·r``) over the freshly exchanged blocks, running the SAME pallas
  kernels so every recomputed cell is
  bitwise identical to the off-schedule value — and blend the width-``w``
  bands back tile-locally (``ops/halo_blend``; x bands are contiguous
  plane DUS).

Correctness rests on two invariants the tier-1 suites pin: (a) a cell at
distance ≥ ``w`` from the shell has a per-level dependency cone that never
reads shell values, so interior-pass values equal off-schedule values
bitwise; (b) the 3-sweep exchange's output halos depend only on interior
values — each sweep's surviving writes come from interior slabs or halos
freshly written by an earlier sweep of the same exchange — so the stale
shell the split schedule carries between macros can never leak into any
valid cell.  Shell cells of a split-step output differ from the off
schedule (stale pass-through vs fresh), which is already sacrificial state:
stream steps mark the shell stale and every consumer re-exchanges.

Structurally ``split`` engages on the ``plane`` and plain ``wavefront``
routes; ``wrap`` has no exchange to hide and the z-slab wavefront
interleaves its slab permutes with the pass, so both degrade to ``off``
with a warning.  Padded (uneven) shards ARE supported: the high-side band
offsets ride the same traced ``n_valid`` arithmetic as the exchange's
dynamic halo blends.

**Fused unpack→blend** (``halo ∈ {array, fused}``, a tuner axis —
docs/tuning.md "Fused halo consumption"): under the packed ``yzpack_*``
exchange routes the macro's unpack step is redundant — the received shell
messages are blended into the big array only so the pass can read them
back out one plane later.  ``halo="fused"`` removes the round trip: the
macro calls ``fused_shell_exchange`` (ops/exchange.py), which returns the
received per-axis shell BUFFERS (corner-patched on the small buffers in
the exchange's sweep order), and the pass consumes them as side inputs —
each level-0 plane is patched in VMEM (x-shell planes replaced from the x
slabs, then y rows from the sublane-major y buffer, then z columns from
the lane-major z buffer, replaying the x→y→z sweep order) before any
kernel level runs.  The big array is NEVER written with halo data: no
blend kernels, no halo DUS, no unpack kernels — the generalization of the
z-slab wavefront's bespoke zero-big-array-halo scheme to every axis of
the plane and plain-wavefront routes.  Because the patched level-0 planes
are bitwise equal to the unfused post-exchange planes, every pass output
— interior AND shell — is bitwise-identical to ``halo="array"``.
Structural gates: the ``yzpack_*`` exchange route, even shards (the pack
cuts at static offsets), blend-supported dtypes, ``overlap=off`` (the
split schedule's exterior bands read exchanged BLOCKS), and the plane /
plain-wavefront routes (a z-slab plan re-plans to the plain form first,
like split).  Ineligible requests degrade to ``array`` with a warning;
the ladder steps ``fused``→``array`` at the same depth before any depth
descent.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from stencil_tpu.core.dim3 import Dim3
from stencil_tpu.utils.compat import shard_map
from stencil_tpu import telemetry
from stencil_tpu.telemetry import names as tm
from stencil_tpu.ops.jacobi_pallas import (
    COMPUTE_UNITS,
    MXU_INPUTS,
    _make_roll,
    _padded_plane_bytes,
    _tpu_compiler_params,
    _vmem_budget,
    _VMEM_STACK_MARGIN,
    _WRAP_MAX_K,
    band_operands,
    make_plane_nbr_sum,
    mxu_flops_per_plane,
    plane_band_unit,
    resolve_compute_unit,
    resolve_mxu_input,
    unit_uses_mxu,
)


#: overlap schedules for the exchanging stream routes — a first-class tuner
#: axis (tune/space.py ``stream_space``; docs/tuning.md "Stream overlap"):
#: ``off`` = exchange-then-compute (the static fallback), ``split`` = the
#: interior/exterior split-step schedule (see module docstring).
STREAM_OVERLAP = ("off", "split")

#: halo consumption for the exchanging stream routes — a first-class tuner
#: axis (tune/space.py ``stream_space``; docs/tuning.md "Fused halo
#: consumption"): ``array`` = the exchange unpacks received shells into the
#: big arrays and the pass reads them back (the static fallback), ``fused``
#: = the packed messages land directly in the pass's level-0 VMEM working
#: planes and the big array never sees a halo write (see module docstring).
STREAM_HALO = ("array", "fused")


class PlaneView:
    """Resident-plane window for one quantity inside a streaming kernel.

    ``sh(dx, dy, dz)`` mirrors ``ShardView.sh`` (the reference's
    ``src[o + Dim3(dx,dy,dz)]`` Accessor read, accessor.hpp:27-40): the
    x offset selects one of the ``2r+1`` VMEM-resident planes, the y/z
    offsets are in-plane rotates.  Rotate wraparound at the plane edges only
    contaminates shell cells the validity contract already sacrifices.

    ``plane_nbr_sum()`` is the compute-unit seam for AXIS-SEPARABLE
    kernels: the sum of the four in-plane face neighbors of the center
    plane, lowered as the historical roll+add chain under ``vpu`` or as ONE
    banded contraction per axis on the matrix unit under the MXU units
    (``bands`` set — the dense ``band_matrix`` circulants under ``mxu``,
    the blocked ``band_wide_tile`` form under ``mxu_band``; ulp-pinned vs
    the chain, a pure summation-order difference).  A kernel's ``mxu``
    form (``make_stream_step(mxu_kernel=...)``) writes its separable
    in-plane taps through this helper; kernels with no such form never see
    bands and structurally degrade to ``vpu``.
    """

    def __init__(self, window: Tuple[jax.Array, ...], roll, bands=None):
        self._window = window
        self._r = (len(window) - 1) // 2
        self._roll = roll
        self._bands = bands  # nbr_sum(center) closure over the resident
        # contraction constants (ops/jacobi_pallas.make_plane_nbr_sum
        # bound to this pass's refs), or None (= vpu)

    def sh(self, dx: int = 0, dy: int = 0, dz: int = 0) -> jax.Array:
        # ALL axes are bounded by the declared read radius: an in-plane
        # shift beyond it would wrap opposite-edge values into cells the
        # validity contract counts as correct — silently wrong results, so
        # fail at trace time instead
        assert all(-self._r <= d <= self._r for d in (dx, dy, dz)), (
            (dx, dy, dz), self._r,
        )
        v = self._window[self._r + dx]
        if dy:
            v = self._roll(v, -dy, 0)
        if dz:
            v = self._roll(v, -dz, 1)
        return v

    def plane_nbr_sum(self) -> jax.Array:
        """``sh(0,1,0) + sh(0,-1,0) + sh(0,0,1) + sh(0,0,-1)`` — on the MXU
        as banded contractions when this view carries band constants."""
        c = self.center()
        if self._bands is not None:
            return self._bands(c)
        return (
            self.sh(0, 1, 0)
            + self.sh(0, -1, 0)
            + self.sh(0, 0, 1)
            + self.sh(0, 0, -1)
        )

    def center(self) -> jax.Array:
        return self._window[self._r]


@dataclasses.dataclass
class PlaneInfo:
    """Traced per-plane context handed to streaming kernels.  ``coords``
    returns broadcast-compatible pieces — x a scalar (the whole plane shares
    one global x), y a column, z a row — so kernels written against
    ``BlockInfo.coords()`` broadcasting run unchanged."""

    x_global: jax.Array  # int32 scalar: wrapped global x of the output plane
    y_global: jax.Array  # (Y, 1) int32 wrapped global y
    z_global: jax.Array  # (1, Z) int32 wrapped global z
    global_size: Dim3
    level: int  # wavefront level (1-based); 1 on the plane route

    def coords(self):
        return self.x_global, self.y_global, self.z_global


#: a streaming kernel is just a StepKernel evaluated on planes
PlaneKernel = Callable[[Dict[str, PlaneView], PlaneInfo], Dict[str, jax.Array]]


def _yz_coord_planes(origin_ref, Yr, Zr, off_y, off_z, gsize):
    """Wrapped global y/z coordinates of the raw plane, as a (Yr, 1) column
    and a (1, Zr) row (2D iotas — Mosaic has no 1D iota)."""
    y = lax.broadcasted_iota(jnp.int32, (Yr, 1), 0)
    z = lax.broadcasted_iota(jnp.int32, (1, Zr), 1)
    gy, gz = jnp.int32(gsize.y), jnp.int32(gsize.z)
    # + gsize keeps lax.rem's operand non-negative (origin - shell >= -shell)
    y_g = lax.rem(origin_ref[1] + gy + y - jnp.int32(off_y), gy)
    z_g = lax.rem(origin_ref[2] + gz + z - jnp.int32(off_z), gz)
    return y_g, z_g


def _fused_plane_patch(v, xplane, yst, zst, t, lo_y, hi_y, lo_z, hi_z):
    """Patch one level-0 VMEM plane from the fused shell buffers, replaying
    the exchange's sweep order x -> y -> z: replace the whole plane when
    this is an x-shell position (``t`` is the threshold-iota row bound —
    the plane height at shell positions, 0 otherwise: the broadcast-compare
    pattern the dynamic blend kernels use), then land the y rows from the
    sublane-major buffer and the z columns from the lane-major one.
    Shared by the plane and wavefront passes (``fused_shell`` mode)."""
    Y, Z = v.shape
    rowv = lax.broadcasted_iota(jnp.int32, (Y, Z), 0)
    colv = lax.broadcasted_iota(jnp.int32, (Y, Z), 1)
    v = jnp.where(rowv < t, xplane, v)
    for j in range(lo_y):
        v = jnp.where(rowv == j, yst[j][None, :], v)
    for j in range(hi_y):
        v = jnp.where(rowv == Y - hi_y + j, yst[lo_y + j][None, :], v)
    for j in range(lo_z):
        v = jnp.where(colv == j, zst[j][:, None], v)
    for j in range(hi_z):
        v = jnp.where(colv == Z - hi_z + j, zst[lo_z + j][:, None], v)
    return v


def _pass_band_setup(compute_unit: str, mxu_input: str, plane_y: int,
                     plane_z: int, where: str):
    """``(effective unit, band args, band in_specs, nbr_sum)`` for one
    streaming pass's plane geometry — empty/None pieces under ``vpu``.
    Each pass tiles its OWN geometry (the split schedule's narrow band
    sub-blocks differ from the interior pass), so the band→dense
    structural degrade (``plane_band_unit``) is per pass; the contraction
    VALUES stay identical across variants up to summation order, so the
    pass outputs keep the documented ulp pins either way."""
    if not unit_uses_mxu(compute_unit):
        return compute_unit, [], [], None
    unit = plane_band_unit(compute_unit, plane_y, plane_z, where=where)
    args, specs = band_operands(plane_y, plane_z, unit, mxu_input)
    nbr = make_plane_nbr_sum(plane_y, plane_z, unit, mxu_input)
    return unit, args, specs, nbr


def stream_plane_pass(
    kernel: PlaneKernel,
    names: Sequence[str],
    raws: Sequence[jax.Array],  # per-quantity (X, Y, Z) shell-carrying blocks
    lo: Dim3,
    hi: Dim3,  # shell widths (allocation minus interior)
    x_radius: int,  # kernel x read distance r; ring depth is 2r
    origin: jax.Array,  # (3,) int32 global coords of the interior start
    global_size: Dim3,
    interpret: bool = False,
    compute_unit: str = "vpu",  # "mxu"/"mxu_band": band constants ride in
    # as resident inputs and the views' plane_nbr_sum contracts on the
    # matrix unit (dense circulants vs blocked band tiles)
    mxu_input: str = "f32",  # MXU operand precision (jacobi_wrap_step)
    f32_accumulate: bool = False,  # bf16-storage variant: planes upcast to
    # f32 for the kernel, one downcast at the interior store (pass-through
    # shell planes keep their storage bytes bit-exact)
    fused_shell=None,  # (xbufs, ybufs, zbufs) per quantity — the packed
    # halo messages land in the level-0 planes in VMEM instead of having
    # been unpacked into the blocks (halo="fused"; see module docstring)
) -> List[jax.Array]:
    """ONE kernel level over shell-carrying blocks, streaming x-planes with a
    ``2r``-deep ring per quantity; shell planes and the in-plane shell ring
    pass through unchanged (the exchange owns halo cells).  Generalizes
    ``mean6_plane_step``/``jacobi_plane_step`` to user kernels, any field
    count, and any ``r >= 1``.

    With ``fused_shell`` the blocks' shell cells are STALE and the fresh
    halos ride as side inputs (``fused_shell_exchange``'s buffers): every
    loaded plane is patched in VMEM — x-shell planes replaced from the x
    slabs, then y rows, then z columns, replaying the exchange's sweep
    order — before it feeds the ring, the kernel, or the pass-through, so
    the pass is bitwise-identical to running over exchanged blocks."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nq = len(names)
    X, Y, Z = raws[0].shape
    r = x_radius
    assert r >= 1 and lo.x >= r and hi.x >= r, (r, lo, hi)
    assert lo.y >= r and hi.y >= r and lo.z >= r and hi.z >= r, (r, lo, hi)
    y0, y1 = lo.y, Y - hi.y
    z0, z1 = lo.z, Z - hi.z
    roll = _make_roll(interpret)
    gsize = global_size
    compute_unit, b_args, b_specs, nbr = _pass_band_setup(
        compute_unit, mxu_input, Y, Z, "stream-plane"
    )
    mxu = unit_uses_mxu(compute_unit)
    up = (lambda v: v.astype(jnp.float32)) if f32_accumulate else (lambda v: v)

    def body(origin_ref, *refs):
        in_refs = refs[:nq]
        if mxu:
            b1, b2 = refs[nq][...], refs[nq + 1][...]
            bands = lambda c: nbr(c, b1, b2)
            refs = refs[: nq] + refs[nq + 2 :]
        else:
            bands = None
        if fused_shell is not None:
            xs_refs = refs[nq : 2 * nq]
            ys_refs = refs[2 * nq : 3 * nq]
            zs_refs = refs[3 * nq : 4 * nq]
            refs = refs[:nq] + refs[4 * nq :]
        out_refs = refs[nq : 2 * nq]
        rings = refs[2 * nq :]
        i = pl.program_id(0)
        curs = [ref[0] for ref in in_refs]
        if fused_shell is not None:
            # level-0 VMEM patch (module docstring; _fused_plane_patch)
            ip = jnp.minimum(i, X - 1)  # the replayed last-plane refetches
            t = jnp.where(
                jnp.logical_or(ip < lo.x, ip >= X - hi.x),
                jnp.int32(Y),
                jnp.int32(0),
            )
            for q in range(nq):
                curs[q] = _fused_plane_patch(
                    curs[q], xs_refs[q][0], ys_refs[q][0], zs_refs[q][0],
                    t, lo.y, hi.y, lo.z, hi.z,
                )

        y_g, z_g = _yz_coord_planes(origin_ref, Y, Z, lo.y, lo.z, gsize)

        # output plane j = i - r; window is raw planes j-r .. j+r
        j = i - r
        in_window = jnp.logical_and(j >= lo.x, j <= X - hi.x - 1)

        def plane(q, t):  # raw plane i - t for quantity q (t in [0, 2r])
            return curs[q] if t == 0 else rings[q][(i - t) % (2 * r)]

        @pl.when(jnp.logical_and(i >= 1, i <= X + r - 1))
        def _():
            @pl.when(in_window)
            def _():
                views = {
                    names[q]: PlaneView(
                        tuple(up(plane(q, 2 * r - d)) for d in range(2 * r + 1)),
                        roll,
                        bands=bands,
                    )
                    for q in range(nq)
                }
                x_g = lax.rem(
                    origin_ref[0] + jnp.int32(gsize.x) + j - jnp.int32(lo.x),
                    jnp.int32(gsize.x),
                )
                info = PlaneInfo(x_g, y_g, z_g, gsize, 1)
                vals = kernel(views, info)
                for q, name in enumerate(names):
                    cent = plane(q, r)
                    out_refs[q][0] = cent  # keep the y/z shell ring
                    if name in vals:
                        out_refs[q][0, y0:y1, z0:z1] = vals[name][
                            y0:y1, z0:z1
                        ].astype(cent.dtype)

            @pl.when(jnp.logical_not(in_window))
            def _():
                for q in range(nq):
                    # shell plane j = i - r passes through from the ring
                    # (slot is garbage for i < r, where plane j < 0 doesn't
                    # exist — those writes land on out plane 0, which step
                    # i == r rewrites with the real pass-through)
                    out_refs[q][0] = plane(q, r)

        @pl.when(i == 0)
        def _():
            for q in range(nq):
                out_refs[q][0] = curs[q]  # first plane passes through

        # push the fetched plane (skip replayed last-plane refetches)
        @pl.when(i <= X - 1)
        def _():
            for q in range(nq):
                rings[q][i % (2 * r)] = curs[q]

    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + [
        pl.BlockSpec((1, Y, Z), lambda i: (jnp.minimum(i, X - 1), 0, 0))
        for _ in range(nq)
    ]
    args = [origin.astype(jnp.int32), *raws]
    if mxu:
        # resident contraction constants, fetched once like the d2 plane
        in_specs += b_specs
        args += b_args
    if fused_shell is not None:
        xs_list, ys_list, zs_list = fused_shell
        assert all(b.shape == (lo.x + hi.x, Y, Z) for b in xs_list)
        assert all(b.shape == (X, lo.y + hi.y, Z) for b in ys_list)
        assert all(b.shape == (X, lo.z + hi.z, Y) for b in zs_list)

        def xidx(i):
            # the x slab plane for shell positions; the long interior
            # stretch clamps to slot 0 (a constant index — no refetch)
            ip = jnp.minimum(i, X - 1)
            return (
                jnp.where(
                    ip < lo.x,
                    ip,
                    jnp.where(ip >= X - hi.x, lo.x + ip - (X - hi.x), 0),
                ),
                0,
                0,
            )

        in_specs += [pl.BlockSpec((1, Y, Z), xidx) for _ in range(nq)]
        in_specs += [
            pl.BlockSpec(
                (1, lo.y + hi.y, Z), lambda i: (jnp.minimum(i, X - 1), 0, 0)
            )
            for _ in range(nq)
        ]
        in_specs += [
            pl.BlockSpec(
                (1, lo.z + hi.z, Y), lambda i: (jnp.minimum(i, X - 1), 0, 0)
            )
            for _ in range(nq)
        ]
        args += list(xs_list) + list(ys_list) + list(zs_list)
    out_specs = tuple(
        pl.BlockSpec((1, Y, Z), lambda i: (jnp.clip(i - r, 0, X - 1), 0, 0))
        for _ in range(nq)
    )
    out_shape = tuple(
        jax.ShapeDtypeStruct((X, Y, Z), b.dtype) for b in raws
    )
    outs = pl.pallas_call(
        body,
        grid=(X + r,),
        in_specs=in_specs,
        out_specs=out_specs if nq > 1 else out_specs[0],
        out_shape=out_shape if nq > 1 else out_shape[0],
        scratch_shapes=[
            pltpu.VMEM((2 * r, Y, Z), b.dtype) for b in raws
        ],
        interpret=interpret,
        **_tpu_compiler_params(interpret),
    )(*args)
    return list(outs) if nq > 1 else [outs]


def stream_wavefront_pass(
    kernel: PlaneKernel,
    names: Sequence[str],
    raws: Sequence[jax.Array],  # per-quantity (Xr, Yr, Zr) FILLED-shell blocks
    m: int,  # levels to advance (<= shell width)
    s_off: int,  # shell width (raw index of the interior start)
    origin: jax.Array,
    global_size: Dim3,
    z_slabs: Sequence[jax.Array] = None,  # per-q (Xr, 2s, Yr) z-major slabs
    z_valid: int = None,  # logical plane width; [z_valid, Zr) is lane padding
    alias: bool = False,
    interpret: bool = False,
    compute_unit: str = "vpu",  # "mxu"/"mxu_band": resident band constants
    # + contraction via the views' plane_nbr_sum (see stream_plane_pass)
    mxu_input: str = "f32",  # MXU operand precision (jacobi_wrap_step)
    f32_accumulate: bool = False,  # bf16-storage variant: upcast at load,
    # f32 level rings + arithmetic, one downcast at the final store/emit
    fused_shell=None,  # (xbufs, ybufs, zbufs) per quantity — the packed
    # halo messages land in the level-0 planes in VMEM (halo="fused");
    # mutually exclusive with z_slabs (the bespoke z-only scheme)
):
    """``m`` kernel levels in ONE pass over ``s_off``-shell-carrying shards —
    the user-kernel generalization of ``jacobi_shell_wavefront_step`` (see
    its docstring for the shrinking-validity contamination argument, the
    z-slab layout, and the lane-padding rationale; all carry over verbatim).
    Returns the advanced blocks, plus per-quantity outgoing z slabs when
    ``z_slabs`` is given.

    With ``fused_shell`` the blocks' shell cells are STALE and every axis's
    fresh halos ride as side inputs (``fused_shell_exchange``): each
    level-0 plane is patched in VMEM — x-shell planes replaced, then y
    rows, then z columns (the exchange's sweep order) — so the level chain
    sees exactly the planes an in-array exchange would have produced and
    the pass output is bitwise-identical to the unfused form."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nq = len(names)
    Xr, Yr, Zr = raws[0].shape
    zv = Zr if z_valid is None else z_valid
    assert 1 <= m <= s_off and 2 * s_off < min(Xr, Yr, zv), (m, s_off, zv)
    assert z_slabs is None or fused_shell is None
    gsize = global_size
    assert 2 * s_off < gsize.x, (s_off, gsize)  # non-negative lax.rem operand
    roll = _make_roll(interpret)
    compute_unit, b_args, b_specs, nbr = _pass_band_setup(
        compute_unit, mxu_input, Yr, Zr, "stream-wavefront"
    )
    mxu = unit_uses_mxu(compute_unit)
    acc_dtypes = [
        jnp.float32 if f32_accumulate else b.dtype for b in raws
    ]
    up = (lambda v: v.astype(jnp.float32)) if f32_accumulate else (lambda v: v)

    def body(origin_ref, *refs):
        in_refs = refs[:nq]
        refs = refs[nq:]
        if mxu:
            b1, b2 = refs[0][...], refs[1][...]
            bands = lambda c: nbr(c, b1, b2)
            refs = refs[2:]
        else:
            bands = None
        if fused_shell is not None:
            xs_refs = refs[:nq]
            ys_refs = refs[nq : 2 * nq]
            zsf_refs = refs[2 * nq : 3 * nq]
            refs = refs[3 * nq :]
        if z_slabs is not None:
            zs_refs = refs[:nq]
            out_refs = refs[nq : 2 * nq]
            zout_refs = refs[2 * nq : 3 * nq]
            rings = refs[3 * nq :]
        else:
            out_refs = refs[:nq]
            zout_refs = None
            rings = refs[nq :]
        i = pl.program_id(0)
        # level-0 raw plane i per quantity (upcast once under f32_accumulate)
        vals = [up(ref[0]) for ref in in_refs]
        y_g, z_g = _yz_coord_planes(origin_ref, Yr, Zr, s_off, s_off, gsize)
        if fused_shell is not None:
            # level-0 VMEM patch (module docstring; _fused_plane_patch —
            # upcast once under f32_accumulate, like the raw planes)
            s = s_off
            t = jnp.where(
                jnp.logical_or(i < s, i >= Xr - s), jnp.int32(Yr), jnp.int32(0)
            )
            for q in range(nq):
                vals[q] = _fused_plane_patch(
                    vals[q], up(xs_refs[q][0]), up(ys_refs[q][0]),
                    up(zsf_refs[q][0]), t, s, s, s, s,
                )
        if z_slabs is not None:
            # patch the z-shell columns in VMEM — never stored in the big
            # array (see jacobi_shell_wavefront_step)
            col = lax.broadcasted_iota(jnp.int32, (Yr, Zr), 1)
            for q in range(nq):
                zst = up(jnp.swapaxes(zs_refs[q][0], 0, 1))  # (Yr, 2s)
                v = vals[q]
                for j in range(s_off):
                    v = jnp.where(col == j, zst[:, j][:, None], v)
                    v = jnp.where(
                        col == zv - s_off + j, zst[:, s_off + j][:, None], v
                    )
                vals[q] = v
        for s in range(1, m + 1):
            prevs = [rings[q][s - 1, i % 2] for q in range(nq)]
            cents = [rings[q][s - 1, (i + 1) % 2] for q in range(nq)]
            for q in range(nq):
                rings[q][s - 1, i % 2] = vals[q]  # push plane i-s+1
            views = {
                names[q]: PlaneView((prevs[q], cents[q], vals[q]), roll,
                                    bands=bands)
                for q in range(nq)
            }
            x_g = lax.rem(
                origin_ref[0] + jnp.int32(gsize.x) + i - jnp.int32(s + s_off),
                jnp.int32(gsize.x),
            )
            info = PlaneInfo(x_g, y_g, z_g, gsize, s)
            new = kernel(views, info)
            vals = [
                new[names[q]].astype(cents[q].dtype)
                if names[q] in new
                else cents[q]
                for q in range(nq)
            ]
        for q in range(nq):
            # level-m plane i-m (the one f32_accumulate downcast)
            out_refs[q][0] = vals[q].astype(raws[q].dtype)
            if zout_refs is not None:
                emit = jnp.concatenate(
                    [
                        vals[q][:, zv - 2 * s_off : zv - s_off],
                        vals[q][:, s_off : 2 * s_off],
                    ],
                    axis=1,
                ).astype(raws[q].dtype)  # (Yr, 2s)
                zout_refs[q][0] = jnp.swapaxes(emit, 0, 1)

    out_idx = lambda i: (jnp.maximum(i - m, 0), 0, 0)
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + [
        pl.BlockSpec((1, Yr, Zr), lambda i: (i, 0, 0)) for _ in range(nq)
    ]
    out_specs: list = [pl.BlockSpec((1, Yr, Zr), out_idx) for _ in range(nq)]
    out_shape: list = [
        jax.ShapeDtypeStruct((Xr, Yr, Zr), b.dtype) for b in raws
    ]
    args = [origin.astype(jnp.int32), *raws]
    if mxu:
        in_specs += b_specs
        args += b_args
    if fused_shell is not None:
        xs_list, ys_list, zs_list = fused_shell
        s = s_off
        assert all(b.shape == (2 * s, Yr, Zr) for b in xs_list)
        assert all(b.shape == (Xr, 2 * s, Zr) for b in ys_list)
        assert all(b.shape == (Xr, 2 * s, Yr) for b in zs_list)

        def xidx(i):
            # x slab slot for shell planes; interior clamps to a constant
            # slot 0 (no refetch over the long middle stretch)
            return (
                jnp.where(
                    i < s, i, jnp.where(i >= Xr - s, s + i - (Xr - s), 0)
                ),
                0,
                0,
            )

        in_specs += [pl.BlockSpec((1, Yr, Zr), xidx) for _ in range(nq)]
        in_specs += [
            pl.BlockSpec((1, 2 * s, Zr), lambda i: (i, 0, 0))
            for _ in range(nq)
        ]
        in_specs += [
            pl.BlockSpec((1, 2 * s, Yr), lambda i: (i, 0, 0))
            for _ in range(nq)
        ]
        args += list(xs_list) + list(ys_list) + list(zs_list)
    if z_slabs is not None:
        for q in range(nq):
            assert z_slabs[q].shape == (Xr, 2 * s_off, Yr), z_slabs[q].shape
        in_specs += [
            pl.BlockSpec((1, 2 * s_off, Yr), lambda i: (i, 0, 0))
            for _ in range(nq)
        ]
        out_specs += [pl.BlockSpec((1, 2 * s_off, Yr), out_idx) for _ in range(nq)]
        out_shape += [
            jax.ShapeDtypeStruct((Xr, 2 * s_off, Yr), b.dtype) for b in raws
        ]
        args += list(z_slabs)
    # in-place safe (write trails read by m+1 planes); un-aliased is ~20%
    # faster at deep m (probe21b) at the cost of fresh output buffers.
    # (Band-matrix inputs sit between the raws and the slabs, so the alias
    # map stays raw-q -> out-q regardless.)
    aliases = {1 + q: q for q in range(nq)} if alias else {}
    outs = pl.pallas_call(
        body,
        grid=(Xr,),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        input_output_aliases=aliases,
        scratch_shapes=[
            pltpu.VMEM((m, 2, Yr, Zr), acc) for acc in acc_dtypes
        ],
        interpret=interpret,
        **_tpu_compiler_params(interpret),
    )(*args)
    outs = list(outs)
    if z_slabs is not None:
        return outs[:nq], outs[nq:]
    return outs, None


def stream_wrap_pass(
    kernel: PlaneKernel,
    names: Sequence[str],
    blocks: Sequence[jax.Array],  # per-quantity BARE (X, Y, Z) interiors
    k: int,  # temporal depth (1 <= k <= X//2)
    origin: jax.Array,  # (3,) int32 — global coords of the block start
    global_size: Dim3,
    interpret: bool = False,
    compute_unit: str = "vpu",  # "mxu"/"mxu_band": resident band constants
    # + contraction via the views' plane_nbr_sum (see stream_plane_pass)
    mxu_input: str = "f32",  # MXU operand precision (jacobi_wrap_step)
    f32_accumulate: bool = False,  # bf16-storage variant (see
    # stream_wavefront_pass)
) -> List[jax.Array]:
    """``k`` kernel levels over the WHOLE (single-device) domain with the
    periodic wrap folded in — the user-kernel generalization of
    ``jacobi_wrap_step`` (see its docstring: the x-wrap rides the modular
    block index map with a ``2k``-step replay closing every level's ring;
    the y/z wrap is the natural roll wraparound on exact-sized planes).
    No shell, no exchange, ~8/k HBM bytes per cell per iteration."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nq = len(names)
    X, Y, Z = blocks[0].shape
    assert 1 <= k <= X // 2, (k, X)
    roll = _make_roll(interpret)
    gsize = global_size
    compute_unit, b_args, b_specs, nbr = _pass_band_setup(
        compute_unit, mxu_input, Y, Z, "stream-wrap"
    )
    mxu = unit_uses_mxu(compute_unit)
    acc_dtypes = [
        jnp.float32 if f32_accumulate else b.dtype for b in blocks
    ]
    up = (lambda v: v.astype(jnp.float32)) if f32_accumulate else (lambda v: v)

    def body(origin_ref, *refs):
        in_refs = refs[:nq]
        refs = refs[nq:]
        if mxu:
            b1, b2 = refs[0][...], refs[1][...]
            bands = lambda c: nbr(c, b1, b2)
            refs = refs[2:]
        else:
            bands = None
        out_refs = refs[:nq]
        rings = refs[nq:]
        i = pl.program_id(0)
        vals = [up(ref[0]) for ref in in_refs]  # level-0 plane i (mod X)
        y_g, z_g = _yz_coord_planes(origin_ref, Y, Z, 0, 0, gsize)
        for s in range(1, k + 1):
            prevs = [rings[q][s - 1, i % 2] for q in range(nq)]
            cents = [rings[q][s - 1, (i + 1) % 2] for q in range(nq)]
            for q in range(nq):
                rings[q][s - 1, i % 2] = vals[q]
            views = {
                names[q]: PlaneView((prevs[q], cents[q], vals[q]), roll,
                                    bands=bands)
                for q in range(nq)
            }
            x_g = lax.rem(
                origin_ref[0] + jnp.int32(gsize.x) + i - jnp.int32(s),
                jnp.int32(gsize.x),
            )
            info = PlaneInfo(x_g, y_g, z_g, gsize, s)
            new = kernel(views, info)
            vals = [
                new[names[q]].astype(cents[q].dtype)
                if names[q] in new
                else cents[q]
                for q in range(nq)
            ]
        for q in range(nq):
            # level-k plane (i - k) % X (the one f32_accumulate downcast)
            out_refs[q][0] = vals[q].astype(blocks[q].dtype)

    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + [
        pl.BlockSpec((1, Y, Z), lambda i: (i % X, 0, 0)) for _ in range(nq)
    ]
    args = [origin.astype(jnp.int32), *blocks]
    if mxu:
        in_specs += b_specs
        args += b_args
    outs = pl.pallas_call(
        body,
        grid=(X + 2 * k,),
        in_specs=in_specs,
        out_specs=tuple(
            pl.BlockSpec((1, Y, Z), lambda i: ((i - k) % X, 0, 0))
            for _ in range(nq)
        ),
        out_shape=tuple(
            jax.ShapeDtypeStruct((X, Y, Z), b.dtype) for b in blocks
        ),
        scratch_shapes=[pltpu.VMEM((k, 2, Y, Z), acc) for acc in acc_dtypes],
        interpret=interpret,
        **_tpu_compiler_params(interpret),
    )(*args)
    # out_shape is always a tuple, so pallas returns a tuple even for nq=1
    return list(outs)


def stream_vmem_fits(
    m: int, plane_y: int, plane_z: int, itemsizes: Sequence[int], z_slabs: bool,
    ring_itemsizes: Sequence[int] = None,
) -> bool:
    """VMEM model of the generic wavefront: per quantity, 2m ring planes +
    4 pipeline planes (+ 4 z-slab blocks), plus a PER-QUANTITY stack margin —
    the level loop holds each field's roll/select temporaries live at once
    (measured: 8-field m=2 at 518x640 planes reported 108.6 MB against an
    85 MB block model, ~2.6 MB of stack per field).  Same padded-bytes
    accounting as ``wavefront_vmem_bytes``.  ``ring_itemsizes`` overrides
    the ring planes' itemsizes: bf16 STORAGE streams 2-byte pipeline planes
    but carries its level rings at f32 (the ``f32_accumulate`` contract),
    so the rings must be modeled at the NATIVE itemsize or the gate lies."""
    ring = itemsizes if ring_itemsizes is None else ring_itemsizes
    est = 0
    for it, rit in zip(itemsizes, ring):
        est += 2 * m * _padded_plane_bytes(plane_y, plane_z, rit)
        est += 4 * _padded_plane_bytes(plane_y, plane_z, it)
        if z_slabs:
            est += 4 * _padded_plane_bytes(2 * m, plane_y, it)
    return est + _VMEM_STACK_MARGIN * len(itemsizes) <= _vmem_budget()


def _tuned_stream_plan(dd, x_radius: int, separable: bool) -> dict:
    """A structurally VALID persisted plan for this domain from the
    autotuner, or None.  Validity is re-checked here (not trusted from the
    file): the cache key pins chip/shape/dtype/mesh/radius/route, but a
    hand-edited or cross-version file must degrade to the static plan, not
    crash the build."""
    from stencil_tpu import tune

    cfg = tune.best_config(dd.tune_key("stream"))
    if cfg is None:
        return None
    route = cfg.get("route")
    m = cfg.get("m")
    plan = {
        "route": route,
        "m": m,
        "z_slabs": bool(cfg.get("z_slabs", False)),
        "grouping": cfg.get("grouping", "joint"),
    }
    if cfg.get("alias") is not None:
        plan["alias"] = bool(cfg["alias"])
    # the overlap axis joined the persisted vocabulary WITHOUT a schema bump:
    # pre-overlap (v2-era) entries simply lack the key, and the resolver
    # falls through to the static ``off`` — warm caches stay warm.  A
    # present-but-garbage value invalidates the plan below (miss to static,
    # never a crash), like any other hand-edited field.
    if cfg.get("overlap") is not None:
        plan["overlap"] = cfg["overlap"]
    # the compute-unit axis rides the same no-schema-bump rule: absent =
    # the static vpu, garbage invalidates the plan below.  Pre-variant
    # entries (``mxu`` from before the band form existed) stay warm: the
    # value is still in the vocabulary
    if cfg.get("compute_unit") is not None:
        plan["compute_unit"] = cfg["compute_unit"]
    # ...and the MXU input-precision axis: absent = the static f32
    if cfg.get("mxu_input") is not None:
        plan["mxu_input"] = cfg["mxu_input"]
    # ...and so does the fused-halo axis: pre-halo entries lack the key and
    # resolve to the static "array"; garbage invalidates to static
    if cfg.get("halo") is not None:
        plan["halo"] = cfg["halo"]
    n = dd.local_spec().sz
    shell = dd._shell_radius
    lo, hi = shell.lo(), shell.hi()
    padded = any(v is not None for v in dd._valid_last)
    ok = isinstance(m, int) and m >= 1
    if ok and plan.get("overlap") is not None:
        ok = plan["overlap"] in STREAM_OVERLAP
    if ok and plan.get("halo") is not None:
        ok = plan["halo"] in STREAM_HALO
    if ok and plan.get("compute_unit") is not None:
        ok = plan["compute_unit"] in COMPUTE_UNITS
    if ok and plan.get("mxu_input") is not None:
        ok = plan["mxu_input"] in MXU_INPUTS
    if ok and plan["grouping"] == "per-field":
        ok = separable and len(dd._handles) > 1
    elif ok and plan["grouping"] != "joint":
        ok = False
    if ok and route == "wrap":
        ok = dd.num_subdomains() == 1 and x_radius == 1 and m <= n.x // 2
    elif ok and route == "wavefront":
        uniform = len({lo.x, lo.y, lo.z, hi.x, hi.y, hi.z}) == 1
        v_min = min(
            (dd._valid_last[ax] if dd._valid_last[ax] is not None else n[ax])
            for ax in range(3)
        )
        ok = (
            x_radius == 1
            and uniform
            and lo.x >= 2
            and 2 <= m <= min(lo.x, v_min)
            and not (plan["z_slabs"] and padded)
        )
    elif ok and route == "plane":
        ok = m == 1 and not plan["z_slabs"]
    elif ok:
        ok = False
    if not ok:
        from stencil_tpu.utils.logging import log_warn

        log_warn(
            f"tuned stream config {cfg} is structurally invalid for this "
            "domain (shell/shards changed since it was measured?); using "
            "the static plan"
        )
        return None
    return plan


def plan_stream(dd, x_radius: int, path: str = "auto", separable: bool = False,
                max_m: int = None) -> dict:
    """Route planning for ``make_stream_step`` on a REALIZED domain.

    Returns ``{"route": "wrap"|"wavefront"|"plane", "m": int,
    "z_slabs": bool, "grouping": str}``.  On a SINGLE subdomain the wrap
    route wins (periodic boundary folded into the kernel: no shell reads,
    no exchange, deepest temporal blocking).  Wavefront needs: x_radius 1,
    uniform face shell >= 2; depth m = the deepest level count that fits
    the VMEM model, capped by the shell width and the measured plateau
    (_WRAP_MAX_K).  The plane route covers everything else the engine
    supports.

    PADDED (uneven) shards run BOTH routes: the exchange blends each halo at
    the dynamic valid-width offset, i.e. contiguously after the valid cells,
    so (a) every valid cell's stencil reads the right neighbor, (b) the
    wrapped linear coordinate formula ``(origin - s + index) mod g`` is
    correct at the halo positions too (the global size equals the last
    shard's origin + valid width), and (c) pad cells beyond the halo
    contaminate only the sacrificial shrinking-validity levels — the same
    argument as the wavefront's dead lane padding.  Hence the PLAIN
    wavefront works on padded shards with no kernel changes; only the
    z-slab form (static emit slices at the interior z boundary) stays
    even-shard-only, and the depth is additionally capped by the smallest
    VALID extent (a shard narrower than the shell cannot fill its
    neighbor's halo).

    ``path`` forces a route: "plane" skips the wavefront upgrade (per-step
    exchange parity, e.g. comm-volume modeling); "wavefront" raises instead
    of falling back.  Raises ValueError for N-D component data (the engine
    streams scalar planes only).

    ``separable=True`` declares that the kernel handles arbitrary SUBSETS of
    the views dict (each field's update reads only that field — astaroth's
    per-field mean).  When all fields together blow the VMEM model, the plan
    then falls back to per-field kernel calls ("grouped": one streaming pass
    per field per macro, same total HBM traffic) instead of a shallower m.
    ``max_m`` caps the wavefront depth (the runtime compile-failure fallback
    steps it down).
    """
    if any(h.components for h in dd._handles):
        raise ValueError("the streaming engine does not support N-D component data")
    if path not in ("auto", "plane", "wavefront", "wrap"):
        raise ValueError(f"unknown stream path {path!r}")
    # the autotuner's persisted pick wins over the static model below, but
    # only on the unconstrained auto path: a forced route is an explicit
    # request, and a depth cap (user stream_depth / the ladder's compile-
    # failure step-down) must re-plan statically under the cap rather than
    # re-apply the tuned depth that just failed
    if path == "auto" and max_m is None:
        tuned = _tuned_stream_plan(dd, x_radius, separable)
        if tuned is not None:
            return tuned
    padded = any(v is not None for v in dd._valid_last)
    shell = dd._shell_radius
    lo, hi = shell.lo(), shell.hi()
    n = dd.local_spec().sz
    if not all(lo[ax] >= x_radius and hi[ax] >= x_radius for ax in range(3)):
        raise ValueError(
            f"shell {lo}/{hi} narrower than the kernel x_radius {x_radius}"
        )
    uniform = len({lo.x, lo.y, lo.z, hi.x, hi.y, hi.z}) == 1
    s = lo.x
    # pipeline planes stream at the STORAGE itemsize; the level rings carry
    # the f32_accumulate working precision, i.e. the native itemsize
    itemsizes = [dd.field_dtype(h).itemsize for h in dd._handles]
    ring_sizes = [h.dtype.itemsize for h in dd._handles]
    # single device: the WRAP route folds the periodic boundary into the
    # kernel's index maps/rotates — no shell reads, no exchange, the deepest
    # temporal blocking (the user-kernel analog of jacobi_wrap_step)
    if path in ("auto", "wrap") and dd.num_subdomains() == 1 and x_radius == 1:
        cap = min(_WRAP_MAX_K, n.x // 2)
        if max_m is not None:
            cap = min(cap, max_m)
        best = None
        for grouping, sizes, rsizes in (
            [("joint", itemsizes, ring_sizes)]
            + (
                [("per-field", [max(itemsizes)], [max(ring_sizes)])]
                if separable and len(itemsizes) > 1
                else []
            )
        ):
            k = 0
            for cand in range(1, cap + 1):
                if stream_vmem_fits(cand, n.y, n.z, sizes, False, rsizes):
                    k = cand
            # deepest k across groupings — depth is the traffic lever
            # (~8/k B/cell/iter); joint wins ties
            if k >= 1 and (best is None or k > best["m"]):
                best = {"route": "wrap", "m": k, "z_slabs": False, "grouping": grouping}
        if best is not None:
            return best
    if path == "wrap":
        raise ValueError(
            "path='wrap' needs a single subdomain with >= 2 x-planes, "
            "x_radius 1, and VMEM for at least one resident plane ring"
        )
    if path != "plane" and x_radius == 1 and uniform and s >= 2:
        # (No shell-traffic heuristic here: the shell width s is GIVEN — the
        # domain already allocated and exchanges it — so advancing more
        # levels per exchange is strictly less traffic.)  realize() already
        # rejects any shard whose valid extent is below the shell width
        # (domain.py "subdomain ... smaller than radius shell"), so every
        # shard this plan can see fills an s-wide halo from valid cells.
        v_min = min(
            (dd._valid_last[ax] if dd._valid_last[ax] is not None else n[ax])
            for ax in range(3)
        )
        assert v_min >= s, (v_min, s)  # the realize() invariant
        cap = min(s, _WRAP_MAX_K)
        if max_m is not None:
            cap = min(cap, max_m)
        raw = dd.local_spec().raw_size()
        zp = -(-raw.z // 128) * 128
        # evaluate joint (all fields per pass) AND per-field grouping for
        # separable kernels, then take the DEEPEST m — depth is the traffic
        # lever (~8/m B/cell/iter); grouping only changes VMEM pressure and
        # per-pass ramp overhead, so joint wins ties
        group_options = [("joint", itemsizes, ring_sizes)]
        if separable and len(itemsizes) > 1:
            group_options.append(
                ("per-field", [max(itemsizes)], [max(ring_sizes)])
            )
        best = None
        # z-slab form's static emit slices assume even shards
        z_modes = ((False, raw.z),) if padded else ((True, zp), (False, raw.z))
        for grouping, sizes, rsizes in group_options:
            for z_mode, plane_z in z_modes:
                m = 0 if z_mode else 1
                for cand in range(2, cap + 1):
                    if stream_vmem_fits(cand, raw.y, plane_z, sizes, z_mode, rsizes):
                        m = cand
                if m >= 2 and (best is None or m > best["m"]):
                    best = {
                        "route": "wavefront",
                        "m": m,
                        "z_slabs": z_mode,
                        "grouping": grouping,
                    }
                if m >= 2:
                    # take the z-slab form for this grouping even if the
                    # plain form could fit a level deeper (its slab blocks
                    # are tiny): the plain form pays the ~64x-amplified
                    # thin-z in-array exchange every macro (probe12d)
                    break
        if best is not None:
            return best
    if path == "wavefront":
        raise ValueError(
            "path='wavefront' needs x_radius 1, a uniform face shell >= 2, "
            "valid shard extents >= the depth, and VMEM for m >= 2; got "
            f"shell {lo}/{hi}"
        )
    raw = dd.local_spec().raw_size()
    grouping = "joint"
    # the PLANE pass's ring scratch holds RAW (storage-dtype) planes —
    # stream_plane_pass upcasts transiently at view construction, never in
    # the ring — so its gate models rings at the STORAGE itemsize, unlike
    # the wavefront/wrap passes whose rings carry the f32 accumulator
    if not stream_vmem_fits(x_radius, raw.y, raw.z, itemsizes, False, itemsizes):
        # (2r+4) resident planes per field blow the budget jointly
        if separable and len(itemsizes) > 1:
            grouping = "per-field"
    return {"route": "plane", "m": 1, "z_slabs": False, "grouping": grouping}


def lane_pad_width(z: int) -> int:
    """Plane width rounded up to a 128 multiple — ragged lane extents stream
    ~30% slower (probe22), so z-slab wavefronts pad with dead columns."""
    return -(-z // 128) * 128


def prime_z_slabs(block: jax.Array, Zr: int, s: int) -> jax.Array:
    """The initial outgoing z-slab buffer for a macro chain: the block's
    interior z-boundary columns, packed [(-z)-bound | (+z)-bound] and
    transposed z-major (Xr, 2s, Yr) — the one strided read per dispatch;
    every later slab is kernel-emitted."""
    return jnp.concatenate(
        [
            jnp.swapaxes(block[:, :, Zr - 2 * s : Zr - s], 1, 2),
            jnp.swapaxes(block[:, :, s : 2 * s], 1, 2),
        ],
        axis=1,
    )


def make_slab_extenders(Xr: int, Yr: int, s: int, mesh_shape, axis_names=None):
    """(yext, xext) for z-major slab buffers: after the z ppermute, each slab
    is extended with rows from the y neighbors and then planes from the x
    neighbors — two hops that carry the xyz-corner cells from the diagonal
    blocks, mirroring the in-array exchange's sweep order.  Shared by the
    generic engine and the bespoke jacobi wavefront."""
    from stencil_tpu.ops.exchange import _shift_from_high, _shift_from_low
    from stencil_tpu.parallel.mesh import MESH_AXES

    names = MESH_AXES if axis_names is None else axis_names

    def yext(S):
        lo_ = _shift_from_low(S[:, :, Yr - 2 * s : Yr - s], names[1], mesh_shape[1])
        hi_ = _shift_from_high(S[:, :, s : 2 * s], names[1], mesh_shape[1])
        # stencil-lint: disable=halo-set-in-loop writes land on the thin z-slab buffers (2s planes), not the full domain — slab extension IS the design that keeps z halos out of the big array (PERF_NOTES z-slabs)
        return S.at[:, :, 0:s].set(lo_).at[:, :, Yr - s : Yr].set(hi_)

    def xext(S):
        lo_ = _shift_from_low(S[Xr - 2 * s : Xr - s], names[0], mesh_shape[0])
        hi_ = _shift_from_high(S[s : 2 * s], names[0], mesh_shape[0])
        # stencil-lint: disable=halo-set-in-loop same: x-extension of the thin z-slab buffers, sublane-cheap and off the big array
        return S.at[0:s].set(lo_).at[Xr - s : Xr].set(hi_)

    return yext, xext


def permute_and_extend_z_slabs(zout, s: int, mesh_shape, yext, xext):
    """One macro's incoming z-slab buffer from the previous macro's outgoing
    one: ppermute the two direction halves along z, then extend with y- and
    x-neighbor content (corner propagation)."""
    from stencil_tpu.ops.exchange import _shift_from_high, _shift_from_low
    from stencil_tpu.parallel.mesh import MESH_AXES

    zlo = _shift_from_low(zout[:, 0:s, :], MESH_AXES[2], mesh_shape[2])
    zhi = _shift_from_high(zout[:, s : 2 * s, :], MESH_AXES[2], mesh_shape[2])
    return jnp.concatenate([xext(yext(zlo)), xext(yext(zhi))], axis=1)


def _resolve_stream_alias(plan: dict, n_fields: int) -> bool:
    """input_output_aliases decision for a stream plan.  Precedence mirrors
    the bespoke wavefront path (models/jacobi.py): an autotuner CANDIDATE
    build (``alias_forced`` — its A/B trials must actually differ, whatever
    the environment says) > ``STENCIL_STREAM_ALIAS=0/1`` (validated read) >
    the plan's persisted tuned ``alias`` > the >= 4-fields static rule."""
    from stencil_tpu.utils.config import env_choice

    if plan.get("alias_forced") and plan.get("alias") is not None:
        return bool(plan["alias"])
    env = env_choice("STENCIL_STREAM_ALIAS", "auto", ("auto", "0", "1"))
    if env != "auto":
        return env == "1"
    if plan.get("alias") is not None:
        return bool(plan["alias"])
    return n_fields >= 4


def _overlap_request(plan: dict) -> Tuple[str, str]:
    """Pre-structural (value, source) of a stream plan's overlap schedule.
    Precedence mirrors the exchange route and stream alias rules: a FORCED
    plan value (``overlap_forced`` — explicit ``make_step(stream_overlap=
    ...)``/``make_stream_step(overlap=...)`` requests, autotuner candidate
    builds, and the ladder's split→off step-down, none of which ever consult
    further) > ``STENCIL_STREAM_OVERLAP`` (validated read) > the plan's
    tuned ``overlap`` > the static ``off``."""
    from stencil_tpu.utils.config import env_choice

    val: Optional[str] = None
    source = "static"
    if plan.get("overlap_forced") and plan.get("overlap") is not None:
        val, source = plan["overlap"], "explicit"
        if val not in STREAM_OVERLAP:
            raise ValueError(
                f"unknown stream overlap {val!r} (one of {STREAM_OVERLAP})"
            )
    else:
        env = env_choice(
            "STENCIL_STREAM_OVERLAP", "auto", ("auto",) + STREAM_OVERLAP
        )
        if env != "auto":
            val, source = env, "env"
        elif plan.get("overlap") is not None:
            tuned = plan["overlap"]
            if tuned in STREAM_OVERLAP:
                val, source = str(tuned), "tuned"
            else:
                from stencil_tpu.utils.logging import log_warn

                log_warn(
                    f"tuned stream overlap {tuned!r} is not one of "
                    f"{STREAM_OVERLAP}; using the static 'off' fallback"
                )
    if val is None:
        val = "off"
    return val, source


def _resolve_stream_overlap(plan: dict) -> Tuple[str, str]:
    """``_overlap_request`` plus the structural guard: a ``split`` the plan
    cannot serve — the wrap route has no exchange to hide, the z-slab
    wavefront interleaves its slab permutes with the pass — degrades to
    ``off`` with a warning (source tagged ``/degraded``), never an error: a
    stale persisted config or a cross-route env var must not kill a run
    ``off`` could have served.  (``make_stream_step`` re-plans a z-slab
    wavefront to the plain form BEFORE this guard when split was requested,
    so the degrade here is the last resort, not the common path.)"""
    val, source = _overlap_request(plan)
    if val == "split" and (
        plan.get("route") not in ("plane", "wavefront") or plan.get("z_slabs")
    ):
        from stencil_tpu.utils.logging import log_warn

        why = (
            "the z-slab wavefront interleaves its slab permutes with the pass"
            if plan.get("z_slabs")
            else f"the {plan.get('route')!r} route has no exchange to hide"
        )
        log_warn(
            f"overlap=split ({source}) cannot engage here ({why}); "
            "degrading to overlap=off"
        )
        val, source = "off", source + "/degraded"
    return val, source


def fused_halo_ineligible(dd, plan: dict, exch_route: str) -> Optional[str]:
    """Why ``halo="fused"`` cannot engage for this plan/domain/exchange
    route — or None when it can.  The structural gates (module docstring):
    the fused exchange packs at static offsets from even shards, patches
    need blend-supported tile geometry, the split schedule's exterior
    bands read exchanged BLOCKS, and only the plane / plain-wavefront
    routes stream level-0 planes the buffers can land in."""
    from stencil_tpu.ops import halo_blend
    from stencil_tpu.ops.exchange import Y_PACK_ROUTES

    if plan.get("route") not in ("plane", "wavefront"):
        return f"the {plan.get('route')!r} route has no exchange to fuse"
    if plan.get("z_slabs"):
        return "the z-slab wavefront already keeps z halos out of the big array"
    if plan.get("overlap") == "split":
        return "the split schedule's exterior band passes read exchanged blocks"
    if exch_route not in Y_PACK_ROUTES:
        return (
            f"the {exch_route!r} exchange route does not pack the y shell "
            f"(fused needs one of {Y_PACK_ROUTES})"
        )
    if any(v is not None for v in dd._valid_last):
        return "padded (uneven) shards — the fused pack cuts at static offsets"
    if not all(halo_blend.supports(dd.field_dtype(h)) for h in dd._handles):
        return "a field dtype without known tile geometry"
    return None


def _halo_request(plan: dict) -> Tuple[Optional[str], str]:
    """Pre-structural (value, source) of a stream plan's halo consumption
    mode.  Precedence mirrors the overlap axis: a FORCED plan value
    (``halo_forced`` — explicit requests, autotuner candidate builds, the
    ladder's fused→array step-down) > ``STENCIL_STREAM_HALO`` (validated
    read) > the plan's tuned ``halo`` > the static ``array``."""
    from stencil_tpu.utils.config import env_choice

    val: Optional[str] = None
    source = "static"
    if plan.get("halo_forced") and plan.get("halo") is not None:
        val, source = plan["halo"], "explicit"
        if val not in STREAM_HALO:
            raise ValueError(
                f"unknown stream halo mode {val!r} (one of {STREAM_HALO})"
            )
    else:
        env = env_choice("STENCIL_STREAM_HALO", "auto", ("auto",) + STREAM_HALO)
        if env != "auto":
            val, source = env, "env"
        elif plan.get("halo") is not None:
            tuned = plan["halo"]
            if tuned in STREAM_HALO:
                val, source = str(tuned), "tuned"
            else:
                from stencil_tpu.utils.logging import log_warn

                log_warn(
                    f"tuned stream halo {tuned!r} is not one of "
                    f"{STREAM_HALO}; using the static 'array' fallback"
                )
    if val is None:
        val = "array"
    return val, source


def _resolve_stream_halo(dd, plan: dict, exch_route: str) -> Tuple[str, str]:
    """``_halo_request`` plus the structural guard: a ``fused`` the plan
    cannot serve degrades to ``array`` with a warning (source tagged
    ``/degraded``), never an error — a stale persisted config or a
    cross-route env var must not kill a run ``array`` could have served.
    (``make_stream_step`` re-plans a z-slab wavefront to the plain form
    BEFORE this guard when fused was requested, like the split path.)"""
    val, source = _halo_request(plan)
    if val == "fused":
        why = fused_halo_ineligible(dd, plan, exch_route)
        if why is not None:
            from stencil_tpu.utils.logging import log_warn

            log_warn(
                f"halo=fused ({source}) cannot engage here ({why}); "
                "degrading to halo=array"
            )
            val, source = "array", source + "/degraded"
    return val, source


def plain_wavefront_plan(dd, plan: dict, max_depth: Optional[int] = None) -> Optional[dict]:
    """The PLAIN-form twin of a z-slab wavefront plan, at the deepest depth
    the VMEM model fits (the z-slab blocks leave the budget; the unpadded
    ``raw.z`` planes enter it) — or None when no plain depth >= 2 fits.
    The split-step schedule needs it: z halos must live in the big array for
    the exchange the interior pass overlaps, and the packed ``zpack_*``
    exchange routes already de-amplified the thin-z traffic the z-slab form
    exists to dodge.  Shared by ``make_stream_step`` (a split request
    re-plans through it) and ``tune/space.py`` (the split candidate)."""
    if plan.get("route") != "wavefront" or not plan.get("z_slabs"):
        return None
    shell = dd._shell_radius
    s = shell.lo().x
    raw = dd.local_spec().raw_size()
    itemsizes = [dd.field_dtype(h).itemsize for h in dd._handles]
    ring_sizes = [h.dtype.itemsize for h in dd._handles]
    per_field = plan.get("grouping") == "per-field" and len(itemsizes) > 1
    sizes = [max(itemsizes)] if per_field else itemsizes
    rsizes = [max(ring_sizes)] if per_field else ring_sizes
    cap = min(s, _WRAP_MAX_K)
    if max_depth is not None:
        cap = min(cap, max_depth)
    m = 0
    for cand in range(2, cap + 1):
        if stream_vmem_fits(cand, raw.y, raw.z, sizes, False, rsizes):
            m = cand
    if m < 2:
        return None
    out = dict(plan)
    out["z_slabs"] = False
    out["m"] = m
    return out


def _build_stream_step(dd, kernel, x_radius, plan, interpret, donate=True,
                       mxu_kernel=None):
    from jax.sharding import PartitionSpec as P

    from stencil_tpu.ops.exchange import (
        fused_shell_exchange,
        halo_exchange_multi,
    )
    from stencil_tpu.parallel.mesh import MESH_AXES

    names = [h.name for h in dd._handles]
    valid_last = dd._valid_last
    n = dd.local_spec().sz
    shell = dd._shell_radius
    lo, hi = shell.lo(), shell.hi()
    mesh_shape = tuple(dd.mesh.shape[a] for a in MESH_AXES)
    gsize = dd._size
    raw = dd.local_spec().raw_size()
    spec = P(*MESH_AXES)
    # per-field grouping: one streaming pass per group per macro (valid only
    # for kernels declared separable); the exchange stays JOINT (<= 6
    # permutes for any field count) either way
    if plan.get("grouping") == "per-field":
        groups = [[q] for q in range(len(names))]
    else:
        groups = [list(range(len(names)))]
    # the z sweep of every in-step exchange runs the domain's realize-
    # resolved route (packed z-shell vs direct — ops/exchange.py), so stream
    # steps escape the 64×-amplified thin-z path exactly like exchange()
    exch_route = getattr(dd, "_exchange_route", "direct")
    # Un-aliased wavefront passes are ~10-20% faster for FEW fields
    # (probe21b: the in-place alias serializes the deep-m pipeline) but cost
    # one fresh raw-sized buffer per pass.  From 4 fields up, alias: a joint
    # pass would double a multi-GB working set (8 x ~700 MB exhausted HBM in
    # bench), and even per-field grouped passes measured ~50% SLOWER
    # un-aliased at 8x512^3 (19.1 vs 12.8 ms/iter, r5 bench) — the per-pass
    # allocate/free churn costs more than the aliasing serialization saves.
    alias = _resolve_stream_alias(plan, len(names))
    # split-step overlap schedule (module docstring): resolve, write the
    # decision back into the plan (the ladder and step._stream_plan read it),
    # and record it — the stream-engine twin of the exchange.route event
    overlap, overlap_source = _resolve_stream_overlap(plan)
    plan["overlap"] = overlap
    telemetry.emit_event(
        tm.EVENT_STEP_OVERLAP,
        overlap=overlap,
        source=overlap_source,
        route=plan["route"],
        m=plan["m"],
    )
    split = overlap == "split"
    # fused unpack→blend axis (module docstring): resolved AFTER overlap —
    # the split schedule structurally excludes fused — written back into
    # the plan (the ladder and step._stream_plan read it) and recorded,
    # the stream-engine twin of the exchange.route / step.overlap events
    halo, halo_source = _resolve_stream_halo(dd, plan, exch_route)
    plan["halo"] = halo
    telemetry.emit_event(
        tm.EVENT_STEP_HALO,
        halo=halo,
        source=halo_source,
        route=plan["route"],
        m=plan["m"],
        exchange_route=exch_route,
    )
    fused = halo == "fused"
    # compute-unit axis (ops/jacobi_pallas COMPUTE_UNITS): shared precedence
    # chain (forced plan value = explicit requests / autotuner candidates /
    # ladder step-downs > STENCIL_COMPUTE_UNIT > tuned plan > static vpu)
    # plus the stream engine's structural gate — mxu needs a DECLARED
    # axis-separable contraction form (``mxu_kernel``; opaque user kernels
    # have none and degrade with a warning) and f32 compute dtypes.  bf16
    # STORAGE (``f32_accumulate``) computes at the native f32 and qualifies.
    f32_acc = any(dd.field_dtype(h) != h.dtype for h in dd._handles)
    unit_req = (
        plan.get("compute_unit") if plan.get("compute_unit_forced") else None
    )
    unit_tuned = None if unit_req is not None else plan.get("compute_unit")
    compute_unit, _unit_src = resolve_compute_unit(
        unit_req,
        unit_tuned,
        [h.dtype for h in dd._handles],
        where=f"stream:{plan['route']}",
        engine_ok=mxu_kernel is not None,
        engine_why=(
            "the kernel declares no axis-separable contraction form "
            "(make_stream_step mxu_kernel=...)"
        ),
    )
    plan["compute_unit"] = compute_unit
    # MXU input precision (ops/jacobi_pallas MXU_INPUTS): resolved AFTER
    # the unit (bf16 inputs only exist under an engaged MXU unit) through
    # the same forced > env > tuned > static chain
    mi_req = plan.get("mxu_input") if plan.get("mxu_input_forced") else None
    mi_tuned = None if mi_req is not None else plan.get("mxu_input")
    mxu_input, _mi_src = resolve_mxu_input(
        mi_req, mi_tuned, compute_unit, where=f"stream:{plan['route']}"
    )
    plan["mxu_input"] = mxu_input
    if unit_uses_mxu(compute_unit):
        # the mxu form is the SAME stencil written through the views'
        # plane_nbr_sum seam; every pass (interior, exterior bands, wrap)
        # runs it, so the split-schedule bitwise argument holds per unit
        kernel = mxu_kernel
    unit_kw = {
        "compute_unit": compute_unit,
        "f32_accumulate": f32_acc,
        "mxu_input": mxu_input,
    }

    if split:
        from stencil_tpu.ops import halo_blend

        interp_blend = interpret or halo_blend.interpret_mode()
        lo_t = (lo.x, lo.y, lo.z)
        hi_t = (hi.x, hi.y, hi.z)

        def _n_valid(ax):
            """Valid interior width on ``ax`` for THIS shard: a plain int on
            even axes, traced on padded ones (the last shard owns the
            remainder — the same arithmetic as the exchange's dynamic halo
            offsets, so band positions land exactly where the halos did)."""
            if valid_last[ax] is None:
                return n[ax]
            idx = lax.axis_index(MESH_AXES[ax])
            return jnp.where(
                idx == mesh_shape[ax] - 1, valid_last[ax], n[ax]
            ).astype(jnp.int32)

        def _starts3(ax, start):
            # uniform index dtype: a traced (int32) padded-axis offset must
            # not mix with python-int (x64) zeros in dynamic_slice/DUS
            starts = [jnp.int32(0)] * 3
            starts[ax] = jnp.asarray(start, jnp.int32)
            return tuple(starts)

        def _sub_slice(b, ax, start, width):
            sizes = list(b.shape)
            sizes[ax] = width
            return lax.dynamic_slice(b, _starts3(ax, start), tuple(sizes))

        def _blend_band(block, band, ax, pos):
            """Write a recomputed width-``w`` band at ``pos`` along ``ax``.
            x bands are whole contiguous planes (DUS at slab cost); y/z bands
            go through the tile-local blend kernels exactly like the
            exchange's halo writes (static offset on even axes, traced on
            padded ones)."""
            if ax == 0:
                # stencil-lint: disable=sliver-dus x-plane band write-back: whole contiguous planes, the exchange's sanctioned axis-0 pattern (no relayout bait)
                return lax.dynamic_update_slice(block, band, _starts3(0, pos))
            if not halo_blend.supports(block.dtype):
                # exotic-dtype correctness fallback, off the measured path
                # stencil-lint: disable=sliver-dus exotic-dtype (no known tile geometry) fallback — the blend kernels cannot engage, and such dtypes are off the measured fast path
                return lax.dynamic_update_slice(block, band, _starts3(ax, pos))
            if isinstance(pos, int):
                return halo_blend.blend_slab(
                    block, band, ax, pos, interpret=interp_blend
                )
            return halo_blend.blend_slab_dynamic(
                block, band, ax, pos, interpret=interp_blend
            )

        # Mosaic rejects thin band sub-blocks outright (a 6-sublane ring
        # scratch is an "invalid offsets in tiling target"; thin-lane shapes
        # likewise): the band window is rounded up to the axis tile granule
        # — 32 sublanes / 128 lanes cover the native tiling of every dtype —
        # which costs nothing the VMEM tile padding wasn't already paying
        # (PERF_NOTES "Thin z-region access": a 6-lane sliver occupies full
        # 128-lane tiles regardless).  x slices whole planes (the grid
        # axis — no granule).  Interpret mode pads identically so tier-1
        # exercises the same window arithmetic the TPU compiles.
        _BAND_GRANULE = (1, 32, 128)

        def _band_window(ax, start, w, raw_ax):
            """(clamped start, width) of one band's support window: ``3w``
            rounded up to the axis granule, slid down (never past 0) to stay
            inside the raw extent.  The clamp only widens the interior side
            of the window, so the band keeps its full dependency cone."""
            g = _BAND_GRANULE[ax]
            width = min(-(-3 * w // g) * g, raw_ax)
            if isinstance(start, int):
                return max(min(start, raw_ax - width), 0), width
            return jnp.clip(start, 0, raw_ax - width), width

        def _exterior_fix(outs, ex, w, origin, narrow_pass):
            """Recompute the six width-``w`` boundary bands of ``outs`` from
            the freshly exchanged blocks ``ex`` and blend them in.  Each
            band's support window is ``>= 3w`` wide (band + ``w`` of fresh
            shell + interior, granule-padded), so the narrow pass reproduces
            the full pass's values bitwise on the band; band overlaps at
            edges and corners write identical values twice."""
            outs = list(outs)
            for ax in range(3):
                nv = _n_valid(ax)
                for start, pos in (
                    (lo_t[ax] - w, lo_t[ax]),  # low face: static offsets
                    # high face: right after this shard's valid cells —
                    # static on even axes, traced on padded ones
                    (lo_t[ax] + nv - 2 * w, lo_t[ax] + nv - w),
                ):
                    start, width = _band_window(ax, start, w, ex[0].shape[ax])
                    subs = [_sub_slice(e, ax, start, width) for e in ex]
                    sub_outs = narrow_pass(subs, ax, start, w, origin)
                    for q in range(len(outs)):
                        band = _sub_slice(sub_outs[q], ax, pos - start, w)
                        outs[q] = _blend_band(outs[q], band, ax, pos)
            return outs

    def origin_of():
        # NOTE: must be called INSIDE the fori_loop body that consumes it.
        # axis_index lowers to partition-id; a while-loop OPERAND whose def
        # chain includes partition-id trips XLA's SPMD partitioner
        # ("PartitionId instruction is not supported for SPMD partitioning")
        # on some toolchains, while the same op inside the body partitions
        # fine (and LICM hoists it after partitioning anyway).
        return jnp.stack(
            [lax.axis_index(MESH_AXES[ax]) * n[ax] for ax in range(3)]
        )

    if plan["route"] == "wrap":
        k = plan["m"]

        def per_shard(steps, *blocks_raw):
            bs = tuple(
                lax.slice(b, (lo.x, lo.y, lo.z), (lo.x + n.x, lo.y + n.y, lo.z + n.z))
                for b in blocks_raw
            )

            def one(depth, bs):
                origin = origin_of()
                out = list(bs)
                for g in groups:
                    outs = stream_wrap_pass(
                        kernel, [names[q] for q in g], [bs[q] for q in g],
                        depth, origin, gsize, interpret=interpret, **unit_kw,
                    )
                    for q, o in zip(g, outs):
                        out[q] = o
                return tuple(out)

            blocked, rem = divmod(steps, k)
            bs = lax.fori_loop(0, blocked, lambda _, b: one(k, b), bs)
            if rem:
                bs = one(rem, bs)
            return tuple(
                # stencil-lint: disable=sliver-dus whole-interior write-back after the wrap loop — b spans the full interior, not a y/z sliver
                lax.dynamic_update_slice(rb, b, (lo.x, lo.y, lo.z))
                for rb, b in zip(blocks_raw, bs)
            )

    elif plan["route"] == "plane":

        def plane_groups(bs, origin, fused_bufs=None):
            out = list(bs)
            for g in groups:
                fs = None
                if fused_bufs is not None:
                    xb, yb, zb = fused_bufs
                    fs = (
                        [xb[q] for q in g],
                        [yb[q] for q in g],
                        [zb[q] for q in g],
                    )
                outs = stream_plane_pass(
                    kernel, [names[q] for q in g], [bs[q] for q in g],
                    lo, hi, x_radius, origin, gsize, interpret=interpret,
                    fused_shell=fs,
                    **unit_kw,
                )
                for q, o in zip(g, outs):
                    out[q] = o
            return out

        if fused:

            def per_shard(steps, *blocks):
                def body(_, bs):
                    origin = origin_of()
                    bs = list(bs)
                    # the packed messages never unpack into the blocks: the
                    # received shell buffers ride into the pass and land in
                    # the level-0 VMEM planes — no big-array halo write
                    bufs = fused_shell_exchange(
                        bs, shell, mesh_shape, route=exch_route
                    )
                    return tuple(plane_groups(bs, origin, bufs))

                return lax.fori_loop(0, steps, body, tuple(blocks))

        elif split:

            def narrow_plane(subs, ax, start, w, origin):
                """One kernel level over ``3w``-wide face sub-blocks (``w ==
                x_radius``): the sliced axis carries a ``w``-deep pseudo
                shell, the other axes keep the true shell widths, and the
                origin shifts so wrapped coordinates match the full pass at
                every sub-block position (traced on padded axes)."""
                lo2 = Dim3(*[w if b == ax else lo_t[b] for b in range(3)])
                hi2 = Dim3(*[w if b == ax else hi_t[b] for b in range(3)])
                delta = [
                    jnp.asarray(start - lo_t[b] + w if b == ax else 0, jnp.int32)
                    for b in range(3)
                ]
                origin_sub = origin + jnp.stack(delta)
                out = list(subs)
                for g in groups:
                    outs = stream_plane_pass(
                        kernel, [names[q] for q in g], [subs[q] for q in g],
                        lo2, hi2, x_radius, origin_sub, gsize,
                        interpret=interpret, **unit_kw,
                    )
                    for q, o in zip(g, outs):
                        out[q] = o
                return out

            def per_shard(steps, *blocks):
                def body(_, bs):
                    origin = origin_of()
                    bs = list(bs)
                    # the ppermutes read slabs of the PRE-exchange blocks;
                    # the interior pass below also reads those blocks — no
                    # data dependency between them, so XLA's latency-hiding
                    # scheduler flies the collectives behind the pass
                    ex = list(
                        halo_exchange_multi(
                            bs, shell, mesh_shape, valid_last=valid_last,
                            route=exch_route,
                        )
                    )
                    with telemetry.annotate(tm.SPAN_OVERLAP_INTERIOR):
                        out = plane_groups(bs, origin)
                    with telemetry.annotate(tm.SPAN_OVERLAP_EXTERIOR):
                        out = _exterior_fix(out, ex, x_radius, origin, narrow_plane)
                    return tuple(out)

                return lax.fori_loop(0, steps, body, tuple(blocks))

        else:

            def per_shard(steps, *blocks):
                def body(_, bs):
                    origin = origin_of()
                    bs = list(
                        halo_exchange_multi(
                            bs, shell, mesh_shape, valid_last=valid_last,
                            route=exch_route,
                        )
                    )
                    return tuple(plane_groups(bs, origin))

                return lax.fori_loop(0, steps, body, tuple(blocks))

    else:
        m = plan["m"]
        s = lo.x
        z_slab_mode = plan["z_slabs"]
        Xr, Yr, Zr = raw.x, raw.y, raw.z
        Zp = lane_pad_width(Zr) if z_slab_mode else Zr
        yext, xext = make_slab_extenders(Xr, Yr, s, mesh_shape)

        def wavefront_groups(bs, depth, origin, zs=None, fused_bufs=None):
            """Run the m-level pass group by group; returns (outs, zouts)."""
            outs = list(bs)
            zouts = [None] * len(bs) if zs is not None else None
            for g in groups:
                fs = None
                if fused_bufs is not None:
                    xb, yb, zb = fused_bufs
                    fs = (
                        [xb[q] for q in g],
                        [yb[q] for q in g],
                        [zb[q] for q in g],
                    )
                o, z = stream_wavefront_pass(
                    kernel, [names[q] for q in g], [bs[q] for q in g],
                    depth, s, origin, gsize,
                    z_slabs=[zs[q] for q in g] if zs is not None else None,
                    z_valid=Zr if zs is not None else None,
                    alias=alias,
                    interpret=interpret,
                    fused_shell=fs,
                    **unit_kw,
                )
                for j, q in enumerate(g):
                    outs[q] = o[j]
                    if z is not None:
                        zouts[q] = z[j]
            return outs, zouts

        def narrow_wavefront(subs, ax, start, w, origin):
            """``w`` kernel levels over ``3w``-wide face sub-blocks (``w`` is
            this macro's depth; the remainder macro passes a shallower one).
            The sub-block's pseudo shell is ``w`` on every axis — minimal
            support for a width-``w`` band at level ``w`` — with the origin
            shifted so wrapped coordinates match the full pass."""
            delta = [
                jnp.asarray(start - lo_t[b] + w if b == ax else w - lo_t[b],
                            jnp.int32)
                for b in range(3)
            ]
            origin_sub = origin + jnp.stack(delta)
            out = list(subs)
            for g in groups:
                o, _ = stream_wavefront_pass(
                    kernel, [names[q] for q in g], [subs[q] for q in g],
                    w, w, origin_sub, gsize, alias=False, interpret=interpret,
                    **unit_kw,
                )
                for q, oo in zip(g, o):
                    out[q] = oo
            return out

        def per_shard(steps, *blocks):
            if not z_slab_mode:

                if fused:

                    def macro(depth, bs):
                        origin = origin_of()
                        bs = list(bs)
                        # messages pack from the (stale-shell) blocks, the
                        # received buffers corner-patch each other in the
                        # sweep order, and the pass lands them in VMEM —
                        # the big array never sees a halo write
                        bufs = fused_shell_exchange(
                            bs, shell, mesh_shape, route=exch_route
                        )
                        outs, _ = wavefront_groups(
                            bs, depth, origin, fused_bufs=bufs
                        )
                        return tuple(outs)

                elif split:

                    def macro(depth, bs):
                        origin = origin_of()
                        bs = list(bs)
                        # ppermutes on slabs of the PRE-exchange blocks; the
                        # interior pass reads the same blocks — independent
                        # dataflow, so the collectives fly behind the m-level
                        # pass and only the narrow band passes wait for them
                        ex = list(
                            halo_exchange_multi(
                                bs, shell, mesh_shape, valid_last=valid_last,
                                route=exch_route,
                            )
                        )
                        with telemetry.annotate(tm.SPAN_OVERLAP_INTERIOR):
                            outs, _ = wavefront_groups(bs, depth, origin)
                        with telemetry.annotate(tm.SPAN_OVERLAP_EXTERIOR):
                            outs = _exterior_fix(
                                outs, ex, depth, origin, narrow_wavefront
                            )
                        return tuple(outs)

                else:

                    def macro(depth, bs):
                        origin = origin_of()
                        bs = list(
                            halo_exchange_multi(
                                bs, shell, mesh_shape, valid_last=valid_last,
                                route=exch_route,
                            )
                        )
                        outs, _ = wavefront_groups(bs, depth, origin)
                        return tuple(outs)

                macros, rem = divmod(steps, m)
                bs = lax.fori_loop(0, macros, lambda _, b: macro(m, b), tuple(blocks))
                if rem:
                    bs = macro(rem, bs)
                return bs

            def macro(depth, carry):
                origin = origin_of()
                bs, zouts = carry
                bs = list(
                    halo_exchange_multi(bs, shell, mesh_shape, axes=(0, 1))
                )
                zs = [
                    permute_and_extend_z_slabs(zout, s, mesh_shape, yext, xext)
                    for zout in zouts
                ]
                outs, zouts = wavefront_groups(bs, depth, origin, zs)
                return tuple(outs), tuple(zouts)

            # prime slabs from the blocks' interior z boundaries, lane-pad
            bs = tuple(
                jnp.pad(b, ((0, 0), (0, 0), (0, Zp - Zr))) for b in blocks
            )
            zouts = tuple(prime_z_slabs(b, Zr, s) for b in blocks)
            macros, rem = divmod(steps, m)
            carry = lax.fori_loop(
                0, macros, lambda _, c: macro(m, c), (bs, zouts)
            )
            if rem:
                carry = macro(rem, carry)
            return tuple(b[:, :, :Zr] for b in carry[0])

    donate_kw = {"donate_argnums": 0} if donate else {}

    @partial(jax.jit, static_argnums=1, **donate_kw)
    def step(curr, steps: int = 1):
        # check_vma off: pallas_call outputs carry no vma annotation
        fn = shard_map(
            partial(per_shard, steps),
            mesh=dd.mesh,
            in_specs=tuple(spec for _ in names),
            out_specs=tuple(spec for _ in names),
            check_vma=False,
        )
        outs = fn(*[curr[k] for k in names])
        return dict(zip(names, outs))

    return step


def make_stream_step(
    dd,
    kernel: PlaneKernel,
    x_radius: int = 1,
    path: str = "auto",
    separable: bool = False,
    interpret: bool = False,
    donate: bool = True,
    max_depth: int = None,
    overlap: str = "auto",
    halo: str = "auto",
    compute_unit: str = "auto",
    mxu_input: str = "auto",
    mxu_kernel: PlaneKernel = None,
):
    """Build a ``step(curr, steps) -> curr`` running ``kernel`` under the
    plane-streaming engine — the fast-by-default path for user stencils
    (``DistributedDomain.make_step(..., engine="stream")``).

    The kernel is the SAME ``(views, info) -> {name: values}`` callable the
    XLA route accepts, restricted to: ALL shifts (x, y, and z) within
    ``x_radius`` (``PlaneView.sh`` asserts this at trace time), elementwise
    arithmetic (every view read and ``info.coords()`` piece broadcasts to
    the plane), no N-D component data.
    ``separable=True`` additionally declares the kernel correct on arbitrary
    view subsets, letting many-field domains stream per-field (see
    ``plan_stream``).

    ``max_depth`` caps the temporal depth (wrap k / wavefront m).  The auto
    planner maximizes depth because depth is the HBM-traffic lever
    (~bytes/k per cell) — correct for bandwidth-bound kernels, but a
    COMPUTE-heavy kernel (e.g. 27 taps/cell) multiplies its VPU work by the
    depth with nothing to amortize; cap it low (2-4) for such kernels.

    ``overlap`` selects the split-step schedule (module docstring):
    ``"auto"`` resolves ``STENCIL_STREAM_OVERLAP`` > the tuned config >
    the static ``off``; an explicit ``"off"``/``"split"`` is an explicit
    request and never consults further.  ``split`` is bitwise-identical to
    ``off`` on every valid cell; a route it cannot serve (wrap, z-slab
    wavefront) degrades to ``off`` with a warning, and a compile-rejected
    split build steps down to ``off`` at the same depth through the ladder
    before any depth descent.

    ``halo`` selects the fused unpack→blend mode (module docstring):
    ``"auto"`` resolves ``STENCIL_STREAM_HALO`` > the tuned config > the
    static ``"array"``; under ``"fused"`` the packed exchange messages
    land directly in the pass's level-0 VMEM planes and the big array
    never sees a halo write — bitwise-identical to ``"array"``.  A plan
    it cannot serve (wrap, split schedule, non-``yzpack_*`` exchange
    route, uneven shards) degrades to ``"array"`` with a warning; a
    z-slab wavefront plan re-plans to the plain form first (like split);
    a compile-rejected fused build steps down to ``"array"`` at the same
    depth through the ladder before any depth descent.

    ``compute_unit`` selects the level kernels' execution unit (a tuner
    axis — docs/tuning.md "Compute unit and storage dtype"): ``"auto"``
    resolves ``STENCIL_COMPUTE_UNIT`` > the tuned config > the static
    ``vpu``; ``"mxu"`` routes the separable in-plane taps through one
    banded contraction per axis on the matrix unit, which requires the
    kernel's declared contraction form ``mxu_kernel`` — the SAME stencil
    written against ``PlaneView.plane_nbr_sum`` (pinned ≤1 ulp/level
    against the vpu form); ``"mxu_band"`` tiles that contraction to the
    band's nonzeros (blocked ``(2r+1)``-band matmul — ulp-pinned against
    the dense form, ~``n/(2r+1)``× fewer FLOPs, KB-scale resident
    constants).  A kernel with no mxu form, or non-f32 compute dtypes,
    degrades to ``vpu`` with a warning; an untilable plane geometry
    degrades ``mxu_band`` to the dense form per pass; a compile-rejected
    build steps down band → dense → vpu at the same depth through the
    ladder before any depth descent.

    ``mxu_input`` selects the contraction operand precision (a tuner
    axis): ``"auto"`` resolves ``STENCIL_MXU_INPUT`` > the tuned config >
    the static ``"f32"``; ``"bf16"`` feeds bfloat16 operands to the MXU
    under the unchanged f32-accumulate contract (analytic bound
    ``tests/ulp.mxu_bf16_input_atol``) — the ~2× ratio leg of the "VPU
    wall" break-even model.  Structurally inert under ``vpu``.

    The returned step rides the resilience DEGRADATION LADDER
    (``resilience/ladder.py``): if Mosaic rejects the planned wavefront depth
    (scoped-VMEM OOM, or any other classified compile reject), the ladder
    re-plans one level shallower and retries, logging a recalibration hint,
    until the plane route is reached — at which point the failure propagates.
    Re-invocation is donation-guarded (a deleted input buffer refuses the
    descent), and fault-injection hooks labeled ``stream:<rung>`` fire at
    build and execute time (``STENCIL_FAULT_PLAN``).  The current plan is
    exposed as ``step._stream_plan``; the descent history as
    ``step._resilience.descents``.
    """
    if max_depth is not None:
        import operator

        if isinstance(max_depth, bool):  # True would cap depth at 1 silently
            raise ValueError(f"stream_depth must be an integer, got {max_depth!r}")
        try:
            max_depth = operator.index(max_depth)  # int, np.int64, ...
        except TypeError:
            raise ValueError(
                f"stream_depth must be an integer >= 1, got {max_depth!r}"
            ) from None
        if max_depth < 1:
            raise ValueError(
                f"stream_depth must be >= 1, got {max_depth} (a 0/negative "
                "cap would silently disable temporal blocking)"
            )
    from stencil_tpu.resilience.ladder import DegradationLadder, Rung

    if overlap not in ("auto",) + STREAM_OVERLAP:
        raise ValueError(
            f"unknown stream overlap {overlap!r} (one of "
            f"{('auto',) + STREAM_OVERLAP})"
        )
    if halo not in ("auto",) + STREAM_HALO:
        raise ValueError(
            f"unknown stream halo mode {halo!r} (one of "
            f"{('auto',) + STREAM_HALO})"
        )
    if compute_unit not in ("auto",) + COMPUTE_UNITS:
        raise ValueError(
            f"unknown compute unit {compute_unit!r} (one of "
            f"{('auto',) + COMPUTE_UNITS})"
        )
    if mxu_input not in ("auto",) + MXU_INPUTS:
        raise ValueError(
            f"unknown mxu input {mxu_input!r} (one of "
            f"{('auto',) + MXU_INPUTS})"
        )
    plan = plan_stream(dd, x_radius, path, separable, max_m=max_depth)
    if (overlap != "auto" or halo != "auto" or compute_unit != "auto"
            or mxu_input != "auto"):
        plan = dict(plan)
    if overlap != "auto":
        plan["overlap"] = overlap
        plan["overlap_forced"] = True
    if halo != "auto":
        plan["halo"] = halo
        plan["halo_forced"] = True
    if compute_unit != "auto":
        plan["compute_unit"] = compute_unit
        plan["compute_unit_forced"] = True
    if mxu_input != "auto":
        plan["mxu_input"] = mxu_input
        plan["mxu_input_forced"] = True
    # a split request (explicit/env/tuned) against a z-slab wavefront plan
    # re-plans to the PLAIN form when it fits: split needs z halos in the
    # big array for the exchange it overlaps, and the packed zpack_* routes
    # already de-amplified the thin-z traffic the slab form dodges.  When no
    # plain depth fits, the build's structural guard degrades split -> off.
    # The FUSED halo request re-plans the same way: the fused buffers are
    # the level-0 patch of a plain pass, and the packed routes make the
    # plain form's exchange cheap — when no plain depth fits, the build's
    # structural guard degrades fused -> array.
    if _overlap_request(plan)[0] == "split" or _halo_request(plan)[0] == "fused":
        plain = plain_wavefront_plan(dd, plan, max_depth=max_depth)
        if plain is not None:
            plan = plain

    from stencil_tpu.ops.jacobi_pallas import mxu_supported

    def _prospective_unit(p) -> str:
        """The unit the build WILL resolve (same chain as
        _build_stream_step, emit=False) — rung names must show an
        env/tuned-sourced mxu, not just an explicit one.  Skipped when mxu
        cannot engage (no declared form / non-f32), where the build's own
        resolve owns the single degrade warning."""
        if mxu_kernel is None or not mxu_supported(
            [h.dtype for h in dd._handles]
        ):
            return "vpu"
        u_req = p.get("compute_unit") if p.get("compute_unit_forced") else None
        u_tuned = None if u_req is not None else p.get("compute_unit")
        unit, _ = resolve_compute_unit(
            u_req, u_tuned, [h.dtype for h in dd._handles],
            where=f"stream:{p['route']}", emit=False,
        )
        return unit

    def rung_for(p):
        # build() resolves _build_stream_step through module globals at call
        # time, so tests may monkeypatch it
        suffix = ",split" if p.get("overlap") == "split" else ""
        if p.get("halo") == "fused":
            suffix += ",fused"
        unit = _prospective_unit(p)
        if unit != "vpu":
            suffix += f",{unit}"
        return Rung(
            name=f"{p['route']}[m={p['m']}{suffix}]",
            build=lambda: _build_stream_step(
                dd, kernel, x_radius, p, interpret, donate,
                mxu_kernel=mxu_kernel,
            ),
            state={"plan": p},
        )

    def lower(rung, cls, exc):
        plan_now = rung.state["plan"]
        from stencil_tpu.utils.logging import log_warn

        # key the axis step-down on the unit the rung actually RESOLVES
        # (the build's chain, mirrored by _prospective_unit) — an env/tuned-
        # sourced mxu leaves the plan dict unset, and keying on the dict
        # alone would wrongly descend DEPTH for a reject that is the
        # contraction's fault (incl. the prefilter's static band-matrix
        # reject), violating the axis-drops-first-at-same-depth rule
        unit_now = _prospective_unit(plan_now)
        if unit_now == "mxu_band":
            # first rung down: band → DENSE at the SAME depth/schedule —
            # the blocked form carries its own reshape/batched-dot lowering
            # surface, so a reject may be the tiling's fault while the
            # dense contraction still compiles
            log_warn(
                f"compute_unit=mxu_band on {plan_now['route']}"
                f"[m={plan_now['m']}] exceeded the compiler's capability "
                f"({cls.value}); stepping down to the dense mxu form at the "
                "same depth"
            )
            p2 = dict(plan_now)
            p2["compute_unit"] = "mxu"
            p2["compute_unit_forced"] = True
            return rung_for(p2)
        if unit_now == "mxu":
            # next rung down: drop the MXU contraction form at the SAME
            # depth/schedule — the band matmuls carry their own resident
            # constants and matrix-unit lowering, so a VMEM_OOM or compile
            # reject may be the contraction's fault, not the depth's
            log_warn(
                f"compute_unit=mxu on {plan_now['route']}[m={plan_now['m']}] "
                f"exceeded the compiler's capability ({cls.value}); stepping "
                "down to vpu at the same depth"
            )
            p2 = dict(plan_now)
            p2["compute_unit"] = "vpu"
            p2["compute_unit_forced"] = True
            # moot without a contraction — pin f32 so the resolve stays quiet
            p2["mxu_input"] = "f32"
            p2["mxu_input_forced"] = True
            return rung_for(p2)
        if plan_now.get("halo") == "fused":
            # next rung down: drop the fused halo mode at the SAME depth —
            # the fused pass carries extra side-buffer blocks and per-plane
            # patch selects, so a VMEM_OOM or compile reject may be the
            # fused form's fault, not the depth's
            log_warn(
                f"halo=fused on {plan_now['route']}[m={plan_now['m']}] "
                f"exceeded the compiler's capability ({cls.value}); stepping "
                "down to halo=array at the same depth"
            )
            p2 = dict(plan_now)
            p2["halo"] = "array"
            p2["halo_forced"] = True
            return rung_for(p2)
        if plan_now.get("overlap") == "split":
            # first rung down: drop the split schedule at the SAME depth —
            # the exterior passes carry their own scratch, so a VMEM_OOM or
            # compile reject may be the overlap's fault, not the depth's
            log_warn(
                f"split-step overlap on {plan_now['route']}[m={plan_now['m']}] "
                f"exceeded the compiler's capability ({cls.value}); stepping "
                "down to overlap=off at the same depth"
            )
            p2 = dict(plan_now)
            p2["overlap"] = "off"
            p2["overlap_forced"] = True
            return rung_for(p2)
        if plan_now["route"] not in ("wavefront", "wrap") or plan_now["m"] <= 1:
            return None  # plane route is the bottom rung — propagate
        new_max = plan_now["m"] - 1
        log_warn(
            f"{plan_now['route']} depth m={plan_now['m']} exceeded the "
            f"compiler's capability ({cls.value}) at runtime; stepping down to "
            f"m<={new_max} (the VMEM model under-estimates on this "
            "toolchain — consider recalibrating _VMEM_STACK_MARGIN / "
            "STENCIL_VMEM_LIMIT_BYTES)"
        )
        p2 = dict(plan_stream(dd, x_radius, path, separable, max_m=new_max))
        # a descent never re-enables split, fused, or mxu: carry the
        # (post-step-down) axis state into the shallower plan as forced
        p2["overlap"] = plan_now.get("overlap", "off")
        p2["overlap_forced"] = True
        p2["halo"] = plan_now.get("halo", "array")
        p2["halo_forced"] = True
        p2["compute_unit"] = plan_now.get("compute_unit", "vpu")
        p2["compute_unit_forced"] = True
        p2["mxu_input"] = plan_now.get("mxu_input", "f32")
        p2["mxu_input_forced"] = True
        return rung_for(p2)

    # static prefilters on real backends: a rung the VMEM model
    # (analysis/vmem.py) already rejects descends WITHOUT compiling — the
    # mxu twin's resident band matrices are the case plan_stream's depth
    # gate never modeled, previously a compile-and-catch VMEM_OOM — and a
    # rung the Mosaic legality model (analysis/kernels.py) rejects
    # descends as a recorded COMPILE_REJECT the same way (the tuple
    # verdict names the class).  Interpret mode has no Mosaic: nothing to
    # budget, nothing to lower, the models must not veto there.
    prefilter = None
    if not interpret:
        def prefilter(rung):
            from stencil_tpu.analysis import check_kernel_legal, check_vmem
            from stencil_tpu.resilience.taxonomy import FailureClass

            # model what build() will actually compile: the unit resolves
            # through the same chain the build uses (_prospective_unit —
            # env/tuned mxu folds the band matrices in, a request that
            # structurally degrades to vpu must NOT be priced as mxu)
            p = dict(rung.state["plan"])
            p["compute_unit"] = _prospective_unit(p)
            reason = check_vmem(dd, p)
            if reason is not None:
                return reason
            reason = check_kernel_legal(dd, p)
            if reason is not None:
                return (reason, FailureClass.COMPILE_REJECT)
            return None

    ladder = DegradationLadder(
        rung_for(plan), lower=lower, label="stream", prefilter=prefilter
    )

    raw = dd.local_spec().raw_size()
    n_doms = dd.num_subdomains()
    band_area = 2 * (raw.y * raw.z + raw.x * raw.z + raw.x * raw.y) * len(
        dd._handles
    ) * n_doms
    # analytic MXU FLOPs of ONE raw iteration under the RESOLVED
    # contraction variant (all shards, all fields) — the dense model
    # over-reports a band-tiled run by ~n/(2r+1), which would poison every
    # roofline and perf-ledger series built on kernel.mxu.flops.  Modeled
    # on the plane geometry the pass actually CONTRACTS, not the raw
    # dims: the wrap route slices the bare interior, and the z-slab
    # wavefront lane-pads its planes — both change which band tiling (if
    # any) engages, so raw-dims pricing could count the wrong variant
    n_int = dd.local_spec().sz

    def _mxu_flops_iter(plan_now: dict) -> int:
        unit = plan_now.get("compute_unit", "vpu")
        if plan_now.get("route") == "wrap":
            py, pz, px = n_int.y, n_int.z, n_int.x
        else:
            py, px = raw.y, raw.x
            pz = lane_pad_width(raw.z) if plan_now.get("z_slabs") else raw.z
        return (
            mxu_flops_per_plane(py, pz, unit)
            * px * len(dd._handles) * n_doms
        )

    def _exterior_cells(plan_now, steps: int) -> int:
        """Analytic cells recomputed by the exterior band passes for this
        dispatch (all shards, all fields) — 0 under ``overlap=off``."""
        if plan_now.get("overlap") != "split":
            return 0
        if plan_now["route"] == "wavefront":
            mm = plan_now["m"]
            blocked, rem = divmod(steps, mm)
            return band_area * (blocked * mm + rem)
        return band_area * x_radius * steps

    def step(curr, steps: int = 1):
        out = ladder.step(curr, steps)
        plan_now = ladder.rung.state["plan"]
        step._stream_plan = plan_now
        cells = _exterior_cells(plan_now, steps)
        if cells:
            telemetry.inc(tm.STEP_OVERLAP_EXTERIOR_CELLS, cells)
        if unit_uses_mxu(plan_now.get("compute_unit", "vpu")):
            telemetry.inc(
                tm.KERNEL_MXU_FLOPS, steps * _mxu_flops_iter(plan_now)
            )
        return out

    step._marks_shell_stale = True
    # the eager build may already have descended (compile-phase rejection),
    # so expose the LADDER's plan, not the initial one
    step._stream_plan = ladder.rung.state["plan"]
    step._resilience = ladder
    step._resilience_label = "stream"
    return step


# --- batched dispatch (serve/pack.py) ----------------------------------------
#
# The serving layer's batch planner stacks geometry-matched tenant states
# along a leading axis and runs them as ONE dispatch.  How the batch axis
# is carried depends on the engine under the step:
#
# * the XLA slice engine (``make_step``'s jnp route) is plain traceable
#   jax — ``vmap`` threads the batch axis straight through the shard_map
#   and its ppermutes, and XLA fuses the batched program;
# * the plane pipeline (``make_stream_step``) bottoms out in pallas_call
#   grids whose VMEM plane rings are sized for ONE shard — vmap over a
#   pallas grid is not a supported lowering, so the batch axis is carried
#   as an EXPLICIT leading dim instead: ``lax.scan`` over the stacked
#   states calls the unbatched pass once per element inside one jitted
#   program (one dispatch at the host boundary, which is what serving
#   throughput is bounded by — see docs/serving.md "Throughput").
#
# Either way the per-element program is the UNBATCHED step itself, so each
# tenant's slice is bitwise-identical to a serial dispatch (the soak's
# packed legs pin this digest-for-digest).


def batch_axis_mode(step) -> str:
    """How a batched dispatch must carry the leading batch axis over
    ``step``: ``"vmap"`` for traceable-jax steps, ``"leading_dim"`` (an
    explicit scan) for plane-pipeline steps (``_stream_plan`` present)
    whose pallas grids vmap cannot lower."""
    return (
        "leading_dim"
        if getattr(step, "_stream_plan", None) is not None
        else "vmap"
    )


def make_batched_dispatch(
    step_fn: Callable, steps: int, mode: str
) -> Callable:
    """One jitted callable running ``step_fn(curr, steps)`` over every
    element of a stacked state dict (leading batch axis), per ``mode``
    (see ``batch_axis_mode``).  ``step_fn`` must be the RESOLVED per-shard
    callable — a raw ``make_step`` jit or a ladder's ``built()`` — not the
    telemetry-wrapping closure.  The stacked input is donated: callers
    stack with ``jnp.stack`` (a copy), so the per-tenant source buffers
    stay live for the serial fallback path."""
    if mode not in ("vmap", "leading_dim"):
        raise ValueError(
            f"unknown batch axis mode {mode!r} (vmap | leading_dim)"
        )
    if mode == "vmap":

        def batched(stacked):
            return jax.vmap(lambda c: step_fn(c, steps))(stacked)

    else:

        def batched(stacked):
            def body(carry, c):
                return carry, step_fn(c, steps)

            return lax.scan(body, 0, stacked)[1]

    return jax.jit(batched, donate_argnums=0)
