"""Plane-streaming engine for USER step kernels — fast by default.

In the reference, the stencil kernel is USER code: apps write plain CUDA
through ``Accessor`` (accessor.hpp:13-40, jacobi3d.cu:65-108,
astaroth_sim.cu:65-83) and the GPU cache hierarchy gives every such kernel
operand reuse for free.  The TPU analog of that cache reuse is an explicit
VMEM plane ring — which rounds 1-4 hard-coded into the jacobi/astaroth fast
paths.  This module is the generalization: it runs the SAME ``StepKernel``
signature that ``make_step``'s XLA route runs — ``views[name].sh(dx,dy,dz)``
reads plus ``info.coords()`` — but streams x-planes through VMEM so each HBM
plane is read once per pass instead of once per shifted operand (the XLA
slice formulation re-reads the block ~6x, measured 5-7.5 Gcells/s at 512^3
vs ~40+ for the streamed form).

Two routes, chosen by ``make_stream_step``:

* **plane** — one level per pass: exchange the shell, then stream planes
  with a ``2r``-deep ring (``r`` = the kernel's declared x read distance).
  Works for any per-axis shell widths and any ``r >= 1``.
* **wavefront** — ``m`` levels per pass over an ``s``-wide-shell shard
  (``m <= s // r``, ``r == 1`` only): each HBM plane is read and written
  once per ``m`` iterations (~``8/m`` B/cell), the temporal blocking that
  makes the flagship paths beat the bandwidth roofline.  Supports the z-slab
  form (z halos never touch the tiled array — see
  ``jacobi_shell_wavefront_step``'s layout notes) including the lane-padding
  of ragged plane widths, generalized to any field count.

The engine is bit-compatible with the XLA route: both call the user kernel
with the same per-cell arithmetic, so outputs agree exactly (modulo compiler
excess precision, which the interpret-mode tests pin).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from stencil_tpu.core.dim3 import Dim3
from stencil_tpu.utils.compat import shard_map
from stencil_tpu.ops.jacobi_pallas import (
    _make_roll,
    _padded_plane_bytes,
    _tpu_compiler_params,
    _vmem_budget,
    _VMEM_STACK_MARGIN,
    _WRAP_MAX_K,
)


class PlaneView:
    """Resident-plane window for one quantity inside a streaming kernel.

    ``sh(dx, dy, dz)`` mirrors ``ShardView.sh`` (the reference's
    ``src[o + Dim3(dx,dy,dz)]`` Accessor read, accessor.hpp:27-40): the
    x offset selects one of the ``2r+1`` VMEM-resident planes, the y/z
    offsets are in-plane rotates.  Rotate wraparound at the plane edges only
    contaminates shell cells the validity contract already sacrifices.
    """

    def __init__(self, window: Tuple[jax.Array, ...], roll):
        self._window = window
        self._r = (len(window) - 1) // 2
        self._roll = roll

    def sh(self, dx: int = 0, dy: int = 0, dz: int = 0) -> jax.Array:
        # ALL axes are bounded by the declared read radius: an in-plane
        # shift beyond it would wrap opposite-edge values into cells the
        # validity contract counts as correct — silently wrong results, so
        # fail at trace time instead
        assert all(-self._r <= d <= self._r for d in (dx, dy, dz)), (
            (dx, dy, dz), self._r,
        )
        v = self._window[self._r + dx]
        if dy:
            v = self._roll(v, -dy, 0)
        if dz:
            v = self._roll(v, -dz, 1)
        return v

    def center(self) -> jax.Array:
        return self._window[self._r]


@dataclasses.dataclass
class PlaneInfo:
    """Traced per-plane context handed to streaming kernels.  ``coords``
    returns broadcast-compatible pieces — x a scalar (the whole plane shares
    one global x), y a column, z a row — so kernels written against
    ``BlockInfo.coords()`` broadcasting run unchanged."""

    x_global: jax.Array  # int32 scalar: wrapped global x of the output plane
    y_global: jax.Array  # (Y, 1) int32 wrapped global y
    z_global: jax.Array  # (1, Z) int32 wrapped global z
    global_size: Dim3
    level: int  # wavefront level (1-based); 1 on the plane route

    def coords(self):
        return self.x_global, self.y_global, self.z_global


#: a streaming kernel is just a StepKernel evaluated on planes
PlaneKernel = Callable[[Dict[str, PlaneView], PlaneInfo], Dict[str, jax.Array]]


def _yz_coord_planes(origin_ref, Yr, Zr, off_y, off_z, gsize):
    """Wrapped global y/z coordinates of the raw plane, as a (Yr, 1) column
    and a (1, Zr) row (2D iotas — Mosaic has no 1D iota)."""
    y = lax.broadcasted_iota(jnp.int32, (Yr, 1), 0)
    z = lax.broadcasted_iota(jnp.int32, (1, Zr), 1)
    gy, gz = jnp.int32(gsize.y), jnp.int32(gsize.z)
    # + gsize keeps lax.rem's operand non-negative (origin - shell >= -shell)
    y_g = lax.rem(origin_ref[1] + gy + y - jnp.int32(off_y), gy)
    z_g = lax.rem(origin_ref[2] + gz + z - jnp.int32(off_z), gz)
    return y_g, z_g


def stream_plane_pass(
    kernel: PlaneKernel,
    names: Sequence[str],
    raws: Sequence[jax.Array],  # per-quantity (X, Y, Z) shell-carrying blocks
    lo: Dim3,
    hi: Dim3,  # shell widths (allocation minus interior)
    x_radius: int,  # kernel x read distance r; ring depth is 2r
    origin: jax.Array,  # (3,) int32 global coords of the interior start
    global_size: Dim3,
    interpret: bool = False,
) -> List[jax.Array]:
    """ONE kernel level over shell-carrying blocks, streaming x-planes with a
    ``2r``-deep ring per quantity; shell planes and the in-plane shell ring
    pass through unchanged (the exchange owns halo cells).  Generalizes
    ``mean6_plane_step``/``jacobi_plane_step`` to user kernels, any field
    count, and any ``r >= 1``."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nq = len(names)
    X, Y, Z = raws[0].shape
    r = x_radius
    assert r >= 1 and lo.x >= r and hi.x >= r, (r, lo, hi)
    assert lo.y >= r and hi.y >= r and lo.z >= r and hi.z >= r, (r, lo, hi)
    y0, y1 = lo.y, Y - hi.y
    z0, z1 = lo.z, Z - hi.z
    roll = _make_roll(interpret)
    gsize = global_size

    def body(origin_ref, *refs):
        in_refs = refs[:nq]
        out_refs = refs[nq : 2 * nq]
        rings = refs[2 * nq :]
        i = pl.program_id(0)
        curs = [ref[0] for ref in in_refs]

        y_g, z_g = _yz_coord_planes(origin_ref, Y, Z, lo.y, lo.z, gsize)

        # output plane j = i - r; window is raw planes j-r .. j+r
        j = i - r
        in_window = jnp.logical_and(j >= lo.x, j <= X - hi.x - 1)

        def plane(q, t):  # raw plane i - t for quantity q (t in [0, 2r])
            return curs[q] if t == 0 else rings[q][(i - t) % (2 * r)]

        @pl.when(jnp.logical_and(i >= 1, i <= X + r - 1))
        def _():
            @pl.when(in_window)
            def _():
                views = {
                    names[q]: PlaneView(
                        tuple(plane(q, 2 * r - d) for d in range(2 * r + 1)),
                        roll,
                    )
                    for q in range(nq)
                }
                x_g = lax.rem(
                    origin_ref[0] + jnp.int32(gsize.x) + j - jnp.int32(lo.x),
                    jnp.int32(gsize.x),
                )
                info = PlaneInfo(x_g, y_g, z_g, gsize, 1)
                vals = kernel(views, info)
                for q, name in enumerate(names):
                    cent = plane(q, r)
                    out_refs[q][0] = cent  # keep the y/z shell ring
                    if name in vals:
                        out_refs[q][0, y0:y1, z0:z1] = vals[name][
                            y0:y1, z0:z1
                        ].astype(cent.dtype)

            @pl.when(jnp.logical_not(in_window))
            def _():
                for q in range(nq):
                    # shell plane j = i - r passes through from the ring
                    # (slot is garbage for i < r, where plane j < 0 doesn't
                    # exist — those writes land on out plane 0, which step
                    # i == r rewrites with the real pass-through)
                    out_refs[q][0] = plane(q, r)

        @pl.when(i == 0)
        def _():
            for q in range(nq):
                out_refs[q][0] = curs[q]  # first plane passes through

        # push the fetched plane (skip replayed last-plane refetches)
        @pl.when(i <= X - 1)
        def _():
            for q in range(nq):
                rings[q][i % (2 * r)] = curs[q]

    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + [
        pl.BlockSpec((1, Y, Z), lambda i: (jnp.minimum(i, X - 1), 0, 0))
        for _ in range(nq)
    ]
    out_specs = tuple(
        pl.BlockSpec((1, Y, Z), lambda i: (jnp.clip(i - r, 0, X - 1), 0, 0))
        for _ in range(nq)
    )
    out_shape = tuple(
        jax.ShapeDtypeStruct((X, Y, Z), b.dtype) for b in raws
    )
    outs = pl.pallas_call(
        body,
        grid=(X + r,),
        in_specs=in_specs,
        out_specs=out_specs if nq > 1 else out_specs[0],
        out_shape=out_shape if nq > 1 else out_shape[0],
        scratch_shapes=[
            pltpu.VMEM((2 * r, Y, Z), b.dtype) for b in raws
        ],
        interpret=interpret,
        **_tpu_compiler_params(interpret),
    )(origin.astype(jnp.int32), *raws)
    return list(outs) if nq > 1 else [outs]


def stream_wavefront_pass(
    kernel: PlaneKernel,
    names: Sequence[str],
    raws: Sequence[jax.Array],  # per-quantity (Xr, Yr, Zr) FILLED-shell blocks
    m: int,  # levels to advance (<= shell width)
    s_off: int,  # shell width (raw index of the interior start)
    origin: jax.Array,
    global_size: Dim3,
    z_slabs: Sequence[jax.Array] = None,  # per-q (Xr, 2s, Yr) z-major slabs
    z_valid: int = None,  # logical plane width; [z_valid, Zr) is lane padding
    alias: bool = False,
    interpret: bool = False,
):
    """``m`` kernel levels in ONE pass over ``s_off``-shell-carrying shards —
    the user-kernel generalization of ``jacobi_shell_wavefront_step`` (see
    its docstring for the shrinking-validity contamination argument, the
    z-slab layout, and the lane-padding rationale; all carry over verbatim).
    Returns the advanced blocks, plus per-quantity outgoing z slabs when
    ``z_slabs`` is given."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nq = len(names)
    Xr, Yr, Zr = raws[0].shape
    zv = Zr if z_valid is None else z_valid
    assert 1 <= m <= s_off and 2 * s_off < min(Xr, Yr, zv), (m, s_off, zv)
    gsize = global_size
    assert 2 * s_off < gsize.x, (s_off, gsize)  # non-negative lax.rem operand
    roll = _make_roll(interpret)

    def body(origin_ref, *refs):
        in_refs = refs[:nq]
        if z_slabs is not None:
            zs_refs = refs[nq : 2 * nq]
            out_refs = refs[2 * nq : 3 * nq]
            zout_refs = refs[3 * nq : 4 * nq]
            rings = refs[4 * nq :]
        else:
            out_refs = refs[nq : 2 * nq]
            zout_refs = None
            rings = refs[2 * nq :]
        i = pl.program_id(0)
        vals = [ref[0] for ref in in_refs]  # level-0 raw plane i per quantity
        y_g, z_g = _yz_coord_planes(origin_ref, Yr, Zr, s_off, s_off, gsize)
        if z_slabs is not None:
            # patch the z-shell columns in VMEM — never stored in the big
            # array (see jacobi_shell_wavefront_step)
            col = lax.broadcasted_iota(jnp.int32, (Yr, Zr), 1)
            for q in range(nq):
                zst = jnp.swapaxes(zs_refs[q][0], 0, 1)  # (Yr, 2s)
                v = vals[q]
                for j in range(s_off):
                    v = jnp.where(col == j, zst[:, j][:, None], v)
                    v = jnp.where(
                        col == zv - s_off + j, zst[:, s_off + j][:, None], v
                    )
                vals[q] = v
        for s in range(1, m + 1):
            prevs = [rings[q][s - 1, i % 2] for q in range(nq)]
            cents = [rings[q][s - 1, (i + 1) % 2] for q in range(nq)]
            for q in range(nq):
                rings[q][s - 1, i % 2] = vals[q]  # push plane i-s+1
            views = {
                names[q]: PlaneView((prevs[q], cents[q], vals[q]), roll)
                for q in range(nq)
            }
            x_g = lax.rem(
                origin_ref[0] + jnp.int32(gsize.x) + i - jnp.int32(s + s_off),
                jnp.int32(gsize.x),
            )
            info = PlaneInfo(x_g, y_g, z_g, gsize, s)
            new = kernel(views, info)
            vals = [
                new[names[q]].astype(cents[q].dtype)
                if names[q] in new
                else cents[q]
                for q in range(nq)
            ]
        for q in range(nq):
            out_refs[q][0] = vals[q]  # level-m plane i-m
            if zout_refs is not None:
                emit = jnp.concatenate(
                    [
                        vals[q][:, zv - 2 * s_off : zv - s_off],
                        vals[q][:, s_off : 2 * s_off],
                    ],
                    axis=1,
                )  # (Yr, 2s)
                zout_refs[q][0] = jnp.swapaxes(emit, 0, 1)

    out_idx = lambda i: (jnp.maximum(i - m, 0), 0, 0)
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + [
        pl.BlockSpec((1, Yr, Zr), lambda i: (i, 0, 0)) for _ in range(nq)
    ]
    out_specs: list = [pl.BlockSpec((1, Yr, Zr), out_idx) for _ in range(nq)]
    out_shape: list = [
        jax.ShapeDtypeStruct((Xr, Yr, Zr), b.dtype) for b in raws
    ]
    args = [origin.astype(jnp.int32), *raws]
    if z_slabs is not None:
        for q in range(nq):
            assert z_slabs[q].shape == (Xr, 2 * s_off, Yr), z_slabs[q].shape
        in_specs += [
            pl.BlockSpec((1, 2 * s_off, Yr), lambda i: (i, 0, 0))
            for _ in range(nq)
        ]
        out_specs += [pl.BlockSpec((1, 2 * s_off, Yr), out_idx) for _ in range(nq)]
        out_shape += [
            jax.ShapeDtypeStruct((Xr, 2 * s_off, Yr), b.dtype) for b in raws
        ]
        args += list(z_slabs)
    # in-place safe (write trails read by m+1 planes); un-aliased is ~20%
    # faster at deep m (probe21b) at the cost of fresh output buffers
    aliases = {1 + q: q for q in range(nq)} if alias else {}
    outs = pl.pallas_call(
        body,
        grid=(Xr,),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        input_output_aliases=aliases,
        scratch_shapes=[
            pltpu.VMEM((m, 2, Yr, Zr), b.dtype) for b in raws
        ],
        interpret=interpret,
        **_tpu_compiler_params(interpret),
    )(*args)
    outs = list(outs)
    if z_slabs is not None:
        return outs[:nq], outs[nq:]
    return outs, None


def stream_wrap_pass(
    kernel: PlaneKernel,
    names: Sequence[str],
    blocks: Sequence[jax.Array],  # per-quantity BARE (X, Y, Z) interiors
    k: int,  # temporal depth (1 <= k <= X//2)
    origin: jax.Array,  # (3,) int32 — global coords of the block start
    global_size: Dim3,
    interpret: bool = False,
) -> List[jax.Array]:
    """``k`` kernel levels over the WHOLE (single-device) domain with the
    periodic wrap folded in — the user-kernel generalization of
    ``jacobi_wrap_step`` (see its docstring: the x-wrap rides the modular
    block index map with a ``2k``-step replay closing every level's ring;
    the y/z wrap is the natural roll wraparound on exact-sized planes).
    No shell, no exchange, ~8/k HBM bytes per cell per iteration."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nq = len(names)
    X, Y, Z = blocks[0].shape
    assert 1 <= k <= X // 2, (k, X)
    roll = _make_roll(interpret)
    gsize = global_size

    def body(origin_ref, *refs):
        in_refs = refs[:nq]
        out_refs = refs[nq : 2 * nq]
        rings = refs[2 * nq :]
        i = pl.program_id(0)
        vals = [ref[0] for ref in in_refs]  # level-0 plane i (mod X)
        y_g, z_g = _yz_coord_planes(origin_ref, Y, Z, 0, 0, gsize)
        for s in range(1, k + 1):
            prevs = [rings[q][s - 1, i % 2] for q in range(nq)]
            cents = [rings[q][s - 1, (i + 1) % 2] for q in range(nq)]
            for q in range(nq):
                rings[q][s - 1, i % 2] = vals[q]
            views = {
                names[q]: PlaneView((prevs[q], cents[q], vals[q]), roll)
                for q in range(nq)
            }
            x_g = lax.rem(
                origin_ref[0] + jnp.int32(gsize.x) + i - jnp.int32(s),
                jnp.int32(gsize.x),
            )
            info = PlaneInfo(x_g, y_g, z_g, gsize, s)
            new = kernel(views, info)
            vals = [
                new[names[q]].astype(cents[q].dtype)
                if names[q] in new
                else cents[q]
                for q in range(nq)
            ]
        for q in range(nq):
            out_refs[q][0] = vals[q]  # level-k plane (i - k) % X

    outs = pl.pallas_call(
        body,
        grid=(X + 2 * k,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [pl.BlockSpec((1, Y, Z), lambda i: (i % X, 0, 0)) for _ in range(nq)],
        out_specs=tuple(
            pl.BlockSpec((1, Y, Z), lambda i: ((i - k) % X, 0, 0))
            for _ in range(nq)
        ),
        out_shape=tuple(
            jax.ShapeDtypeStruct((X, Y, Z), b.dtype) for b in blocks
        ),
        scratch_shapes=[pltpu.VMEM((k, 2, Y, Z), b.dtype) for b in blocks],
        interpret=interpret,
        **_tpu_compiler_params(interpret),
    )(origin.astype(jnp.int32), *blocks)
    # out_shape is always a tuple, so pallas returns a tuple even for nq=1
    return list(outs)


def stream_vmem_fits(
    m: int, plane_y: int, plane_z: int, itemsizes: Sequence[int], z_slabs: bool
) -> bool:
    """VMEM model of the generic wavefront: per quantity, 2m ring planes +
    4 pipeline planes (+ 4 z-slab blocks), plus a PER-QUANTITY stack margin —
    the level loop holds each field's roll/select temporaries live at once
    (measured: 8-field m=2 at 518x640 planes reported 108.6 MB against an
    85 MB block model, ~2.6 MB of stack per field).  Same padded-bytes
    accounting as ``wavefront_vmem_bytes``."""
    est = 0
    for it in itemsizes:
        est += (2 * m + 4) * _padded_plane_bytes(plane_y, plane_z, it)
        if z_slabs:
            est += 4 * _padded_plane_bytes(2 * m, plane_y, it)
    return est + _VMEM_STACK_MARGIN * len(itemsizes) <= _vmem_budget()


def _tuned_stream_plan(dd, x_radius: int, separable: bool) -> dict:
    """A structurally VALID persisted plan for this domain from the
    autotuner, or None.  Validity is re-checked here (not trusted from the
    file): the cache key pins chip/shape/dtype/mesh/radius/route, but a
    hand-edited or cross-version file must degrade to the static plan, not
    crash the build."""
    from stencil_tpu import tune

    cfg = tune.best_config(dd.tune_key("stream"))
    if cfg is None:
        return None
    route = cfg.get("route")
    m = cfg.get("m")
    plan = {
        "route": route,
        "m": m,
        "z_slabs": bool(cfg.get("z_slabs", False)),
        "grouping": cfg.get("grouping", "joint"),
    }
    if cfg.get("alias") is not None:
        plan["alias"] = bool(cfg["alias"])
    n = dd.local_spec().sz
    shell = dd._shell_radius
    lo, hi = shell.lo(), shell.hi()
    padded = any(v is not None for v in dd._valid_last)
    ok = isinstance(m, int) and m >= 1
    if ok and plan["grouping"] == "per-field":
        ok = separable and len(dd._handles) > 1
    elif ok and plan["grouping"] != "joint":
        ok = False
    if ok and route == "wrap":
        ok = dd.num_subdomains() == 1 and x_radius == 1 and m <= n.x // 2
    elif ok and route == "wavefront":
        uniform = len({lo.x, lo.y, lo.z, hi.x, hi.y, hi.z}) == 1
        v_min = min(
            (dd._valid_last[ax] if dd._valid_last[ax] is not None else n[ax])
            for ax in range(3)
        )
        ok = (
            x_radius == 1
            and uniform
            and lo.x >= 2
            and 2 <= m <= min(lo.x, v_min)
            and not (plan["z_slabs"] and padded)
        )
    elif ok and route == "plane":
        ok = m == 1 and not plan["z_slabs"]
    elif ok:
        ok = False
    if not ok:
        from stencil_tpu.utils.logging import log_warn

        log_warn(
            f"tuned stream config {cfg} is structurally invalid for this "
            "domain (shell/shards changed since it was measured?); using "
            "the static plan"
        )
        return None
    return plan


def plan_stream(dd, x_radius: int, path: str = "auto", separable: bool = False,
                max_m: int = None) -> dict:
    """Route planning for ``make_stream_step`` on a REALIZED domain.

    Returns ``{"route": "wrap"|"wavefront"|"plane", "m": int,
    "z_slabs": bool, "grouping": str}``.  On a SINGLE subdomain the wrap
    route wins (periodic boundary folded into the kernel: no shell reads,
    no exchange, deepest temporal blocking).  Wavefront needs: x_radius 1,
    uniform face shell >= 2; depth m = the deepest level count that fits
    the VMEM model, capped by the shell width and the measured plateau
    (_WRAP_MAX_K).  The plane route covers everything else the engine
    supports.

    PADDED (uneven) shards run BOTH routes: the exchange blends each halo at
    the dynamic valid-width offset, i.e. contiguously after the valid cells,
    so (a) every valid cell's stencil reads the right neighbor, (b) the
    wrapped linear coordinate formula ``(origin - s + index) mod g`` is
    correct at the halo positions too (the global size equals the last
    shard's origin + valid width), and (c) pad cells beyond the halo
    contaminate only the sacrificial shrinking-validity levels — the same
    argument as the wavefront's dead lane padding.  Hence the PLAIN
    wavefront works on padded shards with no kernel changes; only the
    z-slab form (static emit slices at the interior z boundary) stays
    even-shard-only, and the depth is additionally capped by the smallest
    VALID extent (a shard narrower than the shell cannot fill its
    neighbor's halo).

    ``path`` forces a route: "plane" skips the wavefront upgrade (per-step
    exchange parity, e.g. comm-volume modeling); "wavefront" raises instead
    of falling back.  Raises ValueError for N-D component data (the engine
    streams scalar planes only).

    ``separable=True`` declares that the kernel handles arbitrary SUBSETS of
    the views dict (each field's update reads only that field — astaroth's
    per-field mean).  When all fields together blow the VMEM model, the plan
    then falls back to per-field kernel calls ("grouped": one streaming pass
    per field per macro, same total HBM traffic) instead of a shallower m.
    ``max_m`` caps the wavefront depth (the runtime compile-failure fallback
    steps it down).
    """
    if any(h.components for h in dd._handles):
        raise ValueError("the streaming engine does not support N-D component data")
    if path not in ("auto", "plane", "wavefront", "wrap"):
        raise ValueError(f"unknown stream path {path!r}")
    # the autotuner's persisted pick wins over the static model below, but
    # only on the unconstrained auto path: a forced route is an explicit
    # request, and a depth cap (user stream_depth / the ladder's compile-
    # failure step-down) must re-plan statically under the cap rather than
    # re-apply the tuned depth that just failed
    if path == "auto" and max_m is None:
        tuned = _tuned_stream_plan(dd, x_radius, separable)
        if tuned is not None:
            return tuned
    padded = any(v is not None for v in dd._valid_last)
    shell = dd._shell_radius
    lo, hi = shell.lo(), shell.hi()
    n = dd.local_spec().sz
    if not all(lo[ax] >= x_radius and hi[ax] >= x_radius for ax in range(3)):
        raise ValueError(
            f"shell {lo}/{hi} narrower than the kernel x_radius {x_radius}"
        )
    uniform = len({lo.x, lo.y, lo.z, hi.x, hi.y, hi.z}) == 1
    s = lo.x
    itemsizes = [h.dtype.itemsize for h in dd._handles]
    # single device: the WRAP route folds the periodic boundary into the
    # kernel's index maps/rotates — no shell reads, no exchange, the deepest
    # temporal blocking (the user-kernel analog of jacobi_wrap_step)
    if path in ("auto", "wrap") and dd.num_subdomains() == 1 and x_radius == 1:
        cap = min(_WRAP_MAX_K, n.x // 2)
        if max_m is not None:
            cap = min(cap, max_m)
        best = None
        for grouping, sizes in (
            [("joint", itemsizes)]
            + ([("per-field", [max(itemsizes)])] if separable and len(itemsizes) > 1 else [])
        ):
            k = 0
            for cand in range(1, cap + 1):
                if stream_vmem_fits(cand, n.y, n.z, sizes, False):
                    k = cand
            # deepest k across groupings — depth is the traffic lever
            # (~8/k B/cell/iter); joint wins ties
            if k >= 1 and (best is None or k > best["m"]):
                best = {"route": "wrap", "m": k, "z_slabs": False, "grouping": grouping}
        if best is not None:
            return best
    if path == "wrap":
        raise ValueError(
            "path='wrap' needs a single subdomain with >= 2 x-planes, "
            "x_radius 1, and VMEM for at least one resident plane ring"
        )
    if path != "plane" and x_radius == 1 and uniform and s >= 2:
        # (No shell-traffic heuristic here: the shell width s is GIVEN — the
        # domain already allocated and exchanges it — so advancing more
        # levels per exchange is strictly less traffic.)  realize() already
        # rejects any shard whose valid extent is below the shell width
        # (domain.py "subdomain ... smaller than radius shell"), so every
        # shard this plan can see fills an s-wide halo from valid cells.
        v_min = min(
            (dd._valid_last[ax] if dd._valid_last[ax] is not None else n[ax])
            for ax in range(3)
        )
        assert v_min >= s, (v_min, s)  # the realize() invariant
        cap = min(s, _WRAP_MAX_K)
        if max_m is not None:
            cap = min(cap, max_m)
        raw = dd.local_spec().raw_size()
        zp = -(-raw.z // 128) * 128
        # evaluate joint (all fields per pass) AND per-field grouping for
        # separable kernels, then take the DEEPEST m — depth is the traffic
        # lever (~8/m B/cell/iter); grouping only changes VMEM pressure and
        # per-pass ramp overhead, so joint wins ties
        group_options = [("joint", itemsizes)]
        if separable and len(itemsizes) > 1:
            group_options.append(("per-field", [max(itemsizes)]))
        best = None
        # z-slab form's static emit slices assume even shards
        z_modes = ((False, raw.z),) if padded else ((True, zp), (False, raw.z))
        for grouping, sizes in group_options:
            for z_mode, plane_z in z_modes:
                m = 0 if z_mode else 1
                for cand in range(2, cap + 1):
                    if stream_vmem_fits(cand, raw.y, plane_z, sizes, z_mode):
                        m = cand
                if m >= 2 and (best is None or m > best["m"]):
                    best = {
                        "route": "wavefront",
                        "m": m,
                        "z_slabs": z_mode,
                        "grouping": grouping,
                    }
                if m >= 2:
                    # take the z-slab form for this grouping even if the
                    # plain form could fit a level deeper (its slab blocks
                    # are tiny): the plain form pays the ~64x-amplified
                    # thin-z in-array exchange every macro (probe12d)
                    break
        if best is not None:
            return best
    if path == "wavefront":
        raise ValueError(
            "path='wavefront' needs x_radius 1, a uniform face shell >= 2, "
            "valid shard extents >= the depth, and VMEM for m >= 2; got "
            f"shell {lo}/{hi}"
        )
    raw = dd.local_spec().raw_size()
    grouping = "joint"
    if not stream_vmem_fits(x_radius, raw.y, raw.z, itemsizes, False):
        # (2r+4) resident planes per field blow the budget jointly
        if separable and len(itemsizes) > 1:
            grouping = "per-field"
    return {"route": "plane", "m": 1, "z_slabs": False, "grouping": grouping}


def lane_pad_width(z: int) -> int:
    """Plane width rounded up to a 128 multiple — ragged lane extents stream
    ~30% slower (probe22), so z-slab wavefronts pad with dead columns."""
    return -(-z // 128) * 128


def prime_z_slabs(block: jax.Array, Zr: int, s: int) -> jax.Array:
    """The initial outgoing z-slab buffer for a macro chain: the block's
    interior z-boundary columns, packed [(-z)-bound | (+z)-bound] and
    transposed z-major (Xr, 2s, Yr) — the one strided read per dispatch;
    every later slab is kernel-emitted."""
    return jnp.concatenate(
        [
            jnp.swapaxes(block[:, :, Zr - 2 * s : Zr - s], 1, 2),
            jnp.swapaxes(block[:, :, s : 2 * s], 1, 2),
        ],
        axis=1,
    )


def make_slab_extenders(Xr: int, Yr: int, s: int, mesh_shape, axis_names=None):
    """(yext, xext) for z-major slab buffers: after the z ppermute, each slab
    is extended with rows from the y neighbors and then planes from the x
    neighbors — two hops that carry the xyz-corner cells from the diagonal
    blocks, mirroring the in-array exchange's sweep order.  Shared by the
    generic engine and the bespoke jacobi wavefront."""
    from stencil_tpu.ops.exchange import _shift_from_high, _shift_from_low
    from stencil_tpu.parallel.mesh import MESH_AXES

    names = MESH_AXES if axis_names is None else axis_names

    def yext(S):
        lo_ = _shift_from_low(S[:, :, Yr - 2 * s : Yr - s], names[1], mesh_shape[1])
        hi_ = _shift_from_high(S[:, :, s : 2 * s], names[1], mesh_shape[1])
        # stencil-lint: disable=halo-set-in-loop writes land on the thin z-slab buffers (2s planes), not the full domain — slab extension IS the design that keeps z halos out of the big array (PERF_NOTES z-slabs)
        return S.at[:, :, 0:s].set(lo_).at[:, :, Yr - s : Yr].set(hi_)

    def xext(S):
        lo_ = _shift_from_low(S[Xr - 2 * s : Xr - s], names[0], mesh_shape[0])
        hi_ = _shift_from_high(S[s : 2 * s], names[0], mesh_shape[0])
        # stencil-lint: disable=halo-set-in-loop same: x-extension of the thin z-slab buffers, sublane-cheap and off the big array
        return S.at[0:s].set(lo_).at[Xr - s : Xr].set(hi_)

    return yext, xext


def permute_and_extend_z_slabs(zout, s: int, mesh_shape, yext, xext):
    """One macro's incoming z-slab buffer from the previous macro's outgoing
    one: ppermute the two direction halves along z, then extend with y- and
    x-neighbor content (corner propagation)."""
    from stencil_tpu.ops.exchange import _shift_from_high, _shift_from_low
    from stencil_tpu.parallel.mesh import MESH_AXES

    zlo = _shift_from_low(zout[:, 0:s, :], MESH_AXES[2], mesh_shape[2])
    zhi = _shift_from_high(zout[:, s : 2 * s, :], MESH_AXES[2], mesh_shape[2])
    return jnp.concatenate([xext(yext(zlo)), xext(yext(zhi))], axis=1)


def _resolve_stream_alias(plan: dict, n_fields: int) -> bool:
    """input_output_aliases decision for a stream plan.  Precedence mirrors
    the bespoke wavefront path (models/jacobi.py): an autotuner CANDIDATE
    build (``alias_forced`` — its A/B trials must actually differ, whatever
    the environment says) > ``STENCIL_STREAM_ALIAS=0/1`` (validated read) >
    the plan's persisted tuned ``alias`` > the >= 4-fields static rule."""
    from stencil_tpu.utils.config import env_choice

    if plan.get("alias_forced") and plan.get("alias") is not None:
        return bool(plan["alias"])
    env = env_choice("STENCIL_STREAM_ALIAS", "auto", ("auto", "0", "1"))
    if env != "auto":
        return env == "1"
    if plan.get("alias") is not None:
        return bool(plan["alias"])
    return n_fields >= 4


def _build_stream_step(dd, kernel, x_radius, plan, interpret, donate=True):
    from jax.sharding import PartitionSpec as P

    from stencil_tpu.ops.exchange import halo_exchange_multi
    from stencil_tpu.parallel.mesh import MESH_AXES

    names = [h.name for h in dd._handles]
    valid_last = dd._valid_last
    n = dd.local_spec().sz
    shell = dd._shell_radius
    lo, hi = shell.lo(), shell.hi()
    mesh_shape = tuple(dd.mesh.shape[a] for a in MESH_AXES)
    gsize = dd._size
    raw = dd.local_spec().raw_size()
    spec = P(*MESH_AXES)
    # per-field grouping: one streaming pass per group per macro (valid only
    # for kernels declared separable); the exchange stays JOINT (<= 6
    # permutes for any field count) either way
    if plan.get("grouping") == "per-field":
        groups = [[q] for q in range(len(names))]
    else:
        groups = [list(range(len(names)))]
    # the z sweep of every in-step exchange runs the domain's realize-
    # resolved route (packed z-shell vs direct — ops/exchange.py), so stream
    # steps escape the 64×-amplified thin-z path exactly like exchange()
    exch_route = getattr(dd, "_exchange_route", "direct")
    # Un-aliased wavefront passes are ~10-20% faster for FEW fields
    # (probe21b: the in-place alias serializes the deep-m pipeline) but cost
    # one fresh raw-sized buffer per pass.  From 4 fields up, alias: a joint
    # pass would double a multi-GB working set (8 x ~700 MB exhausted HBM in
    # bench), and even per-field grouped passes measured ~50% SLOWER
    # un-aliased at 8x512^3 (19.1 vs 12.8 ms/iter, r5 bench) — the per-pass
    # allocate/free churn costs more than the aliasing serialization saves.
    alias = _resolve_stream_alias(plan, len(names))

    def origin_of():
        # NOTE: must be called INSIDE the fori_loop body that consumes it.
        # axis_index lowers to partition-id; a while-loop OPERAND whose def
        # chain includes partition-id trips XLA's SPMD partitioner
        # ("PartitionId instruction is not supported for SPMD partitioning")
        # on some toolchains, while the same op inside the body partitions
        # fine (and LICM hoists it after partitioning anyway).
        return jnp.stack(
            [lax.axis_index(MESH_AXES[ax]) * n[ax] for ax in range(3)]
        )

    if plan["route"] == "wrap":
        k = plan["m"]

        def per_shard(steps, *blocks_raw):
            bs = tuple(
                lax.slice(b, (lo.x, lo.y, lo.z), (lo.x + n.x, lo.y + n.y, lo.z + n.z))
                for b in blocks_raw
            )

            def one(depth, bs):
                origin = origin_of()
                out = list(bs)
                for g in groups:
                    outs = stream_wrap_pass(
                        kernel, [names[q] for q in g], [bs[q] for q in g],
                        depth, origin, gsize, interpret=interpret,
                    )
                    for q, o in zip(g, outs):
                        out[q] = o
                return tuple(out)

            blocked, rem = divmod(steps, k)
            bs = lax.fori_loop(0, blocked, lambda _, b: one(k, b), bs)
            if rem:
                bs = one(rem, bs)
            return tuple(
                # stencil-lint: disable=sliver-dus whole-interior write-back after the wrap loop — b spans the full interior, not a y/z sliver
                lax.dynamic_update_slice(rb, b, (lo.x, lo.y, lo.z))
                for rb, b in zip(blocks_raw, bs)
            )

    elif plan["route"] == "plane":

        def per_shard(steps, *blocks):
            def body(_, bs):
                origin = origin_of()
                bs = list(
                    halo_exchange_multi(
                        bs, shell, mesh_shape, valid_last=valid_last,
                        route=exch_route,
                    )
                )
                out = list(bs)
                for g in groups:
                    outs = stream_plane_pass(
                        kernel, [names[q] for q in g], [bs[q] for q in g],
                        lo, hi, x_radius, origin, gsize, interpret=interpret,
                    )
                    for q, o in zip(g, outs):
                        out[q] = o
                return tuple(out)

            return lax.fori_loop(0, steps, body, tuple(blocks))

    else:
        m = plan["m"]
        s = lo.x
        z_slab_mode = plan["z_slabs"]
        Xr, Yr, Zr = raw.x, raw.y, raw.z
        Zp = lane_pad_width(Zr) if z_slab_mode else Zr
        yext, xext = make_slab_extenders(Xr, Yr, s, mesh_shape)

        def wavefront_groups(bs, depth, origin, zs=None):
            """Run the m-level pass group by group; returns (outs, zouts)."""
            outs = list(bs)
            zouts = [None] * len(bs) if zs is not None else None
            for g in groups:
                o, z = stream_wavefront_pass(
                    kernel, [names[q] for q in g], [bs[q] for q in g],
                    depth, s, origin, gsize,
                    z_slabs=[zs[q] for q in g] if zs is not None else None,
                    z_valid=Zr if zs is not None else None,
                    alias=alias,
                    interpret=interpret,
                )
                for j, q in enumerate(g):
                    outs[q] = o[j]
                    if z is not None:
                        zouts[q] = z[j]
            return outs, zouts

        def per_shard(steps, *blocks):
            if not z_slab_mode:

                def macro(depth, bs):
                    origin = origin_of()
                    bs = list(
                        halo_exchange_multi(
                            bs, shell, mesh_shape, valid_last=valid_last,
                            route=exch_route,
                        )
                    )
                    outs, _ = wavefront_groups(bs, depth, origin)
                    return tuple(outs)

                macros, rem = divmod(steps, m)
                bs = lax.fori_loop(0, macros, lambda _, b: macro(m, b), tuple(blocks))
                if rem:
                    bs = macro(rem, bs)
                return bs

            def macro(depth, carry):
                origin = origin_of()
                bs, zouts = carry
                bs = list(
                    halo_exchange_multi(bs, shell, mesh_shape, axes=(0, 1))
                )
                zs = [
                    permute_and_extend_z_slabs(zout, s, mesh_shape, yext, xext)
                    for zout in zouts
                ]
                outs, zouts = wavefront_groups(bs, depth, origin, zs)
                return tuple(outs), tuple(zouts)

            # prime slabs from the blocks' interior z boundaries, lane-pad
            bs = tuple(
                jnp.pad(b, ((0, 0), (0, 0), (0, Zp - Zr))) for b in blocks
            )
            zouts = tuple(prime_z_slabs(b, Zr, s) for b in blocks)
            macros, rem = divmod(steps, m)
            carry = lax.fori_loop(
                0, macros, lambda _, c: macro(m, c), (bs, zouts)
            )
            if rem:
                carry = macro(rem, carry)
            return tuple(b[:, :, :Zr] for b in carry[0])

    donate_kw = {"donate_argnums": 0} if donate else {}

    @partial(jax.jit, static_argnums=1, **donate_kw)
    def step(curr, steps: int = 1):
        # check_vma off: pallas_call outputs carry no vma annotation
        fn = shard_map(
            partial(per_shard, steps),
            mesh=dd.mesh,
            in_specs=tuple(spec for _ in names),
            out_specs=tuple(spec for _ in names),
            check_vma=False,
        )
        outs = fn(*[curr[k] for k in names])
        return dict(zip(names, outs))

    return step


def make_stream_step(
    dd,
    kernel: PlaneKernel,
    x_radius: int = 1,
    path: str = "auto",
    separable: bool = False,
    interpret: bool = False,
    donate: bool = True,
    max_depth: int = None,
):
    """Build a ``step(curr, steps) -> curr`` running ``kernel`` under the
    plane-streaming engine — the fast-by-default path for user stencils
    (``DistributedDomain.make_step(..., engine="stream")``).

    The kernel is the SAME ``(views, info) -> {name: values}`` callable the
    XLA route accepts, restricted to: ALL shifts (x, y, and z) within
    ``x_radius`` (``PlaneView.sh`` asserts this at trace time), elementwise
    arithmetic (every view read and ``info.coords()`` piece broadcasts to
    the plane), no N-D component data.
    ``separable=True`` additionally declares the kernel correct on arbitrary
    view subsets, letting many-field domains stream per-field (see
    ``plan_stream``).

    ``max_depth`` caps the temporal depth (wrap k / wavefront m).  The auto
    planner maximizes depth because depth is the HBM-traffic lever
    (~bytes/k per cell) — correct for bandwidth-bound kernels, but a
    COMPUTE-heavy kernel (e.g. 27 taps/cell) multiplies its VPU work by the
    depth with nothing to amortize; cap it low (2-4) for such kernels.

    The returned step rides the resilience DEGRADATION LADDER
    (``resilience/ladder.py``): if Mosaic rejects the planned wavefront depth
    (scoped-VMEM OOM, or any other classified compile reject), the ladder
    re-plans one level shallower and retries, logging a recalibration hint,
    until the plane route is reached — at which point the failure propagates.
    Re-invocation is donation-guarded (a deleted input buffer refuses the
    descent), and fault-injection hooks labeled ``stream:<rung>`` fire at
    build and execute time (``STENCIL_FAULT_PLAN``).  The current plan is
    exposed as ``step._stream_plan``; the descent history as
    ``step._resilience.descents``.
    """
    if max_depth is not None:
        import operator

        if isinstance(max_depth, bool):  # True would cap depth at 1 silently
            raise ValueError(f"stream_depth must be an integer, got {max_depth!r}")
        try:
            max_depth = operator.index(max_depth)  # int, np.int64, ...
        except TypeError:
            raise ValueError(
                f"stream_depth must be an integer >= 1, got {max_depth!r}"
            ) from None
        if max_depth < 1:
            raise ValueError(
                f"stream_depth must be >= 1, got {max_depth} (a 0/negative "
                "cap would silently disable temporal blocking)"
            )
    from stencil_tpu.resilience.ladder import DegradationLadder, Rung

    plan = plan_stream(dd, x_radius, path, separable, max_m=max_depth)

    def rung_for(p):
        # build() resolves _build_stream_step through module globals at call
        # time, so tests may monkeypatch it
        return Rung(
            name=f"{p['route']}[m={p['m']}]",
            build=lambda: _build_stream_step(dd, kernel, x_radius, p, interpret, donate),
            state={"plan": p},
        )

    def lower(rung, cls, exc):
        plan_now = rung.state["plan"]
        if plan_now["route"] not in ("wavefront", "wrap") or plan_now["m"] <= 1:
            return None  # plane route is the bottom rung — propagate
        from stencil_tpu.utils.logging import log_warn

        new_max = plan_now["m"] - 1
        log_warn(
            f"{plan_now['route']} depth m={plan_now['m']} exceeded the "
            f"compiler's capability ({cls.value}) at runtime; stepping down to "
            f"m<={new_max} (the VMEM model under-estimates on this "
            "toolchain — consider recalibrating _VMEM_STACK_MARGIN / "
            "STENCIL_VMEM_LIMIT_BYTES)"
        )
        return rung_for(plan_stream(dd, x_radius, path, separable, max_m=new_max))

    ladder = DegradationLadder(rung_for(plan), lower=lower, label="stream")

    def step(curr, steps: int = 1):
        out = ladder.step(curr, steps)
        step._stream_plan = ladder.rung.state["plan"]
        return out

    step._marks_shell_stale = True
    # the eager build may already have descended (compile-phase rejection),
    # so expose the LADDER's plan, not the initial one
    step._stream_plan = ladder.rung.state["plan"]
    step._resilience = ladder
    step._resilience_label = "stream"
    return step
