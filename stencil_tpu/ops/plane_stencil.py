"""Generic plane-streaming 6-neighbor-mean kernel (arbitrary shell widths).

Same ring-buffer structure as ops/jacobi_pallas.py (one HBM read + one write
per x-plane) generalized to a shell of any per-axis width: compute planes
``[lo.x, X - hi.x)`` with the in-plane window ``[lo.y, Y - hi.y) x
[lo.z, Z - hi.z)``; every other cell (the shell) passes through unchanged.
Used by the Astaroth proxy (radius-3 shell, distance-1 reads —
astaroth_sim.cu:65-83 via a 3-wide halo it exchanges but does not read, like
the real Astaroth's communication volume model).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from stencil_tpu.core.dim3 import Dim3


def mean6_shell_wavefront_step(
    raw: jax.Array,  # (X+2s, Y+2s, Z+2s), uniform s-wide FILLED shell
    m: int,  # levels to advance, <= the shell width s
    shell_width: int,
    interpret: bool = False,
    compute_unit: str = "vpu",  # "mxu" = one banded in-plane contraction
    # per axis on the matrix unit (ops/jacobi_pallas.band_matrix); ≤1
    # ulp/level vs the "vpu" roll+add chain; "mxu_band" = its blocked
    # (2r+1)-band form (ops/jacobi_pallas.band_wide_tile)
    f32_accumulate: bool = False,  # bf16-storage variant: upcast at load,
    # f32 level ring + arithmetic, one downcast at the final store
    mxu_input: str = "f32",  # MXU operand precision (jacobi_wrap_step)
) -> jax.Array:
    """``m`` mean-of-6 levels in ONE pass over an s-shell-carrying shard —
    the Astaroth proxy's temporal wavefront (opt-in ``schedule="wavefront"``).

    The proxy exchanges a radius-3 shell but reads distance 1
    (astaroth_sim.cu:65-83), so the shell ALREADY holds enough boundary data
    for 3 levels of the stencil: validity shrinks one cell per level exactly
    as in ``jacobi_shell_wavefront_step`` (see its docstring for the
    contamination argument), and each HBM plane is read and written once per
    ``m`` iterations instead of once per iteration.  Shell cells land
    garbage/stale; the caller re-exchanges before the next pass and marks
    the shell stale for readback.  Summation order matches
    ``mean6_plane_step`` (x-1, x+1, y-1, y+1, z-1, z+1)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from stencil_tpu.ops.jacobi_pallas import (
        _check_compute_unit,
        _make_level_sum,
        _make_roll,
        _tpu_compiler_params,
        band_operands,
        make_plane_nbr_sum,
        plane_band_unit,
        unit_uses_mxu,
    )

    Xr, Yr, Zr = raw.shape
    assert 1 <= m <= shell_width and 2 * shell_width < min(Xr, Yr, Zr), (
        m, shell_width, raw.shape,
    )
    roll = _make_roll(interpret)
    acc_dtype = jnp.float32 if f32_accumulate else raw.dtype
    _check_compute_unit(compute_unit, acc_dtype)
    mxu = unit_uses_mxu(compute_unit)
    if mxu:
        compute_unit = plane_band_unit(compute_unit, Yr, Zr, where="mean6-wavefront")
    nbr_sum = (
        make_plane_nbr_sum(Yr, Zr, compute_unit, mxu_input) if mxu else None
    )
    level_sum = _make_level_sum(roll, compute_unit, nbr_sum)

    def kernel(in_ref, *rest):
        if mxu:
            by_ref, bz_ref, out_ref, ring = rest
            by, bz = by_ref[...], bz_ref[...]
        else:
            out_ref, ring = rest
            by = bz = None
        # ring[s] holds the two most recent level-s planes (level 0 = input)
        i = pl.program_id(0)
        vals = in_ref[0].astype(acc_dtype)  # level-0 raw plane i
        for s in range(1, m + 1):
            prev = ring[s - 1, i % 2]  # level-(s-1) plane i-s-1
            cent = ring[s - 1, (i + 1) % 2]  # level-(s-1) plane i-s
            ring[s - 1, i % 2] = vals  # push plane i-s+1 (after prev read)
            val = level_sum(prev, vals, cent, by, bz) / 6.0
            vals = val.astype(acc_dtype)
        # level-m plane i-m; valid for the interior (the one f32_accumulate
        # downcast)
        out_ref[0] = vals.astype(raw.dtype)

    in_specs = [pl.BlockSpec((1, Yr, Zr), lambda i: (i, 0, 0))]
    args = [raw]
    if mxu:
        b_args, b_specs = band_operands(Yr, Zr, compute_unit, mxu_input)
        in_specs += b_specs
        args += b_args
    return pl.pallas_call(
        kernel,
        grid=(Xr,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Yr, Zr), lambda i: (jnp.maximum(i - m, 0), 0, 0)),
        out_shape=jax.ShapeDtypeStruct((Xr, Yr, Zr), raw.dtype),
        # write of plane i-m trails the fetch of plane i+1: in-place safe
        input_output_aliases={0: 0},
        scratch_shapes=[pltpu.VMEM((m, 2, Yr, Zr), acc_dtype)],
        interpret=interpret,
        **_tpu_compiler_params(interpret),
    )(*args)


def mean6_plane_step(
    block: jax.Array, lo: Dim3, hi: Dim3, interpret: bool = False,
    compute_unit: str = "vpu", f32_accumulate: bool = False,
    mxu_input: str = "f32",
) -> jax.Array:
    """One mean-of-6-face-neighbors iteration over a shell-carrying block.

    ``compute_unit="mxu"`` computes the in-plane neighbor pair sums as one
    banded contraction per axis (``band_matrix``; ``"mxu_band"`` runs the
    blocked form); the interior window ``[y0, y1) x [z0, z1)`` sits at
    least one cell inside the plane, so the circulant wrap rows/columns
    never enter the sliced result and the contraction is exactly the
    shifted-slice sum up to summation order (≤1 ulp).  ``f32_accumulate``
    is the bf16-storage variant: the mean is computed at f32 and rounded
    once at the interior store (pass-through shell planes keep their
    storage bytes untouched)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from stencil_tpu.ops.jacobi_pallas import (
        _check_compute_unit,
        _tpu_compiler_params,
        band_operands,
        make_plane_nbr_sum,
        plane_band_unit,
        unit_uses_mxu,
    )

    X, Y, Z = block.shape
    # every side needs >= 1 shell cell: the distance-1 reads and the
    # plane-replay at the grid edges assume neighbors exist in-allocation
    assert lo.all_ge(1) and hi.all_ge(1), (lo, hi)
    y0, y1 = lo.y, Y - hi.y
    z0, z1 = lo.z, Z - hi.z
    acc_dtype = jnp.float32 if f32_accumulate else block.dtype
    _check_compute_unit(compute_unit, acc_dtype)
    mxu = unit_uses_mxu(compute_unit)
    if mxu:
        compute_unit = plane_band_unit(compute_unit, Y, Z, where="mean6-plane")
    nbr_sum = (
        make_plane_nbr_sum(Y, Z, compute_unit, mxu_input) if mxu else None
    )
    up = (lambda v: v.astype(jnp.float32)) if f32_accumulate else (lambda v: v)

    def kernel(in_ref, *rest):
        if mxu:
            by_ref, bz_ref, out_ref, ring = rest
        else:
            out_ref, ring = rest
        i = pl.program_id(0)
        cur = in_ref[0]

        @pl.when(i == 0)
        def _():
            out_ref[0] = cur  # first plane passes through

        @pl.when(jnp.logical_and(i >= 1, i <= X))
        def _():
            cent = ring[(i + 1) % 2]  # plane i-1

            in_window = jnp.logical_and(i - 1 >= lo.x, i - 1 <= X - hi.x - 1)

            @pl.when(in_window)
            def _():
                prev = ring[i % 2]  # plane i-2
                if mxu:
                    c = up(cent)
                    nbr = nbr_sum(c, by_ref[...], bz_ref[...])
                    mean = (
                        up(prev[y0:y1, z0:z1])
                        + up(cur[y0:y1, z0:z1])
                        + nbr[y0:y1, z0:z1]
                    ) / 6.0
                else:
                    mean = (
                        up(prev[y0:y1, z0:z1])
                        + up(cur[y0:y1, z0:z1])
                        + up(cent[y0 - 1 : y1 - 1, z0:z1])
                        + up(cent[y0 + 1 : y1 + 1, z0:z1])
                        + up(cent[y0:y1, z0 - 1 : z1 - 1])
                        + up(cent[y0:y1, z0 + 1 : z1 + 1])
                    ) / 6.0
                out_ref[0] = cent  # keep the y/z shell
                out_ref[0, y0:y1, z0:z1] = mean.astype(cur.dtype)

            @pl.when(jnp.logical_not(in_window))
            def _():
                out_ref[0] = cent  # shell plane passes through

        @pl.when(i <= X - 1)
        def _():
            ring[i % 2] = cur

    in_specs = [pl.BlockSpec((1, Y, Z), lambda i: (jnp.minimum(i, X - 1), 0, 0))]
    args = [block]
    if mxu:
        b_args, b_specs = band_operands(Y, Z, compute_unit, mxu_input)
        in_specs += b_specs
        args += b_args
    return pl.pallas_call(
        kernel,
        grid=(X + 1,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Y, Z), lambda i: (jnp.clip(i - 1, 0, X - 1), 0, 0)),
        out_shape=jax.ShapeDtypeStruct((X, Y, Z), block.dtype),
        scratch_shapes=[pltpu.VMEM((2, Y, Z), block.dtype)],
        interpret=interpret,
        **_tpu_compiler_params(interpret),
    )(*args)
