"""Generic plane-streaming 6-neighbor-mean kernel (arbitrary shell widths).

Same ring-buffer structure as ops/jacobi_pallas.py (one HBM read + one write
per x-plane) generalized to a shell of any per-axis width: compute planes
``[lo.x, X - hi.x)`` with the in-plane window ``[lo.y, Y - hi.y) x
[lo.z, Z - hi.z)``; every other cell (the shell) passes through unchanged.
Used by the Astaroth proxy (radius-3 shell, distance-1 reads —
astaroth_sim.cu:65-83 via a 3-wide halo it exchanges but does not read, like
the real Astaroth's communication volume model).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from stencil_tpu.core.dim3 import Dim3


def mean6_plane_step(
    block: jax.Array, lo: Dim3, hi: Dim3, interpret: bool = False
) -> jax.Array:
    """One mean-of-6-face-neighbors iteration over a shell-carrying block."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    X, Y, Z = block.shape
    # every side needs >= 1 shell cell: the distance-1 reads and the
    # plane-replay at the grid edges assume neighbors exist in-allocation
    assert lo.all_ge(1) and hi.all_ge(1), (lo, hi)
    y0, y1 = lo.y, Y - hi.y
    z0, z1 = lo.z, Z - hi.z

    def kernel(in_ref, out_ref, ring):
        i = pl.program_id(0)
        cur = in_ref[0]

        @pl.when(i == 0)
        def _():
            out_ref[0] = cur  # first plane passes through

        @pl.when(jnp.logical_and(i >= 1, i <= X))
        def _():
            cent = ring[(i + 1) % 2]  # plane i-1

            in_window = jnp.logical_and(i - 1 >= lo.x, i - 1 <= X - hi.x - 1)

            @pl.when(in_window)
            def _():
                prev = ring[i % 2]  # plane i-2
                mean = (
                    prev[y0:y1, z0:z1]
                    + cur[y0:y1, z0:z1]
                    + cent[y0 - 1 : y1 - 1, z0:z1]
                    + cent[y0 + 1 : y1 + 1, z0:z1]
                    + cent[y0:y1, z0 - 1 : z1 - 1]
                    + cent[y0:y1, z0 + 1 : z1 + 1]
                ) / 6.0
                out_ref[0] = cent  # keep the y/z shell
                out_ref[0, y0:y1, z0:z1] = mean.astype(cur.dtype)

            @pl.when(jnp.logical_not(in_window))
            def _():
                out_ref[0] = cent  # shell plane passes through

        @pl.when(i <= X - 1)
        def _():
            ring[i % 2] = cur

    return pl.pallas_call(
        kernel,
        grid=(X + 1,),
        in_specs=[pl.BlockSpec((1, Y, Z), lambda i: (jnp.minimum(i, X - 1), 0, 0))],
        out_specs=pl.BlockSpec((1, Y, Z), lambda i: (jnp.clip(i - 1, 0, X - 1), 0, 0)),
        out_shape=jax.ShapeDtypeStruct((X, Y, Z), block.dtype),
        scratch_shapes=[pltpu.VMEM((2, Y, Z), block.dtype)],
        interpret=interpret,
    )(block)
