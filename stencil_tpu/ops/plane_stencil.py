"""Generic plane-streaming 6-neighbor-mean kernel (arbitrary shell widths).

Same ring-buffer structure as ops/jacobi_pallas.py (one HBM read + one write
per x-plane) generalized to a shell of any per-axis width: compute planes
``[lo.x, X - hi.x)`` with the in-plane window ``[lo.y, Y - hi.y) x
[lo.z, Z - hi.z)``; every other cell (the shell) passes through unchanged.
Used by the Astaroth proxy (radius-3 shell, distance-1 reads —
astaroth_sim.cu:65-83 via a 3-wide halo it exchanges but does not read, like
the real Astaroth's communication volume model).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from stencil_tpu.core.dim3 import Dim3


def mean6_shell_wavefront_step(
    raw: jax.Array,  # (X+2s, Y+2s, Z+2s), uniform s-wide FILLED shell
    m: int,  # levels to advance, <= the shell width s
    shell_width: int,
    interpret: bool = False,
) -> jax.Array:
    """``m`` mean-of-6 levels in ONE pass over an s-shell-carrying shard —
    the Astaroth proxy's temporal wavefront (opt-in ``schedule="wavefront"``).

    The proxy exchanges a radius-3 shell but reads distance 1
    (astaroth_sim.cu:65-83), so the shell ALREADY holds enough boundary data
    for 3 levels of the stencil: validity shrinks one cell per level exactly
    as in ``jacobi_shell_wavefront_step`` (see its docstring for the
    contamination argument), and each HBM plane is read and written once per
    ``m`` iterations instead of once per iteration.  Shell cells land
    garbage/stale; the caller re-exchanges before the next pass and marks
    the shell stale for readback.  Summation order matches
    ``mean6_plane_step`` (x-1, x+1, y-1, y+1, z-1, z+1)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from stencil_tpu.ops.jacobi_pallas import _make_roll, _tpu_compiler_params

    Xr, Yr, Zr = raw.shape
    assert 1 <= m <= shell_width and 2 * shell_width < min(Xr, Yr, Zr), (
        m, shell_width, raw.shape,
    )
    roll = _make_roll(interpret)

    def kernel(in_ref, out_ref, ring):
        # ring[s] holds the two most recent level-s planes (level 0 = input)
        i = pl.program_id(0)
        vals = in_ref[0]  # level-0 raw plane i
        for s in range(1, m + 1):
            prev = ring[s - 1, i % 2]  # level-(s-1) plane i-s-1
            cent = ring[s - 1, (i + 1) % 2]  # level-(s-1) plane i-s
            ring[s - 1, i % 2] = vals  # push plane i-s+1 (after prev read)
            val = (
                prev
                + vals
                + roll(cent, 1, 0)
                + roll(cent, -1, 0)
                + roll(cent, 1, 1)
                + roll(cent, -1, 1)
            ) / 6.0
            vals = val.astype(vals.dtype)
        out_ref[0] = vals  # level-m plane i-m; valid for the interior

    return pl.pallas_call(
        kernel,
        grid=(Xr,),
        in_specs=[pl.BlockSpec((1, Yr, Zr), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((1, Yr, Zr), lambda i: (jnp.maximum(i - m, 0), 0, 0)),
        out_shape=jax.ShapeDtypeStruct((Xr, Yr, Zr), raw.dtype),
        # write of plane i-m trails the fetch of plane i+1: in-place safe
        input_output_aliases={0: 0},
        scratch_shapes=[pltpu.VMEM((m, 2, Yr, Zr), raw.dtype)],
        interpret=interpret,
        **_tpu_compiler_params(interpret),
    )(raw)


def mean6_plane_step(
    block: jax.Array, lo: Dim3, hi: Dim3, interpret: bool = False
) -> jax.Array:
    """One mean-of-6-face-neighbors iteration over a shell-carrying block."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from stencil_tpu.ops.jacobi_pallas import _tpu_compiler_params

    X, Y, Z = block.shape
    # every side needs >= 1 shell cell: the distance-1 reads and the
    # plane-replay at the grid edges assume neighbors exist in-allocation
    assert lo.all_ge(1) and hi.all_ge(1), (lo, hi)
    y0, y1 = lo.y, Y - hi.y
    z0, z1 = lo.z, Z - hi.z

    def kernel(in_ref, out_ref, ring):
        i = pl.program_id(0)
        cur = in_ref[0]

        @pl.when(i == 0)
        def _():
            out_ref[0] = cur  # first plane passes through

        @pl.when(jnp.logical_and(i >= 1, i <= X))
        def _():
            cent = ring[(i + 1) % 2]  # plane i-1

            in_window = jnp.logical_and(i - 1 >= lo.x, i - 1 <= X - hi.x - 1)

            @pl.when(in_window)
            def _():
                prev = ring[i % 2]  # plane i-2
                mean = (
                    prev[y0:y1, z0:z1]
                    + cur[y0:y1, z0:z1]
                    + cent[y0 - 1 : y1 - 1, z0:z1]
                    + cent[y0 + 1 : y1 + 1, z0:z1]
                    + cent[y0:y1, z0 - 1 : z1 - 1]
                    + cent[y0:y1, z0 + 1 : z1 + 1]
                ) / 6.0
                out_ref[0] = cent  # keep the y/z shell
                out_ref[0, y0:y1, z0:z1] = mean.astype(cur.dtype)

            @pl.when(jnp.logical_not(in_window))
            def _():
                out_ref[0] = cent  # shell plane passes through

        @pl.when(i <= X - 1)
        def _():
            ring[i % 2] = cur

    return pl.pallas_call(
        kernel,
        grid=(X + 1,),
        in_specs=[pl.BlockSpec((1, Y, Z), lambda i: (jnp.minimum(i, X - 1), 0, 0))],
        out_specs=pl.BlockSpec((1, Y, Z), lambda i: (jnp.clip(i - 1, 0, X - 1), 0, 0)),
        out_shape=jax.ShapeDtypeStruct((X, Y, Z), block.dtype),
        scratch_shapes=[pltpu.VMEM((2, Y, Z), block.dtype)],
        interpret=interpret,
        **_tpu_compiler_params(interpret),
    )(block)
