"""Device-side ops: halo exchange collectives, pack/unpack, stencil helpers."""

from stencil_tpu.ops.exchange import halo_exchange_shard, make_exchange_fn

__all__ = ["halo_exchange_shard", "make_exchange_fn"]
