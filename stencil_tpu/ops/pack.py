"""Halo pack/unpack — fused per-neighbor message buffers.

Parity target: ``DevicePacker``/``DeviceUnpacker`` (reference
include/stencil/packer.cuh:71-366) and the pack/unpack kernels
(pack_kernel.cuh:5-46, copy.cuh:26-83).  The reference fuses all quantities ×
all directions of one neighbor into ONE contiguous aligned device buffer:
for each message (sorted by direction), for each quantity, the offset is
aligned to the element size and the ``halo_extent(-dir)`` region is appended
(packer.cuh:146-160) — the ``-dir`` convention: the *receiver's* halo width
rules the message size (packer.cuh:91-93).

TPU design: the production exchange (ops/exchange.py) has two message
shapes.  The ``direct`` route sends slabs as sliced — XLA fuses the slicing
into the ppermute, playing the role of the pack kernel.  The packed routes
(``zpack_*`` / ``yzpack_*``, tuner axes since the exchange-route PRs) send
the thin shells through THIS module's pack pipelines instead, one twin per
shell ORIENTATION:

* **z shell** (``pack_zshell_*`` / ``unpack_zshell_*``): on the
  (8,128)-tiled layout a thin-z sliver read/write is ~64×-amplified
  (PERF_NOTES "Thin z-region access"), so the shell leaves HBM as whole
  x-plane DMAs, is cut and transposed in VMEM, and travels LANE-major as
  ``(2m, Y, Xpad)`` — the thin ``2m`` extent becomes the untiled leading
  dim, X (whole, well-shaped, lane-padded to a 128 multiple with dead
  columns the unpack never reads) becomes the lane dim.
* **y shell** (``pack_yshell_*`` / ``unpack_yshell_*``): the y window is a
  SUBLANE sliver — ``2m`` rows of the 8-row (f32) sublane granule, so a
  radius-r y exchange through the big array is ~8/(2r)-amplified
  (PERF_NOTES "Thin y-region access").  The same move, one axis over: the
  shell leaves HBM as whole x-planes, the row window is cut in VMEM, and
  the message travels SUBLANE-major as ``(2m, X, Z)`` — the thin extent is
  again the untiled leading dim, X becomes the (padding-tolerant) sublane
  dim, and Z stays the lane dim untouched, so no explicit pad is needed
  (ragged sublane extents are nearly free, PERF_NOTES "Ragged lane
  extents").

Both orientations keep the invariant that the BIG array is only ever read
(and, on the pallas twins, written) as whole x-planes; the thin cut exists
only in VMEM and in the small message buffer.
This module also holds (a) parity of the reference's buffer-layout math
(``PackPlan``, byte-exact with the reference incl. the 264-byte multi-dtype
case, test_cuda_packer.cu:74-92) and (b) the ``bench-pack`` kernel
benchmark.  Two backends:

* ``xla`` — gather/scatter via slice + bitcast + concat; XLA fuses this into
  a handful of copies (the analog of the reference's CUDA-Graph replay being
  jit's compilation cache, packer.cuh:168-187).
* ``pallas`` — per-plane pipelined kernels: the pallas grid streams whole
  x-planes HBM -> VMEM (lane-tile-aligned movement) and the VPU cuts or
  patches the unaligned halo window in VMEM.

Slab-internal element order is C-order on (x, y, z) arrays (z fastest); the
reference's flatten is x fastest (pack_kernel.cuh:16-40).  Offsets and sizes
are identical; only the within-slab byte order differs (both sides of our
exchange use the same order, so the invariant is preserved).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from stencil_tpu.core.dim3 import Dim3
from stencil_tpu.core.geometry import LocalSpec


def next_align_of(x: int, align: int) -> int:
    """Round ``x`` up to a multiple of ``align`` (reference align.cuh:7)."""
    return (x + align - 1) // align * align


@dataclasses.dataclass(frozen=True)
class PackSlot:
    """One (message, quantity) slice of the packed buffer."""

    direction: Dim3
    quantity: int
    offset: int  # bytes from buffer start (aligned to itemsize)
    pos: Dim3  # allocation-relative source position (interior side)
    unpack_pos: Dim3  # allocation-relative destination position (halo side)
    extent: Dim3
    itemsize: int

    @property
    def nbytes(self) -> int:
        return self.extent.flatten() * self.itemsize


@dataclasses.dataclass(frozen=True)
class PackPlan:
    """Buffer layout for one neighbor's fused message
    (packer.cuh:136-178 prepare)."""

    slots: Tuple[PackSlot, ...]
    size: int  # total bytes

    @staticmethod
    def make(spec: LocalSpec, directions: Sequence, itemsizes: Sequence[int]) -> "PackPlan":
        dirs = sorted((Dim3.of(d) for d in directions))  # sorted by dir (packer.cuh:140)
        slots: List[PackSlot] = []
        size = 0
        for d in dirs:
            for qi, isz in enumerate(itemsizes):
                size = next_align_of(size, isz)
                ext = spec.halo_extent(-d)  # receiver's -d halo width rules
                slots.append(
                    PackSlot(
                        direction=d,
                        quantity=qi,
                        offset=size,
                        pos=spec.halo_pos(d, halo=False),
                        unpack_pos=spec.halo_pos(-d, halo=True),
                        extent=ext,
                        itemsize=isz,
                    )
                )
                size += ext.flatten() * isz
        if size == 0:
            raise ValueError("zero-size packer was prepared")  # packer.cuh:162
        return PackPlan(tuple(slots), size)


def _slab(block: jax.Array, pos: Dim3, ext: Dim3) -> jax.Array:
    return block[
        pos.x : pos.x + ext.x,
        pos.y : pos.y + ext.y,
        pos.z : pos.z + ext.z,
    ]


def _to_bytes(slab: jax.Array) -> jax.Array:
    """Flatten a typed slab to its uint8 representation."""
    if slab.dtype == jnp.uint8:
        return slab.ravel()
    return lax.bitcast_convert_type(slab, jnp.uint8).ravel()


def _from_bytes(buf: jax.Array, ext: Dim3, dtype) -> jax.Array:
    """Inverse of ``_to_bytes`` for one slab's bytes."""
    dtype = jnp.dtype(dtype)
    if dtype == jnp.uint8:
        return buf.reshape(tuple(ext))
    shaped = buf.reshape(tuple(ext) + (dtype.itemsize,))
    return lax.bitcast_convert_type(shaped, dtype)


def make_pack_fn(spec: LocalSpec, directions: Sequence, dtypes: Sequence):
    """Jitted ``pack(blocks) -> uint8 buffer`` over one subdomain's raw blocks
    (one per quantity, each of shape ``spec.raw_size()``)."""
    dtypes = [jnp.dtype(t) for t in dtypes]
    plan = PackPlan.make(spec, directions, [t.itemsize for t in dtypes])

    @jax.jit
    def pack(blocks: Sequence[jax.Array]) -> jax.Array:
        parts = []
        cursor = 0
        for slot in plan.slots:
            if slot.offset != cursor:  # alignment gap
                parts.append(jnp.zeros((slot.offset - cursor,), jnp.uint8))
            parts.append(_to_bytes(_slab(blocks[slot.quantity], slot.pos, slot.extent)))
            cursor = slot.offset + slot.nbytes
        return jnp.concatenate(parts)

    return pack, plan


def make_unpack_fn(spec: LocalSpec, directions: Sequence, dtypes: Sequence):
    """Jitted ``unpack(buffer, blocks) -> blocks`` writing each slot into the
    halo shell (copy.cuh:26-64 semantics)."""
    dtypes = [jnp.dtype(t) for t in dtypes]
    plan = PackPlan.make(spec, directions, [t.itemsize for t in dtypes])

    @partial(jax.jit, donate_argnums=1)
    def unpack(buf: jax.Array, blocks: Sequence[jax.Array]) -> List[jax.Array]:
        out = list(blocks)
        for slot in plan.slots:
            chunk = buf[slot.offset : slot.offset + slot.nbytes]
            slab = _from_bytes(chunk, slot.extent, dtypes[slot.quantity])
            p, e = slot.unpack_pos, slot.extent
            out[slot.quantity] = out[slot.quantity].at[
                p.x : p.x + e.x, p.y : p.y + e.y, p.z : p.z + e.z
            ].set(slab)
        return out

    return unpack, plan


# --- Pallas backend ----------------------------------------------------------


def pallas_pack_slab(block: jax.Array, pos: Dim3, ext: Dim3, interpret: bool = False):
    """Pack one halo slab with an explicit DMA kernel: the block stays in
    HBM/ANY; each grid step DMAs one full x-plane into VMEM, then the VPU
    slices out the (possibly tiling-unaligned) halo window (pallas_guide.md
    "Async DMA (Local Copies)").  HBM DMAs must be lane-tile aligned, so the
    plane is copied whole and the unaligned cut happens in VMEM.  This is the
    hand-written analog of the reference's grid-stride ``grid_pack``
    (pack_kernel.cuh:16-40)."""
    from jax.experimental import pallas as pl

    raw_y, raw_z = block.shape[1], block.shape[2]

    def kernel(src_ref, out_ref):
        out_ref[0] = src_ref[0, pos.y : pos.y + ext.y, pos.z : pos.z + ext.z]

    return pl.pallas_call(
        kernel,
        grid=(ext.x,),
        # one full x-plane per step: HBM->VMEM movement must be lane-tile
        # aligned, so the pipeline streams whole planes and the VPU cuts the
        # (possibly unaligned) halo window in VMEM
        in_specs=[pl.BlockSpec((1, raw_y, raw_z), lambda i: (pos.x + i, 0, 0))],
        out_specs=pl.BlockSpec((1, ext.y, ext.z), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(tuple(ext), block.dtype),
        interpret=interpret,
    )(block)


def pallas_unpack_slab(
    block: jax.Array, slab: jax.Array, pos: Dim3, ext: Dim3, interpret: bool = False
):
    """Scatter a packed slab back into the halo shell at ``pos`` with per-plane
    DMA, updating ``block`` in place (input_output_aliases — the analog of
    unpacking into the existing allocation, copy.cuh:64-83)."""
    from jax.experimental import pallas as pl

    raw_y, raw_z = block.shape[1], block.shape[2]

    def kernel(blk_ref, slab_ref, out_ref):
        # read-modify-write one full x-plane: copy it through, then patch the
        # halo window (unwritten planes keep the aliased input's data)
        out_ref[0] = blk_ref[0]
        out_ref[0, pos.y : pos.y + ext.y, pos.z : pos.z + ext.z] = slab_ref[0]

    plane = pl.BlockSpec((1, raw_y, raw_z), lambda i: (pos.x + i, 0, 0))
    return pl.pallas_call(
        kernel,
        grid=(ext.x,),
        in_specs=[plane, pl.BlockSpec((1, ext.y, ext.z), lambda i: (i, 0, 0))],
        out_specs=plane,
        out_shape=jax.ShapeDtypeStruct(block.shape, block.dtype),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(block, slab)


def make_pack_fn_pallas(spec: LocalSpec, directions: Sequence, dtype, interpret: bool = False):
    """Pallas-backed ``pack(block) -> list of slabs`` for one quantity.  Each
    direction's slab is produced by its own DMA kernel; the caller may ravel
    and concatenate for a flat buffer (layout per ``PackPlan``)."""
    dtype = jnp.dtype(dtype)
    plan = PackPlan.make(spec, directions, [dtype.itemsize])

    @jax.jit
    def pack(block: jax.Array) -> List[jax.Array]:
        return [
            pallas_pack_slab(block, slot.pos, slot.extent, interpret=interpret)
            for slot in plan.slots
        ]

    return pack, plan


def make_unpack_fn_pallas(spec: LocalSpec, directions: Sequence, dtype, interpret: bool = False):
    """Pallas-backed ``unpack(block, slabs) -> block`` (single quantity)."""
    dtype = jnp.dtype(dtype)
    plan = PackPlan.make(spec, directions, [dtype.itemsize])

    @jax.jit
    def unpack(block: jax.Array, slabs: Sequence[jax.Array]) -> jax.Array:
        for slot, slab in zip(plan.slots, slabs):
            block = pallas_unpack_slab(
                block, slab, slot.unpack_pos, slot.extent, interpret=interpret
            )
        return block

    return unpack, plan


# --- Production z-shell pack route -------------------------------------------
#
# The exchange's packed z route (ops/exchange.py ``zpack_*``): the z shell of
# a (X, Y, Z) shard travels as a lane-major ``(depth, Y, Xpad)`` buffer.
# Rationale (PERF_NOTES "Thin z-region access" / "Block SHAPE orientation"):
# a (X, Y, depth) z-sliver has ``depth`` lanes — lane-padded to 128, every
# read/write of it through the big array costs a whole tile-column pass
# (~64× amplification at depth 2).  z-major, the lane dim is X (whole, well
# shaped, padded up to a 128 multiple with dead columns the unpack never
# reads), and the thin ``depth`` extent sublane-pads to at most 8.


def lane_pad(n: int) -> int:
    """Round a lane extent up to the (8,128) tiling's 128-lane multiple."""
    return next_align_of(n, 128)


def zshell_buffer_shape(block_shape, depth: int):
    """Shape of one z-shell message buffer for a ``(X, Y, Z)`` block."""
    X, Y = block_shape[0], block_shape[1]
    return (depth, Y, lane_pad(X))


def pack_zshell_xla(block: jax.Array, z0: int, depth: int) -> jax.Array:
    """``block[:, :, z0:z0+depth]`` as the lane-major ``(depth, Y, Xpad)``
    message buffer, via plain XLA (slice + transpose + lane pad).  XLA is
    free to fuse the reshaping into the ppermute operand — a measurably
    different message shape from ``direct``, hence its own tuner candidate."""
    X = block.shape[0]
    buf = jnp.transpose(block[:, :, z0 : z0 + depth], (2, 1, 0))
    pad = lane_pad(X) - X
    if pad:
        buf = jnp.pad(buf, ((0, 0), (0, 0), (0, pad)))
    return buf


def zshell_to_slab(buf: jax.Array, x: int) -> jax.Array:
    """Inverse of the pack transpose: the received ``(depth, Y, Xpad)``
    buffer as an ``(x, Y, depth)`` slab (dead pad columns dropped) — the
    shape the exchange's existing halo-write path (blend kernel or set)
    consumes.  Only the small message buffer is read thin-z here, never the
    big array."""
    return jnp.transpose(buf[:, :, :x], (2, 1, 0))


def pack_zshell_pallas(
    block: jax.Array, z0: int, depth: int, interpret: bool = False
) -> jax.Array:
    """Pallas z-shell pack: grid-stream whole x-planes HBM -> VMEM (lane-
    tile-aligned movement), cut the ``[z0, z0+depth)`` window and transpose
    it z-major on the VPU (small (Y, depth) <-> (depth, Y) in-kernel
    transposes are supported — PERF_NOTES "Mosaic limits"), land each
    plane's column in the ``(depth, Y, Xpad)`` buffer.  Pad columns past X
    are never visited (their contents are dead; the unpack never reads
    them)."""
    from jax.experimental import pallas as pl

    X, Y, Z = block.shape

    def kernel(src_ref, out_ref):
        out_ref[:, :, 0] = src_ref[0, :, z0 : z0 + depth].T

    return pl.pallas_call(
        kernel,
        grid=(X,),
        in_specs=[pl.BlockSpec((1, Y, Z), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((depth, Y, 1), lambda i: (0, 0, i)),
        out_shape=jax.ShapeDtypeStruct(zshell_buffer_shape(block.shape, depth), block.dtype),
        interpret=interpret,
    )(block)


def unpack_zshell_pallas(
    block: jax.Array, buf: jax.Array, z0: int, depth: int, interpret: bool = False
) -> jax.Array:
    """Blend a received ``(depth, Y, Xpad)`` z-shell buffer into
    ``block[:, :, z0:z0+depth]`` — aliased read-modify-write of whole
    x-planes (``input_output_aliases``), the transpose back happening in
    VMEM.  The big array is written plane-at-a-time in its native tiled
    layout; the thin-z patch exists only inside VMEM, so the ``sliver-dus``
    relayout trap is impossible by construction."""
    from jax.experimental import pallas as pl

    X, Y, Z = block.shape

    def kernel(blk_ref, buf_ref, out_ref):
        out_ref[0] = blk_ref[0]
        out_ref[0, :, z0 : z0 + depth] = buf_ref[:, :, 0].T

    plane = pl.BlockSpec((1, Y, Z), lambda i: (i, 0, 0))
    return pl.pallas_call(
        kernel,
        grid=(X,),
        in_specs=[plane, pl.BlockSpec((depth, Y, 1), lambda i: (0, 0, i))],
        out_specs=plane,
        out_shape=jax.ShapeDtypeStruct(block.shape, block.dtype),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(block, buf)


# --- Production y-shell pack route -------------------------------------------
#
# The exchange's packed y sweep (ops/exchange.py ``yzpack_*``): the y shell
# of a (X, Y, Z) shard travels as a sublane-major ``(depth, X, Z)`` buffer.
# Rationale (PERF_NOTES "Thin y-region access"): a (X, depth, Z) y-sliver
# has ``depth`` sublanes — sublane-padded to the 8-row (f32) granule, every
# read/write of it through the big array costs ~8/depth× its logical bytes.
# Sublane-major, the thin ``depth`` extent is the untiled leading dim, X
# becomes the sublane dim (whole; ragged sublane extents are nearly free),
# and Z stays the lane dim untouched — no explicit pad needed, unlike the
# z twin's lane_pad.


def yshell_buffer_shape(block_shape, depth: int):
    """Shape of one y-shell message buffer for a ``(X, Y, Z)`` block."""
    X, Z = block_shape[0], block_shape[2]
    return (depth, X, Z)


def pack_yshell_xla(block: jax.Array, y0: int, depth: int) -> jax.Array:
    """``block[:, y0:y0+depth, :]`` as the sublane-major ``(depth, X, Z)``
    message buffer, via plain XLA (slice + transpose).  XLA is free to fuse
    the reshaping into the ppermute operand — the y twin of
    ``pack_zshell_xla``."""
    return jnp.transpose(block[:, y0 : y0 + depth, :], (1, 0, 2))


def yshell_to_slab(buf: jax.Array) -> jax.Array:
    """Inverse of the pack transpose: the received ``(depth, X, Z)`` buffer
    as an ``(X, depth, Z)`` slab — the shape the exchange's existing
    halo-write path (blend kernel or set) consumes.  Only the small message
    buffer is read thin-y here, never the big array."""
    return jnp.transpose(buf, (1, 0, 2))


def pack_yshell_pallas(
    block: jax.Array, y0: int, depth: int, interpret: bool = False
) -> jax.Array:
    """Pallas y-shell pack: grid-stream whole x-planes HBM -> VMEM (lane-
    tile-aligned movement), cut the ``[y0, y0+depth)`` row window in VMEM,
    land each plane's rows in the ``(depth, X, Z)`` buffer.  No transpose is
    needed (the row cut keeps Z as the lane dim), so the kernel is a pure
    VMEM window copy — the y twin of ``pack_zshell_pallas``."""
    from jax.experimental import pallas as pl

    X, Y, Z = block.shape

    def kernel(src_ref, out_ref):
        out_ref[:, 0] = src_ref[0, y0 : y0 + depth, :]

    return pl.pallas_call(
        kernel,
        grid=(X,),
        in_specs=[pl.BlockSpec((1, Y, Z), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((depth, 1, Z), lambda i: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct(
            yshell_buffer_shape(block.shape, depth), block.dtype
        ),
        interpret=interpret,
    )(block)


def unpack_yshell_pallas(
    block: jax.Array, buf: jax.Array, y0: int, depth: int, interpret: bool = False
) -> jax.Array:
    """Blend a received ``(depth, X, Z)`` y-shell buffer into
    ``block[:, y0:y0+depth, :]`` — aliased read-modify-write of whole
    x-planes, the row patch happening in VMEM.  Like the z twin, the big
    array is written plane-at-a-time in its native tiled layout; the
    sublane sliver exists only inside VMEM."""
    from jax.experimental import pallas as pl

    X, Y, Z = block.shape

    def kernel(blk_ref, buf_ref, out_ref):
        out_ref[0] = blk_ref[0]
        out_ref[0, y0 : y0 + depth, :] = buf_ref[:, 0]

    plane = pl.BlockSpec((1, Y, Z), lambda i: (i, 0, 0))
    return pl.pallas_call(
        kernel,
        grid=(X,),
        in_specs=[plane, pl.BlockSpec((depth, 1, Z), lambda i: (0, i, 0))],
        out_specs=plane,
        out_shape=jax.ShapeDtypeStruct(block.shape, block.dtype),
        input_output_aliases={0: 0},
        interpret=interpret,
    )(block, buf)
