"""Pallas plane-streaming 7-point Jacobi kernel — the flagship fast path.

XLA compiles the 6-shifted-slice Jacobi update to ~6 HBM reads of the block
per iteration (each shifted operand is re-read; no stencil reuse), measured at
~5-7.5 Gcells/s on v5e for 512^3 — far below HBM bandwidth.  This kernel
streams x-planes through VMEM with a 2-plane ring buffer so every plane is
read from HBM ONCE and written ONCE (~8 B/cell), the classic stencil
optimization (reference analog: the fused stencil kernels of jacobi3d.cu:
65-108, which get the same effect from the GPU cache hierarchy).

Grid: ``X + 1`` sequential steps over the raw block's x-planes.  At step i the
pipeline delivers input plane ``min(i, X-1)``; VMEM scratch holds the two
previous planes; step i >= 2 computes output plane ``i-1`` from planes
``i-2, i-1, i``.  Steps 0 and X pass the x-halo planes through unchanged, and
each computed plane keeps its y/z halo ring (the exchange owns halo cells).

Semantics match ``models.jacobi.Jacobi3D._kernel`` exactly: mean of 6 face
neighbors, hot/cold sphere forcing.  Sphere membership uses the integer
predicate ``d2 < (r+1)^2``, exactly equivalent to the reference's
truncated-float-sqrt test (jacobi3d.cu:31-33) for these magnitudes — see
models/jacobi.py.  The y/z part of ``d2`` (both spheres share the same y/z
center, jacobi3d.cu:44-63) is precomputed once per shard and parked in VMEM
via a constant-index block, so the per-plane forcing is two compares and two
selects.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from stencil_tpu.core.dim3 import Dim3

HOT_TEMP = 1.0
COLD_TEMP = 0.0

#: compute-unit axis for the streaming level kernels — a first-class tuner
#: candidate (tune/space.py; docs/tuning.md "Compute unit and storage
#: dtype"): ``vpu`` = the measured roll+add chain (the static cold-cache
#: fallback, bitwise-pinned by tier-1), ``mxu`` = the per-axis stencil
#: application as ONE banded contraction per axis on the matrix unit
#: against the dense ``(n, n)`` circulant — the wafer-scale stencil
#: mapping (PAPERS.md arxiv 2605.07954 / 2601.17754) aimed at the
#: measured VPU wall (PERF_NOTES "VPU wall": the k≈12-24 plateau is
#: roll+add-bound, not DMA), ``mxu_band`` = the same contraction TILED to
#: the band's nonzeros (blocked ``(2r+1)``-band matmul: each output block
#: contracts against only its ≤3 neighbor input blocks via small shifted
#: dense tiles, ``band_wide_tile``), cutting the per-level FLOPs from
#: ``2·Y²·Z + 2·Y·Z²`` to ``6·g·Y·Z`` per axis — the mechanism step the
#: "VPU wall" break-even model asks for.
COMPUTE_UNITS = ("vpu", "mxu", "mxu_band")

#: the units that contract on the matrix unit — every ``== "mxu"`` gate in
#: the tree routes through :func:`unit_uses_mxu` so both variants ride the
#: same structural guards, VMEM/FLOP accounting hooks, and ladder rungs
MXU_UNITS = ("mxu", "mxu_band")

#: input-precision axis for the MXU contraction operands (independent of
#: the compute-unit variant and of STORAGE dtype): ``f32`` feeds the plane
#: and the band constants at f32, ``bf16`` narrows BOTH contraction
#: operands to bfloat16 — the 0/1 band constants are exact in bfloat16,
#: the plane pays one round-to-nearest per read — while
#: ``preferred_element_type=f32`` keeps the accumulator (the
#: ``accum-dtype`` contract still machine-checks every traced
#: ``dot_general``).  The MXU's bf16 ratio is ~2× its f32 ratio, which is
#: the doubling the "VPU wall" break-even model needs; the analytic error
#: bound is ``tests/ulp.mxu_bf16_input_atol``.
MXU_INPUTS = ("f32", "bf16")


def unit_uses_mxu(compute_unit: str) -> bool:
    """True for every compute-unit value that contracts on the matrix unit
    (dense or band-tiled) — the one predicate the rest of the tree keys
    structural gates, flop counters, and VMEM terms on."""
    return compute_unit in MXU_UNITS

#: storage-dtype axis for field buffers — ``native`` keeps the user dtype
#: end to end; ``bf16`` stores f32 fields as bfloat16 (HBM planes, VMEM
#: pipeline blocks, exchange messages all narrow to 2 B/cell) while the
#: level kernels accumulate at f32 (load → upcast → compute → downcast on
#: the final store; the ``f32_accumulate`` kernel contract).
STORAGE_DTYPES = ("native", "bf16")


def mxu_supported(compute_dtypes) -> bool:
    """Structural gate for the MXU contraction form: every field must
    COMPUTE at f32 — the banded ``dot_general`` accumulates at f32
    (``preferred_element_type``), so an f64 field would silently lose
    precision through the matrix unit (violating the ≤1-ulp-per-level
    contract) and integer/bool fields have no matmul form at all.  A bf16
    STORAGE field computes at f32 (``f32_accumulate``) and qualifies; a
    native-bf16 field does not (its vpu path computes at bf16 in interpret
    mode, so no cross-unit ulp contract could be pinned)."""
    return all(jnp.dtype(dt) == jnp.float32 for dt in compute_dtypes)


def bf16_supported(native_dtypes) -> bool:
    """Structural gate for bf16 storage: only f32 fields narrow — the
    downcast keeps the full f32 exponent range (losslessly-enough per the
    analytic bound: one round-to-nearest of ≤ 2^-9 relative per store).
    f64 would shed 45 mantissa bits (no analytic contract worth having),
    and integer/bool fields have no bf16 form."""
    return all(jnp.dtype(dt) == jnp.float32 for dt in native_dtypes)


def _resolve_axis_value(request, tuned, env_name: str, choices, static: str):
    """Shared precedence chain for the compute-unit / storage-dtype axes
    (mirrors the exchange-route and stream-overlap rules): an explicit
    request wins and never consults further; then the validated env knob;
    then the tuned config's field (garbage warns and falls through); then
    the static fallback.  Returns ``(value, source)`` pre-structural."""
    from stencil_tpu.utils.config import env_choice

    if request not in (None, "auto"):
        if request not in choices:
            raise ValueError(f"unknown value {request!r} (one of {choices})")
        return request, "explicit"
    env = env_choice(env_name, "auto", ("auto",) + tuple(choices))
    if env != "auto":
        return env, "env"
    if tuned is not None:
        if tuned in choices:
            return str(tuned), "tuned"
        from stencil_tpu.utils.logging import log_warn

        log_warn(
            f"tuned {env_name.lower()} value {tuned!r} is not one of "
            f"{choices}; using the static {static!r} fallback"
        )
    return static, "static"


def resolve_compute_unit(
    request, tuned, compute_dtypes, where: str = "kernel",
    engine_ok: bool = True,
    engine_why: str = "this engine has no pallas level kernel",
    emit: bool = True,
):
    """Resolve the compute-unit axis for one kernel build: precedence
    explicit > ``STENCIL_COMPUTE_UNIT`` > tuned > static ``vpu``, then the
    structural guard — an ``mxu`` the kernels cannot serve (non-f32 compute
    dtypes, or an engine with no pallas level kernel at all) degrades to
    ``vpu`` with a warning, never an error.  Every resolution is a
    ``kernel.compute_unit`` telemetry event (``emit=False`` for PROSPECTIVE
    resolutions — a planner peeking at the unit before the authoritative
    build-time resolve emits the one real event).  Returns ``(unit, source)``."""
    val, source = _resolve_axis_value(
        request, tuned, "STENCIL_COMPUTE_UNIT", COMPUTE_UNITS, "vpu"
    )
    if unit_uses_mxu(val) and not (engine_ok and mxu_supported(compute_dtypes)):
        from stencil_tpu.utils.logging import log_warn

        why = (
            engine_why
            if not engine_ok
            else f"fields compute at {[jnp.dtype(d).name for d in compute_dtypes]}, not f32"
        )
        log_warn(
            f"compute_unit={val} ({source}) cannot engage for {where} ({why}); "
            "degrading to vpu"
        )
        val, source = "vpu", source + "/degraded"
    if emit:
        from stencil_tpu import telemetry
        from stencil_tpu.telemetry import names as tm

        telemetry.emit_event(
            tm.EVENT_KERNEL_COMPUTE_UNIT, unit=val, source=source, where=where
        )
    return val, source


def resolve_storage_dtype(
    request, tuned, native_dtypes, where: str = "kernel",
    engine_ok: bool = True,
    engine_why: str = (
        "this engine accumulates at the storage dtype (no f32-accumulate "
        "kernel)"
    ),
):
    """Resolve the storage-dtype axis for one model build: precedence
    explicit > ``STENCIL_STORAGE_DTYPE`` > tuned > static ``native``, then
    the structural guard — ``bf16`` on non-f32 fields, or on an engine
    whose kernels would accumulate at bf16 instead of f32 (the XLA slice
    route), degrades to ``native`` with a warning.  Every resolution is a
    ``kernel.storage_dtype`` telemetry event.  Returns ``(sd, source)``."""
    val, source = _resolve_axis_value(
        request, tuned, "STENCIL_STORAGE_DTYPE", STORAGE_DTYPES, "native"
    )
    if val == "bf16" and not (engine_ok and bf16_supported(native_dtypes)):
        from stencil_tpu.utils.logging import log_warn

        why = (
            engine_why
            if not engine_ok
            else f"fields are {[jnp.dtype(d).name for d in native_dtypes]}, not f32"
        )
        log_warn(
            f"storage_dtype=bf16 ({source}) cannot engage for {where} ({why}); "
            "degrading to native"
        )
        val, source = "native", source + "/degraded"
    from stencil_tpu import telemetry
    from stencil_tpu.telemetry import names as tm

    telemetry.emit_event(
        tm.EVENT_KERNEL_STORAGE_DTYPE, storage=val, source=source, where=where
    )
    return val, source


def resolve_mxu_input(
    request, tuned, compute_unit: str, where: str = "kernel", emit: bool = True
):
    """Resolve the MXU input-precision axis for one kernel build: precedence
    explicit > ``STENCIL_MXU_INPUT`` > tuned > static ``f32``, then the
    structural guard — ``bf16`` inputs only exist under an engaged MXU unit
    (the vpu chain has no contraction to feed), so a vpu resolution pins
    ``f32``; the degrade warns only for explicit/env requests (a persisted
    tuned ``bf16`` consulted by a vpu build is routine, not drift).  Every
    resolution is a ``kernel.mxu_input`` telemetry event (``emit=False``
    for prospective resolutions, like the compute-unit resolver).  Returns
    ``(value, source)``."""
    val, source = _resolve_axis_value(
        request, tuned, "STENCIL_MXU_INPUT", MXU_INPUTS, "f32"
    )
    if val == "bf16" and not unit_uses_mxu(compute_unit):
        if source in ("explicit", "env"):
            from stencil_tpu.utils.logging import log_warn

            log_warn(
                f"mxu_input=bf16 ({source}) has no effect for {where}: the "
                f"resolved compute unit is {compute_unit!r} (no contraction "
                "to feed); using f32"
            )
        val, source = "f32", source + "/degraded"
    if emit:
        from stencil_tpu import telemetry
        from stencil_tpu.telemetry import names as tm

        telemetry.emit_event(
            tm.EVENT_KERNEL_MXU_INPUT,
            input=val,
            source=source,
            unit=compute_unit,
            where=where,
        )
    return val, source


def band_matrix(n: int, dtype=jnp.float32, r: int = 1) -> jax.Array:
    """The ``(n, n)`` circulant ``(2r+1)``-band for the dense MXU
    contraction form: ``(B @ v)[i] == Σ_{d=1..r} v[(i-d) % n] + v[(i+d) % n]``
    — exactly the ``roll(v, d) + roll(v, -d)`` chain of the vpu form, as ONE
    banded matmul (the wafer-scale stencil mapping: a (2r+1)-diagonal
    coefficient band contracted against the plane, with the periodic wrap —
    the same wrap the vpu rotate has, so shell/garbage cells keep the
    identical dependency structure and the ≤1-ulp-per-level contract is a
    pure summation-order statement).  Symmetric, so the same matrix serves
    both orientations (``B @ plane`` for the sublane axis, ``plane @ B``
    for the lane axis).  Materialized ONCE per plan as a constant-index-map
    pallas input — resident in VMEM at (sublane, 128)-tile-padded size,
    like the d2 plane.  Built as a SUM of the per-offset shift matrices
    (not a membership predicate) so degenerate extents stay value-exact:
    at n=2, r=1 both offsets land on the same neighbor and the entry is
    2.0, matching the vpu chain's double-counted roll."""
    i = jnp.arange(n)
    d = (i[:, None] - i[None, :]) % n
    out = jnp.zeros((n, n), dtype)
    for off in range(1, r + 1):
        out = out + (d == off % n).astype(dtype) + (d == (n - off) % n).astype(dtype)
    return out


def band_tile_size(n: int, r: int = 1):
    """The band-tile granule for one plane axis of extent ``n`` under the
    ``mxu_band`` variant, or None when no admissible tiling exists (the
    kernel then runs the dense form — ``plane_band_unit``).

    A granule ``g`` must divide ``n`` (the blocked form reshapes the axis
    into ``n/g`` whole blocks), must cover the band half-width
    (``g >= 2r+1`` keeps every neighbor read within the adjacent block, so
    each output block contracts against ≤3 input blocks), and must
    actually CUT FLOPs vs the dense circulant (``6·g`` per element per
    axis < the dense ``2·n`` ⟺ ``3·g < n`` — a near-``n/2`` granule would
    dispatch MORE dense-tile FLOPs than the circulant it replaces, so
    such geometries run dense instead).  Preference among admissible
    divisors: the smallest sublane-granule multiple (8 — keeps the
    (8, 128)-tiled layout native for the reshape and the tile operands),
    else the smallest: smaller granules mean fewer dispatched FLOPs
    (``mxu_flops_per_plane``)."""
    divs = [
        d
        for d in range(max(2 * r + 1, 2), n)
        if n % d == 0 and 3 * d < n
    ]
    for d in divs:
        if d % 8 == 0:
            return d
    return divs[0] if divs else None


def band_tile_plan(plane_y: int, plane_z: int, r: int = 1):
    """``(gy, gz)`` band-tile granules for one (Y, Z) plane geometry, or
    None when EITHER in-plane axis admits no tiling — the ``mxu_band``
    variant engages whole-plane or not at all (a mixed band/dense plane
    would split the ulp pin and the FLOP model per axis for no modeled
    win)."""
    gy = band_tile_size(plane_y, r)
    gz = band_tile_size(plane_z, r)
    if gy is None or gz is None:
        return None
    return gy, gz


def band_wide_tile(g: int, r: int = 1, dtype=jnp.float32) -> jax.Array:
    """The ``(g, 3g)`` wide tile ``[L | D | U]`` of the blocked
    ``(2r+1)``-band matmul: column ``j`` of the tile addresses position
    ``j - g`` relative to the output block's start (the previous block's
    rows, the block itself, the next block's rows, concatenated), so
    ``W[p, j] = 1  iff  1 <= |p + g - j| <= r`` — the band's nonzeros and
    nothing else.  ``out_block_i = W @ [c_{i-1}; c_i; c_{i+1}]`` then
    reproduces the dense circulant contraction exactly (each output element
    sums the same ``2r`` neighbor values; zeros add exactly), at
    ``2·(3g)·g`` FLOPs per block instead of ``2·n·g``.  Transpose for the
    lane-axis (right-multiplication) orientation."""
    p = jnp.arange(g)[:, None]
    j = jnp.arange(3 * g)[None, :]
    d = jnp.abs(p + g - j)
    return ((d >= 1) & (d <= r)).astype(dtype)


def plane_band_unit(compute_unit: str, plane_y: int, plane_z: int,
                    r: int = 1, where: str = "kernel") -> str:
    """The EFFECTIVE contraction variant for one concrete plane geometry:
    ``mxu_band`` on a plane either of whose in-plane axes admits no band
    tile (``band_tile_plan`` — prime extents foremost) degrades to the
    dense ``mxu`` form with a warning.  The resolve-time chain cannot see
    per-kernel plane dims (the split schedule's narrow band sub-blocks run
    the same ``compute_unit`` over different geometry), so this is the last
    structural gate, applied by every kernel builder."""
    if compute_unit == "mxu_band" and band_tile_plan(plane_y, plane_z, r) is None:
        from stencil_tpu.utils.logging import log_warn

        log_warn(
            f"compute_unit=mxu_band cannot tile a ({plane_y}, {plane_z}) "
            f"plane at r={r} for {where} (no admissible granule divides "
            "both extents); running the dense mxu form"
        )
        return "mxu"
    return compute_unit


def band_operands(plane_y: int, plane_z: int, compute_unit: str,
                  mxu_input: str = "f32", r: int = 1):
    """``(args, in_specs)`` of the resident contraction constants for one
    (Y, Z) plane geometry — the two arrays every MXU kernel parks in VMEM
    via constant index maps (like the d2 plane).  Dense: the two circulants
    (``band_matrix``, (Y, Y) + (Z, Z)); band: the two wide tiles
    (``band_wide_tile``, (gy, 3gy) + the transposed (3gz, gz)) — a
    few-KB footprint where the dense constants cost plane-squared bytes
    (the VMEM-model term that makes previously-pruned mxu candidates
    admissible).  ``mxu_input="bf16"`` materializes the constants narrow
    (0/1/2 band entries are exact in bfloat16), halving their residency."""
    from jax.experimental import pallas as pl

    assert unit_uses_mxu(compute_unit), compute_unit
    dt = jnp.bfloat16 if mxu_input == "bf16" else jnp.float32
    if compute_unit == "mxu_band":
        gy, gz = band_tile_plan(plane_y, plane_z, r)  # gated by the builder
        args = [band_wide_tile(gy, r, dt), jnp.transpose(band_wide_tile(gz, r, dt))]
        specs = [
            pl.BlockSpec((gy, 3 * gy), lambda i: (0, 0)),
            pl.BlockSpec((3 * gz, gz), lambda i: (0, 0)),
        ]
        return args, specs
    args = [band_matrix(plane_y, dt, r), band_matrix(plane_z, dt, r)]
    specs = [
        pl.BlockSpec((plane_y, plane_y), lambda i: (0, 0)),
        pl.BlockSpec((plane_z, plane_z), lambda i: (0, 0)),
    ]
    return args, specs


def _block_roll(c3, amt: int, axis: int):
    """Roll by WHOLE blocks along a non-minor axis, as two static slices +
    a concatenate (the unaligned-plane lowering ``_make_roll`` uses —
    block-granular major/second-minor slices are tile-aligned by
    construction, so Mosaic accepts them at any granule)."""
    n = c3.shape[axis]
    k = amt % n
    if k == 0:
        return c3
    return jax.lax.concatenate(
        [
            jax.lax.slice_in_dim(c3, n - k, n, axis=axis),
            jax.lax.slice_in_dim(c3, 0, n - k, axis=axis),
        ],
        dimension=axis,
    )


def make_plane_nbr_sum(plane_y: int, plane_z: int, compute_unit: str,
                       mxu_input: str = "f32", r: int = 1):
    """The in-kernel ``(2r+1)``-band in-plane neighbor sum for one (Y, Z)
    plane geometry under an MXU compute unit: returns
    ``nbr_sum(c, b1, b2) -> (Y, Z)`` where ``b1``/``b2`` are the VALUES of
    the resident constants ``band_operands`` built for the same geometry
    (the kernels read them out of their refs once per invocation).

    ``mxu`` contracts the dense circulants; ``mxu_band`` runs the blocked
    band form: the tiled axis reshapes into granule blocks, each output
    block contracts against its ≤3 neighbor blocks through the wide tile —
    one batched ``dot_general`` for the sublane (y) axis (the tile
    broadcast over blocks keeps the output layout transpose-free) and one
    free-dims ``dot_general`` for the lane (z) axis.  Both variants sum the
    same ``2r`` neighbor values per element per axis (zeros add exactly),
    so band-vs-dense divergence is pure summation order — the same ulp
    regime as the mxu-vs-vpu pin.  ``mxu_input="bf16"`` rounds the plane
    operand to bfloat16 once per read (constants are exact);
    ``preferred_element_type`` pins the f32 accumulator either way."""
    assert unit_uses_mxu(compute_unit), compute_unit
    cast = (
        (lambda v: v.astype(jnp.bfloat16))
        if mxu_input == "bf16"
        else (lambda v: v)
    )
    if compute_unit == "mxu":

        def nbr_sum(c, by, bz):
            dn = (((1,), (0,)), ((), ()))
            cc = cast(c)
            return jax.lax.dot_general(
                by, cc, dn, preferred_element_type=jnp.float32
            ) + jax.lax.dot_general(
                cc, bz, dn, preferred_element_type=jnp.float32
            )

        return nbr_sum

    gy, gz = band_tile_plan(plane_y, plane_z, r)  # gated by plane_band_unit
    nby, nbz = plane_y // gy, plane_z // gz

    def nbr_sum(c, wy, wz):
        cc = cast(c)
        # y axis: granule blocks of rows against the (gy, 3gy) wide tile,
        # batched over blocks (the broadcast tile is KBs; batching keeps
        # the (block, row, lane) output layout transpose-free)
        c3 = cc.reshape(nby, gy, plane_z)
        ext = jnp.concatenate(
            [_block_roll(c3, 1, 0), c3, _block_roll(c3, -1, 0)], axis=1
        )  # (nby, 3gy, Z)
        wyb = jnp.broadcast_to(wy, (nby,) + wy.shape)
        ysum = jax.lax.dot_general(
            wyb, ext, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(plane_y, plane_z)
        # z axis: granule blocks of lanes against the transposed (3gz, gz)
        # tile — the lhs free dims (Y, block) keep the layout in place
        c3z = cc.reshape(plane_y, nbz, gz)
        extz = jnp.concatenate(
            [_block_roll(c3z, 1, 1), c3z, _block_roll(c3z, -1, 1)], axis=2
        )  # (Y, nbz, 3gz)
        zsum = jax.lax.dot_general(
            extz, wz, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).reshape(plane_y, plane_z)
        return ysum + zsum

    return nbr_sum


def plane_nbr_sum_host(c: jax.Array, compute_unit: str, r: int = 1,
                       mxu_input: str = "f32") -> jax.Array:
    """Host-level (non-pallas) evaluation of the in-plane ``(2r+1)``-band
    neighbor sum under one compute unit — the shared harness for the
    contraction-form probes and the radii-{1,2} equivalence pins
    (``scripts/probes/probe_mxu_band.py``, tests/test_kernel_axes.py).
    ``vpu`` is the roll chain; the MXU units build their own resident
    constants for this plane and contract exactly as the kernels do."""
    Y, Z = c.shape
    if compute_unit == "vpu":
        out = jnp.zeros_like(c)
        for off in range(1, r + 1):
            out = (
                out
                + jnp.roll(c, off, 0) + jnp.roll(c, -off, 0)
                + jnp.roll(c, off, 1) + jnp.roll(c, -off, 1)
            )
        return out
    unit = plane_band_unit(compute_unit, Y, Z, r, where="host")
    args, _ = band_operands(Y, Z, unit, mxu_input, r)
    return make_plane_nbr_sum(Y, Z, unit, mxu_input, r)(c, *args)


def _make_level_sum(roll, compute_unit: str, nbr_sum=None):
    """The per-level 6-neighbor numerator, per compute unit.  ``vpu`` is
    the historical roll+add chain VERBATIM (same left-fold order — tier-1
    pins it bitwise); the MXU units replace the four in-plane rolls with
    ``nbr_sum`` (``make_plane_nbr_sum`` — one banded contraction per axis,
    dense or band-tiled; ``preferred_element_type=f32`` pins the
    accumulator, which the ``accum-dtype`` lint rule makes mandatory in
    ops/).  The forms differ only in summation order, hence the
    ulps-per-level contract."""
    if unit_uses_mxu(compute_unit):
        assert nbr_sum is not None

        def level_sum(prev, vals, cent, b1, b2):
            return prev + vals + nbr_sum(cent, b1, b2)

    else:

        def level_sum(prev, vals, cent, b1, b2):
            del b1, b2
            return (
                prev
                + vals
                + roll(cent, 1, 0)
                + roll(cent, -1, 0)
                + roll(cent, 1, 1)
                + roll(cent, -1, 1)
            )

    return level_sum


def _check_compute_unit(compute_unit: str, acc_dtype) -> None:
    """Build-time guard: the resolvers degrade structurally-impossible
    requests BEFORE a kernel build, so reaching a kernel with an MXU unit
    on a non-f32 accumulator is a wiring bug, not a user error."""
    assert compute_unit in COMPUTE_UNITS, compute_unit
    if unit_uses_mxu(compute_unit):
        assert jnp.dtype(acc_dtype) == jnp.float32, (
            "mxu contraction requires an f32 accumulator; the resolver "
            f"should have degraded this build (got {jnp.dtype(acc_dtype)})"
        )


def mxu_flops_per_plane(plane_y: int, plane_z: int,
                        compute_unit: str = "mxu", r: int = 1) -> int:
    """Analytic MXU FLOPs of ONE level over one (Y, Z) plane, for the
    RESOLVED contraction variant — the ``kernel.mxu.flops`` counter and
    every roofline/perf-ledger series built on it would be poisoned by
    ~``n/(2r+1)`` if the dense model kept reporting for a band-tiled run.

    * ``mxu`` (dense): the y-axis band matmul is (Y,Y)x(Y,Z) = 2·Y²·Z
      FLOPs and the z-axis (Y,Z)x(Z,Z) = 2·Y·Z² — dense FLOPs over a
      mostly-zero band, the deliberate wafer-scale trade.
    * ``mxu_band``: per axis, each output granule block contracts one
      (g, 3g)-tile matmul — ``2·(3g)·g`` FLOPs per block × ``n/g`` blocks
      × the other extent = ``6·g·Y·Z`` per axis (``band_tile_plan`` picks
      the granules).  A geometry with no admissible tiling runs (and is
      counted as) the dense form.

    Feeds the ``kernel.mxu.flops`` telemetry counter — modeled, like the
    exchange bytes, so the hot path stays an int multiply."""
    if compute_unit == "mxu_band":
        plan = band_tile_plan(plane_y, plane_z, r)
        if plan is not None:
            gy, gz = plan
            return 6 * gy * plane_y * plane_z + 6 * gz * plane_y * plane_z
    return 2 * plane_y * plane_y * plane_z + 2 * plane_y * plane_z * plane_z


def sphere_params(gx: int):
    """hot/cold sphere x-centers and the integer membership bound
    d2 < (r+1)^2 (the truncated-float-sqrt test, jacobi3d.cu:31-33 — see
    models/jacobi.py for the exact-equivalence bound)."""
    return gx // 3, gx * 2 // 3, (gx // 10 + 1) ** 2


def yz_dist2_plane(origin_y, origin_z, shape_yz: Tuple[int, int], global_size) -> jax.Array:
    """(y - gy/2)^2 + (z - gz/2)^2 over the interior plane, wrapped
    periodically; shared by both spheres (same y/z center)."""
    gy, gz = global_size[1], global_size[2]
    cy, cz = gy // 2, gz // 2
    y = (origin_y + jnp.arange(shape_yz[0])) % gy
    z = (origin_z + jnp.arange(shape_yz[1])) % gz
    return ((y - cy) ** 2)[:, None] + ((z - cz) ** 2)[None, :]


#: The scoped-VMEM budget REQUESTED from the compiler
#: (``CompilerParams(vmem_limit_bytes=...)``) and the stack margin its
#: temporaries (rolls, selects) claim beyond the block buffers.  Mosaic's
#: 16 MB default is only a default: v5e physically carries 128 MB of VMEM and
#: raising the request to 100 MB compiles and RUNS FASTER at every depth
#: probed (scripts/probe20*, 512^3 f32: k=3 97 -> k=12 190 -> k=16 ~200
#: Gcells/s; k=32 at a 120 MB request regresses to 152 — leave headroom for
#: the pipeline's double buffers).  The r04 calibration anchors (16 MB
#: pass/fail points, probe10/14/17) describe the DEFAULT budget and survive
#: as the behavior when ``STENCIL_VMEM_LIMIT_BYTES`` forces the old value.
_VMEM_BUDGET_DEFAULT = 100 * 1024 * 1024
_VMEM_STACK_MARGIN = 3_000_000


_vmem_warned: set = set()


def _vmem_budget() -> int:
    """Requested scoped-VMEM bytes; ``STENCIL_VMEM_LIMIT_BYTES`` overrides
    (read per call so tests can force an over-budget compile).  The read is
    VALIDATED (``utils.config.env_int``): a malformed value raises a message
    naming the env var instead of a bare ``ValueError`` deep inside
    planning, a zero/negative value (which would silently disable every
    streaming route) is rejected, and a value under Mosaic's 16 MB default
    warns once per distinct value."""
    from stencil_tpu.utils.config import env_int

    val = env_int("STENCIL_VMEM_LIMIT_BYTES", _VMEM_BUDGET_DEFAULT, minimum=1)
    if val < 16 * 1024 * 1024 and val not in _vmem_warned:
        _vmem_warned.add(val)
        from stencil_tpu.utils.logging import log_warn

        log_warn(
            f"STENCIL_VMEM_LIMIT_BYTES={val} is below Mosaic's 16 MB default "
            "scoped-VMEM budget; deep streaming routes will degrade to "
            "shallow/plane rungs"
        )
    return val

#: deepest depth validated on hardware and the measured plateau: probe20b/c/d
#: (512^3, 100 MB budget) k=8 128-132, k=12 190, k=16 142-202, k=20 190,
#: k=24 190, k=32 152 Gcells/s — the plateau spans ~12-24 with run-to-run
#: contention noise; 16 sits mid-plateau at modest (40 MB) VMEM
_WRAP_MAX_K = 16


def _tpu_compiler_params(interpret: bool):
    """kwargs dict requesting the calibrated scoped-VMEM budget — empty in
    interpret mode (no Mosaic, nothing to budget)."""
    if interpret:
        return {}
    from stencil_tpu.utils.compat import tpu_compiler_params

    return {
        "compiler_params": tpu_compiler_params(vmem_limit_bytes=_vmem_budget())
    }


def _padded_plane_bytes(plane_y: int, plane_z: int, itemsize: int) -> int:
    """HBM/VMEM bytes of one (plane_y, plane_z) plane after (sublane, 128)
    tile padding — lane padding is what the naive y*z*itemsize model misses
    (516 lanes really occupy 640)."""
    sub = max(8, 32 // itemsize)  # f32 -> 8, bf16 -> 16, i8 -> 32
    return (-(-plane_y // sub) * sub) * (-(-plane_z // 128) * 128) * itemsize


def mxu_vmem_extra_bytes(plane_y: int, plane_z: int, compute_unit="mxu",
                         mxu_input: str = "f32", r: int = 1) -> int:
    """Resident VMEM bytes of the contraction constants for one (Y, Z)
    plane geometry — the per-variant term every depth gate folds in.
    Dense parks the two full circulants (plane-squared bytes, the term
    that historically pruned mxu candidates); the band variant parks only
    the two wide tiles (KBs — which is why previously VMEM-pruned mxu
    candidates become admissible under ``mxu_band``; its ext/block
    temporaries are transient and live in the same stack margin the vpu
    chain's roll temporaries do).  ``mxu_input="bf16"`` halves the
    constants (they materialize narrow).  An untilable band geometry is
    priced as the dense form it will actually run."""
    it = 2 if mxu_input == "bf16" else 4
    if compute_unit == "mxu_band":
        plan = band_tile_plan(plane_y, plane_z, r)
        if plan is not None:
            gy, gz = plan
            return _padded_plane_bytes(gy, 3 * gy, it) + _padded_plane_bytes(
                3 * gz, gz, it
            )
    return _padded_plane_bytes(plane_y, plane_y, it) + _padded_plane_bytes(
        plane_z, plane_z, it
    )


def _mxu_unit_of(mxu) -> str:
    """Normalize the VMEM models' ``mxu`` parameter: historically a bool
    (True = the dense form), now also the compute-unit string so the
    models price the RESOLVED variant.  Falsy -> no MXU term."""
    if mxu is True:
        return "mxu"
    if isinstance(mxu, str) and unit_uses_mxu(mxu):
        return mxu
    return ""


def wavefront_vmem_bytes(
    k: int,
    plane_y: int,
    plane_z: int,
    itemsize: int,
    z_slabs: bool = False,
    d2_itemsize: int = 4,
    ring_itemsize: int = None,
    mxu=False,
    mxu_input: str = "f32",
) -> int:
    """Modeled VMEM footprint of a k-level plane wavefront: 2k ring planes,
    4 pipeline (in/out double-buffer) planes, the resident d2 plane
    (``d2_itemsize`` 2 when ``pack_d2`` can clamp to int16), and (z-slab
    variant) 4 double-buffered packed-slab blocks.  ``ring_itemsize``
    overrides the ring planes' itemsize: bf16 STORAGE (``f32_accumulate``)
    streams 2-byte pipeline planes but carries its level ring at f32, so
    the ring must be modeled at 4 bytes or the gate lies.  ``mxu`` (a bool
    for the dense form, or the compute-unit string) adds the resident
    contraction constants of the resolved variant — the dense circulants
    or the band variant's small wide tiles (``mxu_vmem_extra_bytes``);
    ``mxu_input`` narrows them."""
    ring_it = itemsize if ring_itemsize is None else ring_itemsize
    plane = _padded_plane_bytes(plane_y, plane_z, itemsize)
    est = 2 * k * _padded_plane_bytes(plane_y, plane_z, ring_it) + 4 * plane
    if d2_itemsize:  # 0 = kernel variant with no resident d2 plane
        est += _padded_plane_bytes(plane_y, plane_z, d2_itemsize)
    if z_slabs:
        # z-major (1, 2k, plane_y) blocks: sublane-pad the 2k rows
        est += 4 * _padded_plane_bytes(2 * k, plane_y, itemsize)
    unit = _mxu_unit_of(mxu)
    if unit:
        est += mxu_vmem_extra_bytes(plane_y, plane_z, unit, mxu_input)
    return est


def wavefront_vmem_fits(
    k: int,
    plane_y: int,
    plane_z: int,
    itemsize: int,
    z_slabs: bool = False,
    d2_itemsize: int = 4,
    ring_itemsize: int = None,
    mxu=False,
    mxu_input: str = "f32",
) -> bool:
    est = wavefront_vmem_bytes(
        k, plane_y, plane_z, itemsize, z_slabs, d2_itemsize, ring_itemsize,
        mxu, mxu_input,
    )
    return est + _VMEM_STACK_MARGIN <= _vmem_budget()


def pack_d2(yz_d2: jax.Array, global_size) -> jax.Array:
    """The d2 plane as int32.  (An int16 clamp would halve the resident
    plane and is numerically exact for gx < ~1800, but Mosaic on v5e
    rejects 16-bit vector comparisons — "Target does not support this
    comparison" — so the narrow form is not usable today.)"""
    del global_size
    return yz_d2.astype(jnp.int32)


def warn_if_over_vmem_budget(k: int, plane_y: int, plane_z: int, itemsize: int,
                             ring_itemsize: int = None,
                             mxu=False) -> None:
    if not wavefront_vmem_fits(k, plane_y, plane_z, itemsize,
                               ring_itemsize=ring_itemsize, mxu=mxu):
        est = wavefront_vmem_bytes(k, plane_y, plane_z, itemsize,
                                   ring_itemsize=ring_itemsize, mxu=mxu)
        from stencil_tpu.utils.logging import log_warn

        log_warn(
            f"temporal depth {k} models {est / 1e6:.1f} MB of VMEM blocks "
            f"(+{_VMEM_STACK_MARGIN / 1e6:.0f} stack > {_vmem_budget() / 1e6:.0f} budget); "
            "expect a compile failure on real TPU (fine in interpret mode)"
        )


def choose_temporal_k(
    shape: Tuple[int, int, int], itemsize: int, requested="auto",
    tune_key=None, ring_itemsize: int = None, mxu=False,
) -> int:
    """Pick the wrap kernel's temporal blocking depth: the deepest k whose
    VMEM footprint fits the calibrated budget (``auto``), or a validated
    explicit int.  Measured sweep (scripts/probe10b, v5e f32): 512^3
    41 -> 94 Gcells/s (k=3), 384^3 -> 120 (k=6), 256^3 -> 134 (k=6).

    ``tune_key`` (a ``tune.WorkloadKey``) consults the measurement-driven
    autotuner first: a persisted on-device-measured depth for this
    chip/shape/dtype wins over the static model below (which is the v5e
    calibration, kept as the no-tune/cold-cache fallback — docs/tuning.md).
    A tuned depth may legitimately exceed ``_WRAP_MAX_K``: the plateau is a
    property of the probed chip, not the kernel.

    ``ring_itemsize`` overrides the level ring's itemsize in the VMEM
    model: under bf16 STORAGE the pipeline planes stream at 2 B but the
    ring carries the f32 accumulator (the ``f32_accumulate`` contract), so
    a storage-itemsize-only model would admit depths whose f32 ring blows
    the budget.  ``mxu`` (bool for the dense form, or the compute-unit
    string) folds the resolved variant's resident contraction constants
    into the model the same way — the dense circulants, or the band
    variant's KB tiles."""
    X, Y, Z = shape
    if requested != "auto":
        k = int(requested)
        if not 1 <= k <= max(1, X // 2):
            raise ValueError(f"temporal_k={k} needs 1 <= k <= X//2 = {X // 2}")
        warn_if_over_vmem_budget(k, Y, Z, itemsize, ring_itemsize, mxu=mxu)
        return k
    if tune_key is not None:
        from stencil_tpu import tune

        cfg = tune.best_config(tune_key)
        if cfg is not None:
            k = cfg.get("k")
            if isinstance(k, int) and 1 <= k <= max(1, X // 2):
                return k
            from stencil_tpu.utils.logging import log_warn

            log_warn(
                f"tuned config {cfg} for {tune_key.label()} is structurally "
                f"invalid here (need 1 <= k <= {max(1, X // 2)}); using the "
                "static pick"
            )
    k = 1
    for cand in range(2, _WRAP_MAX_K + 1):
        if cand <= X // 2 and wavefront_vmem_fits(
            cand, Y, Z, itemsize, ring_itemsize=ring_itemsize, mxu=mxu
        ):
            k = cand
    return k


def _make_roll(interpret: bool):
    """Interpret-aware plane rotate shared by the streaming kernels: jnp.roll
    in interpret mode, pltpu.roll (amount normalized into range) compiled.
    Mosaic's rotate is 32-bit-only ("Rotate with non-32-bit data"): narrower
    FLOAT dtypes upcast to f32 (value-exact for bf16/f16) and stay f32 on
    return, so the caller's stencil sum accumulates in f32 and downcasts
    once at its existing per-level astype — better accuracy than a narrow
    sum and fewer converts than a per-roll round trip (Mosaic CSEs the
    repeated upcast of the same plane).  8-byte dtypes are not silently
    truncated; they fail loudly in Mosaic.

    Mosaic additionally rejects its rotate on planes that are not natively
    tiled ("unsupported unaliged shape": second-minor % 8 / minor % 128 for
    the 32-bit tiling) — exactly the shape class of shell-padded multi-chip
    blocks (e.g. 132x132 raw planes) and the split-step overlap schedule's
    narrow band sub-blocks (ops/stream.py).  A STATIC python amount (stencil
    offsets, wrap closures — every streaming-kernel site) on an unaligned
    plane therefore takes an equivalent two-static-slices + concatenate form
    instead, which Mosaic accepts at any alignment; aligned planes keep the
    single rotate instruction (the measured single-chip fast path), and
    TRACED amounts (the slab route's per-plane column rotate) have no
    static-slice form and stay on Mosaic's rotate."""
    from jax.experimental.pallas import tpu as pltpu

    def roll(v, amt, axis):
        if interpret:
            return jnp.roll(v, amt, axis)
        if v.dtype.itemsize < 4 and jnp.issubdtype(v.dtype, jnp.floating):
            v = v.astype(jnp.float32)
        aligned = v.shape[-1] % 128 == 0 and (
            v.ndim < 2 or v.shape[-2] % 8 == 0
        )
        if aligned or not isinstance(amt, int):
            return pltpu.roll(v, amt % v.shape[axis], axis)
        n = v.shape[axis]
        k = amt % n
        if k == 0:
            return v
        return jax.lax.concatenate(
            [
                jax.lax.slice_in_dim(v, n - k, n, axis=axis),
                jax.lax.slice_in_dim(v, 0, n - k, axis=axis),
            ],
            dimension=axis,
        )

    return roll


def jacobi_wrap_step(
    block: jax.Array,
    interpret: bool = False,
    k: int = 1,
    compute_unit: str = "vpu",  # "vpu" = the historical roll+add chain
    # (bitwise-pinned); "mxu" = one banded contraction per in-plane axis on
    # the matrix unit (band_matrix + _make_level_sum; ≤1 ulp/level vs vpu);
    # "mxu_band" = the blocked (2r+1)-band form of the same contraction
    # (band_wide_tile — ulp-pinned vs dense, O(g)-per-element FLOPs)
    f32_accumulate: bool = False,  # bf16-STORAGE variant: the block streams
    # at its (narrow) dtype but the kernel upcasts at load, carries the
    # level ring and all arithmetic at f32, and downcasts ONCE at the final
    # store — one round-to-nearest per k levels instead of one per level
    mxu_input: str = "f32",  # MXU operand precision: "bf16" narrows the
    # contraction operands (f32 accumulator pinned) — analytic bound in
    # tests/ulp.mxu_bf16_input_atol; ignored under vpu
) -> jax.Array:
    """``k`` Jacobi iterations over the WHOLE (unsharded) domain with the
    periodic wrap folded into the kernel — the single-device fast path.

    With one device there is no neighbor: the reference still runs its
    same-GPU ``PeerAccessSender`` translate kernels to fill the shell
    (tx_cuda.cuh:39-104); here the shell disappears entirely.  The x-wrap
    rides the block index map (planes are re-fetched modulo X after the last
    plane so every level can close its ring), and the y/z wrap is a
    lane/sublane rotate of the resident plane.

    ``k > 1`` is TEMPORAL BLOCKING (a wavefront over time steps): each HBM
    plane is read ONCE and the output written ONCE per ``k`` iterations —
    ~8/k bytes/cell.  This chip's DMA fabric caps pallas pipelines at
    ~350 GB/s (scripts/probe9e/9f: one giant HBM->HBM DMA, multi-queue, and
    multi-buffer all plateau there, while XLA vector-core fusions stream
    ~720), so at k=1 the plane pipeline is already AT its hardware ceiling
    and only temporal reuse can pass it.  Level ``s`` consumes the planes of
    level ``s-1`` as they emerge; each level keeps a 2-plane ring; the replay
    (grid X + 2k) recomputes each level's early planes so the x-wrap closes
    for every level — the k=1 schedule is exactly the original wrap kernel.

    ``block`` is the bare (X, Y, Z) logical domain; semantics match ``k``
    applications of ``models.jacobi.Jacobi3D._kernel`` exactly (bit-exact:
    summation order is identical per level).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    X, Y, Z = block.shape
    assert 1 <= k <= X // 2, (k, X)
    gx = X
    hot_x, cold_x, in_r2 = sphere_params(gx)

    roll = _make_roll(interpret)
    acc_dtype = jnp.float32 if f32_accumulate else block.dtype
    _check_compute_unit(compute_unit, acc_dtype)
    mxu = unit_uses_mxu(compute_unit)
    if mxu:
        compute_unit = plane_band_unit(compute_unit, Y, Z, where="wrap")
    nbr_sum = (
        make_plane_nbr_sum(Y, Z, compute_unit, mxu_input) if mxu else None
    )
    level_sum = _make_level_sum(roll, compute_unit, nbr_sum)

    def kernel(in_ref, d2_ref, *rest):
        if mxu:
            by_ref, bz_ref, out_ref, ring = rest
            by, bz = by_ref[...], bz_ref[...]
        else:
            out_ref, ring = rest
            by = bz = None
        # ring[s] holds the two most recent level-s planes (level 0 = input)
        i = pl.program_id(0)
        d2 = d2_ref[...]
        vals = in_ref[0].astype(acc_dtype)  # level-0 plane i (mod X)
        for s in range(1, k + 1):
            # level-s plane (i - s) from level-(s-1) planes (i-s-1, i-s,
            # i-s+1); early steps compute garbage that the replay rewrites
            prev = ring[s - 1, i % 2]  # plane i-s-1
            cent = ring[s - 1, (i + 1) % 2]  # plane i-s
            ring[s - 1, i % 2] = vals  # push plane i-s+1 (after prev read)
            val = level_sum(prev, vals, cent, by, bz) / 6.0
            x_g = (i - s) % X
            val = jnp.where(d2 < in_r2 - (x_g - hot_x) ** 2, HOT_TEMP, val)
            val = jnp.where(d2 < in_r2 - (x_g - cold_x) ** 2, COLD_TEMP, val)
            vals = val.astype(acc_dtype)
        # level-k plane (i - k) % X; last write is valid.  The one downcast
        # of the f32_accumulate contract happens here.
        out_ref[0] = vals.astype(block.dtype)

    d2 = yz_dist2_plane(0, 0, (Y, Z), block.shape)

    const = lambda a, b: pl.BlockSpec((a, b), lambda i: (0, 0))
    in_specs = [
        pl.BlockSpec((1, Y, Z), lambda i: (i % X, 0, 0)),
        # constant index map: fetched once, stays resident in VMEM
        const(Y, Z),
    ]
    args = [block, d2.astype(jnp.int32)]
    if mxu:
        # resident contraction constants (dense circulants or band tiles),
        # fetched once like the d2 plane
        b_args, b_specs = band_operands(Y, Z, compute_unit, mxu_input)
        in_specs += b_specs
        args += b_args
    return pl.pallas_call(
        kernel,
        grid=(X + 2 * k,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Y, Z), lambda i: ((i - k) % X, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((X, Y, Z), block.dtype),
        scratch_shapes=[pltpu.VMEM((k, 2, Y, Z), acc_dtype)],
        interpret=interpret,
        **_tpu_compiler_params(interpret),
    )(*args)


def jacobi_shell_wavefront_step(
    raw: jax.Array,  # (X+2s, Y+2s, Z+2s) block with FILLED s-wide shell, s >= m
    m: int,  # levels to advance (<= the shell width)
    origin: jax.Array,  # (3,) int32 global coords of the shard's interior start
    d2: jax.Array,  # (Y+2s, Z+2s) int32 yz_dist2_plane over the RAW plane
    global_size: Tuple[int, int, int],
    interior_offset: int = None,  # raw index of the interior start (= shell
    # width s; defaults to m — pass it when advancing FEWER levels than the
    # shell is wide, e.g. a steps%m remainder dispatch)
    interpret: bool = False,
    alias: bool = True,  # in-place (input_output_aliases); False trades the
    # aliasing for a fresh output buffer (uninitialized high shell)
    z_slabs: jax.Array = None,  # (Xr, 2s, Yr) TRANSPOSED, s = the shell
    # width: the z-halo content, kept OUT of the big array (a z halo
    # write/read on the tiled layout costs a whole (8,128)-tile column
    # pass, ~64x amplification — scripts/probe12d).  Rows [0, s) = my low
    # halo (zlo), [s, 2s) = my high halo (zhi) — ONE packed buffer, stored
    # z-major so each streamed (1, 2s, Yr) block pads to (8, lanes) instead
    # of (sublanes, 128): ~20 KB/block vs 266 — a 13x VMEM saving per
    # double-buffered block that still matters for deep-m budgets (and was
    # what fit 516^2 planes under Mosaic's old 16 MB default, kept reachable
    # via STENCIL_VMEM_LIMIT_BYTES).  The kernel transposes
    # the small block in VMEM, patches the z columns of every streamed
    # plane, and, when set, ALSO emits the next macro step's outgoing slabs
    # in the same layout, returning (out, z_out) with z_out rows [0, s) =
    # my top interior cols [Zr-2s, Zr-s) (the -z-bound message) and
    # [s, 2s) = my bottom interior cols [s, 2s) (the +z-bound message).
    z_valid: int = None,  # logical z extent of the raw planes (shell incl.);
    # columns [z_valid, Zr) are DEAD LANE PADDING that rounds the plane width
    # up to a 128 multiple.  Ragged lane extents cripple the plane DMA
    # (probe22: 512x512x516 streams 30% slower than 512x512x512 while
    # 512x512x640 runs at full per-byte rate), so the caller pads the array
    # and the kernel treats [z_valid, Zr) as outside the domain.  Dead-column
    # garbage rolls into halo column 0 / z_valid-1 at level 1 — columns that
    # are only valid at level 0 anyway, so the shrinking-validity argument is
    # unchanged: level s remains valid on [s, z_valid - s).
    compute_unit: str = "vpu",  # "mxu" = one banded in-plane contraction
    # per axis on the matrix unit (see jacobi_wrap_step); ≤1 ulp/level vs
    # vpu; "mxu_band" = its blocked (2r+1)-band form
    f32_accumulate: bool = False,  # bf16-storage variant: upcast at load,
    # f32 level ring + arithmetic, ONE downcast at the final store/emit
    mxu_input: str = "f32",  # MXU operand precision (see jacobi_wrap_step)
) -> jax.Array:
    """``m`` Jacobi levels over an m-shell-carrying shard in ONE pass — the
    multi-device temporal-blocking path.

    The halo-multiplier machinery (domain.set_halo_multiplier) already
    exchanges ``m*r``-wide shells every ``m`` steps; this kernel is its
    compute half done the wrap-kernel way: a wavefront over time steps where
    each HBM plane is read once and written once per ``m`` iterations
    (~8/m B/cell), instead of ``m`` separate full passes.  Validity shrinks
    exactly one cell per level from each face — the roll wraparound at the
    y/z plane edges and the missing planes at the x ends contaminate only
    the cells the shell was sized to sacrifice: level ``s`` is valid on
    ``[s, ext-s)`` per axis, and the interior ``[m, ext-m)`` is exactly
    level ``m``'s guarantee.  Unlike ``jacobi_wrap_step`` there is no ring
    closure, hence no replay: the grid is one step per raw plane.

    The interior lands advanced ``m`` levels; shell cells hold garbage
    (low-x planes) or their pre-step values (aliased high-x planes) — the
    caller re-exchanges before the next wavefront and marks the shell stale
    for readback, so no consumer ever observes them.

    Reference analog: the halo-multiplier idea the reference lists as future
    work (README.md:157-176 "exchange every k steps"); here it is what makes
    the multi-GPU pipeline's traffic match the single-device fast path.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    Xr, Yr, Zr = raw.shape
    zv = Zr if z_valid is None else z_valid
    s_off = m if interior_offset is None else interior_offset
    # raw must carry a shell at least m wide plus >= 1 interior cell per axis
    assert 1 <= m <= s_off and 2 * s_off < min(Xr, Yr, zv), (m, s_off, raw.shape, zv)
    assert zv <= Zr, (zv, Zr)
    gx = global_size[0]
    # the in-kernel lax.rem relies on its operand being non-negative:
    # i - s - s_off >= -2*s_off > -gx, so one added gx suffices.  Enforce the
    # precondition instead of assuming it (an x-unsharded mesh with a deep
    # explicit temporal_k could otherwise silently mis-force shell planes).
    assert 2 * s_off < gx, (s_off, gx)
    hot_x, cold_x, in_r2 = sphere_params(gx)

    roll = _make_roll(interpret)
    acc_dtype = jnp.float32 if f32_accumulate else raw.dtype
    _check_compute_unit(compute_unit, acc_dtype)
    mxu = unit_uses_mxu(compute_unit)
    if mxu:
        compute_unit = plane_band_unit(compute_unit, Yr, Zr, where="wavefront")
    nbr_sum = (
        make_plane_nbr_sum(Yr, Zr, compute_unit, mxu_input) if mxu else None
    )
    level_sum = _make_level_sum(roll, compute_unit, nbr_sum)

    def kernel(origin_ref, in_ref, d2_ref, *rest):
        if mxu:
            by_ref, bz_ref, rest = rest[0], rest[1], rest[2:]
            by, bz = by_ref[...], bz_ref[...]
        else:
            by = bz = None
        if z_slabs is not None:
            zs_ref, out_ref, zout_ref, ring = rest
        else:
            out_ref, ring = rest
        # ring[s] holds the two most recent level-s planes (level 0 = input)
        i = pl.program_id(0)
        d2v = d2_ref[...]
        vals = in_ref[0].astype(acc_dtype)  # level-0 raw plane i
        if z_slabs is not None:
            # patch the z-shell columns in VMEM — they are never stored in
            # the big array.  One small (2s, Yr) -> (Yr, 2s) transpose per
            # plane turns the z-major block into the column vectors needed.
            zst = jnp.swapaxes(zs_ref[0], 0, 1).astype(acc_dtype)  # (Yr, 2s)
            col = jax.lax.broadcasted_iota(jnp.int32, (Yr, Zr), 1)
            for j in range(s_off):
                vals = jnp.where(col == j, zst[:, j][:, None], vals)
                vals = jnp.where(
                    col == zv - s_off + j, zst[:, s_off + j][:, None], vals
                )
        for s in range(1, m + 1):
            prev = ring[s - 1, i % 2]  # level-(s-1) plane i-s-1
            cent = ring[s - 1, (i + 1) % 2]  # level-(s-1) plane i-s
            ring[s - 1, i % 2] = vals  # push plane i-s+1 (after prev read)
            val = level_sum(prev, vals, cent, by, bz) / 6.0
            # global x of level-s plane i-s (raw index -> interior-origin
            # coords; + gx keeps lax.rem's operand non-negative:
            # i-s-s_off >= -2*s_off > -gx).  Shell planes matter too: their
            # intermediate-level values feed valid higher-level cells, so
            # forcing must follow the periodic global coordinate everywhere.
            x_g = jax.lax.rem(
                origin_ref[0] + jnp.int32(gx) + i - jnp.int32(s + s_off), jnp.int32(gx)
            )

            val = jnp.where(d2v < in_r2 - (x_g - hot_x) ** 2, HOT_TEMP, val)
            val = jnp.where(d2v < in_r2 - (x_g - cold_x) ** 2, COLD_TEMP, val)
            vals = val.astype(acc_dtype)
        # level-m plane i-m; valid for interior planes.  The f32_accumulate
        # contract's ONE downcast happens at this store (and the slab emit).
        out_ref[0] = vals.astype(raw.dtype)
        if z_slabs is not None:
            # emit next macro's outgoing z slabs: my interior z-boundary
            # columns at the output level (shell planes/rows carry garbage
            # here; the caller's slab extensions overwrite them), packed
            # [(-z)-bound message | (+z)-bound message], z-major
            emit = jnp.concatenate(
                [vals[:, zv - 2 * s_off : zv - s_off], vals[:, s_off : 2 * s_off]],
                axis=1,
            ).astype(raw.dtype)  # (Yr, 2s)
            zout_ref[0] = jnp.swapaxes(emit, 0, 1)

    out_idx = lambda i: (jnp.maximum(i - m, 0), 0, 0)
    assert jnp.issubdtype(d2.dtype, jnp.integer), d2.dtype
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, Yr, Zr), lambda i: (i, 0, 0)),
        # constant index map: fetched once, stays resident in VMEM
        pl.BlockSpec((Yr, Zr), lambda i: (0, 0)),
    ]
    out_specs = pl.BlockSpec((1, Yr, Zr), out_idx)
    out_shape = jax.ShapeDtypeStruct((Xr, Yr, Zr), raw.dtype)
    args = [origin.astype(jnp.int32), raw, d2]
    if mxu:
        # resident contraction constants of the resolved variant, fetched
        # once like the d2 plane
        b_args, b_specs = band_operands(Yr, Zr, compute_unit, mxu_input)
        in_specs += b_specs
        args += b_args
    if z_slabs is not None:
        assert z_slabs.shape == (Xr, 2 * s_off, Yr), (z_slabs.shape, raw.shape)
        in_specs += [pl.BlockSpec((1, 2 * s_off, Yr), lambda i: (i, 0, 0))]
        out_specs = (
            out_specs,
            pl.BlockSpec((1, 2 * s_off, Yr), out_idx),
        )
        out_shape = (
            out_shape,
            jax.ShapeDtypeStruct((Xr, 2 * s_off, Yr), raw.dtype),
        )
        args += [z_slabs]
    return pl.pallas_call(
        kernel,
        grid=(Xr,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        # in-place: the write of plane i-m trails the fetch of plane i+1 by
        # m+1 planes, so aliasing is hazard-free; unwritten high-shell planes
        # keep their pre-step bytes
        input_output_aliases={1: 0} if alias else {},
        scratch_shapes=[pltpu.VMEM((m, 2, Yr, Zr), acc_dtype)],
        interpret=interpret,
        **_tpu_compiler_params(interpret),
    )(*args)


#: lane offset of the interior segment in the z-ring working plane; the lo
#: halo sits immediately below it, the hi halo wraps to lane 0 (see
#: jacobi_zring_wavefront_step) — must stay a multiple of 128 so the
#: staging/output slices are lane-aligned, and >= 2*s_off
_ZRING_OFF = 128


def zring_dist2_plane(origin_y, origin_z, s_off: int, shape_y: int, z_interior: int, global_size):
    """``yz_dist2_plane`` for the z-RING working layout: lanes [0, s_off)
    hold the hi halo (z = Zi..Zi+s_off), lanes [_ZRING_OFF - s_off,
    _ZRING_OFF) the lo halo, lanes [_ZRING_OFF, _ZRING_OFF + Zi) the
    interior — the linear formula covers interior+lo contiguously and one
    select fixes the wrapped hi segment (dead lanes get harmless wrapped
    values)."""
    gy, gz = global_size[1], global_size[2]
    W = _ZRING_OFF + z_interior
    y = (origin_y + jnp.arange(shape_y)) % gy
    c = jnp.arange(W)
    z_lin = origin_z + c - _ZRING_OFF
    z_hi = origin_z + z_interior + c
    z = jnp.where(c < s_off, z_hi, z_lin) % gz
    cy, cz = gy // 2, gz // 2
    return ((y - cy) ** 2)[:, None] + ((z - cz) ** 2)[None, :]


def jacobi_zring_wavefront_step(
    raw: jax.Array,  # (Xr, Yr, Zi): x/y FILLED shell carried in-array, z
    # INTERIOR-ONLY (the 20%-of-DMA z-shell/lane-pad columns are gone from
    # HBM entirely); Zi % 128 == 0
    m: int,  # levels to advance (<= the shell width)
    origin: jax.Array,  # (3,) int32 global coords of the shard's interior start
    d2: jax.Array,  # (Yr, Zi + 128) int32 from zring_dist2_plane
    global_size: Tuple[int, int, int],
    z_slabs: jax.Array,  # (Xr, 2s, Yr) z-major: rows [0, s) = my lo halo,
    # [s, 2s) = my hi halo (same convention as jacobi_shell_wavefront_step)
    interior_offset: int = None,
    alias: bool = False,
    interpret: bool = False,
    compute_unit: str = "vpu",  # "mxu" = banded in-plane contraction over
    # the RING-layout working plane (the circulant wrap of band_matrix is
    # exactly the ring seam's lane wrap); ≤1 ulp/level vs "vpu";
    # "mxu_band" = its blocked form (the block-granular wrap of the tiled
    # z contraction is the same ring seam)
    f32_accumulate: bool = False,  # bf16-storage variant (see
    # jacobi_shell_wavefront_step)
    mxu_input: str = "f32",  # MXU operand precision (see jacobi_wrap_step)
):
    """``m`` Jacobi levels per pass with the z halo in a RING-layout VMEM
    working plane — the deep-wavefront path that streams NO z padding.

    probe24: at 512^3 m=16 the macro is ~82% kernel pass, and the pass costs
    exactly the wrap kernel x the padded-array ratio (544^2 x 640 / 512^3 =
    1.41).  The z share of that ratio is pure waste: in z-slab mode the
    in-array z-shell columns are never read (the kernel patches halos from
    the slab buffers), yet they force either ragged-lane DMA (~30% slower,
    probe22) or 640-wide lane padding.  Here HBM stores only the Zi
    interior columns; each streamed (Yr, Zi) plane is staged into a
    (Yr, Zi + 128) working plane at lane offset 128 whose LANE WRAP is
    periodic-consistent by construction:

        lanes [0, s)            hi halo  (z = Zi .. Zi+s)
        lanes [s, 128 - s)      dead
        lanes [128 - s, 128)    lo halo  (z = -s .. 0)
        lanes [128, 128 + Zi)   interior (z = c - 128)

    ``roll(plane, -1)`` brings lane 0 (hi halo z=Zi) to lane 127+Zi
    (interior z=Zi-1) — its true +z neighbor; ``roll(plane, +1)`` brings
    lane 127 (lo halo z=-1) to lane 128 (interior z=0).  Both seams are
    neighbor-correct, the hi/lo outermost halo lanes border dead lanes and
    are valid only at level 0 — exactly the shrinking-validity contract —
    and every staging/output slice sits at a 128-aligned lane offset.
    Returns ``(out, z_out)`` with the same z_out convention as the
    shell-layout kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    Xr, Yr, Zi = raw.shape
    s_off = m if interior_offset is None else interior_offset
    OFF = _ZRING_OFF
    W = OFF + Zi
    assert Zi % 128 == 0 and 2 * s_off <= OFF, (Zi, s_off)
    assert 1 <= m <= s_off and 2 * s_off < min(Xr, Yr), (m, s_off, raw.shape)
    gx = global_size[0]
    assert 2 * s_off < gx, (s_off, gx)
    assert d2.shape == (Yr, W) and jnp.issubdtype(d2.dtype, jnp.integer), d2.shape
    assert z_slabs.shape == (Xr, 2 * s_off, Yr), (z_slabs.shape, raw.shape)
    hot_x, cold_x, in_r2 = sphere_params(gx)
    roll = _make_roll(interpret)
    acc_dtype = jnp.float32 if f32_accumulate else raw.dtype
    _check_compute_unit(compute_unit, acc_dtype)
    mxu = unit_uses_mxu(compute_unit)
    if mxu:
        # the contraction spans the WORKING plane width W: its wrap at
        # lanes 0/W-1 is exactly the ring layout's periodic-consistent seam
        compute_unit = plane_band_unit(compute_unit, Yr, W, where="zring")
    nbr_sum = (
        make_plane_nbr_sum(Yr, W, compute_unit, mxu_input) if mxu else None
    )
    level_sum = _make_level_sum(roll, compute_unit, nbr_sum)

    def kernel(origin_ref, in_ref, d2_ref, zs_ref, *rest):
        if mxu:
            by_ref, bz_ref, out_ref, zout_ref, ring = rest
            by, bz = by_ref[...], bz_ref[...]
        else:
            out_ref, zout_ref, ring = rest
            by = bz = None
        i = pl.program_id(0)
        d2v = d2_ref[...]
        # stage the interior plane at lane offset OFF and patch the halo
        # segments from the slab block (one small transpose per plane)
        vals = jnp.pad(in_ref[0].astype(acc_dtype), ((0, 0), (OFF, 0)))
        zst = jnp.swapaxes(zs_ref[0], 0, 1).astype(acc_dtype)  # (Yr, 2s)
        col = jax.lax.broadcasted_iota(jnp.int32, (Yr, W), 1)
        for j in range(s_off):
            vals = jnp.where(col == OFF - s_off + j, zst[:, j][:, None], vals)
            vals = jnp.where(col == j, zst[:, s_off + j][:, None], vals)
        for s in range(1, m + 1):
            prev = ring[s - 1, i % 2]
            cent = ring[s - 1, (i + 1) % 2]
            ring[s - 1, i % 2] = vals
            val = level_sum(prev, vals, cent, by, bz) / 6.0
            x_g = jax.lax.rem(
                origin_ref[0] + jnp.int32(gx) + i - jnp.int32(s + s_off), jnp.int32(gx)
            )
            val = jnp.where(d2v < in_r2 - (x_g - hot_x) ** 2, HOT_TEMP, val)
            val = jnp.where(d2v < in_r2 - (x_g - cold_x) ** 2, COLD_TEMP, val)
            vals = val.astype(acc_dtype)
        # level-m plane i-m, interior lanes (the f32_accumulate downcast)
        out_ref[0] = vals[:, OFF:].astype(raw.dtype)
        # outgoing slabs: top interior cols [Zi-s, Zi) = lanes [W-s, W)
        # (the -z-bound message), bottom cols [0, s) = lanes [OFF, OFF+s)
        emit = jnp.concatenate(
            [vals[:, W - s_off : W], vals[:, OFF : OFF + s_off]], axis=1
        ).astype(raw.dtype)
        zout_ref[0] = jnp.swapaxes(emit, 0, 1)

    out_idx = lambda i: (jnp.maximum(i - m, 0), 0, 0)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, Yr, Zi), lambda i: (i, 0, 0)),
        pl.BlockSpec((Yr, W), lambda i: (0, 0)),  # resident d2
        pl.BlockSpec((1, 2 * s_off, Yr), lambda i: (i, 0, 0)),
    ]
    args = [origin.astype(jnp.int32), raw, d2, z_slabs]
    if mxu:
        b_args, b_specs = band_operands(Yr, W, compute_unit, mxu_input)
        in_specs += b_specs
        args += b_args
    return pl.pallas_call(
        kernel,
        grid=(Xr,),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, Yr, Zi), out_idx),
            pl.BlockSpec((1, 2 * s_off, Yr), out_idx),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((Xr, Yr, Zi), raw.dtype),
            jax.ShapeDtypeStruct((Xr, 2 * s_off, Yr), raw.dtype),
        ),
        input_output_aliases={1: 0} if alias else {},
        scratch_shapes=[pltpu.VMEM((m, 2, Yr, W), acc_dtype)],
        interpret=interpret,
        **_tpu_compiler_params(interpret),
    )(*args)


def jacobi_slab_step(
    block: jax.Array,  # (X, Y, Z) bare interior — NO carried shell
    xlo: jax.Array,  # (Y, Z)  received from -x neighbor (its top plane)
    xhi: jax.Array,  # (Y, Z)  received from +x neighbor (its bottom plane)
    ylo: jax.Array,  # (X, Z)  received from -y neighbor (its top row per plane)
    yhi: jax.Array,  # (X, Z)  received from +y neighbor
    zlo: jax.Array,  # (Y, X)  received from -z neighbor, TRANSPOSED
    zhi: jax.Array,  # (Y, X)  received from +z neighbor, TRANSPOSED
    origin: jax.Array,  # (3,) int32 global coords of block start
    yz_d2: jax.Array,  # (Y, Z) int32 from yz_dist2_plane over the FULL plane
    global_size: Tuple[int, int, int],
    interpret: bool = False,
    f32_accumulate: bool = False,  # bf16-storage variant: the six-neighbor
    # mean is computed at f32 and rounded once at the store (single-level
    # kernel, so "accumulate" here is just the mean's arithmetic dtype)
) -> jax.Array:
    """One Jacobi iteration consuming received halo slabs DIRECTLY as kernel
    inputs — the multi-device fast path.

    The shell-carrying formulation pays for its generality twice per step:
    halo slabs are blended into the block (extra HBM writes + tile-local
    kernels) and the compute kernel then re-reads them as part of the
    (X+2r)-sized raw block.  Here the block is the bare interior; the six
    ppermuted face slabs ride into VMEM as small resident blocks and the
    plane-streaming kernel patches the boundary rows/columns with selects —
    one HBM read + one write per plane, zero halo writes, exactly the traffic
    of the single-device wrap kernel.  This is the TPU expression of the
    reference's overlapped multi-GPU pipeline (jacobi3d.cu:265-337): where
    the GPU hides exchange latency behind interior kernels, the TPU folds the
    received bytes into the one pass that was already reading the domain.

    Slab layouts are chosen for the TPU tiled memory model: y-slabs are
    (X, Z) 2D arrays (plane-major, lanes on z) and z-slabs arrive TRANSPOSED
    as (Y, X) (lanes on x) — a (X, Y, 1) column slab would lane-pad 128x in
    HBM and VMEM.  Per output plane the kernel reads one dynamic row/column
    from each resident slab.

    Summation order matches ``jacobi_wrap_step``/``jacobi_plane_step``:
    (x-1) + (x+1) + (y-1) + (y+1) + (z-1) + (z+1), so a mesh-[1,1,1] run
    (self-permuted slabs = periodic wrap) is bit-identical to the wrap path.
    """
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    X, Y, Z = block.shape
    # at X == 1 the i == 1 and i == X branches both fire and the second reads
    # ring[1], which is never written — shards must carry >= 2 x-planes
    assert X >= 2, f"jacobi_slab_step requires X >= 2 planes per shard, got {X}"
    gx = global_size[0]
    hot_x, cold_x, in_r2 = sphere_params(gx)

    roll = _make_roll(interpret)

    def kernel(
        origin_ref, in_ref, xlo_ref, xhi_ref, ylo_ref, yhi_ref, zlo_ref, zhi_ref,
        d2_ref, out_ref, ring,
    ):
        i = pl.program_id(0)
        cur = in_ref[0]

        def compute(prev, cent, nxt, o):
            up = roll(cent, 1, 0)
            down = roll(cent, -1, 0)
            left = roll(cent, 1, 1)
            right = roll(cent, -1, 1)
            row = lax.broadcasted_iota(jnp.int32, (Y, Z), 0)
            col = lax.broadcasted_iota(jnp.int32, (Y, Z), 1)
            # boundary rows/cols: the roll wrapped within the block; patch
            # with the neighbor's received face cells
            up = jnp.where(row == 0, ylo_ref[pl.ds(o, 1), :], up)
            down = jnp.where(row == Y - 1, yhi_ref[pl.ds(o, 1), :], down)
            # dynamic LANE slicing is not supported (lane offsets must be
            # 128-aligned); rotate column o to lane 0 and slice statically
            def zcol(ref):
                if interpret:
                    return jnp.roll(ref[...], -o, axis=1)[:, 0:1]
                return roll(ref[...], X - o, 1)[:, 0:1]

            left = jnp.where(col == 0, zcol(zlo_ref), left)
            right = jnp.where(col == Z - 1, zcol(zhi_ref), right)
            if f32_accumulate:
                prev, nxt, up, down, left, right = (
                    t.astype(jnp.float32)
                    for t in (prev, nxt, up, down, left, right)
                )
            val = (prev + nxt + up + down + left + right) / 6.0
            x_g = (origin_ref[0] + o) % gx
            d2 = d2_ref[...]
            val = jnp.where(d2 < in_r2 - (x_g - hot_x) ** 2, HOT_TEMP, val)
            val = jnp.where(d2 < in_r2 - (x_g - cold_x) ** 2, COLD_TEMP, val)
            out_ref[0] = val.astype(cur.dtype)

        @pl.when(i == 1)
        def _():
            compute(xlo_ref[...], ring[0], cur, 0)

        @pl.when(jnp.logical_and(i >= 2, i <= X - 1))
        def _():
            compute(ring[i % 2], ring[(i + 1) % 2], cur, i - 1)

        @pl.when(i == X)
        def _():
            compute(ring[i % 2], ring[(i + 1) % 2], xhi_ref[...], X - 1)

        @pl.when(i <= X - 1)
        def _():
            ring[i % 2] = cur

    const = lambda *shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape))
    return pl.pallas_call(
        kernel,
        grid=(X + 1,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, Y, Z), lambda i: (jnp.minimum(i, X - 1), 0, 0)),
            const(Y, Z),  # xlo — fetched once, resident
            const(Y, Z),  # xhi
            const(X, Z),  # ylo
            const(X, Z),  # yhi
            const(Y, X),  # zlo (transposed)
            const(Y, X),  # zhi (transposed)
            const(Y, Z),  # yz_d2
        ],
        out_specs=pl.BlockSpec((1, Y, Z), lambda i: (jnp.maximum(i - 1, 0), 0, 0)),
        out_shape=jax.ShapeDtypeStruct((X, Y, Z), block.dtype),
        scratch_shapes=[pltpu.VMEM((2, Y, Z), block.dtype)],
        interpret=interpret,
        **_tpu_compiler_params(interpret),
    )(
        origin.astype(jnp.int32),
        block,
        xlo, xhi, ylo, yhi, zlo, zhi,
        yz_d2.astype(jnp.int32),
    )


def jacobi_plane_step(
    block: jax.Array,
    origin: jax.Array,  # (3,) int32: global coords of this shard's interior start
    yz_d2: jax.Array,  # (Y-2, Z-2) int32 from yz_dist2_plane
    global_size: Tuple[int, int, int],
    interpret: bool = False,
    f32_accumulate: bool = False,  # bf16-storage variant: f32 mean, one
    # downcast at the interior store (halo ring passes through untouched)
) -> jax.Array:
    """One Jacobi iteration over a radius-1 shell-carrying block (X, Y, Z)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    X, Y, Z = block.shape
    gx = global_size[0]
    hot_x, cold_x, in_r2 = sphere_params(gx)

    def kernel(origin_ref, in_ref, d2_ref, out_ref, ring):
        i = pl.program_id(0)
        cur = in_ref[0]

        @pl.when(i == 0)
        def _():
            out_ref[0] = cur  # -x halo plane passes through

        @pl.when(jnp.logical_and(i >= 2, i <= X - 1))
        def _():
            prev = ring[i % 2]  # plane i-2
            cent = ring[(i + 1) % 2]  # plane i-1
            up = (
                (lambda v: v.astype(jnp.float32))
                if f32_accumulate
                else (lambda v: v)
            )
            mean = (
                up(prev[1:-1, 1:-1])
                + up(cur[1:-1, 1:-1])
                + up(cent[:-2, 1:-1])
                + up(cent[2:, 1:-1])
                + up(cent[1:-1, :-2])
                + up(cent[1:-1, 2:])
            ) / 6.0
            # raw plane i-1 -> interior x = i-2; sphere test per cell is just
            # a compare of the precomputed y/z distances against a scalar
            x_g = (origin_ref[0] + i - 2) % gx
            d2 = d2_ref[...]
            val = jnp.where(d2 < in_r2 - (x_g - hot_x) ** 2, HOT_TEMP, mean)
            val = jnp.where(d2 < in_r2 - (x_g - cold_x) ** 2, COLD_TEMP, val)
            out_ref[0] = cent  # keep the y/z halo ring
            out_ref[0, 1:-1, 1:-1] = val.astype(cur.dtype)

        @pl.when(i == X)
        def _():
            out_ref[0] = ring[(i + 1) % 2]  # +x halo plane (X-1) passes through

        # ring update: store the current input plane (skip the replayed last
        # plane at i == X so the ring stays consistent)
        @pl.when(i <= X - 1)
        def _():
            ring[i % 2] = cur

    return pl.pallas_call(
        kernel,
        grid=(X + 1,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, Y, Z), lambda i: (jnp.minimum(i, X - 1), 0, 0)),
            # constant index map: fetched once, stays resident in VMEM
            pl.BlockSpec((Y - 2, Z - 2), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Y, Z), lambda i: (jnp.clip(i - 1, 0, X - 1), 0, 0)),
        out_shape=jax.ShapeDtypeStruct((X, Y, Z), block.dtype),
        scratch_shapes=[pltpu.VMEM((2, Y, Z), block.dtype)],
        interpret=interpret,
        **_tpu_compiler_params(interpret),
    )(origin.astype(jnp.int32), block, yz_d2.astype(jnp.int32))
