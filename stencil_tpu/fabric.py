"""``python -m stencil_tpu.fabric`` — probe the realized mesh fabric.

Runs the point-to-point ``ppermute`` sweep (``telemetry/fabric.py``) over
every neighbor hop of the device mesh, prints the per-axis link model and
slowest-link callout, and persists the stamped matrix artifact under the
fabric cache (``STENCIL_FABRIC_CACHE``) so later runs — the comms
roofline in ``scripts/perf_report.py``, placement/tuner consumers — load
it without device work.

The mesh defaults to the repo's canonical factorization of all visible
devices (``parallel/mesh.make_mesh``); ``--grid X Y Z`` forces one.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "stencil_tpu.fabric",
        description="measure per-link fabric bandwidth over the realized "
        "device mesh (see docs/observability.md 'Fabric observatory')",
    )
    p.add_argument(
        "--grid", type=int, nargs=3, metavar=("X", "Y", "Z"), default=None,
        help="force the mesh grid (must multiply to the device count)",
    )
    p.add_argument(
        "--nbytes", type=int, default=None,
        help="bandwidth payload per shard in bytes (default: 8 MiB)",
    )
    p.add_argument(
        "--lat-nbytes", type=int, default=None, metavar="N",
        help="run a second small-payload sweep and report per-edge latency",
    )
    p.add_argument("--reps", type=int, default=3, help="timed rounds per edge")
    p.add_argument(
        "--inner", type=int, default=1, help="chained dispatches per timed round"
    )
    p.add_argument(
        "--cache", default=None, metavar="DIR",
        help="fabric cache directory (default: STENCIL_FABRIC_CACHE or "
        "~/.cache/stencil_tpu/fabric)",
    )
    p.add_argument(
        "--force", action="store_true",
        help="re-probe even when a matching cached matrix exists",
    )
    p.add_argument(
        "--out", default=None, metavar="PATH",
        help="also write the probe artifact to PATH (atomic)",
    )
    p.add_argument("--json", action="store_true", help="print the raw artifact")
    args = p.parse_args(argv)

    import numpy as np

    import jax
    from stencil_tpu.core.radius import Radius
    from stencil_tpu.parallel.mesh import make_mesh, mesh_from_grid
    from stencil_tpu.telemetry import fabric

    if args.cache is not None:
        fabric.set_dir_override(args.cache)
    devices = jax.devices()
    if args.grid is not None:
        nx, ny, nz = args.grid
        if nx * ny * nz != len(devices):
            p.error(
                f"--grid {nx}x{ny}x{nz} needs {nx * ny * nz} devices, "
                f"have {len(devices)}"
            )
        mesh = mesh_from_grid(np.array(devices).reshape(nx, ny, nz))
    else:
        # a dummy cubic domain: the probe only cares about the device grid,
        # and this is the factorization real runs get by default
        mesh, _ = make_mesh((128, 128, 128), Radius.constant(1), devices)

    kwargs = dict(
        lat_nbytes=args.lat_nbytes, reps=args.reps, inner=args.inner
    )
    if args.nbytes is not None:
        kwargs["nbytes"] = args.nbytes
    doc = fabric.ensure(mesh, force=args.force, **kwargs)

    if args.out:
        from stencil_tpu.utils.artifact import atomic_write_json

        atomic_write_json(args.out, doc)
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 0

    model = fabric.link_model(doc)
    topo = "x".join(str(v) for v in doc["topology"])
    print(
        f"fabric probe: topology {topo} on {doc['chip']} "
        f"({doc['protocol']['edges']} unique edges, {doc['seconds']:.3g}s, "
        f"nbytes {doc['nbytes']})"
    )
    for axis, sides in sorted(model["axes"].items()):
        for side in ("low", "high"):
            if side in sides:
                s = sides[side]
                print(
                    f"  {axis}.{side}: med {s['gbps_med']:.3g} GB/s, "
                    f"min {s['gbps_min']:.3g} GB/s over {s['links']} link(s)"
                )
    slow = model["slowest"]
    if slow:
        print(
            f"  slowest link: {slow['axis']}.{slow['side']} "
            f"{slow['src']}->{slow['dst']} at {slow['gbps']:.3g} GB/s"
        )
    else:
        print("  no fabric links (single-device mesh)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
