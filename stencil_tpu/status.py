"""``python -m stencil_tpu.status <dir>`` — render a run's flight-recorder
state, live or post-mortem.

Reads the ``status.json`` heartbeat and ``crash_report.json`` (both
written by ``telemetry/flight.py`` under the supervised run's directory —
usually the checkpoint dir) and prints a human summary: phase, progress,
steady-state rate, heartbeat age (a stale heartbeat on a ``running`` phase
means the process died without a word), checkpoint age, restarts, last
error, and the crash report's classified cause plus its last-events tail.

``--json`` prints the merged raw documents instead (for scripts).
jax-free and import-light: inspecting a wedged run must not wait on a
backend.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from stencil_tpu.telemetry.flight import read_crash_report, read_status


def _age(ts) -> str:
    try:
        dt = max(time.time() - float(ts), 0.0)
    except (TypeError, ValueError):
        return "?"
    if dt < 120:
        return f"{dt:.1f}s"
    if dt < 7200:
        return f"{dt / 60:.1f}m"
    return f"{dt / 3600:.1f}h"


def _fmt_stat(v) -> str:
    if v is None:
        return "-"
    return f"{v:.6g}"


def _numerics_lines(doc, indent: str = "  ") -> list:
    """Per-quantity health lines from one numerics snapshot document
    (telemetry/numerics.py ``NumericsSnapshot.as_json``)."""
    if not isinstance(doc, dict):
        return []
    lines = []
    step = doc.get("step")
    head = f"{indent}numerics"
    if step is not None:
        head += f" @ step {step}"
    win = doc.get("window")
    if isinstance(win, (list, tuple)) and len(win) == 2:
        head += f" (window {win[0]}..{win[1]}]"
    lines.append(head + ":")
    for name, st in sorted((doc.get("quantities") or {}).items()):
        if not isinstance(st, dict):
            continue
        row = (
            f"{indent}  {name}: min {_fmt_stat(st.get('min'))}, "
            f"max {_fmt_stat(st.get('max'))}, "
            f"mean {_fmt_stat(st.get('mean'))}, "
            f"l2 {_fmt_stat(st.get('l2'))}"
        )
        nbad = st.get("nonfinite") or 0
        if nbad:
            row += f", NON-FINITE x{nbad}"
            coord = st.get("first_nonfinite")
            if isinstance(coord, (list, tuple)):
                row += f" (first at global {tuple(coord)})"
        else:
            row += ", finite"
        lines.append(row)
    return lines


def _tenant_lines(rows, indent: str = "  ") -> list:
    """The serving heartbeat's tenant table (serve/server.py
    ``tenant_table`` rows): one aligned line per tenant — state, envelope
    rung, request counters, and the per-tenant latency percentiles."""
    if not isinstance(rows, list) or not rows:
        return []
    lines = [indent + "tenants:"]
    for row in rows:
        if not isinstance(row, dict):
            continue
        pcts = "/".join(
            _fmt_stat(row.get(k)) for k in ("p50_ms", "p95_ms", "p99_ms")
        )
        line = (
            f"{indent}  {row.get('tenant', '?')}: {row.get('state', '?')}"
            f" prio {row.get('priority', 0)}, rung {row.get('rung', 0)},"
            f" {row.get('completed', 0)}/{row.get('admitted', 0)} done,"
            f" shed {row.get('shed', 0)}, retries {row.get('retries', 0)},"
            f" p50/p95/p99 {pcts} ms"
        )
        if row.get("why"):
            line += f" [{row['why']}]"
        lines.append(line)
    return lines


def _fabric_lines(doc, indent: str = "  ") -> list:
    """The fabric observatory's heartbeat state (``telemetry/fabric.summary``
    shape): per-axis median link bandwidth, the slowest-link callout, and
    the per-neighbor matrix (rows = sending flat device index)."""
    if not isinstance(doc, dict):
        return []
    topo = "x".join(str(v) for v in (doc.get("topology") or [])) or "?"
    lines = [f"{indent}fabric (topology {topo}, {doc.get('chip', '?')}):"]
    for axis, sides in sorted((doc.get("axes") or {}).items()):
        if not isinstance(sides, dict):
            continue
        per = ", ".join(
            f"{side} {_fmt_stat(sides.get(side))} GB/s"
            for side in ("low", "high")
            if side in sides
        )
        lines.append(f"{indent}  axis {axis}: {per}")
    slow = doc.get("slowest")
    if isinstance(slow, dict):
        lines.append(
            f"{indent}  slowest link: {slow.get('axis')}.{slow.get('side')} "
            f"{slow.get('src')}->{slow.get('dst')} at "
            f"{_fmt_stat(slow.get('gbps'))} GB/s"
        )
    matrix = doc.get("matrix")
    if isinstance(matrix, list) and matrix and len(matrix) <= 16:
        lines.append(f"{indent}  link matrix (GB/s):")
        for row in matrix:
            cells = " ".join(
                f"{v:7.2f}" if isinstance(v, (int, float)) and v else "      ."
                for v in row
            )
            lines.append(f"{indent}    {cells}")
    return lines


def render(status, crash, stale_after: float = 300.0) -> str:
    """The human view of one run directory's flight state."""
    lines = []
    if status is None and crash is None:
        return "no flight-recorder state found (no status.json / crash_report.json)"
    if status is not None:
        phase = status.get("phase", "?")
        ts = status.get("ts")
        stale = (
            phase == "running"
            and isinstance(ts, (int, float))
            and time.time() - ts > stale_after
        )
        total = status.get("total_steps")
        prog = f"{status.get('step')}/{total}" if total else str(status.get("step"))
        rate = status.get("rate_steps_per_s")
        lines.append(
            f"run '{status.get('label')}' [{phase}]"
            + (" — heartbeat STALE (process likely dead)" if stale else "")
        )
        lines.append(
            f"  step {prog}"
            + (f" @ {rate:.3g} steps/s" if isinstance(rate, (int, float)) else "")
            + f", heartbeat {_age(ts)} ago (pid {status.get('pid')})"
        )
        extras = []
        for key, label in (
            ("checkpoint_age_s", "checkpoint age"),
            ("restarts", "restarts"),
            ("ladder_rung", "ladder rung"),
            ("watchdog", "watchdog"),
            ("mesh", "mesh"),
            ("mesh_transitions", "mesh transitions"),
            ("queue_depth", "queue depth"),
        ):
            if status.get(key) is not None:
                val = status[key]
                if key == "checkpoint_age_s":
                    val = f"{float(val):.1f}s"
                if key == "mesh" and isinstance(val, list):
                    val = "x".join(str(v) for v in val)
                extras.append(f"{label} {val}")
        if extras:
            lines.append("  " + ", ".join(extras))
        # elastic-capacity breadcrumbs: the last few grow/shrink moves
        # (in-memory reshards and restore fallbacks), live or post-mortem
        history = status.get("mesh_history") or []
        for t in history[-5:]:
            frm = "x".join(str(v) for v in (t.get("from") or [])) or "?"
            to = "x".join(str(v) for v in (t.get("to") or [])) or "?"
            lines.append(
                f"  mesh {t.get('kind', '?')} at step {t.get('step')}: "
                f"{frm} -> {to} in {t.get('seconds')}s ({t.get('source')})"
            )
        # numerics observatory: the heartbeat's last per-quantity health
        # snapshot (docs/observability.md "Numerics observatory")
        lines.extend(_numerics_lines(status.get("numerics")))
        # serving heartbeats carry the per-tenant table (docs/serving.md)
        lines.extend(_tenant_lines(status.get("tenants")))
        # fabric observatory: the probed link model the run started under
        # (docs/observability.md "Fabric observatory")
        lines.extend(_fabric_lines(status.get("fabric")))
        if status.get("last_error"):
            lines.append(f"  last error: {status['last_error']}")
    if crash is not None:
        lines.append(
            f"crash report [{crash.get('cause')}] at {_age(crash.get('ts'))} ago"
        )
        if crash.get("error"):
            lines.append(f"  error: {crash['error']}")
        # the numerics snapshot ring: on a DIVERGENCE exit this is the
        # field-health history leading up to the trip — render the final
        # snapshot in full, and say how much history the report carries
        ring = crash.get("numerics_ring") or []
        if ring:
            lines.append(f"  numerics ring: {len(ring)} snapshot(s); last:")
            lines.extend(_numerics_lines(ring[-1], indent="    "))
        events = crash.get("events") or []
        if events:
            lines.append(f"  last {len(events)} events:")
            for e in events[-10:]:
                fields = {
                    k: v for k, v in e.items() if k not in ("ts", "event")
                }
                lines.append(f"    {e.get('event')}: {fields}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "stencil_tpu.status",
        description="render a supervised run's flight-recorder state "
        "(see docs/observability.md 'Flight recorder')",
    )
    p.add_argument("dir", help="run directory holding status.json / crash_report.json")
    p.add_argument("--json", action="store_true", help="print the raw documents")
    p.add_argument(
        "--stale-after",
        type=float,
        default=300.0,
        metavar="S",
        help="seconds after which a 'running' heartbeat is reported stale",
    )
    args = p.parse_args(argv)
    status = read_status(args.dir)
    crash = read_crash_report(args.dir)
    if args.json:
        print(json.dumps({"status": status, "crash_report": crash}, indent=2))
    else:
        print(render(status, crash, stale_after=args.stale_after))
    return 0 if (status is not None or crash is not None) else 1


if __name__ == "__main__":
    sys.exit(main())
