"""Serving vocabulary: tenants, requests, responses, admission refusals.

A *tenant* is one independent simulation domain sharing the fleet with
others; a *request* asks the serving layer to advance that tenant's model
by ``steps`` raw iterations before ``deadline_s`` on the server's clock.
Everything here is plain data — the policy lives in ``server.py`` — except
``AdmissionRefused``, which carries its taxonomy class so callers handle a
refusal exactly like any other classified failure (``docs/serving.md``).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Optional

from stencil_tpu.resilience.taxonomy import FailureClass, ResilienceError


class AdmissionRefused(ResilienceError):
    """A request was refused AT ADMISSION (before any execution): the
    static VMEM verdict failed, the tenant is quarantined/evicted, or a
    cold workload key could not be made warm.  Carries the refusing
    ``failure_class`` per instance — a VMEM verdict refusal classifies
    VMEM_OOM (degradable: re-submit a shallower plan), an evicted-tenant
    refusal FATAL (re-submitting changes nothing).  Load refusals raise
    ``OverloadError`` instead (retryable after backoff)."""

    def __init__(self, why: str, failure_class: FailureClass, tenant: str = None):
        self.why = why
        self.failure_class = failure_class
        self.tenant = tenant
        msg = f"admission refused: {why}"
        if tenant is not None:
            msg = f"admission refused for tenant {tenant}: {why}"
        super().__init__(msg)


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's standing contract with the server.

    ``priority`` orders dispatch and shedding (HIGHER wins a slot and
    survives a make-room shed); ``retry_allowance`` seeds the tenant's
    shared ``RetryBudget``; ``max_rungs`` bounds how many degradation
    descents the envelope tolerates before the tenant is quarantined."""

    tenant_id: str
    priority: int = 0
    retry_allowance: int = 8
    max_rungs: int = 3
    #: optional stream-plan dict for the static VMEM verdict at admission
    #: (``analysis.check_vmem``); None skips the check (non-stream routes)
    plan: Optional[dict] = None


#: server-wide admission order (tie-break within a priority level: FIFO)
_seq = itertools.count()


@dataclasses.dataclass
class Request:
    """One unit of admitted work: advance ``tenant``'s model by ``steps``."""

    tenant: str
    steps: int = 1
    #: ABSOLUTE deadline on the server's (injectable) clock; None = no
    #: deadline (never shed for lateness, still sheddable for priority)
    deadline_s: Optional[float] = None
    priority: int = 0
    #: workload-key digest (tune/key.py) when the request names one — the
    #: AOT-cache lookup key; None inherits the tenant's realized workload
    key_digest: Optional[str] = None
    enqueued_at: float = 0.0
    seq: int = dataclasses.field(default_factory=lambda: next(_seq))

    def expired(self, now: float) -> bool:
        return self.deadline_s is not None and now > self.deadline_s


@dataclasses.dataclass
class Response:
    """The outcome the server hands back for one request."""

    request: Request
    ok: bool
    latency_s: float = 0.0
    steps_done: int = 0
    error: Optional[str] = None
    failure_class: Optional[str] = None
