"""AOT executable cache: admission-time compiles, bounded and pre-warmed.

Admission control must answer "is this workload key WARM?" without running
anything: a warm key dispatches immediately; a cold key pays a
``jax.jit(...).lower().compile()`` at admission, bounded by the admission
budget (``compile_budget_s``) so one tenant's exotic workload cannot park
the dispatch loop behind an unbounded compile.  A cold compile that blows
the budget is STILL kept — the work is done, discarding it would re-pay it
— but the triggering request is refused with a classified, retryable
``OverloadError(compile_budget)``: its re-submission hits the now-warm key
and admits instantly, and every other tenant saw one bounded stall instead
of an open-ended one.

Two warmth layers (docs/serving.md "Admission"):

* **in-process** — the compiled executable itself, keyed by
  ``tune/key.py`` ``WorkloadKey.digest()``;
* **cross-process** — a JSON stamp per digest (tune/cache.py's schema +
  toolchain-stamp pattern: corrupt/stale = miss, never a crash) recording
  that this key compiled before.  A stamped key re-compiles WITHOUT the
  budget refusal on a server restart: ``STENCIL_COMPILE_CACHE_DIR`` (the
  persistent XLA executable cache, applied at package import) makes that
  rebuild a cache read, so treating it as warm is honest — and when the
  XLA cache was wiped the stamp's recorded seconds tell admission what the
  rebuild will really cost.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Optional

from stencil_tpu import telemetry
from stencil_tpu.resilience.taxonomy import OverloadError
from stencil_tpu.telemetry import names as tm

#: bump when the stamp vocabulary changes incompatibly (tune/cache.py SCHEMA
#: convention: a mismatch is a MISS, never a crash)
SCHEMA = 1


def _toolchain():
    import jax

    try:
        import jaxlib

        jaxlib_v = getattr(jaxlib, "__version__", "")
    except Exception:  # noqa: BLE001 — jaxlib layout varies across builds
        jaxlib_v = ""
    return jax.__version__, jaxlib_v


def default_stamp_dir() -> Optional[str]:
    """``<STENCIL_COMPILE_CACHE_DIR>/serve_aot`` when the persistent XLA
    cache is configured (the stamps describe ITS contents, so they live
    beside it), else None — in-process warmth only."""
    from stencil_tpu.utils.config import env_str

    root = env_str("STENCIL_COMPILE_CACHE_DIR", None)
    if root is None:
        return None
    return os.path.join(os.path.abspath(os.path.expanduser(root)), "serve_aot")


class AOTCache:
    """Compiled executables by workload-key digest, with persisted warmth
    stamps.  ``clock`` is injectable (fake-clock tests measure compiles
    without sleeping)."""

    def __init__(self, stamp_dir: Optional[str] = None, clock: Callable[[], float] = time.monotonic):
        self._exec: dict = {}
        self._stamps: dict = {}
        self.clock = clock
        self.stamp_dir = stamp_dir if stamp_dir is not None else default_stamp_dir()
        if self.stamp_dir:
            self._load_stamps()

    # --- warmth ---------------------------------------------------------------

    def warm(self, digest: str) -> bool:
        """True when the executable is resident in THIS process."""
        return digest in self._exec

    def stamped(self, digest: str) -> bool:
        """True when a previous process compiled this key on this
        toolchain (re-compiling it is a persistent-XLA-cache read, not a
        fresh compile — admission treats it as warm)."""
        return digest in self._stamps

    def get(self, digest: str):
        return self._exec.get(digest)

    # --- compile --------------------------------------------------------------

    def compile(
        self,
        digest: str,
        build: Callable[[], object],
        budget_s: Optional[float] = None,
        label: str = "serve",
        key_doc: Optional[dict] = None,
    ):
        """Build (``jax.jit(...).lower().compile()`` inside ``build``),
        cache, and stamp the executable for ``digest``.  Raises a
        retryable ``OverloadError(compile_budget)`` when the measured
        compile exceeded ``budget_s`` AND the key was not stamped warm by
        a previous process — AFTER caching, so the refusal can never
        repeat for this key."""
        t0 = self.clock()
        exe = build()
        seconds = self.clock() - t0
        telemetry.observe(tm.SERVE_COMPILE_SECONDS, seconds)
        self._exec[digest] = exe
        was_stamped = self.stamped(digest)
        self._store_stamp(digest, seconds, key_doc)
        if budget_s is not None and seconds > budget_s and not was_stamped:
            raise OverloadError(
                why="compile_budget",
                tenant=label,
                # the key is warm NOW: an immediate re-submission admits
                retry_after_s=0.0,
            )
        return exe, seconds

    # --- persisted stamps (tune/cache.py pattern) -----------------------------

    def _stamp_path(self, digest: str) -> str:
        return os.path.join(self.stamp_dir, f"{digest}.json")

    def _load_stamps(self) -> None:
        try:
            entries = os.listdir(self.stamp_dir)
        except OSError:
            return  # absent dir = cold cache
        jax_v, jaxlib_v = _toolchain()
        for name in entries:
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.stamp_dir, name)) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError, UnicodeDecodeError):
                continue  # corrupt stamp = miss, never a crash
            if (
                not isinstance(doc, dict)
                or doc.get("schema") != SCHEMA
                or doc.get("jax") != jax_v
                or doc.get("jaxlib") != jaxlib_v
            ):
                continue  # stale toolchain: the XLA cache entry is too
            self._stamps[name[: -len(".json")]] = doc

    def _store_stamp(self, digest: str, seconds: float, key_doc: Optional[dict]) -> None:
        doc = {"schema": SCHEMA, "seconds": seconds, "key": key_doc or {}}
        jax_v, jaxlib_v = _toolchain()
        doc["jax"], doc["jaxlib"] = jax_v, jaxlib_v
        self._stamps[digest] = doc
        if not self.stamp_dir:
            return
        try:
            from stencil_tpu.utils.artifact import atomic_write_json

            atomic_write_json(self._stamp_path(digest), doc)
        except OSError as e:
            from stencil_tpu.utils.logging import log_warn

            log_warn(
                f"serve AOT stamp for {digest} not persisted ({e}); "
                "the key stays warm in-process only"
            )
