"""Multi-tenant serving layer: admission control, tenant fault isolation,
overload shedding, load-driven elasticity.

Public surface (docs/serving.md):

* :class:`TenantSpec` / :class:`Request` / :class:`Response` — the
  request/response vocabulary (``request.py``);
* :class:`AdmissionRefused` — the classified admission refusal;
* :class:`BoundedQueue` — the deadline-propagating admission queue;
* :class:`AOTCache` — warm executables by ``tune/key.py`` digest;
* :class:`Tenant` — the per-tenant resilience envelope;
* :class:`ElasticityPolicy` — queue depth -> grow/shrink with hysteresis;
* :class:`StencilServer` — the serving loop tying them together;
* ``pack`` — the throughput packers: the geometry-matched batch planner
  (``plan_batches`` / :class:`BatchExecutor`) and the fabric-scored
  sub-slice bin-packer (``plan_subslices`` / ``place_subslices``).

The driver is ``python -m stencil_tpu.bin.stencil_serve`` (synthetic load
generator included); the serving chaos soak is ``scripts/run_soak.py
--serve``.
"""

from stencil_tpu.serve import pack
from stencil_tpu.serve.aot import AOTCache
from stencil_tpu.serve.policy import ElasticityPolicy
from stencil_tpu.serve.queue import BoundedQueue
from stencil_tpu.serve.request import (
    AdmissionRefused,
    Request,
    Response,
    TenantSpec,
)
from stencil_tpu.serve.server import StencilServer
from stencil_tpu.serve.tenant import ACTIVE, EVICTED, QUARANTINED, Tenant

__all__ = [
    "ACTIVE",
    "AOTCache",
    "AdmissionRefused",
    "BoundedQueue",
    "ElasticityPolicy",
    "EVICTED",
    "QUARANTINED",
    "Request",
    "Response",
    "StencilServer",
    "Tenant",
    "TenantSpec",
    "pack",
]
