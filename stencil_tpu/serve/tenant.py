"""The per-tenant resilience envelope: fault isolation as a state machine.

Every classified failure of a tenant's request is answered INSIDE that
tenant's envelope — the whole point of the serving layer's isolation
contract (chaos-proven by ``scripts/run_soak.py --serve``):

* VMEM_OOM / COMPILE_REJECT — step down THAT tenant's degradation rung
  (``model.step_down`` when the model exposes one — Jacobi3D's ladder —
  else just the recorded rung); past ``max_rungs`` descents the tenant is
  quarantined instead of thrashing the fleet with doomed rebuilds.
* DIVERGENCE — quarantine/evict ONLY this tenant: its numerics are broken
  (a poisoned request), and no amount of re-running or degrading fixes
  arithmetic.  Other tenants' fields stay bitwise untouched.
* TRANSIENT_RUNTIME — retried in place by the dispatch wrapper, charged to
  this tenant's shared ``RetryBudget`` (``resilience/retry.py``) so one
  flaky tenant cannot monopolize dispatch slots with endless retries.
* PREEMPTED / STALL / CAPACITY_LOSS / FATAL — not a tenant-local matter:
  the envelope reports ``"propagate"`` and the server/supervisor layer
  owns the response.

The tenant also carries its own latency ``Statistics`` — the p50/p95/p99
the heartbeat tenant table and the serve soak artifact report per tenant.
"""

from __future__ import annotations

from typing import Optional

from stencil_tpu.resilience.retry import RetryBudget
from stencil_tpu.resilience.taxonomy import FailureClass
from stencil_tpu.serve.request import TenantSpec
from stencil_tpu.utils.statistics import Statistics

#: envelope states
ACTIVE = "active"
QUARANTINED = "quarantined"
EVICTED = "evicted"


class Tenant:
    """One admitted tenant: spec + model + envelope state + SLO stats."""

    def __init__(self, spec: TenantSpec, model=None):
        self.spec = spec
        self.model = model
        self.state = ACTIVE
        self.rung = 0  # degradation descents the envelope has answered
        self.budget = RetryBudget(spec.retry_allowance, label=spec.tenant_id)
        self.latency = Statistics()
        self.admitted = 0
        self.completed = 0
        self.shed = 0
        self.retries = 0
        self.why: Optional[str] = None  # quarantine/evict reason

    @property
    def tenant_id(self) -> str:
        return self.spec.tenant_id

    def active(self) -> bool:
        return self.state == ACTIVE

    # --- the envelope ---------------------------------------------------------

    def handle_failure(self, cls: FailureClass, error: str = "") -> str:
        """Answer a classified failure of THIS tenant's request; returns
        the action taken: ``degrade`` | ``evict`` | ``retry_exhausted`` |
        ``propagate``.  Never touches any other tenant's state."""
        if cls in (FailureClass.VMEM_OOM, FailureClass.COMPILE_REJECT):
            self.rung += 1
            # Jacobi3D exposes its runtime descent as ``_step_down(cls) ->
            # bool`` (False = nothing shallower); models without one just
            # get the rung counted against max_rungs
            step_down = getattr(self.model, "step_down", None) or getattr(
                self.model, "_step_down", None
            )
            if callable(step_down):
                try:
                    descended = step_down(cls)
                except Exception:  # noqa: BLE001 — a raising descent means
                    # the ladder is broken, not just exhausted
                    descended = False
                if descended is False:
                    self.quarantine(f"ladder exhausted after {cls.value}")
                    return "evict"
            if self.rung > self.spec.max_rungs:
                self.quarantine(f"{self.rung} descents exceed max_rungs")
                return "evict"
            return "degrade"
        if cls is FailureClass.DIVERGENCE:
            self.quarantine(error or "divergence")
            return "evict"
        if cls is FailureClass.TRANSIENT_RUNTIME:
            # the in-place retries already ran (and were charged to
            # self.budget) inside the dispatch wrapper; reaching the
            # envelope means they exhausted
            return "retry_exhausted"
        return "propagate"

    def quarantine(self, why: str) -> None:
        self.state = QUARANTINED
        self.why = why

    def evict(self, why: str) -> None:
        self.state = EVICTED
        self.why = why

    # --- reporting ------------------------------------------------------------

    def percentile_ms(self, q: float) -> Optional[float]:
        if self.latency.count() == 0:
            return None
        return self.latency.quantile(q) * 1e3

    def table_row(self) -> dict:
        """The heartbeat/status tenant-table entry (JSON-safe scalars)."""
        row = {
            "tenant": self.tenant_id,
            "state": self.state,
            "priority": self.spec.priority,
            "rung": self.rung,
            "admitted": self.admitted,
            "completed": self.completed,
            "shed": self.shed,
            "retries": self.retries,
            "budget_remaining": self.budget.remaining,
        }
        for name, q in (("p50_ms", 0.50), ("p95_ms", 0.95), ("p99_ms", 0.99)):
            v = self.percentile_ms(q)
            row[name] = round(v, 3) if v is not None else None
        if self.why:
            row["why"] = self.why
        return row
