"""The serving loop: admission, fair dispatch, per-tenant envelopes,
shedding, elasticity.

One ``StencilServer`` owns many tenants on one fleet.  The life of a
request (docs/serving.md):

1. ``submit`` — ADMISSION: the tenant must be active, the static VMEM
   verdict (``analysis.check_vmem``) must pass for the tenant's declared
   plan, the request's workload key must be warm in the AOT cache (or
   compile under the admission budget), and the bounded queue must yield a
   slot (shedding expired and, for a higher-priority arrival, the lowest-
   priority queued request first).  Refusals are CLASSIFIED: load refusals
   are ``OverloadError`` (retryable after backoff), verdict refusals are
   ``AdmissionRefused`` carrying VMEM_OOM (degradable: re-submit a
   shallower plan), evicted-tenant refusals are FATAL.
2. ``cycle`` — DISPATCH: shed whatever expired while queued, then serve
   the oldest request of the next tenant in round-robin rotation, retries
   charged to that tenant's shared budget, every classified failure
   answered inside that tenant's envelope (``tenant.py``) — no failure of
   tenant A ever touches tenant B's state or fields.
3. after every cycle the elasticity policy observes the queue depth; a
   grow/shrink decision routes through ``capacity`` (the supervisor's
   coalescing ``request_capacity``, or a direct reshard callback) — the
   server never touches a mesh itself.

The clock and sleep are injectable so the tier-1 twins drive deadlines,
backoff, and slow-tenant penalties with a fake clock and zero real sleeps.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Dict, List, Optional

from stencil_tpu import telemetry
from stencil_tpu.resilience import inject
from stencil_tpu.resilience.retry import RetryPolicy, execute_with_retry
from stencil_tpu.resilience.taxonomy import (
    FailureClass,
    OverloadError,
    classify,
)
from stencil_tpu.serve import pack
from stencil_tpu.serve.aot import AOTCache
from stencil_tpu.serve.queue import BoundedQueue
from stencil_tpu.serve.request import AdmissionRefused, Request, Response, TenantSpec
from stencil_tpu.serve.tenant import Tenant
from stencil_tpu.telemetry import names as tm
from stencil_tpu.utils.logging import log_info, log_warn


class StencilServer:
    """Admission + fair dispatch + isolation envelopes over one fleet."""

    def __init__(
        self,
        queue_max: int = 64,
        default_deadline_s: Optional[float] = None,
        compile_budget_s: Optional[float] = None,
        policy=None,
        capacity: Optional[Callable[[str], None]] = None,
        aot: Optional[AOTCache] = None,
        retry_policy: Optional[RetryPolicy] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
        flight=None,
        slow_penalty_s: float = 0.25,
        batch_max: int = 0,
        subslice: bool = False,
        fleet=None,
        link_model=None,
    ):
        self.tenants: Dict[str, Tenant] = {}
        self.queue = BoundedQueue(queue_max)
        self.default_deadline_s = default_deadline_s
        self.compile_budget_s = compile_budget_s
        self.policy = policy
        self.capacity = capacity
        self.aot = aot if aot is not None else AOTCache(clock=clock)
        self.retry_policy = retry_policy
        self.clock = clock
        self.sleep = sleep
        self.rng = rng
        self.flight = flight
        self.slow_penalty_s = slow_penalty_s
        # throughput packing (docs/serving.md "Throughput: batching and
        # sub-slice packing"): batch_max >= 2 turns batched dispatch on;
        # subslice turns the bin-packer on; fleet pins the device pool
        # (derived from the tenants' meshes when None); link_model is the
        # measured fabric doc (or devices -> doc callable) the packer
        # scores slices against
        self.batch_max = int(batch_max)
        self.subslice = bool(subslice)
        self.fleet = list(fleet) if fleet is not None else None
        self.link_model = link_model
        self._batch_exec = pack.BatchExecutor()
        self._rotation: List[str] = []
        self._builders: Dict[str, Callable] = {}
        self._slow_pending = False
        self._completed_total = 0
        self._prev_slow_handler = inject.set_slow_handler(self._on_slow)

    def close(self) -> None:
        """Restore the previous slow-tenant hook (pair with construction)."""
        inject.set_slow_handler(self._prev_slow_handler)

    # --- tenants --------------------------------------------------------------

    def add_tenant(self, spec: TenantSpec, model=None) -> Tenant:
        if spec.tenant_id in self.tenants:
            raise ValueError(f"tenant {spec.tenant_id!r} already registered")
        t = Tenant(spec, model)
        self.tenants[spec.tenant_id] = t
        self._rotation.append(spec.tenant_id)
        self._gauge_tenants()
        return t

    def register_workload(self, digest: str, build: Callable[[], object]) -> None:
        """Associate an AOT build (``jax.jit(...).lower().compile()``
        inside) with a workload-key digest so admission can warm it."""
        self._builders[digest] = build

    def _gauge_tenants(self) -> None:
        telemetry.set_gauge(
            tm.SERVE_TENANTS_ACTIVE,
            sum(1 for t in self.tenants.values() if t.active()),
        )

    # --- admission ------------------------------------------------------------

    def submit(self, req: Request) -> None:
        """Admit ``req`` into the queue or raise a classified refusal."""
        now = self.clock()
        tenant = self.tenants.get(req.tenant)
        if tenant is None:
            self._reject(req, "unknown tenant", "fatal")
            raise AdmissionRefused(
                "unknown tenant", FailureClass.FATAL, tenant=req.tenant
            )
        if not tenant.active():
            why = f"tenant is {tenant.state} ({tenant.why})"
            self._reject(req, why, "fatal")
            raise AdmissionRefused(why, FailureClass.FATAL, tenant=req.tenant)
        # static VMEM verdict: reject a plan the compiler would refuse
        # BEFORE it can waste a dispatch slot failing (analysis/vmem.py)
        if tenant.spec.plan is not None and getattr(tenant.model, "dd", None) is not None:
            from stencil_tpu.analysis import check_vmem

            reason = check_vmem(tenant.model.dd, tenant.spec.plan)
            if reason is not None:
                self._reject(req, reason, FailureClass.VMEM_OOM.value)
                raise AdmissionRefused(
                    reason, FailureClass.VMEM_OOM, tenant=req.tenant
                )
        compile_s = self._warm_key(req)
        if req.deadline_s is None and self.default_deadline_s is not None:
            req.deadline_s = now + self.default_deadline_s
        try:
            self.queue.push(req, now)
        except OverloadError:
            # the shed ladder: expired first, then a lower-priority victim
            # for a HIGHER-priority arrival; refuse only when neither opens
            # a slot (queue.py module docstring)
            for victim in self.queue.shed_expired(now):
                self._shed(victim, "deadline", now)
            if self.queue.full():
                victim = self.queue.shed_lowest_priority(req.priority)
                if victim is not None:
                    self._shed(victim, "priority", now)
            if self.queue.full():
                telemetry.inc(tm.SERVE_REJECTED)
                telemetry.emit_event(
                    tm.EVENT_SERVE_ADMISSION,
                    tenant=req.tenant,
                    admitted=False,
                    why="queue_full",
                    queue_depth=self.queue.depth(),
                )
                raise
            self.queue.push(req, now)
        tenant.admitted += 1
        telemetry.inc(tm.SERVE_ADMITTED)
        telemetry.set_gauge(tm.SERVE_QUEUE_DEPTH, self.queue.depth())
        telemetry.emit_event(
            tm.EVENT_SERVE_ADMISSION,
            tenant=req.tenant,
            admitted=True,
            why="ok",
            queue_depth=self.queue.depth(),
            compile_s=compile_s,
        )

    def _warm_key(self, req: Request) -> Optional[float]:
        """AOT admission: a warm key is free; a cold key with a registered
        build compiles under the admission budget (``aot.py`` owns the
        over-budget refusal).  Returns the compile seconds paid, if any."""
        digest = req.key_digest
        if digest is None or self.aot.warm(digest):
            return None
        build = self._builders.get(digest)
        if build is None:
            return None  # no AOT contract for this key; the model self-compiles
        budget = None if self.aot.stamped(digest) else self.compile_budget_s
        try:
            _, seconds = self.aot.compile(
                digest, build, budget_s=budget, label=req.tenant
            )
        except OverloadError:
            self._reject(req, "compile over budget", FailureClass.OVERLOAD.value)
            raise
        return seconds

    def _reject(self, req: Request, why: str, cls: str) -> None:
        telemetry.inc(tm.SERVE_REJECTED)
        telemetry.emit_event(
            tm.EVENT_SERVE_ADMISSION,
            tenant=req.tenant,
            admitted=False,
            why=f"{cls}: {why}"[:300],
            queue_depth=self.queue.depth(),
        )

    # --- shedding -------------------------------------------------------------

    def _shed(self, req: Request, why: str, now: float) -> Response:
        t = self.tenants.get(req.tenant)
        if t is not None:
            t.shed += 1
        telemetry.inc(tm.SERVE_SHED)
        telemetry.emit_event(
            tm.EVENT_SERVE_SHED,
            tenant=req.tenant,
            why=why,
            queue_depth=self.queue.depth(),
            waited_s=max(0.0, now - req.enqueued_at),
        )
        log_warn(f"serve: shed {req.tenant} request (why={why})")
        return Response(
            request=req,
            ok=False,
            latency_s=max(0.0, now - req.enqueued_at),
            error=f"request {why} shed",
            failure_class=FailureClass.OVERLOAD.value,
        )

    # --- dispatch -------------------------------------------------------------

    def _on_slow(self, phase: str, label: str) -> None:
        # the seeded slow_tenant notice (inject.py): inflate the CURRENT
        # request's service time by the penalty at dispatch time
        self._slow_pending = True

    def cycle(self) -> List[Response]:
        """One dispatch cycle: shed expired, then serve as much of the
        queue as one dispatch can carry — a geometry-matched BATCH, a
        sub-slice PACK, or (the default) one request — and observe the
        elasticity policy.  Returns every response produced (shed
        responses included); empty list = nothing queued."""
        now = self.clock()
        out = [self._shed(r, "deadline", now) for r in self.queue.shed_expired(now)]
        served: List[str] = []
        plan = self._plan_packed()
        if plan is not None:
            kind, payload = plan
            if kind == "batched":
                out.extend(self._dispatch_batched(payload))
                served = [r.tenant for r in payload]
            else:
                out.extend(self._dispatch_subslice(payload))
                served = [r.tenant for r, _m, _d in payload]
        else:
            req = self.queue.take(self._rotation)
            if req is not None:
                model = self.tenants[req.tenant].model
                self._gauge_occupancy(self._model_devices(model))
                out.append(self._dispatch(req))
                served = [req.tenant]
        # rotate AFTER serving: the served tenants go to the back, in
        # served order, so dispatch slots keep round-robin fairness
        for tid in served:
            if tid in self._rotation:
                self._rotation.remove(tid)
                self._rotation.append(tid)
        depth = self.queue.depth()
        telemetry.set_gauge(tm.SERVE_QUEUE_DEPTH, depth)
        if self.policy is not None:
            kind = self.policy.observe(depth, self.clock())
            if kind is not None:
                telemetry.emit_event(
                    tm.EVENT_SERVE_ELASTICITY,
                    kind=kind,
                    queue_depth=depth,
                    source="policy",
                )
                log_info(f"serve: elasticity policy requests {kind} (depth {depth})")
                if self.capacity is not None:
                    self.capacity(kind)
        return out

    # --- packed dispatch (serve/pack.py; docs/serving.md "Throughput") --------

    def _plan_packed(self):
        """The scheduler policy: a geometry-matched batch wins (one
        dispatch, N tenants), else a sub-slice pack of >= 2 movable
        tenants, else None (serial).  Chosen requests are claimed out of
        the queue before dispatch."""
        if self.batch_max < 2 and not self.subslice:
            return None
        pending = self.queue.peek_all()
        if len(pending) < 2:
            return None
        if self.batch_max >= 2:
            group = pack.plan_batches(
                pending, self.tenants, self._rotation, self.batch_max
            )
            if group:
                claimed = [r for r in group if self.queue.remove(r)]
                if len(claimed) >= 2:
                    return ("batched", claimed)
                for r in claimed:  # unreachable in the single-threaded loop
                    self.queue.push(r, self.clock())
        if self.subslice:
            cands = pack.plan_subslice_candidates(
                pending, self.tenants, self._rotation
            )
            if cands:
                fleet = self._fleet_devices()
                assignments = pack.plan_subslices(
                    [(r, self.tenants[r.tenant].model) for r in cands],
                    fleet,
                    self.link_model,
                )
                if assignments:
                    claimed = [
                        a for a in assignments if self.queue.remove(a[0])
                    ]
                    if len(claimed) >= 2:
                        return ("subslice", claimed)
                    for a in claimed:  # unreachable, as above
                        self.queue.push(a[0], self.clock())
        return None

    def _probe_envelope(self, req: Request):
        """Fire exactly the injected-fault surface a serial dispatch of
        ``req`` would fire (dispatch hook, then the execute hook under the
        retry policy, charged to the tenant's budget) WITHOUT running the
        model — the batched path consumes each member's envelope up front
        so a seeded fault against one tenant of a batch surfaces before
        any state is installed.  Returns (attempts, error-or-None)."""
        tenant = self.tenants[req.tenant]
        label = f"serve:{req.tenant}"
        attempts = [0]

        def probe():
            attempts[0] += 1
            inject.maybe_fail("execute", label)

        try:
            inject.maybe_fail("dispatch", label)
            execute_with_retry(
                probe,
                label=label,
                policy=self.retry_policy,
                budget=tenant.budget,
                sleep=self.sleep,
                rng=self.rng,
            )
        except Exception as e:  # noqa: BLE001 — classified by the caller
            return attempts[0], e
        tenant.retries += max(0, attempts[0] - 1)
        return attempts[0], None

    def _dispatch_batched(self, reqs: List[Request]) -> List[Response]:
        """ONE dispatch for a geometry-matched group: per-member fault
        envelopes fire first (in queue order); then the stacked states run
        as one batched program and slice back out.  ANY classified
        failure — a member's envelope or the batched execution itself —
        falls the group back to serial re-execution, so isolation
        semantics (eviction, shedding, budgets) are exactly the serial
        path's; nothing installs unless the whole batch succeeds."""
        failed = None
        for r in reqs:
            attempts, err = self._probe_envelope(r)
            if err is not None:
                failed = (r, err, attempts)
                break
        if failed is None:
            if self._slow_pending:
                self._slow_pending = False
                self.sleep(self.slow_penalty_s)
            models = [self.tenants[r.tenant].model for r in reqs]
            try:
                self._batch_exec.run(models, reqs[0].steps)
            except Exception as e:  # noqa: BLE001 — classified serially below
                failed = (None, e, 0)
        if failed is not None:
            bad, err, attempts = failed
            telemetry.inc(tm.SERVE_BATCH_FALLBACKS)
            log_warn(
                f"serve: batched dispatch of {len(reqs)} requests fell "
                f"back to serial ({type(err).__name__}: {str(err)[:160]})"
            )
            out = []
            for r in reqs:
                if r is bad:
                    out.append(
                        self._on_dispatch_failure(
                            r, self.tenants[r.tenant], err, attempts
                        )
                    )
                else:
                    out.append(self._dispatch(r))
            return out
        now = self.clock()
        telemetry.inc(tm.SERVE_BATCH_DISPATCHES)
        telemetry.observe(tm.SERVE_BATCH_SIZE, len(reqs))
        self._gauge_occupancy(
            self._model_devices(self.tenants[reqs[0].tenant].model)
        )
        out = []
        for r in reqs:
            tenant = self.tenants[r.tenant]
            latency = max(0.0, now - r.enqueued_at)
            tenant.completed += 1
            tenant.latency.insert(latency)
            self._completed_total += 1
            telemetry.inc(tm.SERVE_COMPLETED)
            telemetry.observe(tm.SERVE_LATENCY_SECONDS, latency)
            out.append(
                Response(
                    request=r, ok=True, latency_s=latency, steps_done=r.steps
                )
            )
        self._heartbeat()
        return out

    def _dispatch_subslice(self, assignments) -> List[Response]:
        """Place each tenant on its disjoint sub-slice, then dispatch
        every request through the UNCHANGED serial envelope back-to-back —
        async dispatch overlaps the step programs across the disjoint
        device sets, and every fault/retry/budget semantic is literally
        the serial path's.  A placement failure (reshard restores state)
        degrades to serial dispatch on whatever mesh each tenant holds."""
        try:
            pack.place_subslices(assignments)
        except Exception as e:  # noqa: BLE001 — placement only; state restored
            telemetry.inc(tm.SERVE_BATCH_FALLBACKS)
            log_warn(
                f"serve: sub-slice placement of {len(assignments)} tenants "
                f"fell back to serial ({type(e).__name__}: {str(e)[:160]})"
            )
        else:
            telemetry.inc(tm.SERVE_SUBSLICE_DISPATCHES)
            telemetry.observe(tm.SERVE_SUBSLICE_COUNT, len(assignments))
            self._gauge_occupancy(
                sum(
                    self._model_devices(m) for _r, m, _d in assignments
                )
            )
        return [self._dispatch(r) for r, _m, _d in assignments]

    @staticmethod
    def _model_devices(model) -> int:
        dd = getattr(model, "dd", None)
        if dd is None or getattr(dd, "mesh", None) is None:
            return 0
        return int(dd.mesh.devices.size)

    def _fleet_devices(self) -> list:
        """The device pool the bin-packer carves: the pinned ``fleet``
        when given, else the union of the tenants' current meshes."""
        if self.fleet is not None:
            return list(self.fleet)
        seen: Dict[int, object] = {}
        for t in self.tenants.values():
            dd = getattr(t.model, "dd", None)
            if dd is None or getattr(dd, "mesh", None) is None:
                continue
            for d in dd.mesh.devices.flat:
                seen[d.id] = d
        return [seen[i] for i in sorted(seen)]

    def _gauge_occupancy(self, busy_devices: int) -> None:
        fleet = len(self._fleet_devices())
        if fleet > 0:
            telemetry.set_gauge(
                tm.SERVE_OCCUPANCY, min(1.0, busy_devices / fleet)
            )

    def _dispatch(self, req: Request) -> Response:
        tenant = self.tenants[req.tenant]
        label = f"serve:{req.tenant}"
        attempts = [0]

        def work():
            attempts[0] += 1
            inject.maybe_fail("execute", label)
            if self._slow_pending:
                # a seeded slow tenant: its request hogs its slot for the
                # penalty — charged to THIS request's latency only
                self._slow_pending = False
                self.sleep(self.slow_penalty_s)
            if tenant.model is not None:
                tenant.model.step(req.steps)

        try:
            inject.maybe_fail("dispatch", label)
            execute_with_retry(
                work,
                label=label,
                policy=self.retry_policy,
                budget=tenant.budget,
                sleep=self.sleep,
                rng=self.rng,
            )
        except Exception as e:  # noqa: BLE001 — classified right below
            return self._on_dispatch_failure(req, tenant, e, attempts[0])
        now = self.clock()
        latency = max(0.0, now - req.enqueued_at)
        tenant.completed += 1
        tenant.retries += max(0, attempts[0] - 1)
        tenant.latency.insert(latency)
        self._completed_total += 1
        telemetry.inc(tm.SERVE_COMPLETED)
        telemetry.observe(tm.SERVE_LATENCY_SECONDS, latency)
        self._heartbeat()
        return Response(
            request=req, ok=True, latency_s=latency, steps_done=req.steps
        )

    def _on_dispatch_failure(
        self, req: Request, tenant: Tenant, e: Exception, attempts: int
    ) -> Response:
        now = self.clock()
        cls = classify(e)
        tenant.retries += max(0, attempts - 1)
        if cls is FailureClass.OVERLOAD:
            # an injected overload at the dispatch hook: shed THIS request,
            # never evict the (healthy) tenant it happened to land on
            return self._shed(req, "injected", now)
        action = tenant.handle_failure(cls, str(e))
        if action == "evict":
            telemetry.inc(tm.SERVE_EVICTED)
            telemetry.emit_event(
                tm.EVENT_SERVE_EVICTION,
                tenant=req.tenant,
                failure_class=cls.value,
                why=str(e)[:300],
            )
            log_warn(
                f"serve: tenant {req.tenant} quarantined after {cls.value}: {e}"
            )
            self._gauge_tenants()
        elif action == "propagate" and cls is FailureClass.PREEMPTED:
            raise e  # a preemption outranks serving bookkeeping
        self._heartbeat()
        return Response(
            request=req,
            ok=False,
            latency_s=max(0.0, now - req.enqueued_at),
            error=str(e)[:300],
            failure_class=cls.value,
        )

    # --- loops + reporting ----------------------------------------------------

    def drain(self, max_cycles: int = 10_000) -> List[Response]:
        """Cycle until the queue is empty (or the cycle bound trips —
        never an unbounded loop inside a bounded-queue package).  A
        truncated drain is NOT silent: it logs the bound and the work
        left behind, and counts ``serve.drain.truncated``."""
        out: List[Response] = []
        for _ in range(max_cycles):
            if self.queue.depth() == 0:
                break
            out.extend(self.cycle())
        remaining = self.queue.depth()
        if remaining > 0:
            telemetry.inc(tm.SERVE_DRAIN_TRUNCATED)
            log_warn(
                f"serve: drain truncated at max_cycles={max_cycles} with "
                f"{remaining} request(s) still queued"
            )
        return out

    def tenant_table(self) -> List[dict]:
        return [t.table_row() for t in self.tenants.values()]

    def _heartbeat(self) -> None:
        if self.flight is None:
            return
        self.flight.heartbeat(
            self._completed_total,
            phase="serving",
            queue_depth=self.queue.depth(),
            tenants=self.tenant_table(),
        )
