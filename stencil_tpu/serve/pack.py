"""Throughput packing: the batch planner and the sub-slice bin-packer.

PR 16's server dispatches ONE request per cycle — tenants timeshare the
fleet serially, so aggregate throughput is 1/N of what the hardware can
deliver.  This module gives ``StencilServer.cycle`` two concurrency
mechanisms behind one scheduler (docs/serving.md "Throughput: batching
and sub-slice packing"), both bitwise-pinned by the soak's packed legs:

* **batched dispatch** — requests whose workloads share a step GEOMETRY
  (same domain shape / mesh / route / dtype — the same tuple the AOT
  cache key digests) stack along a leading batch axis and run as ONE
  dispatch: ``vmap`` over the jitted step where the route permits, or an
  explicit leading dim (``lax.scan``) for the plane-pipeline routes vmap
  cannot carry (``ops/stream.py make_batched_dispatch``).  Per-tenant
  outputs slice back out; a classified failure against any member falls
  the whole group back to serial re-execution so the per-tenant fault
  envelopes keep their exact semantics.

* **sub-slice bin-packing** — tenants whose shapes DON'T match get
  bin-packed onto disjoint contiguous sub-slices of the fleet (greedy
  decreasing by state footprint, each tenant taking the cheapest
  remaining slice under the measured ``fabric.link_model`` cost — the
  serving-time analog of the reference's QAP-over-measured-distances
  placement, PAPER.md L5), then dispatched back-to-back WITHOUT an
  intermediate block so async dispatch overlaps their execution on the
  disjoint device sets.

Disjointness is not a comment: the ``batch-isolation`` program contract
(analysis/contracts.py) machine-checks the traced canonical programs
``serve:batched`` / ``serve:subslice`` — no cross-tenant dataflow, no
gathering collective, collectives confined to each sub-slice's mesh.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from stencil_tpu.utils.logging import log_info

#: the packed dispatch modes the scheduler can pick (serial dispatch is
#: the ABSENCE of packing, not a mode).  The canonical-program matrix
#: must trace one program per mode (``serve:batched``/``serve:subslice``
#: in analysis/programs.py) — analysis/registry.py CANONICAL_AXES pins
#: this tuple against that matrix.
SERVE_MODES = ("batched", "subslice")


# --- geometry keys -----------------------------------------------------------


def geometry_key(model, steps: int) -> Optional[tuple]:
    """The batch-compatibility key: two requests may share one batched
    dispatch iff their keys are equal — same autotuner workload digest
    (chip / domain / mesh / route / dtype, ``tune/key.py``), same buffer
    shapes+dtypes (halo multiplier and storage axis included), same
    device placement, same step count.  ``None`` = not batchable (no
    realized domain under the model)."""
    dd = getattr(model, "dd", None)
    step = getattr(model, "_step", None)
    if dd is None or step is None or not getattr(dd, "_realized", False):
        return None
    buffers = tuple(
        (name, tuple(arr.shape), str(arr.dtype))
        for name, arr in sorted(dd._curr.items())
    )
    devices = tuple(sorted(d.id for d in dd.mesh.devices.flat))
    return (
        dd.tune_key(dd.exchange_route()).digest(),
        buffers,
        devices,
        int(steps),
    )


def footprint_bytes(model) -> int:
    """The tenant's resident field-state bytes — the greedy bin-packer's
    decreasing sort key (the biggest tenant chooses its slice first)."""
    dd = getattr(model, "dd", None)
    if dd is None or not getattr(dd, "_realized", False):
        return 0
    return sum(int(arr.nbytes) for arr in dd._curr.values())


def _packable(tenant) -> bool:
    return (
        tenant is not None
        and tenant.active()
        and tenant.model is not None
        and getattr(tenant.model, "dd", None) is not None
        and getattr(tenant.model.dd, "_realized", False)
    )


def _oldest_per_tenant(pending, rotation) -> "List":
    """The oldest queued request of each tenant, in rotation-fair order
    (tenants outside the rotation ride at the back in queue order)."""
    oldest: Dict[str, object] = {}
    for r in pending:
        if r.tenant not in oldest:
            oldest[r.tenant] = r
    order = [t for t in rotation if t in oldest]
    order += [t for t in oldest if t not in order]
    return [oldest[t] for t in order]


# --- the batch planner -------------------------------------------------------


def plan_batches(pending, tenants, rotation, batch_max: int):
    """Pick ONE batch group: the oldest queued request of each packable
    tenant, grouped by ``geometry_key``; the first group (rotation-fair
    order) with >= 2 members dispatches together, capped at ``batch_max``.
    Returns the request list, or ``None`` when nothing groups."""
    if batch_max < 2:
        return None
    keyed: Dict[tuple, list] = {}
    for r in _oldest_per_tenant(pending, rotation):
        t = tenants.get(r.tenant)
        if not _packable(t):
            continue
        k = geometry_key(t.model, r.steps)
        if k is None:
            continue
        keyed.setdefault(k, []).append(r)
    for group in keyed.values():
        if len(group) >= 2:
            return group[:batch_max]
    return None


class BatchExecutor:
    """Runs a geometry-matched group as ONE dispatch with a leading batch
    axis, caching the compiled batched callable per (geometry, resolved
    step, mode).  Results install only on success — an exception leaves
    every tenant's state untouched for the serial fallback."""

    def __init__(self):
        self._cache: Dict[tuple, Callable] = {}

    @staticmethod
    def _resolved(model):
        """The per-shard callable to batch over: a ladder-backed step
        batches its CURRENTLY-BUILT rung (so degradation decisions keep
        applying under batching), a raw jitted step batches itself."""
        step = model._step
        ladder = getattr(step, "_resilience", None)
        return ladder.built() if ladder is not None else step

    def run(self, models: Sequence, steps: int) -> None:
        from stencil_tpu.ops.stream import (
            batch_axis_mode,
            make_batched_dispatch,
        )

        rep = models[0]
        fn = self._resolved(rep)
        mode = batch_axis_mode(rep._step)
        key = (geometry_key(rep, steps), mode, id(fn))
        batched = self._cache.get(key)
        if batched is None:
            batched = make_batched_dispatch(fn, steps, mode)
            self._cache[key] = batched
        names = sorted(rep.dd._curr)
        # jnp.stack COPIES: the stacked buffer is donated to the dispatch
        # while every tenant's source buffers stay live (serial fallback)
        stacked = {
            n: jnp.stack([m.dd._curr[n] for m in models]) for n in names
        }
        out = batched(stacked)
        for i, m in enumerate(models):
            m.dd._curr = {n: out[n][i] for n in names}
            m.dd.mark_shell_stale()


# --- the sub-slice bin-packer ------------------------------------------------


def plan_subslice_candidates(pending, tenants, rotation):
    """The oldest queued request of each DISTINCT packable tenant whose
    model can move meshes (``rebuild_after_reshard``), rotation-fair
    order; ``None`` unless at least two tenants qualify."""
    picks = []
    for r in _oldest_per_tenant(pending, rotation):
        t = tenants.get(r.tenant)
        if not _packable(t):
            continue
        if not hasattr(t.model, "rebuild_after_reshard"):
            continue
        picks.append(r)
    return picks if len(picks) >= 2 else None


def _slice_cost(model, devices, link) -> float:
    """Modeled shell-exchange seconds/step for ``model`` on ``devices``
    under a measured ``fabric.link_model`` doc: per mesh axis, two shells
    of that axis's face area cross the axis's slowest measured link.
    ``link`` is a doc (uniform fabric), a callable ``devices -> doc``
    (per-slice measured docs), or ``None`` (no fabric data: every slice
    prices equal and the greedy order decides)."""
    doc = link(devices) if callable(link) else link
    axes = (doc or {}).get("axes") or {}
    if not axes:
        return 0.0
    dd = model.dd
    size = dd.size()
    bytes_per_cell = sum(
        jnp.dtype(dd.field_dtype(h)).itemsize for h in dd._handles
    )
    area = {
        "x": size.y * size.z,
        "y": size.x * size.z,
        "z": size.x * size.y,
    }
    cost = 0.0
    for axis, face in area.items():
        sides = axes.get(axis)
        if not sides:
            continue
        gbps = min(
            float(s.get("gbps_min", s.get("gbps_med", 0.0)) or 0.0)
            for s in sides.values()
        )
        if gbps <= 0.0:
            continue
        cost += (2.0 * face * bytes_per_cell) / (gbps * 1e9)
    return cost


def plan_subslices(entries, fleet, link=None):
    """Greedy decreasing bin-pack of tenants onto DISJOINT contiguous
    sub-slices of ``fleet``: the fleet splits into equal contiguous
    slices (one per tenant), tenants sort by descending state footprint,
    and each takes the cheapest remaining slice under ``_slice_cost`` —
    high-traffic shell directions stay on fast links, the measured-QAP
    analog.  ``entries`` is ``[(request, model), ...]`` (distinct
    tenants); returns ``[(request, model, slice_devices), ...]`` or
    ``None`` when the fleet cannot give every tenant a device."""
    k = min(len(entries), len(fleet))
    if k < 2:
        return None
    entries = list(entries)[:k]
    width = len(fleet) // k
    slices = [tuple(fleet[i * width : (i + 1) * width]) for i in range(k)]
    order = sorted(
        entries, key=lambda e: footprint_bytes(e[1]), reverse=True
    )
    remaining = list(range(k))
    assigned = []
    for req, model in order:
        best = min(
            remaining, key=lambda i: (_slice_cost(model, slices[i], link), i)
        )
        remaining.remove(best)
        assigned.append((req, model, slices[best]))
    return assigned


def place_subslices(assignments) -> int:
    """Move each assigned tenant onto its disjoint sub-slice (a no-op
    when already there): a bounded-staging reshard plus the model's step
    rebuild.  Placement is all that happens here — the server then
    dispatches every request through its unchanged serial envelope
    back-to-back, and async dispatch overlaps the step programs across
    the disjoint device sets; per-tenant digests stay bitwise-identical
    to full-fleet serial execution (mesh-shape independence, pinned by
    the soak's ``subslice`` leg).  A reshard failure restores the
    tenant's state (domain.py), so the caller can degrade to serial
    dispatch on whatever mesh each tenant holds.  Returns how many
    tenants actually moved."""
    moved = 0
    for req, model, devices in assignments:
        current = tuple(sorted(d.id for d in model.dd.mesh.devices.flat))
        want = tuple(sorted(d.id for d in devices))
        if current != want:
            model.dd.reshard(devices=list(devices), source="subslice")
            model.rebuild_after_reshard()
            moved += 1
            log_info(
                f"serve: packed tenant {req.tenant} onto sub-slice "
                f"{list(want)}"
            )
    return moved
