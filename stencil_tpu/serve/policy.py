"""Load-driven elasticity: queue depth in, grow/shrink decisions out.

The policy is a pure observer — it never touches a mesh itself.  The
server feeds it the queue depth after every dispatch cycle; when it
decides, the server routes the decision through the SAME drain-and-reshard
protocol every other capacity change uses (``RunSupervisor.request_capacity``
or a direct ``DistributedDomain.reshard``), so a policy reshard coalesces
with operator signals and seeded capacity notices instead of racing them.

Hysteresis (docs/serving.md "Elasticity"), both knobs pinned by tests:

* **consecutive observations** — one spiky sample must not move the mesh:
  the depth has to sit above ``high`` (or at/below ``low``) for
  ``consecutive`` successive observations before the policy acts;
* **cooldown** — after any action the policy holds for ``cooldown_s`` of
  (injectable) clock time, longer than a reshard takes, so it reacts to
  the post-transition steady state rather than to its own transient;
* **no repeats** — a decision is only emitted when it CHANGES the fleet
  level (grow after grow is suppressed until a shrink intervened): the
  capacity model behind the policy is two-level (half fleet / full
  fleet), so a repeated decision could only re-request the mesh it
  already has;
* **shrink arms on load** — an idle server that never saw depth above
  ``low`` has nothing to give back: shrink observations only count after
  the first sample above the low-water mark, so a fresh server does not
  open with a scale-down flap.

``low < high`` is enforced: the dead band between them is what prevents
grow/shrink ping-pong at a steady load level.
"""

from __future__ import annotations

from typing import Optional


class ElasticityPolicy:
    """Threshold + hysteresis policy over queue depth."""

    def __init__(
        self,
        high: int = 8,
        low: int = 1,
        consecutive: int = 3,
        cooldown_s: float = 30.0,
    ):
        if low >= high:
            raise ValueError(
                f"elasticity dead band is empty: low={low} must be < high={high}"
            )
        self.high = int(high)
        self.low = int(low)
        self.consecutive = int(consecutive)
        self.cooldown_s = float(cooldown_s)
        self._above = 0
        self._below = 0
        self._armed = False  # shrink counts only after load was seen
        self._last_kind: Optional[str] = None
        self._last_action_at: Optional[float] = None
        self.decisions: list = []  # (now, kind) history, for the soak artifact

    def observe(self, depth: int, now: float) -> Optional[str]:
        """Feed one queue-depth sample; returns ``"grow"``/``"shrink"``
        when the hysteresis gate opens, else None."""
        if depth > self.low:
            self._armed = True
        if depth > self.high:
            self._above += 1
            self._below = 0
        elif depth <= self.low:
            self._below += 1 if self._armed else 0
            self._above = 0
        else:
            self._above = self._below = 0  # the dead band resets both runs
        if (
            self._last_action_at is not None
            and now - self._last_action_at < self.cooldown_s
        ):
            return None
        kind = None
        if self._above >= self.consecutive and self._last_kind != "grow":
            kind = "grow"
        elif self._below >= self.consecutive and self._last_kind != "shrink":
            kind = "shrink"
        if kind is not None:
            self._above = self._below = 0
            self._last_kind = kind
            self._last_action_at = now
            self.decisions.append((now, kind))
        return kind
