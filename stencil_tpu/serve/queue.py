"""The bounded admission queue: deadline propagation, load shedding,
backpressure.

One queue for the whole server (dispatch slots are the scarce fleet-wide
resource, not per-tenant buffers), BOUNDED by construction — the
``bounded-queue`` lint rule rejects any unbounded buffering in this
package: an unbounded queue converts overload into latency collapse and
OOM instead of a classified, retryable refusal at the edge.

Shedding order (docs/serving.md "Shedding policy"):

1. expired requests first, oldest first — work past its deadline is
   already worthless to its caller, so it is the cheapest load to drop;
2. then, to make room for a HIGHER-priority arrival only, the oldest
   request of the lowest-priority tenant;
3. otherwise the ARRIVAL is refused (``OverloadError``) — backpressure to
   the caller, who owns the back-off decision.
"""

from __future__ import annotations

import collections
from typing import List, Optional, Sequence

from stencil_tpu.resilience.taxonomy import OverloadError
from stencil_tpu.serve.request import Request


class BoundedQueue:
    """FIFO-per-tenant, priority-aware, deadline-propagating; refuses
    instead of growing past ``maxlen``."""

    def __init__(self, maxlen: int = 64):
        if maxlen < 1:
            raise ValueError(f"queue maxlen must be >= 1, got {maxlen}")
        self.maxlen = int(maxlen)
        self._q = collections.deque(maxlen=self.maxlen)

    def depth(self) -> int:
        return len(self._q)

    def full(self) -> bool:
        return len(self._q) >= self.maxlen

    def push(self, req: Request, now: float) -> None:
        """Enqueue, or raise ``OverloadError`` (queue_full) — the caller
        (``server.submit``) runs the shed ladder before giving up."""
        if self.full():
            raise OverloadError(
                why="queue_full",
                queue_depth=self.depth(),
                tenant=req.tenant,
                # the soonest-queued request's age is a fair "come back
                # when a slot likely opened" hint; crude but honest
                retry_after_s=1.0,
            )
        req.enqueued_at = now
        self._q.append(req)

    def shed_expired(self, now: float) -> List[Request]:
        """Remove every queued request whose deadline has passed, OLDEST
        first — deadline propagation means nobody downstream should spend
        fleet time on work its caller already abandoned."""
        expired = [r for r in self._q if r.expired(now)]
        if expired:
            keep = [r for r in self._q if not r.expired(now)]
            self._q.clear()
            self._q.extend(keep)
        return sorted(expired, key=lambda r: (r.enqueued_at, r.seq))

    def shed_lowest_priority(self, below: int) -> Optional[Request]:
        """Remove the oldest request of the LOWEST priority strictly below
        ``below`` (make-room shed for a higher-priority arrival); None when
        every queued request is at least that important."""
        victims = [r for r in self._q if r.priority < below]
        if not victims:
            return None
        victim = min(victims, key=lambda r: (r.priority, r.enqueued_at, r.seq))
        self._q.remove(victim)
        return victim

    def take(self, rotation: Sequence[str]) -> Optional[Request]:
        """Dequeue the oldest request of the first tenant in ``rotation``
        that has one queued — the server rotates the order after every
        dispatch, so tenants share dispatch slots round-robin instead of
        one chatty tenant starving the rest.  Falls back to plain FIFO for
        requests from tenants not in the rotation."""
        for tid in rotation:
            for r in self._q:
                if r.tenant == tid:
                    self._q.remove(r)
                    return r
        if self._q:
            return self._q.popleft()
        return None

    def remove(self, req: Request) -> bool:
        """Remove a specific queued request (the packed-dispatch planners
        pick requests by PEEKING — ``peek_all`` — then claim them here);
        False when it is no longer queued (e.g. shed meanwhile)."""
        try:
            self._q.remove(req)
            return True
        except ValueError:
            return False

    def peek_all(self) -> List[Request]:
        return list(self._q)
