"""bench-pack — pack/unpack kernel bandwidth.

Parity target: reference bin/bench_pack.cu: for a 512^3 float quantity with
radius 3, time packing/unpacking the x, y, and z face slabs on one chip
(bench_pack.cu:91-107).  Output format matches the reference
(``<ext> <dir> <bytes> <packTime> <unpackTime>``), plus a GB/s column (the
BASELINE.md metric).  ``--backend pallas`` uses the explicit-DMA Pallas
kernels; ``xla`` (default) the fused slice/concat path.
"""

from __future__ import annotations

import argparse
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from stencil_tpu.core.dim3 import Dim3
from stencil_tpu.core.geometry import LocalSpec
from stencil_tpu.core.radius import Radius
from stencil_tpu.ops.pack import (
    make_pack_fn,
    make_pack_fn_pallas,
    make_unpack_fn,
    make_unpack_fn_pallas,
)


def bench(sz: Dim3, direction: Dim3, n_iters: int, backend: str, interpret: bool):
    """Returns (bytes, pack_s_per_iter, unpack_s_per_iter)."""
    spec = LocalSpec.make(sz, Dim3(0, 0, 0), Radius.constant(3))
    raw = tuple(spec.raw_size())
    rng = np.random.default_rng(0)
    block = jnp.asarray(rng.random(raw), dtype=jnp.float32)

    if backend == "pallas":
        pack, plan = make_pack_fn_pallas(spec, [direction], jnp.float32, interpret=interpret)
        unpack, _ = make_unpack_fn_pallas(spec, [direction], jnp.float32, interpret=interpret)
        packed = pack(block)
        jax.block_until_ready(packed)

        def run_pack():
            jax.block_until_ready(pack(block))

        def run_unpack():
            jax.block_until_ready(unpack(block, packed))

    else:
        pack, plan = make_pack_fn(spec, [direction], [jnp.float32])
        unpack, _ = make_unpack_fn(spec, [direction], [jnp.float32])
        packed = pack([block])
        jax.block_until_ready(packed)
        # unpack donates its blocks; chain them so the buffer is reused in
        # place and the timed loop measures only the halo scatter
        state = {"blocks": [block + 0]}

        def run_pack():
            jax.block_until_ready(pack([block]))

        def run_unpack():
            state["blocks"] = unpack(packed, state["blocks"])
            jax.block_until_ready(state["blocks"])

    run_pack()
    run_unpack()  # compile both outside timing
    t0 = time.perf_counter()
    for _ in range(n_iters):
        run_pack()
    pack_t = (time.perf_counter() - t0) / n_iters
    t0 = time.perf_counter()
    for _ in range(n_iters):
        run_unpack()
    unpack_t = (time.perf_counter() - t0) / n_iters
    return plan.size, pack_t, unpack_t


def bench_roundtrip(sz: Dim3, direction: Dim3, n_iters: int, inner: int, backend: str, interpret: bool, rt: float):
    """pack->unpack round trips, ``inner`` per device dispatch with the host
    round trip subtracted — the honest protocol for tunneled backends (per-
    call sync costs ~100 ms there; see bench.py).  Returns
    (bytes, seconds per round trip)."""
    from functools import partial

    from jax import lax

    spec = LocalSpec.make(sz, Dim3(0, 0, 0), Radius.constant(3))
    raw = tuple(spec.raw_size())
    rng = np.random.default_rng(0)
    block = jnp.asarray(rng.random(raw), dtype=jnp.float32)

    if backend == "pallas":
        pack, plan = make_pack_fn_pallas(spec, [direction], jnp.float32, interpret=interpret)
        unpack, _ = make_unpack_fn_pallas(spec, [direction], jnp.float32, interpret=interpret)

        def one(b):
            return unpack(b, pack(b))

    else:
        pack, plan = make_pack_fn(spec, [direction], [jnp.float32])
        unpack, _ = make_unpack_fn(spec, [direction], [jnp.float32])

        def one(b):
            return unpack(pack([b]), [b])[0]

    @partial(jax.jit, donate_argnums=0, static_argnums=1)
    def loop(b, s):
        return lax.fori_loop(0, s, lambda _, x: one(x), b)

    from stencil_tpu.bin import _common

    state = {"b": block}

    def run(k):
        state["b"] = loop(state["b"], k)
        float(jnp.sum(state["b"][0, 0, 0:1]))  # honest completion (tunnel)

    # auto-scaled inner: rt subtraction can never clamp to 0.0, and every
    # timed dispatch reuses the executable warmed at the SAME static count
    samples, _ = _common.timed_inner_loop(run, inner, rt, max(n_iters, 3))
    return plan.size, min(samples)


def main(argv=None) -> int:
    p = argparse.ArgumentParser("bench-pack")
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--size", type=int, default=512)
    p.add_argument("--backend", choices=["xla", "pallas"], default="xla")
    p.add_argument(
        "--interpret",
        action="store_true",
        help="run pallas kernels in interpreter mode (CPU testing)",
    )
    p.add_argument(
        "--inner",
        type=int,
        default=1,
        help="pack+unpack round trips per device dispatch (use >1 on "
        "tunneled backends; prints roundtrip time instead of pack/unpack)",
    )
    from stencil_tpu.bin import _common

    _common.add_telemetry_flags(p)
    args = p.parse_args(argv)
    _common.telemetry_begin(args)

    ext = Dim3(args.size, args.size, args.size)
    if args.inner > 1:
        rt = _common.host_round_trip_s()
        for d in (Dim3(1, 0, 0), Dim3(0, 1, 0), Dim3(0, 0, 1)):
            nbytes, rt_t = bench_roundtrip(
                ext, d, max(args.iters, 3), args.inner, args.backend, args.interpret, rt
            )
            gbps = 2 * nbytes / rt_t / 1e9  # payload packed + unpacked
            print(f"{ext} {d} {nbytes} roundtrip {rt_t:g} {gbps:.2f}GB/s")
        _common.telemetry_end(args)
        return 0
    for d in (Dim3(1, 0, 0), Dim3(0, 1, 0), Dim3(0, 0, 1)):
        nbytes, pack_t, unpack_t = bench(ext, d, args.iters, args.backend, args.interpret)
        gbps = nbytes / min(pack_t, unpack_t) / 1e9
        print(f"{ext} {d} {nbytes} {pack_t:g} {unpack_t:g} {gbps:.2f}GB/s")
    _common.telemetry_end(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
