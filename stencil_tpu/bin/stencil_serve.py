"""``python -m stencil_tpu.bin.stencil_serve`` — the multi-tenant serving
driver + synthetic load generator.

Builds N independent Jacobi tenants timesharing the visible fleet, drives
a triangle load ramp (requests per dispatch cycle climb to ``--peak`` at
mid-run, then fall back to zero), and serves it through
:class:`stencil_tpu.serve.StencilServer` — admission control, per-tenant
envelopes, bounded-queue shedding, and (``--elastic``) the load-driven
grow/shrink loop through ``DistributedDomain.reshard``.

Chaos comes from the environment: ``STENCIL_FAULT_PLAN`` seeds
``poison_request``/``vmem_oom``/``overload``/``slow_tenant`` entries
against ``serve:<tenant>`` labels exactly like the kill/capacity classes
(``scripts/run_soak.py --serve`` drives reference-vs-chaos pairs and
compares the per-tenant digests this driver records).

Artifact: ``serve_summary.json`` under ``--out`` with ``bench:
"serve_soak"`` — per-tenant table rows + final-field digests, fleet
p99/shed-rate SLO numbers (``scripts/perf_ledger.py`` ingests them as
lower-is-better series), elasticity decisions, and mesh transitions.
"""

from __future__ import annotations

import argparse
import hashlib
import os
import sys
import time

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "stencil_serve",
        description="multi-tenant serving driver + synthetic load generator "
        "(docs/serving.md)",
    )
    p.add_argument("--tenants", type=int, default=3, help="tenant count")
    p.add_argument("--size", type=int, default=16, help="cubic domain edge per tenant")
    p.add_argument("--cycles", type=int, default=40, help="load-generator cycles")
    p.add_argument("--steps", type=int, default=1, help="raw steps per request")
    p.add_argument("--peak", type=int, default=3, help="requests/cycle at the ramp peak")
    p.add_argument("--queue-max", type=int, default=32, help="admission queue bound")
    p.add_argument(
        "--deadline-s", type=float, default=30.0,
        help="per-request deadline (generous by default: shedding should "
        "come from injected overload, not CI jitter)",
    )
    p.add_argument(
        "--compile-budget-s", type=float, default=None,
        help="admission budget for a cold AOT compile (default: unbounded)",
    )
    p.add_argument(
        "--batch", type=int, default=0,
        help="batch up to N geometry-matched requests into one dispatch "
        "(0/1 disables; docs/serving.md 'Throughput')",
    )
    p.add_argument(
        "--subslice", action="store_true",
        help="bin-pack non-matching tenants onto disjoint sub-meshes",
    )
    p.add_argument("--elastic", action="store_true", help="enable the grow/shrink policy")
    p.add_argument("--elastic-high", type=int, default=6, help="grow above this queue depth")
    p.add_argument("--elastic-low", type=int, default=0, help="shrink at/below this depth")
    p.add_argument("--elastic-consecutive", type=int, default=3, help="observations before acting")
    p.add_argument("--elastic-cooldown-s", type=float, default=0.0, help="hold time after acting")
    p.add_argument("--out", default="serve_out", help="artifact/heartbeat directory")
    p.add_argument(
        "--fixed-mesh", action="store_true",
        help="ignore --elastic decisions (the reference leg of the "
        "elasticity bitwise A/B)",
    )
    return p


def _digest(arr: np.ndarray) -> str:
    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()[:16]


def _ramp(cycle: int, cycles: int, peak: int) -> int:
    """Triangle profile: 0 -> peak at mid-run -> 0 (int requests/cycle)."""
    half = max(cycles // 2, 1)
    frac = cycle / half if cycle <= half else max(0.0, 2.0 - cycle / half)
    return int(round(peak * frac))


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    import jax

    from stencil_tpu import telemetry
    from stencil_tpu.telemetry import names as tm
    from stencil_tpu.models.jacobi import Jacobi3D
    from stencil_tpu.resilience import inject
    from stencil_tpu.resilience.taxonomy import OverloadError
    from stencil_tpu.serve import (
        AdmissionRefused,
        ElasticityPolicy,
        Request,
        StencilServer,
        TenantSpec,
    )
    from stencil_tpu.telemetry.flight import FlightRecorder
    from stencil_tpu.utils.artifact import atomic_write_json
    from stencil_tpu.utils.logging import log_info

    devices = list(jax.devices())
    full = list(devices)
    half = devices[: max(len(devices) // 2, 1)]
    # elastic runs start on the half fleet so the grow leg has somewhere to
    # go (grow reshards half -> full, the post-drain shrink returns it);
    # --fixed-mesh keeps the same starting mesh so the bitwise A/B compares
    # like with like
    start = half if args.elastic else full
    current = {"devices": list(start)}
    transitions: list = []

    models = {}
    for i in range(args.tenants):
        tid = f"tenant-{chr(ord('a') + i)}"
        m = Jacobi3D(args.size, args.size, args.size, devices=start)
        m.realize()
        models[tid] = m

    def capacity(kind: str) -> None:
        if args.fixed_mesh:
            return
        target = full if kind in ("grow", "refit") else half
        if {d.id for d in target} == {d.id for d in current["devices"]}:
            return  # already there: a repeat decision is a no-op
        for tid, m in models.items():
            stats = m.dd.reshard(devices=target, source="policy")
            m.rebuild_after_reshard()
            transitions.append(
                {"kind": kind, "tenant": tid, "seconds": stats.get("seconds")}
            )
        current["devices"] = list(target)
        log_info(f"stencil_serve: policy {kind} -> {len(target)} devices")

    policy = None
    if args.elastic:
        policy = ElasticityPolicy(
            high=args.elastic_high,
            low=args.elastic_low + 1 if args.elastic_low >= args.elastic_high else args.elastic_low,
            consecutive=args.elastic_consecutive,
            cooldown_s=args.elastic_cooldown_s,
        )

    flight = FlightRecorder(dir=args.out, label="stencil_serve")
    srv = StencilServer(
        queue_max=args.queue_max,
        default_deadline_s=args.deadline_s,
        compile_budget_s=args.compile_budget_s,
        policy=policy,
        capacity=capacity,
        flight=flight,
        batch_max=args.batch,
        subslice=args.subslice,
        fleet=full,
    )
    submitted = rejected = 0
    latencies: list = []
    responses: list = []
    t_start = time.perf_counter()
    try:
        order = sorted(models)
        for tid in order:
            srv.add_tenant(TenantSpec(tenant_id=tid), models[tid])
        for cycle in range(args.cycles):
            for k in range(_ramp(cycle, args.cycles, args.peak)):
                tid = order[(cycle + k) % len(order)]
                submitted += 1
                try:
                    srv.submit(Request(tenant=tid, steps=args.steps))
                except (OverloadError, AdmissionRefused):
                    rejected += 1
            responses.extend(srv.cycle())
        responses.extend(srv.drain())
        # settle: a few empty cycles after the drain so the elasticity
        # policy can observe the now-idle queue and take its shrink leg
        # (exactly `consecutive` observations — one decision, no repeats)
        for _ in range(args.elastic_consecutive):
            responses.extend(srv.cycle())
    finally:
        srv.close()
    wall_s = max(time.perf_counter() - t_start, 1e-9)

    latencies = sorted(r.latency_s for r in responses if r.ok)
    shed = sum(
        1 for r in responses if not r.ok and r.failure_class == "overload"
    )
    p99_ms = (
        latencies[min(len(latencies) - 1, int(0.99 * len(latencies)))] * 1e3
        if latencies
        else None
    )
    plan = inject.active_plan()
    completed = sum(1 for r in responses if r.ok)
    # cells advanced per completed request: every tenant is a cubic Jacobi
    # domain of --size edge stepping --steps raw steps
    mcells = completed * args.steps * (args.size**3) / 1e6
    snap = telemetry.snapshot()
    summary = {
        "bench": "serve_soak",
        "tenants": srv.tenant_table(),
        "digests": {tid: _digest(m.temperature()) for tid, m in models.items()},
        "requests": submitted,
        "rejected": rejected,
        "completed": completed,
        "throughput": {
            "wall_s": wall_s,
            "requests_per_s": completed / wall_s,
            "mcells_per_s": mcells / wall_s,
            "batch_max": args.batch,
            "subslice": bool(args.subslice),
        },
        "shed": shed,
        "shed_rate": (shed / submitted) if submitted else 0.0,
        "p99_ms": p99_ms,
        "elasticity": {
            "enabled": bool(args.elastic and not args.fixed_mesh),
            "decisions": [k for _, k in (policy.decisions if policy else [])],
            "transitions": transitions,
        },
        "fault_plan": os.environ.get(inject.ENV_VAR),
        # the driver can only judge isolation against a reference run —
        # run_soak.py --serve fills the verdict in after comparing digests;
        # a fault-free run is trivially isolated
        "isolation_ok": True if plan is None else None,
        "counters": {
            k: v
            for k, v in snap.get("counters", {}).items()
            if k.startswith("serve.") or k.startswith("resilience.")
        },
        # packed-dispatch evidence: run_soak.py asserts batching actually
        # engaged (count > 0) on the packed legs, not just that digests match
        "batching": {
            name: snap.get("histograms", {}).get(name)
            for name in (tm.SERVE_BATCH_SIZE, tm.SERVE_SUBSLICE_COUNT)
        },
    }
    path = atomic_write_json(os.path.join(args.out, "serve_summary.json"), summary)
    flight.heartbeat(
        args.cycles,
        total_steps=args.cycles,
        phase="complete",
        queue_depth=srv.queue.depth(),
        tenants=srv.tenant_table(),
    )
    log_info(f"stencil_serve: wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
